"""Speculative-decode benchmark: decode launches per generated token.

Question answered: when the paged serving engine turns on speculative
multi-token decode (``spec_decode=True``, README "Speculative
decoding") — prompt-lookup n-gram drafts verified as ragged spans, with
rejected K/V rolled back by block-tail truncation — how many decode
program launches does a generated token cost, on a repetition-heavy
trace (where the drafter should shine) and on an adversarial
low-acceptance trace (where it must at least not regress)? And are the
token streams still byte-identical to speculation off?

Both legs run the SAME paged engine geometry, model and scheduling
(``decode_chunk=1``, chunking off — the traces are decode-dominated by
construction; chunk interplay is bench_ragged's subject) — the only
difference is ``spec_decode``:

- **baseline** — one unified launch advances every slot by exactly one
  token; per-launch weight streaming is the decode wall (ROADMAP's
  MBU observation), so tok/s ∝ 1 / launches-per-token;
- **spec** — each launch verifies up to ``SPEC_K`` drafted tokens per
  slot as one span and emits the accepted prefix plus the model's own
  correction, so a launch advances a slot by 1..SPEC_K+1 tokens.

Methodology: launch counts are EXACT (counted through the engines'
program accessors — every decode-path device call goes through one),
token streams are asserted byte-identical, and the clock model charges
every decode launch the SAME measured warm per-launch cost (best-of-N
decode-only step on the baseline engine). Charging both legs one shared
cost is the honest structural model on this CPU substrate: decode is
weight-streaming-bound on the target hardware, where a verify span's
extra live positions ride the same HBM pass (the ragged kernel prices
live spans only) — while the CPU jnp oracle computes the spec engine's
packed buffer densely, an artifact banked openly under
``cpu_wall_ms`` (same discipline as RAGGED_BENCH's
``cpu_oracle_wall_ms``). Drafter host time is measured and banked too
(``drafter_ms_per_launch``) — it overlaps device work in a real
deployment but is reported, not hidden.

Headline: ``modeled_tok_s_ratio`` on the repetitive trace (acceptance
gate: >= 2x) with the adversarial trace at >= 1x (no regression — an
empty/rejected draft degenerates to a span-1 decode row).

Usage:
  python scripts/bench_spec.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_chunked import BLOCK_SIZE, _model, _timed  # noqa: E402

NUM_SLOTS = 4
SPEC_K = 6
REP_NEW = 128        # repetition-heavy leg: long greedy generations
ADV_NEW = 64         # adversarial leg: sampled, no exploitable repeats
ACCEPT_RATIO = 2.0   # ISSUE 9 acceptance bar: >= 2x modeled decode tok/s


def _mk_engine(model, s_max, spec):
    from paddle_tpu.serving import ContinuousBatchingEngine
    return ContinuousBatchingEngine(
        model, num_slots=NUM_SLOTS, max_seq_len=s_max, decode_chunk=1,
        prefix_block_size=BLOCK_SIZE, prefill_chunk=None,
        spec_decode=spec, spec_k=SPEC_K,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}))


def _clone(r):
    from paddle_tpu.serving import GenerationRequest
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             seed=r.seed)


def _trace_repetitive():
    """Repetition-heavy greedy traffic: motif-tiled prompts prime the
    prompt-lookup drafter, and long greedy continuations settle into
    the loops greedy decode of a fixed model exhibits — the quoting /
    structured-output / self-repetition regime speculative decode
    exists for."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(5)
    reqs = []
    for _ in range(2 * NUM_SLOTS):
        motif = rng.randint(0, 2048, (8,)).astype(np.int32)
        reqs.append(GenerationRequest(prompt=np.tile(motif, 4),
                                      max_new_tokens=REP_NEW))
    return reqs


def _trace_adversarial():
    """Low-acceptance traffic: random prompts, SAMPLED continuations
    (temperature keeps the stream off any deterministic loop), so the
    drafter's guesses almost never verify — the leg that pins 'a wrong
    guess costs no launches'."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(11)
    return [GenerationRequest(
        prompt=rng.randint(0, 2048, (32,)).astype(np.int32),
        max_new_tokens=ADV_NEW, temperature=0.9, top_k=8, seed=300 + i)
        for i in range(2 * NUM_SLOTS)]


def _count_launches(eng):
    """Exact decode-path launch counter wrapped around the engine's
    program accessors (spec engine: the verify program; baseline: the
    unified ragged program)."""
    calls = {"decode": 0, "cold": 0}
    orig_prefill = eng._prefill_fn
    eng._prefill_fn = lambda: (calls.__setitem__(
        "cold", calls["cold"] + 1) or orig_prefill())
    if eng.spec_decode:
        orig = eng._spec_fn
        eng._spec_fn = lambda: (calls.__setitem__(
            "decode", calls["decode"] + 1) or orig())
    else:
        orig = eng._ragged_fn
        eng._ragged_fn = lambda n: (calls.__setitem__(
            "decode", calls["decode"] + 1) or orig(n))
    return calls


def _measure_t_step(model, s_max):
    """Warm per-launch cost of one decode-only baseline step (all slots
    resident), best-of-N — the shared clock both legs are charged."""
    from paddle_tpu.serving import GenerationRequest
    eng = _mk_engine(model, s_max, spec=False)
    rng = np.random.RandomState(3)
    for _ in range(NUM_SLOTS):
        eng.submit(GenerationRequest(
            prompt=rng.randint(0, 2048, (32,)).astype(np.int32),
            max_new_tokens=64))
    eng.step()
    eng.step()
    # best-of-9 floor (the bench_dispatch/bench_trace repeat
    # discipline, ISSUE 13): fewer rounds leave ~4% scheduler noise in
    # the floor on a loaded box — the modeled ratios divide this cost
    # out of both legs, but the banked absolute tok/s figures read it
    # directly, so the floor must be converged, not lucky
    t = min(_timed(eng.step) for _ in range(9))
    while eng.has_work():
        eng.step()
    return t


def _run_leg(model, s_max, reqs, spec, t_step):
    eng = _mk_engine(model, s_max, spec)
    calls = _count_launches(eng)
    # drafter host cost: measured around the whole run (propose() is
    # the only host work speculation adds outside the launch)
    t0 = time.perf_counter()
    outs = eng.generate([_clone(r) for r in reqs])
    wall = time.perf_counter() - t0
    tokens = sum(len(o) for o in outs)
    launches = calls["decode"]
    modeled_s = launches * t_step
    return {
        "decode_launches": launches,
        "cold_prefills": calls["cold"],
        "tokens": tokens,
        "tokens_per_launch": round(tokens / max(launches, 1), 3),
        "modeled_decode_tok_s": round(tokens / modeled_s, 1)
        if modeled_s > 0 else 0.0,
        "spec_proposed": eng.stats["spec_proposed"],
        "spec_accepted": eng.stats["spec_accepted"],
        "acceptance_rate": round(
            eng.stats["spec_accepted"]
            / max(eng.stats["spec_proposed"], 1), 3),
        "decode_compilations": eng.decode_compilations(),
        "cpu_wall_ms": round(wall * 1e3, 1),
    }, [list(o) for o in outs]


def measure_spec_decode(quick=True):
    s_max = 1024 if quick else 2048
    model = _model(quick)
    # warm every program both legs touch before the timed calibration
    warm = _trace_repetitive()[:NUM_SLOTS]
    for spec in (False, True):
        eng = _mk_engine(model, s_max, spec)
        eng.generate([_clone(r) for r in warm])
    t_step = _measure_t_step(model, s_max)
    out = {"t_step_ms": round(t_step * 1e3, 3), "spec_k": SPEC_K,
           "num_slots": NUM_SLOTS}
    ratios = {}
    for trace_name, reqs in (("repetitive", _trace_repetitive()),
                             ("adversarial", _trace_adversarial())):
        base, base_streams = _run_leg(model, s_max, reqs, False, t_step)
        spec, spec_streams = _run_leg(model, s_max, reqs, True, t_step)
        spec2, spec_streams2 = _run_leg(model, s_max, reqs, True, t_step)
        ratio = spec["modeled_tok_s_ratio"] = round(
            spec["modeled_decode_tok_s"]
            / max(base["modeled_decode_tok_s"], 1e-9), 3)
        ratios[trace_name] = ratio
        out[trace_name] = {
            "baseline": base, "spec": spec,
            "tokens_equal": spec_streams == base_streams,
            "deterministic": spec_streams2 == spec_streams
            and spec2["decode_launches"] == spec["decode_launches"],
            "launches_eliminated":
                base["decode_launches"] - spec["decode_launches"],
        }
    accepted = bool(
        ratios["repetitive"] >= ACCEPT_RATIO
        and ratios["adversarial"] >= 1.0
        and all(out[t]["tokens_equal"] and out[t]["deterministic"]
                for t in ("repetitive", "adversarial")))
    out.update({
        "modeled_tok_s_ratio_repetitive": ratios["repetitive"],
        "modeled_tok_s_ratio_adversarial": ratios["adversarial"],
        "accept_ratio": ACCEPT_RATIO,
        "accepted": accepted,
        "drafter": "NgramDrafter(max_ngram=3, min_ngram=1)",
        "clock_model":
            "modeled decode tok/s = tokens / (decode launches x one "
            "shared measured warm per-launch step cost); launch counts "
            "are real dispatches through the program accessors, not "
            "modeled. Decode is weight-streaming-bound on target "
            "hardware, so launches-per-token is the structural lever; "
            "the CPU jnp oracle computes the spec packed buffer "
            "densely — that unmodeled substrate cost is banked under "
            "cpu_wall_ms, not hidden in the headline.",
        "trace": f"repetitive: {2 * NUM_SLOTS} motif-tiled 32-token "
                 f"greedy prompts x {REP_NEW} new tokens; adversarial: "
                 f"{2 * NUM_SLOTS} random 32-token prompts, sampled "
                 f"(T=0.9, top-k 8) x {ADV_NEW} new tokens",
    })
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "spec_decode": measure_spec_decode(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0 if res["spec_decode"]["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
