"""Tiered KV prefix-cache benchmark (README "Tiered KV prefix cache").

Question answered: when the working set of prefix families exceeds the
HBM trie budget, how much of the lost hit-rate does the host-RAM spill
tier recover — and what does a tier-hit admission cost at first-token
time compared to recomputing the evicted prefix from scratch?

Two measurements, both HBM-only (``host_tier_bytes=0``) vs tiered
(same HBM cap, generous host budget), identical greedy requests:

- **rotation** — ``families`` 2-block prompt families revisited in
  rotation under an HBM cap that holds only a third of them. HBM-only:
  every revisit lands after its family was evicted and re-prefills
  from scratch. Tiered: evictions spill to host RAM and the revisit's
  recording lookup streams the chain back (readmission), so revisits
  hit. Acceptance: tiered hit-rate >= ACCEPT_HIT_RATE_RATIO x the
  HBM-only hit-rate.
- **ttft** — two long (8-block) families alternating under a cap that
  holds exactly one, ``max_new_tokens=1`` so the per-request wall IS
  time-to-first-token. Every tiered sample is a tier-hit readmission
  (copy the spilled chain h2d, prefill only the 6-token tail); every
  HBM-only sample is a full-prompt recompute. Acceptance: median
  tier-hit TTFT beats median recompute TTFT by ACCEPT_TTFT_RATIO.

Token streams are asserted byte-identical between the legs of each
measurement (the tier moves bytes, never changes them — the ISSUE 16
transparency gate), and ``decode_compilations() == 1`` per leg (tier
fetch/inject programs live in their own compile-once registry, not the
engine jit cache).

Usage:
  python scripts/bench_tier.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_decode import _models  # noqa: E402

NUM_SLOTS = 2
S_MAX = 128
BLOCK_SIZE = 8
FAMILY_BLOCKS = 2                 # rotation families: 16-token preambles
TAIL = 6
HBM_CAP_BLOCKS = 4                # rotation trie cap: holds 2 of 6 families
PROBE_BLOCKS = 8                  # ttft families: 64-token preambles
TIER_BYTES = 1 << 26              # generous host budget: nothing re-evicts
ACCEPT_HIT_RATE_RATIO = 2.0       # tiered hit-rate vs HBM-only (ISSUE 16)
ACCEPT_TTFT_RATIO = 1.25          # recompute TTFT / tier-hit TTFT


def _req(preamble, tail):
    from paddle_tpu.serving import GenerationRequest
    return GenerationRequest(
        prompt=np.concatenate([preamble, tail]).astype(np.int32),
        max_new_tokens=TAIL)


def _rotation_workload(vocab, families=6, rounds=3):
    """Long-tail rotation: every family revisited each round, always
    with a fresh tail — more families than HBM_CAP_BLOCKS holds. One
    immediate same-family revisit per round keeps the HBM-only
    baseline hit-rate non-zero (the ratio denominator is real)."""
    rng = np.random.RandomState(47)
    preambles = [rng.randint(0, vocab, (FAMILY_BLOCKS * BLOCK_SIZE,))
                 .astype(np.int32) for _ in range(families)]
    reqs = []
    for _ in range(rounds):
        for p in preambles:
            reqs.append(_req(p, rng.randint(0, vocab, (TAIL,))))
        reqs.append(_req(preambles[-1], rng.randint(0, vocab, (TAIL,))))
    return reqs


def _engine(model, host_tier_bytes, prefix_blocks):
    from paddle_tpu.serving import ContinuousBatchingEngine
    return ContinuousBatchingEngine(
        model, num_slots=NUM_SLOTS, max_seq_len=S_MAX, decode_chunk=1,
        prefix_cache=True, prefix_block_size=BLOCK_SIZE,
        prefix_blocks=prefix_blocks, host_tier_bytes=host_tier_bytes,
        jit_cache=model.__dict__.setdefault("_serving_jit_tierbench", {}))


def _classified_serial(eng, reqs):
    """Run serially, timing each request's full wall and classifying it
    by what the recording lookup did (tier-hit readmission beats plain
    hit beats miss) — the per-class walls are the latency signal."""
    pc = eng.prefix_cache
    streams, walls = [], {"tier_hit": [], "hbm_hit": [], "miss": []}
    for r in reqs:
        before = dict(pc.stats)
        t0 = time.perf_counter()
        out = eng.generate([r])[0]
        dt = time.perf_counter() - t0
        streams.append(np.asarray(out).tolist())
        if pc.stats["tier_hits"] > before["tier_hits"]:
            walls["tier_hit"].append(dt)
        elif pc.stats["hits"] > before["hits"]:
            walls["hbm_hit"].append(dt)
        else:
            walls["miss"].append(dt)
    return streams, walls


def _rotation_leg(model, reqs, host_tier_bytes):
    eng = _engine(model, host_tier_bytes, HBM_CAP_BLOCKS)
    t0 = time.perf_counter()
    streams, walls = _classified_serial(eng, reqs)
    wall = time.perf_counter() - t0
    st = eng.prefix_cache.stats
    hit_rate = st["hits"] / max(st["hits"] + st["misses"], 1)
    return {
        "hits": st["hits"], "misses": st["misses"],
        "hit_rate": round(hit_rate, 4),
        "tier_hits": st["tier_hits"],
        "spilled_blocks": st["spilled_blocks"],
        "readmitted_blocks": st["readmitted_blocks"],
        "tier_evictions": st["tier_evictions"],
        "prefill_tokens_saved": eng.stats["prefill_tokens_saved"],
        "requests_by_class": {k: len(v) for k, v in walls.items()},
        "wall_s": round(wall, 4),
        "decode_compilations": eng.decode_compilations(),
    }, streams


def _ttft_leg(model, host_tier_bytes, samples):
    """Alternate two PROBE_BLOCKS-long families under a cap that holds
    exactly one; max_new_tokens=1 makes the request wall the TTFT."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(53)
    vocab = model.config.vocab_size
    fams = [rng.randint(0, vocab, (PROBE_BLOCKS * BLOCK_SIZE,))
            .astype(np.int32) for _ in range(2)]
    tails = [rng.randint(0, vocab, (TAIL,)).astype(np.int32)
             for _ in range(samples + 3)]
    eng = _engine(model, host_tier_bytes, PROBE_BLOCKS)
    pc = eng.prefix_cache

    def one(fam, tail, max_new=1):
        r = GenerationRequest(
            prompt=np.concatenate([fams[fam], tail]).astype(np.int32),
            max_new_tokens=max_new)
        before = dict(pc.stats)
        t0 = time.perf_counter()
        out = np.asarray(eng.generate([r])[0]).tolist()
        dt = time.perf_counter() - t0
        cls = ("tier_hit" if pc.stats["tier_hits"] > before["tier_hits"]
               else "hbm_hit" if pc.stats["hits"] > before["hits"]
               else "miss")
        return out, dt, cls

    # warm both families (publishing B displaces A under the one-chain
    # cap) and every program the timed loop will run — including the
    # first readmission's inject trace on the tiered leg; walls
    # discarded. Same three requests either way, so the legs' stream
    # comparison stays aligned.
    one(0, tails[samples]), one(1, tails[samples + 1])
    one(0, tails[samples + 2])
    streams, walls, classes = [], [], []
    for i in range(samples):
        out, dt, cls = one(1 - i % 2, tails[i])
        streams.append(out)
        walls.append(dt)
        classes.append(cls)
    return {
        "samples": samples,
        "classes": classes,
        "ttft_ms_median": round(float(np.median(walls)) * 1e3, 3),
        "ttft_ms_p90": round(float(np.percentile(walls, 90)) * 1e3, 3),
        "prompt_tokens": PROBE_BLOCKS * BLOCK_SIZE + TAIL,
        "decode_compilations": eng.decode_compilations(),
    }, streams


def measure_tier(quick=True, families=None, rounds=None, samples=None):
    model = _models(quick)["jnp"]
    reqs = _rotation_workload(model.config.vocab_size,
                              families=families or (6 if quick else 8),
                              rounds=rounds or (3 if quick else 4))
    samples = samples or (8 if quick else 12)

    hbm, hbm_streams = _rotation_leg(model, reqs, host_tier_bytes=0)
    tiered, tier_streams = _rotation_leg(model, reqs,
                                         host_tier_bytes=TIER_BYTES)
    rot_equal = hbm_streams == tier_streams

    cold_ttft, cold_streams = _ttft_leg(model, 0, samples)
    warm_ttft, warm_streams = _ttft_leg(model, TIER_BYTES, samples)
    ttft_equal = cold_streams == warm_streams
    ttft_ratio = cold_ttft["ttft_ms_median"] / max(
        warm_ttft["ttft_ms_median"], 1e-9)

    hit_ratio = tiered["hit_rate"] / max(hbm["hit_rate"], 1e-9)
    compile_once = all(
        leg["decode_compilations"] == 1
        for leg in (hbm, tiered, cold_ttft, warm_ttft))
    accepted = bool(
        rot_equal and ttft_equal and compile_once
        and hit_ratio >= ACCEPT_HIT_RATE_RATIO
        and tiered["tier_hits"] > 0
        and all(c == "tier_hit" for c in warm_ttft["classes"])
        and all(c == "miss" for c in cold_ttft["classes"])
        and ttft_ratio >= ACCEPT_TTFT_RATIO)
    return {
        "block_size": BLOCK_SIZE,
        "hbm_cap_blocks": HBM_CAP_BLOCKS,
        "host_tier_bytes": TIER_BYTES,
        "requests": len(reqs),
        "hbm_only": hbm,
        "tiered": tiered,
        "hit_rate_ratio": round(hit_ratio, 4),
        "ttft_recompute": cold_ttft,
        "ttft_tier_hit": warm_ttft,
        "ttft_recompute_over_tier_hit": round(ttft_ratio, 4),
        "tokens_equal": bool(rot_equal and ttft_equal),
        "compile_once": compile_once,
        "accepted": accepted,
        "workload": "rotation: 2-block families revisited under an HBM "
                    "cap holding a third of them (revisits recompute "
                    "vs readmit from the host tier); ttft: two 8-block "
                    "families alternating under a one-chain cap, "
                    "max_new=1 so per-request wall is first-token "
                    "latency.",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "tier": measure_tier(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0 if res["tier"]["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
