"""Tensor-parallel serving benchmark: TP-over-heads on the CPU mesh
(README "Tensor-parallel serving").

Questions answered (all deterministic — exact counters + token
comparison, no wall-clock in the gates):

- **transparency**: are TP=2 streams BYTE-IDENTICAL to the single-chip
  baseline — greedy AND seeded-sampled — with fp collectives, and does
  ``decode_compilations() == 1`` hold inclusive of the sharded
  geometry?
- **collective bytes**: per-layer all-reduce wire bytes, fp vs
  EQuARX-style int8 (``collective_dtype="int8"``) — EXACT counter
  accounting (``serving_collective_bytes_total{dtype}`` reads the same
  ledger), cross-checked against the shared wire model
  (``quantization.collective_wire_bytes``) re-derived here from the
  trace's launch shapes. Acceptance: ratio >= 3x.
- **quality**: greedy-stream divergence of int8 collectives vs the
  fp/single-chip baseline — MEASURED (divergence rate + mean matched-
  prefix fraction), never assumed zero — plus replay determinism.

Runs on a virtual CPU mesh: XLA_FLAGS forces the host device count
BEFORE jax initializes (the multi-chip leg on real hardware banks the
same document shape, like MULTICHIP_r0*.json).

Usage:
  python scripts/bench_tp.py --quick [--json PATH]
"""
import argparse
import json
import os
import sys

if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_decode import _models  # noqa: E402  (same model as the other legs)

BLOCK_SIZE = 16
TP = 2


def _trace(quick=True):
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(23)
    sys_prompt = rng.randint(0, 2048, (32,)).astype(np.int32)
    n_req, max_new = (10, 8) if quick else (24, 16)
    reqs = []
    for i in range(n_req):
        tail = rng.randint(0, 2048, (8 + (i % 3) * 40,)).astype(np.int32)
        prompt = np.concatenate([sys_prompt, tail]) if i % 2 else tail
        kw = {}
        if i % 3 == 2:          # a sampled minority rides along
            kw = dict(temperature=0.8, top_k=32, seed=100 + i)
        reqs.append(GenerationRequest(prompt=prompt,
                                      max_new_tokens=max_new, **kw))
    return reqs


def _engine(model, tp, collective_dtype="fp", cost=None):
    from paddle_tpu.serving import ContinuousBatchingEngine
    eng = ContinuousBatchingEngine(
        model, num_slots=4, max_seq_len=192, decode_chunk=1,
        prefix_block_size=BLOCK_SIZE, prefill_chunk=32,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}),
        tp=tp, collective_dtype=collective_dtype)
    if cost is not None:
        eng.cost = cost
    return eng


def _run(model, tp, collective_dtype="fp", cost=None, quick=True):
    eng = _engine(model, tp, collective_dtype, cost=cost)
    outs = eng.generate(_trace(quick))
    return [tuple(int(t) for t in np.asarray(o)) for o in outs], eng


def _matched_prefix(a, b):
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n / max(min(len(a), len(b)), 1)


def _exact_ledger_check(model, collective_dtype):
    """Closed-form cross-check of the collective-bytes ledger: one
    24-token prompt, 5 greedy tokens, no chunking — exactly ONE cold
    prefill launch (group padded to 1, prompt bucket 32) and four
    single-tick unified steps (the padded packed buffer, once per
    layer per all-reduce site). The counter must equal the shared wire
    model applied to those known shapes TO THE BYTE."""
    from paddle_tpu.profiler.cost import CostObservatory
    from paddle_tpu.quantization import collective_wire_bytes
    from paddle_tpu.serving import GenerationRequest
    c = model.config
    co = CostObservatory()
    eng = _engine(model, TP, collective_dtype, cost=co)
    prompt = (np.arange(24, dtype=np.int32) % 100)
    eng.generate([GenerationRequest(prompt=prompt, max_new_tokens=5)])
    L, hm = c.num_hidden_layers, c.hidden_size
    expected = 2 * L * collective_wire_bytes(32, hm, TP, collective_dtype)
    expected += 4 * 2 * L * collective_wire_bytes(
        eng._token_budget, hm, TP, collective_dtype)
    return co.collective_bytes(collective_dtype), expected


def measure_tp(quick=True):
    from paddle_tpu.profiler.cost import CostObservatory
    from paddle_tpu.quantization import collective_wire_bytes

    model = _models(quick, attns=("jnp",))["jnp"]
    c = model.config

    # ---- transparency: tp=1 vs tp=2 byte-identical, compile-once
    base, eng1 = _run(model, 1, quick=quick)
    tp2, eng2 = _run(model, TP, "fp", quick=quick)
    tokens_equal = base == tp2
    compile_once = {"tp1": eng1.decode_compilations(),
                    "tp2": eng2.decode_compilations()}

    # ---- collective bytes: fp vs int8 wire traffic, exact counters
    co_fp, co_q = CostObservatory(), CostObservatory()
    _, _ = _run(model, TP, "fp", cost=co_fp, quick=quick)
    q_streams, _ = _run(model, TP, "int8", cost=co_q, quick=quick)
    fp_bytes = co_fp.collective_bytes("fp")
    q_bytes = co_q.collective_bytes("int8")
    fp_ops = co_fp.collectives["fp"]["ops"]
    q_ops = co_q.collectives["int8"]["ops"]
    # the two runs replay the same trace through the same scheduler, so
    # they launch the same shapes the same number of times (op counts
    # must MATCH) — the byte ratio then isolates the WIRE FORMAT
    ratio = fp_bytes / max(q_bytes, 1)
    # closed-form ledger cross-check on a fully known workload, both
    # wire dtypes — counter == model, to the byte
    got_fp, want_fp = _exact_ledger_check(model, "fp")
    got_q, want_q = _exact_ledger_check(model, "int8")
    exact_vs_model = (fp_ops == q_ops and got_fp == want_fp
                      and got_q == want_q)

    # ---- quality: int8-collective greedy divergence, MEASURED
    greedy_idx = [i for i, r in enumerate(_trace(quick))
                  if float(r.temperature) <= 0.0]
    div = [i for i in greedy_idx if q_streams[i] != base[i]]
    matched = [_matched_prefix(q_streams[i], base[i])
               for i in greedy_idx]
    q_again, _ = _run(model, TP, "int8", quick=quick)
    int8_deterministic = q_again == q_streams

    accepted = (tokens_equal and compile_once["tp1"] == 1
                and compile_once["tp2"] == 1 and ratio >= 3.0
                and exact_vs_model and int8_deterministic)
    return {
        "quick": bool(quick), "tp": TP,
        "model": {"hidden": c.hidden_size, "layers": c.num_hidden_layers,
                  "heads": c.num_attention_heads,
                  "kv_heads": c.num_key_value_heads},
        "tokens_equal": bool(tokens_equal),
        "compile_once": compile_once,
        "collective_bytes": {
            "fp": int(fp_bytes), "int8": int(q_bytes),
            "fp_ops": int(fp_ops), "int8_ops": int(q_ops),
            "reduction_ratio": round(ratio, 4),
            "exact_vs_model": bool(exact_vs_model),
            "exact_check": {"fp": [int(got_fp), int(want_fp)],
                            "int8": [int(got_q), int(want_q)]},
        },
        "greedy_divergence": {
            "streams": len(greedy_idx), "diverged": len(div),
            "divergence_rate": round(len(div) / max(len(greedy_idx), 1),
                                     6),
            "mean_matched_prefix": round(float(np.mean(matched)), 6)
            if matched else 1.0,
        },
        "int8_deterministic": bool(int8_deterministic),
        "collective_bytes_reduction": round(ratio, 4),
        "accepted": bool(accepted),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()
    doc = measure_tp(quick=True if args.quick else False)
    out = json.dumps(doc, indent=2, sort_keys=True)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
    return 0 if doc["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
