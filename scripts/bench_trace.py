"""Tracer overhead benchmark (README "Tracing & debugging").

Question answered: what does the request-lifecycle span tracer
(``profiler/tracing.py``) cost the serving engine — (a) when it is
merely INSTALLED but disabled (the production default: every
instrumentation site must reduce to one attribute check), and (b) when
it is recording?

Three legs drive the SAME engine configuration, kernel, and seeded
request set through ``engine.generate()`` in-process (the
``bench_serve`` direct leg's methodology — same model, same
two-program baseline configuration as the banked SERVE_BENCH.json, so
the numbers are comparable to that bank):

- **baseline** — no tracer installed (``engine.tracer is None``);
- **disabled** — a tracer installed, not recording. The acceptance
  gate: ≤ 1% wall overhead vs baseline;
- **enabled** — recording everything into a ring sized to hold the
  full run; reported openly (lifecycle spans + step phases are built
  per step, so this is the real cost of ``--trace``).

Legs are interleaved and each is scored by its BEST wall over
``repeats`` rounds (identical code modulo the tracer, so best-of
converges to the same floor when the tracer truly costs nothing).
``repeats=9`` and a FLOOR-ratio acceptance gate (the disabled leg
against the fastest of the three legs, the ``bench_dispatch.py``
method): on a loaded box best-of-5 leaves ~4% scheduler noise between
legs — observed as the ENABLED leg measuring faster than baseline —
which would fail a 1% gate on pure jitter. Token streams are asserted
identical across all legs — tracing must observe, never perturb.

Usage:
  python scripts/bench_trace.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_decode import _models  # noqa: E402  (same model as bench_serve)
from bench_serve import _requests  # noqa: E402

SERVE_BENCH_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "SERVE_BENCH.json")


def _run_leg(model, reqs, num_slots, s_max, tracer):
    """One timed pass of the whole request set through a fresh engine
    (shared jit cache — compile cost excluded), with ``tracer`` as the
    engine's tracer (None = baseline)."""
    from dataclasses import replace

    from paddle_tpu.serving import ContinuousBatchingEngine
    eng = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_seq_len=s_max, decode_chunk=1,
        ragged_step=False, spec_decode=False,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}))
    eng.tracer = tracer
    t0 = time.perf_counter()
    outs = eng.generate([replace(r) for r in reqs])
    dt = time.perf_counter() - t0
    return dt, [o.tolist() for o in outs]


def measure_trace_overhead(quick=True, n_requests=8, max_new=None,
                           num_slots=4, repeats=9):
    from paddle_tpu.profiler.tracing import SpanTracer
    max_new = max_new or (24 if quick else 64)
    s_max = 128 if quick else 256
    model = _models(quick)["jnp"]
    reqs = _requests(n_requests, max_new, model.config.vocab_size)
    # a ring big enough that the enabled leg never drops (drop
    # bookkeeping is cheap, but the measured leg should be the
    # everything-retained worst case)
    tr_off = SpanTracer(capacity=1 << 16)
    tr_on = SpanTracer(capacity=1 << 16).enable()
    # warm every program shape once (shared jit cache)
    _run_leg(model, reqs[:2], num_slots, s_max, None)
    best = {"baseline": None, "disabled": None, "enabled": None}
    toks = {}
    for _ in range(repeats):    # interleave; best wall per leg
        for name, tracer in (("baseline", None), ("disabled", tr_off),
                             ("enabled", tr_on)):
            if tracer is tr_on:
                tr_on.clear()
                tr_on.enable()
            dt, out = _run_leg(model, reqs, num_slots, s_max, tracer)
            toks[name] = out
            if best[name] is None or dt < best[name]:
                best[name] = dt
    tokens = sum(len(o) for o in toks["baseline"])
    tokens_equal = (toks["baseline"] == toks["disabled"]
                    == toks["enabled"])
    events = len(tr_on.events())
    # the acceptance ratio measures the disabled leg against the FLOOR
    # (fastest of the three legs): all three run identical device work,
    # so the floor is the machine's true wall for the workload and the
    # disabled leg's distance from it bounds the guard's cost — a
    # baseline leg that lands slow (scheduler jitter) must not
    # manufacture a >1% "overhead" out of noise
    floor = min(best.values())
    disabled_ratio = best["disabled"] / floor
    enabled_ratio = best["enabled"] / floor
    # context: the banked HTTP serve bench this engine config mirrors
    banked = None
    try:
        with open(SERVE_BENCH_PATH) as f:
            banked = json.load(f)["serve_http"]["direct"]
    except (OSError, ValueError, KeyError):
        pass
    return {
        "baseline_wall_s": round(best["baseline"], 4),
        "disabled_wall_s": round(best["disabled"], 4),
        "enabled_wall_s": round(best["enabled"], 4),
        "disabled_overhead_ratio": round(disabled_ratio, 4),
        "enabled_overhead_ratio": round(enabled_ratio, 4),
        "disabled_vs_baseline_ratio": round(
            best["disabled"] / best["baseline"], 4),
        "enabled_events_captured": events,
        "enabled_us_per_event": round(
            max(best["enabled"] - best["baseline"], 0.0)
            / max(events, 1) * 1e6, 3),
        "tokens": tokens,
        "tokens_equal": tokens_equal,
        "repeats": repeats,
        "n_requests": n_requests, "max_new": max_new,
        "num_slots": num_slots,
        "banked_serve_direct": banked,
        # the acceptance gate: a disabled tracer must be free (<= 1%),
        # and tracing must never change a token
        "accepted": bool(tokens_equal and disabled_ratio <= 1.01),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "trace_overhead": measure_trace_overhead(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0 if res["trace_overhead"]["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
