"""Round-3 perf sweep: time the bench train step under config variants.

Each variant runs in a child process (isolated compile cache / OOM blast
radius). Prints one JSON line per variant.

Usage:
    python scripts/perf_sweep.py            # run all variants
    python scripts/perf_sweep.py --child '{"attention_layout": "bhsd"}'
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

VARIANTS = [
    ("base", {}),
    ("dots", {"recompute_policy": "dots"}),
    ("bhsd", {"attention_layout": "bhsd"}),
    ("chunk512", {"loss_chunk": 512}),
    ("bhsd+chunk", {"attention_layout": "bhsd", "loss_chunk": 512}),
    ("bhsd+chunk+dots", {"attention_layout": "bhsd", "loss_chunk": 512,
                         "recompute_policy": "dots"}),
    ("bhsd+chunk+norematt", {"attention_layout": "bhsd", "loss_chunk": 512,
                             "use_recompute": False}),
]


def child(overrides):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.profiler.metrics import peak_flops_per_chip

    paddle.seed(0)
    kw = dict(vocab_size=32000, hidden_size=1024, intermediate_size=2816,
              num_hidden_layers=24, num_attention_heads=16,
              num_key_value_heads=16, max_position_embeddings=2048,
              use_recompute=True, dtype="bfloat16")
    kw.update(overrides)
    cfg = LlamaConfig(**kw)
    model = LlamaForCausalLM(cfg)
    n_params = model.num_params()
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    step = TrainStep(model, lambda loss, _lab: loss, opt)

    B, S = 8, 2048
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32))

    t0 = time.perf_counter()
    for _ in range(3):
        float(step.step((ids, ids), (ids,)).value)
    compile_s = time.perf_counter() - t0

    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step.step((ids, ids), (ids,))
    final_loss = float(loss.value)
    dt = time.perf_counter() - t0

    tokens_per_sec = iters * B * S / dt
    mfu = tokens_per_sec * 6.0 * n_params / peak_flops_per_chip()
    print(json.dumps({"mfu": round(float(mfu), 4),
                      "tok_s": round(tokens_per_sec),
                      "step_ms": round(dt / iters * 1000, 1),
                      "warm_s": round(compile_s, 1),
                      "loss": round(final_loss, 3)}))
    return 0


def main():
    for name, overrides in VARIANTS:
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child",
                 json.dumps(overrides)],
                timeout=600, capture_output=True, text=True, cwd=REPO)
            line = next((ln for ln in reversed(p.stdout.splitlines())
                         if ln.startswith("{")), None)
            if p.returncode == 0 and line:
                print(f"{name:24s} {line}", flush=True)
            else:
                print(f"{name:24s} FAILED rc={p.returncode} "
                      f"{p.stderr.strip()[-300:]}", flush=True)
        except subprocess.TimeoutExpired:
            print(f"{name:24s} TIMEOUT", flush=True)
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(child(json.loads(sys.argv[sys.argv.index("--child") + 1])))
    sys.exit(main())
