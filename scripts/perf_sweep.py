"""Round-3 perf sweep: time the bench train step under config variants.

Each variant runs in a child process (isolated compile cache / OOM blast
radius). Prints one JSON line per variant.

Usage:
    python scripts/perf_sweep.py            # run all variants
    python scripts/perf_sweep.py --child '{"attention_layout": "bhsd"}'
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

VARIANTS = [
    ("base", {}),
    ("dots", {"recompute_policy": "dots"}),
    ("bhsd", {"attention_layout": "bhsd"}),
    ("chunk512", {"loss_chunk": 512}),
    ("bhsd+chunk", {"attention_layout": "bhsd", "loss_chunk": 512}),
    ("bhsd+chunk+dots", {"attention_layout": "bhsd", "loss_chunk": 512,
                         "recompute_policy": "dots"}),
    ("bhsd+chunk+norematt", {"attention_layout": "bhsd", "loss_chunk": 512,
                             "use_recompute": False}),
    # no-remat via grad accumulation: fwd+bwd per microbatch inside a scan
    # keeps only one microbatch's activations live, so the full-layer remat
    # (its ~2N extra FLOP/token) can be dropped without OOM
    ("noremat+accum2", {"use_recompute": False, "_accum": 2}),
    ("noremat+accum2+chunk", {"use_recompute": False, "loss_chunk": 512,
                              "_accum": 2}),
    ("noremat+accum4+chunk", {"use_recompute": False, "loss_chunk": 512,
                              "_accum": 4}),
    ("bhsd+noremat+accum2+chunk", {"attention_layout": "bhsd",
                                   "use_recompute": False,
                                   "loss_chunk": 512, "_accum": 2}),
    ("v2:bhsd+noremat+accum4+chunk", {"attention_layout": "bhsd",
                                      "use_recompute": False,
                                      "loss_chunk": 512, "_accum": 4}),
    # hd=128: same H=1024 / params, 8 heads x 128 — the attention
    # contractions fill the 128-wide MXU instead of running at 50% (hd=64)
    ("v2:hd128+noremat+accum4+chunk", {"num_attention_heads": 8,
                                       "num_key_value_heads": 8,
                                       "use_recompute": False,
                                       "loss_chunk": 512, "_accum": 4}),
    ("v2:bhsd+hd128+noremat+accum4+chunk", {"attention_layout": "bhsd",
                                            "num_attention_heads": 8,
                                            "num_key_value_heads": 8,
                                            "use_recompute": False,
                                            "loss_chunk": 512, "_accum": 4}),
    # larger global batch amortizes the optimizer update + accum epilogue
    ("v2:hd128+noremat+accum8+chunk+B16", {"num_attention_heads": 8,
                                           "num_key_value_heads": 8,
                                           "use_recompute": False,
                                           "loss_chunk": 512, "_accum": 8,
                                           "_B": 16}),
]


def child(overrides):
    """Thin wrapper over bench._measure_config — ONE measurement harness
    (same model, token accounting, and MFU formula as the driver bench)."""
    import bench
    r = bench._measure_config("sweep", dict(overrides))
    print(json.dumps({"mfu": round(r["mfu"], 4),
                      "tok_s": round(r["tok_s"]),
                      "step_ms": round(r["step_ms"], 1),
                      "warm_s": round(r["warm_s"], 1),
                      "loss": round(r["loss"], 3)}))
    return 0


def main():
    only = None
    if "--only" in sys.argv:
        pos = sys.argv.index("--only") + 1
        if pos >= len(sys.argv):
            print("usage: perf_sweep.py [--only substr[,substr...]]",
                  file=sys.stderr)
            return 2
        only = sys.argv[pos].split(",")
    for name, overrides in VARIANTS:
        if only is not None and not any(s in name for s in only):
            continue
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child",
                 json.dumps(overrides)],
                timeout=600, capture_output=True, text=True, cwd=REPO)
            line = next((ln for ln in reversed(p.stdout.splitlines())
                         if ln.startswith("{")), None)
            if p.returncode == 0 and line:
                print(f"{name:24s} {line}", flush=True)
            else:
                print(f"{name:24s} FAILED rc={p.returncode} "
                      f"{p.stderr.strip()[-300:]}", flush=True)
        except subprocess.TimeoutExpired:
            print(f"{name:24s} TIMEOUT", flush=True)
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(child(json.loads(sys.argv[sys.argv.index("--child") + 1])))
    sys.exit(main())
