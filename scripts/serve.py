#!/usr/bin/env python
"""Launcher shim for the HTTP serving gateway — identical to
``python -m paddle_tpu.serving.server``; see that module (or README
"Serving over HTTP") for flags and curl examples.

    python scripts/serve.py --preset tiny --port 8000
    python scripts/serve.py --preset tiny --port 8000 \\
        --classes 'latency*,standard,batch' --slo-ttft-ms 80,200,0
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.serving.server.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
