"""Child for the CP-inside-PP parity test: fresh interpreter with the
legacy partitioner from the start (mixing partitioners in one process
aborts XLA's CPU backend)."""
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
import jax

jax.config.update("jax_platforms", "cpu")
# --shardy: run under the Shardy partitioner (the default going forward).
# Works since the ring body stopped calling jax.lax.axis_index inside the
# nested manual region (its position now arrives as a sharded iota input).
if "--shardy" in sys.argv:
    sys.argv.remove("--shardy")
    jax.config.update("jax_use_shardy_partitioner", True)
else:
    jax.config.update("jax_use_shardy_partitioner", False)

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW
from paddle_tpu.parallel import mesh as mesh_mod


def losses(pp, sep, cp, micro):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    mesh_mod._STATE["mesh"] = None
    s = fleet.DistributedStrategy()
    s.hybrid_configs = {"pp_degree": pp, "sep_degree": sep,
                        "dp_degree": 8 // (pp * sep)}
    fleet.init(is_collective=True, strategy=s)
    hcg = fleet.get_hybrid_communicate_group()
    paddle.seed(52)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=32,
                      use_recompute=False, context_parallel=cp,
                      pipeline_microbatches=micro)
    model = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = TrainStep(model, lambda loss, _l: loss,
                     opt, mesh=hcg.mesh if (pp > 1 or sep > 1) else None)
    ids = paddle.to_tensor(np.random.RandomState(9).randint(
        0, 64, (8, 16)).astype(np.int32))
    return [float(step.step((ids, ids), (ids,)).value) for _ in range(3)]


if __name__ == "__main__":
    cp = sys.argv[1] if len(sys.argv) > 1 else "ring"
    serial = losses(pp=1, sep=1, cp="", micro=0)
    nested = losses(pp=2, sep=2, cp=cp, micro=2)
    np.testing.assert_allclose(serial, nested, rtol=2e-4, atol=2e-5)
    print(f"CP({cp})-inside-PP parity OK: {serial} == {nested}")
