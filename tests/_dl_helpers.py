"""Picklable dataset helpers for the multiprocess DataLoader tests (spawn
workers re-import this module, so the classes must live at module scope)."""
import os

import numpy as np

from paddle_tpu.io import Dataset


class RangeSquareDataset(Dataset):
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i, i * i], np.float32)


class CrashingDataset(Dataset):
    """Hard-kills the worker process on a poisoned index (simulates a
    segfaulting C extension, not a catchable Python error)."""

    def __init__(self, n, poison):
        self.n = n
        self.poison = poison

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if i == self.poison:
            os._exit(13)
        return np.asarray([i], np.float32)


class RaisingDataset(Dataset):
    def __init__(self, n, bad):
        self.n = n
        self.bad = bad

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if i == self.bad:
            raise ValueError(f"bad sample {i}")
        return np.asarray([i], np.float32)


class WorkerIdDataset(Dataset):
    """Returns the worker id serving each index (get_worker_info check)."""

    def __len__(self):
        return 16

    def __getitem__(self, i):
        from paddle_tpu.io.dataloader import get_worker_info
        info = get_worker_info()
        return np.asarray([i, -1 if info is None else info.id], np.float32)


def _ring_producer(name):
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from paddle_tpu.csrc import ShmRing
    w = ShmRing.open(name)
    for i in range(10):
        w.push(bytes([i]) * 1000)
    w.close(unlink=False)
