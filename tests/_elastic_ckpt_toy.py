"""Elastic trainer toy for the elastic x checkpoint e2e test
(tests/test_elastic.py): trains under the CURRENT elastic world size with
a world-dependent hybrid layout, resumes from the distributed checkpoint
(reshard-on-load) if one exists, saves one after its steps, and — in the
pre-scale phase — idles so the external agent can trigger the scale event.

Phase layouts differ on purpose: mp4 x sharding2 before the scale,
mp2 x sharding4 after — both the mp-sharded weights AND the ZeRO-sharded
optimizer slots must reshard on resume (SURVEY §5.3 <-> §5.4 loop).
"""
import json
import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import nn  # noqa: E402
from paddle_tpu.core.tensor import Tensor  # noqa: E402
from paddle_tpu.distributed import fleet  # noqa: E402
from paddle_tpu.distributed import load_state_dict, save_state_dict  # noqa: E402
from paddle_tpu.jit import TrainStep  # noqa: E402
from paddle_tpu.optimizer import AdamW  # noqa: E402
from paddle_tpu.parallel.fleet.mp import (ColumnParallelLinear,  # noqa: E402
                                          RowParallelLinear)

OUT = sys.argv[1] if len(sys.argv) > 1 else "."
CKPT = os.path.join(OUT, "ckpt")
# default 1 so the e2e test can IMPORT this module for MpMLP/oracle reuse
WORLD = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
# the world size drives PHASE selection only: the second elastic "node"
# in the test is a bare heartbeat agent with no trainer, so this single
# trainer must not attempt a 2-process jax.distributed rendezvous
for _k in ("PADDLE_TRAINERS_NUM", "PADDLE_TRAINER_ID", "PADDLE_MASTER",
           "PADDLE_TRAINER_ENDPOINTS"):
    os.environ.pop(_k, None)
STEPS_PER_PHASE = 2


class MpMLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.up = ColumnParallelLinear(16, 32, gather_output=False)
        self.down = RowParallelLinear(32, 16, input_is_parallel=True)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        return self.down(F.relu(self.up(x)))


def build_step():
    degrees = ({"mp_degree": 4, "sharding_degree": 2} if WORLD == 1
               else {"mp_degree": 2, "sharding_degree": 4})
    s = fleet.DistributedStrategy()
    s.hybrid_configs = degrees
    fleet.init(is_collective=True, strategy=s)
    hcg = fleet.get_hybrid_communicate_group()
    paddle.seed(0)
    model = MpMLP()
    opt = AdamW(learning_rate=0.05, parameters=model.parameters())
    step = TrainStep(model, lambda out, label: ((out - label) ** 2).mean(),
                     opt, mesh=hcg.mesh, sharding_stage=2)
    return step, degrees


def flat_state(step):
    tree = {"params": step.params, "opt": step.opt_state}
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(kp): Tensor(v) for kp, v in leaves}


def main():
    step, degrees = build_step()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))

    start = 0
    progress = os.path.join(OUT, "progress.json")
    if os.path.exists(os.path.join(CKPT, "0.metadata.json")):
        st = flat_state(step)
        load_state_dict(st, CKPT)  # reshard-on-load into the NEW layout
        tree = {"params": step.params, "opt": step.opt_state}
        leaves_kp, treedef = jax.tree_util.tree_flatten_with_path(tree)
        new_tree = jax.tree_util.tree_unflatten(
            treedef,
            [st[jax.tree_util.keystr(kp)].value for kp, _ in leaves_kp])
        step._params = new_tree["params"]
        step._opt_state = new_tree["opt"]
        start = json.load(open(progress))["step"]

    losses = [float(step.step((x,), (y,)).value)
              for _ in range(STEPS_PER_PHASE)]

    save_state_dict(flat_state(step), CKPT)
    json.dump({"step": start + STEPS_PER_PHASE}, open(progress, "w"))
    json.dump({"start": start, "losses": losses, "world": WORLD,
               "degrees": degrees},
              open(os.path.join(OUT, f"phase.{WORLD}.json"), "w"))
    if WORLD == 1:
        time.sleep(120)  # idle until the scale event tears us down


if __name__ == "__main__":
    main()
