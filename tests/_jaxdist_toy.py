"""Worker for the REAL 2-process jax.distributed rendezvous test: imports
paddle_tpu (must NOT initialize the backend), init_parallel_env (agrees a
coordinator port via the rendezvous store when --master has port 0), then
proves the distributed runtime is actually up with process_count()."""
import sys

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist

dist.init_parallel_env()
import jax  # noqa: E402

x = paddle.to_tensor(np.float32([1.0 + dist.get_rank()]))
print(f"JAXDIST rank={jax.process_index()} nproc={jax.process_count()} "
      f"val={float(x.numpy()[0])}", flush=True)
