"""Toy worker that fails on its first run (marker file), succeeds after —
exercises the launcher's elastic restart-with-backoff path."""
import os
import sys

marker = os.path.join(sys.argv[1], "ran_once")
if not os.path.exists(marker):
    open(marker, "w").write("1")
    print("first run: failing deliberately")
    sys.exit(1)
print("second run: ok")
