"""Toy worker for launcher tests: dumps its paddle env to a per-rank file.
Deliberately imports no jax/paddle — launcher plumbing only."""
import json
import os
import sys

out_dir = sys.argv[1]
rank = os.environ.get("PADDLE_TRAINER_ID", "?")
with open(os.path.join(out_dir, f"env.{rank}.json"), "w") as f:
    json.dump({k: v for k, v in os.environ.items()
               if k.startswith(("PADDLE_", "FLAGS_selected"))}, f)
print(f"toy worker rank={rank} ok")
