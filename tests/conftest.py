"""Test environment: force the CPU backend with 8 virtual devices — the
reference's single-node multi-process test pattern (SURVEY.md §4) mapped to
a virtual device mesh. Must run before jax initializes its backend."""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# fp32 matmuls in tests compare against float64-free numpy oracles
jax.config.update("jax_default_matmul_precision", "highest")
