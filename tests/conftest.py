"""Test environment: force the CPU backend with 8 virtual devices — the
reference's single-node multi-process test pattern (SURVEY.md §4) mapped to
a virtual device mesh. Must run before jax initializes its backend."""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
# EXPORTED (not just config.update) so multiprocessing-spawn children —
# DataLoader workers, launcher toys, shm-ring producers — inherit them:
# with the axon tunnel dead/busy, a child that initializes the axon PJRT
# plugin hangs at import, and in-function env fixes run too late because
# the helper module imports paddle_tpu at module scope.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# fp32 matmuls in tests compare against float64-free numpy oracles
jax.config.update("jax_default_matmul_precision", "highest")
