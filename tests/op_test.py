"""OpTest harness (reference: ``test/legacy_test/op_test.py``).

Pattern: each op is checked against a NumPy oracle (``check_output``) and its
analytic gradient against numeric differentiation (``check_grad``) — run
through the eager tape AND the jitted path, the two execution engines of this
framework (the reference runs eager + static graph).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


class OpTest:
    rtol = 1e-5
    atol = 1e-6

    def check_output(self, op_fn, np_fn, inputs, rtol=None, atol=None, **kwargs):
        """Run op eagerly and jitted; compare both against the numpy oracle."""
        rtol = rtol or self.rtol
        atol = atol or self.atol
        tensors = [paddle.to_tensor(x) for x in inputs]
        expected = np_fn(*[np.asarray(x) for x in inputs])
        # eager
        out = op_fn(*tensors, **kwargs)
        self._compare(out, expected, rtol, atol, "eager")
        # jitted
        import jax

        def pure(vals):
            ts = [Tensor(v) for v in vals]
            r = op_fn(*ts, **kwargs)
            import jax as _j
            return _j.tree.map(lambda t: t.value, r,
                               is_leaf=lambda t: isinstance(t, Tensor))

        out_j = jax.jit(pure)([t.value for t in tensors])
        self._compare_raw(out_j, expected, rtol, atol, "jit")
        return out

    def _compare(self, out, expected, rtol, atol, tag):
        if isinstance(expected, (tuple, list)):
            for o, e in zip(out, expected):
                np.testing.assert_allclose(np.asarray(o.value), e, rtol=rtol,
                                           atol=atol, err_msg=tag)
        else:
            np.testing.assert_allclose(np.asarray(out.value), expected,
                                       rtol=rtol, atol=atol, err_msg=tag)

    def _compare_raw(self, out, expected, rtol, atol, tag):
        import jax
        flat = jax.tree.leaves(out)
        eflat = expected if isinstance(expected, (tuple, list)) else [expected]
        for o, e in zip(flat, eflat):
            np.testing.assert_allclose(np.asarray(o), e, rtol=rtol, atol=atol,
                                       err_msg=tag)

    def check_grad(self, op_fn, inputs, output_idx=0, eps=1e-3, rtol=2e-2,
                   atol=1e-3, **kwargs):
        """Numeric vs analytic gradient (sum-of-outputs loss)."""
        tensors = [paddle.to_tensor(np.asarray(x, np.float64).astype(np.float32),
                                    stop_gradient=False) for x in inputs]

        def loss_of(ts):
            out = op_fn(*ts, **kwargs)
            if isinstance(out, (tuple, list)):
                out = out[output_idx]
            return out.sum() if out.ndim > 0 else out

        loss = loss_of(tensors)
        loss.backward()
        for i, t in enumerate(tensors):
            analytic = np.asarray(t.grad.value)
            numeric = np.zeros_like(np.asarray(t.value))
            flatv = np.asarray(t.value).ravel()
            for j in range(flatv.size):
                for sign, acc in ((1, None), ):
                    pass
                plus = flatv.copy()
                plus[j] += eps
                minus = flatv.copy()
                minus[j] -= eps
                tp = [paddle.to_tensor(np.asarray(x.value)) for x in tensors]
                tp[i] = paddle.to_tensor(plus.reshape(t.shape))
                tm = [paddle.to_tensor(np.asarray(x.value)) for x in tensors]
                tm[i] = paddle.to_tensor(minus.reshape(t.shape))
                with paddle.no_grad():
                    lp = float(loss_of(tp).value)
                    lm = float(loss_of(tm).value)
                numeric.ravel()[j] = (lp - lm) / (2 * eps)
            np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                                       err_msg=f"grad of input {i}")
