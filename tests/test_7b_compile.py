"""7B-shaped hybrid-parallel compile evidence (VERDICT r2 item 3).

AOT-lowers LLaMA with REAL 7B layer shapes (hidden 4096, ffn 11008,
32 heads, vocab 32000) over hybrid meshes using ShapeDtypeStruct inputs
(no host RAM for weights), and asserts the partitioned HLO never
materializes a full-size decoder weight via all-gather (the OOM signature
of a wrong layout: ZeRO-3-style gather of [4096,11008] onto every device).

Three cases:
- fwd+bwd over dp2 x mp2 x sharding2 — the TP/ZeRO gradient+optimizer
  layout story (pipeline off); full backend compile on XLA-CPU.
- fwd over pp2 x mp2 x sharding2 — the pipeline layout story
  (collective-permute handoffs, stage-resident weights); full compile.
- fwd+BWD over pp2 x mp2 x sharding2 at depth 4 AND the full 32 layers —
  XLA-CPU's backend codegen SIGABRTs on this module, so the evidence is
  pinned at the partitioning level: a child dumps the
  after_spmd-partitioning HLO (which completes before the crash) and the
  test asserts its collective structure.

The first two cases run at depth 4 (GSPMD layout decisions are per-layer
and the CPU backend cannot codegen deeper); the partition-level backward
case covers depth 32. Matches BASELINE.json config 3 (LLaMA-2 7B Fleet
hybrid).
"""
import re

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.parallel import mesh as mesh_mod


def _reset_fleet(**degrees):
    mesh_mod._STATE["mesh"] = None
    s = fleet.DistributedStrategy()
    s.hybrid_configs = degrees
    fleet.init(is_collective=True, strategy=s)
    return fleet.get_hybrid_communicate_group()


# full-size decoder weight shapes that must never appear as an all-gather
# result (materializing a whole layer's ffn/attn matrix on every device)
_FORBIDDEN = [
    (4096, 11008),   # gate/up full matrix
    (11008, 4096),   # down full matrix
    (4096, 4096),    # qkv/o full matrix
]

H, I, V, NH, HD = 4096, 11008, 32000, 32, 128
L = 4  # 7B per-layer dims; depth shrunk for CPU compile viability


def _params_sds(mesh):
    dt = jnp.bfloat16

    def sds(shape, spec):
        return jax.ShapeDtypeStruct(shape, dt,
                                    sharding=NamedSharding(mesh, spec))

    return dict(
        embed=sds((V, H), P("mp", None)),
        wq=sds((L, H, NH * HD), P("pp", None, "mp")),
        wk=sds((L, H, NH * HD), P("pp", None, "mp")),
        wv=sds((L, H, NH * HD), P("pp", None, "mp")),
        wo=sds((L, NH * HD, H), P("pp", "mp", None)),
        w_gate=sds((L, H, I), P("pp", None, "mp")),
        w_up=sds((L, H, I), P("pp", None, "mp")),
        w_down=sds((L, I, H), P("pp", "mp", None)),
        input_ln=sds((L, H), P("pp", None)),
        post_ln=sds((L, H), P("pp", None)),
        final_norm=sds((H,), P(None)),
        lm_head=sds((H, V), P(None, "mp")),
    )


def _loss_fn(pipeline_microbatches):
    from paddle_tpu.models.llama import _llama_forward

    def loss_fn(params, ids):
        return _llama_forward.raw_fn(
            ids, ids, NH, NH, HD, 1e-5, 10000.0, True, False,
            policy="full", pipeline_microbatches=pipeline_microbatches,
            attention_layout="bhsd", loss_chunk=128, **params)

    return loss_fn


def _assert_no_full_weight_allgather(hlo):
    bad = []
    for line in hlo.splitlines():
        if "all-gather(" not in line and " all-gather" not in line:
            continue
        shapes = re.findall(r"bf16\[([0-9,]+)\]", line.split("=")[0])
        for sh in shapes:
            dims = tuple(int(d) for d in sh.split(","))
            for fb in _FORBIDDEN:
                if len(dims) >= 2 and tuple(dims[-2:]) == fb:
                    bad.append(line[:160])
    assert not bad, "full-weight all-gathers found:\n" + "\n".join(bad)


class TestLlama7BHybridCompile:
    @pytest.mark.slow
    def test_7b_fwd_bwd_tp_zero_layout(self):
        """Train-step gradients at 7B dims over dp2 x mp2 x sharding2:
        partitions without gathering any full decoder weight."""
        hcg = _reset_fleet(dp_degree=2, mp_degree=2, sharding_degree=2)
        mesh = hcg.mesh
        params = _params_sds(mesh)
        B, S = 4, 512
        ids = jax.ShapeDtypeStruct(
            (B, S), jnp.int32,
            sharding=NamedSharding(mesh, P(("dp", "sharding"), None)))
        loss_fn = _loss_fn(0)

        def train_obj(params, ids):
            return jax.value_and_grad(loss_fn)(params, ids)

        compiled = jax.jit(train_obj).lower(params, ids).compile()
        hlo = compiled.as_text()
        assert "all-reduce" in hlo or "reduce-scatter" in hlo  # grad sync
        _assert_no_full_weight_allgather(hlo)
        mem = compiled.memory_analysis()
        if mem is not None:
            arg_gb = mem.argument_size_in_bytes / 2**30
            assert arg_gb < 12.0, f"{arg_gb:.1f} GiB args per device"

    @pytest.mark.slow
    def test_7b_fwd_pipeline_layout(self):
        """Forward at 7B dims over pp2 x mp2 x sharding2 with the real
        pipeline schedule: collective-permute handoffs present, no full
        decoder weight gathered."""
        hcg = _reset_fleet(pp_degree=2, mp_degree=2, sharding_degree=2,
                           dp_degree=1)
        mesh = hcg.mesh
        params = _params_sds(mesh)
        B, S = 4, 256
        ids = jax.ShapeDtypeStruct(
            (B, S), jnp.int32,
            sharding=NamedSharding(mesh, P(("dp", "sharding"), None)))
        compiled = jax.jit(_loss_fn(2)).lower(params, ids).compile()
        hlo = compiled.as_text()
        assert "collective-permute" in hlo  # pp handoffs
        _assert_no_full_weight_allgather(hlo)

    @pytest.mark.slow
    @pytest.mark.parametrize("depth", [4, 32])
    def test_7b_pipeline_backward_partitioned_layout(self, depth):
        """The scoped-out half of the r3 evidence (VERDICT r3 item 5): the
        pipeline BACKWARD sharding at 7B dims, pinned at the partitioning
        level — including FULL 32-layer depth (r3 weak 8: the prior
        evidence was 4 layers deep). XLA-CPU's backend codegen SIGABRTs on
        this module, but the SPMD partitioner runs to completion first —
        so the child process compiles with --xla_dump_hlo_pass_re=spmd.*
        and this test harvests the after_spmd-partitioning dump the crash
        leaves behind, then asserts the partitioned fwd+bwd has pipeline
        collective-permutes, gradient all-reduces, and NO
        full-decoder-weight all-gather."""
        import glob
        import os
        import subprocess
        import sys
        import tempfile

        dump = tempfile.mkdtemp(prefix="xla7b_")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        child = f"""
import os, sys
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           "--xla_dump_to={dump} "
                           "--xla_dump_hlo_pass_re=spmd.*")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
sys.path.insert(0, os.path.join({repo!r}, "tests"))
import jax.numpy as jnp
import test_7b_compile as t
t.L = {depth}
from jax.sharding import NamedSharding, PartitionSpec as P
hcg = t._reset_fleet(pp_degree=2, mp_degree=2, sharding_degree=2, dp_degree=1)
params = t._params_sds(hcg.mesh)
ids = jax.ShapeDtypeStruct((4, 256), jnp.int32,
    sharding=NamedSharding(hcg.mesh, P(("dp", "sharding"), None)))
fn = t._loss_fn(2)
jax.jit(lambda p, i: jax.value_and_grad(fn)(p, i)).lower(
    params, ids).compile()
"""
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.run([sys.executable, "-c", child], env=env,
                           capture_output=True, text=True, timeout=1500)
        # rc 0 (backend fixed) and rc -6/134 (known codegen SIGABRT) both
        # leave the partitioned dump; anything else is a real failure
        assert p.returncode in (0, -6, 134), (p.returncode, p.stderr[-800:])
        dumps = glob.glob(os.path.join(dump, "*after_spmd-partitioning*"))
        assert dumps, f"no spmd-partitioning dump in {dump}"
        hlo = open(max(dumps, key=os.path.getsize)).read()
        assert hlo.count("collective-permute") >= 2  # fwd AND bwd handoffs
        assert "all-reduce" in hlo                   # grad sync
        _assert_no_full_weight_allgather(hlo)
