"""paddle.audio tests (reference: ``test/legacy_test/test_audio_functions.py``
† pattern — mel scale math, filterbanks, windows, feature layers against
scipy/closed-form oracles)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.audio import functional as AF

scipy_signal = pytest.importorskip("scipy.signal")


class TestScales:
    def test_hz_mel_roundtrip(self):
        f = np.array([0.0, 440.0, 1000.0, 4000.0], np.float32)
        mel = AF.hz_to_mel(paddle.to_tensor(f))
        back = AF.mel_to_hz(mel)
        np.testing.assert_allclose(back.numpy(), f, rtol=1e-4, atol=1e-2)

    def test_known_values_slaney(self):
        # the slaney scale is linear below 1 kHz: 1000 Hz == 15 mel
        assert abs(AF.hz_to_mel(1000.0) - 15.0) < 1e-4
        assert abs(AF.mel_to_hz(15.0) - 1000.0) < 1e-2

    def test_htk(self):
        assert abs(AF.hz_to_mel(1000.0, htk=True)
                   - 2595.0 * np.log10(1.0 + 1000.0 / 700.0)) < 1e-2

    def test_fft_frequencies(self):
        got = AF.fft_frequencies(8000, 256).numpy()
        np.testing.assert_allclose(got, np.fft.rfftfreq(256, 1 / 8000.0),
                                   rtol=1e-6)


class TestFilterbankDct:
    def test_fbank_shape_and_coverage(self):
        fb = AF.compute_fbank_matrix(8000, 256, n_mels=32).numpy()
        assert fb.shape == (32, 129)
        assert (fb >= 0).all()
        # every filter has some support; interior bins are covered
        assert (fb.sum(axis=1) > 0).all()

    def test_dct_ortho(self):
        d = AF.create_dct(13, 32, norm="ortho").numpy()  # [n_mels, n_mfcc]
        gram = d.T @ d
        np.testing.assert_allclose(gram, np.eye(13), atol=1e-5)

    def test_power_to_db(self):
        x = paddle.to_tensor(np.array([1.0, 0.1, 0.01], np.float32))
        db = AF.power_to_db(x, top_db=None).numpy()
        np.testing.assert_allclose(db, [0.0, -10.0, -20.0], atol=1e-4)
        capped = AF.power_to_db(x, top_db=15.0).numpy()
        np.testing.assert_allclose(capped, [0.0, -10.0, -15.0], atol=1e-4)


class TestWindows:
    @pytest.mark.parametrize("name", ["hann", "hamming", "blackman",
                                      "bartlett"])
    def test_matches_scipy(self, name):
        ours = AF.get_window(name, 64).numpy()
        ref = scipy_signal.get_window(name, 64, fftbins=True)
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_kaiser(self):
        ours = AF.get_window(("kaiser", 8.0), 64).numpy()
        ref = scipy_signal.get_window(("kaiser", 8.0), 64, fftbins=True)
        np.testing.assert_allclose(ours, ref, atol=1e-4)


class TestFeatureLayers:
    def _tone(self, freq=440.0, sr=8000, n=4000):
        t = np.arange(n) / sr
        return np.sin(2 * np.pi * freq * t).astype(np.float32)[None]

    def test_spectrogram_peak_at_tone(self):
        sr, f0 = 8000, 1000.0
        from paddle_tpu.audio.features import Spectrogram
        spec = Spectrogram(n_fft=256)(paddle.to_tensor(self._tone(f0, sr)))
        s = spec.numpy()[0]
        peak_bin = s.mean(axis=-1).argmax()
        np.testing.assert_allclose(peak_bin * sr / 256, f0, atol=sr / 256)

    def test_mel_and_mfcc_shapes_finite(self):
        from paddle_tpu.audio.features import (LogMelSpectrogram, MFCC,
                                               MelSpectrogram)
        x = paddle.to_tensor(self._tone())
        mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=32)(x)
        assert mel.shape[1] == 32 and np.isfinite(mel.numpy()).all()
        lm = LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32, top_db=80.0)(x)
        assert np.isfinite(lm.numpy()).all()
        mfcc = MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32)(x)
        assert mfcc.shape[1] == 13 and np.isfinite(mfcc.numpy()).all()

    def test_mel_energy_concentrates_at_tone(self):
        from paddle_tpu.audio.features import MelSpectrogram
        sr = 8000
        m = MelSpectrogram(sr=sr, n_fft=512, n_mels=40, f_min=0.0)
        lo = m(paddle.to_tensor(self._tone(300.0, sr))).numpy()[0].mean(-1)
        hi = m(paddle.to_tensor(self._tone(3000.0, sr))).numpy()[0].mean(-1)
        assert lo.argmax() < hi.argmax()
