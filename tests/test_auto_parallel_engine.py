"""Auto-parallel Engine semantics (VERDICT r2 item 10): fit/evaluate/predict
driving a mesh-compiled TrainStep from shard_tensor annotations.
Reference: ``python/paddle/distributed/auto_parallel/static/engine.py`` †.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.io import Dataset
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.auto_parallel import (Engine, ProcessMesh, Replicate,
                                               Shard, shard_tensor)


class _XYDataset(Dataset):
    def __init__(self, n=64, din=16, dout=4):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, din).astype(np.float32)
        w = rng.randn(din, dout).astype(np.float32)
        self.y = self.x @ w + 0.01 * rng.randn(n, dout).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class _MLP(nn.Layer):
    def __init__(self, din=16, dh=32, dout=4):
        super().__init__()
        self.fc1 = nn.Linear(din, dh)
        self.fc2 = nn.Linear(dh, dout)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _mse(out, lab):
    return ((out - lab) ** 2).mean()


class TestAutoParallelEngine:
    def setup_method(self, _m):
        mesh_mod._STATE["mesh"] = None

    def _build(self):
        pm = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        paddle.seed(55)
        model = _MLP()
        # Megatron-style annotations: fc1 column-sharded, fc2 row-sharded
        shard_tensor(model.fc1.weight, pm, [Replicate(), Shard(1)])
        shard_tensor(model.fc2.weight, pm, [Shard(0), Replicate()])
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        return pm, model, Engine(model=model, loss=_mse, optimizer=opt,
                                 mesh=pm)

    def test_shard_tensor_annotates_parameter_in_place(self):
        pm = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        lin = nn.Linear(8, 16)
        w = shard_tensor(lin.weight, pm, [Replicate(), Shard(1)])
        assert w is lin.weight  # in-place annotation, not a copy
        assert tuple(lin.weight.dist_spec) == (None, "mp")
        assert lin.weight.value.sharding.spec[1] in ("mp", ("mp",))

    def test_fit_reduces_loss_and_places_params(self):
        pm, model, engine = self._build()

        def eval_loss():
            ev = engine.evaluate(_XYDataset(), batch_size=16, verbose=0)
            loss = ev["loss"] if isinstance(ev, dict) else ev
            return float(np.ravel(loss)[0])

        before = eval_loss()
        engine.fit(_XYDataset(), epochs=5, batch_size=16, verbose=0)
        after = eval_loss()
        # the compiled step placed fc1.weight mp-sharded on the mesh
        w1 = engine.train_step.params["fc1.weight"]
        assert w1.sharding.spec[1] in ("mp", ("mp",))
        assert w1.addressable_shards[0].data.shape[1] == 32 // 4
        assert after < before * 0.6, (before, after)

    def test_predict_returns_outputs(self):
        class _XOnly(_XYDataset):
            def __getitem__(self, i):
                return self.x[i]

        pm, model, engine = self._build()
        engine.fit(_XYDataset(n=32), epochs=1, batch_size=16, verbose=0)
        preds = engine.predict(_XOnly(n=32), batch_size=16, verbose=0)
        arrs = [np.asarray(p) for p in np.atleast_1d(preds)] if not \
            isinstance(preds, list) else [np.asarray(p) for p in preds]
        total = sum(a.shape[0] if a.ndim else 1 for a in arrs)
        assert total >= 2  # batches came back
