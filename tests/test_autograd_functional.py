"""Functional autograd tests (reference:
``test/autograd/test_autograd_functional_dynamic.py`` † — jacobian/
hessian/jvp/vjp against closed forms and numeric differentiation)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import autograd as AG


def _t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


class TestJacobian:
    def test_elementwise_square_is_diagonal(self):
        x = _t([1.0, 2.0, 3.0])
        J = AG.jacobian(lambda a: a * a, x)
        np.testing.assert_allclose(np.asarray(J), np.diag([2.0, 4.0, 6.0]),
                                   rtol=1e-6)

    def test_matmul_jacobian_matches_numeric(self):
        rng = np.random.RandomState(0)
        W = _t(rng.rand(3, 2))
        x = _t(rng.rand(3))
        J = np.asarray(AG.jacobian(lambda a: paddle.matmul(a, W), x))
        # d(xW)/dx = W^T rows
        np.testing.assert_allclose(J, np.asarray(W.numpy()).T, rtol=1e-5)

    def test_multi_input(self):
        x, y = _t([1.0, 2.0]), _t([3.0, 4.0])
        Jx, Jy = AG.jacobian(lambda a, b: a * b, [x, y])
        np.testing.assert_allclose(np.asarray(Jx), np.diag([3.0, 4.0]))
        np.testing.assert_allclose(np.asarray(Jy), np.diag([1.0, 2.0]))

    def test_batched(self):
        xb = _t(np.arange(6, dtype=np.float32).reshape(2, 3))
        Jb = AG.jacobian(lambda a: a * a, xb, batch_axis=0)
        assert Jb.shape == [2, 3, 3]
        np.testing.assert_allclose(np.asarray(Jb)[1],
                                   np.diag([6.0, 8.0, 10.0]))

    def test_jacobian_class_flattens(self):
        x = _t(np.ones((2, 2)))
        Jc = AG.Jacobian(lambda a: paddle.sum(a * a, axis=1), x)
        assert Jc.shape == [2, 4]
        row0 = np.asarray(Jc[0].value)
        np.testing.assert_allclose(row0, [2.0, 2.0, 0.0, 0.0])


class TestHessian:
    def test_cubic_sum(self):
        x = _t([1.0, 2.0, 3.0])
        H = AG.hessian(lambda a: paddle.sum(a * a * a), x)
        np.testing.assert_allclose(np.asarray(H),
                                   np.diag([6.0, 12.0, 18.0]), rtol=1e-6)

    def test_quadratic_form(self):
        rng = np.random.RandomState(1)
        A = rng.rand(3, 3).astype(np.float32)
        A = (A + A.T) / 2
        At = _t(A)
        H = AG.hessian(
            lambda v: 0.5 * paddle.sum(v * paddle.matmul(At, v)), _t(rng.rand(3)))
        np.testing.assert_allclose(np.asarray(H), A, rtol=1e-4, atol=1e-5)

    def test_hessian_class(self):
        Hc = AG.Hessian(lambda a: paddle.sum(a * a), _t([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(Hc[:].value), 2 * np.eye(2),
                                   rtol=1e-6)

    def test_nonscalar_raises(self):
        with pytest.raises(ValueError, match="scalar"):
            AG.hessian(lambda a: a * a, _t([1.0, 2.0]))


class TestJvpVjp:
    def test_jvp_matches_directional_derivative(self):
        x = _t([0.5, 1.5])
        v = _t([1.0, -1.0])
        out, tan = AG.jvp(lambda a: paddle.exp(a), x, v)
        np.testing.assert_allclose(np.asarray(tan),
                                   np.exp([0.5, 1.5]) * [1.0, -1.0],
                                   rtol=1e-5)

    def test_vjp_matches_backward(self):
        x = _t([1.0, 2.0, 3.0])
        out, g = AG.vjp(lambda a: paddle.sum(paddle.sin(a)), x)
        np.testing.assert_allclose(np.asarray(g), np.cos([1.0, 2.0, 3.0]),
                                   rtol=1e-5)

    def test_jvp_vjp_duality(self):
        # <J v, u> == <v, J^T u> for random u, v
        rng = np.random.RandomState(2)
        W = _t(rng.rand(3, 3))
        fn = lambda a: paddle.tanh(paddle.matmul(a, W))
        x = _t(rng.rand(3))
        v = rng.rand(3).astype(np.float32)
        u = rng.rand(3).astype(np.float32)
        _, Jv = AG.jvp(fn, x, _t(v))
        _, JTu = AG.vjp(fn, x, _t(u))
        np.testing.assert_allclose(np.dot(np.asarray(Jv), u),
                                   np.dot(v, np.asarray(JTu)), rtol=1e-4)

    def test_incubate_namespace(self):
        assert paddle.incubate.autograd.jacobian is AG.jacobian
        assert paddle.incubate.autograd.Hessian is AG.Hessian


class TestReviewRegressions:
    def test_hessian_class_multi_input_full_blocks(self):
        x, y = _t([1.0, 2.0]), _t([3.0, 4.0])
        Hc = AG.Hessian(lambda a, b: paddle.sum(a * b), [x, y])
        # f = sum(a*b): d2f/da db = I, diagonal blocks zero
        expect = np.block([[np.zeros((2, 2)), np.eye(2)],
                           [np.eye(2), np.zeros((2, 2))]])
        np.testing.assert_allclose(np.asarray(Hc[:].value), expect,
                                   atol=1e-6)

    def test_jacobian_class_multi_input(self):
        x, y = _t([1.0, 2.0]), _t([3.0, 4.0])
        Jc = AG.Jacobian(lambda a, b: a * b, [x, y])
        assert Jc.shape == [2, 4]
        np.testing.assert_allclose(
            np.asarray(Jc[:].value),
            np.hstack([np.diag([3.0, 4.0]), np.diag([1.0, 2.0])]))

    def test_hessian_invalid_batch_axis_raises(self):
        with pytest.raises(ValueError, match="batch_axis"):
            AG.hessian(lambda a: paddle.sum(a * a), _t([[1.0, 2.0]]),
                       batch_axis=1)

    def test_batched_nonscalar_raises(self):
        with pytest.raises(ValueError, match="scalar"):
            AG.hessian(lambda a: a * a, _t(np.ones((2, 3))), batch_axis=0)

    def test_create_graph_unsupported(self):
        with pytest.raises(NotImplementedError, match="compose"):
            AG.jacobian(lambda a: a * a, _t([1.0]), create_graph=True)

    def test_batched_hessian_class(self):
        xb = _t(np.arange(6, dtype=np.float32).reshape(2, 3))
        Hc = AG.Hessian(lambda a: paddle.sum(a * a * a), xb,
                        is_batched=True)
        assert Hc.shape == [2, 3, 3]
        np.testing.assert_allclose(np.asarray(Hc[1].value),
                                   np.diag(6.0 * np.arange(3, 6)),
                                   rtol=1e-5)
