"""Driver-flow contract for bench.py (no device; children are stubbed).

The driver runs bench.py exactly once per round and parses its LAST stdout
line as JSON (SURVEY §6). These tests pin the three properties the r3-r5
tunnel failures taught us to defend:

1. total-backend-failure still prints one parseable line, reporting the
   best PRIOR self-measured config with its provenance stamp rather
   than a 0.0;
2. a successful sweep banks every leg into BENCH_SELF, runs the risky
   decode leg LAST (a timeout-kill wedges the tunnel's remote device
   session — observed twice on-chip in r5), and records a failed decode's
   rc + stderr tail instead of null;
3. the reserved hand-maintained "record" key survives artifact rebuilds.
"""
import contextlib
import io
import importlib.util
import json
import os
import shutil

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench(tmp_path, artifact=None):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench.BACKOFFS_S = (0,)
    bench.SELF_BENCH_PATH = str(tmp_path / "self_bench.json")
    # keep the repo's real previous-round artifact out of the tests —
    # prior-config/record rollover must come from the fixture only
    bench.LEGACY_SELF_BENCH_PATHS = ()
    if artifact is not None:
        with open(bench.SELF_BENCH_PATH, "w") as f:
            json.dump(artifact, f)
    return bench


def _headline(bench):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = bench.watchdog()
    assert rc == 0
    return json.loads(buf.getvalue().strip().splitlines()[-1])


PRIOR = {
    "metric": "llama_350m_train_mfu_bf16",
    "measured_at": "2026-07-31T01:55:00Z", "git_head": "4eab7ea",
    "configs": [{"name": "winner", "mfu": 0.4548, "tok_s": 39943.0,
                 "loss": 7.06, "n_params": 3.7e8, "peak": 1.97e14,
                 "step_ms": 410.0, "warm_s": 52.0}],
    "record": {"provenance_note": "session-2 sweep"},
}


class TestBenchDriverFlow:
    def test_total_failure_reports_prior_with_provenance(self, tmp_path):
        bench = _load_bench(tmp_path, artifact=PRIOR)
        bench._run = lambda args, timeout, env=None: (124, "", "dead")
        doc = _headline(bench)
        assert doc["metric"] == bench.METRIC
        assert doc["value"] == pytest.approx(0.4548)
        assert "2026-07-31T01:55:00Z" in doc["unit"]
        assert "4eab7ea" in doc["unit"]
        # even with the tunnel dead, the CPU-forced decode_cb and
        # serve_http legs' outcomes (here: failed) are banked up front
        art = json.load(open(bench.SELF_BENCH_PATH))
        assert art["decode_cb"]["ok"] is False
        assert art["serve_http"]["ok"] is False
        assert art["prefix_cache"]["ok"] is False
        assert art["paged_attn"]["ok"] is False
        assert art["chunked_prefill"]["ok"] is False
        assert art["ragged_step"]["ok"] is False
        assert art["spec_decode"]["ok"] is False
        assert art["chaos"]["ok"] is False
        assert art["trace_overhead"]["ok"] is False
        assert art["dispatch"]["ok"] is False
        assert art["density"]["ok"] is False
        assert art["tp"]["ok"] is False
        assert art["tier"]["ok"] is False
        assert any(c["mfu"] == pytest.approx(0.4548)
                   for c in art["prior_configs"])

    def test_success_flow_decode_last_and_diagnosed(self, tmp_path):
        bench = _load_bench(tmp_path, artifact=PRIOR)
        order = []

        def fake_run(args, timeout, env=None):
            if args[0] == "-c":
                return 0, "NDEV 1", ""
            leg = next(a for a in args if a.startswith("--"))
            order.append(leg)
            if leg == "--decode-cb":
                # scheduling leg must be hang-proof: CPU-forced child
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps({"name": "decode_cb", "ok": True,
                                      "speedup": 1.47}), ""
            if leg == "--serve-http":
                # gateway-overhead leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps({"name": "serve_http", "ok": True,
                                      "overhead_ratio": 1.17,
                                      "tokens_equal": True}), ""
            if leg == "--prefix-cache":
                # prefix-cache leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps({"name": "prefix_cache", "ok": True,
                                      "prefill_work_reduction": 2.0,
                                      "hit_rate": 0.67,
                                      "tokens_equal": True}), ""
            if leg == "--paged-attn":
                # paged-attention leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps({"name": "paged_attn", "ok": True,
                                      "copy_dispatches_eliminated": 24,
                                      "paged_copy_dispatches": 0,
                                      "hbm_reduction": 2.27,
                                      "tokens_equal": True}), ""
            if leg == "--chunked-prefill":
                # chunked-prefill TTFT leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps({"name": "chunked_prefill",
                                      "ok": True,
                                      "p95_ttft_ratio": 4.4,
                                      "accepted": True,
                                      "tokens_equal": True}), ""
            if leg == "--ragged":
                # unified-ragged-step launch leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps({"name": "ragged_step", "ok": True,
                                      "launches_saved_per_mixed_step": 1.0,
                                      "accepted": True,
                                      "tokens_equal": True}), ""
            if leg == "--spec":
                # speculative-decode leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps({"name": "spec_decode", "ok": True,
                                      "modeled_tok_s_ratio_repetitive":
                                          2.3,
                                      "accepted": True}), ""
            if leg == "--chaos":
                # fault-tolerance leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps({"name": "chaos", "ok": True,
                                      "accepted": True,
                                      "chaos": {"requests_lost": 0},
                                      "deterministic": True}), ""
            if leg == "--trace-overhead":
                # tracer-overhead leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps({"name": "trace_overhead",
                                      "ok": True,
                                      "disabled_overhead_ratio": 1.002,
                                      "accepted": True,
                                      "tokens_equal": True}), ""
            if leg == "--dispatch":
                # dispatch-cost leg (now carrying the multi-tick
                # decode ladder): same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps(
                    {"name": "dispatch", "ok": True,
                     "baseline_dispatches_per_decoded_token": 0.32,
                     "dispatches_per_decoded_token_by_ticks":
                         {"1": 0.32, "4": 0.13, "8": 0.11},
                     "multitick_dispatch_reduction": 3.0,
                     "exact_vs_program_accessors": True,
                     # ISSUE 20: the one-kernel fused ladder rides the
                     # same banked leg
                     "fused": {
                         "fused_tick_launch_reduction": 6.0,
                         "scanned_per_tick_device_launches": 6,
                         "fused_per_tick_device_launches": 1,
                         "streams_equal_to_scanned_legs": True,
                         "host_ladder_matches_scanned": True,
                         "collective_overlap": {"wire_bytes": 4096}},
                     "accepted": True}), ""
            if leg == "--density":
                # quantized-density leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps(
                    {"name": "density", "ok": True,
                     "slot_capacity_ratio": 3.5,
                     "greedy_divergence": {"divergence_rate": 0.0},
                     "int8_deterministic": True,
                     "int8_bytes_per_token": 2496.0,
                     "fp8_bytes_per_token": 2316.0,
                     "fp8_greedy_divergence": {"divergence_rate": 0.0},
                     "fp8_deterministic": True,
                     "a8_greedy_divergence":
                         {"matched_prefix_fraction": 0.953125},
                     "a8_deterministic": True,
                     "default_streams_unchanged": True,
                     "accepted": True}), ""
            if leg == "--tp":
                # tensor-parallel leg: same hang-proof contract (the
                # child forces its own virtual-mesh device count)
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps(
                    {"name": "tp", "ok": True,
                     "tokens_equal": True,
                     "compile_once": {"tp1": 1, "tp2": 1},
                     "collective_bytes_reduction": 3.92,
                     "greedy_divergence": {"divergence_rate": 0.0},
                     "int8_deterministic": True,
                     "accepted": True}), ""
            if leg == "--tier":
                # tiered-prefix-cache leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps(
                    {"name": "tier", "ok": True,
                     "tokens_equal": True,
                     "compile_once": True,
                     "hit_rate_ratio": 5.0,
                     "ttft_recompute_over_tier_hit": 2.01,
                     "accepted": True}), ""
            if leg == "--slo":
                # multi-tenant SLO leg: same hang-proof contract
                assert env == {"JAX_PLATFORMS": "cpu"}
                return 0, json.dumps(
                    {"name": "slo", "ok": True,
                     "tokens_equal": True,
                     "replay_identical": True,
                     "compile_once": True,
                     "ttft_p95_degrade_ratio_fifo_over_policy": 6.48,
                     "batch_throughput_ratio_policy_over_fifo": 0.84,
                     "accepted": True}), ""
            if leg == "--smoke":
                return 0, json.dumps({"kernel": "k", "ok": True}), ""
            if leg == "--config":
                i = int(args[args.index("--config") + 1])
                return 0, json.dumps(
                    {"name": bench.CONFIGS[i][0], "mfu": 0.40 + i * 0.001,
                     "tok_s": 1.0, "loss": 7.0, "n_params": 3.7e8,
                     "peak": 1.97e14, "step_ms": 1.0, "warm_s": 1.0}), ""
            if leg == "--layer7b":
                return 0, json.dumps({"layer7b_tok_s": 1,
                                      "layer7b_mfu": 0.5}), ""
            if leg == "--trace":
                return 0, json.dumps({"name": "x", "mfu": 0.4,
                                      "top_ops": []}), ""
            if leg == "--decode":
                assert timeout == bench.DECODE_TIMEOUT_S
                attn = args[args.index("--decode") + 1]
                if attn == "pallas":  # pallas child dies -> jnp fallback
                    return 124, "", \
                        "# decode: model built, compiling generate()"
                return 0, json.dumps({"name": "decode[jnp]", "ok": True,
                                      "attn": "jnp", "decode_tok_s": 321.0,
                                      "decode_mbu": 0.4, "B": 8,
                                      "prompt": 128, "max_new": 256}), ""
            raise AssertionError(args)

        bench._run = fake_run
        doc = _headline(bench)
        assert doc["value"] > 0
        assert "decode[jnp] 321" in doc["unit"]
        # decode is the final leg: a wedge there cannot cost the trace —
        # and the tunnel-independent scheduling + gateway + prefix-cache
        # legs run before anything that can wedge
        assert order[-1] == "--decode" and "--trace" in order
        assert order[:14] == ["--decode-cb", "--serve-http",
                              "--prefix-cache", "--paged-attn",
                              "--chunked-prefill", "--ragged", "--spec",
                              "--chaos", "--trace-overhead",
                              "--dispatch", "--density", "--tp",
                              "--tier", "--slo"]
        art = json.load(open(bench.SELF_BENCH_PATH))
        assert art["decode"]["ok"] is True and art["decode"]["attn"] == "jnp"
        assert art["serve_http"]["overhead_ratio"] == 1.17
        assert art["prefix_cache"]["prefill_work_reduction"] == 2.0
        assert art["paged_attn"]["paged_copy_dispatches"] == 0
        assert art["paged_attn"]["copy_dispatches_eliminated"] == 24
        assert art["chunked_prefill"]["accepted"] is True
        assert art["chunked_prefill"]["p95_ttft_ratio"] == 4.4
        assert art["ragged_step"]["accepted"] is True
        assert art["ragged_step"]["launches_saved_per_mixed_step"] == 1.0
        assert art["spec_decode"]["accepted"] is True
        assert art["spec_decode"]["modeled_tok_s_ratio_repetitive"] == 2.3
        assert art["chaos"]["accepted"] is True
        assert art["chaos"]["chaos"]["requests_lost"] == 0
        assert art["trace_overhead"]["accepted"] is True
        assert art["trace_overhead"]["disabled_overhead_ratio"] == 1.002
        assert art["dispatch"]["accepted"] is True
        assert art["dispatch"]["exact_vs_program_accessors"] is True
        # the multi-tick ladder rides the same banked leg
        assert art["dispatch"]["multitick_dispatch_reduction"] == 3.0
        assert art["dispatch"][
            "dispatches_per_decoded_token_by_ticks"]["8"] == 0.11
        # the fused one-kernel ladder rides the same banked leg
        # (ISSUE 20): census-exact per-tick reduction, scanned-host
        # parity and the overlapped-collective wire ledger all land in
        # the artifact
        fused = art["dispatch"]["fused"]
        assert fused["fused_tick_launch_reduction"] == 6.0
        assert fused["fused_per_tick_device_launches"] == 1
        assert fused["streams_equal_to_scanned_legs"] is True
        assert fused["host_ladder_matches_scanned"] is True
        assert fused["collective_overlap"]["wire_bytes"] > 0
        assert art["density"]["accepted"] is True
        assert art["density"]["slot_capacity_ratio"] == 3.5
        assert art["density"][
            "greedy_divergence"]["divergence_rate"] == 0.0
        # the fp8/a8 low-precision legs ride the same banked artifact:
        # fp8 cached tokens strictly cheaper than int8's, divergence
        # measured (not assumed) and deterministic either leg
        assert art["density"]["fp8_bytes_per_token"] \
            < art["density"]["int8_bytes_per_token"]
        assert art["density"][
            "fp8_greedy_divergence"]["divergence_rate"] <= 0.02
        assert art["density"]["fp8_deterministic"] is True
        assert art["density"]["a8_deterministic"] is True
        # the tensor-parallel leg rides the same banked artifact
        assert art["tp"]["accepted"] is True
        assert art["tp"]["tokens_equal"] is True
        assert art["tp"]["compile_once"] == {"tp1": 1, "tp2": 1}
        assert art["tp"]["collective_bytes_reduction"] == 3.92
        # the tiered-prefix-cache leg rides the same banked artifact
        assert art["tier"]["accepted"] is True
        assert art["tier"]["hit_rate_ratio"] == 5.0
        assert art["tier"]["ttft_recompute_over_tier_hit"] == 2.01
        # the multi-tenant SLO leg rides the same banked artifact
        assert art["slo"]["accepted"] is True
        assert art["slo"]["tokens_equal"] is True
        assert art["slo"][
            "ttft_p95_degrade_ratio_fifo_over_policy"] == 6.48
        assert art["slo"][
            "batch_throughput_ratio_policy_over_fifo"] == 0.84
        # the pallas attempt's forensic trail rides along with the success
        (fa,) = art["decode"]["failed_attempts"]
        assert fa["attn"] == "pallas" and fa["rc"] == 124
        assert "compiling generate" in fa["stderr_tail"]
        assert art["record"]["provenance_note"] == "session-2 sweep"
        assert art["layer7b"]["layer7b_mfu"] == 0.5
        # prior best rides along so a later fallback can still cite it
        assert any(c["mfu"] == pytest.approx(0.4548)
                   for c in art["prior_configs"])
