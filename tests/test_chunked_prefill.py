"""Chunked prefill on the paged serving path (serving/engine.py
``prefill_chunk``, README "Chunked prefill"): long cold prompts prefill
``prefill_chunk`` tokens per engine step, interleaved with the fused
decode tick, instead of monopolizing a step.

The load-bearing properties:

- **Transparency**: chunked token streams are byte-identical to the
  unchunked engine — greedy AND seeded-sampled, cold and prefix-cache
  hit admissions alike. Only the FINAL chunk samples (and advances the
  PRNG), so the key walk is exactly the one-shot prefill's.
- **Interleaving**: decode slots keep emitting a token on every step a
  chunk runs — the TTFT win chunking exists for.
- **Compile discipline**: ``decode_compilations() == 1`` and a CLOSED
  chunk-prefill compile set (full chunks share the ``prefill_chunk``
  bucket; remainders ride the pow2 grid) under varied prompt lengths
  and a mixed hit/miss/cancel/divergence matrix.
- **Lifecycle**: cancellation/timeout mid-chunk restores ``num_free``
  exactly — the partial block chain is freed (or donated to the trie,
  which later resumes the SAME prompt at the donated offset).
- **Generated-token trie extension**: retirement donates full
  *generated* blocks too, so a multi-turn resubmission of turn N's
  assistant text hits turn N's own blocks.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (ContinuousBatchingEngine, FIFOScheduler,
                                GenerationRequest)

from test_metrics_prom import parse_prometheus

BS = 8      # block size
CHUNK = 16  # 2 blocks per chunk


@pytest.fixture(scope="module")
def model():
    paddle.seed(21)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


def _engine(model, **kw):
    kw.setdefault("jit_cache", model.__dict__.setdefault("_serving_jit", {}))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefix_cache", False)
    kw.setdefault("prefill_chunk", CHUNK)
    # fixed-cap chunk pacing: the step-count/offset pins below assume
    # exactly CHUNK tokens per grant; the headroom-adaptive budget is
    # wall-clock-fed (nondeterministic on a shared box) and is pinned
    # separately in test_ragged_step.py with an injected clock
    kw.setdefault("headroom_mult", None)
    return ContinuousBatchingEngine(model, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _req(ps, n=40, **kw):
    kw.setdefault("max_new_tokens", 6)
    return GenerationRequest(prompt=_prompt(ps, n), **kw)


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             eos_token_id=r.eos_token_id, seed=r.seed)


def _run(model, reqs, **kw):
    eng = _engine(model, **kw)
    outs = eng.generate([_clone(r) for r in reqs])
    return [o.tolist() for o in outs], eng


class TestTransparency:
    @pytest.mark.slow  # 8 s transparency duplicate: test_chunked_equals_
    # unchunked_with_prefix_hits below is the stricter default rep (870s cap)
    def test_chunked_equals_unchunked_greedy_and_sampled(self, model):
        """The acceptance pin: varied prompt lengths (sub-chunk,
        multi-chunk, non-block-multiple), greedy and seeded-sampled,
        stream the exact unchunked tokens, with one decode program."""
        reqs = [_req(1, n=40), _req(2, n=61), _req(3, n=12),
                _req(4, n=53, temperature=0.9, top_k=5, seed=123),
                _req(5, n=33, temperature=0.7, top_k=3, seed=9)]
        want, _ = _run(model, reqs, prefill_chunk=None)
        got, eng = _run(model, reqs)
        assert got == want
        assert eng.stats["prefill_chunks"] >= 8  # 40,61,53,33 all chunked
        assert eng.decode_compilations() == 1

    def test_chunked_equals_unchunked_with_prefix_hits(self, model):
        """Hit admissions: the installed chain counts toward the resume
        offset (zero-copy) and streams stay byte-identical to both the
        unchunked-hit and the cold engines."""
        sysp = _prompt(50, 32)
        reqs = [GenerationRequest(
            prompt=np.concatenate([sysp, _prompt(51 + i, 24)]),
            max_new_tokens=5,
            **({"temperature": 0.8, "top_k": 4, "seed": 3} if i == 2
               else {})) for i in range(3)]
        cold, _ = _run(model, reqs, prefix_cache=False, prefill_chunk=None)
        unchunked, _ = _run(model, reqs, prefix_cache=True,
                            prefill_chunk=None)
        chunked, eng = _run(model, reqs, prefix_cache=True)
        assert chunked == unchunked == cold
        assert eng.prefix_cache.stats["hits"] >= 1
        assert eng.stats["prefill_copy_dispatches"] == 0
        # the hit's covered tokens were never re-prefilled
        assert eng.stats["prefill_tokens_saved"] > 0

    def test_decode_slots_keep_emitting_while_chunk_runs(self, model):
        """The TTFT property itself: on every step that advances a
        pending prefill chunk, the live decode slot still emits a
        token — no decode batch ever waits behind the long prompt."""
        eng = _engine(model)
        short = eng.submit(_req(10, n=8, max_new_tokens=40))
        eng.step()                      # short admitted + first token
        assert short.status == "running"
        longy = eng.submit(_req(11, n=80, max_new_tokens=4))
        n_chunk_steps = 0
        while longy.status != "running":
            before = len(short.tokens)
            chunks0 = eng.stats["prefill_chunks"]
            eng.step()
            assert eng.stats["prefill_chunks"] == chunks0 + 1
            assert len(short.tokens) == before + 1  # decode kept going
            n_chunk_steps += 1
        assert n_chunk_steps == 5       # ceil(80 / 16) chunks
        # the long prompt's stream is still the solo/unchunked one
        while eng.has_work():
            eng.step()
        want, _ = _run(model, [_req(11, n=80, max_new_tokens=4)],
                       prefill_chunk=None)
        assert longy.tokens == want[0]

    def test_prefilling_status_walks_and_offsets_block_aligned(self, model):
        eng = _engine(model)
        seq = eng.submit(_req(12, n=50, max_new_tokens=2))
        assert seq.status == "queued"
        offs = []
        eng.step()
        while seq.status == "prefilling":
            offs.append(seq.prefilled)
            eng.step()
        assert seq.status in ("running", "finished")
        assert offs == [16, 32, 48]     # block-aligned resume offsets
        assert seq.prefilled == 50


class TestCompileDiscipline:
    def test_closed_compile_set_under_mixed_matrix(self, model):
        """The acceptance pin: a mixed hit/miss/cancel/divergence
        traffic matrix over varied prompt lengths leaves
        decode_compilations() == 1, and once the (group, bucket) grid
        is warm a repeat wave adds ZERO prefill/suffix traces — chunk
        calls all land in the prefill_chunk (or remainder pow2)
        buckets."""
        jit = {}
        eng = _engine(model, jit_cache=jit, prefix_cache=True,
                      num_slots=2)
        sysp = _prompt(60, 32)

        def wave(cancel_at=None):
            reqs = [GenerationRequest(prompt=np.concatenate(
                        [sysp, _prompt(61 + i, 9 + 8 * i)]),
                        max_new_tokens=4) for i in range(3)]
            reqs.append(_req(65, n=43, temperature=0.8, top_k=6, seed=2))
            seqs = [eng.submit(r) for r in reqs]
            steps = 0
            while eng.has_work():
                eng.step()
                steps += 1
                if cancel_at is not None and steps == cancel_at:
                    victim = next((s for s in seqs
                                   if s.status == "prefilling"), None)
                    if victim is not None:
                        eng.cancel(victim)
            return [s.tokens for s in seqs]

        first = wave()
        wave(cancel_at=2)               # cancel mid-chunk in the mix
        assert eng.decode_compilations() == 1
        prefill0 = eng.prefill_compilations()
        third = wave()
        assert third == first           # steady-state determinism
        assert eng.decode_compilations() == 1
        assert eng.prefill_compilations() == prefill0  # zero new traces

    def test_chunk_bucket_is_shared_across_prompt_lengths(self, model):
        """Prompts of many lengths chunk through ONE full-chunk bucket:
        the suffix compile count stays bounded by the pow2 grid, not by
        the number of distinct prompt lengths."""
        jit = {}
        eng = _engine(model, jit_cache=jit, max_seq_len=96)
        for i, n in enumerate((33, 41, 49, 57, 65, 73, 81, 89)):
            eng.generate([_req(70 + i, n=n, max_new_tokens=2)])
        # full chunks: one (G=1, 16) trace; remainders: pow2 buckets
        # {8, 16} at G=1 -> <= 3 suffix traces total for 8 lengths
        assert eng.prefill_compilations() <= 3
        assert eng.decode_compilations() == 1


class TestLifecycle:
    def test_cancel_mid_chunk_restores_num_free_exactly(self, model):
        """No trie: cancelling a half-prefilled prompt returns every
        pool block and the slot; the engine is byte-for-byte reusable."""
        eng = _engine(model)
        pool = eng.cache.pool
        blocks0, slots0 = pool.num_free, eng.cache.num_free
        bystander = eng.submit(_req(20, n=8, max_new_tokens=20))
        victim = eng.submit(_req(21, n=70, max_new_tokens=4))
        want = None
        for _ in range(3):
            eng.step()
        assert victim.status == "prefilling"
        assert 0 < victim.prefilled < 70
        assert eng.cancel(victim) is True
        assert victim.finish_reason == "cancelled"
        assert victim.tokens == []
        assert eng.cache.num_free == slots0 - 1   # bystander still live
        while eng.has_work():
            eng.step()
        assert pool.num_free == blocks0
        assert eng.cache.num_free == slots0
        want, _ = _run(model, [_req(20, n=8, max_new_tokens=20)],
                       prefill_chunk=None)
        assert bystander.tokens == want[0]        # bystander untouched

    def test_timeout_mid_chunk_frees_partial_chain(self, model):
        eng = _engine(model)
        pool = eng.cache.pool
        blocks0 = pool.num_free
        seq = eng.submit(_req(22, n=70, max_new_tokens=4,
                              timeout_s=60.0))
        eng.step()
        assert seq.status == "prefilling"
        # force expiry deterministically (a tiny wall-clock timeout_s
        # can fire while still queued on a loaded box): the sweep reads
        # the absolute deadline, so backdating it IS the timeout
        seq.deadline = time.monotonic() - 1.0
        eng.step()                       # deadline sweep fires
        assert seq.finish_reason == "timeout"
        assert seq.tokens == []
        assert eng.stats["timeouts"] == 1
        assert pool.num_free == blocks0
        assert eng.cache.num_free == eng.num_slots

    def test_cancelled_chunk_donates_partial_chain_to_trie(self, model):
        """With the prefix cache on, a mid-prefill cancel DONATES the
        block-aligned partial chain — resubmitting the same prompt
        resumes from the donated offset instead of starting cold."""
        eng = _engine(model, prefix_cache=True)
        seq = eng.submit(_req(23, n=70, max_new_tokens=4))
        eng.step()
        eng.step()
        assert seq.prefilled == 32
        eng.cancel(seq)
        matched = eng.prefix_cache.lookup(_prompt(23, 70), record=False)
        assert len(matched) == 4         # 32 donated rows = 4 blocks
        # resume: same prompt now hit-installs the donated chain and
        # still streams the unchunked tokens
        want, _ = _run(model, [_req(23, n=70, max_new_tokens=4)],
                       prefill_chunk=None)
        out = eng.generate([_req(23, n=70, max_new_tokens=4)])[0]
        assert out.tolist() == want[0]
        assert eng.stats["prefill_tokens_saved"] >= 32


class TestGeneratedTokenDonation:
    def test_multi_turn_resubmission_hits_generated_blocks(self, model):
        """Turn N+1's prompt embeds turn N's assistant output:
        retirement donated the generated full blocks, so the lookup
        covers past the original prompt and the stream still matches a
        cold engine byte for byte."""
        eng = _engine(model, prefix_cache=True)
        turn1 = _req(30, n=40, max_new_tokens=10)
        out1 = eng.generate([_clone(turn1)])[0]
        history = np.concatenate([turn1.prompt, out1.ids])
        # generated rows: all but the last sampled token are in KV
        matched = eng.prefix_cache.lookup(
            np.concatenate([history, [1, 2, 3]]), record=False)
        assert len(matched) * BS >= 48   # covers into the generated tail
        assert eng.prefix_cache.stats["donated_blocks"] >= 6
        turn2 = GenerationRequest(
            prompt=np.concatenate([history, [1, 2, 3]]).astype(np.int32),
            max_new_tokens=6)
        want, _ = _run(model, [turn2], prefix_cache=False,
                       prefill_chunk=None)
        got = eng.generate([_clone(turn2)])[0]
        assert got.tolist() == want[0]
        assert eng.prefix_cache.stats["hit_tokens"] >= 48

    def test_last_token_kv_never_donated(self, model):
        """The final sampled token's KV is never written (its append
        would belong to the decode tick that never ran) — donation must
        cap at the written rows, or a later hit would read garbage."""
        eng = _engine(model, prefix_cache=True, max_seq_len=96)
        # 39 prompt + 9 generated = 48 content rows, 47 written: block 5
        # (rows 40..47) must NOT be donated even though content fills it
        r = _req(31, n=39, max_new_tokens=9)
        out = eng.generate([_clone(r)])[0]
        full = np.concatenate([r.prompt, out.ids])
        matched = eng.prefix_cache.lookup(
            np.concatenate([full, [7]]), record=False)
        assert len(matched) == 5         # 47 written rows -> 5 blocks


class TestSchedulerPolicy:
    def test_prefill_plan_budgets_fifo_block_aligned(self):
        class S:
            def __init__(self, plen, done):
                # work_len is what the plan budgets (== prompt_len for
                # anything not restored for recovery-by-recompute)
                self.work_len, self.prefilled = plen, done
        sched = FIFOScheduler()
        a, b = S(100, 64), S(50, 0)
        sched.enter_prefill(a)
        sched.enter_prefill(b)
        # head's final 36 tokens fit; leftover 28 block-aligns to 24
        assert sched.prefill_plan(64, align=8) == [(a, 36), (b, 24)]
        # a non-final cut is rounded DOWN to a block boundary
        a.prefilled = 0
        assert sched.prefill_plan(20, align=8) == [(a, 16)]
        # sub-block leftover stops the plan instead of splitting
        assert sched.prefill_plan(4, align=8) == []
        sched.leave_prefill(a)
        assert sched.prefill_plan(64, align=8) == [(b, 50)]
        assert sched.leave_prefill(a) is False   # idempotent

    def test_pending_prefill_forces_single_stepping(self):
        class S:
            def __init__(self, remaining):
                self.remaining = remaining
        sched = FIFOScheduler(decode_chunk=8)
        assert sched.choose_num_steps([S(20), S(20)]) == 8
        sched.enter_prefill(object())
        assert sched.choose_num_steps([S(20), S(20)]) == 1
        sched.prefilling.clear()
        assert sched.choose_num_steps([S(20), S(20)]) == 8


class TestConfigSurface:
    def test_chunk_rounds_up_to_block_multiple(self, model):
        eng = _engine(model, prefill_chunk=17)
        assert eng._chunk == 24          # next multiple of BS=8
        assert eng.prefill_chunk == 24   # the public effective value
        with pytest.raises(ValueError, match="prefill_chunk"):
            _engine(model, prefill_chunk=-1)
        with pytest.raises(ValueError, match="prefill_chunk"):
            # the dense engine rejects the same bad value (an A/B
            # toggle must not turn the error into a silent no-op)
            _engine(model, paged_attn=False, prefill_chunk=-1)
        assert _engine(model, prefill_chunk=0).prefill_chunk == 0
        assert _engine(model, prefill_chunk=None)._chunk is None

    def test_dense_engine_ignores_chunking(self, model):
        """The dense path has no block tables to resume through:
        prefill_chunk is inert there, prompts one-shot, streams
        unchanged."""
        reqs = [_req(40, n=50), _req(41, n=12)]
        want, _ = _run(model, reqs, paged_attn=False, prefill_chunk=None)
        got, eng = _run(model, reqs, paged_attn=False)
        assert got == want
        assert eng.prefill_chunk == 0
        assert eng.stats["prefill_chunks"] == 0

    def test_metrics_surface_strict_parsed(self, model):
        """serving_prefill_chunks_total counts chunk work on /metrics
        and serving_ttft_seconds uses the TTFT bucket ladder — all
        valid under the strict v0.0.4 parser."""
        from paddle_tpu.profiler.metrics import TTFT_BUCKETS
        from paddle_tpu.serving.server import ServingGateway
        eng = _engine(model)
        gw = ServingGateway(eng, start=False)   # no driver thread needed
        eng.generate([_req(42, n=50, max_new_tokens=2)])
        gw._m_ttft.observe(0.0007)   # engine-direct runs bypass the
        # gateway's submit path; one observation materializes the series
        fams = parse_prometheus(gw.registry.render())
        name = "serving_prefill_chunks_total"
        assert fams[name]["type"] == "counter"
        assert fams[name]["samples"][(name, ())] == \
            eng.stats["prefill_chunks"] >= 3
        # the TTFT histogram exposes the dedicated ladder
        le = [k for k in fams["serving_ttft_seconds"]["samples"]
              if k[0] == "serving_ttft_seconds_bucket"]
        bounds = {lbl[1] for _, lbls in le for lbl in lbls
                  if lbl[0] == "le"}
        assert "0.0005" in bounds          # sub-ms low end
        assert "30" in bounds              # _fmt_value renders 30.0 -> 30
        assert len(bounds) == len(TTFT_BUCKETS) + 1  # ladder + +Inf
