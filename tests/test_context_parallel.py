"""Long-context stack tests (VERDICT r1 item 4): ring attention and
Ulysses all-to-all attention over the 'sep' mesh axis — parity and
gradients vs the reference attention, plus LLaMA end-to-end routing.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.jit import TrainStep
from paddle_tpu.kernels.flash_attention import _ref_attention
from paddle_tpu.optimizer import AdamW
from paddle_tpu.parallel.sp_attention import ring_attention, ulysses_attention


def _reset_fleet(**degrees):
    from paddle_tpu.parallel import mesh as mesh_mod
    mesh_mod._STATE["mesh"] = None
    s = fleet.DistributedStrategy()
    s.hybrid_configs = degrees
    fleet.init(is_collective=True, strategy=s)
    return fleet.get_hybrid_communicate_group()


def _qkv(B=2, H=4, S=32, D=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    return mk(), mk(), mk()


def _ref_bhsd(q, k, v, causal):
    # [B,H,S,D] -> paddle layout for the oracle -> back
    o = _ref_attention(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                       jnp.swapaxes(v, 1, 2), causal)
    return jnp.swapaxes(o, 1, 2)


class TestRingAttention:
    @pytest.mark.parametrize("sep", [2, 4])
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_parity(self, sep, causal):
        hcg = _reset_fleet(sep_degree=sep, dp_degree=8 // sep)
        q, k, v = _qkv()
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, causal=causal, mesh=hcg.mesh))(q, k, v)
        ref = _ref_bhsd(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_parity(self):
        hcg = _reset_fleet(sep_degree=4, dp_degree=2)
        q, k, v = _qkv(seed=1)

        def loss_ring(q, k, v):
            o = ring_attention(q, k, v, causal=True, mesh=hcg.mesh)
            return jnp.sum(o * jnp.cos(o))

        def loss_ref(q, k, v):
            o = _ref_bhsd(q, k, v, True)
            return jnp.sum(o * jnp.cos(o))

        g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g0 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, n in zip(g0, g1, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"d{n}")

    def test_ppermute_in_hlo(self):
        """The ring actually rides neighbor transfers, not gathers."""
        hcg = _reset_fleet(sep_degree=4, dp_degree=2)
        q, k, v = _qkv()
        hlo = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, causal=True, mesh=hcg.mesh)).lower(
                q, k, v).compile().as_text()
        assert "collective-permute" in hlo


class TestUlyssesAttention:
    @pytest.mark.parametrize("sep", [2, 4])
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_parity(self, sep, causal):
        hcg = _reset_fleet(sep_degree=sep, dp_degree=8 // sep)
        q, k, v = _qkv()  # H=4 divisible by sep
        out = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, causal=causal, mesh=hcg.mesh))(q, k, v)
        ref = _ref_bhsd(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_parity(self):
        hcg = _reset_fleet(sep_degree=2, dp_degree=4)
        q, k, v = _qkv(seed=2)

        def loss_uly(q, k, v):
            o = ulysses_attention(q, k, v, causal=True, mesh=hcg.mesh)
            return jnp.sum(o * jnp.cos(o))

        def loss_ref(q, k, v):
            o = _ref_bhsd(q, k, v, True)
            return jnp.sum(o * jnp.cos(o))

        g1 = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
        g0 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, n in zip(g0, g1, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"d{n}")

    def test_all_to_all_in_hlo(self):
        hcg = _reset_fleet(sep_degree=4, dp_degree=2)
        q, k, v = _qkv()
        hlo = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, causal=True, mesh=hcg.mesh)).lower(
                q, k, v).compile().as_text()
        assert "all-to-all" in hlo


class TestLlamaContextParallel:
    def _losses(self, cp, sep, steps=2, seed=9):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        hcg = _reset_fleet(sep_degree=sep, dp_degree=8 // sep)
        paddle.seed(43)
        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=4, max_position_embeddings=32,
                          use_recompute=False, context_parallel=cp)
        model = LlamaForCausalLM(cfg)
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = TrainStep(model, lambda loss, _l: loss, opt,
                         mesh=hcg.mesh if sep > 1 else None)
        ids = paddle.to_tensor(np.random.RandomState(seed).randint(
            0, 64, (4, 16)).astype(np.int32))
        return [float(step.step((ids, ids), (ids,)).value)
                for _ in range(steps)]

    def test_llama_ring_sep2_matches_serial(self):
        serial = self._losses(cp="", sep=1)
        ring = self._losses(cp="ring", sep=2)
        np.testing.assert_allclose(serial, ring, rtol=2e-4, atol=2e-5)

    def test_llama_ulysses_sep2_matches_serial(self):
        serial = self._losses(cp="", sep=1)
        uly = self._losses(cp="ulysses", sep=2)
        np.testing.assert_allclose(serial, uly, rtol=2e-4, atol=2e-5)

    def test_llama_ring_gqa(self):
        """GQA (nkv < nh) routes through the kv-head repeat."""
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        hcg = _reset_fleet(sep_degree=2, dp_degree=4)
        paddle.seed(44)
        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=32,
                          use_recompute=False, context_parallel="ring")
        model = LlamaForCausalLM(cfg)
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = TrainStep(model, lambda loss, _l: loss, opt, mesh=hcg.mesh)
        ids = paddle.to_tensor(np.random.RandomState(10).randint(
            0, 64, (4, 16)).astype(np.int32))
        loss = float(step.step((ids, ids), (ids,)).value)
        assert np.isfinite(loss)


class TestCPInsidePipeline:
    """r2 §5.7 weak item: CP x PP composition was rejected outright. The
    ring shard_map re-binds to the context AbstractMesh inside the
    pipeline's manual 'pp' region, so the two compose — under BOTH
    partitioners (r5: the ring position is a sharded-iota input, not an
    axis_index call, which was the one Shardy-rejected lowering). Mixing
    partitioners in one process aborts XLA-CPU, so each parity check runs
    in a fresh child interpreter (tests/_cp_pp_child.py)."""

    def _run_child(self, cp, extra=()):
        import os
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, PYTHONPATH=repo, PALLAS_AXON_POOL_IPS="")
        p = subprocess.run(
            [sys.executable, os.path.join(repo, "tests", "_cp_pp_child.py"),
             cp, *extra],
            capture_output=True, text=True, timeout=420, env=env, cwd=repo)
        assert p.returncode == 0, p.stderr[-600:]
        assert "parity OK" in p.stdout

    def test_ring_cp_inside_pp2_matches_serial(self):
        self._run_child("ring")

    def test_ring_cp_inside_pp_shardy(self):
        """r3's strict-xfail canary, now a REAL pass (VERDICT r4 item 6):
        the ring body takes its ring position as a P('sep')-sharded iota
        input instead of calling jax.lax.axis_index — whose lowering is
        an sdy.manual_computation binding every other mesh axis, the one
        construct Shardy rejects inside the pipeline's manual 'pp'
        region. ppermute + shard_map transpose were never the blocker, so
        fwd+bwd now compile and match serial under BOTH partitioners."""
        self._run_child("ring", extra=("--shardy",))

    def test_ulysses_inside_pp_rejected_with_guidance(self):
        """Ulysses' head-scatter all_to_all cannot partition inside a
        nested manual region (XLA GSPMD CHECK on either partitioner) —
        the model rejects it with a pointer to ring."""
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.parallel import mesh as mesh_mod
        mesh_mod._STATE["mesh"] = None
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"pp_degree": 2, "sep_degree": 2, "dp_degree": 2}
        fleet.init(is_collective=True, strategy=s)
        hcg = fleet.get_hybrid_communicate_group()
        paddle.seed(53)
        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                          num_hidden_layers=4, num_attention_heads=4,
                          num_key_value_heads=4, max_position_embeddings=32,
                          use_recompute=False, context_parallel="ulysses",
                          pipeline_microbatches=2)
        model = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(np.random.RandomState(9).randint(
            0, 64, (8, 16)).astype(np.int32))
        with pytest.raises(ValueError, match="ring"):
            model(ids, ids)
