"""Device-boundary cost observatory (profiler/cost.py + its threading
through the serving stack; README "Cost attribution & /debug/profile").

The properties under test, per the observability contract:

- the observatory itself: exact per-program call counts, abstract-shape
  byte accounting (host-resident args = h2d, declared host-fetched
  results = d2h, device-resident leaves never charged), compile-event
  deltas, phase attribution — all with no device sync;
- EXACTNESS: on real engine runs of all four configurations (dense /
  paged two-program / unified ragged / speculative), the observatory's
  dispatch totals equal independent counts taken at the engine's
  program accessors, and the per-kind split equals the engine's own
  stats — with token streams byte-identical to an uninstrumented run
  and ``decode_compilations() == 1``;
- determinism: a chaos+spec replay under ``VirtualClock`` exports a
  byte-identical accounting twice, monotonic across the engine
  rebuilds inside it, with zero compile events when warm;
- counter tracks: the engine emits ``ph:"C"`` dispatch/transfer/
  KV-occupancy samples onto the step timeline;
- the gateway surface: ``serving_dispatches_total{program}``,
  ``serving_transfer_bytes_total{direction}``,
  ``serving_dispatches_per_decoded_token`` on ``/metrics``; every
  engine-stat-derived counter monotonic across crash-recovery rebuilds
  (the ISSUE 11 fix); ``GET /debug/profile`` (aggregate + step-bounded
  window) and the ``/debug/requests`` cost columns over live HTTP;
- guard discipline: a static (ast) sweep asserting every tracer/cost
  recording site under ``paddle_tpu/serving/`` routes through the
  one-attribute ``_tr()``/``_co()`` guards;
- the profiler CLI accepts Chrome trace JSON files (the
  ``/debug/trace`` document) with ``--top``/``--json`` honored and
  exit 1 on unparseable input.
"""
import ast
import contextlib
import io
import json
import pathlib
import re
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.profiler.cost import CostObservatory, _CountedProgram
from paddle_tpu.profiler.tracing import SpanTracer
from paddle_tpu.serving import (ContinuousBatchingEngine, FaultPlan,
                                GenerationRequest, VirtualClock)
from paddle_tpu.serving.server import ServingGateway, serve

from test_metrics_prom import parse_prometheus
from test_tracing import _chaos_run, _chaos_workload

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "scripts"))
# the ONE independent program-accessor counter (bench_ragged's method):
# shared with the bench so the exactness pin and the banked
# exact_vs_program_accessors gate can never drift apart
from bench_dispatch import _count_accessor_launches  # noqa: E402

NUM_SLOTS, S_MAX = 2, 256


@pytest.fixture(scope="module")
def model():
    paddle.seed(31)
    return LlamaForCausalLM(llama_tiny())


def _reqs(n=3, max_new=4, plen=8, long_prompt=False):
    rng = np.random.RandomState(7)
    out = []
    for i in range(n):
        kw = {}
        if i % 3 == 2:
            kw = dict(temperature=0.8, top_k=5, seed=100 + i)
        out.append(GenerationRequest(
            prompt=rng.randint(0, 256, (plen,)).astype(np.int32),
            max_new_tokens=max_new, **kw))
    if long_prompt:
        out.append(GenerationRequest(
            prompt=rng.randint(0, 256, (72,)).astype(np.int32),
            max_new_tokens=max_new))
    return out


# ------------------------------------------------------------------ unit
class TestCostObservatoryUnit:
    def test_byte_accounting_abstract_and_exact(self):
        co = CostObservatory(clock=VirtualClock())
        f = jax.jit(lambda a, b: (a + 1.0, jnp.sum(b)))
        w = co.wrap(("decode", 1), f, host_out=(1,))
        a = np.zeros((4, 8), np.float32)      # host arg: 128 bytes h2d
        b = jnp.zeros((2, 2), jnp.float32)    # device arg: never charged
        w(a, b)
        rec = co.programs["decode[1]"]
        assert rec["calls"] == 1
        assert rec["h2d_bytes"] == 128
        assert rec["d2h_bytes"] == 4          # the () f32 host_out leaf
        assert rec["compiles"] == 1           # first call traced
        w(a, b)
        assert rec["calls"] == 2 and rec["compiles"] == 1
        assert co.totals["dispatches"] == 2
        assert co.totals["h2d_bytes"] == 256
        assert co.kind_calls("decode") == 2
        assert co.kind_calls("ragged") == 0

    def test_phase_attribution(self):
        co = CostObservatory(clock=VirtualClock())
        w = co.wrap(("prefill",), jax.jit(lambda x: x), host_out=())
        co.set_phase("admit")
        w(np.zeros(2, np.float32))
        co.set_phase("launch")
        w(np.zeros(2, np.float32))
        w(np.zeros(2, np.float32))
        co.set_phase(None)
        assert co.phases["admit"]["dispatches"] == 1
        assert co.phases["launch"]["dispatches"] == 2

    def test_export_delta_and_snapshot(self):
        co = CostObservatory(clock=VirtualClock())
        w = co.wrap(("ragged", 2, 10, 1, "jnp"), jax.jit(lambda x: x),
                    host_out=())
        w(np.zeros(4, np.float32))
        base = co.snapshot_full()
        s0 = co.snapshot()
        w(np.zeros(4, np.float32))
        w(np.zeros(4, np.float32))
        assert co.delta(s0)["dispatches"] == 2
        doc = co.export(base=base)
        assert doc["totals"]["dispatches"] == 2
        (prog,) = doc["programs"]
        assert prog["program"] == "ragged[2,10,1,jnp]"
        assert prog["calls"] == 2 and prog["kind"] == "ragged"
        full = co.export()
        assert full["totals"]["dispatches"] == 3
        json.dumps(full)                       # JSON-serializable

    def test_disabled_handout_is_raw(self, model):
        eng = ContinuousBatchingEngine(model, num_slots=NUM_SLOTS,
                                       max_seq_len=S_MAX, jit_cache={})
        # no observatory / disabled observatory: the accessor hands out
        # the RAW jitted program — zero wrapper on the hot path
        assert not isinstance(eng._prefill_fn(), _CountedProgram)
        eng.cost = CostObservatory().disable()
        assert not isinstance(eng._prefill_fn(), _CountedProgram)
        eng.cost.enable()
        assert isinstance(eng._prefill_fn(), _CountedProgram)


# ----------------------------------------------------------- tier ledger
class TestTierLedger:
    """ISSUE 16 satellite: KV-tier traffic (spill d2h / readmit h2d /
    fleet peer transfer) gets its OWN ledger — mirroring the PR-15
    collectives rule — so cache-plane bytes never pollute the
    per-program h2d/d2h baselines DISPATCH_BENCH.json banks."""

    def test_record_tier_unit_and_separation(self):
        co = CostObservatory(clock=VirtualClock())
        co.record_tier("d2h", 2, 4096)
        co.record_tier("d2h", 1, 2048)
        co.record_tier("h2d", 1, 2048)
        assert co.tier_bytes("d2h") == 6144
        assert co.tier_bytes("h2d") == 2048
        assert co.tier_bytes("peer") == 0      # unseen: explicit zero
        assert co.tiers["d2h"] == {"blocks": 3, "bytes": 6144}
        # THE SEPARATE-LEDGER RULE: tier traffic never touches the
        # per-program transfer totals or the dispatch count
        assert co.totals["h2d_bytes"] == 0
        assert co.totals["d2h_bytes"] == 0
        assert co.totals["dispatches"] == 0

    def test_export_delta_and_snapshot_carry_tiers(self):
        co = CostObservatory(clock=VirtualClock())
        co.record_tier("d2h", 1, 100)
        base = co.snapshot_full()
        assert base["tiers"]["d2h"] == {"blocks": 1, "bytes": 100}
        co.record_tier("d2h", 2, 200)
        co.record_tier("peer", 1, 50)
        doc = co.export(base=base)
        assert doc["tiers"] == {"d2h": {"blocks": 2, "bytes": 200},
                                "peer": {"blocks": 1, "bytes": 50}}
        full = co.export()
        assert full["tiers"]["d2h"] == {"blocks": 3, "bytes": 300}
        json.dumps(full)                       # JSON-serializable

    def test_engine_tier_traffic_never_pollutes_program_baselines(
            self, model):
        """A thrashed tiered engine moves real spill/readmit bytes —
        and the per-program totals still equal exactly the sum over
        the program records, as if the tier did not exist."""
        fams = [np.random.RandomState(900 + f).randint(
            0, 256, (16,)).astype(np.int32) for f in range(2)]
        reqs = []
        for i in range(3):
            for f in range(2):
                tail = np.random.RandomState(10 * f + i).randint(
                    0, 256, (5,)).astype(np.int32)
                reqs.append(GenerationRequest(
                    prompt=np.concatenate([fams[f], tail]),
                    max_new_tokens=3))
        eng = ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
            decode_chunk=1, paged_attn=False, prefix_cache=True,
            prefix_block_size=8, prefix_blocks=2,
            host_tier_bytes=1 << 24, jit_cache={})
        co = CostObservatory()
        eng.cost = co
        for r in reqs:     # serial: each publish thrashes the 2-block pool
            eng.generate([r])
        pc = eng.prefix_cache
        assert pc.stats["spilled_blocks"] > 0
        assert pc.stats["readmitted_blocks"] > 0
        assert co.tier_bytes("d2h") > 0 and co.tier_bytes("h2d") > 0
        # bytes moved match the ledger's own block count × block bytes
        assert co.tiers["d2h"]["bytes"] == \
            pc.stats["spilled_blocks"] * pc.pool.block_nbytes
        assert co.tiers["h2d"]["bytes"] == \
            pc.stats["readmitted_blocks"] * pc.pool.block_nbytes
        # separation: totals are exactly the per-program sums
        progs = list(co.programs.values())
        assert co.totals["h2d_bytes"] == \
            sum(rec["h2d_bytes"] for rec in progs)
        assert co.totals["d2h_bytes"] == \
            sum(rec["d2h_bytes"] for rec in progs)

    def test_gateway_tier_series_and_profile_doc(self, model):
        """``serving_tier_bytes_total{direction}`` scrapes from a
        tiered gateway (d2h/h2d > 0, peer an explicit 0 — all three
        series exist), the ``serving_prefix_*`` tier counters/gauges
        agree with the trie's stats, and ``/debug/profile`` carries
        the tiers section without touching per-program columns."""
        fams = [np.random.RandomState(910 + f).randint(
            0, 256, (16,)).astype(np.int32) for f in range(2)]
        eng = ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
            decode_chunk=1, paged_attn=False, prefix_cache=True,
            prefix_block_size=8, prefix_blocks=2,
            host_tier_bytes=1 << 24, jit_cache={})
        gw = ServingGateway(eng, start=False)  # installs gw.cost on eng
        for i in range(3):
            for f in range(2):
                tail = np.random.RandomState(20 * f + i).randint(
                    0, 256, (5,)).astype(np.int32)
                eng.generate([GenerationRequest(
                    prompt=np.concatenate([fams[f], tail]),
                    max_new_tokens=3)])
        pc = eng.prefix_cache
        assert pc.stats["spilled_blocks"] > 0
        fams_p = parse_prometheus(gw.registry.render())

        def val(name, **labels):
            key = tuple(sorted(labels.items()))
            return fams_p[name]["samples"][(name, key)]

        assert val("serving_tier_bytes_total", direction="d2h") == \
            gw.cost.tier_bytes("d2h") > 0
        assert val("serving_tier_bytes_total", direction="h2d") == \
            gw.cost.tier_bytes("h2d") > 0
        assert val("serving_tier_bytes_total", direction="peer") == 0
        assert val("serving_prefix_spilled_blocks_total") == \
            pc.stats["spilled_blocks"]
        assert val("serving_prefix_tier_hits_total") == \
            pc.stats["tier_hits"] > 0
        assert val("serving_prefix_readmitted_blocks_total") == \
            pc.stats["readmitted_blocks"] > 0
        assert val("serving_prefix_tier_blocks") == pc.tier.num_blocks > 0
        assert val("serving_prefix_tier_bytes") == pc.tier.bytes_used > 0
        assert val("serving_prefix_tier_bytes_capacity") == 1 << 24
        assert val("serving_prefix_cached_blocks") == \
            pc.num_cached_blocks
        doc = gw.profile_doc()
        tiers = doc["tiers"]
        assert tiers["host_tier_bytes"] == 1 << 24
        assert tiers["tier_blocks"] == pc.tier.num_blocks
        assert tiers["per_direction"]["d2h"]["bytes"] == \
            gw.cost.tier_bytes("d2h")
        assert "bytes_per_decoded_token" in tiers["per_direction"]["d2h"]

    def test_tierless_gateway_scrapes_explicit_zeros(self, model):
        gw = ServingGateway(ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
            decode_chunk=1, prefix_cache=True, prefix_block_size=8,
            jit_cache={}), start=False)
        fams_p = parse_prometheus(gw.registry.render())
        s = fams_p["serving_tier_bytes_total"]["samples"]
        for tdir in ("d2h", "h2d", "peer"):
            assert s[("serving_tier_bytes_total",
                      (("direction", tdir),))] == 0
        assert fams_p["serving_prefix_tier_bytes_capacity"]["samples"][
            ("serving_prefix_tier_bytes_capacity", ())] == 0
        # same idiom as collectives on a tp=1 engine: the export key
        # exists, empty — no occupancy section is synthesized
        assert gw.profile_doc()["tiers"] == {}

    def test_tier_counters_monotonic_across_rebuild(self, model):
        """A crash-recovery rebuild starts a fresh trie AND a fresh
        host tier, zeroing their stats — the gateway banks the dead
        incarnation's tier counts (CARRIED_PREFIX_STATS) so the
        ``serving_prefix_*`` tier series stay monotonic."""
        jit = {}
        fams = [np.random.RandomState(920 + f).randint(
            0, 256, (16,)).astype(np.int32) for f in range(2)]

        def factory():
            return ContinuousBatchingEngine(
                model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
                decode_chunk=1, paged_attn=False, prefix_cache=True,
                prefix_block_size=8, prefix_blocks=2,
                host_tier_bytes=1 << 24, jit_cache=jit)

        plan = FaultPlan().at_step(8, "fatal")
        gw = ServingGateway(factory(), engine_factory=factory,
                            fault_hook=plan, retry_backoff_s=0.0,
                            start=False)
        reqs = []
        for i in range(3):
            for f in range(2):
                tail = np.random.RandomState(30 * f + i).randint(
                    0, 256, (5,)).astype(np.int32)
                reqs.append(GenerationRequest(
                    prompt=np.concatenate([fams[f], tail]),
                    max_new_tokens=3))
        gw.start()
        for r in reqs:             # serial: publishes land in order
            gw.submit(r).result()
        assert gw.restarts >= 1
        # the dead incarnation spilled before dying, and its counts
        # were banked into the carried base at the rebuild
        pc_base = gw._counter_state[1]
        assert pc_base["spilled_blocks"] > 0
        total = gw._pc_stat("spilled_blocks")
        assert total == pc_base["spilled_blocks"] + \
            gw.engine.prefix_cache.stats["spilled_blocks"]
        fams_p = parse_prometheus(gw.registry.render())
        assert fams_p["serving_prefix_spilled_blocks_total"]["samples"][
            ("serving_prefix_spilled_blocks_total", ())] == total
        gw.shutdown(drain=True, timeout=30)


# ------------------------------------------------------------ exactness
class TestExactAccounting:
    CONFIGS = (
        ("dense", dict(paged_attn=False, ragged_step=False)),
        ("paged", dict(paged_attn=True, ragged_step=False,
                       prefill_chunk=32, prefix_block_size=8)),
        ("ragged", dict(paged_attn=True, ragged_step=True,
                        prefill_chunk=32, prefix_block_size=8,
                        headroom_mult=None)),
        ("spec", dict(paged_attn=True, ragged_step=True,
                      prefill_chunk=32, prefix_block_size=8,
                      headroom_mult=None, spec_decode=True, spec_k=3)),
    )

    @pytest.mark.slow  # 15 s exact-count duplicate: test_launch_attribution_
    # per_request below keeps the default exact-accounting rep (870s cap)
    def test_counts_exact_streams_unchanged(self, model):
        reqs = _reqs(3, max_new=4, long_prompt=True)
        for name, cfg in self.CONFIGS:
            jit = {}
            base_eng = ContinuousBatchingEngine(
                model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
                decode_chunk=1, jit_cache=jit, **cfg)
            base = [o.tolist() for o in base_eng.generate(reqs)]
            eng = ContinuousBatchingEngine(
                model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
                decode_chunk=1, jit_cache=jit, **cfg)
            co = CostObservatory()
            eng.cost = co
            accessor = _count_accessor_launches(eng)
            out = [o.tolist() for o in eng.generate(reqs)]
            # observing never changes a token
            assert out == base, name
            # dispatch count == independent program-accessor count
            assert co.totals["dispatches"] == accessor["n"], name
            assert co.totals["dispatches"] > 0
            # per-kind split == the engine's own stats
            if name == "dense":
                assert co.kind_calls("decode") == \
                    eng.stats["decode_calls"]
            elif name == "paged":
                assert co.kind_calls("pdecode") == \
                    eng.stats["decode_calls"]
                assert co.kind_calls("psuffix") >= 1   # chunked prompt
            elif name == "ragged":
                assert co.kind_calls("ragged") == \
                    eng.stats["unified_steps"]
            else:
                assert co.kind_calls("spec") == eng.stats["spec_steps"]
            # compile-once survives the counting facade (raw fns stay
            # in the jit-cache), and the warm run retraced nothing
            assert eng.decode_compilations() == 1, name
            assert co.totals["compiles"] == 0, name
            # boundary bytes flowed both ways
            assert co.totals["h2d_bytes"] > 0
            assert co.totals["d2h_bytes"] > 0
            # every launch landed in a named phase
            assert None not in co.phases
            assert co.phases.keys() <= {"admit", "plan", "launch",
                                        "host-accept"}

    def test_launch_attribution_per_request(self, model):
        eng = ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
            decode_chunk=1, prefill_chunk=32, prefix_block_size=8,
            headroom_mult=None, jit_cache={})
        seqs = [eng.submit(r) for r in _reqs(2, max_new=4,
                                             long_prompt=True)]
        while eng.has_work():
            eng.step()
        for seq in seqs:
            # every request rode >= 1 prefill launch + >= 1 decode
            assert seq.launches >= 2
        # the chunked long prompt paid one launch per chunk too
        assert seqs[-1].launches >= 3


# -------------------------------------------------------- counter tracks
class TestCounterTracks:
    def test_step_timeline_counter_events(self, model):
        tr = SpanTracer().enable()
        eng = ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
            decode_chunk=1, jit_cache={})
        eng.tracer = tr
        eng.cost = CostObservatory()
        eng.generate(_reqs(2, max_new=4))
        evs = tr.events()
        counters = [e for e in evs if e["ph"] == "C"]
        names = {e["name"] for e in counters}
        assert {"dispatches", "transfer_bytes", "kv_blocks",
                "block_table_fill"} <= names
        steps = [e for e in evs if e["name"] == "step"]
        # one sample per track per step
        for track in names:
            assert len([e for e in counters if e["name"] == track]) \
                == len(steps)
        disp = [e for e in counters if e["name"] == "dispatches"]
        assert sum(e["args"]["per_step"] for e in disp) == \
            eng.cost.totals["dispatches"]
        xfer = [e for e in counters if e["name"] == "transfer_bytes"]
        assert all({"h2d", "d2h"} <= set(e["args"]) for e in xfer)
        kv = [e for e in counters if e["name"] == "kv_blocks"]
        occ = eng.cache.occupancy()
        assert kv[-1]["args"] == occ
        assert set(occ) == {"live", "trie", "free"}

    def test_no_counters_without_cost_or_tracer(self, model):
        # tracer on, cost absent: spans yes, dispatch counters no
        tr = SpanTracer().enable()
        eng = ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
            decode_chunk=1, jit_cache={})
        eng.tracer = tr
        eng.generate(_reqs(1, max_new=2))
        names = {e["name"] for e in tr.events() if e["ph"] == "C"}
        assert "dispatches" not in names
        assert "transfer_bytes" not in names
        # KV occupancy is tracer-only — it still rides along
        assert "kv_blocks" in names


# ----------------------------------------------------- chaos determinism
class TestChaosDeterminism:
    def test_cost_accounting_byte_identical_and_monotonic(self, model):
        jit = {}
        reqs = _chaos_workload()
        # warm every program (recovery-path buckets included)
        _chaos_run(model, jit, reqs, with_plan=True, trace=True)
        outs1, _, gw1, eng1, plan1 = _chaos_run(
            model, jit, reqs, with_plan=True, trace=True)
        outs2, _, gw2, eng2, plan2 = _chaos_run(
            model, jit, reqs, with_plan=True, trace=True)
        assert outs1 == outs2 and plan1.log == plan2.log
        # the accounting replays byte-identically under VirtualClock
        doc1 = json.dumps(gw1.profile_doc(), sort_keys=True)
        doc2 = json.dumps(gw2.profile_doc(), sort_keys=True)
        assert doc1 == doc2
        d = json.loads(doc1)
        assert d["totals"]["dispatches"] > 0
        assert d["totals"]["decoded_tokens"] > 0
        assert d["totals"]["dispatches_per_decoded_token"] > 0
        # the observatory survived >= 3 engine rebuilds monotonic (it
        # is gateway-owned), and the warm replay retraced NOTHING —
        # compile-once across rebuilds, now measured rather than
        # inferred
        assert gw1.restarts >= 3
        assert d["totals"]["compiles"] == 0
        assert eng1.decode_compilations() == 1
        # per-program calls sum to the total (no unattributed launches)
        assert sum(p["calls"] for p in d["programs"]) == \
            d["totals"]["dispatches"]


# ------------------------------------------------------- gateway surface
class TestGatewaySurface:
    def test_metrics_families_and_values(self, model):
        gw = ServingGateway(ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
            decode_chunk=1, jit_cache={}), start=False)
        streams = [gw.submit(r) for r in _reqs(3, max_new=4)]
        gw.start()
        for s in streams:
            s.result()
        fams = parse_prometheus(gw.registry.render())
        gw.shutdown(drain=True, timeout=30)
        disp = fams["serving_dispatches_total"]
        assert disp["type"] == "counter"
        by_kind = {lab[0][1]: v for (_, lab), v in
                   disp["samples"].items()}
        assert set(by_kind) == {"prefill", "suffix", "psuffix",
                                "decode", "pdecode", "ragged", "mtick",
                                "spec"}
        assert by_kind["ragged"] > 0          # the engine default path
        assert sum(by_kind.values()) == gw.cost.totals["dispatches"]
        xfer = {lab[0][1]: v for (_, lab), v in
                fams["serving_transfer_bytes_total"]["samples"].items()}
        assert xfer["h2d"] > 0 and xfer["d2h"] > 0
        g = fams["serving_dispatches_per_decoded_token"]
        assert g["type"] == "gauge"
        (val,) = g["samples"].values()
        assert val == pytest.approx(
            gw.cost.totals["dispatches"]
            / max(gw._stat("tokens_generated"), 1))
        assert fams["serving_program_compiles_total"]["samples"][
            ("serving_program_compiles_total", ())] >= 1  # cold start

    def test_shared_prefix_cache_not_double_counted(self, model):
        """An adopted SHARED PrefixCache rides into every rebuilt
        engine with its stats intact — the rebuild carry must not bank
        them too (that would double hits/misses per restart)."""
        jit = {}
        seed_eng = ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
            decode_chunk=1, prefix_cache=True, prefix_block_size=8,
            jit_cache=jit)
        shared = seed_eng.prefix_cache

        def factory():
            return ContinuousBatchingEngine(
                model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
                decode_chunk=1, prefix_cache=shared,
                prefix_block_size=8, jit_cache=jit)

        plan = FaultPlan().at_step(2, "fatal")
        gw = ServingGateway(factory(), engine_factory=factory,
                            fault_hook=plan, retry_backoff_s=0.0,
                            start=False)
        streams = [gw.submit(r) for r in _reqs(3, max_new=4)]
        gw.start()
        for s in streams:
            s.result()
        assert gw.restarts >= 1
        # the shared trie's own counts ARE the totals — no carry
        assert gw._pc_stat("misses") == shared.stats["misses"]
        assert gw._pc_stat("hits") == shared.stats["hits"]
        gw.shutdown(drain=True, timeout=30)

    def test_stat_counters_monotonic_across_rebuild(self, model):
        """ISSUE 11 satellite: engine ``stats`` reset on crash-recovery
        rebuild; every derived /metrics counter must carry a
        gateway-side base. A scrape thread samples the affected series
        THROUGH the fault matrix and each must be non-decreasing."""
        jit = {}
        clk = VirtualClock()

        def factory():
            return ContinuousBatchingEngine(
                model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
                decode_chunk=1, prefix_cache=True, prefix_block_size=8,
                prefill_chunk=32, spec_decode=True, spec_k=3,
                headroom_mult=None, step_clock=clk, jit_cache=jit)

        plan = (FaultPlan(clock=clk)
                .at_step(3, "fatal").at_step(7, "pool")
                .at_step(11, "fatal"))
        gw = ServingGateway(factory(), engine_factory=factory,
                            fault_hook=plan, clock=clk,
                            retry_backoff_s=0.0, max_restarts=16,
                            start=False)
        streams = [gw.submit(r) for r in _chaos_workload()]
        series = ("prefill_chunks", "prefill_tokens_saved",
                  "spec_proposed", "spec_accepted", "preemptions",
                  "tokens_generated")
        samples = []
        stop = threading.Event()

        def scrape():
            while not stop.is_set():
                samples.append(
                    {k: gw._stat(k) for k in series}
                    | {"pc_" + k: gw._pc_stat(k)
                       for k in ("hits", "misses", "evictions")}
                    | {"dispatches": gw.cost.totals["dispatches"]})
                time.sleep(0.002)

        t = threading.Thread(target=scrape)
        t.start()
        gw.start()
        for s in streams:
            ids, reason = s.result()
            assert reason in ("stop", "length")
        stop.set()
        t.join(10)
        assert gw.restarts >= 2
        # the fix itself: the dead incarnations' counts were banked
        assert gw._stat_base["tokens_generated"] > 0
        fams = parse_prometheus(gw.registry.render())
        assert fams["serving_prefill_chunks_total"]["samples"][
            ("serving_prefill_chunks_total", ())] == \
            gw._stat("prefill_chunks")
        gw.shutdown(drain=True, timeout=30)
        assert len(samples) >= 2
        for key in samples[0]:
            vals = [s[key] for s in samples]
            assert all(a <= b for a, b in zip(vals, vals[1:])), \
                f"{key} went backwards: {vals}"


# ------------------------------------------------------------- live HTTP
@pytest.fixture(scope="class")
def server(model):
    srv = serve(model, port=0, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
                max_queue=8, model_name="cost-test")
    s = srv.gateway.submit(GenerationRequest(prompt=[1, 2, 3, 4],
                                             max_new_tokens=2))
    s.result()
    yield srv
    srv.shutdown(drain=False, timeout=30)


def _get(server, path, timeout=60):
    try:
        with urllib.request.urlopen(server.url + path,
                                    timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


class TestDebugProfileHTTP:
    def test_aggregate_profile(self, server):
        status, doc = _get(server, "/debug/profile")
        assert status == 200
        assert doc["window_steps"] is None
        t = doc["totals"]
        assert t["dispatches"] > 0 and t["decoded_tokens"] > 0
        assert t["dispatches_per_decoded_token"] > 0
        assert t["h2d_bytes_per_decoded_token"] > 0
        assert doc["programs"]
        for p in doc["programs"]:
            assert {"program", "kind", "calls", "h2d_bytes",
                    "d2h_bytes", "compiles", "wall_ewma_s",
                    "share_of_wall"} <= set(p)
        assert doc["phases"]
        assert abs(sum(p["share_of_wall"]
                       for p in doc["programs"]) - 1.0) < 0.01

    def test_step_bounded_window(self, server):
        stream = server.gateway.submit(GenerationRequest(
            prompt=[9, 10, 11, 12], max_new_tokens=96))
        status, doc = _get(server, "/debug/profile?steps=4&timeout_s=30")
        stream.result()
        assert status == 200
        # window_steps reports steps actually CAPTURED (== the ask
        # here; a timed-out window reports fewer + truncated flag)
        assert doc["window_steps"] == 4
        assert doc["window_steps_requested"] == 4
        assert doc["window_truncated"] is False
        # a 4-step window over a decoding request: exactly one unified
        # launch per captured step; the request's own prefill launch
        # rides along iff its admission landed inside the window
        (prog,) = [p for p in doc["programs"] if p["kind"] == "ragged"]
        assert prog["calls"] == 4
        assert 4 <= doc["totals"]["dispatches"] <= 5
        status, _ = _get(server, "/debug/profile?steps=bogus")
        assert status == 400

    def test_debug_requests_cost_columns(self, server):
        stream = server.gateway.submit(GenerationRequest(
            prompt=[5, 6, 7, 8], max_new_tokens=64))
        row = None
        for _ in range(200):
            status, doc = _get(server, "/debug/requests")
            assert status == 200
            rows = [r for r in doc["requests"] if r["id"] == stream.id]
            if rows and rows[0]["state"] == "running" \
                    and rows[0]["generated_tokens"] > 1:
                row = rows[0]
                break
            time.sleep(0.02)
        assert row is not None, "request never showed as running"
        assert row["launches"] >= 2        # prefill + >= 1 decode
        assert row["kv_bytes"] > 0
        bm = server.gateway.engine.cache.pool
        assert row["kv_bytes"] % bm.block_nbytes == 0
        stream.result()


# ------------------------------------------------------ guard discipline
RECORDING_METHODS = {"instant", "complete", "span", "counter", "wrap",
                     "set_phase"}
GUARD_RE = re.compile(r"=\s*self\._(tr|co)\(\)")
GUARD_NAMES = {"tr", "tracer", "co", "cost"}
SERVING_DIR = (pathlib.Path(__file__).resolve().parent.parent
               / "paddle_tpu" / "serving")


class TestGuardDiscipline:
    """ISSUE 11 satellite: the ≤1%-disabled-overhead property holds
    only while every tracer/cost instrumentation site goes through the
    one-attribute guards (``_tr()``/``_co()``). This static sweep makes
    the discipline un-regressable as call sites accumulate."""

    def _violations(self):
        violations, guarded = [], 0
        for path in sorted(SERVING_DIR.rglob("*.py")):
            src = path.read_text()
            tree = ast.parse(src)
            funcs = [n for n in ast.walk(tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            for fn in funcs:
                params = {a.arg for a in fn.args.args}
                fn_src = ast.get_source_segment(src, fn) or ""
                for node in ast.walk(fn):
                    if not (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr in RECORDING_METHODS):
                        continue
                    recv = node.func.value
                    where = f"{path.name}:{node.lineno}"
                    if isinstance(recv, ast.Attribute) and \
                            recv.attr in ("tracer", "cost"):
                        # direct self.tracer.X(...) — always unguarded
                        violations.append(
                            f"{where}: direct .{recv.attr}"
                            f".{node.func.attr}() bypasses the guard")
                        continue
                    if not (isinstance(recv, ast.Name)
                            and recv.id in GUARD_NAMES):
                        continue        # unrelated API (e.g. registry)
                    if recv.id in params or GUARD_RE.search(fn_src):
                        guarded += 1    # guard-local or caller-guarded
                    else:
                        violations.append(
                            f"{where}: {recv.id}.{node.func.attr}() "
                            f"without a `= self._tr()/_co()` guard in "
                            f"{fn.name}()")
        return violations, guarded

    def test_every_instrumentation_site_is_guarded(self):
        violations, guarded = self._violations()
        assert not violations, "\n".join(violations)
        # sanity: the sweep actually sees the instrumentation
        assert guarded >= 20, f"only {guarded} guarded sites found"

    def test_sweep_sees_the_multitick_step(self):
        """ISSUE 13 satellite: the multi-tick step path must sit
        behind the same one-attribute guards as every other step
        path. The engine's ``_multitick_step`` is inside the swept
        tree by construction; pin that it (a) exists, (b) contains
        tracer/cost instrumentation, and (c) that instrumentation is
        guard-disciplined (the sweep above would flag violations —
        this test makes sure the sweep actually has multi-tick sites
        to look at, so a refactor that moved them out of serving/
        could not silently shrink coverage)."""
        src = (SERVING_DIR / "engine.py").read_text()
        assert "_multitick_step" in src
        fn = src.split("def _multitick_step(")[1].split("\n    def ")[0]
        # the step's instrumentation goes through the guards...
        assert "tr = self._tr()" in fn and "co = self._co()" in fn
        # ...and the hot sites never touch self.tracer/self.cost raw
        assert "self.tracer." not in fn and "self.cost." not in fn
        # the program handout rides the counting chokepoint, so the
        # mtick program's dispatches are exactly attributed
        assert "_wrap_prog" in src.split("def _mtick_fn(")[1].split(
            "\n    def ")[0]

    def test_sweep_sees_the_quantized_kv_paths(self):
        """ISSUE 14 satellite: the int8-KV append/dequant call sites
        live inside the swept tree and stay guard-disciplined. Every
        quantized append routes through ONE helper (``_kv_write`` —
        quantize-on-write cannot fork per site), the packed forward's
        attention unpacks scales through ``_kv_attn_args`` (the one
        dequant handoff), and the engine hands pool arguments out
        through ``kv_args()`` at the SAME ``_wrap_prog``-counted
        launch sites as before — so quantized dispatches are exactly
        attributed and no new raw tracer/cost touch appeared."""
        dec = (SERVING_DIR / "decode.py").read_text()
        for fn_name in ("_packed_span_forward", "_fused_decode_tick",
                        "_paged_suffix_prefill_impl"):
            body = dec.split(f"def {fn_name}(")[1].split("\ndef ")[0]
            assert "_kv_write(" in body, fn_name
            assert "_kv_attn_args(" in body or "_kv_gather_rows(" \
                in body, fn_name
            # no stray raw pool scatter survived the refactor: appends
            # that bypass _kv_write would silently skip quantization
            assert ".at[phys" not in body, fn_name
        eng = (SERVING_DIR / "engine.py").read_text()
        for step in ("_unified_step", "_multitick_step", "_spec_step"):
            body = eng.split(f"def {step}(")[1].split("\n    def ")[0]
            assert "kv_args()" in body, step
            assert "self.tracer." not in body \
                and "self.cost." not in body, step
        # the quantized program variants ride the same counted handout
        for fn_name in ("_ragged_fn", "_mtick_fn", "_spec_fn",
                        "_suffix_fn", "_prefill_fn"):
            body = eng.split(f"def {fn_name}(")[1].split("\n    def ")[0]
            assert "_wrap_prog" in body, fn_name
            assert "_kvtag" in body or "_wtag" in body, fn_name

    def test_sweep_pins_a8_layer_body_dequant_free(self):
        """ISSUE 19 satellite: under ``quantize_activations`` the
        scanned layer body is PROVABLY dequant-free — no int8 weight is
        ever materialized at fp in the layer body; the only fp
        materialization is the int32 accumulator's post-dot rescale.
        Pinned structurally (AST, not substrings) so a refactor that
        quietly re-introduced a ``q.astype(f32) * s`` weight dequant
        into the a8 path fails here, not in a perf trace."""
        src = (SERVING_DIR / "decode.py").read_text()
        tree = ast.parse(src)
        fns = {n.name: n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)}
        # the a8 short-circuit is the FIRST statement of _dq_layer:
        # nothing dequantizes ahead of the early return
        first = [n for n in fns["_dq_layer"].body
                 if not (isinstance(n, ast.Expr)
                         and isinstance(n.value, ast.Constant))][0]
        assert isinstance(first, ast.If) \
            and isinstance(first.body[0], ast.Return)
        # _dq_head's a8 branch passes the int8 pair through (transpose
        # only) — it never falls into the _dq call below it
        head_first = [n for n in fns["_dq_head"].body
                      if isinstance(n, ast.If)][0]
        assert not any(isinstance(c, ast.Call)
                       and isinstance(c.func, ast.Name)
                       and c.func.id == "_dq"
                       for n in head_first.body for c in ast.walk(n))
        # none of the int8x8 projection helpers reach the dequant
        # helper (directly or via _dq_layer)
        for name in ("_a8_apply", "_a8_dot", "quantize_act_rows",
                     "_qkv_proj", "_swiglu_proj", "_o_proj",
                     "_head_logits"):
            calls = [n for n in ast.walk(fns[name])
                     if isinstance(n, ast.Call)
                     and isinstance(n.func, ast.Name)]
            assert not any(c.func.id in ("_dq", "_dq_layer")
                           for c in calls), name
        # _a8_apply: ONE dot_general with int32 accumulate, and the
        # single astype applies to the accumulator — never the weight
        a8 = fns["_a8_apply"]
        astypes = [n for n in ast.walk(a8) if isinstance(n, ast.Call)
                   and isinstance(n.func, ast.Attribute)
                   and n.func.attr == "astype"]
        assert len(astypes) == 1
        assert isinstance(astypes[0].func.value, ast.Name) \
            and astypes[0].func.value.id == "acc"
        dots = [n for n in ast.walk(a8) if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "dot_general"]
        assert len(dots) == 1
        assert any(kw.arg == "preferred_element_type"
                   for kw in dots[0].keywords)
        # every scanned layer body routes its projections through the
        # structure-dispatch helpers — an inline einsum could not
        # reintroduce a dequant site unnoticed
        for fn_name in ("_packed_span_forward", "_fused_decode_tick",
                        "_paged_suffix_prefill_impl", "_prefill_impl"):
            body = src.split(f"def {fn_name}(")[1].split("\ndef ")[0]
            for helper in ("_qkv_proj(", "_swiglu_proj(", "_o_proj(",
                           "_dq_layer("):
                assert helper in body, (fn_name, helper)

    def test_sweep_sees_the_tp_launch_path(self):
        """ISSUE 15 satellite: the tensor-parallel launch path stays
        guard-disciplined. Collective-byte accounting (the one NEW
        instrumentation the sharded path adds) routes through ONE
        engine helper (``_record_collectives``) and every call site
        sits behind the ``co = self._co()`` guard — the sweep above
        would flag a raw touch; this test makes sure the TP sites are
        actually inside the swept tree. The sharded programs ride the
        SAME ``_wrap_prog`` chokepoint (the tp tag joins the key, so
        dispatch attribution stays exact per variant), and the
        builders' shard_map wiring lives in decode.py where the
        quantized-path sweep already looks."""
        eng = (SERVING_DIR / "engine.py").read_text()
        assert "_record_collectives" in eng
        # every _record_collectives call site is co-guarded: the call
        # always receives the guarded `co` local, never self.cost
        sites = list(re.finditer(
            r"self\._record_collectives\(\s*([a-z_]+)", eng))
        assert len(sites) >= 5      # unified/mtick/spec/cold/suffix
        assert all(m.group(1) == "co" for m in sites)
        assert "self.cost.record_collective" not in eng
        # the sharded program handout rides the counted chokepoint
        # with the tp tag in the key
        for fn_name in ("_ragged_fn", "_mtick_fn", "_spec_fn",
                        "_suffix_fn", "_prefill_fn"):
            body = eng.split(f"def {fn_name}(")[1].split("\n    def ")[0]
            assert "_wrap_prog" in body, fn_name
            assert "_tptag" in body, fn_name
        # the TP wiring lives in the swept decode module: shard_map
        # wrapper + param/pool partition specs + the per-layer reduce
        dec = (SERVING_DIR / "decode.py").read_text()
        for name in ("_tp_shard", "_params_pspec", "_pool_pspec",
                     "_tp_allreduce"):
            assert f"def {name}(" in dec, name
        # every layer body applies tp_reduce at BOTH sites (o-proj +
        # down-proj) — the one-all-reduce-pair-per-layer contract
        for fn_name in ("_packed_span_forward", "_fused_decode_tick",
                        "_paged_suffix_prefill_impl", "_prefill_impl"):
            body = dec.split(f"def {fn_name}(")[1].split("\ndef ")[0]
            assert body.count("tp_reduce(o)") == 1, fn_name
            assert body.count("tp_reduce(m)") == 1, fn_name

    def test_sweep_sees_the_fused_tick_and_overlap_path(self):
        """ISSUE 20 satellite: the one-kernel decode path stays inside
        the counted/guarded tree. (a) The fused-tick program launches
        ONLY through the ``_wrap_prog``-counted ``_ragged_fn``/
        ``_mtick_fn`` handouts — the ``fk`` tag joins exactly those two
        keys (never prefill/suffix/spec), so fused dispatches are
        exactly attributed and the compile pin stays inclusive. (b) The
        kernel module itself is instrumentation-free (pure program —
        accounting happens at the engine chokepoint, so the sweep's
        serving/-scope is sufficient). (c) The overlap schedule is
        constructed at ONE site (``_tp_allreduce``) and applied at
        exactly the o-proj + down-proj ``tp_reduce`` pair the
        per-layer contract already pins — the three DECODE builders
        pass ``overlap=`` while the prefill/suffix builders cannot
        (decode latency is the target; prefill keys stay banked). (d)
        The census accessor rides the ``_wrap_prog`` chokepoint: the
        ONE ``record_census`` call site is ``_CountedProgram.__call__``
        — no serving code records a census of its own."""
        dec_path = SERVING_DIR / "decode.py"
        dec = dec_path.read_text()
        tree = ast.parse(dec)
        top = {n.name: n for n in tree.body
               if isinstance(n, ast.FunctionDef)}

        def calls_in(fn, callee):
            return [n for n in ast.walk(fn)
                    if isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == callee]

        # (c) one construction site: _overlap_reduce/_permute_allreduce
        # are referenced (outside their own defs) only from
        # _tp_allreduce and _overlap_reduce respectively
        for helper, owner in (("_overlap_reduce", "_tp_allreduce"),
                              ("_permute_allreduce", "_tp_allreduce")):
            users = [name for name, fn in top.items()
                     if name != helper
                     and any(isinstance(n, ast.Name) and n.id == helper
                             for n in ast.walk(fn))]
            assert users == [owner], (helper, users)
        # ...and exactly the decode-step builders request the overlap
        with_ov, without_ov = [], []
        for name, fn in top.items():
            for call in calls_in(fn, "_tp_allreduce"):
                kwargs = {kw.arg for kw in call.keywords}
                (with_ov if "overlap" in kwargs
                 else without_ov).append(name)
        assert sorted(with_ov) == ["build_multitick_step_fn",
                                   "build_ragged_step_fn",
                                   "build_spec_verify_fn"]
        assert sorted(without_ov) == ["build_paged_suffix_prefill_fn",
                                      "build_prefill_fn"]
        # the overlapped reduce lands at the SAME two per-layer sites
        # the tp contract pins (tp_reduce(o) / tp_reduce(m) above) —
        # no third application point exists anywhere in the module
        assert dec.count("tp_reduce(") == dec.count("tp_reduce(o)") \
            + dec.count("tp_reduce(m)") + dec.count("tp_reduce(x)")
        # (a) the fused program rides the counted handouts: the kernel
        # entry point is called ONLY from _fused_decode_tick (lazy
        # import), and the fk tag joins exactly the ragged+mtick keys
        assert dec.count("import fused_decode_tick") == 1
        body = dec.split("def _fused_decode_tick(")[1].split("\ndef ")[0]
        assert "fused_decode_tick(" in body
        eng = (SERVING_DIR / "engine.py").read_text()
        for fn_name, has_fk in (("_ragged_fn", True), ("_mtick_fn", True),
                                ("_spec_fn", False), ("_suffix_fn", False),
                                ("_prefill_fn", False)):
            fbody = eng.split(f"def {fn_name}(")[1].split("\n    def ")[0]
            assert "_wrap_prog" in fbody, fn_name
            assert ("_fktag" in fbody) is has_fk, fn_name
        # and the compile pin counts fk programs as decode programs
        dc = eng.split("def decode_compilations(")[1].split("\n    def ")[0]
        assert "_fktag" in dc
        # (b) the kernel module is pure: no tracer/cost/observatory
        # touch — accounting stays at the engine chokepoint
        kern = (SERVING_DIR.parent / "kernels"
                / "pallas_fused_decode_tick.py").read_text()
        for needle in ("tracer", "self.cost", "CostObservatory",
                       "record_"):
            assert needle not in kern, needle
        # (d) census recording has ONE call site: the counted-program
        # chokepoint in the profiler itself
        cost_src = (SERVING_DIR.parent / "profiler" / "cost.py").read_text()
        assert cost_src.count("co.record_census(") == 1
        assert "_CountedProgram" in cost_src.split(
            "co.record_census(")[0].rsplit("class ", 1)[1]
        serving_srcs = "".join(p.read_text()
                               for p in SERVING_DIR.rglob("*.py"))
        assert "record_census" not in serving_srcs

    def test_sweep_sees_the_tier_path(self):
        """ISSUE 16 satellite: the KV-tier spill/readmit/transfer call
        sites live inside the swept tree and stay guard-disciplined.
        The trie has no driver-installed tracer of its own, so the
        engine's ``_co()`` is the ONE chokepoint that hands it the
        observatory (``pc.cost`` sync) — and every ``record_tier``
        site reads a None-guarded local, never ``self.cost`` raw. The
        transfer programs ride the compile-once lru-cache registry
        (``kv_cache.tier_compilations``), so spilling a block can
        never add a jit key a future refactor would miss."""
        pcs = (SERVING_DIR / "prefix_cache.py").read_text()
        # spill (d2h) and readmit (h2d) both record through the
        # guarded local; no raw self.cost touch anywhere in the trie
        assert "co = self.cost" in pcs
        assert "self.cost.record_tier" not in pcs
        assert len(re.findall(r"co\.record_tier\(", pcs)) >= 2
        flt = (SERVING_DIR / "fleet" / "fleet.py").read_text()
        assert "self.cost.record_tier" not in flt
        assert re.search(r"co\.record_tier\(\s*\"peer\"", flt)
        # the engine's _co() guard is where the trie gets (and loses)
        # its observatory — one attribute sync, same discipline as the
        # handout guards
        eng = (SERVING_DIR / "engine.py").read_text()
        co_fn = eng.split("def _co(")[1].split("\n    def ")[0]
        assert "prefix_cache" in co_fn and "pc.cost" in co_fn
        # compile-once transfer pair: runtime-scalar block ids through
        # the registered lru-cached programs, counted by the accessor
        kvc = (SERVING_DIR / "kv_cache.py").read_text()
        for name in ("_tier_fetch", "_tier_inject", "tier_compilations"):
            assert f"def {name}(" in kvc, name
        assert "_TIER_PROGRAMS" in kvc
        bm = (SERVING_DIR / "block_manager.py").read_text()
        assert "_tier_fetch" in bm and "_tier_inject" in bm

    def test_sweep_covers_the_fleet_package(self):
        """ISSUE 12 satellite: the rglob sweep must keep covering
        ``serving/fleet/`` — the fleet's router-decision/failover/
        migration instants ride the same one-attribute ``_tr()``
        discipline as the engine's sites, and a future re-layout that
        moved the fleet out of ``serving/`` would silently shrink the
        sweep."""
        swept = {p.name for p in SERVING_DIR.rglob("*.py")}
        assert {"fleet.py", "router.py", "replica.py"} <= swept
        # and the fleet actually contributes guarded sites: the fleet
        # module's _tr() pattern must appear at least once
        fleet_src = (SERVING_DIR / "fleet" / "fleet.py").read_text()
        assert GUARD_RE.search(fleet_src) is not None

    def test_sweep_sees_the_policy_paths(self):
        """ISSUE 18 satellite: the multi-tenant policy package lives
        inside the swept tree and its decision sites stay
        guard-disciplined. The scheduler's admission decisions record
        through the same nullable ``_tr()`` idiom as the engine (the
        engine syncs the alias at the top of every step, BEFORE
        ``_policy_preempt`` runs, so preemption and headroom instants
        ride the step's already-guarded tracer), and the engine's
        SLO-preemption site reads the guarded local — a refactor that
        moved the policy out of ``serving/`` or grew a raw
        ``self.tracer.`` touch would silently shed the ≤1%-disabled-
        overhead property on the hottest new decision path."""
        swept = {p.name for p in SERVING_DIR.rglob("*.py")}
        assert {"classes.py", "admission.py", "victim.py"} <= swept
        adm = (SERVING_DIR / "policy" / "admission.py").read_text()
        body = adm.split("def admissions(")[1].split("\n    def ")[0]
        assert "tr = self._tr()" in body
        assert "self.tracer." not in body
        eng = (SERVING_DIR / "engine.py").read_text()
        pp = eng.split("def _policy_preempt(")[1].split("\n    def ")[0]
        assert "tr = self._tr()" in pp
        assert "self.tracer." not in pp
        # the step syncs the scheduler's alias before consulting policy
        assert "self.scheduler.tracer = tr" in eng
        assert eng.index("self.scheduler.tracer = tr") < \
            eng.index("self._policy_preempt()")


# ---------------------------------------------------- profiler CLI (json)
class TestProfilerCLIChrome:
    @pytest.fixture(scope="class")
    def trace_file(self, model, tmp_path_factory):
        tr = SpanTracer().enable()
        eng = ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
            decode_chunk=1, jit_cache={})
        eng.tracer = tr
        eng.cost = CostObservatory()
        eng.generate(_reqs(2, max_new=4))
        p = tmp_path_factory.mktemp("chrome") / "trace.json"
        p.write_text(json.dumps(tr.export()))
        return str(p)

    def _run(self, argv):
        from paddle_tpu.profiler.__main__ import main
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(argv)
        return rc, buf.getvalue()

    def test_text_table_per_lane_self_time(self, trace_file):
        rc, out = self._run([trace_file, "--top", "6"])
        assert rc == 0
        assert "self_ms" in out and "engine:" in out
        assert "counter samples" in out

    def test_json_and_top_honored(self, trace_file):
        rc, out = self._run([trace_file, "--json", "--top", "3"])
        assert rc == 0
        doc = json.loads(out)
        assert 0 < len(doc["rows"]) <= 3
        for r in doc["rows"]:
            assert {"lane", "name", "count", "total_ms",
                    "self_ms"} <= set(r)
        # self time <= total time, always
        assert all(r["self_ms"] <= r["total_ms"] + 1e-6
                   for r in doc["rows"])

    def test_unparseable_exits_one(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("not json at all")
        rc, out = self._run([str(bad)])
        assert rc == 1 and "unparseable" in out
        noevents = tmp_path / "noevents.json"
        noevents.write_text(json.dumps({"foo": 1}))
        rc, out = self._run([str(noevents)])
        assert rc == 1
        rc, out = self._run([str(noevents), "--json"])
        assert rc == 1 and "error" in json.loads(out)
