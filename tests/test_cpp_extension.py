"""Custom-op extension tests (reference:
``test/custom_op/test_custom_relu_op_setup.py`` † pattern — build an
out-of-tree op, check forward against a closed form and the registered
backward against the analytic gradient)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension

_SRC = r"""
#include <cstdint>
#include <cmath>
extern "C" void swish2(int n_in, const float** ins, const int64_t* sizes,
                       float* out, int64_t out_size) {
  const float* x = ins[0];
  for (int64_t i = 0; i < out_size; ++i)
    out[i] = x[i] / (1.0f + std::exp(-x[i]));
}
extern "C" void swish2_bwd(int n_in, const float** ins, const int64_t* sizes,
                           float* out, int64_t out_size) {
  const float* x = ins[0];
  const float* g = ins[1];
  for (int64_t i = 0; i < out_size; ++i) {
    float s = 1.0f / (1.0f + std::exp(-x[i]));
    out[i] = g[i] * (s + x[i] * s * (1.0f - s));
  }
}
extern "C" void wsum(int n_in, const float** ins, const int64_t* sizes,
                     float* out, int64_t out_size) {
  // out = a + 2*b : exercises multi-input plumbing
  for (int64_t i = 0; i < out_size; ++i)
    out[i] = ins[0][i] + 2.0f * ins[1][i];
}
"""


@pytest.fixture(scope="module")
def lib(tmp_path_factory):
    d = tmp_path_factory.mktemp("ext")
    src = os.path.join(str(d), "ops.cpp")
    with open(src, "w") as f:
        f.write(_SRC)
    return cpp_extension.load("t_ops", [src], build_directory=str(d))


class TestCppExtension:
    def test_forward_matches_closed_form(self, lib):
        swish = lib.def_op("swish2")
        x = np.array([-2.0, -0.5, 0.0, 1.5], np.float32)
        out = np.asarray(swish(paddle.to_tensor(x)).value)
        np.testing.assert_allclose(out, x / (1 + np.exp(-x)), rtol=1e-6)

    def test_registered_backward(self, lib):
        swish = lib.def_op("swish2", backward_symbol="swish2_bwd")
        xv = np.array([-1.0, 0.0, 2.0], np.float32)
        x = paddle.to_tensor(xv)
        x.stop_gradient = False
        loss = paddle.sum(swish(x))
        loss.backward()
        s = 1 / (1 + np.exp(-xv))
        np.testing.assert_allclose(np.asarray(x.grad),
                                   s + xv * s * (1 - s), rtol=1e-5)

    def test_multi_input_and_jit(self, lib):
        wsum = lib.def_op("wsum")
        a = np.arange(4, dtype=np.float32)
        b = np.ones(4, np.float32)
        from paddle_tpu.jit import to_static
        f = to_static(lambda ta, tb: wsum(ta, tb))
        out = np.asarray(f(paddle.to_tensor(a), paddle.to_tensor(b)).value)
        np.testing.assert_allclose(out, a + 2 * b)

    def test_no_backward_is_nondifferentiable(self, lib):
        swish = lib.def_op("swish2")  # no backward_symbol
        x = paddle.to_tensor(np.ones(3, np.float32))
        x.stop_gradient = False
        y = swish(x)
        # op registered non-differentiable: output carries no grad node
        assert y.stop_gradient

    def test_rebuild_cache(self, lib, tmp_path):
        src = tmp_path / "ops2.cpp"
        src.write_text(_SRC)
        l1 = cpp_extension.load("t2", [str(src)],
                                build_directory=str(tmp_path))
        l2 = cpp_extension.load("t2", [str(src)],
                                build_directory=str(tmp_path))
        assert l1.path == l2.path  # content hash: no rebuild
        src.write_text(_SRC + "\n// changed\n")
        l3 = cpp_extension.load("t2", [str(src)],
                                build_directory=str(tmp_path))
        assert l3.path != l1.path

    def test_cuda_extension_guides_to_pallas(self):
        with pytest.raises(RuntimeError, match="Pallas"):
            cpp_extension.CUDAExtension(sources=["x.cu"])
