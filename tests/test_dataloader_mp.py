"""Multiprocess DataLoader tests (VERDICT r2 item 9 — loader was
thread-pool only). Reference: ``python/paddle/io/dataloader/worker.py`` †:
spawn workers, order preservation, exception propagation, crash detection.
"""
import numpy as np
import pytest

from paddle_tpu.io import DataLoader

from _dl_helpers import (CrashingDataset, RaisingDataset, RangeSquareDataset,
                         WorkerIdDataset, _ring_producer)


class TestMultiprocessDataLoader:
    def test_order_and_values(self):
        ds = RangeSquareDataset(32)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False, worker_mode="process")
        batches = [b.numpy() if hasattr(b, "numpy") else np.asarray(b)
                   for b in dl]
        assert len(batches) == 8
        flat = np.concatenate(batches)
        np.testing.assert_allclose(
            flat, np.stack([[i, i * i] for i in range(32)]).astype(np.float32))

    @pytest.mark.slow  # each mp-worker spawn costs ~14 s on this image;
    # test_order_and_values is the default-run representative
    def test_worker_exception_propagates(self):
        ds = RaisingDataset(16, bad=5)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False, worker_mode="process")
        with pytest.raises(RuntimeError, match="bad sample 5"):
            list(dl)

    @pytest.mark.slow
    def test_worker_crash_detected(self):
        """A worker hard-exiting (os._exit) must surface as a RuntimeError,
        not a hang."""
        ds = CrashingDataset(16, poison=6)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False,
                        worker_mode="process", timeout=10)
        with pytest.raises(RuntimeError,
                           match="exited unexpectedly|timed out"):
            list(dl)

    @pytest.mark.slow
    def test_get_worker_info_in_workers(self):
        dl = DataLoader(WorkerIdDataset(), batch_size=4, num_workers=2,
                        shuffle=False, worker_mode="process")
        rows = np.concatenate([b.numpy() for b in dl])
        # every sample served by a real worker (id >= 0), both workers used
        assert (rows[:, 1] >= 0).all()

    def test_thread_workers_still_available(self):
        ds = RangeSquareDataset(16)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False)
        flat = np.concatenate([b.numpy() for b in dl])
        np.testing.assert_allclose(
            flat, np.stack([[i, i * i] for i in range(16)]).astype(np.float32))

    def test_shared_memory_ring_transport(self):
        """Results travel via the native shm ring when available; values and
        order must be identical to the queue path."""
        from paddle_tpu.csrc import available
        if not available():
            pytest.skip("no native toolchain")
        ds = RangeSquareDataset(32)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False,
                        worker_mode="process", use_shared_memory=True)
        flat = np.concatenate([b.numpy() for b in dl])
        np.testing.assert_allclose(
            flat, np.stack([[i, i * i] for i in range(32)]).astype(np.float32))

    @pytest.mark.slow
    def test_queue_fallback_when_shm_disabled(self):
        ds = RangeSquareDataset(16)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False,
                        worker_mode="process", use_shared_memory=False)
        flat = np.concatenate([b.numpy() for b in dl])
        np.testing.assert_allclose(
            flat, np.stack([[i, i * i] for i in range(16)]).astype(np.float32))


class TestShmRing:
    """Direct tests of the native SPSC ring (paddle_tpu/csrc/shm_ring.cpp)."""

    def test_roundtrip_and_wraparound(self):
        from paddle_tpu.csrc import ShmRing, available
        if not available():
            pytest.skip("no native toolchain")
        r = ShmRing.create("/pt_ring_t1", 1 << 16)
        w = ShmRing.open("/pt_ring_t1")
        try:
            for i in range(64):  # total bytes >> capacity: exercises wrap
                w.push(bytes([i % 256]) * 2900)
                assert r.pop(2000) == bytes([i % 256]) * 2900
        finally:
            w.close(unlink=False)
            r.close(unlink=True)

    def test_eof_and_timeout(self):
        from paddle_tpu.csrc import ShmRing, available
        if not available():
            pytest.skip("no native toolchain")
        r = ShmRing.create("/pt_ring_t2", 1 << 14)
        w = ShmRing.open("/pt_ring_t2")
        try:
            assert r.pop(timeout_ms=50) is None  # empty -> timeout
            w.push(b"last")
            w.mark_closed()
            assert r.pop(1000) == b"last"
            with pytest.raises(EOFError):
                r.pop(1000)
        finally:
            w.close(unlink=False)
            r.close(unlink=True)

    def test_oversize_message_rejected(self):
        from paddle_tpu.csrc import ShmRing, available
        if not available():
            pytest.skip("no native toolchain")
        r = ShmRing.create("/pt_ring_t3", 1 << 12)
        try:
            with pytest.raises(ValueError):
                r.push(b"x" * (1 << 13))
        finally:
            r.close(unlink=True)

    @pytest.mark.slow  # ~100s spawn+compile; in-process ring transport
    # tests above stay as the default-run shm-ring representatives
    def test_cross_process(self):
        """Producer in a real spawned process."""
        import multiprocessing as mp
        from paddle_tpu.csrc import ShmRing, available
        if not available():
            pytest.skip("no native toolchain")
        r = ShmRing.create("/pt_ring_t4", 1 << 16)
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=_ring_producer, args=("/pt_ring_t4",))
        p.start()
        try:
            got = [r.pop(10000) for _ in range(10)]
            assert got == [bytes([i]) * 1000 for i in range(10)]
        finally:
            p.join(timeout=10)
            r.close(unlink=True)
