"""Multiprocess DataLoader tests (VERDICT r2 item 9 — loader was
thread-pool only). Reference: ``python/paddle/io/dataloader/worker.py`` †:
spawn workers, order preservation, exception propagation, crash detection.
"""
import numpy as np
import pytest

from paddle_tpu.io import DataLoader

from _dl_helpers import (CrashingDataset, RaisingDataset, RangeSquareDataset,
                         WorkerIdDataset)


class TestMultiprocessDataLoader:
    def test_order_and_values(self):
        ds = RangeSquareDataset(32)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False, worker_mode="process")
        batches = [b.numpy() if hasattr(b, "numpy") else np.asarray(b)
                   for b in dl]
        assert len(batches) == 8
        flat = np.concatenate(batches)
        np.testing.assert_allclose(
            flat, np.stack([[i, i * i] for i in range(32)]).astype(np.float32))

    def test_worker_exception_propagates(self):
        ds = RaisingDataset(16, bad=5)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False, worker_mode="process")
        with pytest.raises(RuntimeError, match="bad sample 5"):
            list(dl)

    def test_worker_crash_detected(self):
        """A worker hard-exiting (os._exit) must surface as a RuntimeError,
        not a hang."""
        ds = CrashingDataset(16, poison=6)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False,
                        worker_mode="process", timeout=10)
        with pytest.raises(RuntimeError,
                           match="exited unexpectedly|timed out"):
            list(dl)

    def test_get_worker_info_in_workers(self):
        dl = DataLoader(WorkerIdDataset(), batch_size=4, num_workers=2,
                        shuffle=False, worker_mode="process")
        rows = np.concatenate([b.numpy() for b in dl])
        # every sample served by a real worker (id >= 0), both workers used
        assert (rows[:, 1] >= 0).all()

    def test_thread_workers_still_available(self):
        ds = RangeSquareDataset(16)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False)
        flat = np.concatenate([b.numpy() for b in dl])
        np.testing.assert_allclose(
            flat, np.stack([[i, i * i] for i in range(16)]).astype(np.float32))
