"""Decode-path validation (VERDICT r2 item 7 — the KV-cache decode path had
no correctness test). Prefill-then-decode must equal the full forward for
``FusedMultiTransformer`` (reference ``fused_multi_transformer_op.cu`` †,
SURVEY §3.5), MHA and GQA both, plus the decode-throughput meter.
"""
import numpy as np
import jax
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import FusedMultiTransformer
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.profiler.metrics import DecodeMeter


def _model(E=32, H=4, FF=64, L=3, kv=None):
    paddle.seed(77)
    return FusedMultiTransformer(
        embed_dim=E, num_heads=H, dim_feedforward=FF, num_layers=L,
        kv_num_heads=kv)


def _run_full(m, x_np):
    return m(paddle.to_tensor(x_np)).numpy()


def _run_prefill_decode(m, x_np, prefill_len, s_max=None):
    """Prefill `prefill_len` tokens, then decode the rest one at a time."""
    B, S, E = x_np.shape
    Hkv, D = m.kv_num_heads, m.head_dim
    L = m.num_layers
    s_max = s_max or S
    cache = np.zeros((L, 2, B, s_max, Hkv, D), np.float32)
    outs = []
    out, cache = m(paddle.to_tensor(x_np[:, :prefill_len]),
                   caches=paddle.to_tensor(cache), time_step=0)
    outs.append(out.numpy())
    for t in range(prefill_len, S):
        out, cache = m(paddle.to_tensor(x_np[:, t:t + 1]),
                       caches=cache, time_step=t)
        outs.append(out.numpy())
    return np.concatenate(outs, axis=1)


class TestDecodeParity:
    def setup_method(self, _m):
        mesh_mod._STATE["mesh"] = None

    @pytest.mark.slow  # the GQA variant above is the stricter default rep
    def test_prefill_then_decode_matches_full_mha(self):
        m = _model()
        x = np.random.RandomState(0).randn(2, 10, 32).astype(np.float32)
        full = _run_full(m, x)
        inc = _run_prefill_decode(m, x, prefill_len=6)
        np.testing.assert_allclose(inc, full, rtol=2e-4, atol=2e-5)

    def test_prefill_then_decode_matches_full_gqa(self):
        m = _model(H=8, kv=2)
        x = np.random.RandomState(1).randn(2, 8, 32).astype(np.float32)
        full = _run_full(m, x)
        inc = _run_prefill_decode(m, x, prefill_len=4)
        np.testing.assert_allclose(inc, full, rtol=2e-4, atol=2e-5)

    @pytest.mark.slow  # step-wise parity; covered daily by the serving
    # engine equivalence tests at a fraction of the wall time
    def test_decode_all_tokens_one_by_one(self):
        """Pure decode from t=0 (prefill of 1)."""
        m = _model(L=2)
        x = np.random.RandomState(2).randn(1, 6, 32).astype(np.float32)
        full = _run_full(m, x)
        inc = _run_prefill_decode(m, x, prefill_len=1)
        np.testing.assert_allclose(inc, full, rtol=2e-4, atol=2e-5)

    @pytest.mark.slow  # the GQA prefill+decode rep above covers the same
    # kernel; every serving test also runs max_seq_len > prompt+budget,
    # so the padded-tail property has daily default-run coverage
    def test_cache_longer_than_sequence(self):
        """s_max > S: the padded cache tail must not leak into attention."""
        m = _model(L=2)
        x = np.random.RandomState(3).randn(1, 6, 32).astype(np.float32)
        full = _run_full(m, x)
        inc = _run_prefill_decode(m, x, prefill_len=3, s_max=16)
        np.testing.assert_allclose(inc, full, rtol=2e-4, atol=2e-5)

    def test_gqa_cache_shape_is_kv_heads(self):
        """The cache stores Hkv (not H) heads — the GQA memory win."""
        m = _model(H=8, kv=2)
        assert m.kv_num_heads == 2
        D = m.head_dim
        x = np.random.RandomState(4).randn(1, 4, 32).astype(np.float32)
        cache = np.zeros((3, 2, 1, 8, 2, D), np.float32)
        out, new_cache = m(paddle.to_tensor(x),
                           caches=paddle.to_tensor(cache), time_step=0)
        assert tuple(new_cache.shape) == (3, 2, 1, 8, 2, D)


class TestDecodeMeter:
    def test_decode_meter_reports(self):
        import time
        meter = DecodeMeter(n_params=1000, n_chips=1)
        meter.start()
        time.sleep(0.01)
        meter.end_prefill(64)
        for _ in range(3):
            meter.start()
            time.sleep(0.002)
            meter.end_decode(1)
        rep = meter.report()
        assert rep["prefill_tokens_per_sec"] > 0
        assert rep["decode_tokens_per_sec"] > 0
        assert rep["decode_ms_per_token"] > 0
        assert "decode_mbu" in rep
