"""paddle.distribution tests (SURVEY §2.2 row 26 — package was absent).
Oracles: closed-form moments/log-probs and sample-statistics convergence;
KL registry checked against analytic formulas.
Reference surface: ``python/paddle/distribution/`` †.
"""
import math

import numpy as np
import pytest
from scipy import stats

import paddle_tpu as paddle
from paddle_tpu.distribution import (Bernoulli, Beta, Categorical, Dirichlet,
                                     Exponential, Gamma, Geometric, Gumbel,
                                     Laplace, LogNormal, Multinomial, Normal,
                                     Poisson, StudentT, Uniform,
                                     kl_divergence)


def setup_module(m):
    paddle.seed(1234)


class TestMoments:
    def test_normal(self):
        d = Normal(2.0, 3.0)
        assert np.isclose(float(d.mean.numpy()), 2.0)
        assert np.isclose(float(d.variance.numpy()), 9.0)
        s = d.sample((20000,)).numpy()
        assert abs(s.mean() - 2.0) < 0.1
        assert abs(s.std() - 3.0) < 0.1

    def test_uniform(self):
        d = Uniform(-1.0, 3.0)
        assert np.isclose(float(d.mean.numpy()), 1.0)
        s = d.sample((20000,)).numpy()
        assert s.min() >= -1.0 and s.max() < 3.0
        assert abs(s.mean() - 1.0) < 0.1

    def test_gamma_exponential_laplace_gumbel(self):
        g = Gamma(3.0, 2.0)
        assert np.isclose(float(g.mean.numpy()), 1.5)
        e = Exponential(4.0)
        assert np.isclose(float(e.mean.numpy()), 0.25)
        l = Laplace(1.0, 2.0)
        assert np.isclose(float(l.variance.numpy()), 8.0)
        gu = Gumbel(0.0, 1.0)
        assert np.isclose(float(gu.mean.numpy()), 0.5772156649, atol=1e-6)

    def test_discrete(self):
        b = Bernoulli(0.3)
        assert np.isclose(float(b.mean.numpy()), 0.3)
        p = Poisson(5.0)
        assert np.isclose(float(p.variance.numpy()), 5.0)
        geo = Geometric(0.25)
        assert np.isclose(float(geo.mean.numpy()), 3.0)

    def test_multinomial_counts(self):
        m = Multinomial(10, [0.2, 0.3, 0.5])
        s = m.sample((500,)).numpy()
        assert s.shape == (500, 3)
        np.testing.assert_allclose(s.sum(-1), 10.0)
        np.testing.assert_allclose(s.mean(0), [2.0, 3.0, 5.0], atol=0.4)


class TestLogProb:
    def test_normal_matches_scipy(self):
        d = Normal(1.0, 2.0)
        x = np.linspace(-3, 5, 7).astype(np.float32)
        np.testing.assert_allclose(d.log_prob(paddle.to_tensor(x)).numpy(),
                                   stats.norm.logpdf(x, 1.0, 2.0), rtol=1e-5, atol=1e-5)

    def test_gamma_matches_scipy(self):
        d = Gamma(2.5, 1.5)
        x = np.array([0.3, 1.0, 2.7], np.float32)
        np.testing.assert_allclose(
            d.log_prob(paddle.to_tensor(x)).numpy(),
            stats.gamma.logpdf(x, 2.5, scale=1 / 1.5), rtol=1e-5, atol=1e-5)

    def test_beta_matches_scipy(self):
        d = Beta(2.0, 3.0)
        x = np.array([0.1, 0.5, 0.9], np.float32)
        np.testing.assert_allclose(d.log_prob(paddle.to_tensor(x)).numpy(),
                                   stats.beta.logpdf(x, 2.0, 3.0), rtol=1e-5, atol=1e-5)

    def test_poisson_and_geometric(self):
        d = Poisson(4.0)
        k = np.array([0.0, 3.0, 7.0], np.float32)
        np.testing.assert_allclose(d.log_prob(paddle.to_tensor(k)).numpy(),
                                   stats.poisson.logpmf(k, 4.0), rtol=1e-5, atol=1e-5)
        g = Geometric(0.3)
        np.testing.assert_allclose(
            g.log_prob(paddle.to_tensor(k)).numpy(),
            stats.geom.logpmf(k + 1, 0.3), rtol=1e-5, atol=1e-5)  # scipy counts trials

    def test_studentt_matches_scipy(self):
        d = StudentT(5.0, 1.0, 2.0)
        x = np.array([-1.0, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(paddle.to_tensor(x)).numpy(),
            stats.t.logpdf(x, 5.0, loc=1.0, scale=2.0), rtol=1e-5, atol=1e-5)

    def test_lognormal_matches_scipy(self):
        d = LogNormal(0.5, 0.8)
        x = np.array([0.5, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(paddle.to_tensor(x)).numpy(),
            stats.lognorm.logpdf(x, 0.8, scale=math.exp(0.5)), rtol=1e-5, atol=1e-5)

    def test_categorical(self):
        d = Categorical(logits=np.log(np.array([0.2, 0.3, 0.5], np.float32)))
        lp = d.log_prob(paddle.to_tensor(np.array([0, 2]))).numpy()
        np.testing.assert_allclose(lp, np.log([0.2, 0.5]), rtol=1e-5, atol=1e-5)
        ent = float(d.entropy().numpy())
        assert np.isclose(ent, -(0.2 * np.log(0.2) + 0.3 * np.log(0.3)
                                 + 0.5 * np.log(0.5)), rtol=1e-5, atol=1e-5)


class TestKL:
    def test_normal_normal_analytic(self):
        p, q = Normal(0.0, 1.0), Normal(1.0, 2.0)
        kl = float(kl_divergence(p, q).numpy())
        expect = (math.log(2.0) + (1 + 1) / (2 * 4) - 0.5)
        assert np.isclose(kl, expect, rtol=1e-5, atol=1e-5)

    def test_categorical_categorical(self):
        p = Categorical(probs=[0.5, 0.5])
        q = Categorical(probs=[0.9, 0.1])
        kl = float(kl_divergence(p, q).numpy())
        expect = 0.5 * math.log(0.5 / 0.9) + 0.5 * math.log(0.5 / 0.1)
        assert np.isclose(kl, expect, rtol=1e-5, atol=1e-5)

    def test_mc_fallback(self):
        """Unregistered pair falls back to Monte-Carlo (sanity: KL >= 0,
        roughly right for Normal-vs-Laplace)."""
        p = Normal(0.0, 1.0)
        q = Gumbel(0.0, 1.0)
        kl = float(kl_divergence(p, q).numpy())
        assert kl > 0

    def test_gamma_gamma_vs_mc(self):
        p, q = Gamma(2.0, 1.0), Gamma(3.0, 2.0)
        analytic = float(kl_divergence(p, q).numpy())
        x = p.sample((40000,)).numpy()
        mc = np.mean(stats.gamma.logpdf(x, 2.0, scale=1.0) -
                     stats.gamma.logpdf(x, 3.0, scale=0.5))
        assert np.isclose(analytic, mc, rtol=0.1)


class TestGradients:
    def test_rsample_reparam_grad(self):
        """rsample is differentiable w.r.t. parameters (the point of the
        reparameterization design)."""
        import jax
        import jax.numpy as jnp

        def f(mu):
            paddle.seed(7)
            d = Normal(mu, 1.0)
            return jnp.mean(d.rsample((64,)).value ** 2)

        g = jax.grad(f)(2.0)
        # d/dmu E[(mu+eps)^2] = 2mu
        assert abs(float(g) - 4.0) < 0.5
