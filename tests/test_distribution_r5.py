"""Round-5 distribution completions (reference:
``python/paddle/distribution/`` †): Cauchy/Chi2/Binomial/
ContinuousBernoulli/MultivariateNormal/LKJCholesky, Independent +
TransformedDistribution wrappers, and the Transform bijector family —
all pinned against torch.distributions oracles."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distribution as D

torch = pytest.importorskip("torch")


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestNewDistributions:
    def test_cauchy_matches_torch(self):
        c = D.Cauchy(_t(np.float32(1.0)), _t(np.float32(2.0)))
        tc = torch.distributions.Cauchy(1.0, 2.0)
        v = np.linspace(-5, 5, 7, dtype=np.float32)
        np.testing.assert_allclose(c.log_prob(_t(v)).numpy(),
                                   tc.log_prob(torch.tensor(v)).numpy(),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(c.entropy()), float(tc.entropy()),
                                   rtol=1e-5)
        np.testing.assert_allclose(c.cdf(_t(v)).numpy(),
                                   tc.cdf(torch.tensor(v)).numpy(),
                                   rtol=1e-5)

    def test_chi2_matches_torch(self):
        x2 = D.Chi2(_t(np.float32(3.0)))
        v = np.asarray([0.5, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(
            x2.log_prob(_t(v)).numpy(),
            torch.distributions.Chi2(3.0).log_prob(torch.tensor(v)).numpy(),
            rtol=1e-4)

    def test_binomial_matches_torch(self):
        paddle.seed(1)
        b = D.Binomial(_t(np.float32(10)), _t(np.float32(0.3)))
        k = np.asarray([0.0, 3.0, 10.0], np.float32)
        np.testing.assert_allclose(
            b.log_prob(_t(k)).numpy(),
            torch.distributions.Binomial(10, 0.3).log_prob(
                torch.tensor(k)).numpy(), rtol=1e-5)
        s = b.sample((4000,)).numpy()
        assert abs(s.mean() - 3.0) < 0.15

    def test_continuous_bernoulli_matches_torch(self):
        x = np.asarray([0.1, 0.5, 0.9], np.float32)
        for p in (0.3, 0.5):  # incl. the Taylor-limit region
            cb = D.ContinuousBernoulli(_t(np.float32(p)))
            tcb = torch.distributions.ContinuousBernoulli(p)
            np.testing.assert_allclose(
                cb.log_prob(_t(x)).numpy(),
                tcb.log_prob(torch.tensor(x)).numpy(), rtol=1e-3)
        np.testing.assert_allclose(
            float(D.ContinuousBernoulli(_t(np.float32(0.3))).mean),
            float(torch.distributions.ContinuousBernoulli(0.3).mean),
            rtol=1e-4)

    def test_multivariate_normal_matches_torch(self):
        paddle.seed(2)
        rng = np.random.RandomState(0)
        A = rng.randn(3, 3).astype(np.float32)
        cov = (A @ A.T + 3 * np.eye(3)).astype(np.float32)
        loc = rng.randn(3).astype(np.float32)
        mv = D.MultivariateNormal(_t(loc), covariance_matrix=_t(cov))
        tmv = torch.distributions.MultivariateNormal(torch.tensor(loc),
                                                     torch.tensor(cov))
        pt = rng.randn(5, 3).astype(np.float32)
        np.testing.assert_allclose(mv.log_prob(_t(pt)).numpy(),
                                   tmv.log_prob(torch.tensor(pt)).numpy(),
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(float(mv.entropy()), float(tmv.entropy()),
                                   rtol=1e-4)
        s = mv.sample((8000,)).numpy()
        np.testing.assert_allclose(s.mean(0), loc, atol=0.15)
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.4)

    def test_lkj_cholesky(self):
        paddle.seed(3)
        lkj = D.LKJCholesky(3, _t(np.float32(1.5)))
        L = lkj.sample((500,)).numpy()
        corr = L @ np.swapaxes(L, -1, -2)
        np.testing.assert_allclose(
            np.diagonal(corr, axis1=-2, axis2=-1), 1.0, atol=1e-4)
        tlkj = torch.distributions.LKJCholesky(3, 1.5)
        L1 = np.asarray(tlkj.sample((1,))[0], np.float32)
        np.testing.assert_allclose(float(lkj.log_prob(_t(L1))),
                                   float(tlkj.log_prob(torch.tensor(L1))),
                                   rtol=1e-4)


class TestNewKLs:
    def test_cauchy_and_mvn_kl_match_torch(self):
        rng = np.random.RandomState(0)
        p = D.Cauchy(_t(np.float32(0.0)), _t(np.float32(1.0)))
        q = D.Cauchy(_t(np.float32(2.0)), _t(np.float32(3.0)))
        np.testing.assert_allclose(
            float(D.kl_divergence(p, q)),
            float(torch.distributions.kl_divergence(
                torch.distributions.Cauchy(0.0, 1.0),
                torch.distributions.Cauchy(2.0, 3.0))), rtol=1e-5)
        A = rng.randn(3, 3).astype(np.float32)
        c1 = (A @ A.T + 3 * np.eye(3)).astype(np.float32)
        B = rng.randn(3, 3).astype(np.float32)
        c2 = (B @ B.T + 3 * np.eye(3)).astype(np.float32)
        l1 = rng.randn(3).astype(np.float32)
        l2 = rng.randn(3).astype(np.float32)
        got = float(D.kl_divergence(
            D.MultivariateNormal(_t(l1), covariance_matrix=_t(c1)),
            D.MultivariateNormal(_t(l2), covariance_matrix=_t(c2))))
        want = float(torch.distributions.kl_divergence(
            torch.distributions.MultivariateNormal(torch.tensor(l1),
                                                   torch.tensor(c1)),
            torch.distributions.MultivariateNormal(torch.tensor(l2),
                                                   torch.tensor(c2))))
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_mvn_kl_batched_posterior_vs_unbatched_prior(self):
        """r5 review: the standard VI shape — batched posterior against
        an unbatched prior — must broadcast, returning a [B] KL."""
        rng = np.random.RandomState(0)
        locs = rng.randn(4, 3).astype(np.float32)
        A = rng.randn(3, 3).astype(np.float32)
        cov = (A @ A.T + 3 * np.eye(3)).astype(np.float32)
        kl = D.kl_divergence(
            D.MultivariateNormal(_t(locs),
                                 covariance_matrix=_t(np.tile(cov,
                                                              (4, 1, 1)))),
            D.MultivariateNormal(_t(np.zeros(3, np.float32)),
                                 covariance_matrix=_t(
                                     np.eye(3, dtype=np.float32)))).numpy()
        assert kl.shape == (4,)
        want = torch.distributions.kl_divergence(
            torch.distributions.MultivariateNormal(
                torch.tensor(locs), torch.tensor(np.tile(cov, (4, 1, 1)))),
            torch.distributions.MultivariateNormal(
                torch.zeros(3), torch.eye(3))).numpy()
        np.testing.assert_allclose(kl, want, rtol=1e-4)


class TestWrappers:
    def test_independent_sums_event_dims(self):
        rng = np.random.RandomState(1)
        base = D.Normal(_t(np.zeros((4, 3), np.float32)),
                        _t(np.ones((4, 3), np.float32)))
        ind = D.Independent(base, 1)
        tind = torch.distributions.Independent(
            torch.distributions.Normal(torch.zeros(4, 3),
                                       torch.ones(4, 3)), 1)
        v = rng.randn(4, 3).astype(np.float32)
        np.testing.assert_allclose(ind.log_prob(_t(v)).numpy(),
                                   tind.log_prob(torch.tensor(v)).numpy(),
                                   rtol=1e-5)
        assert ind.event_shape == [3] and ind.batch_shape == [4]

    def test_transformed_vector_event_base(self):
        """r5 review: an elementwise transform over a vector-event base
        must keep the vector event (log-det sums over event dims)."""
        rng = np.random.RandomState(0)
        cov = np.eye(2, dtype=np.float32) * 0.5
        td = D.TransformedDistribution(
            D.MultivariateNormal(_t(np.zeros(2, np.float32)),
                                 covariance_matrix=_t(cov)),
            [D.ExpTransform()])
        ttd = torch.distributions.TransformedDistribution(
            torch.distributions.MultivariateNormal(torch.zeros(2),
                                                   torch.tensor(cov)),
            [torch.distributions.transforms.ExpTransform()])
        y = np.abs(rng.randn(5, 2).astype(np.float32)) + 0.2
        np.testing.assert_allclose(td.log_prob(_t(y)).numpy(),
                                   ttd.log_prob(torch.tensor(y)).numpy(),
                                   rtol=1e-4, atol=1e-5)
        assert td.event_shape == [2]

    def test_binomial_degenerate_probs_finite(self):
        b = D.Binomial(_t(np.float32(10)), _t(np.float32(1.0)))
        assert np.isfinite(float(b.log_prob(_t(np.float32(10)))))
        b0 = D.Binomial(_t(np.float32(10)), _t(np.float32(0.0)))
        assert np.isfinite(float(b0.log_prob(_t(np.float32(0)))))

    def test_lkj_sampler_marginal_matches_torch(self):
        """r5 review caught a wrong Beta concentration in the onion
        sampler; pin the (1,0) correlation marginal against torch's
        sampler (same construction => same histogram shape)."""
        paddle.seed(5)
        L = D.LKJCholesky(3, _t(np.float32(1.0))).sample((4000,)).numpy()
        corr = (L @ np.swapaxes(L, -1, -2))[:, 1, 0]
        hist, _ = np.histogram(corr, bins=4, range=(-1, 1))
        tL = torch.distributions.LKJCholesky(3, 1.0).sample((4000,))
        tcorr = (tL @ tL.transpose(-1, -2))[:, 1, 0].numpy()
        thist, _ = np.histogram(tcorr, bins=4, range=(-1, 1))
        np.testing.assert_allclose(hist, thist, rtol=0.12)

    def test_transformed_normal_exp_is_lognormal(self):
        td = D.TransformedDistribution(
            D.Normal(_t(np.float32(0.0)), _t(np.float32(1.0))),
            [D.ExpTransform()])
        tl = torch.distributions.LogNormal(0.0, 1.0)
        y = np.asarray([0.5, 1.0, 2.0], np.float32)
        np.testing.assert_allclose(td.log_prob(_t(y)).numpy(),
                                   tl.log_prob(torch.tensor(y)).numpy(),
                                   rtol=1e-5)
        paddle.seed(4)
        s = td.sample((4000,)).numpy()
        assert abs(np.log(s).mean()) < 0.1


class TestTransforms:
    @pytest.mark.parametrize("pair", [
        ("exp", lambda: (D.ExpTransform(),
                         torch.distributions.transforms.ExpTransform())),
        ("sigmoid", lambda: (D.SigmoidTransform(),
                             torch.distributions.transforms.SigmoidTransform())),
        ("tanh", lambda: (D.TanhTransform(),
                          torch.distributions.transforms.TanhTransform())),
        ("affine", lambda: (D.AffineTransform(_t(np.float32(1.0)),
                                              _t(np.float32(-2.0))),
                            torch.distributions.transforms.AffineTransform(
                                1.0, -2.0))),
        ("power", lambda: (D.PowerTransform(_t(np.float32(3.0))),
                           torch.distributions.transforms.PowerTransform(3.0))),
    ], ids=lambda p: p[0] if isinstance(p, tuple) else str(p))
    def test_elementwise_transforms_match_torch(self, pair):
        ours, theirs = pair[1]()
        x = np.asarray([0.3, 0.7, 1.3], np.float32)
        np.testing.assert_allclose(ours.forward(_t(x)).numpy(),
                                   theirs(torch.tensor(x)).numpy(),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            ours.forward_log_det_jacobian(_t(x)).numpy(),
            theirs.log_abs_det_jacobian(
                torch.tensor(x), theirs(torch.tensor(x))).numpy(),
            rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(ours.inverse(ours.forward(_t(x))).numpy(),
                                   x, rtol=1e-4, atol=1e-5)

    def test_stick_breaking_matches_torch(self):
        rng = np.random.RandomState(2)
        sb = D.StickBreakingTransform()
        tsb = torch.distributions.transforms.StickBreakingTransform()
        x = rng.randn(2, 4).astype(np.float32)
        np.testing.assert_allclose(sb.forward(_t(x)).numpy(),
                                   tsb(torch.tensor(x)).numpy(),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(
            sb.forward_log_det_jacobian(_t(x)).numpy(),
            tsb.log_abs_det_jacobian(torch.tensor(x),
                                     tsb(torch.tensor(x))).numpy(),
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(sb.inverse(sb.forward(_t(x))).numpy(), x,
                                   rtol=1e-3, atol=1e-4)
        assert sb.forward_shape((2, 4)) == (2, 5)
        assert sb.inverse_shape((2, 5)) == (2, 4)

    def test_chain_reshape_stack_and_guards(self):
        ch = D.ChainTransform([D.ExpTransform(),
                               D.AffineTransform(_t(np.float32(0.0)),
                                                 _t(np.float32(2.0)))])
        x = np.asarray([0.1, 0.5], np.float32)
        np.testing.assert_allclose(ch.forward(_t(x)).numpy(),
                                   2 * np.exp(x), rtol=1e-5)
        np.testing.assert_allclose(ch.inverse(ch.forward(_t(x))).numpy(), x,
                                   rtol=1e-5)
        rt = D.ReshapeTransform((4,), (2, 2))
        y = rt.forward(_t(np.arange(8, dtype=np.float32).reshape(2, 4)))
        assert y.shape == [2, 2, 2]
        st = D.StackTransform([D.ExpTransform(), D.TanhTransform()], axis=0)
        v = np.stack([x, x])
        out = st.forward(_t(v)).numpy()
        np.testing.assert_allclose(out[0], np.exp(x), rtol=1e-5)
        np.testing.assert_allclose(out[1], np.tanh(x), rtol=1e-5)
        with pytest.raises(NotImplementedError):
            D.AbsTransform().forward_log_det_jacobian(_t(x))
        with pytest.raises(NotImplementedError):
            D.SoftmaxTransform().forward_log_det_jacobian(_t(x))
