"""Eager-API holes from VERDICT r2 item 4: PyLayer (custom differentiable
ops), point-to-point send/recv/batch_isend_irecv, and the
FLAGS_check_nan_inf debug guard.

Reference surfaces: ``python/paddle/autograd/py_layer.py`` †,
``paddle/fluid/operators/collective/send_v2_op.cu`` †,
``paddle/fluid/framework/details/nan_inf_utils_detail`` †.
"""
import numpy as np
import jax
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.autograd import PyLayer
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import SGD
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.utils.flags import set_flags


class _Cube(PyLayer):
    @staticmethod
    def forward(ctx, x):
        ctx.save_for_backward(x)
        return x * x * x

    @staticmethod
    def backward(ctx, g):
        (x,) = ctx.saved_tensor()
        # deliberately NOT the analytic 3x^2 — proves the custom rule runs
        return g * 2.0 * x


class _ScaledAdd(PyLayer):
    @staticmethod
    def forward(ctx, x, y, alpha):
        ctx.save_for_backward(x, y)
        return x + alpha * y, x - y

    @staticmethod
    def backward(ctx, g_sum, g_diff):
        return g_sum + g_diff, g_sum - g_diff


class TestPyLayer:
    def test_custom_backward_eager(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        y = _Cube.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(y.numpy(), [1.0, 8.0])
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])  # custom 2x

    def test_custom_backward_under_jit(self):
        """A tape-only PyLayer would lose the custom grad under jax.grad;
        the custom_vjp design keeps it."""
        def f(u):
            return _Cube.apply(paddle.to_tensor(u)).value.sum()

        g = jax.jit(jax.grad(f))(np.array([3.0], np.float32))
        np.testing.assert_allclose(np.asarray(g), [6.0])

    def test_multi_input_output_and_static_args(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        y = paddle.to_tensor(np.array([3.0, 4.0], np.float32),
                             stop_gradient=False)
        s, d = _ScaledAdd.apply(x, y, 2.0)  # alpha is a non-tensor static
        np.testing.assert_allclose(s.numpy(), [7.0, 10.0])
        (s.sum() + d.sum()).backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])
        np.testing.assert_allclose(y.grad.numpy(), [0.0, 0.0])

    def test_in_layer_training(self):
        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, x):
                return _Cube.apply(self.lin(x)).sum()

        m = M()
        step = TrainStep(m, lambda out, _l: out,
                         SGD(learning_rate=0.01, parameters=m.parameters()))
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4)
                             .astype(np.float32))
        l0 = float(step.step((x,), (x,)).value)
        assert np.isfinite(l0)


class TestP2P:
    def setup_method(self, m):
        mesh_mod._STATE["mesh"] = None

    def test_send_recv_moves_shard(self):
        from paddle_tpu.distributed import recv, send
        n = len(jax.devices())
        buf = paddle.to_tensor(
            np.arange(n * 4, dtype=np.float32).reshape(n, 4))
        out = paddle.to_tensor(np.zeros((n, 4), np.float32))
        send(buf, dst=2)
        recv(out, src=0)
        got = out.numpy()
        np.testing.assert_allclose(got[2], buf.numpy()[0])  # dst got src's
        np.testing.assert_allclose(got[0], 0.0)  # others untouched

    def test_recv_without_send_raises(self):
        from paddle_tpu.distributed import recv
        n = len(jax.devices())
        out = paddle.to_tensor(np.zeros((n, 2), np.float32))
        with pytest.raises(RuntimeError, match="matching"):
            recv(out, src=1)

    def test_batch_isend_irecv_ring(self):
        """The SURVEY §5.7 ring primitive: every rank sends its shard to
        rank+1 — one fused ppermute."""
        from paddle_tpu.distributed import P2POp, batch_isend_irecv, irecv, isend
        n = len(jax.devices())
        buf = paddle.to_tensor(
            np.arange(n * 2, dtype=np.float32).reshape(n, 2))
        out = paddle.to_tensor(np.zeros((n, 2), np.float32))
        ops = []
        for r in range(n):
            ops.append(P2POp(isend, buf, peer=(r + 1) % n, rank=r))
            ops.append(P2POp(irecv, out, peer=(r - 1) % n, rank=r))
        tasks = batch_isend_irecv(ops)
        for t in tasks:
            t.wait()
        np.testing.assert_allclose(out.numpy(),
                                   np.roll(buf.numpy(), 1, axis=0))

    def test_batch_requires_rank(self):
        from paddle_tpu.distributed import P2POp, batch_isend_irecv, isend
        buf = paddle.to_tensor(np.zeros((8, 2), np.float32))
        with pytest.raises(ValueError, match="rank"):
            batch_isend_irecv([P2POp(isend, buf, peer=1)])


class TestNanGuard:
    def test_nan_in_loss_raises(self):
        set_flags({"FLAGS_check_nan_inf": True})
        try:
            m = nn.Linear(4, 4)

            class NaNLoss:
                def __call__(self, out, _l):
                    return (out.sum() - out.sum()) / (out.sum() - out.sum())

            step = TrainStep(m, NaNLoss(),
                             SGD(learning_rate=0.1,
                                 parameters=m.parameters()))
            x = paddle.to_tensor(np.ones((2, 4), np.float32))
            with pytest.raises(RuntimeError, match="non-finite"):
                step.step((x,), (x,))
        finally:
            set_flags({"FLAGS_check_nan_inf": False})

    def test_clean_step_does_not_raise(self):
        set_flags({"FLAGS_check_nan_inf": True})
        try:
            m = nn.Linear(4, 2)
            step = TrainStep(m, lambda out, _l: (out * out).mean(),
                             SGD(learning_rate=0.1,
                                 parameters=m.parameters()))
            x = paddle.to_tensor(np.ones((2, 4), np.float32))
            l0 = float(step.step((x,), (x,)).value)
            assert np.isfinite(l0)
        finally:
            set_flags({"FLAGS_check_nan_inf": False})

    def test_guard_off_by_default(self):
        m = nn.Linear(2, 2)
        step = TrainStep(m, lambda out, _l: out.sum() * np.float32("nan"),
                         SGD(learning_rate=0.1, parameters=m.parameters()))
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        loss = step.step((x,), (x,))  # must NOT raise
        assert np.isnan(float(loss.value))


class TestGlobalRngLocking:
    """ISSUE 19 satellite (ADVICE): ``manual_seed``/``set_state`` must
    hold the generator lock like ``next_key``/``get_state`` do — an
    unlocked reseed racing a split could publish a half-updated key (or
    split a stale one) and silently fork the deterministic stream."""

    def test_all_four_mutators_hold_the_lock(self):
        import inspect
        from paddle_tpu.core.random import _GlobalGenerator
        for name in ("manual_seed", "next_key", "get_state", "set_state"):
            src = inspect.getsource(getattr(_GlobalGenerator, name))
            assert "with self._lock" in src, name

    def test_concurrent_reseed_never_corrupts_the_stream(self):
        import threading
        from paddle_tpu.core.random import _GlobalGenerator
        gen = _GlobalGenerator(0)
        errs, stop = [], threading.Event()

        def hammer():
            try:
                while not stop.is_set():
                    k = gen.next_key()
                    assert k is not None and k.shape == (2,)
                    assert gen.get_state() is not None
            except Exception as e:  # pragma: no cover - the regression
                errs.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for s in range(50):
                gen.manual_seed(s)
                gen.set_state(jax.random.PRNGKey(s))
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errs, errs
        # the stream is deterministic once the racing writers are done:
        # a reseed fully replaces the key, so the split sequence matches
        # a fresh generator's from the same seed
        gen.manual_seed(42)
        want = _GlobalGenerator(42)
        for _ in range(3):
            np.testing.assert_array_equal(np.asarray(gen.next_key()),
                                          np.asarray(want.next_key()))
