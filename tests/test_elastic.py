"""Elastic manager tests (reference:
``test/collective/fleet/test_elastic_manager.py`` † — membership, TTL
eviction, scale events — with the KV store standing in for ETCD)."""
import os
import signal
import subprocess
import sys
import time

import pytest

from paddle_tpu.parallel.fleet.elastic import ElasticManager, ElasticStatus
from paddle_tpu.parallel.launch.rendezvous import KVServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mgr(srv, node, np="1:4", hb=0.1, ttl=0.6):
    return ElasticManager(srv.endpoint, "ejob", node, np=np,
                          heartbeat_interval=hb, ttl=ttl)


class TestElasticManager:
    def test_membership_and_ttl_eviction(self):
        srv = KVServer(port=0)
        try:
            a = _mgr(srv, "a").start()
            b = _mgr(srv, "b").start()
            time.sleep(0.2)
            assert a.live_nodes() == ["a", "b"]
            # b stops heartbeating -> evicted after TTL
            b._stop.set()
            b._thread.join()
            deadline = time.time() + 3
            while "b" in a.live_nodes():
                assert time.time() < deadline, "b never evicted"
                time.sleep(0.1)
            assert a.live_nodes() == ["a"]
            a.stop()
        finally:
            srv.stop()

    def test_wait_ready_ranks_and_epoch(self):
        srv = KVServer(port=0)
        try:
            a = _mgr(srv, "a", np="2:3").start()
            b = _mgr(srv, "b", np="2:3").start()
            ea, ra, wa, ta = a.wait_ready(timeout=10)
            eb, rb, wb, tb = b.wait_ready(timeout=10)
            assert (wa, wb) == (2, 2)
            assert ea == eb and ta == tb
            assert sorted([ra, rb]) == [0, 1]
            # deterministic: sorted node ids
            assert ta == {"a": 0, "b": 1}
            a.stop(); b.stop()
        finally:
            srv.stop()

    def test_hold_below_min(self):
        srv = KVServer(port=0)
        try:
            a = _mgr(srv, "a", np="2:4").start()
            time.sleep(0.2)
            assert a.status() == ElasticStatus.HOLD
            with pytest.raises(TimeoutError):
                a.wait_ready(timeout=0.8)
            a.stop()
        finally:
            srv.stop()

    def test_scale_up_bumps_epoch(self):
        srv = KVServer(port=0)
        try:
            a = _mgr(srv, "a", np="1:3").start()
            e1, r1, w1, _ = a.wait_ready(timeout=10)
            assert (r1, w1) == (0, 1)
            assert not a.has_changed(e1)
            b = _mgr(srv, "b", np="1:3").start()
            deadline = time.time() + 5
            while not a.has_changed(e1):
                assert time.time() < deadline, "scale-up never detected"
                time.sleep(0.1)
            e2, r2, w2, t2 = a.wait_ready(timeout=10)
            # epoch IS the membership signature: deterministic, race-free
            assert e2 != e1 and w2 == 2 and t2 == {"a": 0, "b": 1}
            assert e2 == "a:0,b:1"
            a.stop(); b.stop()
        finally:
            srv.stop()

    def test_commit_round_blocks_non_master(self):
        """ADVICE r3: per-node stability alone is not agreement. A
        non-master must NOT return from wait_ready until the master has
        published the membership table it also sees."""
        import threading
        srv = KVServer(port=0)
        try:
            a = _mgr(srv, "a", np="2:3").start()
            b = _mgr(srv, "b", np="2:3").start()
            out = {}

            def b_wait():
                out["b"] = b.wait_ready(timeout=10)
            t = threading.Thread(target=b_wait)
            t.start()
            # b's view is stable well within 1s, but no commit exists yet
            time.sleep(1.0)
            assert "b" not in out, "non-master returned without a commit"
            ea, ra, wa, ta = a.wait_ready(timeout=10)  # master: publishes
            t.join(timeout=10)
            assert not t.is_alive() and "b" in out
            eb, rb, wb, tb = out["b"]
            assert (ea, ta) == (eb, tb) and sorted([ra, rb]) == [0, 1]
            # the committed table is readable on the store
            import json as _json
            doc = _json.loads(a._kv.get(a._commit_key))
            assert doc["sig"] == ea and doc["table"] == {"a": 0, "b": 1}
            a.stop(); b.stop()
        finally:
            srv.stop()

    def test_scale_down_reassigns_ranks(self):
        srv = KVServer(port=0)
        try:
            a = _mgr(srv, "a", np="1:3").start()
            b = _mgr(srv, "b", np="1:3").start()
            e1, _, w1, _ = a.wait_ready(timeout=10)
            assert w1 == 2
            b.stop()  # deletes its key: immediate scale-down
            deadline = time.time() + 5
            while not a.has_changed(e1):
                assert time.time() < deadline
                time.sleep(0.1)
            e2, r2, w2, _ = a.wait_ready(timeout=10)
            assert w2 == 1 and r2 == 0 and e2 != e1
            a.stop()
        finally:
            srv.stop()


class TestLauncherElastic:
    def test_launch_elastic_completes_single_node(self, tmp_path):
        toy = os.path.join(REPO, "tests", "_launch_toy.py")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["PADDLE_ELASTIC_HEARTBEAT_INTERVAL"] = "0.1"
        env["PADDLE_ELASTIC_TTL"] = "1.0"
        p = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--procs", "1", "--master", "127.0.0.1:0", "--elastic_level",
             "1", "--nnodes", "1:3", "--log_dir", str(tmp_path / "logs"),
             toy, str(tmp_path)],
            capture_output=True, text=True, env=env, timeout=90, cwd=REPO)
        assert p.returncode == 0, p.stderr[-800:]
        import json
        with open(tmp_path / "env.0.json") as f:
            e = json.load(f)
        assert e["PADDLE_TRAINERS_NUM"] == "1"

    @pytest.mark.slow  # ~50 s multi-relaunch e2e; the single-node
    # completes-cleanly e2e above is the default-run representative
    def test_elastic_scale_resumes_from_checkpoint(self, tmp_path):
        """VERDICT r3 item 6 — the 5.3<->5.4 loop e2e: train 2 steps on a
        mp4 x sharding2 layout, an external agent triggers a scale event,
        the launcher relaunches with world=2, and the trainer resumes from
        the distributed checkpoint via reshard-on-load into a DIFFERENT
        mp2 x sharding4 layout. Loss must continue the phase-1 trajectory
        (match a serial uninterrupted oracle within tolerance)."""
        import json
        toy = os.path.join(REPO, "tests", "_elastic_ckpt_toy.py")
        announce = tmp_path / "kv.endpoint"
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8")
        env["PADDLE_ELASTIC_HEARTBEAT_INTERVAL"] = "0.1"
        env["PADDLE_ELASTIC_TTL"] = "1.0"
        env["PADDLE_LAUNCH_KV_ANNOUNCE"] = str(announce)
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--procs", "1", "--master", "127.0.0.1:0", "--elastic_level",
             "1", "--nnodes", "1:3", "--log_dir", str(tmp_path / "logs"),
             toy, str(tmp_path)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        joined = None
        try:
            # phase 1 finishes its 2 steps and checkpoints
            deadline = time.time() + 120
            while not (tmp_path / "phase.1.json").exists():
                assert time.time() < deadline, "phase 1 never checkpointed"
                assert proc.poll() is None, proc.stdout.read()[-800:]
                time.sleep(0.3)
            endpoint = None
            while endpoint is None or not endpoint.strip():
                endpoint = announce.read_text() if announce.exists() else None
                time.sleep(0.1)
                assert time.time() < deadline
            # external agent joins -> membership change -> relaunch
            joined = ElasticManager(endpoint.strip(), "default", "node-zz",
                                    np="1:3", heartbeat_interval=0.1,
                                    ttl=1.0).start()
            while not (tmp_path / "phase.2.json").exists():
                assert time.time() < deadline, "no post-scale resume"
                assert proc.poll() is None
                time.sleep(0.3)
        finally:
            if joined is not None:
                joined.stop()
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
        p1 = json.load(open(tmp_path / "phase.1.json"))
        p2 = json.load(open(tmp_path / "phase.2.json"))
        assert p1["world"] == 1 and p2["world"] == 2
        assert p1["degrees"] != p2["degrees"]  # layouts really differed
        assert p2["start"] == 2               # resumed, not restarted
        # oracle: the same 4 steps uninterrupted, serial in this process
        import paddle_tpu as paddle
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.optimizer import AdamW
        from paddle_tpu.parallel import mesh as mesh_mod
        sys.path.insert(0, os.path.join(REPO, "tests"))
        import _elastic_ckpt_toy as toy_mod
        mesh_mod._STATE["mesh"] = None
        paddle.seed(0)
        import numpy as np
        model = toy_mod.MpMLP()
        opt = AdamW(learning_rate=0.05, parameters=model.parameters())
        step = TrainStep(model,
                         lambda out, label: ((out - label) ** 2).mean(), opt)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        oracle = [float(step.step((x,), (y,)).value) for _ in range(4)]
        np.testing.assert_allclose(p1["losses"], oracle[:2], rtol=2e-4,
                                   atol=2e-5)
        np.testing.assert_allclose(p2["losses"], oracle[2:], rtol=2e-4,
                                   atol=2e-5)

    @pytest.mark.slow
    def test_launch_restarts_on_scale_up(self, tmp_path):
        """A second node agent joins mid-run: the launcher must tear down
        its trainers and respawn them with the doubled world size."""
        sleeper = tmp_path / "sleeper.py"
        sleeper.write_text(
            "import json, os, sys, time\n"
            "d = sys.argv[1]\n"
            "n = os.environ['PADDLE_TRAINERS_NUM']\n"
            "open(os.path.join(d, f'world.{n}'), 'w').write(n)\n"
            "time.sleep(60)\n")
        announce = tmp_path / "kv.endpoint"
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["PADDLE_ELASTIC_HEARTBEAT_INTERVAL"] = "0.1"
        env["PADDLE_ELASTIC_TTL"] = "1.0"
        env["PADDLE_LAUNCH_KV_ANNOUNCE"] = str(announce)
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--procs", "1", "--master", "127.0.0.1:0", "--elastic_level",
             "1", "--nnodes", "1:3", "--log_dir", str(tmp_path / "logs"),
             str(sleeper), str(tmp_path)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        joined = None
        try:
            deadline = time.time() + 30
            while not (tmp_path / "world.1").exists():
                assert time.time() < deadline, "first spawn never happened"
                assert proc.poll() is None
                time.sleep(0.2)
            endpoint = None
            while endpoint is None or not endpoint.strip():
                endpoint = announce.read_text() if announce.exists() else None
                time.sleep(0.1)
                assert time.time() < deadline
            joined = ElasticManager(endpoint.strip(), "default", "node-zz",
                                    np="1:3", heartbeat_interval=0.1,
                                    ttl=1.0).start()
            deadline = time.time() + 45
            while not (tmp_path / "world.2").exists():
                assert time.time() < deadline, "no relaunch at world=2"
                assert proc.poll() is None
                time.sleep(0.2)
        finally:
            if joined is not None:
                joined.stop()
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
