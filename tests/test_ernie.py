"""ERNIE/BERT encoder family (reference PaddleNLP ``ernie/modeling.py`` †:
ErnieModel + MaskedLM / SequenceClassification heads)."""
import numpy as np

import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import (ErnieForMaskedLM,
                               ErnieForSequenceClassification, ErnieModel,
                               ernie_tiny)
from paddle_tpu.optimizer import AdamW


def _ids(b, s, v, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randint(0, v, (b, s)).astype(np.int32))


class TestErnie:
    def test_encoder_shapes_and_pooler(self):
        paddle.seed(0)
        cfg = ernie_tiny()
        m = ErnieModel(cfg)
        seq, pooled = m(_ids(2, 16, cfg.vocab_size))
        assert seq.shape == [2, 16, cfg.hidden_size]
        assert pooled.shape == [2, cfg.hidden_size]
        # pooled = tanh(linear(CLS)) -> bounded
        assert float(pooled.abs().max()) <= 1.0 + 1e-6

    def test_attention_mask_blocks_padding(self):
        """Padded positions must not influence unmasked outputs: compare a
        short sequence against the same tokens padded out, masked."""
        paddle.seed(1)
        cfg = ernie_tiny()
        m = ErnieModel(cfg)
        ids8 = _ids(1, 8, cfg.vocab_size, seed=3)
        full, _ = m(ids8)
        padded = np.zeros((1, 16), np.int32)
        padded[:, :8] = ids8.numpy()
        mask = np.zeros((1, 16), np.float32)
        mask[:, :8] = 1.0
        out, _ = m(paddle.to_tensor(padded),
                   attention_mask=paddle.to_tensor(mask))
        np.testing.assert_allclose(out.numpy()[:, :8], full.numpy(),
                                   rtol=2e-4, atol=2e-5)

    def test_token_type_embeddings_matter(self):
        paddle.seed(2)
        cfg = ernie_tiny()
        m = ErnieModel(cfg)
        ids = _ids(1, 8, cfg.vocab_size, seed=4)
        seg0 = paddle.to_tensor(np.zeros((1, 8), np.int32))
        seg1 = paddle.to_tensor(np.ones((1, 8), np.int32))
        a, _ = m(ids, token_type_ids=seg0)
        b, _ = m(ids, token_type_ids=seg1)
        assert np.abs(a.numpy() - b.numpy()).max() > 1e-4

    def test_mlm_training_converges(self):
        paddle.seed(3)
        cfg = ernie_tiny()
        m = ErnieForMaskedLM(cfg)
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda loss, _l: loss, opt)
        ids = _ids(4, 16, cfg.vocab_size, seed=5)
        labels = ids  # reconstruct-everything objective for the smoke
        losses = [float(step.step((ids, None, None, labels), (ids,)).value)
                  for _ in range(8)]
        assert losses[-1] < losses[0]

    def test_mlm_ignore_index(self):
        """-100 positions must be EXCLUDED from the mean, pinned against a
        manually computed masked-CE oracle over the same logits."""
        paddle.seed(4)
        cfg = ernie_tiny()
        m = ErnieForMaskedLM(cfg)
        ids = _ids(2, 8, cfg.vocab_size, seed=6)
        lab = ids.numpy().copy()
        lab[:, ::2] = -100  # unmasked positions excluded from the loss
        l_half = float(m(ids, labels=paddle.to_tensor(lab)))
        logits = np.asarray(m(ids).numpy(), np.float64)
        lse = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) \
            + logits.max(-1)
        keep = lab != -100
        picked = np.take_along_axis(
            logits, np.where(keep, lab, 0)[..., None], axis=-1)[..., 0]
        oracle = ((lse - picked) * keep).sum() / keep.sum()
        np.testing.assert_allclose(l_half, oracle, rtol=2e-4)

    @pytest.mark.slow  # mlm_training_converges stays the default-run
    # ernie convergence representative
    def test_sequence_classification_trains(self):
        paddle.seed(5)
        cfg = ernie_tiny(hidden_dropout_prob=0.0)
        m = ErnieForSequenceClassification(cfg, num_classes=3)
        opt = AdamW(learning_rate=2e-3, parameters=m.parameters())
        step = TrainStep(m, lambda loss, _l: loss, opt)
        ids = _ids(6, 12, cfg.vocab_size, seed=7)
        y = paddle.to_tensor(np.asarray([0, 1, 2, 0, 1, 2], np.int32))
        losses = [float(step.step((ids, None, None, y), (y,)).value)
                  for _ in range(10)]
        assert losses[-1] < losses[0]
        logits = m(ids)
        assert logits.shape == [6, 3]
