"""Fault-tolerant serving (ISSUE 8): supervised engine driver with
crash recovery, preemption-by-recompute, and the deterministic
fault-injection harness (serving/faults.py).

The acceptance matrix, per the robustness contract:

- under injected faults (step crash at arbitrary indices, repeated
  crash pinned to one request, pool exhaustion, hung step past the
  watchdog deadline) NO request ever hangs: every submitted request
  terminates with stop|length|cancelled|timeout|error;
- bystander greedy streams are BYTE-IDENTICAL to the fault-free run
  after recovery/preemption (and seeded-sampled streams too — the PRNG
  walk is snapshotted host-side);
- poisoned requests are the ONLY ones failed (finish_reason="error"),
  isolated by the gateway's bisection quarantine;
- ``decode_compilations() == 1`` survives an engine rebuild (the jit
  cache is shared through the factory — no recompile storm);
- slot/block accounting is exact after any crash/preemption/quarantine:
  ``cache.num_free`` restored, no block double-freed or leaked, and
  cancellation arriving DURING recovery is honored;
- ``PoolExhausted`` is typed (RuntimeError subclass), carries pool
  occupancy, and keeps the sizing hint;
- the new /metrics series strict-parse and ``/healthz`` exposes the
  watchdog externally.
"""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (BlockManager, ContinuousBatchingEngine,
                                FINISH_REASONS, FatalFault, FaultPlan,
                                GenerationRequest, PagedKVCache,
                                PoolExhausted, VirtualClock)
from paddle_tpu.serving.server import ServingGateway, serve

from test_metrics_prom import parse_prometheus

BS = 8       # KV block size
CHUNK = 16   # chunked-prefill budget (2 blocks)
SLOTS = 2
S_MAX = 96


@pytest.fixture(scope="module")
def model():
    paddle.seed(33)
    return LlamaForCausalLM(llama_tiny())  # GQA tiny, pallas decode


def _mk_factory(model, jit_cache=None, **kw):
    """An engine factory with the fixed test geometry — the SAME
    factory builds the first engine and every recovery rebuild, sharing
    one jit cache, exactly like ``serve()`` wires it."""
    cache = jit_cache if jit_cache is not None else \
        model.__dict__.setdefault("_serving_jit", {})
    kw.setdefault("num_slots", SLOTS)
    kw.setdefault("max_seq_len", S_MAX)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    kw.setdefault("prefix_cache", True)

    def factory():
        return ContinuousBatchingEngine(model, jit_cache=cache, **kw)
    return factory


def _prompt(seed, n=12):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _req(ps, n=12, **kw):
    kw.setdefault("max_new_tokens", 8)
    return GenerationRequest(prompt=_prompt(ps, n), **kw)


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             eos_token_id=r.eos_token_id, seed=r.seed)


#: the standard mixed workload: greedy shorts, one seeded-sampled row,
#: one long prompt that chunks (60 > CHUNK)
def _traffic():
    return [_req(1), _req(2, n=10),
            _req(3, temperature=0.9, top_k=5, seed=123),
            _req(4, n=60, max_new_tokens=5)]


def _baseline(model, reqs, **kw):
    """Fault-free oracle streams for the same requests."""
    eng = _mk_factory(model, **kw)()
    return [o.tolist() for o in eng.generate([_clone(r) for r in reqs])]


def _drive(eng):
    while eng.has_work():
        eng.step()


class TestPoolExhausted:
    def test_typed_with_counts_and_sizing_hint(self):
        """The satellite pin: PoolExhausted subclasses RuntimeError
        (back-compat), carries live/pinned/free block counts, and the
        sizing hint survives in the message."""
        pool = BlockManager(1, 4, BS, 1, 4)
        cache = PagedKVCache(1, 2, 2 * BS, 1, 4, block_size=BS, pool=pool)
        for _ in range(4):
            pool.ref(pool.alloc())        # simulate pinned occupancy
        with pytest.raises(RuntimeError) as ei:
            cache._alloc_block()
        e = ei.value
        assert isinstance(e, PoolExhausted)
        assert (e.live_blocks, e.pinned_blocks, e.free_blocks) == (4, 4, 0)
        msg = str(e)
        assert "KV block pool exhausted" in msg
        assert "live=4, pinned=4, free=0" in msg
        # the sizing hint the old untyped raise carried is kept
        assert "num_slots * max_blocks + prefix budget" in msg

    def test_error_is_in_finish_vocabulary(self):
        assert "error" in FINISH_REASONS


class TestPreemptionByRecompute:
    def test_pool_fault_preempts_youngest_streams_identical(self, model):
        """Injected pool exhaustion mid-traffic: the engine preempts the
        youngest slot-holder (donating its chain to the trie), re-queues
        it, and every stream — victim included — is byte-identical to
        the fault-free run. Slot and block accounting land exact."""
        reqs = _traffic()
        want = _baseline(model, reqs)
        factory = _mk_factory(model)
        eng = factory()
        seqs = [eng.submit(_clone(r)) for r in reqs]
        FaultPlan().at_step(3, "pool").install(eng)
        _drive(eng)
        assert [s.tokens for s in seqs] == want
        assert eng.stats["preemptions"] == 1
        assert eng.stats["restores"] == 1
        assert all(s.finish_reason in ("length", "stop") for s in seqs)
        # exactly-once accounting: every slot back, pool blocks either
        # free or owned by the trie (refcounts fully released)
        assert eng.cache.num_free == SLOTS
        pool = eng.cache.pool
        assert pool.num_used == eng.prefix_cache.num_cached_blocks
        assert int((pool._ref > 0).sum()) == 0
        # the donated chain made the victim's recompute a trie hit
        assert eng.prefix_cache.stats["hits"] >= 1

    def test_preemption_without_trie_recomputes_cold(self, model):
        """No prefix cache: the preempted chain is freed outright and
        the recompute prefills from scratch — still byte-identical."""
        reqs = _traffic()
        want = _baseline(model, reqs, prefix_cache=False)
        factory = _mk_factory(model, prefix_cache=False)
        eng = factory()
        seqs = [eng.submit(_clone(r)) for r in reqs]
        FaultPlan().at_step(4, "pool").install(eng)
        _drive(eng)
        assert [s.tokens for s in seqs] == want
        assert eng.stats["preemptions"] == 1
        assert eng.cache.num_free == SLOTS
        assert eng.cache.pool.num_used == 0    # nothing leaked

    def test_unrepairable_exhaustion_reraises(self, model):
        """Exhaustion with NO preemptible slot-holder (nothing to
        displace) re-raises instead of spinning — typed, so a
        supervisor can still classify it fatal."""
        eng = _mk_factory(model)()
        eng.submit(_req(5))
        FaultPlan().at_step(0, "pool").install(eng)  # before any admit
        with pytest.raises(PoolExhausted):
            eng.step()
        # the popped-but-never-admitted request went back to the queue
        # intact: the next step admits and finishes it normally
        assert eng.scheduler.num_queued == 1
        _drive(eng)
        assert eng.cache.num_free == SLOTS


class TestEngineRestore:
    def test_restore_mid_stream_byte_identical(self, model):
        """The crash-recovery primitive: live sequences moved to a
        fresh engine mid-decode (prompt + generated tokens + PRNG
        snapshot) continue byte-identically — greedy AND seeded-sampled
        — with no token replayed and no retrace."""
        reqs = _traffic()
        jit = {}
        want = _baseline(model, reqs, jit_cache=jit)
        factory = _mk_factory(model, jit_cache=jit)
        eng = factory()
        seqs = [eng.submit(_clone(r)) for r in reqs]
        emitted = {s.request_id: [] for s in seqs}
        eng.on_token = lambda s, t: emitted[s.request_id].append(t)
        for _ in range(4):
            eng.step()
        # the gateway's recovery snapshot, engine-level
        keys = np.asarray(eng._keys, np.uint32)
        live = sorted((s for s in eng._slots if s is not None
                       and not s.done), key=lambda s: s.request_id)
        for s in live:
            if s.tokens and s.status == "running":
                s.key = keys[s.slot].copy()
        queued = [s for s in eng.scheduler.queue]
        eng2 = factory()
        eng2.on_token = eng.on_token
        before = eng2.decode_compilations()
        for s in live + queued:
            assert eng2.restore(s)
        _drive(eng2)
        assert [s.tokens for s in seqs] == want
        # every token reached on_token exactly once across both engines
        assert [emitted[s.request_id] for s in seqs] == want
        assert eng2.decode_compilations() == before == 1

    def test_mid_admission_crash_unwinds_to_queue(self, model):
        """A NON-pool exception escaping mid-admission (a real runtime
        error, not an injected boundary raise) must not strand the
        popped-but-uninstalled sequences in limbo: they go back to the
        queue, where crash recovery's snapshot — or simply the next
        step — can see them."""
        reqs = _traffic()
        want = _baseline(model, reqs)
        eng = _mk_factory(model)()
        seqs = [eng.submit(_clone(r)) for r in reqs]
        orig = eng._admit_cold
        state = {"armed": True}

        def boom(group, finished):
            if state["armed"]:
                state["armed"] = False
                raise FatalFault("device error mid-admission")
            return orig(group, finished)

        eng._admit_cold = boom
        with pytest.raises(FatalFault):
            eng.step()
        # every popped sequence is back in the queue IN ARRIVAL ORDER
        # (the admitted batch was suffix-sorted; the unwind must restore
        # FIFO), nothing holds a slot or a pin, and the run then
        # completes byte-identically
        assert [q.request_id for q in eng.scheduler.queue] == \
            [s.request_id for s in seqs]
        assert eng.cache.num_free == SLOTS
        _drive(eng)
        assert [s.tokens for s in seqs] == want

    def test_restored_long_content_chunks_cold(self, model):
        """Without a trie to hit, a restored sequence whose
        prompt + generated content exceeds the chunk budget re-enters
        through CHUNKED prefill (recompute never monopolizes a step)."""
        factory = _mk_factory(model, prefix_cache=False)
        eng = factory()
        seq = eng.submit(_req(6, n=40, max_new_tokens=30))
        want = _baseline(model, [_req(6, n=40, max_new_tokens=30)],
                         prefix_cache=False)[0]
        while len(seq.tokens) < 10:
            eng.step()
        eng._preempt(seq)                 # 40 + 9 = 49 rows > CHUNK
        assert seq.status == "queued" and seq.work_len == 49
        chunks0 = eng.stats["prefill_chunks"]
        _drive(eng)
        assert seq.tokens == want
        assert eng.stats["prefill_chunks"] > chunks0

    def test_restored_with_trie_recomputes_by_reference(self, model):
        """With the trie on, the preempted chain was donated, so the
        recompute prefill covers almost everything by ZERO-COPY
        reference — recovery is nearly free (the ROADMAP's
        "preempt-by-donation is cheap" claim, pinned)."""
        factory = _mk_factory(model)
        eng = factory()
        seq = eng.submit(_req(6, n=40, max_new_tokens=30))
        while len(seq.tokens) < 10:
            eng.step()
        saved0 = eng.stats["prefill_tokens_saved"]
        eng._preempt(seq)
        _drive(eng)
        # 49 work rows, 48 coverable by donated blocks (6 full blocks)
        assert eng.stats["prefill_tokens_saved"] - saved0 >= 40


def _await(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert pred(), "condition not reached before timeout"


def _gateway(model, plan, jit_cache=None, **kw):
    """A supervised gateway wired exactly like serve() does it — one
    factory for the first engine and every rebuild — but NOT started,
    so tests submit their whole workload first and the fault plan's
    step indices are deterministic relative to the traffic."""
    factory = _mk_factory(model, jit_cache=jit_cache)
    kw.setdefault("max_queue", 16)
    return ServingGateway(factory(), engine_factory=factory,
                          fault_hook=plan, start=False, **kw)


class TestSupervisedDriver:
    def test_transient_fault_retries_same_engine(self, model):
        reqs = _traffic()
        want = _baseline(model, reqs)
        plan = FaultPlan().at_step(2, "transient")
        gw = _gateway(model, plan)
        streams = [gw.submit(_clone(r)) for r in reqs]
        gw.start()
        outs = [st.result() for st in streams]
        assert [ids.tolist() for ids, _ in outs] == want
        assert gw.restarts == 0           # retried, never rebuilt
        assert plan.log == [(2, "transient")]
        fams = parse_prometheus(gw.registry.render())
        assert fams["serving_faults_total"]["samples"][
            ("serving_faults_total", (("kind", "transient"),))] == 1
        gw.shutdown(drain=True, timeout=30)
        assert gw.health_state == "draining"

    def test_transient_streak_escalates_to_rebuild(self, model):
        plan = FaultPlan()
        for i in range(6):                # > max_transient_retries=3
            plan.at_step(2 + i, "transient")
        gw = _gateway(model, plan, max_transient_retries=3,
                      retry_backoff_s=0.0)
        streams = [gw.submit(_clone(r)) for r in _traffic()]
        gw.start()
        for st in streams:
            st.result()
        assert gw.restarts >= 1
        assert all(st.finish_reason in ("length", "stop")
                   for st in streams)
        gw.shutdown(drain=True, timeout=30)

    def test_fatal_crash_recovers_streams_byte_identical(self, model):
        """The tentpole pin: a fatal step fault rebuilds the engine and
        every in-flight request — greedy and seeded-sampled — continues
        byte-identically, with decode_compilations() still 1 on the
        rebuilt engine (shared jit cache: no recompile storm)."""
        reqs = _traffic()
        jit = {}
        want = _baseline(model, reqs, jit_cache=jit)
        plan = FaultPlan().at_step(3, "fatal")
        gw = _gateway(model, plan, jit_cache=jit)
        streams = [gw.submit(_clone(r)) for r in reqs]
        gw.start()
        outs = [st.result() for st in streams]
        assert [ids.tolist() for ids, _ in outs] == want
        assert [r for _, r in outs] == ["length"] * 3 + ["length"]
        assert gw.restarts == 1
        assert gw.engine.decode_compilations() == 1   # the whole point
        assert len(gw.restart_latencies) == 1
        assert gw.restart_latencies[0] >= 0.0
        gw.shutdown(drain=True, timeout=30)

    def test_nan_corruption_recovery_recomputes(self, model):
        """The nan fault REALLY poisons the KV pool before crashing;
        byte-identical bystanders prove recovery recomputed from host
        token state instead of reusing corrupt device state."""
        reqs = _traffic()
        want = _baseline(model, reqs)
        plan = FaultPlan().at_step(4, "nan")
        gw = _gateway(model, plan)
        streams = [gw.submit(_clone(r)) for r in reqs]
        gw.start()
        outs = [st.result() for st in streams]
        assert [ids.tolist() for ids, _ in outs] == want
        assert gw.restarts == 1
        gw.shutdown(drain=True, timeout=30)

    def test_hung_step_watchdog_rebuilds(self, model):
        """A step that overran the (virtual) watchdog deadline is
        classified hung and recovered like a fatal fault — with the
        injected clock the whole scenario takes no real time."""
        reqs = _traffic()
        want = _baseline(model, reqs)
        clk = VirtualClock()
        plan = FaultPlan(clock=clk).at_step(3, "hung", stall_s=99.0)
        gw = _gateway(model, plan, watchdog_deadline_s=5.0, clock=clk)
        streams = [gw.submit(_clone(r)) for r in reqs]
        gw.start()
        outs = [st.result() for st in streams]
        assert [ids.tolist() for ids, _ in outs] == want
        assert gw.restarts == 1
        fams = parse_prometheus(gw.registry.render())
        assert fams["serving_faults_total"]["samples"][
            ("serving_faults_total", (("kind", "hung"),))] == 1
        gw.shutdown(drain=True, timeout=30)

    def test_watchdog_exempts_compiling_steps(self, model):
        """A step that traced a new program is exempt from the watchdog
        (compile time is not a hang — on a real chip a cold start
        routinely exceeds the deadline and must not burn the restart
        budget); the same stall on a WARM step still classifies hung."""
        clk = VirtualClock()
        plan = (FaultPlan(clock=clk).at_step(0, "hung", stall_s=99.0)
                .at_step(5, "hung", stall_s=99.0))
        gw = _gateway(model, plan, jit_cache={}, watchdog_deadline_s=5.0,
                      clock=clk)
        streams = [gw.submit(_clone(r)) for r in _traffic()]
        gw.start()
        for st in streams:
            st.result()
        assert all(st.finish_reason == "length" for st in streams)
        # step 0 stalled but compiled (fresh jit cache) -> exempt;
        # step 5 stalled warm -> one rebuild, not two
        assert gw.restarts == 1
        gw.shutdown(drain=True, timeout=30)

    def test_no_factory_strands_with_errors_not_hangs(self, model):
        """Without an engine_factory a fatal fault still terminates
        every request (finish_reason via the error event) — the one
        thing that may never happen is a hang."""
        plan = FaultPlan().at_step(2, "fatal")
        factory = _mk_factory(model)
        gw = ServingGateway(factory(), fault_hook=plan, start=False)
        streams = [gw.submit(_clone(r)) for r in _traffic()]
        gw.start()
        for st in streams:
            with pytest.raises(RuntimeError, match="engine driver died"):
                st.result()
        assert all(st.finish_reason == "error" for st in streams)

    def test_restart_budget_exhaustion_strands_with_errors(self, model):
        """An unfixable fault burns the restart budget, then every
        remaining request errors out — bounded, never a crash loop."""
        plan = FaultPlan().poison(lambda s: True, kind="fatal")
        gw = _gateway(model, plan, max_restarts=2, retry_backoff_s=0.0)
        streams = [gw.submit(_clone(r)) for r in _traffic()]
        gw.start()
        for st in streams:
            try:
                st.result()
            except RuntimeError:
                pass
        assert gw.restarts == 2
        assert all(st.finish_reason is not None for st in streams)


class TestPoisonQuarantine:
    def test_bisection_fails_only_the_culprit(self, model):
        """Repeated crash pinned to ONE request: the bisection
        quarantine isolates it, fails it with finish_reason="error",
        and every bystander completes byte-identically."""
        bystanders = [_req(i, n=8 + i) for i in range(4)]      # 8..11
        want = _baseline(model, bystanders)
        poison = _req(50, n=13, max_new_tokens=40)             # unique len
        plan = FaultPlan().poison(lambda s: s.prompt_len == 13)
        gw = _gateway(model, plan, max_restarts=16,
                      retry_backoff_s=0.0)
        streams = [gw.submit(_clone(r)) for r in bystanders]
        bad = gw.submit(_clone(poison))
        gw.start()
        outs = [st.result() for st in streams]
        with pytest.raises(RuntimeError, match="poisoned request"):
            bad.result()
        assert bad.finish_reason == "error"
        assert [ids.tolist() for ids, _ in outs] == want
        assert all(r == "length" for _, r in outs)
        assert gw.restarts >= 2           # fault recurred, then isolated
        # quarantine drained: nothing parked, nothing suspect
        assert not gw._parked and gw._suspect_ids is None
        _await(lambda: gw.health_state == "ok")
        gw.shutdown(drain=True, timeout=30)

    def test_cancel_during_recovery_is_honored(self, model):
        """A cancellation arriving while the gateway is mid-quarantine
        (engine rebuilt at least once, victim still crashing) takes
        effect: the cancelled bystander terminates "cancelled" and its
        slot accounting is exact."""
        plan = FaultPlan().poison(lambda s: s.prompt_len == 13)
        gw = _gateway(model, plan, max_restarts=16,
                      retry_backoff_s=0.0)
        victim = gw.submit(_req(60, n=8, max_new_tokens=60))
        bad = gw.submit(_req(61, n=13, max_new_tokens=60))
        gw.start()
        _await(lambda: gw.restarts >= 1)
        victim.cancel()
        ids, reason = victim.result()
        assert reason in ("cancelled", "length")
        assert victim.finish_reason == reason
        try:
            bad.result()
        except RuntimeError:
            pass
        _await(lambda: gw.engine.cache.num_free == SLOTS)
        gw.shutdown(drain=True, timeout=30)


    def test_parked_deadline_still_expires(self, model):
        """A request benched outside the engine by the bisection is
        beyond the engine's deadline sweep — the gateway's own parked
        sweep must still honor its timeout_s."""
        gw = _gateway(model, None)
        st = gw.submit(_req(80, max_new_tokens=60, timeout_s=0.05))
        gw._admit_intake()            # driver-side submit (thread idle)
        seq = st.seq
        assert gw.engine.scheduler.remove(seq)   # simulate parking
        seq.status = "queued"
        gw._parked.append(seq)
        time.sleep(0.06)
        gw.start()
        ids, reason = st.result()
        assert reason == "timeout" and len(ids) == 0
        gw.shutdown(drain=True, timeout=30)


class TestHealthAndMetrics:
    def test_new_metric_series_strict_parse(self, model):
        """The satellite pin: serving_faults_total{kind},
        serving_engine_restarts_total, serving_preemptions_total,
        serving_recovered_requests_total and the watchdog age gauge all
        render valid Prometheus text with the expected values."""
        clk = VirtualClock()
        plan = (FaultPlan(clock=clk)
                .at_step(2, "transient").at_step(4, "pool")
                .at_step(7, "fatal").at_step(11, "hung", stall_s=99.0))
        gw = _gateway(model, plan, watchdog_deadline_s=5.0,
                      clock=clk)
        streams = [gw.submit(_clone(r)) for r in _traffic()]
        gw.start()
        for st in streams:
            st.result()
        text = gw.registry.render()
        fams = parse_prometheus(text)     # strict: raises on bad format
        faults = fams["serving_faults_total"]
        assert faults["type"] == "counter"
        got = {lab[0][1]: v for (_, lab), v in faults["samples"].items()}
        assert got == {"transient": 1, "fatal": 1, "hung": 1}
        assert fams["serving_engine_restarts_total"]["samples"][
            ("serving_engine_restarts_total", ())] == 2
        assert fams["serving_preemptions_total"]["samples"][
            ("serving_preemptions_total", ())] == 1
        assert fams["serving_recovered_requests_total"]["samples"][
            ("serving_recovered_requests_total", ())] >= 2
        age = fams["serving_watchdog_last_step_age_seconds"]
        assert age["type"] == "gauge"
        # preemptions stay monotonic across the rebuild (base carried)
        assert gw._stat_base["preemptions"] == 1
        gw.shutdown(drain=True, timeout=30)

    def test_healthz_reports_watchdog_and_restarts(self, model):
        """/healthz carries the supervisor's external surface: status,
        seconds-since-last-completed-step, restart count; the SSE and
        blocking error paths return proper terminal responses."""
        plan = FaultPlan().poison(lambda s: s.prompt_len == 13)
        srv = serve(model, port=0, num_slots=SLOTS, max_seq_len=S_MAX,
                    prefix_block_size=BS, prefill_chunk=CHUNK,
                    max_restarts=16, model_name="chaos-test",
                    fault_hook=plan)
        try:
            body = json.dumps({"prompt": _prompt(70, 13).tolist(),
                               "max_tokens": 40}).encode()
            req = urllib.request.Request(
                srv.url + "/v1/completions", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    assert False, f"expected 500, got {r.status}"
            except urllib.error.HTTPError as e:
                assert e.code == 500
                doc = json.load(e)
                assert doc["choices"][0]["finish_reason"] == "error"
                assert doc["error"]["type"] == "server_error"
            with urllib.request.urlopen(srv.url + "/healthz",
                                        timeout=10) as r:
                doc = json.load(r)
            assert doc["status"] in ("ok", "degraded", "recovering")
            assert doc["engine_restarts"] >= 1
            assert isinstance(doc["last_step_age_s"], float)
        finally:
            srv.shutdown(drain=False, timeout=30)
