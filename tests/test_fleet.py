"""Engine fleet (ISSUE 12): replicated serving with prefix-affinity
routing, failover-to-sibling, and live request migration
(serving/fleet/, README "Engine fleet").

The acceptance matrix:

- ROUTER POLICIES are pure and deterministic: least-loaded tie-breaks
  to the lowest index, prefix-affinity wins only within the load band,
  round-robin rotates — and a fixed submission order routes
  identically on every replay (the VirtualClock chaos-replay pin);
- REPLICA KILL mid-decode (supervision exhausted under the chaos
  matrix) loses ZERO requests: every live stream fails over to a
  sibling by ``restore()`` recompute and continues BYTE-IDENTICALLY —
  greedy and seeded-sampled — to an unkilled single-engine run;
- LIVE MIGRATION moves an in-flight request between healthy replicas
  (evict: chain donated + PRNG snapshot; adopt: restore) with the
  stream byte-identical, and drain/rebalance ride it;
- COMPILE-ONCE holds per pool geometry across the fleet: same-geometry
  replicas share one jit-cache dict and each still reports
  ``decode_compilations() == 1``; mixed geometries isolate their
  dicts (pooling shape-keyed traces would break both pins);
- /METRICS carries a ``replica`` label on every per-replica series in
  ONE shared registry, and any single replica's crash-recovery rebuild
  keeps its series monotonic (per-replica carried counter bases);
- the fleet HTTP surface: routed completions, ``GET /debug/fleet``,
  ``POST /fleet/drain`` / ``/fleet/rebalance``, aggregated
  ``/healthz``.
"""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (ContinuousBatchingEngine, FaultPlan,
                                GenerationRequest, VirtualClock)
from paddle_tpu.serving.fleet import (EngineFleet, LeastLoadedRouter,
                                      PrefixAffinityRouter,
                                      RoundRobinRouter, make_router)

from test_metrics_prom import parse_prometheus

BS = 8       # KV block size
CHUNK = 16   # chunked-prefill budget (2 blocks)
SLOTS = 2    # per replica
S_MAX = 96


@pytest.fixture(scope="module")
def model():
    paddle.seed(33)
    return LlamaForCausalLM(llama_tiny())


def _prompt(seed, n=12):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _req(ps, n=12, **kw):
    kw.setdefault("max_new_tokens", 8)
    return GenerationRequest(prompt=_prompt(ps, n), **kw)


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             eos_token_id=r.eos_token_id, seed=r.seed)


#: the standard mixed workload: greedy shorts, one seeded-sampled row,
#: one long prompt that chunks (60 > CHUNK)
def _traffic():
    return [_req(1), _req(2, n=10),
            _req(3, temperature=0.9, top_k=5, seed=123),
            _req(4, n=60, max_new_tokens=5)]


def _baseline(model, reqs, num_slots=SLOTS):
    """Fault-free single-engine oracle streams for the same requests."""
    eng = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_seq_len=S_MAX, decode_chunk=1,
        prefix_cache=True, prefix_block_size=BS, prefill_chunk=CHUNK,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}))
    return [o.tolist() for o in eng.generate([_clone(r) for r in reqs])]


def _fleet(model, **kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("router", "round-robin")
    kw.setdefault("num_slots", SLOTS)
    kw.setdefault("max_seq_len", S_MAX)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    kw.setdefault("max_queue", 32)
    kw.setdefault("retry_backoff_s", 0.0)
    kw.setdefault("start", False)
    return EngineFleet(model, **kw)


def _await(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert pred(), "condition not reached before timeout"


# ----------------------------------------------------------- router units
class _StubReplica:
    """Router-facing stand-in: fixed load + per-prompt match table."""

    def __init__(self, index, load, matches=()):
        self.index = index
        self._load = load
        self._matches = dict(matches)
        self.routable = True
        self.alive = True

    def load(self):
        return self._load

    def prefix_match_tokens(self, prompt):
        return self._matches.get(bytes(np.asarray(prompt)), 0)


class TestRouterPolicies:
    def test_least_loaded_ties_break_to_lowest_index(self):
        reps = [_StubReplica(2, 5), _StubReplica(0, 5), _StubReplica(1, 3)]
        r = LeastLoadedRouter()
        order = r.rank(_req(1), reps)
        assert [x.index for x in order] == [1, 0, 2]
        # exact tie everywhere: pure index order
        reps = [_StubReplica(i, 7) for i in (2, 1, 0)]
        assert [x.index for x in r.rank(_req(1), reps)] == [0, 1, 2]

    def test_affinity_wins_only_within_the_load_band(self):
        req = _req(5)
        key = bytes(np.asarray(req.prompt))
        warm_near = _StubReplica(1, load=4, matches={key: 32})
        cold_min = _StubReplica(0, load=0)
        warm_far = _StubReplica(2, load=40, matches={key: 64})
        r = PrefixAffinityRouter(band=16)
        order = r.rank(req, [cold_min, warm_near, warm_far])
        # warm_near is in band (4 <= 0+16) and matches -> wins; the
        # MOST-matching replica is 40 loads past the floor -> ranked
        # after the whole band no matter its trie
        assert [x.index for x in order] == [1, 0, 2]
        # band=0: only exact-minimum-load replicas are affinity
        # candidates; warm_near (load 4) drops out of the band
        r0 = PrefixAffinityRouter(band=0)
        assert [x.index for x in r0.rank(
            req, [cold_min, warm_near, warm_far])][0] == 0

    def test_affinity_ties_break_by_load_then_index(self):
        req = _req(6)
        key = bytes(np.asarray(req.prompt))
        a = _StubReplica(0, load=2, matches={key: 16})
        b = _StubReplica(1, load=1, matches={key: 16})
        c = _StubReplica(2, load=1, matches={key: 16})
        order = PrefixAffinityRouter(band=16).rank(req, [a, b, c])
        assert [x.index for x in order] == [1, 2, 0]

    def test_round_robin_rotates(self):
        reps = [_StubReplica(i, 0) for i in range(3)]
        r = RoundRobinRouter()
        heads = [r.rank(_req(1), reps)[0].index for _ in range(6)]
        assert heads == [0, 1, 2, 0, 1, 2]

    def test_make_router(self):
        assert isinstance(make_router("least-loaded"), LeastLoadedRouter)
        assert make_router("affinity", band=3).band == 3
        custom = RoundRobinRouter()
        assert make_router(custom) is custom
        with pytest.raises(ValueError, match="unknown router"):
            make_router("random")
        with pytest.raises(ValueError, match="band"):
            PrefixAffinityRouter(band=-1)


# ------------------------------------------------- routing determinism
class TestRoutingDeterminism:
    @pytest.mark.slow  # 6 s replay duplicate: test_kill_replay_is_deterministic
    # below keeps the default fleet-determinism rep (870s cap)
    def test_virtual_clock_replay_routes_identically(self, model):
        """The chaos-replay pin: policies read replica state only, so
        the same submission order over a VirtualClock fleet produces
        the same decision log and the same streams, twice."""
        reqs = _traffic()
        want = _baseline(model, reqs)
        runs = []
        for _ in range(2):
            clk = VirtualClock()
            fleet = _fleet(model, router="least-loaded", clock=clk)
            streams = [fleet.submit(_clone(r)) for r in reqs]
            fleet.start()
            outs = [st.result() for st in streams]
            runs.append(([i for _, i in fleet.decisions],
                         [ids.tolist() for ids, _ in outs]))
            fleet.shutdown(drain=True, timeout=30)
        (dec1, got1), (dec2, got2) = runs
        assert dec1 == dec2
        assert got1 == got2 == want

    def test_full_waiting_room_sheds_sideways_then_429s(self, model):
        from paddle_tpu.serving.server import QueueFullError
        fleet = _fleet(model, router="least-loaded", max_queue=1)
        fleet.submit(_req(1))           # r0 full (driver stopped)
        st2 = fleet.submit(_req(2))     # sheds to r1
        assert fleet.decisions[1][1] != fleet.decisions[0][1]
        with pytest.raises(QueueFullError):
            fleet.submit(_req(3))       # every replica full -> 429
        assert st2.gateway is fleet.replicas[
            fleet.decisions[1][1]].gateway
        fleet.start()
        fleet.shutdown(drain=True, timeout=30)


# ------------------------------------------------ compile-once / shared jit
class TestFleetCompileDiscipline:
    def test_same_geometry_shares_one_jit_cache(self, model):
        """The tentpole compile pin: same-geometry replicas share one
        jit dict — the whole fleet traces each program ONCE — and each
        engine still reports decode_compilations() == 1 after serving
        real traffic."""
        fleet = _fleet(model)
        e0 = fleet.replicas[0].gateway.engine
        e1 = fleet.replicas[1].gateway.engine
        assert e0._jit is e1._jit
        streams = [fleet.submit(_clone(r)) for r in _traffic()]
        fleet.start()
        for st in streams:
            st.result()
        assert e0.decode_compilations() == 1
        assert e1.decode_compilations() == 1
        fleet.shutdown(drain=True, timeout=30)

    def test_mixed_geometry_isolates_jit_caches(self, model):
        """Differing pool geometry (num_slots) must NOT pool traces
        under one fn: isolated dicts, each engine's pin intact."""
        fleet = _fleet(model, num_slots=[SLOTS, SLOTS + 1],
                       router="round-robin")
        e0 = fleet.replicas[0].gateway.engine
        e1 = fleet.replicas[1].gateway.engine
        assert e0._jit is not e1._jit
        streams = [fleet.submit(_clone(r)) for r in _traffic()]
        fleet.start()
        for st in streams:
            st.result()
        assert e0.decode_compilations() == 1
        assert e1.decode_compilations() == 1
        fleet.shutdown(drain=True, timeout=30)

    @pytest.mark.slow  # 7 s geometry duplicate: test_mixed_geometry_isolates_
    # jit_caches above is the default geometry rep (870s cap)
    def test_mixed_prefix_blocks_is_pool_geometry_too(self, model):
        """Review regression: prefix_blocks sizes the pool arrays the
        traced programs close over (num_blocks = live + trie budget),
        so replicas differing ONLY in prefix_blocks must isolate their
        jit dicts — sharing one would double both engines'
        decode_compilations()."""
        fleet = _fleet(model, prefix_blocks=[8, 16],
                       router="round-robin")
        e0 = fleet.replicas[0].gateway.engine
        e1 = fleet.replicas[1].gateway.engine
        assert e0._jit is not e1._jit
        streams = [fleet.submit(_clone(r)) for r in _traffic()]
        fleet.start()
        for st in streams:
            st.result()
        assert e0.decode_compilations() == 1
        assert e1.decode_compilations() == 1
        fleet.shutdown(drain=True, timeout=30)

    def test_heterogeneous_max_seq_len_routes_by_capacity(self, model):
        """Review regression: with per-replica max_seq_len, a request
        only one replica can hold must route there (not 400 off the
        small replica's validate), and failover must never adopt a
        sequence onto a replica too small for it (crash-loop
        cascade)."""
        big = _req(41, n=40, max_new_tokens=20)    # needs 60 rows
        small = _req(42, n=8, max_new_tokens=4)
        want = _baseline(model, [big, small])
        fleet = _fleet(model, max_seq_len=[S_MAX, 32],
                       router="least-loaded", prefill_chunk=CHUNK)
        st_big = fleet.submit(_clone(big))
        st_small = fleet.submit(_clone(small))
        assert st_big.gateway is fleet.replicas[0].gateway  # only fit
        fleet.start()
        outs = [st.result() for st in (st_big, st_small)]
        assert [ids.tolist() for ids, _ in outs] == want
        fleet.shutdown(drain=True, timeout=30)

    def test_failover_skips_too_small_sibling(self, model):
        """A dying replica's oversized request must terminate with an
        error (no sibling can hold it) while its holdable bystanders
        still fail over — never a crash loop on the sibling."""
        big = _req(43, n=40, max_new_tokens=20)    # 60 rows > 32
        ok = _req(44, n=8, max_new_tokens=4)       # fits anywhere
        fleet = _fleet(model, max_seq_len=[S_MAX, 32],
                       router="least-loaded", max_restarts=0,
                       fault_hooks=[FaultPlan().at_step(3, "fatal"),
                                    None])
        st_big = fleet.submit(_clone(big))
        st_ok = fleet.submit(_clone(ok))
        assert st_big.gateway is fleet.replicas[0].gateway
        fleet.start()
        with pytest.raises(RuntimeError):
            st_big.result()
        assert st_big.finish_reason == "error"
        ids, reason = st_ok.result()
        assert reason in ("length", "stop")
        # the sibling survived the failover untouched by the big one
        assert fleet.replicas[1].state in ("ok", "degraded")
        assert fleet.replicas[1].gateway.restarts == 0
        fleet.shutdown(drain=True, timeout=30)


# --------------------------------------------------- failover-to-sibling
class TestFailoverToSibling:
    def test_replica_kill_mid_decode_zero_lost_byte_identical(self, model):
        """THE acceptance pin: a replica whose supervision is
        exhausted mid-decode (fatal fault, no restart budget) loses
        ZERO requests — its live streams (greedy AND seeded-sampled,
        chunked long prompt included) fail over to the sibling and
        finish byte-identically to an unkilled single-engine run."""
        reqs = _traffic()
        want = _baseline(model, reqs)
        fleet = _fleet(model, max_restarts=0,
                       fault_hooks=[FaultPlan().at_step(3, "fatal"),
                                    None])
        streams = [fleet.submit(_clone(r)) for r in reqs]
        fleet.start()
        outs = [st.result() for st in streams]
        assert [ids.tolist() for ids, _ in outs] == want
        assert all(r in ("length", "stop") for _, r in outs)  # 0 lost
        assert fleet.replicas[0].state == "dead"
        assert fleet.replicas[1].state in ("ok", "degraded")
        assert fleet._m_failovers.value() == 1
        assert fleet._m_migrated.value(cause="failover") >= 1
        assert fleet.health_state == "degraded"   # reduced capacity
        fleet.shutdown(drain=True, timeout=30)

    def test_kill_replay_is_deterministic(self, model):
        """Chaos-matrix replay: the same kill plan over the same
        submission order reproduces the same routing decisions, the
        same fault log, and the same streams."""
        reqs = _traffic()
        runs = []
        for _ in range(2):
            plan = FaultPlan().at_step(3, "fatal")
            fleet = _fleet(model, max_restarts=0,
                           fault_hooks=[plan, None])
            streams = [fleet.submit(_clone(r)) for r in reqs]
            fleet.start()
            outs = [st.result() for st in streams]
            runs.append(([i for _, i in fleet.decisions], plan.log,
                         [ids.tolist() for ids, _ in outs]))
            fleet.shutdown(drain=True, timeout=30)
        assert runs[0] == runs[1]

    def test_intra_replica_recovery_never_escalates(self, model):
        """With restart budget available the replica recovers ITSELF
        (the PR-7 path): no failover, replica stays alive, streams
        byte-identical, decode_compilations() still 1 on the rebuilt
        engine."""
        reqs = _traffic()
        want = _baseline(model, reqs)
        fleet = _fleet(model, max_restarts=8,
                       fault_hooks=[FaultPlan().at_step(3, "fatal"),
                                    None])
        streams = [fleet.submit(_clone(r)) for r in reqs]
        fleet.start()
        outs = [st.result() for st in streams]
        assert [ids.tolist() for ids, _ in outs] == want
        rep0 = fleet.replicas[0]
        assert rep0.state != "dead"
        assert rep0.gateway.restarts == 1
        assert rep0.gateway.engine.decode_compilations() == 1
        assert fleet._m_failovers.value() == 0
        fleet.shutdown(drain=True, timeout=30)

    def test_last_replica_death_strands_with_errors_not_hangs(self, model):
        """Nobody to fail over to (single-replica fleet): the
        pre-fleet contract holds — every request terminates with an
        error event, never a hang."""
        fleet = _fleet(model, replicas=1, max_restarts=0,
                       fault_hooks=[FaultPlan().at_step(2, "fatal")])
        streams = [fleet.submit(_clone(r)) for r in _traffic()]
        fleet.start()
        for st in streams:
            with pytest.raises(RuntimeError):
                st.result()
        assert all(st.finish_reason == "error" for st in streams)
        assert fleet.health_state == "draining"


# ----------------------------------------------------- live migration
class TestLiveMigration:
    def test_migrate_mid_decode_byte_identical(self, model):
        req = _req(7, max_new_tokens=40)
        want = _baseline(model, [req])[0]
        fleet = _fleet(model, router="least-loaded", start=True)
        st = fleet.submit(_clone(req))
        _await(lambda: st.seq is not None and len(st.seq.tokens) >= 8)
        source = st.gateway
        fleet.migrate(st, target=1)
        ids, reason = st.result()
        assert ids.tolist() == want and reason == "length"
        assert st.gateway is fleet.replicas[1].gateway
        assert st.gateway is not source
        assert fleet._m_migrated.value(cause="migration") == 1
        # exact accounting on the source: slot freed, nothing leaked
        eng = fleet.replicas[0].gateway.engine
        _await(lambda: eng.cache.num_free == SLOTS)
        fleet.shutdown(drain=True, timeout=30)

    def test_drain_replica_migrates_and_cordons(self, model):
        reqs = [_req(i, max_new_tokens=30) for i in (11, 12, 13, 14)]
        want = _baseline(model, reqs)
        fleet = _fleet(model, router="round-robin", start=True)
        streams = [fleet.submit(_clone(r)) for r in reqs]
        _await(lambda: any(st.seq is not None and st.seq.tokens
                           for st in streams))
        moved = fleet.drain_replica(0)
        assert not fleet.replicas[0].accepting
        assert fleet.replicas[0].state == "draining"
        outs = [st.result() for st in streams]
        assert [ids.tolist() for ids, _ in outs] == want
        assert moved >= 1
        # drained replica took no NEW work; undrain restores routing
        st = fleet.submit(_req(15))
        assert st.gateway is fleet.replicas[1].gateway
        fleet.undrain_replica(0)
        assert fleet.replicas[0].routable
        st.result()
        fleet.shutdown(drain=True, timeout=30)

    def test_migration_refused_recovers_locally(self, model):
        """A migration with no routable target must not lose the
        request: the source restores it locally and the stream still
        finishes byte-identically."""
        req = _req(9, max_new_tokens=30)
        want = _baseline(model, [req])[0]
        fleet = _fleet(model, replicas=1, router="round-robin",
                       start=True)
        st = fleet.submit(_clone(req))
        _await(lambda: st.seq is not None and len(st.seq.tokens) >= 4)
        fleet.migrate(st)               # nowhere to go
        ids, reason = st.result()
        assert ids.tolist() == want and reason == "length"
        fleet.shutdown(drain=True, timeout=30)


# ------------------------------------------------------- fleet metrics
class TestFleetMetrics:
    def test_replica_labels_and_monotonic_across_rebuild(self, model):
        """ISSUE 12 satellite: one shared registry, every per-replica
        series replica-labeled, and a SINGLE replica's crash-recovery
        rebuild keeps its counters monotonic (per-replica carried
        (base, engine) snapshots) while the sibling's series never
        move."""
        reqs = _traffic()
        fleet = _fleet(model, max_restarts=8,
                       fault_hooks=[FaultPlan().at_step(3, "fatal"),
                                    None])
        streams = [fleet.submit(_clone(r)) for r in reqs]
        fleet.start()
        for st in streams:
            st.result()
        gw0 = fleet.replicas[0].gateway
        gw1 = fleet.replicas[1].gateway
        assert gw0.restarts == 1 and gw1.restarts == 0
        # the dead incarnation's tokens were banked into the base...
        assert gw0._stat_base["tokens_generated"] > 0
        text = fleet.registry.render()
        fams = parse_prometheus(text)   # strict: raises on bad format
        restarts = fams["serving_engine_restarts_total"]["samples"]
        assert restarts[("serving_engine_restarts_total",
                         (("replica", "0"),))] == 1
        assert restarts[("serving_engine_restarts_total",
                         (("replica", "1"),))] == 0
        # ...and the rendered per-replica carried series reads
        # base + live — the monotonic carry, now per (replica, base,
        # engine): the scraped value can never be less than the dead
        # incarnation's banked base
        chunks = fams["serving_prefill_chunks_total"]["samples"]
        assert chunks[("serving_prefill_chunks_total",
                       (("replica", "0"),))] == \
            gw0._stat("prefill_chunks") >= \
            gw0._stat_base["prefill_chunks"]
        assert fams["serving_requests_total"]["samples"][
            ("serving_requests_total", (("replica", "0"),))] + \
            fams["serving_requests_total"]["samples"][
            ("serving_requests_total", (("replica", "1"),))] == len(reqs)
        # fleet-level series
        assert fams["serving_fleet_replicas"]["samples"][
            ("serving_fleet_replicas", ())] == 2
        decided = fams["serving_fleet_router_decisions_total"]["samples"]
        assert sum(decided.values()) == len(reqs)
        fleet.shutdown(drain=True, timeout=30)

    def test_fleet_table_reads_like_the_scrape(self, model):
        fleet = _fleet(model, start=False)
        streams = [fleet.submit(_clone(r)) for r in _traffic()]
        fleet.start()
        for st in streams:
            st.result()
        rows = fleet.fleet_table()
        assert [r["replica"] for r in rows] == [0, 1]
        for rep, row in zip(fleet.replicas, rows):
            gw = rep.gateway
            assert row["state"] in ("ok", "degraded", "recovering")
            assert row["tokens_generated"] == gw._stat("tokens_generated")
            assert row["dispatches_per_decoded_token"] == round(
                gw.cost.totals["dispatches"]
                / max(gw._stat("tokens_generated"), 1), 4)
            assert row["restarts"] == 0
            assert row["last_rebuild_age_s"] is None
        assert sum(r["tokens_generated"] for r in rows) > 0
        fleet.shutdown(drain=True, timeout=30)


# ------------------------------------------------------------ HTTP surface
class TestFleetHTTP:
    @pytest.fixture()
    def server(self, model):
        from paddle_tpu.serving.server import serve_fleet
        srv = serve_fleet(model, replicas=2, port=0, num_slots=SLOTS,
                          max_seq_len=S_MAX, prefix_block_size=BS,
                          prefill_chunk=CHUNK, model_name="fleet-test")
        yield srv
        srv.shutdown(drain=False, timeout=30)

    def _get(self, srv, path):
        with urllib.request.urlopen(srv.url + path, timeout=30) as r:
            return r.status, json.load(r)

    def _post(self, srv, path, obj):
        req = urllib.request.Request(
            srv.url + path, data=json.dumps(obj).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.load(r)

    def test_routed_completion_and_debug_fleet(self, server):
        status, doc = self._post(server, "/v1/completions", {
            "prompt": [int(t) for t in _prompt(21)], "max_tokens": 6})
        assert status == 200
        assert doc["choices"][0]["finish_reason"] == "length"
        assert len(doc["choices"][0]["token_ids"]) == 6
        assert doc["id"].startswith("cmpl-r")     # fleet-unique ids
        status, doc = self._get(server, "/debug/fleet")
        assert status == 200
        assert [r["replica"] for r in doc["replicas"]] == [0, 1]
        assert doc["router"] == "affinity"
        for row in doc["replicas"]:
            assert {"state", "live_kv_blocks", "free_kv_blocks",
                    "queue_depth", "dispatches_per_decoded_token",
                    "last_rebuild_age_s", "restarts"} <= set(row)

    def test_healthz_metrics_and_requests_aggregate(self, server):
        status, doc = self._get(server, "/healthz")
        assert status == 200
        assert doc["status"] == "ok"
        assert doc["num_replicas"] == 2 and doc["routable_replicas"] == 2
        assert len(doc["replicas"]) == 2
        with urllib.request.urlopen(server.url + "/metrics",
                                    timeout=30) as r:
            text = r.read().decode()
        fams = parse_prometheus(text)
        assert ("serving_num_slots", (("replica", "0"),)) in \
            fams["serving_num_slots"]["samples"]
        assert ("serving_num_slots", (("replica", "1"),)) in \
            fams["serving_num_slots"]["samples"]
        assert "serving_fleet_replicas" in fams
        status, doc = self._get(server, "/debug/requests")
        assert status == 200 and doc["num_replicas"] == 2
        status, doc = self._get(server, "/debug/profile")
        assert status == 200 and set(doc["replicas"]) == {"0", "1"}
        status, doc = self._get(server, "/debug/trace")
        assert status == 200 and "traceEvents" in doc

    def test_drain_rebalance_endpoints(self, server):
        status, doc = self._post(server, "/fleet/drain", {"replica": 0})
        assert status == 200 and doc["state"] == "draining"
        status, doc = self._get(server, "/healthz")
        assert doc["status"] == "degraded"     # capacity reduced
        status, doc = self._post(server, "/fleet/drain",
                                 {"replica": 0, "undrain": True})
        assert status == 200 and doc["state"] == "accepting"
        status, doc = self._post(server, "/fleet/rebalance", {})
        assert status == 200 and "migrations_requested" in doc
        # bad replica index -> 400
        try:
            self._post(server, "/fleet/drain", {"replica": 9})
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400


# --------------------------------------------------------------- CLI args
class TestFleetCLIArgs:
    def test_bad_num_slots_is_an_argparse_error(self):
        """Review regression: --num-slots grew comma-list parsing and
        must keep argparse error semantics — no tracebacks, no silent
        truncation of a list without --replicas."""
        from paddle_tpu.serving.server.__main__ import main
        for argv in (["--num-slots", "abc"],
                     ["--num-slots", ","],
                     ["--num-slots", "8,4"],                 # replicas=1
                     ["--replicas", "3", "--num-slots", "8,4"]):
            with pytest.raises(SystemExit) as ei:
                main(argv)
            assert ei.value.code == 2                        # usage error


# ------------------------------------------------------------ fleet bench
@pytest.mark.slow   # ISSUE 12 satellite: the fleet bench is nightly-class
def test_bench_fleet_accepts():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    from bench_fleet import measure_fleet
    res = measure_fleet(quick=True)
    assert res["accepted"], res
