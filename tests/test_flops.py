"""paddle.flops (reference ``python/paddle/hapi/dynamic_flops.py`` † —
hook-based MAC counting over a dummy forward)."""
import numpy as np

import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestFlops:
    def test_mlp_hand_count(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        # batch 2: Linear1 = 2*8*(4+1) = 80, ReLU = 16, Linear2 = 2*2*(8+1)
        assert paddle.flops(net, [2, 4]) == 80 + 16 + 36

    def test_conv_count_and_custom_ops(self):
        paddle.seed(1)
        net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU())
        # conv: out elems 1*8*16*16 * (3*3*3 + 1) = 2048 * 28
        want_conv = 8 * 16 * 16 * (27 + 1)
        total = paddle.flops(net, [1, 3, 16, 16])
        assert total == want_conv + 8 * 16 * 16

        class Custom(nn.Layer):
            def forward(self, x):
                return x

        net2 = nn.Sequential(nn.Linear(4, 4), Custom())
        base = paddle.flops(net2, [1, 4])
        with_custom = paddle.flops(
            net2, [1, 4], custom_ops={Custom: lambda l, i, o: 1000})
        assert with_custom == base + 1000

    @pytest.mark.slow  # full resnet50 flops walk (~6s); the op-level
    # flops tests stay default
    def test_resnet_scale_plausible(self):
        paddle.seed(2)
        from paddle_tpu.vision.models import resnet18
        f64 = paddle.flops(resnet18(), [1, 3, 64, 64])
        assert 1e8 < f64 < 3e8  # ~1.8 GMACs at 224 -> ~148M at 64

    def test_restores_per_layer_training_mode_and_removes_hooks(self):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5),
                            nn.BatchNorm1D(4))
        net.train()
        net[2].eval()  # deliberately frozen sublayer must STAY frozen
        paddle.flops(net, [2, 4])
        assert net.training and net[0].training
        assert not net[2].training
        hooks = sum(len(l._forward_post_hooks)
                    for l in net.sublayers(include_self=True))
        assert hooks == 0

    def test_conv_transpose_count(self):
        paddle.seed(4)
        net = nn.Sequential(nn.Conv2DTranspose(8, 3, 3))
        # MACs = in_elems * out_c/groups * k*k (+ bias * out_elems)
        total = paddle.flops(net, [1, 8, 5, 5])
        want = 8 * 5 * 5 * (3 * 3 * 3) + 3 * 7 * 7
        assert total == want, (total, want)
