"""Round-4 nn.functional parity batch vs torch oracles (reference: the
remaining ``python/paddle/nn/functional/`` surface †)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
TF = torch.nn.functional


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestActivationsAndPads:
    def test_thresholded_relu_and_log_sigmoid(self):
        x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
        np.testing.assert_allclose(
            F.thresholded_relu(_t(x), threshold=0.3).numpy(),
            TF.threshold(torch.tensor(x), 0.3, 0.0).numpy(), rtol=1e-6)
        np.testing.assert_allclose(
            F.log_sigmoid(_t(x)).numpy(),
            TF.logsigmoid(torch.tensor(x)).numpy(), rtol=1e-5, atol=1e-6)

    def test_zeropad2d(self):
        x = np.random.RandomState(1).randn(1, 2, 3, 3).astype(np.float32)
        got = F.zeropad2d(_t(x), [1, 2, 0, 1]).numpy()
        want = TF.pad(torch.tensor(x), (1, 2, 0, 1)).numpy()
        np.testing.assert_allclose(got, want)


class TestPools:
    def test_lp_pool2d_matches_torch(self):
        x = np.abs(np.random.RandomState(2).randn(1, 2, 6, 6)) \
            .astype(np.float32)
        got = F.lp_pool2d(_t(x), 2.0, 2, stride=2).numpy()
        want = TF.lp_pool2d(torch.tensor(x), 2.0, 2, stride=2).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_lp_pool1d_matches_torch(self):
        x = np.abs(np.random.RandomState(3).randn(2, 3, 8)) \
            .astype(np.float32)
        got = F.lp_pool1d(_t(x), 3.0, 2, stride=2).numpy()
        want = TF.lp_pool1d(torch.tensor(x), 3.0, 2, stride=2).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_adaptive_max_pool3d(self):
        x = np.random.RandomState(4).randn(1, 2, 6, 8, 4).astype(np.float32)
        got = F.adaptive_max_pool3d(_t(x), [3, 4, 2]).numpy()
        want = TF.adaptive_max_pool3d(torch.tensor(x), (3, 4, 2)).numpy()
        np.testing.assert_allclose(got, want)
        got_odd = F.adaptive_max_pool3d(_t(x), [4, 3, 3]).numpy()
        want_odd = TF.adaptive_max_pool3d(torch.tensor(x), (4, 3, 3)).numpy()
        np.testing.assert_allclose(got_odd, want_odd)


class TestShapeOps:
    def test_pixel_unshuffle_roundtrips_shuffle(self):
        x = np.random.RandomState(5).randn(2, 3, 8, 8).astype(np.float32)
        sh = F.pixel_shuffle(F.pixel_unshuffle(_t(x), 2), 2).numpy()
        np.testing.assert_allclose(sh, x)
        want = TF.pixel_unshuffle(torch.tensor(x), 2).numpy()
        np.testing.assert_allclose(F.pixel_unshuffle(_t(x), 2).numpy(),
                                   want)

    def test_pixel_unshuffle_nhwc_matches_nchw(self):
        # advisor r4: the NHWC branch emitted (ry, rx, c) channel order —
        # the channel-last kernel orders channels (c, ry, rx), identical
        # per-pixel values to the NCHW branch
        x = np.random.RandomState(7).randn(2, 3, 8, 8).astype(np.float32)
        nchw = F.pixel_unshuffle(_t(x), 2).numpy()
        nhwc = F.pixel_unshuffle(_t(x.transpose(0, 2, 3, 1)), 2,
                                 data_format="NHWC").numpy()
        np.testing.assert_allclose(nhwc.transpose(0, 3, 1, 2), nchw)

    def test_temporal_shift(self):
        x = np.random.RandomState(6).randn(4, 8, 2, 2).astype(np.float32)
        got = F.temporal_shift(_t(x), seg_num=2, shift_ratio=0.25).numpy()
        v = x.reshape(2, 2, 8, 2, 2)
        want = v.copy()
        want[:, :, :2] = np.concatenate(
            [v[:, 1:, :2], np.zeros_like(v[:, :1, :2])], axis=1)
        want[:, :, 2:4] = np.concatenate(
            [np.zeros_like(v[:, :1, 2:4]), v[:, :-1, 2:4]], axis=1)
        np.testing.assert_allclose(got, want.reshape(4, 8, 2, 2))


class TestSampling:
    def test_affine_grid_and_grid_sample_identity(self):
        """Identity theta must reproduce the input through grid_sample."""
        x = np.random.RandomState(7).randn(1, 2, 5, 7).astype(np.float32)
        theta = np.asarray([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32)
        grid = F.affine_grid(_t(theta), [1, 2, 5, 7], align_corners=True)
        out = F.grid_sample(_t(x), grid, align_corners=True).numpy()
        np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-5)

    def test_grid_sample_matches_torch(self):
        rng = np.random.RandomState(8)
        x = rng.randn(2, 3, 6, 5).astype(np.float32)
        grid = (rng.rand(2, 4, 4, 2).astype(np.float32) * 2 - 1)
        for mode in ("bilinear", "nearest"):
            got = F.grid_sample(_t(x), _t(grid), mode=mode,
                                align_corners=True).numpy()
            want = TF.grid_sample(torch.tensor(x), torch.tensor(grid),
                                  mode=mode, align_corners=True).numpy()
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_affine_grid_matches_torch(self):
        theta = np.asarray([[[0.8, 0.1, 0.2], [-0.1, 1.1, -0.3]]],
                           np.float32)
        got = F.affine_grid(_t(theta), [1, 1, 4, 6],
                            align_corners=True).numpy()
        want = TF.affine_grid(torch.tensor(theta), (1, 1, 4, 6),
                              align_corners=True).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestMiscOps:
    def test_bilinear_matches_torch(self):
        rng = np.random.RandomState(9)
        x1 = rng.randn(4, 3).astype(np.float32)
        x2 = rng.randn(4, 5).astype(np.float32)
        w = rng.randn(2, 3, 5).astype(np.float32)
        b = rng.randn(2).astype(np.float32)
        got = F.bilinear(_t(x1), _t(x2), _t(w), _t(b)).numpy()
        want = TF.bilinear(torch.tensor(x1), torch.tensor(x2),
                           torch.tensor(w), torch.tensor(b)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_gather_tree_walks_parents(self):
        # T=3, B=1, W=2 beam: final beams trace ancestry through parents
        ids = np.asarray([[[1, 2]], [[3, 4]], [[5, 6]]], np.int32)
        parents = np.asarray([[[0, 0]], [[0, 0]], [[1, 0]]], np.int32)
        got = F.gather_tree(_t(ids), _t(parents)).numpy()
        # beam 0 at t=2 has parent 1: path = ids[0][par(par)]..: [2, 4, 5]?
        # walk: t2 tok ids[2,0,[0,1]]=[5,6]; parents -> [1,0]
        #       t1 tok ids[1,0,[1,0]]=[4,3]; parents[1,0,[1,0]] = [0,0]
        #       t0 tok ids[0,0,[0,0]]=[1,1]
        want = np.asarray([[[1, 1]], [[4, 3]], [[5, 6]]], np.int32)
        np.testing.assert_array_equal(got, want)

    def test_margin_cross_entropy_reduces_to_ce_at_zero_margin(self):
        rng = np.random.RandomState(10)
        # cosine-similarity logits in [-1, 1]
        logits = (rng.rand(6, 10).astype(np.float32) * 2 - 1) * 0.9
        label = rng.randint(0, 10, 6).astype(np.int32)
        got = float(F.margin_cross_entropy(
            _t(logits), _t(label), margin1=1.0, margin2=0.0, margin3=0.0,
            scale=4.0))
        want = float(F.cross_entropy(_t(logits) * 4.0, _t(label)))
        np.testing.assert_allclose(got, want, rtol=1e-4)
        # with a margin the loss must strictly increase
        harder = float(F.margin_cross_entropy(
            _t(logits), _t(label), margin2=0.5, scale=4.0))
        assert harder > got

    def test_lp_pool_padded_windows_not_overscaled(self):
        """Padding windows must use the true window SUM (divisor pinned to
        the kernel area), not an exclusive average times the area."""
        x = np.ones((1, 1, 4, 4), np.float32)
        got = F.lp_pool2d(_t(x), 1.0, 2, stride=2, padding=1).numpy()
        # corner window holds exactly one real element -> sum 1.0
        assert got[0, 0, 0, 0] == 1.0, got[0, 0]

    def test_margin_ce_column_labels_and_finite_grads(self):
        rng = np.random.RandomState(12)
        logits = (rng.rand(4, 6).astype(np.float32) * 2 - 1) * 0.9
        logits[0, 3] = 1.0  # exact-match cosine must not NaN the backward
        lab = rng.randint(0, 6, (4, 1)).astype(np.int32)
        lt = _t(logits)
        lt.stop_gradient = False
        loss = F.margin_cross_entropy(lt, _t(lab), margin2=0.3, scale=8.0)
        flat = float(F.margin_cross_entropy(_t(logits), _t(lab[:, 0]),
                                            margin2=0.3, scale=8.0))
        np.testing.assert_allclose(float(loss), flat, rtol=1e-5)
        loss.backward()
        assert np.isfinite(lt.grad.numpy()).all()

    def test_grid_sample_rejects_reflection(self):
        x = _t(np.ones((1, 1, 4, 4), np.float32))
        g = _t(np.zeros((1, 2, 2, 2), np.float32))
        with pytest.raises(NotImplementedError, match="reflection"):
            F.grid_sample(x, g, padding_mode="reflection")

    def test_feature_alpha_dropout_masks_whole_channels(self):
        paddle.seed(11)
        x = paddle.to_tensor(np.ones((2, 8, 4, 4), np.float32))
        out = F.feature_alpha_dropout(x, p=0.5, training=True).numpy()
        # each channel map is either all-original-scaled or all-alpha'd
        per_chan = out.reshape(2, 8, -1)
        assert all(np.unique(per_chan[b, c]).size == 1
                   for b in range(2) for c in range(8))
        same = F.feature_alpha_dropout(x, p=0.5, training=False)
        np.testing.assert_allclose(same.numpy(), x.numpy())


class TestNewLayerClasses:
    """Layer-class wrappers over the r4 functional batch + the norm/
    upsample family completions."""

    def test_layer_wrappers_match_functionals(self):
        import paddle_tpu.nn as nn
        rng = np.random.RandomState(13)
        x = _t(rng.randn(1, 4, 4, 4).astype(np.float32))
        np.testing.assert_allclose(
            nn.PixelUnshuffle(2)(x).numpy(),
            F.pixel_unshuffle(x, 2).numpy())
        np.testing.assert_allclose(
            nn.ThresholdedReLU(0.5)(x).numpy(),
            F.thresholded_relu(x, 0.5).numpy())
        np.testing.assert_allclose(
            nn.LogSigmoid()(x).numpy(), F.log_sigmoid(x).numpy())
        # align_corners bilinear vs the TORCH oracle (the functional used
        # to silently ignore align_corners — this pins the real contract)
        up = nn.UpsamplingBilinear2D(scale_factor=2)(x)
        want = TF.interpolate(torch.tensor(np.asarray(x.numpy())),
                              scale_factor=2, mode="bilinear",
                              align_corners=True).numpy()
        np.testing.assert_allclose(up.numpy(), want, rtol=1e-4, atol=1e-5)
        upn = nn.UpsamplingNearest2D(scale_factor=2)(x)
        np.testing.assert_allclose(
            upn.numpy(),
            F.interpolate(x, scale_factor=2, mode="nearest").numpy())

    def test_instance_norm_family(self):
        import paddle_tpu.nn as nn
        rng = np.random.RandomState(14)
        x1 = rng.randn(2, 3, 8).astype(np.float32)
        x3 = rng.randn(2, 3, 4, 4, 4).astype(np.float32)
        got1 = nn.InstanceNorm1D(3)(_t(x1)).numpy()
        want1 = TF.instance_norm(torch.tensor(x1)).numpy()
        np.testing.assert_allclose(got1, want1, rtol=1e-4, atol=1e-4)
        got3 = nn.InstanceNorm3D(3)(_t(x3)).numpy()
        want3 = TF.instance_norm(torch.tensor(x3)).numpy()
        np.testing.assert_allclose(got3, want3, rtol=1e-4, atol=1e-4)

    def test_dropout3d_and_feature_alpha_layers(self):
        import paddle_tpu.nn as nn
        paddle.seed(15)
        x = _t(np.ones((2, 4, 2, 2, 2), np.float32))
        d = nn.Dropout3D(0.5)
        d.train()
        out = d(x).numpy()
        per_chan = out.reshape(2, 4, -1)
        assert all(np.unique(per_chan[b, c]).size == 1
                   for b in range(2) for c in range(4))
        d.eval()
        np.testing.assert_allclose(d(x).numpy(), x.numpy())
        fa = nn.FeatureAlphaDropout(0.3)
        fa.eval()
        np.testing.assert_allclose(fa(_t(np.ones((1, 2, 3, 3),
                                               np.float32))).numpy(), 1.0)


class TestInterpolateFixes:
    def test_ncdhw_scale_factor_uses_true_spatial_dims(self):
        x = _t(np.random.RandomState(16).randn(1, 2, 3, 3, 3)
               .astype(np.float32))
        out = F.interpolate(x, scale_factor=2, mode="trilinear",
                            data_format="NCDHW")
        assert out.shape == [1, 2, 6, 6, 6], out.shape

    def test_trilinear_align_corners_matches_torch(self):
        x = np.random.RandomState(17).randn(1, 2, 3, 4, 5).astype(np.float32)
        got = F.interpolate(_t(x), scale_factor=2, mode="trilinear",
                            align_corners=True,
                            data_format="NCDHW").numpy()
        want = TF.interpolate(torch.tensor(x), scale_factor=2,
                              mode="trilinear", align_corners=True).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_bicubic_align_corners_rejected_not_silently_wrong(self):
        x = _t(np.ones((1, 1, 4, 4), np.float32))
        with pytest.raises(NotImplementedError, match="bicubic"):
            F.interpolate(x, scale_factor=2, mode="bicubic",
                          align_corners=True)


class TestRNNTLoss:
    """paddle.nn.functional.rnnt_loss (reference wraps warp-transducer †;
    here a log-semiring lattice DP) vs a brute-force numpy oracle."""

    @staticmethod
    def _np_rnnt(logits, label, T, U, blank=0):
        m = logits.max(-1, keepdims=True)
        lp = logits - (m + np.log(np.exp(logits - m).sum(-1, keepdims=True)))
        alpha = np.full((T, U + 1), -np.inf)
        alpha[0, 0] = 0.0

        def la(a, b):
            if a == -np.inf:
                return b
            if b == -np.inf:
                return a
            mm = max(a, b)
            return mm + np.log(np.exp(a - mm) + np.exp(b - mm))

        for t in range(T):
            for u in range(U + 1):
                if t == 0 and u == 0:
                    continue
                v = -np.inf
                if t > 0:
                    v = la(v, alpha[t - 1, u] + lp[t - 1, u, blank])
                if u > 0:
                    v = la(v, alpha[t, u - 1] + lp[t, u - 1, label[u - 1]])
                alpha[t, u] = v
        return -(alpha[T - 1, U] + lp[T - 1, U, blank])

    def test_matches_numpy_dp_ragged(self):
        rng = np.random.RandomState(0)
        B, T, U, V = 3, 6, 4, 8
        logits = rng.randn(B, T, U + 1, V).astype(np.float32)
        label = rng.randint(1, V, (B, U)).astype(np.int32)
        in_len = np.asarray([6, 5, 4], np.int32)
        lab_len = np.asarray([4, 3, 2], np.int32)
        want = [self._np_rnnt(logits[b, :in_len[b]], label[b],
                              int(in_len[b]), int(lab_len[b]))
                for b in range(B)]
        got = F.rnnt_loss(_t(logits), _t(label), _t(in_len), _t(lab_len),
                          reduction="none").numpy()
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-4)
        mean = float(F.rnnt_loss(_t(logits), _t(label), _t(in_len),
                                 _t(lab_len)))
        np.testing.assert_allclose(mean, np.mean(want), rtol=1e-4)

    def test_gradients_flow(self):
        rng = np.random.RandomState(1)
        logits = _t(rng.randn(2, 5, 4, 6).astype(np.float32))
        logits.stop_gradient = False
        loss = F.rnnt_loss(
            logits, _t(rng.randint(1, 6, (2, 3)).astype(np.int32)),
            _t(np.asarray([5, 4], np.int32)),
            _t(np.asarray([3, 2], np.int32)))
        loss.backward()
        g = logits.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).max() > 0

    @staticmethod
    def _np_rnnt_grad(logits, label, T, U, blank=0, lam=0.0):
        """Per-sample d(nll)/d(logits) with the FastEmit emit-branch scale
        (1+lam), via brute-force float64 alpha/beta occupancies."""
        lg = logits[:T].astype(np.float64)
        m = lg.max(-1, keepdims=True)
        lse = m + np.log(np.exp(lg - m).sum(-1, keepdims=True))
        lp = lg - lse

        def la(a, b):
            if a == -np.inf:
                return b
            if b == -np.inf:
                return a
            mm = max(a, b)
            return mm + np.log(np.exp(a - mm) + np.exp(b - mm))

        alpha = np.full((T, U + 1), -np.inf)
        alpha[0, 0] = 0.0
        for t in range(T):
            for u in range(U + 1):
                if t == 0 and u == 0:
                    continue
                v = -np.inf
                if t > 0:
                    v = la(v, alpha[t - 1, u] + lp[t - 1, u, blank])
                if u > 0:
                    v = la(v, alpha[t, u - 1] + lp[t, u - 1, label[u - 1]])
                alpha[t, u] = v
        beta = np.full((T, U + 1), -np.inf)
        beta[T - 1, U] = lp[T - 1, U, blank]
        for t in reversed(range(T)):
            for u in reversed(range(U + 1)):
                if t == T - 1 and u == U:
                    continue
                v = -np.inf
                if t + 1 < T:
                    v = la(v, lp[t, u, blank] + beta[t + 1, u])
                if u < U:
                    v = la(v, lp[t, u, label[u]] + beta[t, u + 1])
                beta[t, u] = v
        logZ = alpha[T - 1, U] + lp[T - 1, U, blank]
        np.testing.assert_allclose(beta[0, 0], logZ, rtol=1e-10)
        dlp = np.zeros_like(lp)
        for t in range(T):
            for u in range(U + 1):
                btop = 0.0 if (t, u) == (T - 1, U) else \
                    (beta[t + 1, u] if t + 1 < T else -np.inf)
                dlp[t, u, blank] -= np.exp(
                    alpha[t, u] + lp[t, u, blank] + btop - logZ)
                if u < U:
                    dlp[t, u, label[u]] -= (1.0 + lam) * np.exp(
                        alpha[t, u] + lp[t, u, label[u]]
                        + beta[t, u + 1] - logZ)
        dlogits = dlp - np.exp(lp) * dlp.sum(-1, keepdims=True)
        full = np.zeros_like(logits, dtype=np.float64)
        full[:T] = dlogits
        return full

    @pytest.mark.parametrize("lam", [
        # lam=0 is the fastemit-off degenerate (plain RNNT grad, already
        # pinned by test_gradients_flow); the reweighting case stays the
        # default rep
        pytest.param(0.0, marks=pytest.mark.slow),
        0.5,
    ])
    def test_fastemit_gradient_matches_bruteforce(self, lam):
        """VERDICT r4 weak 5: fastemit_lambda must actually reweight the
        emit-branch gradient by (1+lambda), not just sit in the
        signature. Pinned against explicit occupancy sums."""
        rng = np.random.RandomState(3)
        B, T, U, V = 3, 5, 3, 7
        logits = rng.randn(B, T, U + 1, V).astype(np.float32)
        label = rng.randint(1, V, (B, U)).astype(np.int32)
        in_len = np.asarray([5, 4, 3], np.int32)
        lab_len = np.asarray([3, 2, 1], np.int32)
        x = _t(logits)
        x.stop_gradient = False
        loss = F.rnnt_loss(x, _t(label), _t(in_len), _t(lab_len),
                           fastemit_lambda=lam, reduction="sum")
        loss.backward()
        want = np.stack([self._np_rnnt_grad(
            logits[b], label[b][:lab_len[b]], int(in_len[b]),
            int(lab_len[b]), lam=lam) for b in range(B)])
        np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-4,
                                   atol=1e-6)


class TestInterpolateModeParityR5:
    """nearest/bicubic/area vs the torch oracle (caught in r5: the
    jax.image.resize defaults diverge from the reference kernels —
    half-pixel-rounded nearest, Keys a=-0.5 cubic, and 'area' mapped to a
    linear resize)."""

    def _pair(self):
        rng = np.random.RandomState(7)
        img = rng.randn(1, 2, 6, 6).astype(np.float32)
        return img, torch.tensor(img)

    def test_nearest_trunc_indexing(self):
        img, ti = self._pair()
        for size in ([9, 11], [4, 3]):
            got = F.interpolate(_t(img), size=size, mode="nearest").numpy()
            exp = TF.interpolate(ti, size=size, mode="nearest").numpy()
            np.testing.assert_array_equal(got, exp)

    def test_bicubic_a075_kernel(self):
        img, ti = self._pair()
        for size in ([9, 11], [4, 3]):
            got = F.interpolate(_t(img), size=size, mode="bicubic",
                                align_corners=False).numpy()
            exp = TF.interpolate(ti, size=size, mode="bicubic",
                                 align_corners=False).numpy()
            np.testing.assert_allclose(got, exp, atol=1e-5, rtol=1e-5)

    def test_area_is_adaptive_avg(self):
        img, ti = self._pair()
        for size in ([9, 11], [3, 2]):
            got = F.interpolate(_t(img), size=size, mode="area").numpy()
            exp = TF.interpolate(ti, size=size, mode="area").numpy()
            np.testing.assert_allclose(got, exp, atol=1e-6)

    def test_area_channels_last(self):
        img, ti = self._pair()
        got = F.interpolate(_t(img.transpose(0, 2, 3, 1)), size=[3, 2],
                            mode="area", data_format="NHWC").numpy()
        exp = TF.interpolate(ti, size=[3, 2], mode="area").numpy()
        np.testing.assert_allclose(got.transpose(0, 3, 1, 2), exp, atol=1e-6)

    def test_nearest_align_corners_rounds(self):
        # align_corners=True nearest: round(i * (n-1)/(out-1))
        x = np.arange(4, dtype=np.float32).reshape(1, 1, 4)
        got = F.interpolate(_t(x), size=[7], mode="nearest",
                            align_corners=True, data_format="NCW").numpy()
        np.testing.assert_array_equal(got[0, 0], [0, 1, 1, 2, 2, 3, 3])

    def test_area_1d(self):
        x = np.random.RandomState(3).randn(2, 3, 10).astype(np.float32)
        got = F.interpolate(_t(x), size=[4], mode="area",
                            data_format="NCW").numpy()
        exp = TF.interpolate(torch.tensor(x), size=4, mode="area").numpy()
        np.testing.assert_allclose(got, exp, atol=1e-6)

    def test_size_rank_mismatch_raises(self):
        x = np.zeros((1, 2, 6, 6), np.float32)
        with pytest.raises(ValueError, match="spatial dim"):
            F.interpolate(_t(x), size=[9], mode="nearest")

    def test_area_size_rank_mismatch_raises(self):
        # area skipped the rank-vs-size validation the other resize paths
        # run; a 1-elem size on a 2-spatial-dim input selected pool1d and
        # crashed (or pooled the wrong dims) instead of naming the problem
        x = np.zeros((1, 2, 6, 6), np.float32)
        with pytest.raises(ValueError, match="spatial dim"):
            F.interpolate(_t(x), size=[9], mode="area")
        with pytest.raises(ValueError, match="spatial dim"):
            F.interpolate(_t(np.zeros((1, 6, 6, 2), np.float32)),
                          size=[3, 3, 3], mode="area", data_format="NHWC")


class TestConvPaddingFormsR5:
    """Reference conv padding forms (caught in r5: the flat-2*spatial
    branch intercepted pair-of-pairs input and crashed, and the full
    per-tensor-dim form ignored channel-last layouts)."""

    def _xw(self):
        rng = np.random.RandomState(17)
        return (rng.randn(2, 4, 9, 9).astype(np.float32),
                rng.randn(6, 2, 3, 3).astype(np.float32))

    def test_pair_of_pairs_nchw(self):
        x, w = self._xw()
        got = F.conv2d(_t(x), _t(w), None,
                       padding=[[0, 0], [0, 0], [1, 2], [2, 1]],
                       groups=2).numpy()
        exp = TF.conv2d(TF.pad(torch.tensor(x), (2, 1, 1, 2)),
                        torch.tensor(w), None, groups=2).numpy()
        np.testing.assert_allclose(got, exp, atol=2e-4, rtol=1e-3)

    def test_pair_of_pairs_nhwc_uses_spatial_positions(self):
        x, w = self._xw()
        got = F.conv2d(_t(x.transpose(0, 2, 3, 1)), _t(w), None,
                       padding=[[0, 0], [1, 2], [2, 1], [0, 0]],
                       groups=2, data_format="NHWC").numpy()
        exp = TF.conv2d(TF.pad(torch.tensor(x), (2, 1, 1, 2)),
                        torch.tensor(w), None,
                        groups=2).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(got, exp, atol=2e-4, rtol=1e-3)

    def test_flat_asymmetric(self):
        x, w = self._xw()
        got = F.conv2d(_t(x), _t(w), None, padding=[1, 2, 2, 1],
                       groups=2).numpy()
        exp = TF.conv2d(TF.pad(torch.tensor(x), (2, 1, 1, 2)),
                        torch.tensor(w), None, groups=2).numpy()
        np.testing.assert_allclose(got, exp, atol=2e-4, rtol=1e-3)


class TestChannelsLastConvPoolR5:
    """Channels-last data_format across conv/pool families vs torch
    (caught in r5: conv1d/conv3d/transposes/pools parsed channel-last
    padding but computed channels-first on the raw layout — NLC/NDHWC/NHWC
    inputs produced garbage). Plus the fro-axis spectral-norm fix and the
    asymmetric ceil_mode span."""

    def setup_method(self):
        self.rng = np.random.RandomState(19)

    def test_conv1d_nlc(self):
        x = self.rng.randn(2, 3, 11).astype(np.float32)
        w = self.rng.randn(5, 3, 4).astype(np.float32)
        got = F.conv1d(_t(x.transpose(0, 2, 1)), _t(w), None, stride=2,
                       padding=1, data_format="NLC").numpy()
        exp = TF.conv1d(torch.tensor(x), torch.tensor(w), None, 2,
                        1).numpy().transpose(0, 2, 1)
        np.testing.assert_allclose(got, exp, atol=2e-4, rtol=1e-3)

    def test_conv3d_ndhwc(self):
        x = self.rng.randn(1, 2, 5, 6, 7).astype(np.float32)
        w = self.rng.randn(4, 2, 2, 3, 2).astype(np.float32)
        got = F.conv3d(_t(x.transpose(0, 2, 3, 4, 1)), _t(w), None,
                       padding=1, data_format="NDHWC").numpy()
        exp = TF.conv3d(torch.tensor(x), torch.tensor(w), None,
                        padding=1).numpy().transpose(0, 2, 3, 4, 1)
        np.testing.assert_allclose(got, exp, atol=2e-4, rtol=1e-3)

    def test_conv2d_transpose_nhwc(self):
        x = self.rng.randn(2, 6, 5, 5).astype(np.float32)
        w = self.rng.randn(6, 2, 3, 3).astype(np.float32)
        got = F.conv2d_transpose(
            _t(x.transpose(0, 2, 3, 1)), _t(w), None, stride=2, padding=1,
            output_padding=1, groups=2, data_format="NHWC").numpy()
        exp = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w), None, 2,
                                  1, 1, 2).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(got, exp, atol=2e-4, rtol=1e-3)

    def test_max_pool2d_nhwc_with_mask(self):
        x = self.rng.randn(2, 4, 8, 8).astype(np.float32)
        o, m = F.max_pool2d(_t(x.transpose(0, 2, 3, 1)), 2, stride=2,
                            return_mask=True, data_format="NHWC")
        to, tm = TF.max_pool2d(torch.tensor(x), 2, 2, return_indices=True)
        np.testing.assert_allclose(o.numpy(),
                                   to.numpy().transpose(0, 2, 3, 1))
        np.testing.assert_array_equal(m.numpy(),
                                      tm.numpy().transpose(0, 2, 3, 1))

    def test_avg_pool3d_ndhwc(self):
        x = self.rng.randn(1, 3, 6, 7, 8).astype(np.float32)
        got = F.avg_pool3d(_t(x.transpose(0, 2, 3, 4, 1)), 2, stride=2,
                           data_format="NDHWC").numpy()
        exp = TF.avg_pool3d(torch.tensor(x), 2,
                            2).numpy().transpose(0, 2, 3, 4, 1)
        np.testing.assert_allclose(got, exp, atol=2e-4, rtol=1e-3)

    def test_ceil_mode_asymmetric_pad(self):
        # span 6+1+0-2=5 -> ceil gives 4 windows; the symmetric-pad formula
        # (span 6) would produce 3
        x = self.rng.randn(1, 1, 6, 6).astype(np.float32)
        out = F.max_pool2d(_t(x), 2, stride=2,
                           padding=[[0, 0], [0, 0], [1, 0], [1, 0]],
                           ceil_mode=True)
        assert tuple(out.shape) == (1, 1, 4, 4)

    def test_fro_with_axis_is_frobenius(self):
        m = np.float32([[3, 0], [0, 4]])
        got = float(paddle.linalg.norm(_t(m), "fro", axis=[0, 1]).numpy())
        assert abs(got - 5.0) < 1e-5  # spectral would give 4.0

    def test_nonzero_channel_pad_rejected(self):
        x = np.zeros((1, 2, 4, 4), np.float32)
        w = np.zeros((2, 2, 3, 3), np.float32)
        with pytest.raises(ValueError, match="batch/channel"):
            F.conv2d(_t(x), _t(w), None,
                     padding=[[0, 0], [3, 3], [1, 1], [1, 1]])
