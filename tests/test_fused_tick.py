"""One-kernel decode (engine ``fused_tick=True`` +
``collective_overlap=True``, README "One-kernel decode"): the whole
per-token decode tick — every layer's norms, projections, paged
table-indirect attention, SwiGLU, the final norm/head/sampling — runs
as ONE ``pallas_call`` with the layer loop as a grid dimension, and
the TP per-layer all-reduce pair overlaps with compute as a chunked
reduce-scatter/all-gather schedule. The load-bearing properties:

- **Transparency**: fused streams are BYTE-IDENTICAL to the scanned
  baseline — greedy AND seeded-sampled, cold/hit/chunked, int8/fp8 KV,
  multi-tick, TP=2, across preempt/restore — and overlapped TP=2
  streams equal BOTH the TP=1 and the non-overlapped TP=2 baselines.
- **Launch census**: the claim is PINNED structurally, not vibes — a
  jaxpr census of the multi-tick while body counts exactly 1
  ``pallas_call`` fused vs >= num_layers scanned, surfaced through
  ``/debug/profile``.
- **Compile-once**: ``decode_compilations() == 1`` INCLUSIVE of the
  ``fk`` tag (and ``fk`` x ``tpN`` x ``kv8f``/``a8``); the ``ov`` tag
  keys the overlapped schedule apart in a shared jit cache.
- **Exact accounting**: the overlapped schedule moves the same wire
  payload — ``serving_collective_bytes_total{dtype}`` stays exact to
  the byte in both wire dtypes.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.profiler.cost import CostObservatory
from paddle_tpu.serving import ContinuousBatchingEngine, GenerationRequest
from paddle_tpu.serving.server.gateway import ServingGateway

BS = 8      # block size
CHUNK = 16  # 2 blocks per chunk
SLOTS = 2
S_MAX = 96


@pytest.fixture(scope="module")
def model():
    # llama_tiny defaults decode_attention="pallas": fused_tick takes
    # the TRUE mega-kernel path (single pallas_call, interpret on CPU)
    paddle.seed(33)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


@pytest.fixture(scope="module")
def jnp_model():
    # the jnp-attention oracle route: fused_decode_tick dispatches to
    # the reference replay, byte-identical by construction — pinned
    # here so BOTH dispatch arms stay covered
    paddle.seed(33)
    cfg = llama_tiny()
    cfg.decode_attention = "jnp"
    return LlamaForCausalLM(cfg)


def _jit(model, tag):
    return model.__dict__.setdefault(f"_serving_jit_fused_{tag}", {})


def _engine(model, **kw):
    kw.setdefault("num_slots", SLOTS)
    kw.setdefault("max_seq_len", S_MAX)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    return ContinuousBatchingEngine(model, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _req(ps, n=12, **kw):
    kw.setdefault("max_new_tokens", 5)
    return GenerationRequest(prompt=_prompt(ps, n), **kw)


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             eos_token_id=r.eos_token_id, seed=r.seed)


#: the hit/miss/chunked matrix: greedy shorts, a seeded-sampled row,
#: and a long prompt that chunks (40 > CHUNK)
def _traffic():
    return [_req(1), _req(2, n=10),
            _req(3, temperature=0.9, top_k=5, seed=123),
            _req(4, n=40, max_new_tokens=4)]


def _run_matrix(model, jit, **kw):
    """Two passes of the traffic (pass 2 = trie hits on pass 1's
    donated chains) through one engine; returns (streams, engine)."""
    eng = _engine(model, prefix_cache=True, jit_cache=jit, **kw)
    outs = [o.tolist() for o in eng.generate(_traffic())]
    outs += [o.tolist() for o in
             eng.generate([_clone(r) for r in _traffic()])]
    return outs, eng


def _run_once(model, jit, **kw):
    """One cold pass of the traffic; returns (streams, engine)."""
    eng = _engine(model, prefix_cache=True, jit_cache=jit, **kw)
    return [o.tolist() for o in eng.generate(_traffic())], eng


# ----------------------------------------------------------- transparency
class TestFusedByteIdentity:
    def test_fused_matrix_byte_identical_and_compile_once(self, model):
        """THE tentpole pin: the single-pallas_call fused tick streams
        byte-for-byte equal to the scanned baseline — greedy AND
        seeded-sampled, cold/hit/chunked — with
        ``decode_compilations() == 1`` on BOTH engines (one shared jit
        cache; the fk tag keys the fused trace apart, so neither
        engine's pin sees the other's programs)."""
        jit = _jit(model, "fp")
        base, e1 = _run_matrix(model, jit)
        fused, e2 = _run_matrix(model, jit, fused_tick=True)
        assert fused == base
        assert e1.decode_compilations() == 1
        assert e2.decode_compilations() == 1
        assert e2.prefill_compilations() >= 1
        assert e2.fused_tick is True and e1.fused_tick is False

    @pytest.mark.slow  # 5 s + fixture: the jnp-attention oracle arm
    # (870s cap); the default matrix rep drives the TRUE kernel, and
    # the oracle is the construction both routes are pinned against
    def test_fused_oracle_route_byte_identical(self, jnp_model):
        """decode_attention="jnp" routes fused_decode_tick to the
        reference replay (the oracle arm): streams still equal the
        scanned baseline and the compile pin still holds."""
        jit = _jit(jnp_model, "jnp")
        base, _ = _run_once(jnp_model, jit)
        fused, e2 = _run_once(jnp_model, jit, fused_tick=True)
        assert fused == base
        assert e2.decode_compilations() == 1

    def test_fused_multitick_byte_identical(self, model):
        """The fused program slots into the multi-tick while body:
        fused x decode_ticks=4 equals scanned x decode_ticks=4 (which
        is itself pinned equal to single-tick)."""
        jit = _jit(model, "fp")
        base, _ = _run_once(model, jit, decode_ticks=4)
        fused, e2 = _run_once(model, jit, decode_ticks=4,
                              fused_tick=True)
        assert fused == base
        assert e2.decode_compilations() == 1

    @pytest.mark.slow  # 7 s quant duplicate (870s cap): the matrix +
    # multi-tick reps above run the fused kernel by default; the kv8f
    # x fk compile pin also rides the AST key-discipline sweep
    def test_fused_fp8_kv_byte_identical(self, model):
        """fp8 KV dequantizes IN-KERNEL on the fused path (no
        host-side dequant launch): streams equal the scanned fp8-KV
        engine, compile-once inclusive of kv8f + fk."""
        jit = _jit(model, "kv8f")
        base, _ = _run_matrix(model, jit, kv_dtype="fp8")
        fused, e2 = _run_matrix(model, jit, kv_dtype="fp8",
                                fused_tick=True)
        assert fused == base
        assert e2.decode_compilations() == 1

    @pytest.mark.slow  # 12 s matrix duplicate: the fp8 rep above runs
    # by default (870s cap); int8 adds the scale-plane dequant arm
    def test_fused_int8_kv_byte_identical(self, model):
        jit = _jit(model, "kv8")
        base, _ = _run_matrix(model, jit, kv_dtype="int8")
        fused, e2 = _run_matrix(model, jit, kv_dtype="int8",
                                fused_tick=True)
        assert fused == base
        assert e2.decode_compilations() == 1

    @pytest.mark.slow  # 14 s matrix duplicate: the overlap tests below
    # run fused x tp2 by default (870s cap)
    def test_fused_tp2_byte_identical(self, model):
        """Sharded fused engine (the TP oracle route — in-kernel
        collectives are the remote-DMA follow-on) equals the TP=1
        scanned baseline."""
        jit = _jit(model, "fp")
        base, _ = _run_matrix(model, jit)
        tp2, e2 = _run_matrix(model, jit, tp=2, fused_tick=True)
        assert tp2 == base
        assert e2.decode_compilations() == 1

    def test_fused_preempt_restore_byte_identical(self, model):
        """Mid-decode evict + restore on a fused engine: the chain
        donates to the trie, recompute readmits as a trie hit through
        the fused program, and the continuation equals the
        uninterrupted scanned baseline."""
        jit = _jit(model, "fp")
        reqs = _traffic()
        base = [o.tolist() for o in
                _engine(model, prefix_cache=True, jit_cache=jit)
                .generate([_clone(r) for r in reqs])]
        eng = _engine(model, prefix_cache=True, jit_cache=jit,
                      fused_tick=True)
        seqs = [eng.submit(_clone(r)) for r in reqs]
        for _ in range(3):
            eng.step()
        victim = next(s for s in seqs if s.status == "running")
        assert eng.evict(victim)
        eng.restore(victim)
        while eng.has_work():
            eng.step()
        assert [list(s.output_ids()) for s in seqs] == base
        assert eng.decode_compilations() == 1


# ------------------------------------------------- compute/collective overlap
class TestCollectiveOverlap:
    @pytest.mark.parametrize("dtype", [
        "fp",
        # 10 s wire-dtype duplicate (870s cap): fp is the default rep;
        # the int8 wire format itself is pinned by test_tp's ledger
        pytest.param("int8", marks=pytest.mark.slow)])
    def test_overlap_byte_identical_and_ledger_exact(self, model, dtype):
        """The overlap acceptance pin, both wire dtypes: overlapped
        TP=2 streams equal BOTH the TP=1 baseline and the
        non-overlapped TP=2 engine (greedy AND seeded-sampled), the
        ``serving_collective_bytes_total{dtype}`` ledger is byte-equal
        to the non-overlapped run's (whose exactness test_tp pins
        against the closed-form wire model), and the jaxpr census
        proves the schedule really changed — the overlapped decode
        program carries MORE collective eqns (chunked ppermute
        reduce-scatter/all-gather) than the plain all-reduce pair."""
        jit = _jit(model, f"ovl_{dtype}")
        base, _ = _run_once(model, jit)
        co_p, co_o = CostObservatory(), CostObservatory()
        e_p = _engine(model, prefix_cache=True, jit_cache=jit, tp=2,
                      collective_dtype=dtype)
        e_p.cost = co_p
        plain = [o.tolist() for o in e_p.generate(_traffic())]
        e_o = _engine(model, prefix_cache=True, jit_cache=jit, tp=2,
                      collective_dtype=dtype, collective_overlap=True)
        e_o.cost = co_o
        over = [o.tolist() for o in e_o.generate(_traffic())]
        assert plain == base
        assert over == base
        assert e_p.decode_compilations() == 1
        assert e_o.decode_compilations() == 1
        assert e_o.collective_overlap is True
        # ledger exact to the byte: identical op/byte totals, nonzero
        led_p = co_p.snapshot_full()["collectives"]
        led_o = co_o.snapshot_full()["collectives"]
        assert led_o == led_p
        assert led_o[dtype]["bytes"] > 0 and led_o[dtype]["ops"] > 0
        # the knob is not a no-op: census the decode programs
        cen_p = [c for k, c in co_p.snapshot_full()["censuses"].items()
                 if "ragged" in str(k) or "mtick" in str(k)]
        cen_o = [c for k, c in co_o.snapshot_full()["censuses"].items()
                 if "ragged" in str(k) or "mtick" in str(k)]
        assert cen_p and cen_o
        assert cen_o[0]["collectives"] > cen_p[0]["collectives"]

    @pytest.mark.slow  # 9 s composition duplicate (870s cap): the
    # overlap[fp] + fused-multitick reps above cover both arms default
    def test_overlap_composes_with_fused_multitick(self, model):
        """Full stack: fused_tick x tp=2 x collective_overlap x
        decode_ticks=4 streams equal the scanned single-chip
        decode_ticks=4 baseline, compile-once inclusive of the
        (tp2, dtype, ov) + fk key tail."""
        jit = _jit(model, "stack")
        base, _ = _run_once(model, jit, decode_ticks=4)
        full, e2 = _run_once(model, jit, decode_ticks=4, tp=2,
                             fused_tick=True, collective_overlap=True)
        assert full == base
        assert e2.decode_compilations() == 1
        assert e2.fused_tick and e2.collective_overlap


# ------------------------------------------------------------ launch census
class TestLaunchCensus:
    def test_census_pins_fused_one_launch_scanned_layers(self, model):
        """The structural pin behind the headline: census the
        multi-tick while body (= launches per decode tick). Scanned:
        >= num_layers pallas_calls. Fused: EXACTLY 1. The census rides
        the observatory export, so ``/debug/profile`` program entries
        carry it."""
        L = model.config.num_hidden_layers

        def census_of(co, frag):
            cs = co.snapshot_full()["censuses"]
            keys = [k for k in cs if frag in str(k)]
            assert keys, (frag, list(cs))
            return cs[keys[0]]

        jit = _jit(model, "census")
        co_s, co_f = CostObservatory(), CostObservatory()
        for co, kw in ((co_s, {}), (co_f, dict(fused_tick=True))):
            eng = _engine(model, jit_cache=jit, decode_ticks=4, **kw)
            eng.cost = co
            eng.generate([_req(17, max_new_tokens=6)])
            # export surfaces the census on the program entry — the
            # /debug/profile document is built from this export
            ent = [p for p in co.export()["programs"]
                   if "mtick" in str(p.get("program"))]
            assert ent and ent[0].get("census") is not None
        scanned = census_of(co_s, "mtick")["loop_bodies"][-1]
        fused = census_of(co_f, "mtick")["loop_bodies"][-1]
        assert scanned["pallas_calls"] >= L
        assert fused["pallas_calls"] == 1

    def test_profile_doc_surfaces_census(self, model):
        """A gateway-owned observatory flows the census into
        ``/debug/profile``: program entries carry the launch counts."""
        jit = _jit(model, "fp")
        gw = ServingGateway(
            _engine(model, prefix_cache=True, jit_cache=jit,
                    fused_tick=True),
            max_queue=8, start=False)
        st = gw.submit(_req(19))
        gw.start()
        st.result()
        doc = gw.profile_doc()
        cens = [p["census"] for p in doc["programs"]
                if p.get("census") is not None]
        assert cens
        assert all({"pallas_calls", "collectives",
                    "loop_bodies"} <= set(c) for c in cens)
        gw.shutdown(drain=True, timeout=30)


# ------------------------------------------------------ jit keys / validation
class TestJitKeysAndValidation:
    @pytest.mark.slow  # 6 s key-shape duplicate (870s cap): the AST
    # sweep (test_cost_observatory) pins the fk/ov tag sites, and the
    # compile-once asserts on every default rep pin the key behavior
    def test_jit_keys_carry_fk_and_ov_tags(self, model):
        """The fk tag joins the decode jit keys LAST (after kv8f/a8/
        tpN) and the ov marker rides the tp tag — while knobs-off keys
        stay byte-identical to the pre-fused spelling (banked baselines
        can't have drifted)."""
        jit = {}
        e1 = _engine(model, jit_cache=jit)
        e1.generate([_req(11, max_new_tokens=2)])
        keys1 = set(jit)
        assert all("fk" not in k and "ov" not in k for k in keys1)
        e2 = _engine(model, jit_cache=jit, fused_tick=True)
        e2.generate([_req(11, max_new_tokens=2)])
        keys2 = set(jit) - keys1
        assert keys2 and all(k[-1] == "fk" for k in keys2)
        assert e1.decode_compilations() == 1
        assert e2.decode_compilations() == 1
        e3 = _engine(model, jit_cache=jit, tp=2,
                     collective_overlap=True)
        e3.generate([_req(11, max_new_tokens=2)])
        keys3 = set(jit) - keys1 - keys2
        assert keys3
        decode3 = [k for k in keys3 if "tp2" in k]
        assert decode3 and all("ov" in k for k in decode3)
        assert e3.decode_compilations() == 1

    @pytest.mark.slow  # 8 s geometry duplicate (870s cap): every
    # default rep asserts decode_compilations()==1 on its own geometry
    def test_compile_once_fused_quant_tp_geometries(self, model):
        """The acceptance's hardest compile pin: fk x tp2 x kv8f and
        fk x tp2 x w8+a8 each trace their decode program exactly
        once."""
        e1 = _engine(model, jit_cache=_jit(model, "fk_kv8f"), tp=2,
                     kv_dtype="fp8", fused_tick=True)
        e1.generate([_req(21, max_new_tokens=3)])
        assert e1.decode_compilations() == 1
        e2 = _engine(model, jit_cache=_jit(model, "fk_a8"), tp=2,
                     quantize_weights=True, quantize_activations=True,
                     fused_tick=True)
        e2.generate([_req(22, max_new_tokens=3)])
        assert e2.decode_compilations() == 1

    def test_fused_requires_ragged_paged(self, model):
        with pytest.raises(ValueError, match="unified ragged paged"):
            _engine(model, fused_tick=True, paged_attn=False)
        with pytest.raises(ValueError, match="unified ragged paged"):
            _engine(model, fused_tick=True, ragged_step=False)

    def test_fused_spec_error_enumerates_knobs(self, model):
        """fused x spec is rejected with the COMPATIBLE knob set
        spelled out (the error is documentation)."""
        with pytest.raises(ValueError,
                           match="fused_tick composes with") as ei:
            _engine(model, fused_tick=True, spec_decode=True, spec_k=2)
        msg = str(ei.value)
        for knob in ("prefix_cache", "decode_ticks", "kv_dtype", "tp",
                     "collective_overlap", "priority_classes"):
            assert knob in msg

    def test_multitick_spec_error_enumerates_knobs(self, model):
        """The --decode-ticks x spec_decode error names every
        compatible knob — fused_tick and collective_overlap
        included — so the CLI failure is self-documenting."""
        with pytest.raises(ValueError,
                           match="incompatible with spec_decode") as ei:
            _engine(model, decode_ticks=4, spec_decode=True, spec_k=2)
        msg = str(ei.value)
        for knob in ("fused_tick", "collective_overlap", "paged_attn",
                     "ragged_step", "prefix_cache", "kv_dtype", "tp",
                     "priority_classes"):
            assert knob in msg

    def test_overlap_requires_tp(self, model):
        with pytest.raises(ValueError, match="requires tp > 1"):
            _engine(model, collective_overlap=True)

    def test_fleet_geometry_grows_fused_and_overlap(self, model):
        """(fused_tick, collective_overlap) join the fleet geometry
        tuple — same memory-note discipline as the tp/kv8 tags."""
        from paddle_tpu.serving.fleet import EngineFleet
        model.__dict__.pop("_serving_jit_fleet", None)
        fleet = EngineFleet(model, replicas=1, num_slots=SLOTS,
                            max_seq_len=S_MAX, prefill_chunk=CHUNK,
                            prefix_block_size=BS, fused_tick=True,
                            start=False)
        (geom,) = model.__dict__["_serving_jit_fleet"].keys()
        assert geom[-2:] == (True, False)
        eng = fleet.replicas[0].gateway.engine
        assert eng.fused_tick is True and eng.collective_overlap is False
        fleet.shutdown(drain=False, timeout=5)
