"""LLaMA autoregressive generation tests (L7 decode path, SURVEY §3.5):
the jit-compiled KV-cache decode loop must reproduce the full-forward
greedy continuation token for token, GQA included."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def _greedy_oracle(m, ids, n):
    cur = ids.copy()
    out = []
    for _ in range(n):
        logits = m(paddle.to_tensor(cur)).numpy()
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        out.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], 1)
    return np.stack(out, 1)


class TestLlamaGenerate:
    @pytest.mark.slow  # the MHA twin below is the default-run rep for
    # generate-vs-full-forward parity; GQA decode stays pinned by
    # default via test_decode's prefill+decode-vs-full parity
    def test_greedy_matches_full_forward_gqa(self):
        paddle.seed(11)
        m = LlamaForCausalLM(llama_tiny())  # nkv=2 < nh=4: GQA decode
        ids = np.random.RandomState(0).randint(0, 256, (2, 12)).astype(np.int32)
        oracle = _greedy_oracle(m, ids, 8)
        got = m.generate(paddle.to_tensor(ids), max_new_tokens=8).numpy()
        np.testing.assert_array_equal(got, oracle)

    @pytest.mark.slow  # 12 s full-forward duplicate: the GQA variant above is
    # the stricter default rep (870s cap)
    def test_greedy_matches_full_forward_mha(self):
        paddle.seed(12)
        m = LlamaForCausalLM(llama_tiny(num_key_value_heads=4))
        ids = np.random.RandomState(1).randint(0, 256, (1, 6)).astype(np.int32)
        oracle = _greedy_oracle(m, ids, 6)
        got = m.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
        np.testing.assert_array_equal(got, oracle)

    def test_sampling_reproducible_and_in_vocab(self):
        paddle.seed(13)
        m = LlamaForCausalLM(llama_tiny())
        ids = np.random.RandomState(2).randint(0, 256, (2, 8)).astype(np.int32)
        a = m.generate(paddle.to_tensor(ids), max_new_tokens=5,
                       temperature=0.8, top_k=10, seed=42).numpy()
        b = m.generate(paddle.to_tensor(ids), max_new_tokens=5,
                       temperature=0.8, top_k=10, seed=42).numpy()
        np.testing.assert_array_equal(a, b)  # same seed, same tokens
        assert (a >= 0).all() and (a < 256).all()

    @pytest.mark.slow  # cache-length clamping also pinned (fast) by
    # serving submit validation + model_generate_shares_decode_program
    def test_cache_shorter_than_max_positions(self):
        paddle.seed(14)
        m = LlamaForCausalLM(llama_tiny())
        ids = np.random.RandomState(3).randint(0, 256, (1, 4)).astype(np.int32)
        oracle = _greedy_oracle(m, ids, 4)
        got = m.generate(paddle.to_tensor(ids), max_new_tokens=4,
                         max_cache_len=16).numpy()
        np.testing.assert_array_equal(got, oracle)

    def test_cache_overflow_rejected(self):
        paddle.seed(15)
        m = LlamaForCausalLM(llama_tiny())
        ids = np.random.RandomState(4).randint(0, 256, (1, 8)).astype(np.int32)
        with pytest.raises(ValueError, match="KV cache"):
            m.generate(paddle.to_tensor(ids), max_new_tokens=10,
                       max_cache_len=10)

    def test_jit_cache_reused(self):
        import time
        paddle.seed(16)
        m = LlamaForCausalLM(llama_tiny())
        ids = np.random.RandomState(5).randint(0, 256, (1, 8)).astype(np.int32)
        t = paddle.to_tensor(ids)
        m.generate(t, max_new_tokens=4)  # compile
        t0 = time.perf_counter()
        m.generate(t, max_new_tokens=4)
        warm = time.perf_counter() - t0
        assert warm < 0.5, f"second call took {warm:.2f}s - jit not cached"
