"""paddle.hub / paddle.text / paddle.onnx surface tests (reference:
``python/paddle/hapi/hub.py`` †, ``python/paddle/text/`` †)."""
import itertools
import os

import numpy as np
import pytest

import paddle_tpu as paddle


class TestHub:
    @pytest.fixture()
    def repo(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny_mlp(hidden=4):\n"
            "    'A tiny MLP entrypoint.'\n"
            "    import paddle_tpu as paddle\n"
            "    return paddle.nn.Linear(2, hidden)\n"
            "def _private():\n"
            "    return None\n")
        return str(tmp_path)

    def test_list_skips_private(self, repo):
        assert paddle.hub.list(repo, source="local") == ["tiny_mlp"]

    def test_help_and_load(self, repo):
        assert "tiny MLP" in paddle.hub.help(repo, "tiny_mlp",
                                             source="local")
        m = paddle.hub.load(repo, "tiny_mlp", source="local", hidden=3)
        out = m(paddle.to_tensor(np.ones((1, 2), np.float32)))
        assert out.shape == [1, 3]

    def test_remote_sources_gated(self):
        with pytest.raises(RuntimeError, match="local"):
            paddle.hub.load("user/repo", "model")

    def test_missing_entrypoint(self, repo):
        with pytest.raises(ValueError, match="tiny_mlp"):
            paddle.hub.load(repo, "nope", source="local")


class TestText:
    def test_viterbi_matches_brute_force(self):
        rng = np.random.RandomState(0)
        B, T, N = 2, 5, 3
        pot = rng.rand(B, T, N).astype(np.float32)
        trans = rng.rand(N, N).astype(np.float32)
        score, path = paddle.text.viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans))
        for b in range(B):
            def total(p):
                return pot[b, 0, p[0]] + sum(
                    trans[p[i - 1], p[i]] + pot[b, i, p[i]]
                    for i in range(1, T))
            best = max(itertools.product(range(N), repeat=T), key=total)
            assert tuple(np.asarray(path.value)[b]) == best
            np.testing.assert_allclose(float(np.asarray(score.value)[b]),
                                       total(best), rtol=1e-5)

    def test_viterbi_decoder_layer(self):
        rng = np.random.RandomState(1)
        dec = paddle.text.ViterbiDecoder(
            paddle.to_tensor(rng.rand(3, 3).astype(np.float32)))
        score, path = dec(paddle.to_tensor(rng.rand(1, 4, 3).astype(np.float32)))
        assert path.shape == [1, 4]

    def test_viterbi_lengths_mask_padding(self):
        rng = np.random.RandomState(2)
        pot = rng.rand(2, 5, 3).astype(np.float32)
        trans = rng.rand(3, 3).astype(np.float32)
        lens = np.array([3, 5], np.int64)
        score, path = paddle.text.viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans),
            paddle.to_tensor(lens))
        p = np.asarray(path.value)
        # padded tail zeroed
        assert (p[0, 3:] == 0).all()
        # batch-0 decode == unpadded decode of its first 3 steps
        s0, p0 = paddle.text.viterbi_decode(
            paddle.to_tensor(pot[:1, :3]), paddle.to_tensor(trans))
        np.testing.assert_array_equal(p[0, :3], np.asarray(p0.value)[0])
        np.testing.assert_allclose(float(np.asarray(score.value)[0]),
                                   float(np.asarray(s0.value)[0]), rtol=1e-5)

    def test_viterbi_bos_eos_brute_force(self):
        rng = np.random.RandomState(3)
        T, N = 4, 4  # last tag = BOS, second-to-last = EOS
        pot = rng.rand(1, T, N).astype(np.float32)
        trans = rng.rand(N, N).astype(np.float32)
        score, path = paddle.text.viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans),
            include_bos_eos_tag=True)

        def total(p):
            s = trans[N - 1, p[0]] + pot[0, 0, p[0]]
            for i in range(1, T):
                s += trans[p[i - 1], p[i]] + pot[0, i, p[i]]
            return s + trans[p[-1], N - 2]
        best = max(itertools.product(range(N), repeat=T), key=total)
        assert tuple(np.asarray(path.value)[0]) == best
        np.testing.assert_allclose(float(np.asarray(score.value)[0]),
                                   total(best), rtol=1e-5)

    def test_datasets_gated_offline(self):
        for name in ["Imdb", "Conll05st", "UCIHousing", "WMT14"]:
            with pytest.raises(RuntimeError, match="network egress"):
                getattr(paddle.text, name)()


class TestOnnx:
    def test_export_guides_to_jit(self):
        with pytest.raises(NotImplementedError, match="jit"):
            paddle.onnx.export(None, "/tmp/x")
