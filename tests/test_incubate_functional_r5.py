"""Round-5 incubate.nn.functional completions (reference:
``python/paddle/incubate/nn/functional/`` †): functional forms of the
fused attention/FFN blocks, packed-qkv flash, fused_matmul_bias, varlen
memory-efficient attention, and the masked_multihead_attention decode
op — each pinned against the corresponding layer or a manual oracle."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as IF


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestFusedFunctionals:
    def test_fused_matmul_bias(self):
        rng = np.random.RandomState(0)
        x = rng.randn(3, 4).astype(np.float32)
        y = rng.randn(4, 5).astype(np.float32)
        b = rng.randn(5).astype(np.float32)
        np.testing.assert_allclose(
            IF.fused_matmul_bias(_t(x), _t(y), _t(b)).numpy(),
            x @ y + b, rtol=1e-5)
        np.testing.assert_allclose(
            IF.fused_matmul_bias(_t(x.T), _t(y), transpose_x=True).numpy(),
            x @ y, rtol=1e-5)

    def test_flash_attn_qkvpacked_matches_unpacked(self):
        rng = np.random.RandomState(1)
        qkv = rng.randn(2, 8, 3, 2, 4).astype(np.float32)
        o1, _ = IF.flash_attn_qkvpacked(_t(qkv), causal=True)
        o2, _ = IF.flash_attention(_t(qkv[:, :, 0]), _t(qkv[:, :, 1]),
                                   _t(qkv[:, :, 2]), causal=True)
        np.testing.assert_allclose(o1.numpy(), o2.numpy())

    def test_fused_multi_head_attention_matches_layer(self):
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention
        rng = np.random.RandomState(2)
        m = FusedMultiHeadAttention(8, 2, dropout_rate=0.0,
                                    attn_dropout_rate=0.0,
                                    normalize_before=False)
        m.eval()
        x = rng.randn(2, 6, 8).astype(np.float32)
        want = m(_t(x)).numpy()
        got = IF.fused_multi_head_attention(
            _t(x), m.qkv_weight, m.linear_weight, pre_layer_norm=False,
            ln_scale=m.ln_scale, ln_bias=m.ln_bias, qkv_bias=m.qkv_bias,
            linear_bias=m.linear_bias, dropout_rate=0.0,
            attn_dropout_rate=0.0, training=False).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_fused_feedforward_matches_layer(self):
        from paddle_tpu.incubate.nn import FusedFeedForward
        rng = np.random.RandomState(3)
        ff = FusedFeedForward(8, 16, dropout_rate=0.0, normalize_before=True)
        ff.eval()
        x = rng.randn(2, 6, 8).astype(np.float32)
        want = ff(_t(x)).numpy()
        got = IF.fused_feedforward(
            _t(x), ff.linear1.weight, ff.linear2.weight, ff.linear1.bias,
            ff.linear2.bias, ln1_scale=ff.norm.weight,
            ln1_bias=ff.norm.bias, dropout1_rate=0.0, dropout2_rate=0.0,
            pre_layer_norm=True, training=False).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestVarlenAndDecodeAttention:
    def test_variable_length_attention_masks_both_sides(self):
        rng = np.random.RandomState(4)
        q = rng.randn(2, 2, 4, 4).astype(np.float32)
        k = rng.randn(2, 2, 6, 4).astype(np.float32)
        v = rng.randn(2, 2, 6, 4).astype(np.float32)
        ql = np.asarray([3, 4], np.int32)
        kl = np.asarray([5, 2], np.int32)
        got = IF.variable_length_memory_efficient_attention(
            _t(q), _t(k), _t(v), _t(ql), _t(kl)).numpy()
        # reference documents [batch, 1] length shapes — same result
        got2 = IF.variable_length_memory_efficient_attention(
            _t(q), _t(k), _t(v), _t(ql[:, None]), _t(kl[:, None])).numpy()
        np.testing.assert_allclose(got2, got)
        for bi in range(2):
            lg = (q[bi] @ k[bi].transpose(0, 2, 1)) / 2.0
            lg[:, :, kl[bi]:] = -1e30
            p = np.exp(lg - lg.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            o = p @ v[bi]
            o[:, ql[bi]:] = 0
            np.testing.assert_allclose(got[bi], o, rtol=1e-4, atol=1e-5)

    def test_masked_multihead_attention_decode_step(self):
        rng = np.random.RandomState(5)
        B, H, S, D = 2, 2, 5, 4
        cache = np.zeros((2, B, H, S, D), np.float32)
        cache[0, :, :, :2] = rng.randn(B, H, 2, D)
        cache[1, :, :, :2] = rng.randn(B, H, 2, D)
        x = rng.randn(B, 3 * H * D).astype(np.float32)
        lens = np.asarray([2, 2], np.int32)
        out, newcache = IF.masked_multihead_attention(
            _t(x), _t(cache), sequence_lengths=_t(lens))
        qkv = x.reshape(B, 3, H, D)
        for bi in range(B):
            kc = cache[0, bi].copy()
            vc = cache[1, bi].copy()
            kc[:, 2] = qkv[bi, 1]
            vc[:, 2] = qkv[bi, 2]
            lg = np.einsum("hd,hsd->hs", qkv[bi, 0], kc[:, :3]) / 2.0
            p = np.exp(lg - lg.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            o = np.einsum("hs,hsd->hd", p, vc[:, :3]).reshape(-1)
            np.testing.assert_allclose(out.numpy()[bi], o, rtol=1e-4,
                                       atol=1e-5)
            np.testing.assert_allclose(newcache.numpy()[0, bi, :, 2],
                                       qkv[bi, 1], rtol=1e-6)

    def test_masked_multihead_attention_rejects_quant(self):
        import pytest
        with pytest.raises(NotImplementedError):
            IF.masked_multihead_attention(
                _t(np.zeros((1, 12), np.float32)),
                _t(np.zeros((2, 1, 1, 4, 3), np.float32)), out_scale=0.5)
        # missing sequence_lengths would silently clobber cache slot 0
        # on every step — must refuse (r5 review)
        with pytest.raises(ValueError):
            IF.masked_multihead_attention(
                _t(np.zeros((1, 12), np.float32)),
                _t(np.zeros((2, 1, 1, 4, 3), np.float32)))

    def test_fused_mha_rejects_cache(self):
        import pytest
        with pytest.raises(NotImplementedError):
            IF.fused_multi_head_attention(
                _t(np.zeros((1, 2, 8), np.float32)),
                _t(np.zeros((3, 2, 4, 8), np.float32)),
                _t(np.zeros((8, 8), np.float32)),
                cache_kv=_t(np.zeros((2, 1, 2, 4, 4), np.float32)))
