"""paddle.jit.save/load deployment artifact (reference: python/paddle/jit/
api.py † save → translated program + params; load → TranslatedLayer).

TPU-native artifact: the forward traced once and serialized as StableHLO
via jax.export (.pdmodel, weights baked as constants) beside the state
dict (.pdparams)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
from paddle_tpu.static import InputSpec


def _net():
    paddle.seed(7)
    return paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                                paddle.nn.Linear(8, 2))


class TestJitSaveLoad:
    def test_translated_layer_roundtrip(self, tmp_path):
        net = _net()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(3, 4).astype(np.float32))
        ref = net(x).numpy()
        path = str(tmp_path / "model")
        jit.save(net, path, input_spec=[x])
        assert sorted(os.listdir(tmp_path)) == ["model.pdmodel",
                                                "model.pdparams"]
        loaded = jit.load(path)
        assert type(loaded).__name__ == "TranslatedLayer"
        np.testing.assert_allclose(loaded(x).numpy(), ref, atol=1e-6)
        # the artifact is self-contained: params live in the program
        assert loaded.state_dict()  # sidecar exposed for inspection

    def test_input_spec_form(self, tmp_path):
        net = _net()
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(5, 4).astype(np.float32))
        path = str(tmp_path / "m")
        jit.save(net, path,
                 input_spec=[InputSpec(shape=[5, 4], dtype="float32")])
        np.testing.assert_allclose(jit.load(path)(x).numpy(), net(x).numpy(),
                                   atol=1e-6)

    def test_params_only_save_returns_state(self, tmp_path):
        net = _net()
        path = str(tmp_path / "p")
        jit.save(net, path)
        state = jit.load(path)
        assert isinstance(state, dict)
        fresh = _net()
        fresh.set_state_dict(state)

    def test_dynamic_batch_dim(self, tmp_path):
        # InputSpec None batch dim -> symbolic shape: one export serves
        # every batch size (paddle's canonical dynamic-batch deployment)
        net = _net()
        path = str(tmp_path / "dyn")
        jit.save(net, path,
                 input_spec=[InputSpec(shape=[None, 4], dtype="float32")])
        loaded = jit.load(path)
        for b in (2, 7):
            x = paddle.to_tensor(
                np.random.RandomState(b).randn(b, 4).astype(np.float32))
            np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                                       atol=1e-6)

    def test_pdparams_suffix_path_consistent(self, tmp_path):
        # save('m.pdparams') and load('m.pdparams') must agree on where
        # the traced program lives
        net = _net()
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        path = str(tmp_path / "m.pdparams")
        jit.save(net, path, input_spec=[x])
        loaded = jit.load(path)
        assert type(loaded).__name__ == "TranslatedLayer"
        np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                                   atol=1e-6)

    def test_state_dict_with_spec_raises(self, tmp_path):
        import pytest
        net = _net()
        with pytest.raises(TypeError, match="not.*callable|state_dict"):
            jit.save(net.state_dict(), str(tmp_path / "x"),
                     input_spec=[InputSpec(shape=[2, 4], dtype="float32")])

    def test_translated_layer_refuses_train(self, tmp_path):
        import pytest
        net = _net()
        x = paddle.to_tensor(np.zeros((2, 4), np.float32))
        path = str(tmp_path / "t")
        jit.save(net, path, input_spec=[x])
        with pytest.raises(RuntimeError, match="inference artifact"):
            jit.load(path).train()


class TestHapiInferenceExport:
    """Model.save(training=False) -> jit.save inference artifact
    (reference hapi contract: the deploy path out of fit())."""

    def test_export_and_reload(self, tmp_path):
        net = _net()
        model = paddle.Model(net, inputs=[InputSpec(shape=[None, 4],
                                                    dtype="float32")])
        path = str(tmp_path / "deploy")
        model.save(path, training=False)
        assert sorted(os.listdir(tmp_path)) == ["deploy.pdmodel",
                                                "deploy.pdparams"]
        loaded = jit.load(path)
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(3, 4).astype(np.float32))
        np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                                   atol=1e-6)

    def test_export_without_spec_raises(self, tmp_path):
        import pytest
        model = paddle.Model(_net())
        with pytest.raises(ValueError, match="InputSpec"):
            model.save(str(tmp_path / "x"), training=False)


class TestJitSaveLoadHardening:
    """r5 review findings: eval-mode trace, shared symbolic scope,
    pdmodel-only load, stale-program removal."""

    def test_trace_is_eval_mode_and_restores(self, tmp_path):
        # dropout must not bake into the artifact; BatchNorm running stats
        # must not catch export tracers; the layer's mode is restored
        paddle.seed(3)
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                   paddle.nn.BatchNorm1D(8),
                                   paddle.nn.Dropout(0.5),
                                   paddle.nn.Linear(8, 2))
        net.train()
        x = paddle.to_tensor(
            np.random.RandomState(5).randn(6, 4).astype(np.float32))
        path = str(tmp_path / "ev")
        jit.save(net, path, input_spec=[x])
        assert net.training is True  # restored
        loaded = jit.load(path)
        net.eval()
        ref = net(x).numpy()  # eval forward with the stats as exported
        # deterministic (no dropout baked in) and matches eval-mode forward
        np.testing.assert_allclose(loaded(x).numpy(), ref, atol=1e-5)
        np.testing.assert_allclose(loaded(x).numpy(), loaded(x).numpy())
        # live layer still usable in train mode (no leaked tracers in the
        # BatchNorm buffers)
        net.train()
        _ = net(x).numpy()

    def test_two_dynamic_inputs_share_scope(self, tmp_path):
        class Two(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = paddle.nn.Linear(4, 2)

            def forward(self, a, b):
                return self.lin(a) + b.sum()

        net = Two()
        path = str(tmp_path / "two")
        jit.save(net, path, input_spec=[
            InputSpec(shape=[None, 4], dtype="float32"),
            InputSpec(shape=[None, 3], dtype="float32")])
        loaded = jit.load(path)
        # dynamic axis-0 dims share ONE symbol (the batch axis) so ops
        # combining the inputs export; sizes must agree at call time
        for n in (2, 5):
            a = paddle.to_tensor(np.ones((n, 4), np.float32))
            b = paddle.to_tensor(np.ones((n, 3), np.float32))
            np.testing.assert_allclose(loaded(a, b).numpy(),
                                       net(a, b).numpy(), atol=1e-6)

    def test_pdmodel_alone_is_loadable(self, tmp_path):
        net = _net()
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        path = str(tmp_path / "solo")
        jit.save(net, path, input_spec=[x])
        os.remove(path + ".pdparams")
        loaded = jit.load(path)
        np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                                   atol=1e-6)
        # inference works without the sidecar, but asking for the state
        # dict must raise a descriptive error, not hand back None
        with pytest.raises(FileNotFoundError, match="sidecar"):
            loaded.state_dict()

    def test_params_only_save_clears_stale_program(self, tmp_path):
        net = _net()
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        path = str(tmp_path / "stale")
        jit.save(net, path, input_spec=[x])
        jit.save(_net(), path)  # params-only re-save after retrain
        assert not os.path.exists(path + ".pdmodel")
        assert isinstance(jit.load(path), dict)


class TestToStaticSwitch:
    """paddle.jit.enable_to_static global switch + ignore_module parity."""

    def test_disable_runs_eager(self):
        calls = []

        @jit.to_static
        def f(x):
            calls.append(1)  # side effect visible only on eager re-entry
            return x * 2

        x = paddle.to_tensor(np.float32([1.0]))
        f(x); f(x)
        traced_calls = len(calls)  # jit traces once, then cached
        assert traced_calls == 1
        try:
            jit.enable_to_static(False)
            f(x); f(x)
            assert len(calls) == traced_calls + 2  # eager: every call runs
        finally:
            jit.enable_to_static(True)
        np.testing.assert_allclose(f(x).numpy(), [2.0])

    def test_ignore_module_accepts(self):
        import numpy
        assert jit.ignore_module([numpy]) is None

    def test_disable_covers_layers(self):
        # the escape hatch must also apply to to_static(Layer)
        net = jit.to_static(_net())
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        ref = net(x).numpy()
        calls = []
        orig_fwd = net._layer.forward

        def spy(*a, **k):
            calls.append(1)
            return orig_fwd(*a, **k)

        try:
            jit.enable_to_static(False)
            net._layer.forward = spy
            np.testing.assert_allclose(net(x).numpy(), ref, atol=1e-6)
            assert calls  # eager forward actually ran
        finally:
            net._layer.forward = orig_fwd
            jit.enable_to_static(True)
