"""Int8 block-quantized KV serving + int8 weight-only decode (README
"Quantized serving", ISSUE 14). The load-bearing properties:

- **Measured divergence, not assumed zero**: quantized streams are
  compared token-for-token against the fp32 baseline — greedy AND
  seeded-sampled — and the agreement is asserted as a measured bound.
- **Scales ride the blocks**: the per-row-per-head scale planes are
  indexed by physical block id, so trie donation, zero-copy hits,
  speculative truncation, preemption and restore() all carry them with
  NO dedicated bookkeeping — pinned by scale-plane identity and exact
  ``num_free`` restoration.
- **Compile discipline**: ``decode_compilations() == 1`` inclusive of
  the quantized geometry, with fp32/int8/weight-quantized engines
  sharing ONE jit cache (the variant tags key their traces apart).
- **Transparency of the step machinery**: speculative decode and
  multi-tick decode on int8 KV are byte-identical to their own
  tick-at-a-time quantized baselines; the chaos fault matrix loses
  nothing and replays deterministically.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                GenerationRequest)
from paddle_tpu.serving.faults import FaultPlan
from paddle_tpu.serving.kv_cache import PagedKVCache, quantize_kv_rows
from paddle_tpu.serving.server.gateway import ServingGateway

from test_metrics_prom import parse_prometheus

BS = 8      # block size
CHUNK = 16  # 2 blocks per chunk


@pytest.fixture(scope="module")
def model():
    paddle.seed(33)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


def _engine(model, **kw):
    kw.setdefault("jit_cache", model.__dict__.setdefault("_serving_jit", {}))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    return ContinuousBatchingEngine(model, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _reqs(sampled=False, n_reqs=4, max_new=8):
    """Mixed trace: two shared system prompts with unique tails (trie
    traffic) + repetition so the n-gram drafter has something to hit."""
    sys_p = [_prompt(100 + i, 24) for i in range(2)]
    out = []
    for i in range(n_reqs):
        tail = np.tile(_prompt(i, 4), 3).astype(np.int32)
        kw = dict(max_new_tokens=max_new)
        if sampled:
            kw.update(temperature=0.8, top_k=20, seed=500 + i)
        out.append(GenerationRequest(
            prompt=np.concatenate([sys_p[i % 2], tail]), **kw))
    return out


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             seed=r.seed, eos_token_id=r.eos_token_id)


def _run(eng, reqs):
    return [list(o) for o in eng.generate([_clone(r) for r in reqs])]


def _match_fraction(a, b):
    """Mean matched-prefix fraction across paired streams — the
    measured (not assumed) divergence statistic the density bench
    banks."""
    fracs = []
    for x, y in zip(a, b):
        m = 0
        for t, u in zip(x, y):
            if t != u:
                break
            m += 1
        fracs.append(m / max(len(x), 1))
    return sum(fracs) / len(fracs)


# ------------------------------------------------------------ unit: rows
class TestQuantizeRows:
    def test_roundtrip_error_bounded_per_row_head(self):
        rng = np.random.RandomState(0)
        x = rng.randn(5, 7, 3, 16).astype(np.float32) * \
            rng.uniform(0.1, 10.0, (5, 7, 3, 1)).astype(np.float32)
        q, s = quantize_kv_rows(x)
        q, s = np.asarray(q), np.asarray(s)
        assert q.dtype == np.int8 and s.dtype == np.float32
        assert s.shape == x.shape[:-1]
        deq = q.astype(np.float32) * s[..., None]
        # symmetric round-to-nearest: error <= scale/2 per element,
        # and |dequant| never exceeds the row-head absmax
        assert np.all(np.abs(deq - x) <= s[..., None] / 2 + 1e-7)
        assert np.all(np.abs(deq) <= np.abs(x).max(-1, keepdims=True)
                      + 1e-7)
        assert np.abs(q).max() <= 127

    def test_zero_rows_quantize_to_exact_zero(self):
        q, s = quantize_kv_rows(np.zeros((2, 4, 3, 8), np.float32))
        assert np.all(np.asarray(q) == 0) and np.all(np.asarray(s) == 0)
        assert np.all(np.asarray(q).astype(np.float32)
                      * np.asarray(s)[..., None] == 0)


# ------------------------------------------------------- pool accounting
class TestPoolBytes:
    def test_occupancy_bytes_exact_and_ratio(self, model):
        base = _engine(model)
        q = _engine(model, kv_dtype="int8")
        c = model.config
        L, Hkv, D = (c.num_hidden_layers, c.num_key_value_heads,
                     c.head_dim)
        nb = q.cache.pool.num_blocks
        ob = q.cache.occupancy_bytes()
        assert ob["capacity_kv"] == 2 * L * nb * BS * Hkv * D      # int8
        assert ob["capacity_scales"] == 2 * L * nb * BS * Hkv * 4  # fp32
        ob0 = base.cache.occupancy_bytes()
        assert ob0["capacity_scales"] == 0
        assert ob0["capacity_kv"] == 2 * L * base.cache.pool.num_blocks \
            * BS * Hkv * D * 4                                     # fp32
        # per-token marginal cost: fp32 4D bytes vs int8 D + 4 bytes
        ratio = ob0["per_token"] / ob["per_token"]
        assert ratio == pytest.approx(4 * D / (D + 4))
        assert ratio >= 1.8               # the density headline's floor

    def test_write_prefill_quantizes_on_write(self, model):
        c = model.config
        cache = PagedKVCache(c.num_hidden_layers, 2, 64,
                             c.num_key_value_heads, c.head_dim,
                             block_size=BS, kv_dtype="int8")
        rng = np.random.RandomState(3)
        L, Hkv, D = (c.num_hidden_layers, c.num_key_value_heads,
                     c.head_dim)
        pk = rng.randn(L, 16, Hkv, D).astype(np.float32)
        pv = rng.randn(L, 16, Hkv, D).astype(np.float32)
        slot = cache.alloc()
        cache.write_prefill(slot, pk, pv, 11)
        assert cache.pool.k.dtype == np.int8
        want_q, want_s = quantize_kv_rows(pk)
        blocks = cache.slot_block_ids(slot)
        got_q = np.asarray(cache.pool.k)[:, blocks].reshape(L, -1, Hkv, D)
        got_s = np.asarray(cache.pool.k_scale)[:, blocks].reshape(
            L, -1, Hkv)
        # rows [0, 11) landed quantized with their scales; padding rows
        # past prompt_len dropped (block 2 of the 16-row buffer was
        # never allocated). Tolerances: the jitted writer's fused
        # reduction may differ from the eager recompute by float
        # epsilon, which can flip a round-to-nearest tie by one step.
        np.testing.assert_allclose(got_s[:, :11],
                                   np.asarray(want_s)[:, :11],
                                   rtol=1e-5)
        assert np.abs(got_q[:, :11].astype(np.int32)
                      - np.asarray(want_q)[:, :11]).max() <= 1

    def test_pool_cache_kv_dtype_mismatch_raises(self, model):
        from paddle_tpu.serving.block_manager import BlockManager
        c = model.config
        pool = BlockManager(c.num_hidden_layers, 16, BS,
                            c.num_key_value_heads, c.head_dim)
        with pytest.raises(ValueError, match="kv_dtype"):
            PagedKVCache(c.num_hidden_layers, 2, 64,
                         c.num_key_value_heads, c.head_dim,
                         block_size=BS, pool=pool, kv_dtype="int8")


# ----------------------------------------------------------- validation
class TestValidation:
    def test_int8_requires_unified_ragged_paged(self, model):
        with pytest.raises(ValueError, match="unified ragged"):
            _engine(model, kv_dtype="int8", paged_attn=False)
        with pytest.raises(ValueError, match="unified ragged"):
            _engine(model, kv_dtype="int8", ragged_step=False)

    def test_bad_kv_dtype_rejected(self, model):
        with pytest.raises(ValueError, match="kv_dtype"):
            _engine(model, kv_dtype="int4")


# -------------------------------------------------------------- streams
class TestStreams:
    def test_greedy_divergence_measured_and_bounded(self, model):
        base = _run(_engine(model), _reqs())
        quant = _run(_engine(model, kv_dtype="int8"), _reqs())
        assert [len(s) for s in quant] == [len(s) for s in base]
        frac = _match_fraction(base, quant)
        # MEASURED agreement, not assumed identity: per-token int8 KV
        # holds the greedy argmax walk on this model/trace (frac is
        # 1.0 here today; the bound leaves room for platform jitter
        # while still catching a real quantization regression)
        assert frac >= 0.75, f"greedy matched-prefix fraction {frac}"

    def test_sampled_divergence_measured_and_bounded(self, model):
        base = _run(_engine(model), _reqs(sampled=True))
        quant = _run(_engine(model, kv_dtype="int8"),
                     _reqs(sampled=True))
        frac = _match_fraction(base, quant)
        assert frac >= 0.75, f"sampled matched-prefix fraction {frac}"

    def test_int8_streams_deterministic_across_replays(self, model):
        for sampled in (False, True):
            a = _run(_engine(model, kv_dtype="int8"), _reqs(sampled))
            b = _run(_engine(model, kv_dtype="int8"), _reqs(sampled))
            assert a == b

    def test_default_kv_dtype_unchanged_by_quantized_sibling(self, model):
        """The default path must stay byte-identical with quantized
        engines sharing the SAME jit cache dict — the quantized trace
        keys apart instead of perturbing the baseline programs."""
        before = _run(_engine(model), _reqs())
        _run(_engine(model, kv_dtype="int8", quantize_weights=True),
             _reqs())
        after = _run(_engine(model), _reqs())
        assert before == after


# ---------------------------------------------- lifecycle carries scales
class TestLifecycleCarriesScales:
    def test_trie_hit_zero_copy_and_scale_plane_identity(self, model):
        eng = _engine(model, kv_dtype="int8", prefix_cache=True)
        p = _prompt(7, 32)                  # 4 whole blocks
        r = GenerationRequest(prompt=p, max_new_tokens=4)
        first = list(eng.generate([r])[0])
        matched = eng.prefix_cache.lookup(p)
        assert matched, "retirement should have donated the chain"
        blocks = [n.block_id for n in matched]
        ks_before = np.asarray(eng.cache.pool.k_scale)[:, blocks].copy()
        vs_before = np.asarray(eng.cache.pool.v_scale)[:, blocks].copy()
        second = list(eng.generate([GenerationRequest(
            prompt=p, max_new_tokens=4)])[0])
        assert eng.prefix_cache.stats["hits"] >= 1
        assert second == first              # hit ≡ cold, quantized
        # the donated blocks' scale planes were READ, never rewritten:
        # scale identity is what makes zero-copy hits exact on int8
        np.testing.assert_array_equal(
            np.asarray(eng.cache.pool.k_scale)[:, blocks], ks_before)
        np.testing.assert_array_equal(
            np.asarray(eng.cache.pool.v_scale)[:, blocks], vs_before)

    def test_spec_truncate_restores_num_free_exactly(self, model):
        eng = _engine(model, kv_dtype="int8", spec_decode=True,
                      spec_k=3)
        free0 = eng.cache.pool.num_free
        outs = _run(eng, _reqs())
        assert all(len(s) == 8 for s in outs)
        # every slot retired; with no trie, every draft-rejected and
        # private block went back to the heap exactly once
        assert eng.cache.pool.num_free == free0
        assert eng.cache.num_free == eng.num_slots

    def test_preempt_restore_byte_identical_on_int8(self, model):
        want = _run(_engine(model, kv_dtype="int8",
                            prefix_cache=True), _reqs())
        eng = _engine(model, kv_dtype="int8", prefix_cache=True)
        FaultPlan().at_step(3, "pool").install(eng)
        got = _run(eng, _reqs())
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["restores"] >= 1
        assert got == want

    def test_cancel_mid_decode_restores_pool(self, model):
        eng = _engine(model, kv_dtype="int8")
        free0 = eng.cache.pool.num_free
        seqs = [eng.submit(r) for r in _reqs(max_new=24)]
        for _ in range(3):
            eng.step()
        for s in seqs:
            if not s.done:
                eng.cancel(s)
        assert eng.cache.pool.num_free == free0
        assert eng.cache.num_free == eng.num_slots


# ------------------------------------------------------ chaos, int8 leg
class TestChaosInt8:
    def _factory(self, model, jit):
        def factory():
            return _engine(model, kv_dtype="int8", prefix_cache=True,
                           jit_cache=jit)
        return factory

    def test_fault_matrix_zero_lost_deterministic(self, model):
        # dedicated jit dict: the trie-backed pool is a different arg
        # SHAPE than the no-trie engines elsewhere in this module, and
        # pool-geometry-keyed caches must not collide under the
        # compile pin (jit-cache-per-pool-geometry rule)
        jit = {}
        want = _run(_engine(model, kv_dtype="int8", prefix_cache=True,
                            jit_cache=jit), _reqs())

        def chaos_once():
            plan = (FaultPlan().at_step(2, "transient")
                    .at_step(4, "pool").at_step(6, "fatal")
                    .at_step(8, "nan"))
            factory = self._factory(model, jit)
            gw = ServingGateway(factory(), engine_factory=factory,
                                fault_hook=plan, start=False,
                                max_queue=16)
            streams = [gw.submit(_clone(r)) for r in _reqs()]
            gw.start()
            outs = [st.result() for st in streams]
            kinds = [k for _, k in plan.log]
            comp = gw.engine.decode_compilations()
            gw.shutdown(drain=True, timeout=30)
            return ([ids.tolist() for ids, _ in outs],
                    [r for _, r in outs], kinds, comp)

        ids1, reasons1, kinds1, comp1 = chaos_once()
        ids2, reasons2, kinds2, comp2 = chaos_once()
        assert ids1 == want                 # 0 lost, byte-identical
        assert ids1 == ids2 and reasons1 == reasons2    # deterministic
        assert set(kinds1) >= {"transient", "pool", "fatal", "nan"}
        assert comp1 == 1 and comp2 == 1


# --------------------------------------------------- compile discipline
class TestCompileDiscipline:
    @pytest.mark.slow  # 6 s four-engine matrix duplicate: test_lowprec_decode
    # TestCompileDiscipline keys fp/kv8f/w8+a8 apart by default (870s cap)
    def test_compile_once_inclusive_of_quantized_geometry(self, model):
        # fresh dict: all four engines share one POOL geometry (no
        # trie), so the pin isolates exactly the quantization variants
        jit = {}
        engines = {
            "fp": _engine(model, jit_cache=jit),
            "int8": _engine(model, kv_dtype="int8", jit_cache=jit),
            "w8": _engine(model, quantize_weights=True, jit_cache=jit),
            "both": _engine(model, kv_dtype="int8",
                            quantize_weights=True, jit_cache=jit),
        }
        for eng in engines.values():
            _run(eng, _reqs())
            _run(eng, _reqs(sampled=True))
        for name, eng in engines.items():
            assert eng.decode_compilations() == 1, name
        # second wave re-traces nothing: the prefill compile set is
        # closed per variant
        pre = {n: e.prefill_compilations() for n, e in engines.items()}
        for eng in engines.values():
            _run(eng, _reqs())
        assert {n: e.prefill_compilations()
                for n, e in engines.items()} == pre

    def test_variant_tags_key_programs_apart(self, model):
        jit = {}
        fp = _engine(model, jit_cache=jit)
        q8 = _engine(model, kv_dtype="int8", quantize_weights=True,
                     jit_cache=jit)
        # a short prompt (under the chunk) takes the COLD prefill path
        short = [GenerationRequest(prompt=_prompt(9, 10),
                                   max_new_tokens=2)]
        _run(fp, _reqs(n_reqs=1)), _run(fp, short)
        _run(q8, _reqs(n_reqs=1)), _run(q8, short)
        keys = set(jit)
        attn = model.config.decode_attention
        assert ("ragged", 2, 2 + CHUNK, 1, attn) in keys
        assert ("ragged", 2, 2 + CHUNK, 1, attn, "kv8", "w8") in keys
        assert ("prefill",) in keys and ("prefill", "w8") in keys
        # each engine counts ONLY its own variant
        assert fp.decode_compilations() == 1
        assert q8.decode_compilations() == 1


# ----------------------------------------- spec + multi-tick, int8 pool
class TestSpecAndMultitickInt8:
    @pytest.mark.parametrize("sampled", [False, True])
    def test_spec_decode_byte_identical_to_int8_baseline(self, model,
                                                         sampled):
        base = _run(_engine(model, kv_dtype="int8"), _reqs(sampled))
        spec = _run(_engine(model, kv_dtype="int8", spec_decode=True,
                            spec_k=3), _reqs(sampled))
        assert spec == base

    @pytest.mark.parametrize("sampled", [False, True])
    def test_multitick_byte_identical_to_int8_baseline(self, model,
                                                       sampled):
        base = _run(_engine(model, kv_dtype="int8"), _reqs(sampled))
        mt = _run(_engine(model, kv_dtype="int8", decode_ticks=4),
                  _reqs(sampled))
        assert mt == base


# ------------------------------------------------------- weight-only w8
class TestWeightOnly:
    def test_streams_deterministic_and_close_to_fp(self, model):
        base = _run(_engine(model), _reqs())
        a = _run(_engine(model, quantize_weights=True), _reqs())
        b = _run(_engine(model, quantize_weights=True), _reqs())
        assert a == b                       # deterministic
        frac = _match_fraction(base, a)
        assert frac >= 0.5, f"w8 matched-prefix fraction {frac}"

    def test_converted_params_cached_on_model(self, model):
        e1 = _engine(model, quantize_weights=True)
        e2 = _engine(model, quantize_weights=True)
        assert e1._params is e2._params     # converted ONCE per model
        q, s = e1._params["wq"]
        assert np.asarray(q).dtype == np.int8
        assert s.shape[1] == 1              # per-channel, axis-1 reduced

    def test_rebuild_shares_qparams_and_jit(self, model):
        jit = model.__dict__.setdefault("_serving_jit", {})
        want = _run(_engine(model, quantize_weights=True,
                            jit_cache=jit), _reqs())

        def factory():
            return _engine(model, quantize_weights=True, jit_cache=jit)
        plan = FaultPlan().at_step(3, "fatal")
        gw = ServingGateway(factory(), engine_factory=factory,
                            fault_hook=plan, start=False, max_queue=16)
        streams = [gw.submit(_clone(r)) for r in _reqs()]
        gw.start()
        outs = [st.result() for st in streams]
        assert [ids.tolist() for ids, _ in outs] == want
        assert gw.restarts == 1
        assert gw.engine.decode_compilations() == 1
        gw.shutdown(drain=True, timeout=30)


# -------------------------------------------------------------- metrics
class TestQuantMetrics:
    def test_kv_pool_bytes_gauges_strict_parse(self, model):
        eng = _engine(model, kv_dtype="int8", prefix_cache=True)
        gw = ServingGateway(eng, start=False, max_queue=16)
        eng.submit(GenerationRequest(prompt=_prompt(1, 20),
                                     max_new_tokens=4))
        eng.step()                          # we are the driver thread
        fams = parse_prometheus(gw.registry.render())
        ob = eng.cache.occupancy_bytes()
        kv = fams["kv_pool_bytes"]["samples"]
        assert kv[("kv_pool_bytes", (("kind", "kv"),))] == ob["used_kv"]
        assert kv[("kv_pool_bytes",
                   (("kind", "scales"),))] == ob["used_scales"]
        assert ob["used_kv"] > 0 and ob["used_scales"] > 0
        # int8 data is exactly D bytes per fp32-scale's 4: the ratio
        # of the two gauges is D/4, dtype-awareness in one line
        assert ob["used_kv"] / ob["used_scales"] == \
            model.config.head_dim / 4
        per_tok = fams["serving_kv_bytes_per_token"]["samples"][
            ("serving_kv_bytes_per_token", ())]
        assert per_tok == ob["per_token"]
        gw.shutdown(drain=False, timeout=10)

    def test_profile_doc_reports_bytes_not_blocks(self, model):
        eng = _engine(model, kv_dtype="int8")
        gw = ServingGateway(eng, start=False, max_queue=16)
        eng.submit(GenerationRequest(prompt=_prompt(2, 20),
                                     max_new_tokens=4))
        eng.step()
        doc = gw.profile_doc()
        kvp = doc["kv_pool"]
        assert kvp["kv_dtype"] == "int8"
        per_block = (eng.cache.pool.block_nbytes
                     + eng.cache.pool.scale_block_nbytes)
        occ = eng.cache.occupancy()
        assert kvp["live_bytes"] == occ["live"] * per_block
        assert kvp["live_bytes"] > 0
        assert kvp["capacity_bytes"] == \
            eng.cache.pool.num_blocks * per_block
        assert kvp["bytes_per_token"] == \
            eng.cache.occupancy_bytes()["per_token"]
        gw.shutdown(drain=False, timeout=10)
