"""Launcher + elastic + rendezvous tests (VERDICT r2 item 6 — the launch
CLI had zero tests). Reference: ``python/paddle/distributed/launch`` †
(``controllers/master.py`` KV master, ``test/legacy_test/test_run.py``
launch-CLI test pattern).

The workers here are jax-free toy scripts: these tests exercise process
management, env wiring, logs, restart/backoff, and the rank-0 KV store —
not device code.
"""
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOY = os.path.join(REPO, "tests", "_launch_toy.py")
FLAKY = os.path.join(REPO, "tests", "_launch_flaky.py")


def _run_launch(extra, timeout=60):
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch"] + extra
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # process-management tests: keep the launcher + toy workers off the
    # accelerator backend (its tunnel admits one client)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    return subprocess.run(cmd, capture_output=True, text=True, env=env,
                          timeout=timeout, cwd=REPO)


class TestLaunchCLI:
    def test_procs2_env_and_logs(self, tmp_path):
        log_dir = str(tmp_path / "logs")
        p = _run_launch(["--procs", "2", "--log_dir", log_dir, TOY,
                         str(tmp_path)])
        assert p.returncode == 0, p.stderr[-500:]
        # per-rank env files written by the workers
        envs = {}
        for r in range(2):
            with open(tmp_path / f"env.{r}.json") as f:
                envs[r] = json.load(f)
        for r in range(2):
            assert envs[r]["PADDLE_TRAINER_ID"] == str(r)
            assert envs[r]["PADDLE_TRAINERS_NUM"] == "2"
            assert envs[r]["PADDLE_LOCAL_RANK"] == str(r)
            assert envs[r]["FLAGS_selected_tpus"] == str(r)
        # non-rank-0 workers log to workerlog.<local_rank>
        log1 = os.path.join(log_dir, "workerlog.1")
        assert os.path.exists(log1)
        assert "rank=1 ok" in open(log1).read()

    @pytest.mark.slow  # subprocess-launch family: procs2 test is the
    # default-run representative
    def test_master_env_propagated(self, tmp_path):
        p = _run_launch(["--procs", "1", "--master", "127.0.0.1:0",
                         "--log_dir", str(tmp_path / "logs"), TOY,
                         str(tmp_path)])
        assert p.returncode == 0, p.stderr[-500:]
        with open(tmp_path / "env.0.json") as f:
            env0 = json.load(f)
        assert env0["PADDLE_MASTER"].startswith("127.0.0.1")
        assert "PADDLE_CURRENT_ENDPOINT" in env0

    @pytest.mark.slow
    def test_failure_exit_code(self, tmp_path):
        p = _run_launch(["--procs", "1", "--log_dir", str(tmp_path / "logs"),
                         FLAKY, str(tmp_path)])
        # no elastic: first failure is fatal
        assert p.returncode == 1

    @pytest.mark.slow
    def test_elastic_restart_with_backoff(self, tmp_path):
        t0 = time.time()
        p = _run_launch(["--procs", "1", "--elastic_level", "1",
                         "--max_restart", "3", "--restart_backoff", "1",
                         "--log_dir", str(tmp_path / "logs"),
                         FLAKY, str(tmp_path)])
        dt = time.time() - t0
        assert p.returncode == 0, p.stderr[-500:]
        assert os.path.exists(tmp_path / "ran_once")  # first run happened
        assert "restart 1/3" in p.stderr
        assert dt >= 1.0  # backoff was observed


class TestRendezvousStore:
    def test_kv_put_get_prefix_delete(self):
        from paddle_tpu.parallel.launch.rendezvous import KVClient, KVServer
        srv = KVServer(port=0)
        try:
            cli = KVClient(srv.endpoint)
            cli.put("/job/a/rank/0", "host0:35000")
            cli.put("/job/a/rank/1", "host1:35001")
            assert cli.get("/job/a/rank/0") == "host0:35000"
            assert cli.get("/nope") is None
            table = cli.get_prefix("/job/a/rank/")
            assert len(table) == 2
            cli.delete("/job/a/rank/0")
            assert cli.get("/job/a/rank/0") is None
        finally:
            srv.stop()

    def test_world_barrier(self):
        from paddle_tpu.parallel.launch.rendezvous import KVClient, KVServer
        import threading
        srv = KVServer(port=0)
        try:
            def worker(rank):
                c = KVClient(srv.endpoint)
                time.sleep(0.05 * rank)  # stagger arrivals
                c.register("j1", rank, f"h{rank}:3500{rank}")
                tables[rank] = c.wait_world("j1", world=3, timeout=10)

            tables = {}
            ts = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=15)
            for r in range(3):
                assert tables[r] == {0: "h0:35000", 1: "h1:35001",
                                     2: "h2:35002"}
        finally:
            srv.stop()

    def test_barrier_timeout(self):
        from paddle_tpu.parallel.launch.rendezvous import KVClient, KVServer
        srv = KVServer(port=0)
        try:
            cli = KVClient(srv.endpoint)
            cli.register("j2", 0, "h0:1")
            with pytest.raises(TimeoutError, match="1/2"):
                cli.wait_world("j2", world=2, timeout=0.5)
        finally:
            srv.stop()


def _tcp_available():
    from paddle_tpu import csrc
    return csrc.tcp_store_available()


@pytest.mark.skipif(not _tcp_available(),
                    reason="native TCPStore build unavailable (no g++)")
class TestNativeTCPStore:
    """Native C++ TCPStore (csrc/tcp_store.cpp — reference
    ``paddle/phi/core/distributed/store/tcp_store.cc`` †)."""

    def test_set_get_add_del(self):
        from paddle_tpu.distributed import TCPStore
        m = TCPStore(is_master=True)
        try:
            m.set("/k", "v1")
            assert m.get("/k") == b"v1"
            assert m.get("/missing") is None
            assert m.add("/n", 2) == 2
            assert m.add("/n", 40) == 42
            assert m.delete_key("/k") is True
            assert m.get("/k") is None
        finally:
            m.stop_server()

    def test_set_rejects_non_bytes(self):
        # ADVICE r3: bytes(5) would silently store five NUL bytes
        from paddle_tpu.distributed import TCPStore
        m = TCPStore(is_master=True)
        try:
            with pytest.raises(TypeError, match="str or bytes"):
                m.set("/k", 5)
            m.set("/k", bytearray(b"ok"))
            assert m.get("/k") == b"ok"
        finally:
            m.stop_server()

    def test_stalled_partial_frame_does_not_block_loop(self):
        """ADVICE r3: a client that sends HALF a request frame and stalls
        must not delay other clients (old design: 5s SO_RCVTIMEO blocked
        the whole select loop per stall)."""
        import socket as _socket
        import struct as _struct
        from paddle_tpu.distributed import TCPStore
        m = TCPStore(is_master=True)
        try:
            # handcraft a partial SET frame: cmd + klen, then stall
            s = _socket.create_connection(("127.0.0.1", m.port))
            s.sendall(bytes([1]) + _struct.pack("<I", 100))  # promises 100b key
            c = TCPStore(port=m.port)
            t0 = time.time()
            c.set("/fast", "v")
            assert c.get("/fast") == b"v"
            assert time.time() - t0 < 2.0, "healthy client was blocked"
            s.close()
        finally:
            m.stop_server()

    def test_cross_connection_and_prefix(self):
        from paddle_tpu.distributed import TCPStore
        m = TCPStore(is_master=True)
        try:
            c = TCPStore(port=m.port)
            c.set("/job/z/rank/0", "a:1")
            c.set("/job/z/rank/1", "b:2")
            c.set("/other", "x")
            table = m.get_prefix("/job/z/")
            assert table == {"/job/z/rank/0": b"a:1", "/job/z/rank/1": b"b:2"}
        finally:
            m.stop_server()

    def test_server_side_wait(self):
        import threading
        from paddle_tpu.distributed import TCPStore
        m = TCPStore(is_master=True)
        try:
            c = TCPStore(port=m.port)
            threading.Timer(0.3, lambda: c.set("/late", "1")).start()
            t0 = time.time()
            m.wait("/late", timeout=10)
            assert 0.2 < time.time() - t0 < 5
            with pytest.raises(TimeoutError):
                m.wait("/never", timeout=0.4)
        finally:
            m.stop_server()

    def test_barrier_three_ranks(self):
        import threading
        from paddle_tpu.distributed import TCPStore
        m = TCPStore(is_master=True, world_size=3)
        done = []
        try:
            def rank(i):
                c = TCPStore(port=m.port, world_size=3)
                time.sleep(0.05 * i)
                c.barrier("b", timeout=10)
                done.append(i)

            ts = [threading.Thread(target=rank, args=(i,)) for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=15)
            assert sorted(done) == [0, 1, 2]
        finally:
            m.stop_server()

    def test_native_adapter_wait_world(self):
        from paddle_tpu.parallel.launch.rendezvous import (NativeKVServer,
                                                           connect)
        srv = NativeKVServer(port=0)
        try:
            assert srv.endpoint.startswith("tcp://")
            cli = connect(srv.endpoint)
            cli.register("jn", 0, "h0:1")
            cli.register("jn", 1, "h1:2")
            table = cli.wait_world("jn", world=2, timeout=5)
            assert table == {0: "h0:1", 1: "h1:2"}
            srv.clear()
            assert cli.get_prefix("/job/jn/") == {}
        finally:
            srv.stop()

    @pytest.mark.slow  # CLI-subprocess variant; the in-process TCPStore
    # tests above keep covering the native store in the default run
    def test_launch_cli_tcp_backend(self, tmp_path):
        p = _run_launch(["--procs", "1", "--master", "127.0.0.1:0",
                         "--rdzv_backend", "tcp",
                         "--log_dir", str(tmp_path / "logs"), TOY,
                         str(tmp_path)])
        assert p.returncode == 0, p.stderr[-500:]
        with open(tmp_path / "env.0.json") as f:
            env = json.load(f)
        # native backend when buildable; documented fallback is the HTTP
        # store, whose endpoint carries no scheme
        assert env["PADDLE_MASTER_KV"].startswith("tcp://")

    def test_add_idempotency_token(self):
        """Replaying an ADD with the same token (reconnect-retry semantics)
        must not double-increment."""
        from paddle_tpu.distributed import TCPStore
        m = TCPStore(is_master=True)
        try:
            payload = (5).to_bytes(8, "little", signed=True) + b"T" * 16
            v1 = m._lib.tcp_store_add_raw(m._client, b"/ctr", payload,
                                          len(payload))
            v2 = m._lib.tcp_store_add_raw(m._client, b"/ctr", payload,
                                          len(payload))
            assert (v1, v2) == (5, 5)
            # a fresh token applies normally
            assert m.add("/ctr", 1) == 6
        finally:
            m.stop_server()


class TestRealJaxDistributed:
    """End-to-end 2-process jax.distributed rendezvous through the
    launcher (the multi-host bring-up path, SURVEY §5.8): import must not
    touch the backend, and init_parallel_env agrees a real coordinator
    port through the rendezvous store when --master requests port 0."""

    @pytest.mark.slow  # 2 real jax procs (~15 s); the import-safety
    # canary below stays in the default run
    def test_two_process_rendezvous(self, tmp_path):
        toy = os.path.join(REPO, "tests", "_jaxdist_toy.py")
        p = _run_launch(["--procs", "2", "--master", "127.0.0.1:0",
                         "--log_dir", str(tmp_path / "logs"), toy],
                        timeout=180)
        assert p.returncode == 0, (p.stdout[-300:], p.stderr[-500:])
        logs = p.stdout  # rank 0 streams to the launcher console
        for f in (tmp_path / "logs").iterdir():
            logs += f.read_text()
        assert "JAXDIST rank=0 nproc=2" in logs
        assert "JAXDIST rank=1 nproc=2" in logs

    def test_import_does_not_init_backend(self):
        # the lazy global PRNG is what keeps multi-host init possible
        code = ("import jax\n"
                "orig = jax._src.xla_bridge.backends\n"
                "hits = []\n"
                "jax._src.xla_bridge.backends = "
                "lambda *a, **k: (hits.append(1), orig(*a, **k))[1]\n"
                "import paddle_tpu\n"
                "assert not hits, 'import initialized the XLA backend'\n"
                "print('IMPORT CLEAN')\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, env=env,
                           timeout=120)
        assert p.returncode == 0, p.stderr[-500:]
        assert "IMPORT CLEAN" in p.stdout
