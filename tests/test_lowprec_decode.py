"""End-to-end low-precision decode (README "Quantized serving",
ISSUE 19): fp8 KV with dequant-free attention + the int8x8
(``quantize_activations``) projection path. The load-bearing
properties, PR-13 discipline throughout:

- **Measured divergence, not assumed zero**: fp8 and a8 streams are
  compared token-for-token against the fp32 baseline and the agreement
  asserted as a measured bound; replays are byte-identical.
- **Per-block scales, constant by construction**: the fp8 pool's scale
  planes are ``[L, nb, Hkv]`` ones — e4m3's exponent is the per-value
  scale — so a cached token costs strictly fewer bytes than int8's
  per-row layout and a block's bytes never depend on which program
  wrote it (restore()/replay byte-identity).
- **Compile discipline**: ``decode_compilations() == 1`` inclusive of
  the ``kv8f``/``a8`` variant geometry, with fp/int8/fp8/w8/a8 engines
  sharing ONE jit cache (the tags key their traces apart) and the
  default path byte-identical before/after.
- **Composition**: fp8/a8 ride multi-tick, spec-verify, TP and the
  host tier with streams byte-identical to their own tick-at-a-time
  quantized baselines.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                GenerationRequest)
from paddle_tpu.serving.fleet import EngineFleet
from paddle_tpu.serving.kv_cache import (FP8_MAX, quantize_kv_rows,
                                         quantize_kv_rows_fp8)

BS = 8      # block size
CHUNK = 16  # 2 blocks per chunk


@pytest.fixture(scope="module")
def model():
    paddle.seed(33)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


def _engine(model, **kw):
    kw.setdefault("jit_cache", model.__dict__.setdefault("_serving_jit", {}))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    return ContinuousBatchingEngine(model, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _reqs(sampled=False, n_reqs=4, max_new=8):
    sys_p = [_prompt(100 + i, 24) for i in range(2)]
    out = []
    for i in range(n_reqs):
        tail = np.tile(_prompt(i, 4), 3).astype(np.int32)
        kw = dict(max_new_tokens=max_new)
        if sampled:
            kw.update(temperature=0.8, top_k=20, seed=500 + i)
        out.append(GenerationRequest(
            prompt=np.concatenate([sys_p[i % 2], tail]), **kw))
    return out


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             seed=r.seed, eos_token_id=r.eos_token_id)


def _run(eng, reqs):
    return [list(o) for o in eng.generate([_clone(r) for r in reqs])]


def _match_fraction(a, b):
    fracs = []
    for x, y in zip(a, b):
        m = 0
        for t, u in zip(x, y):
            if t != u:
                break
            m += 1
        fracs.append(m / max(len(x), 1))
    return sum(fracs) / len(fracs)


# -------------------------------------------- rows: roundtrip properties
class TestRoundtripProperties:
    """Randomized quantize/dequantize roundtrip bounds across int8 AND
    fp8 rows — the error model each write rule promises, checked over
    many magnitude regimes, never a single lucky draw."""

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_int8_rows_bounded_by_half_scale(self, seed):
        rng = np.random.RandomState(seed)
        x = rng.randn(4, 6, 3, 16).astype(np.float32) * \
            rng.uniform(1e-3, 100.0, (4, 6, 3, 1)).astype(np.float32)
        q, s = quantize_kv_rows(x)
        q, s = np.asarray(q), np.asarray(s)
        deq = q.astype(np.float32) * s[..., None]
        assert np.all(np.abs(deq - x) <= s[..., None] / 2 + 1e-7)
        assert np.abs(q).max() <= 127

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_fp8_rows_bounded_by_e4m3_relative_step(self, seed):
        """e4m3 round-to-nearest: relative error <= 2^-4 for normals,
        absolute error <= 2^-10 in the subnormal range — with NO scale
        (the per-block planes are the constant 1.0 by design)."""
        rng = np.random.RandomState(seed)
        x = rng.randn(4, 6, 3, 16).astype(np.float32) * \
            rng.uniform(1e-3, 64.0, (4, 6, 3, 1)).astype(np.float32)
        f8 = np.asarray(quantize_kv_rows_fp8(x))
        assert f8.dtype == np.dtype("float8_e4m3fn")
        deq = f8.astype(np.float32)
        bound = np.maximum(np.abs(x) * 2.0 ** -4, 2.0 ** -10)
        assert np.all(np.abs(deq - x) <= bound + 1e-7)
        assert np.all(np.isfinite(deq))

    def test_fp8_saturates_instead_of_nan(self):
        x = np.array([[-1e6, -FP8_MAX, 0.0, FP8_MAX, 1e6]],
                     np.float32)
        deq = np.asarray(quantize_kv_rows_fp8(x)).astype(np.float32)
        np.testing.assert_array_equal(
            deq, [[-FP8_MAX, -FP8_MAX, 0.0, FP8_MAX, FP8_MAX]])

    def test_fp8_zero_rows_exact_and_sign_preserving(self):
        deq = np.asarray(quantize_kv_rows_fp8(
            np.zeros((2, 4, 3, 8), np.float32))).astype(np.float32)
        assert np.all(deq == 0.0)


# --------------------------------------------------- pool byte accounting
class TestFp8PoolBytes:
    def test_per_block_planes_and_strictly_cheaper_tokens(self, model):
        i8 = _engine(model, kv_dtype="int8")
        f8 = _engine(model, kv_dtype="fp8")
        c = model.config
        L, Hkv, D = (c.num_hidden_layers, c.num_key_value_heads,
                     c.head_dim)
        pool = f8.cache.pool
        assert pool.k.dtype == np.dtype("float8_e4m3fn")
        # per-BLOCK planes, initialized to the constant 1.0
        assert pool.k_scale.shape == (L, pool.num_blocks, Hkv)
        assert np.all(np.asarray(pool.k_scale) == 1.0)
        ob8, obf = (i8.cache.occupancy_bytes(),
                    f8.cache.occupancy_bytes())
        # identical data bytes (1 byte/elem both), block_size x fewer
        # scale bytes — so fp8's cached token is STRICTLY cheaper
        nb = f8.cache.pool.num_blocks
        assert obf["capacity_scales"] == 2 * L * nb * Hkv * 4
        assert obf["per_token"] == 2 * L * Hkv * (D + 4 / BS)
        assert obf["per_token"] < ob8["per_token"]

    def test_write_prefill_saturating_cast_scales_untouched(self, model):
        from paddle_tpu.serving.kv_cache import PagedKVCache
        c = model.config
        cache = PagedKVCache(c.num_hidden_layers, 2, 64,
                             c.num_key_value_heads, c.head_dim,
                             block_size=BS, kv_dtype="fp8")
        rng = np.random.RandomState(3)
        L, Hkv, D = (c.num_hidden_layers, c.num_key_value_heads,
                     c.head_dim)
        pk = rng.randn(L, 16, Hkv, D).astype(np.float32) * 100.0
        pv = rng.randn(L, 16, Hkv, D).astype(np.float32)
        slot = cache.alloc()
        cache.write_prefill(slot, pk, pv, 11)
        blocks = cache.slot_block_ids(slot)
        got = np.asarray(cache.pool.k)[:, blocks].reshape(L, -1, Hkv, D)
        want = np.asarray(quantize_kv_rows_fp8(pk))
        np.testing.assert_array_equal(
            got[:, :11].astype(np.float32),
            want[:, :11].astype(np.float32))
        # the scale planes were never written: constant 1.0 planes are
        # what makes restore()-by-recompute byte-identical on fp8
        assert np.all(np.asarray(cache.pool.k_scale) == 1.0)
        assert np.all(np.asarray(cache.pool.v_scale) == 1.0)


# ------------------------------------------------------------ validation
class TestValidation:
    def test_fp8_requires_unified_ragged_paged(self, model):
        with pytest.raises(ValueError, match="unified ragged"):
            _engine(model, kv_dtype="fp8", paged_attn=False)
        with pytest.raises(ValueError, match="unified ragged"):
            _engine(model, kv_dtype="fp8", ragged_step=False)

    def test_a8_requires_weight_quant(self, model):
        with pytest.raises(ValueError, match="quantize_weights"):
            _engine(model, quantize_activations=True)

    def test_a8_requires_unified_ragged_paged(self, model):
        with pytest.raises(ValueError, match="unified ragged"):
            _engine(model, quantize_weights=True,
                    quantize_activations=True, ragged_step=False)

    def test_shared_pool_mode_mismatch_raises(self, model):
        """An int8-pool trie adopted by an fp8 engine is a geometry
        error at build, not an opaque XLA failure at first hit."""
        int8 = _engine(model, kv_dtype="int8", prefix_cache=True)
        with pytest.raises(ValueError, match="kv_dtype"):
            _engine(model, kv_dtype="fp8",
                    prefix_cache=int8.prefix_cache)


# --------------------------------------------------------------- streams
class TestStreams:
    def test_fp8_greedy_divergence_measured_and_bounded(self, model):
        base = _run(_engine(model), _reqs())
        f8 = _run(_engine(model, kv_dtype="fp8"), _reqs())
        assert [len(s) for s in f8] == [len(s) for s in base]
        frac = _match_fraction(base, f8)
        assert frac >= 0.75, f"fp8 greedy matched-prefix fraction {frac}"

    @pytest.mark.slow  # sampled duplicate of the greedy bound above
    def test_fp8_sampled_divergence_measured_and_bounded(self, model):
        base = _run(_engine(model), _reqs(sampled=True))
        f8 = _run(_engine(model, kv_dtype="fp8"), _reqs(sampled=True))
        frac = _match_fraction(base, f8)
        assert frac >= 0.75, f"fp8 sampled matched-prefix fraction {frac}"

    def test_a8_divergence_measured_and_bounded(self, model):
        base = _run(_engine(model), _reqs())
        a8 = _run(_engine(model, quantize_weights=True,
                          quantize_activations=True), _reqs())
        frac = _match_fraction(base, a8)
        assert frac >= 0.5, f"a8 matched-prefix fraction {frac}"

    @pytest.mark.parametrize(
        "sampled", [False, pytest.param(True, marks=pytest.mark.slow)])
    def test_fp8_and_a8_deterministic_across_replays(self, model,
                                                     sampled):
        for kw in (dict(kv_dtype="fp8"),
                   dict(quantize_weights=True,
                        quantize_activations=True),
                   dict(kv_dtype="fp8", quantize_weights=True,
                        quantize_activations=True)):
            a = _run(_engine(model, **kw), _reqs(sampled))
            b = _run(_engine(model, **kw), _reqs(sampled))
            assert a == b, kw

    def test_default_path_unchanged_by_lowprec_siblings(self, model):
        before = _run(_engine(model), _reqs())
        _run(_engine(model, kv_dtype="fp8", quantize_weights=True,
                     quantize_activations=True), _reqs())
        after = _run(_engine(model), _reqs())
        assert before == after


# --------------------------------------------------- compile discipline
class TestCompileDiscipline:
    @pytest.mark.slow  # 9 s four-engine matrix duplicate: the tag-keying
    # test below asserts compile-once for fp/fp8/a8 by default (870s cap)
    def test_compile_once_inclusive_of_kv8f_and_a8(self, model):
        jit = {}
        engines = {
            "fp": _engine(model, jit_cache=jit),
            "fp8": _engine(model, kv_dtype="fp8", jit_cache=jit),
            "a8": _engine(model, quantize_weights=True,
                          quantize_activations=True, jit_cache=jit),
            "all": _engine(model, kv_dtype="fp8", quantize_weights=True,
                           quantize_activations=True, jit_cache=jit),
        }
        for eng in engines.values():
            _run(eng, _reqs())
            _run(eng, _reqs(sampled=True))
        for name, eng in engines.items():
            assert eng.decode_compilations() == 1, name
        pre = {n: e.prefill_compilations() for n, e in engines.items()}
        for eng in engines.values():
            _run(eng, _reqs())
        assert {n: e.prefill_compilations()
                for n, e in engines.items()} == pre

    def test_kv8f_and_a8_tags_key_programs_apart(self, model):
        jit = {}
        fp = _engine(model, jit_cache=jit)
        f8 = _engine(model, kv_dtype="fp8", jit_cache=jit)
        a8 = _engine(model, quantize_weights=True,
                     quantize_activations=True, jit_cache=jit)
        for e in (fp, f8, a8):
            _run(e, _reqs(n_reqs=1))
        keys = set(jit)
        attn = model.config.decode_attention
        assert ("ragged", 2, 2 + CHUNK, 1, attn) in keys
        assert ("ragged", 2, 2 + CHUNK, 1, attn, "kv8f") in keys
        assert ("ragged", 2, 2 + CHUNK, 1, attn, "w8", "a8") in keys
        assert fp.decode_compilations() == 1
        assert f8.decode_compilations() == 1
        assert a8.decode_compilations() == 1


# ------------------------------------------------------------ composition
class TestComposition:
    """fp8/a8 x the step machinery: every combination's streams are
    byte-identical to its own tick-at-a-time low-precision baseline."""

    @pytest.mark.parametrize(
        "sampled", [False, pytest.param(True, marks=pytest.mark.slow)])
    def test_spec_decode_byte_identical_on_fp8(self, model, sampled):
        base = _run(_engine(model, kv_dtype="fp8"), _reqs(sampled))
        spec = _run(_engine(model, kv_dtype="fp8", spec_decode=True,
                            spec_k=3), _reqs(sampled))
        assert spec == base

    @pytest.mark.parametrize(
        "sampled", [False, pytest.param(True, marks=pytest.mark.slow)])
    def test_multitick_byte_identical_on_fp8(self, model, sampled):
        base = _run(_engine(model, kv_dtype="fp8"), _reqs(sampled))
        mt = _run(_engine(model, kv_dtype="fp8", decode_ticks=4),
                  _reqs(sampled))
        assert mt == base

    def test_spec_and_multitick_byte_identical_on_a8(self, model):
        kw = dict(quantize_weights=True, quantize_activations=True)
        base = _run(_engine(model, **kw), _reqs())
        spec = _run(_engine(model, spec_decode=True, spec_k=3, **kw),
                    _reqs())
        mt = _run(_engine(model, decode_ticks=4, **kw), _reqs())
        assert spec == base and mt == base

    @pytest.mark.parametrize("kw", [
        dict(kv_dtype="fp8"),
        dict(quantize_weights=True, quantize_activations=True),
    ], ids=["fp8", "a8"])
    def test_tp2_byte_identical_to_single_chip(self, model, kw):
        base = _run(_engine(model, **kw), _reqs())
        tp = _run(_engine(model, tp=2, **kw), _reqs())
        assert tp == base

    def test_preempt_restore_byte_identical_on_fp8(self, model):
        from paddle_tpu.serving.faults import FaultPlan
        want = _run(_engine(model, kv_dtype="fp8", prefix_cache=True),
                    _reqs())
        eng = _engine(model, kv_dtype="fp8", prefix_cache=True)
        FaultPlan().at_step(3, "pool").install(eng)
        got = _run(eng, _reqs())
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["restores"] >= 1
        assert got == want


# ------------------------------------------------- tier + fleet lifecycle
#: two 2-block system-prompt families: under a 2-block trie budget,
#: alternating them thrashes — every switch spills, every return readmits
_FAMS = [np.random.RandomState(300 + f).randint(
    0, 256, (2 * BS,)).astype(np.int32) for f in range(2)]


def _fam_req(fam, tail_seed, **kw):
    tail = np.random.RandomState(tail_seed).randint(
        0, 256, (6,)).astype(np.int32)
    kw.setdefault("max_new_tokens", 6)
    return GenerationRequest(
        prompt=np.concatenate([_FAMS[fam], tail]), **kw)


def _serial(eng, reqs):
    return [eng.generate([_clone(r)])[0].tolist() for r in reqs]


class TestTierAndFleet:
    def test_fp8_tier_spill_readmit_byte_identical(self, model):
        """The fp8 pool's per-block planes spill and readmit alongside
        the e4m3 data (one tier entry, block-id-keyed like int8's) with
        streams byte-identical to the tier-off fp8 engine."""
        reqs = [_fam_req(f, 10 * f + i, **(
            dict(temperature=0.8, top_k=5, seed=700 + f) if i == 1
            else {}))
            for i in range(3) for f in (0, 1)]
        jit = {}  # private: count THIS geometry's programs, not the
        # fp8 mtick/spec siblings the module's shared cache holds
        off = _engine(model, kv_dtype="fp8", prefix_cache=True,
                      prefix_blocks=2, jit_cache=jit)
        want = _serial(off, reqs)
        eng = _engine(model, kv_dtype="fp8", prefix_cache=True,
                      prefix_blocks=2, host_tier_bytes=1 << 24,
                      jit_cache=jit)
        pc = eng.prefix_cache
        assert _serial(eng, reqs) == want
        assert pc.stats["spilled_blocks"] > 0
        assert pc.stats["readmitted_blocks"] > 0
        # a resident entry carries e4m3 data + the 2-D per-block planes
        with pc.tier._lock:
            bufs = next(iter(pc.tier._entries.values()))[0]
        assert set(bufs) == {"k", "v", "k_scale", "v_scale"}
        assert bufs["k"].dtype == np.dtype("float8_e4m3fn")
        assert bufs["k_scale"].dtype == np.float32
        assert bufs["k_scale"].shape[1] == 1      # [L, 1, Hkv]: 1 block
        assert np.all(bufs["k_scale"] == 1.0)
        assert eng.decode_compilations() == 1

    def test_fp8_fleet_migration_byte_identical(self, model):
        """Live migration off an fp8-pool replica: evict donates the
        quantized chain + PRNG snapshot, adopt restores by recompute on
        the sibling's fp8 pool — stream byte-identical to an unmigrated
        fp8 single-engine run."""
        import time
        req = GenerationRequest(prompt=_prompt(7, 12),
                                max_new_tokens=40)
        want = _run(_engine(model, kv_dtype="fp8"), [req])[0]
        fl = EngineFleet(model, replicas=2, router="least-loaded",
                         num_slots=2, max_seq_len=96,
                         prefix_block_size=BS, prefill_chunk=CHUNK,
                         kv_dtype="fp8", max_queue=8,
                         retry_backoff_s=0.0, start=True)
        try:
            st = fl.submit(_clone(req))
            deadline = time.monotonic() + 30
            while not (st.seq is not None and len(st.seq.tokens) >= 8):
                assert time.monotonic() < deadline
                time.sleep(0.005)
            fl.migrate(st, target=1)
            ids, reason = st.result()
            assert ids.tolist() == want and reason == "length"
            assert st.gateway is fl.replicas[1].gateway
            assert fl._m_migrated.value(cause="migration") == 1
        finally:
            fl.shutdown(drain=True, timeout=30)
