"""Prometheus text-exposition helpers (profiler/metrics.py): the
counter/gauge/histogram layer the serving gateway's ``GET /metrics``
renders through. The parser here is intentionally strict about the
v0.0.4 text format — the same parser validates live scrapes in
tests/test_serving_server.py."""
import math
import re
import threading

import pytest

from paddle_tpu.profiler.metrics import (Counter, Gauge, Histogram,
                                         MetricsRegistry)

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})? '
    r'(?P<value>[^ ]+)$')
_LABEL_RE = re.compile(r'^(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>.*)"$')


def parse_prometheus(text):
    """Parse exposition text -> {family: {"type", "help", "samples"}}
    with samples as {(name, label_items): float}. Raises AssertionError
    on any format violation (samples before TYPE, bad label syntax,
    non-float values, missing trailing newline)."""
    assert text.endswith("\n"), "exposition must end with a newline"
    fams, cur = {}, None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            fams.setdefault(name, {"help": help_, "samples": {}})
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "histogram", "summary")
            fams.setdefault(name, {"samples": {}})["type"] = kind
            cur = name
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name = m.group("name")
        labels = []
        if m.group("labels"):
            for pair in re.findall(r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|'
                                   r'\\.)*"', m.group("labels")):
                lm = _LABEL_RE.match(pair)
                assert lm, f"malformed label: {pair!r}"
                labels.append((lm.group("k"), lm.group("v")))
        v = m.group("value")
        value = math.inf if v == "+Inf" else \
            -math.inf if v == "-Inf" else float(v)
        # samples must belong to the most recent TYPE'd family
        assert cur is not None and name.startswith(cur), \
            f"sample {name} outside its family block (cur={cur})"
        fams[cur]["samples"][(name, tuple(labels))] = value
    return fams


class TestCounter:
    def test_inc_and_expose(self):
        c = Counter("requests_total", "Total requests.")
        c.inc()
        c.inc(4)
        text = "\n".join(c.expose()) + "\n"
        fams = parse_prometheus(text)
        assert fams["requests_total"]["type"] == "counter"
        assert fams["requests_total"]["samples"][
            ("requests_total", ())] == 5

    def test_labels_sorted_and_separate(self):
        c = Counter("finished_total")
        c.inc(reason="stop")
        c.inc(reason="timeout")
        c.inc(2, reason="stop")
        s = parse_prometheus("\n".join(c.expose()) + "\n")[
            "finished_total"]["samples"]
        assert s[("finished_total", (("reason", "stop"),))] == 3
        assert s[("finished_total", (("reason", "timeout"),))] == 1

    def test_decrease_rejected(self):
        c = Counter("n")
        with pytest.raises(ValueError, match="cannot decrease"):
            c.inc(-1)


class TestGauge:
    def test_set_inc_dec(self):
        g = Gauge("queue_depth")
        g.set(7)
        g.inc()
        g.dec(3)
        assert g.value() == 5

    def test_scrape_time_callable(self):
        """set_fn gauges sample at render time — the gateway points
        these at engine state so a scrape can never be stale."""
        depth = [3]
        g = Gauge("active_slots")
        g.set_fn(lambda: depth[0])
        assert "active_slots 3" in g.expose()
        depth[0] = 9
        assert "active_slots 9" in g.expose()


class TestHistogram:
    def test_buckets_cumulative_sum_count(self):
        h = Histogram("latency_seconds", "Request latency.",
                      buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        fams = parse_prometheus("\n".join(h.expose()) + "\n")
        s = fams["latency_seconds"]["samples"]
        assert fams["latency_seconds"]["type"] == "histogram"

        def bucket(le):
            return s[("latency_seconds_bucket", (("le", le),))]

        assert bucket("0.1") == 1
        assert bucket("1") == 3      # cumulative, not per-bin
        assert bucket("10") == 4
        assert bucket("+Inf") == 5
        assert s[("latency_seconds_count", ())] == 5
        assert s[("latency_seconds_sum", ())] == pytest.approx(56.05)

    def test_bucket_monotonicity_invariant(self):
        h = Histogram("x", buckets=(1, 2, 4, 8))
        import random
        rng = random.Random(3)
        for _ in range(200):
            h.observe(rng.uniform(0, 10))
        s = parse_prometheus("\n".join(h.expose()) + "\n")["x"]["samples"]
        buckets = {float(lab[0][1].replace("+Inf", "inf")): v
                   for (name, lab), v in s.items() if name == "x_bucket"}
        counts = [buckets[le] for le in sorted(buckets)]
        assert counts == sorted(counts)  # cumulative ⇒ non-decreasing
        assert counts[-1] == 200

    def test_quantile_interpolates_within_bucket(self):
        """quantile(): histogram_quantile-style linear interpolation —
        exact at bucket boundaries, proportional inside, clamped to the
        last finite bound past it, 0 on an empty series."""
        h = Histogram("q", buckets=(1.0, 2.0, 4.0))
        assert h.quantile(0.5) == 0.0            # empty
        for v in (0.5, 1.5, 1.5, 3.0):           # counts: 1, 3, 4
            h.observe(v)
        # rank 2 of 4 lands in (1, 2]: prev_count 1, bucket count 3
        assert h.quantile(0.5) == pytest.approx(1.0 + (2 - 1) / (3 - 1))
        # target rank == a bucket's cumulative count -> its upper bound
        assert h.quantile(0.25) == pytest.approx(1.0)
        # fractional rank inside the first bucket interpolates from 0
        assert h.quantile(0.125) == pytest.approx(0.5)
        assert h.quantile(1.0) == pytest.approx(4.0)
        h.observe(100.0)                         # beyond the ladder
        assert h.quantile(0.99) == 4.0           # clamps to last bound
        with pytest.raises(ValueError, match="quantile"):
            h.quantile(0.0)
        with pytest.raises(ValueError, match="quantile"):
            h.quantile(1.5)

    def test_ttft_ladder_resolves_sub_ms(self):
        """The serving_ttft_seconds ladder (TTFT_BUCKETS) keeps sub-ms
        resolution at the low end and spans to 30s — the p95 of a
        tight sub-ms population must not collapse into one giant
        default bucket."""
        from paddle_tpu.profiler.metrics import (DEFAULT_BUCKETS,
                                                 TTFT_BUCKETS)
        assert TTFT_BUCKETS[0] < DEFAULT_BUCKETS[0]
        h = Histogram("ttft", buckets=TTFT_BUCKETS)
        for _ in range(100):
            h.observe(0.0008)
        assert h.quantile(0.95) <= 0.001   # resolved, not smeared to 5ms

    def test_step_ladder_strict_parsed_and_resolves_fast_steps(self):
        """The serving_step_duration_seconds ladder (STEP_BUCKETS) —
        the same signal the engine's headroom-adaptive chunk budget
        reads — resolves sub-ms on-chip steps AND tens-of-ms CPU steps,
        and a histogram on it renders valid under the strict parser."""
        from paddle_tpu.profiler.metrics import (MetricsRegistry,
                                                 STEP_BUCKETS)
        assert STEP_BUCKETS[0] <= 0.0005       # real-chip step floor
        assert STEP_BUCKETS[-1] >= 10.0        # wedged-step ceiling
        assert list(STEP_BUCKETS) == sorted(STEP_BUCKETS)
        r = MetricsRegistry()
        h = r.histogram("serving_step_duration_seconds",
                        "Engine step() wall duration.",
                        buckets=STEP_BUCKETS)
        for v in (0.0003, 0.02, 0.02, 1.5):
            h.observe(v)
        fams = parse_prometheus(r.render())
        name = "serving_step_duration_seconds"
        assert fams[name]["type"] == "histogram"
        assert fams[name]["samples"][(name + "_count", ())] == 4
        bounds = {lbl[1] for key, lbls in fams[name]["samples"]
                  if key == name + "_bucket" for lbl in lbls
                  if lbl[0] == "le"}
        assert len(bounds) == len(STEP_BUCKETS) + 1   # ladder + +Inf
        # CPU steps land mid-ladder, not smeared into +Inf
        assert h.quantile(0.5) <= 0.025

    def test_spec_accept_ladder_strict_parsed_integer_resolved(self):
        """The serving_spec_accept_length ladder (SPEC_ACCEPT_BUCKETS)
        — tokens emitted per speculative verify span — gives every
        practical acceptance count (1 .. spec_k+1 for spec_k <= 5) its
        own bucket, and a histogram on it renders valid under the
        strict parser. The engine-level drain into this histogram is
        pinned in tests/test_spec_decode.py."""
        from paddle_tpu.profiler.metrics import (SPEC_ACCEPT_BUCKETS,
                                                 MetricsRegistry)
        assert SPEC_ACCEPT_BUCKETS[0] == 1.0   # nothing-accepted floor
        assert list(SPEC_ACCEPT_BUCKETS) == sorted(SPEC_ACCEPT_BUCKETS)
        assert set(SPEC_ACCEPT_BUCKETS[:6]) == {1, 2, 3, 4, 5, 6}
        r = MetricsRegistry()
        h = r.histogram("serving_spec_accept_length",
                        "Tokens emitted per verify span.",
                        buckets=SPEC_ACCEPT_BUCKETS)
        for v in (1, 1, 4, 2):
            h.observe(v)
        fams = parse_prometheus(r.render())
        name = "serving_spec_accept_length"
        assert fams[name]["type"] == "histogram"
        assert fams[name]["samples"][(name + "_count", ())] == 4
        assert fams[name]["samples"][(name + "_sum", ())] == 8
        bounds = {lbl[1] for key, lbls in fams[name]["samples"]
                  if key == name + "_bucket" for lbl in lbls
                  if lbl[0] == "le"}
        assert len(bounds) == len(SPEC_ACCEPT_BUCKETS) + 1
        # integer counts resolve exactly: the le="1" bucket holds only
        # the nothing-accepted spans
        assert fams[name]["samples"][
            (name + "_bucket", (("le", "1"),))] == 2

    def test_empty_buckets_rejected(self):
        with pytest.raises(ValueError, match="at least one bucket"):
            Histogram("x", buckets=())


class TestRegistry:
    def test_render_whole_registry(self):
        reg = MetricsRegistry()
        reg.counter("a_total", "A.").inc(2)
        reg.gauge("b", "B.").set(1.5)
        reg.histogram("c_seconds", buckets=(1.0,)).observe(0.5)
        fams = parse_prometheus(reg.render())
        assert set(fams) == {"a_total", "b", "c_seconds"}
        assert fams["b"]["samples"][("b", ())] == 1.5

    def test_reregister_returns_same_instance(self):
        reg = MetricsRegistry()
        c1 = reg.counter("x_total")
        c2 = reg.counter("x_total")
        assert c1 is c2
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_thread_safety_counts_exact(self):
        """8 threads x 1000 incs: the registry lock discipline loses
        nothing (the gateway's driver + HTTP threads hit this path)."""
        reg = MetricsRegistry()
        c = reg.counter("hits_total")

        def work():
            for _ in range(1000):
                c.inc()

        ts = [threading.Thread(target=work) for _ in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert c.value() == 8000
