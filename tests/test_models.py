"""Model-zoo tests: the five BASELINE configs at tiny scale, serial and on
the hybrid mesh (parallel-vs-serial parity for the flagship)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW, SGD


def _reset_fleet(**degrees):
    from paddle_tpu.parallel import mesh as mesh_mod
    mesh_mod._STATE["mesh"] = None
    s = fleet.DistributedStrategy()
    s.hybrid_configs = degrees
    fleet.init(is_collective=True, strategy=s)
    return fleet.get_hybrid_communicate_group()


def _no_mesh():
    from paddle_tpu.parallel import mesh as mesh_mod
    mesh_mod._STATE["mesh"] = None


def _tokens(b, s, v, seed=0):
    return np.random.RandomState(seed).randint(0, v, (b, s)).astype(np.int32)


class TestLlama:
    def test_forward_shapes(self):
        _no_mesh()
        paddle.seed(0)
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        cfg = llama_tiny()
        m = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(_tokens(2, 16, cfg.vocab_size))
        logits = m(ids)
        assert logits.shape == [2, 16, cfg.vocab_size]
        loss = m(ids, ids)
        assert loss.ndim == 0

    def test_train_converges_serial(self):
        _no_mesh()
        paddle.seed(1)
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        cfg = llama_tiny(use_recompute=False)
        m = LlamaForCausalLM(cfg)
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda loss, _lab: loss, opt)
        ids = paddle.to_tensor(_tokens(4, 16, cfg.vocab_size))
        losses = [float(step.step((ids, ids), (ids,)).value) for _ in range(8)]
        assert losses[-1] < losses[0]

    def test_recompute_matches_no_recompute(self):
        _no_mesh()
        paddle.seed(2)
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        m1 = LlamaForCausalLM(llama_tiny(use_recompute=False))
        m2 = LlamaForCausalLM(llama_tiny(use_recompute=True))
        m2.set_state_dict(m1.state_dict())
        ids = paddle.to_tensor(_tokens(2, 8, 256))
        l1 = m1(ids, ids)
        l2 = m2(ids, ids)
        np.testing.assert_allclose(float(l1.value), float(l2.value), rtol=1e-5)

    @pytest.mark.slow  # optional config (bench measured it slower than
    # unfused); kernel-level fused-rope grads stay default in pallas tests
    def test_fuse_rope_matches_unfused(self):
        """LlamaConfig.fuse_rope (rope inside the flash kernels, VERDICT
        r3 item 9): loss and grads must match the rope-outside path. On
        CPU the Pallas path is skipped, so force interpret mode to run the
        actual fused kernels."""
        from paddle_tpu.kernels import pallas_flash
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        from paddle_tpu.utils.flags import set_flags
        _no_mesh()
        paddle.seed(5)
        base = dict(use_recompute=False, attention_layout="bhsd",
                    num_key_value_heads=4, max_position_embeddings=256)
        m1 = LlamaForCausalLM(llama_tiny(**base))
        m2 = LlamaForCausalLM(llama_tiny(fuse_rope=True, **base))
        m2.set_state_dict(m1.state_dict())
        ids = paddle.to_tensor(_tokens(2, 128, 256))
        # jnp fallback parity (the path CI normally runs)
        l1, l2 = m1(ids, ids), m2(ids, ids)
        np.testing.assert_allclose(float(l1.value), float(l2.value),
                                   rtol=1e-5)
        # actual fused kernels via interpret mode
        import paddle_tpu.models.llama as llama_mod
        orig = llama_mod._attention_bhsd
        pallas_flash._FORCE_INTERPRET[0] = True

        def force_pallas(q, k, v, nh, rope=None, block_q=0, block_k=0):
            import jax.numpy as jnp

            from paddle_tpu.kernels.pallas_flash import flash_attention_bhsd
            B, Hq, S, D = q.shape
            Hk = k.shape[1]
            if Hk != Hq:
                k = jnp.repeat(k, Hq // Hk, axis=1)
                v = jnp.repeat(v, Hq // Hk, axis=1)
            o = flash_attention_bhsd(q.reshape(B * Hq, S, D),
                                     k.reshape(B * Hq, S, D),
                                     v.reshape(B * Hq, S, D), causal=True,
                                     block_q=128, block_k=128, rope=rope)
            return o.reshape(B, Hq, S, D)

        llama_mod._attention_bhsd = force_pallas
        try:
            l3 = m2(ids, ids)
        finally:
            llama_mod._attention_bhsd = orig
            pallas_flash._FORCE_INTERPRET[0] = False
        np.testing.assert_allclose(float(l1.value), float(l3.value),
                                   rtol=2e-4)

    @pytest.mark.slow  # 7 s mesh-parity duplicate: test_train_converges_serial
    # above is the default Llama train rep (870s cap)
    def test_hybrid_mesh_parity(self):
        """Flagship path: dp2 x mp2 x pp2 (+sharding1) matches serial."""
        paddle.seed(3)
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        cfg = llama_tiny(use_recompute=False)
        _no_mesh()
        m1 = LlamaForCausalLM(cfg)
        s_step = TrainStep(m1, lambda loss, _: loss,
                           AdamW(learning_rate=1e-3,
                                 parameters=m1.parameters()))
        ids = paddle.to_tensor(_tokens(4, 16, cfg.vocab_size))
        serial_losses = [float(s_step.step((ids, ids), (ids,)).value)
                         for _ in range(3)]

        hcg = _reset_fleet(dp_degree=2, mp_degree=2, pp_degree=2)
        m2 = LlamaForCausalLM(cfg)
        m2.set_state_dict(m1.state_dict())
        # m1 already trained 3 steps; reset from ORIGINAL state instead
        paddle.seed(3)
        m3 = LlamaForCausalLM(cfg)
        m2.set_state_dict(m3.state_dict())
        h_step = TrainStep(m2, lambda loss, _: loss,
                           AdamW(learning_rate=1e-3,
                                 parameters=m2.parameters()),
                           mesh=hcg.mesh)
        hybrid_losses = [float(h_step.step((ids, ids), (ids,)).value)
                         for _ in range(3)]
        np.testing.assert_allclose(serial_losses, hybrid_losses, rtol=1e-3,
                                   atol=1e-4)

    def test_hybrid_hlo_has_collectives(self):
        paddle.seed(4)
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        hcg = _reset_fleet(dp_degree=2, mp_degree=2, pp_degree=2)
        cfg = llama_tiny(use_recompute=False)
        m = LlamaForCausalLM(cfg)
        step = TrainStep(m, lambda loss, _: loss,
                         AdamW(learning_rate=1e-3, parameters=m.parameters()),
                         mesh=hcg.mesh)
        ids = paddle.to_tensor(_tokens(4, 16, cfg.vocab_size))
        hlo = step.lower_text((ids, ids), (ids,))
        assert "all-reduce" in hlo

    def test_params_sharded_on_mesh(self):
        paddle.seed(5)
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        hcg = _reset_fleet(mp_degree=2, pp_degree=2, dp_degree=2)
        cfg = llama_tiny()
        m = LlamaForCausalLM(cfg)
        step = TrainStep(m, lambda loss, _: loss,
                         SGD(learning_rate=0.1, parameters=m.parameters()),
                         mesh=hcg.mesh)
        wq = step.params["wq"]  # [L=4, H=64, nh*hd=64], spec (pp, None, mp)
        assert wq.addressable_shards[0].data.shape == (2, 64, 32)


class TestGPT:
    @pytest.mark.slow  # DP training covered by the llama/parallel reps;
    # gpt_mp_matches_serial stays as GPT's default parity test
    def test_gpt_dp_training(self):
        paddle.seed(10)
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        hcg = _reset_fleet(dp_degree=8)
        cfg = gpt_tiny(use_mp_layers=False)
        m = GPTForCausalLM(cfg)
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda loss, _: loss, opt, mesh=hcg.mesh)
        ids = paddle.to_tensor(_tokens(8, 16, cfg.vocab_size))
        losses = [float(step.step((ids, ids), (ids,)).value) for _ in range(5)]
        assert losses[-1] < losses[0]

    def test_gpt_mp_matches_serial(self):
        paddle.seed(11)
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        _no_mesh()
        serial = GPTForCausalLM(gpt_tiny(use_mp_layers=False))
        hcg = _reset_fleet(mp_degree=8)
        mp_model = GPTForCausalLM(gpt_tiny(use_mp_layers=True))
        # align weights (same names/shapes across both variants)
        mp_model.set_state_dict(serial.state_dict())
        ids = paddle.to_tensor(_tokens(2, 8, 128))
        serial.eval()
        mp_model.eval()
        l_s = serial(ids, ids)
        l_m = mp_model(ids, ids)
        np.testing.assert_allclose(float(l_s.value), float(l_m.value),
                                   rtol=1e-4)


class TestErnieViL:
    @pytest.mark.slow  # training-run family (VERDICT r5 weak 3 tiering);
    # test_encoders below stays the ErnieViL default-run representative
    def test_contrastive_training(self):
        _no_mesh()
        paddle.seed(20)
        from paddle_tpu.models import ErnieViLModel, ernie_vil_tiny
        cfg = ernie_vil_tiny()
        m = ErnieViLModel(cfg)
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda loss, _: loss, opt)
        rng = np.random.RandomState(0)
        imgs = rng.randn(4, 3, 32, 32).astype(np.float32)
        txts = rng.randint(0, 128, (4, 16)).astype(np.int32)
        losses = []
        for _ in range(6):
            losses.append(float(step.step(
                (paddle.to_tensor(imgs), paddle.to_tensor(txts)),
                (paddle.to_tensor(np.zeros(1, np.float32)),)).value))
        assert losses[-1] < losses[0]

    def test_encoders(self):
        _no_mesh()
        paddle.seed(21)
        from paddle_tpu.models import ErnieViLModel, ernie_vil_tiny
        m = ErnieViLModel(ernie_vil_tiny())
        img = paddle.to_tensor(np.random.randn(2, 3, 32, 32).astype(np.float32))
        feats = m.encode_image(img)
        assert feats.shape == [2, 32]


class TestMoEGPT:
    @pytest.mark.slow  # 8 s MoE train duplicate: test_moe_ep_mesh below and
    # test_parallel.py TestMoE keep the default MoE-train reps (870s cap)
    def test_moe_training(self):
        _no_mesh()
        paddle.seed(30)
        from paddle_tpu.models import MoEGPTForCausalLM, moe_tiny
        cfg = moe_tiny()
        m = MoEGPTForCausalLM(cfg)
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda loss, _: loss, opt)
        ids = paddle.to_tensor(_tokens(4, 16, cfg.vocab_size))
        losses = [float(step.step((ids, ids), (ids,)).value) for _ in range(6)]
        assert losses[-1] < losses[0]

    @pytest.mark.slow  # model-level EP-mesh step; test_moe_ep's
    # ep_mesh_parity_vs_meshless stays the default EP-on-mesh rep and
    # test_moe_training above keeps MoEGPT training default
    def test_moe_ep_mesh(self):
        paddle.seed(31)
        from paddle_tpu.models import MoEGPTForCausalLM, moe_tiny
        hcg = _reset_fleet(mp_degree=4, dp_degree=2)
        cfg = moe_tiny()
        m = MoEGPTForCausalLM(cfg)
        step = TrainStep(m, lambda loss, _: loss,
                         AdamW(learning_rate=1e-3, parameters=m.parameters()),
                         mesh=hcg.mesh)
        ids = paddle.to_tensor(_tokens(4, 16, cfg.vocab_size))
        l = step.step((ids, ids), (ids,))
        assert np.isfinite(float(l.value))
