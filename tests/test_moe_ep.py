"""Expert-parallel MoE tests (VERDICT r2 item 2).

The r2 MoE ran experts as a replicated Python loop. These test the real EP
path: stacked expert weights sharded over the expert mesh axis, GShard
group-wise dispatch, and the all-to-all the reference implements as CUDA
``global_scatter``/``global_gather``
(``python/paddle/incubate/distributed/models/moe/moe_layer.py`` †):
- parity vs a dense FFN oracle when all experts are identical and capacity
  is effectively infinite (top-2 weights renormalize to 1)
- expert residency: each device holds E/ep experts (addressable_shards)
- compile: all-to-all present in the HLO on an ep>1 mesh
- on-mesh parity vs the meshless path
"""
import numpy as np
import jax
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.moe import ExpertLayer, GShardGate, MoELayer


def _reset_fleet(**degrees):
    mesh_mod._STATE["mesh"] = None
    s = fleet.DistributedStrategy()
    s.hybrid_configs = degrees
    fleet.init(is_collective=True, strategy=s)
    return fleet.get_hybrid_communicate_group()


def _no_mesh():
    mesh_mod._STATE["mesh"] = None


def _identical_experts(d, dh, E, seed=0):
    paddle.seed(seed)
    experts = [ExpertLayer(d, dh) for _ in range(E)]
    for e in experts[1:]:
        e.htoh4.weight.set_value(experts[0].htoh4.weight.numpy())
        e.htoh4.bias.set_value(experts[0].htoh4.bias.numpy())
        e.h4toh.weight.set_value(experts[0].h4toh.weight.numpy())
        e.h4toh.bias.set_value(experts[0].h4toh.bias.numpy())
    return experts


class _MoEModel(nn.Layer):
    def __init__(self, d, dh, E, capacity_factor=2.0):
        super().__init__()
        self.moe = MoELayer(
            d, [ExpertLayer(d, dh) for _ in range(E)],
            gate={"type": "gshard", "top_k": 2},
            capacity_factor=capacity_factor)

    def forward(self, x):
        return self.moe(x)


class TestExpertParallel:
    def test_stacked_weights_absorbed(self):
        _no_mesh()
        experts = _identical_experts(8, 16, 4)
        moe = MoELayer(8, experts, gate={"type": "gshard", "top_k": 2})
        assert moe._stacked
        assert list(moe.w1.shape) == [4, 8, 16]
        assert list(moe.w2.shape) == [4, 16, 8]
        # absorbed params are THE trainable state; no duplicated experts
        names = [n for n, _ in moe.named_parameters()]
        assert any("w1" in n for n in names)
        assert not any("htoh4" in n for n in names)

    @pytest.mark.slow  # the mesh parity test (ep_mesh_parity_vs_meshless)
    # is the stricter default rep of the same dispatch/combine math
    def test_parity_vs_dense_ffn_oracle(self):
        """All experts identical + capacity -> inf: top-2 combine weights
        renormalize to 1, so MoE(x) == FFN(x) exactly."""
        _no_mesh()
        d, dh, E = 16, 32, 4
        experts = _identical_experts(d, dh, E)
        gate = GShardGate(d, E, random_routing=False)
        moe = MoELayer(d, experts, gate=gate, capacity_factor=1e4)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 8, d).astype(np.float32))
        out = moe(x).numpy()
        # dense oracle from the absorbed expert-0 weights
        w1, b1 = experts[0].htoh4.weight.numpy(), experts[0].htoh4.bias.numpy()
        w2, b2 = experts[0].h4toh.weight.numpy(), experts[0].h4toh.bias.numpy()
        xf = x.numpy().reshape(-1, d)
        h = np.asarray(jax.nn.gelu(xf @ w1 + b1))
        dense = (h @ w2 + b2).reshape(2, 8, d)
        np.testing.assert_allclose(out, dense, rtol=2e-5, atol=2e-6)

    def test_capacity_drops_tokens(self):
        """With capacity 4 slots per expert, overflow tokens are dropped
        (output rows go to zero) — pinning GShard capacity semantics."""
        _no_mesh()
        d, E = 8, 2
        experts = _identical_experts(d, 16, E)
        gate = GShardGate(d, E, random_routing=False)
        moe = MoELayer(d, experts, gate=gate, capacity_factor=0.01)
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(1, 64, d).astype(np.float32))
        out = moe(x).numpy().reshape(-1, d)
        # capacity = max(4, ...) = 4 per expert; top-2 over 2 experts means
        # every token wants both experts -> at most 8 rows survive
        nonzero = np.sum(np.any(np.abs(out) > 1e-9, axis=-1))
        assert nonzero <= 8, nonzero

    def test_expert_residency_on_mesh(self):
        """Each device holds E/ep experts — the point of EP (the r2 loop
        replicated all experts everywhere)."""
        hcg = _reset_fleet(mp_degree=4, dp_degree=2)
        paddle.seed(10)
        model = _MoEModel(8, 16, E=8)
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = TrainStep(model, lambda out, _l: out.sum(), opt, mesh=hcg.mesh)
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(8, 4, 8).astype(np.float32))
        float(step.step((x,), (x,)).value)
        w1 = step.params["moe.w1"] if "moe.w1" in step.params else \
            next(v for k, v in step.params.items() if k.endswith("w1"))
        spec = w1.sharding.spec
        assert spec[0] in ("mp", ("mp",))
        assert w1.addressable_shards[0].data.shape[0] == 2  # 8 experts / mp4

    def test_all_to_all_in_hlo(self):
        """The group->expert reshard must compile to an all-to-all on an
        ep>1 mesh (reference: global_scatter/global_gather)."""
        hcg = _reset_fleet(mp_degree=4, dp_degree=2)
        paddle.seed(11)
        model = _MoEModel(8, 16, E=8)
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = TrainStep(model, lambda out, _l: out.sum(), opt, mesh=hcg.mesh)
        x = paddle.to_tensor(
            np.random.RandomState(4).randn(8, 4, 8).astype(np.float32))
        hlo = step.lower_text((x,), (x,))
        assert "all-to-all" in hlo

    @pytest.mark.slow  # parity_vs_dense_ffn_oracle stays the default rep
    def test_mesh_parity_vs_meshless(self):
        """Group-wise dispatch on an ep4 mesh computes the same function as
        the meshless (G=1) path when capacity is non-binding."""
        d, dh, E = 16, 32, 4
        x_np = np.random.RandomState(5).randn(2, 16, d).astype(np.float32)

        def run(on_mesh):
            if on_mesh:
                _reset_fleet(mp_degree=4, dp_degree=2)
            else:
                _no_mesh()
            experts = _identical_experts(d, dh, E, seed=7)
            gate = GShardGate(d, E, random_routing=False)
            moe = MoELayer(d, experts, gate=gate, capacity_factor=1e4)
            return moe(paddle.to_tensor(x_np)).numpy()

        np.testing.assert_allclose(run(False), run(True), rtol=2e-5, atol=2e-6)

    @pytest.mark.slow  # ep4 x mp2 composition (suite wall time, 870s
    # tier-1 cap); ep_mesh_parity_vs_meshless + moe_group_argument
    # keep the dedicated-'ep'-axis behavior default
    def test_dedicated_ep_axis_independent_of_mp(self):
        """VERDICT r3 item 3: EP degree must not be welded to TP degree.
        On an ep4 x mp2 mesh the experts ride 'ep' (E/ep per device) while
        'mp' stays free for tensor parallelism."""
        hcg = _reset_fleet(ep_degree=4, mp_degree=2)
        assert hcg.get_expert_parallel_world_size() == 4
        assert hcg.get_expert_parallel_group().axis_names == ("ep",)
        paddle.seed(20)
        model = _MoEModel(8, 16, E=8)
        assert model.moe._expert_axis == "ep"
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = TrainStep(model, lambda out, _l: out.sum(), opt, mesh=hcg.mesh)
        x = paddle.to_tensor(
            np.random.RandomState(7).randn(8, 4, 8).astype(np.float32))
        float(step.step((x,), (x,)).value)
        w1 = next(v for k, v in step.params.items() if k.endswith("w1"))
        spec = w1.sharding.spec
        assert spec[0] in ("ep", ("ep",)), spec
        assert w1.addressable_shards[0].data.shape[0] == 2  # 8 experts / ep4
        hlo = step.lower_text((x,), (x,))
        assert "all-to-all" in hlo

    def test_moe_group_argument_selects_axis(self):
        """The reference's moe_group communicator arg picks the expert
        axis explicitly (Group facade or axis name)."""
        hcg = _reset_fleet(ep_degree=2, mp_degree=2, dp_degree=2)
        paddle.seed(21)
        experts = _identical_experts(8, 16, 4)
        moe = MoELayer(8, experts, gate={"type": "gshard", "top_k": 2},
                       moe_group=hcg.get_expert_parallel_group())
        assert moe._expert_axis == "ep"
        moe2 = MoELayer(8, _identical_experts(8, 16, 4),
                        gate={"type": "gshard", "top_k": 2},
                        moe_group="sep")
        assert moe2._expert_axis == "sep"
        with pytest.raises(ValueError, match="exactly one mesh axis"):
            MoELayer(8, _identical_experts(8, 16, 4),
                     moe_group=hcg.get_dp_sep_parallel_group())

    def test_ep_mesh_parity_vs_meshless(self):
        """The dedicated-ep dispatch computes the same function as the
        meshless path when capacity is non-binding."""
        d, dh, E = 16, 32, 4
        x_np = np.random.RandomState(8).randn(2, 16, d).astype(np.float32)

        def run(on_mesh):
            if on_mesh:
                _reset_fleet(ep_degree=4, mp_degree=2)
            else:
                _no_mesh()
            experts = _identical_experts(d, dh, E, seed=9)
            gate = GShardGate(d, E, random_routing=False)
            moe = MoELayer(d, experts, gate=gate, capacity_factor=1e4)
            return moe(paddle.to_tensor(x_np)).numpy()

        np.testing.assert_allclose(run(False), run(True), rtol=2e-5,
                                   atol=2e-6)

    def test_replicated_fallback_warns_loudly(self):
        """VERDICT r3 weak 5: losing EP must never be silent — but only
        when there IS an expert axis to lose (meshless runs stay quiet)."""
        import warnings
        paddle.seed(22)

        class OddExpert(nn.Layer):
            def __init__(self, d):
                super().__init__()
                self.fc = nn.Linear(d, d)

            def forward(self, x):
                return self.fc(x)

        _reset_fleet(ep_degree=4, dp_degree=2)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            moe = MoELayer(8, [OddExpert(8) for _ in range(4)],
                           gate={"type": "gshard", "top_k": 2})
        assert not moe._stacked
        assert any("NO expert parallelism" in str(wi.message) for wi in w)
        # no mesh -> no EP to lose -> no noise
        _no_mesh()
        with warnings.catch_warnings(record=True) as w2:
            warnings.simplefilter("always")
            MoELayer(8, [OddExpert(8) for _ in range(4)],
                     gate={"type": "gshard", "top_k": 2})
        assert not any("NO expert parallelism" in str(wi.message)
                       for wi in w2)

    def test_token_count_mismatch_warns(self):
        """VERDICT r4 weak 3: `S % ep != 0` silently returned EP degree 1
        one layer BELOW the stacked/heterogeneous check — a GShard run
        with an awkward tokens-per-device count lost expert parallelism
        with no signal."""
        import warnings
        from paddle_tpu.parallel.moe import _group_degree
        _reset_fleet(ep_degree=4, dp_degree=2)
        with pytest.warns(UserWarning, match="not divisible by"):
            assert _group_degree(10, "ep") == 1
        # divisible: full degree, no warning
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert _group_degree(12, "ep") == 4
        assert not w
        # forward path surfaces it too: 5 tokens across ep=4
        paddle.seed(23)
        model = _MoEModel(8, 16, 4)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(1, 5, 8).astype(np.float32))
        with pytest.warns(UserWarning, match="not divisible by"):
            model(x)
        _no_mesh()

    def test_moe_gradients_flow_to_stacked_experts(self):
        _no_mesh()
        paddle.seed(12)
        d, dh, E = 8, 16, 4
        moe = MoELayer(d, [ExpertLayer(d, dh) for _ in range(E)],
                       gate={"type": "gshard", "top_k": 2},
                       capacity_factor=4.0)
        x = paddle.to_tensor(
            np.random.RandomState(6).randn(2, 8, d).astype(np.float32),
            stop_gradient=False)
        out = moe(x)
        loss = out.sum() + moe.aux_loss * 0.01
        loss.backward()
        assert moe.w1.grad is not None
        assert np.any(np.abs(moe.w1.grad.numpy()) > 0)
        assert moe.gate.gate.weight.grad is not None
