"""Multi-tick on-device decode (engine ``decode_ticks > 1``, README
"Multi-tick decode"): the unified ragged step's fused tail driven past
the host sync — one program runs up to n decode ticks with on-device
EOS/budget retirement, and the host accepts the whole token block in
one ``host-accept``. The load-bearing properties:

- **Transparency**: token streams are byte-identical to
  ``decode_ticks=1`` (and to the two-program baseline) — greedy AND
  seeded-sampled, across a mixed chunked/sampled/cancel matrix and
  under the chaos fault matrix — and ``decode_compilations()`` stays
  at 1 INCLUSIVE of the multi-tick geometry (the tick count is a
  runtime argument of one program).
- **Finish masking**: EOS on tick 0 / tick n-1, budget cuts mid-block,
  and all-slots-finish-early (the program returns with ticks to
  spare) all trim exactly where tick-at-a-time would stop, with the
  device's append cut equal to the host's trim (pool accounting
  restored exactly at retirement).
- **Scheduling**: the tick count adapts — clamped to 1 under mixed
  traffic, shrunk to the nearest guaranteed retirement while the
  queue waits — so admission latency and TTFT never regress.
- **Observability**: ``serving_decode_ticks_per_sync`` on /metrics,
  exact per-decoded-token dispatch attribution via the live
  ``serving_dispatches_per_decoded_token`` gauge, and the
  ``/debug/requests`` TPOT-so-far column derived from accepted-token
  stamps (no clock-inflated numerator mid-step).
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (ContinuousBatchingEngine, FIFOScheduler,
                                GenerationRequest)
from paddle_tpu.serving.faults import FaultPlan
from paddle_tpu.serving.server import ServingGateway

from test_metrics_prom import parse_prometheus

BS = 8      # KV block size
CHUNK = 16  # 2 blocks per chunk
TICKS = 8


@pytest.fixture(scope="module")
def model():
    paddle.seed(33)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


def _jit(model, tag):
    """One jit-cache dict PER POOL GEOMETRY: a trie-backed engine's
    pool has more blocks than a bare one's, so their pool_k/pool_v arg
    shapes differ and sharing one dict would retrace the one mtick fn
    per geometry — breaking the compile-once pins (the fleet isolates
    caches by geometry for exactly this reason)."""
    return model.__dict__.setdefault(f"_serving_jit_mtick_{tag}", {})


def _engine(model, jit_tag="plain", **kw):
    kw.setdefault("jit_cache", _jit(model, jit_tag))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    return ContinuousBatchingEngine(model, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _req(ps, n=12, **kw):
    kw.setdefault("max_new_tokens", 8)
    return GenerationRequest(prompt=_prompt(ps, n), **kw)


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             eos_token_id=r.eos_token_id, seed=r.seed)


def _greedy_ref(model, n=24, seed=5):
    """A reference greedy stream, used to plant EOS tokens at exact
    tick offsets."""
    eng = _engine(model)
    out = eng.generate([GenerationRequest(prompt=_prompt(seed, 10),
                                          max_new_tokens=n)])[0]
    return out.tolist()


# ---------------------------------------------------------- transparency
class TestTransparency:
    @pytest.mark.slow  # 8 s matrix duplicate: test_multitick_equals_two_
    # program_baseline below keeps the default transparency rep (870s cap)
    def test_multitick_equals_single_tick_mixed_matrix(self, model):
        """The acceptance pin: a chunked/sampled/cancel traffic matrix
        — varied prompt lengths, greedy and seeded-sampled rows, a
        long prompt that chunks, a mid-prefill cancellation — streams
        byte-identical between ``decode_ticks=8`` and ``1``, with ONE
        decode program inclusive of the multi-tick geometry."""
        def drive(ticks):
            eng = _engine(model, jit_tag="trie32", decode_ticks=ticks,
                          prefix_cache=True, prefix_blocks=32)
            outs = []
            for wave in range(2):
                reqs = [_req(1, n=40, max_new_tokens=20),
                        _req(2, n=10, max_new_tokens=13),
                        _req(3, n=53, max_new_tokens=9,
                             temperature=0.9, top_k=5, seed=123),
                        _req(4, n=12, max_new_tokens=17,
                             temperature=0.8, top_k=4, seed=7)]
                seqs = [eng.submit(_clone(r)) for r in reqs]
                victim = eng.submit(_req(7, n=70))
                steps = 0
                while eng.has_work():
                    eng.step()
                    steps += 1
                    if steps == 4 and victim.status == "prefilling":
                        eng.cancel(victim)   # mid-chunk cancellation
                outs.append([s.tokens for s in seqs])
            return outs, eng

        want, base = drive(1)
        got, eng = drive(TICKS)
        assert got == want
        assert eng.decode_compilations() == 1
        assert eng.stats["mtick_syncs"] > 0
        assert eng.stats["mtick_ticks"] > eng.stats["mtick_syncs"]
        assert base.stats["mtick_syncs"] == 0
        # the fast path really amortized syncs: fewer decode launches
        assert eng.stats["decode_calls"] < base.stats["decode_calls"]

    @pytest.mark.slow   # re-tiered for the 870s tier-1 cap (PR 13):
    # transitively covered by default reps — multitick ≡ single-tick
    # (the mixed matrix above) and unified ≡ two-program
    # (test_ragged_step) — so the direct two-program comparison is the
    # duplicate chain link
    def test_multitick_equals_two_program_baseline(self, model):
        reqs = [_req(11, n=24, max_new_tokens=12),
                _req(12, n=12, max_new_tokens=10,
                     temperature=0.7, top_k=3, seed=9)]
        a = _engine(model, paged_attn=True, ragged_step=False)
        b = _engine(model, decode_ticks=TICKS)
        oa = [o.tolist() for o in a.generate([_clone(r) for r in reqs])]
        ob = [o.tolist() for o in b.generate([_clone(r) for r in reqs])]
        assert oa == ob

    def test_invalid_configs_raise(self, model):
        with pytest.raises(ValueError, match="decode_ticks"):
            _engine(model, decode_ticks=0)
        with pytest.raises(ValueError, match="unified ragged"):
            _engine(model, decode_ticks=4, paged_attn=False)
        with pytest.raises(ValueError, match="unified ragged"):
            _engine(model, decode_ticks=4, ragged_step=False)
        with pytest.raises(ValueError, match="spec_decode"):
            _engine(model, decode_ticks=4, spec_decode=True)


# --------------------------------------------------------- finish masking
class TestFinishMasking:
    """ISSUE 13 satellite: the on-device EOS/budget edges."""

    def _eos_case(self, model, ref, cut, max_new=24):
        """Run one request whose greedy stream hits EOS at output index
        ``cut``, at decode_ticks 1 and 8; returns both outcomes."""
        eos = ref[cut]
        assert eos not in ref[:cut], "ambiguous EOS plant"
        outs = []
        for ticks in (1, TICKS):
            eng = _engine(model, decode_ticks=ticks)
            seq = eng.submit(GenerationRequest(
                prompt=_prompt(5, 10), max_new_tokens=max_new,
                eos_token_id=eos))
            while eng.has_work():
                eng.step()
            # device append cut == host trim: every pool block handed
            # back at retirement (no trie on this engine)
            assert eng.cache.pool.num_free == eng.cache.pool.num_blocks
            outs.append((seq.tokens, seq.finish_reason, dict(eng.stats)))
        return outs

    def test_eos_on_tick0(self, model):
        ref = _greedy_ref(model)
        # output index 1 is the multi-tick step's tick 0 (output 0
        # comes from the prefill program)
        (t1, r1, _), (t8, r8, st) = self._eos_case(model, ref, 1)
        assert t1 == t8 and r1 == r8 == "stop"
        assert len(t8) == 2
        # the program retired the row at tick 0: one sync, one tick
        assert st["mtick_syncs"] == 1 and st["mtick_ticks"] == 1

    def test_eos_on_last_tick_of_block(self, model):
        ref = _greedy_ref(model)
        # output index 8 lands on tick n-1 of the first 8-tick block
        (t1, r1, _), (t8, r8, st) = self._eos_case(model, ref, 8)
        assert t1 == t8 and r1 == r8 == "stop"
        assert st["mtick_syncs"] == 1 and st["mtick_ticks"] == TICKS

    def test_eos_mid_block_returns_with_ticks_to_spare(self, model):
        ref = _greedy_ref(model)
        # first mid-block output index whose token is unambiguous
        cut = next(c for c in range(3, TICKS - 1)
                   if ref[c] not in ref[:c])
        (t1, r1, _), (t8, r8, st) = self._eos_case(model, ref, cut)
        assert t1 == t8 and r1 == r8 == "stop"
        # all slots finished early: the while_loop exited on the alive
        # mask, not the tick bound — ticks run < ticks requested
        assert st["last_decode_ticks"] < TICKS
        assert st["mtick_ticks"] == cut

    def test_budget_cut_mid_block(self, model):
        outs = []
        for ticks in (1, TICKS):
            eng = _engine(model, decode_ticks=ticks)
            seq = eng.submit(GenerationRequest(prompt=_prompt(5, 10),
                                               max_new_tokens=11))
            while eng.has_work():
                eng.step()
            assert eng.cache.pool.num_free == eng.cache.pool.num_blocks
            outs.append((seq.tokens, seq.finish_reason))
        (t1, r1), (t8, r8) = outs
        assert t1 == t8 and r1 == r8 == "length"
        assert len(t8) == 11

    def test_staggered_eos_rows_retire_independently(self, model):
        """Two slots whose EOS cuts land on different ticks of the
        same block: each trims at its own cut, the survivor keeps
        ticking on device."""
        ref = _greedy_ref(model)

        def drive(ticks):
            eng = _engine(model, decode_ticks=ticks)
            a = eng.submit(GenerationRequest(
                prompt=_prompt(5, 10), max_new_tokens=24,
                eos_token_id=ref[2]))
            b = eng.submit(GenerationRequest(
                prompt=_prompt(21, 14), max_new_tokens=15))
            while eng.has_work():
                eng.step()
            assert eng.cache.pool.num_free == eng.cache.pool.num_blocks
            return a.tokens, a.finish_reason, b.tokens, b.finish_reason

        assert drive(1) == drive(TICKS)

    def test_cancellation_mid_multitick_honored_at_sync_boundary(
            self, model):
        """cancel() runs on the driver thread, so it lands exactly at
        a sync boundary: the cancelled request keeps every token of
        completed blocks and nothing of the next, the bystander's
        stream is untouched, and the pool is exactly restored."""
        def drive(ticks, do_cancel):
            eng = _engine(model, decode_ticks=ticks)
            keep = eng.submit(_req(31, n=12, max_new_tokens=30))
            veto = eng.submit(_req(32, n=12, max_new_tokens=30))
            steps = 0
            while eng.has_work():
                eng.step()
                steps += 1
                if steps == 2 and do_cancel:
                    eng.cancel(veto)
            return keep.tokens, veto.tokens, veto.finish_reason, eng

        k8, v8, vr8, eng8 = drive(TICKS, True)
        k1, v1, _, _ = drive(1, False)
        assert vr8 == "cancelled"
        assert k8 == k1                      # bystander byte-identical
        # the cancelled stream is a prefix of its uncancelled self,
        # cut at a sync boundary (a whole number of accepted blocks)
        assert v8 == v1[:len(v8)]
        assert 0 < len(v8) < 30
        assert eng8.cache.pool.num_free == eng8.cache.pool.num_blocks


# ------------------------------------------------------ adaptive ticks
class _FakeSeq:
    def __init__(self, remaining):
        self.remaining = remaining


class TestAdaptiveTicks:
    def test_clamped_to_one_under_mixed_traffic(self):
        s = FIFOScheduler(1)
        s.enter_prefill("p")
        assert s.choose_decode_ticks([_FakeSeq(50)], 8) == 1

    def test_shrinks_to_nearest_guaranteed_retirement_when_queue_waits(
            self):
        s = FIFOScheduler(1)
        s.submit("waiting")
        active = [_FakeSeq(3), _FakeSeq(40)]
        # min remaining: the earliest guaranteed retirement lands on a
        # sync boundary, so the waiting request is never pushed past it
        assert s.choose_decode_ticks(active, 8) == 3

    def test_runs_to_largest_budget_when_idle(self):
        s = FIFOScheduler(1)
        active = [_FakeSeq(3), _FakeSeq(40)]
        # the alive mask retires the short row on device mid-block —
        # no shrinking the block for everyone
        assert s.choose_decode_ticks(active, 8) == 8
        assert s.choose_decode_ticks([_FakeSeq(5)], 8) == 5

    def test_degenerate_cases(self):
        s = FIFOScheduler(1)
        assert s.choose_decode_ticks([], 8) == 1
        assert s.choose_decode_ticks([_FakeSeq(50)], 1) == 1


# -------------------------------------------------------- fault interplay
def _mk_factory(model, jit_tag="trie", **kw):
    cache = _jit(model, jit_tag)
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("decode_ticks", TICKS)

    def factory():
        return ContinuousBatchingEngine(model, jit_cache=cache, **kw)
    return factory


def _traffic():
    return [_req(1, max_new_tokens=12), _req(2, n=10, max_new_tokens=12),
            _req(3, max_new_tokens=12, temperature=0.9, top_k=5,
                 seed=123),
            _req(4, n=60, max_new_tokens=6)]


class TestFaultInterplay:
    def test_chaos_matrix_byte_identical(self, model):
        """The acceptance pin under faults: transient retry, pool
        exhaustion -> preemption, fatal rebuild and nan KV corruption
        all mid-multi-tick-traffic — a fault unwinds to the last
        accepted token, restore() recomputes from accepted tokens
        only, streams land byte-identical to the fault-free
        ``decode_ticks=1`` oracle, and the rebuilt engine still counts
        ONE decode program."""
        reqs = _traffic()
        base = _engine(model, jit_tag="trie", prefix_cache=True)
        want = [o.tolist()
                for o in base.generate([_clone(r) for r in reqs])]
        plan = (FaultPlan().at_step(2, "transient").at_step(4, "pool")
                .at_step(6, "fatal").at_step(9, "nan"))
        factory = _mk_factory(model)
        gw = ServingGateway(factory(), engine_factory=factory,
                            fault_hook=plan, start=False, max_queue=16,
                            retry_backoff_s=0.0)
        streams = [gw.submit(_clone(r)) for r in reqs]
        gw.start()
        outs = [st.result() for st in streams]
        assert [ids.tolist() for ids, _ in outs] == want
        assert {k for _, k in plan.log} >= {"transient", "pool",
                                            "fatal", "nan"}
        assert gw.restarts >= 1
        assert gw.engine.decode_compilations() == 1
        assert gw.engine.decode_ticks == TICKS
        gw.shutdown(drain=True, timeout=30)


# ------------------------------------------------------- metrics surface
class TestMetricsSurface:
    def test_ticks_per_sync_gauge_and_dispatch_drop(self, model):
        """The satellite pin: ``serving_decode_ticks_per_sync`` > 1 on
        the multi-tick gateway, and the LIVE
        ``serving_dispatches_per_decoded_token`` gauge — the exact
        observatory counter, not a model — drops vs an identical
        ``decode_ticks=1`` gateway on the same decode-heavy traffic."""
        reqs = [_req(41, max_new_tokens=24),
                _req(42, n=10, max_new_tokens=24)]

        def run(ticks):
            factory = _mk_factory(model, jit_tag="plain",
                                  prefix_cache=False,
                                  decode_ticks=ticks)
            gw = ServingGateway(factory(), engine_factory=factory,
                                start=False, max_queue=16)
            streams = [gw.submit(_clone(r)) for r in reqs]
            gw.start()
            outs = [st.result()[0].tolist() for st in streams]
            fams = parse_prometheus(gw.registry.render())

            def g(name):
                return fams[name]["samples"][(name, ())]
            ticks_per_sync = g("serving_decode_ticks_per_sync")
            dpt = g("serving_dispatches_per_decoded_token")
            mtick_disp = fams["serving_dispatches_total"]["samples"][
                ("serving_dispatches_total", (("program", "mtick"),))]
            gw.shutdown(drain=True, timeout=30)
            return outs, ticks_per_sync, dpt, mtick_disp

        outs1, tps1, dpt1, md1 = run(1)
        outs8, tps8, dpt8, md8 = run(TICKS)
        assert outs1 == outs8
        assert tps1 == 0.0 and md1 == 0    # baseline: gauge reads 0
        assert tps8 > 2.0                  # fast path engaged
        assert md8 > 0
        # the live exact counter shows the amortization directly
        assert dpt8 < dpt1 / 2.0

    def test_request_table_tpot_from_accepted_stamps(self, model):
        """ISSUE 13 satellite fix: /debug/requests derives TPOT-so-far
        from the last ACCEPTED token's stamp — two reads between the
        same two syncs must agree (the old clock-based numerator
        inflated for the whole step, freezing a stale-growing figure
        for n ticks under multi-tick decode)."""
        tick = itertools.count()
        clock = lambda: float(next(tick))   # noqa: E731
        factory = _mk_factory(model, jit_tag="plain", prefix_cache=False,
                              step_clock=clock)
        gw = ServingGateway(factory(), engine_factory=factory,
                            start=False, max_queue=16)
        st = gw.submit(_req(51, max_new_tokens=30))
        # drive the gateway's own loop manually (single-threaded, so
        # reads land deterministically BETWEEN syncs)
        gw._admit_intake()
        for _ in range(3):
            gw._step_supervised()
        seq = st.seq
        assert len(seq.tokens) > 1
        row1 = [r for r in gw.request_table() if r["id"] == st.id][0]
        row2 = [r for r in gw.request_table() if r["id"] == st.id][0]
        # stamp-over-stamp: stable across repeated mid-flight reads,
        # even though each request_table() call reads the live clock
        assert row1["tpot_s"] is not None
        assert row1["tpot_s"] == row2["tpot_s"]
        want = (seq.t_last_token - seq.t_first_token) \
            / (len(seq.tokens) - 1)
        assert row1["tpot_s"] == pytest.approx(want, abs=1e-6)
        gw.shutdown(drain=False, timeout=10)
