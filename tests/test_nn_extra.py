"""Tests for the r3 nn batch: 3-D pooling, transposed convs, fold/maxout,
pads, and the loss zoo incl. CTC (reference:
``test/legacy_test/test_{pool3d,conv*transpose,fold,ctc_loss,...}_op.py``).
Oracles: torch (cpu) and closed-form numpy."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn import functional as F

torch = pytest.importorskip("torch")


def _t(x):
    return paddle.to_tensor(np.asarray(x))


def _np(t):
    return np.asarray(t.value if hasattr(t, "value") else t)


class TestPool3D:
    def test_max_pool3d_vs_torch(self):
        x = np.random.RandomState(0).randn(2, 3, 8, 8, 8).astype(np.float32)
        ours = _np(F.max_pool3d(_t(x), 2, stride=2))
        ref = torch.nn.functional.max_pool3d(torch.tensor(x), 2, 2).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-6)

    def test_avg_pool3d_with_padding(self):
        x = np.random.RandomState(1).randn(1, 2, 6, 6, 6).astype(np.float32)
        ours = _np(F.avg_pool3d(_t(x), 3, stride=2, padding=1))
        ref = torch.nn.functional.avg_pool3d(
            torch.tensor(x), 3, 2, 1, count_include_pad=False).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_adaptive_avg_pool3d(self):
        x = np.random.RandomState(2).randn(1, 2, 8, 6, 4).astype(np.float32)
        ours = _np(nn.AdaptiveAvgPool3D((2, 3, 2))(_t(x)))
        ref = torch.nn.functional.adaptive_avg_pool3d(
            torch.tensor(x), (2, 3, 2)).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_adaptive_max_pool1d(self):
        x = np.random.RandomState(3).randn(2, 3, 12).astype(np.float32)
        ours = _np(nn.AdaptiveMaxPool1D(4)(_t(x)))
        ref = torch.nn.functional.adaptive_max_pool1d(
            torch.tensor(x), 4).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-6)

    @pytest.mark.parametrize("L,out", [(12, 4), (10, 3)])  # even + ragged
    def test_adaptive_max_pool1d_return_mask(self, L, out):
        x = np.random.RandomState(5).randn(2, 3, L).astype(np.float32)
        got, mask = F.adaptive_max_pool1d(_t(x), out, return_mask=True)
        want, widx = torch.nn.functional.adaptive_max_pool1d(
            torch.tensor(x), out, return_indices=True)
        np.testing.assert_allclose(_np(got), want.numpy(), atol=1e-6)
        np.testing.assert_array_equal(_np(mask), widx.numpy())

    @pytest.mark.parametrize("shape,out", [((8, 8), (2, 2)),
                                           ((7, 9), (3, 4))])
    def test_adaptive_max_pool2d_return_mask(self, shape, out):
        x = np.random.RandomState(6).randn(2, 2, *shape).astype(np.float32)
        got, mask = F.adaptive_max_pool2d(_t(x), out, return_mask=True)
        want, widx = torch.nn.functional.adaptive_max_pool2d(
            torch.tensor(x), out, return_indices=True)
        np.testing.assert_allclose(_np(got), want.numpy(), atol=1e-6)
        np.testing.assert_array_equal(_np(mask), widx.numpy())

    @pytest.mark.parametrize("shape,out", [((6, 8, 4), (3, 4, 2)),
                                           ((6, 8, 4), (4, 3, 3))])
    def test_adaptive_max_pool3d_return_mask(self, shape, out):
        x = np.random.RandomState(7).randn(1, 2, *shape).astype(np.float32)
        got, mask = F.adaptive_max_pool3d(_t(x), list(out),
                                          return_mask=True)
        want, widx = torch.nn.functional.adaptive_max_pool3d(
            torch.tensor(x), out, return_indices=True)
        np.testing.assert_allclose(_np(got), want.numpy(), atol=1e-6)
        np.testing.assert_array_equal(_np(mask), widx.numpy())

    def test_adaptive_max_pool_layers_return_mask(self):
        x = np.random.RandomState(8).randn(1, 2, 9).astype(np.float32)
        out, mask = nn.AdaptiveMaxPool1D(3, return_mask=True)(_t(x))
        want, widx = torch.nn.functional.adaptive_max_pool1d(
            torch.tensor(x), 3, return_indices=True)
        np.testing.assert_allclose(_np(out), want.numpy(), atol=1e-6)
        np.testing.assert_array_equal(_np(mask), widx.numpy())

    def test_max_unpool2d_roundtrip(self):
        x = np.random.RandomState(4).randn(1, 2, 8, 8).astype(np.float32)
        pooled, mask = F.max_pool2d(_t(x), 2, stride=2, return_mask=True)
        unpooled = _np(F.max_unpool2d(pooled, mask, 2, stride=2))
        # scattered values sit at the argmax positions; re-pooling recovers
        repooled = _np(F.max_pool2d(_t(unpooled), 2, stride=2))
        np.testing.assert_allclose(repooled, _np(pooled), atol=1e-6)
        assert unpooled.shape == x.shape


class TestConvTranspose:
    def test_conv1d_transpose_vs_torch(self):
        x = np.random.RandomState(5).randn(2, 3, 10).astype(np.float32)
        w = np.random.RandomState(6).randn(3, 4, 5).astype(np.float32)
        ours = _np(F.conv1d_transpose(_t(x), _t(w), stride=2, padding=1))
        ref = torch.nn.functional.conv_transpose1d(
            torch.tensor(x), torch.tensor(w), stride=2, padding=1).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-4)

    def test_conv3d_transpose_vs_torch(self):
        x = np.random.RandomState(7).randn(1, 3, 4, 4, 4).astype(np.float32)
        w = np.random.RandomState(8).randn(3, 2, 3, 3, 3).astype(np.float32)
        ours = _np(F.conv3d_transpose(_t(x), _t(w), stride=2, padding=1,
                                      output_padding=1))
        ref = torch.nn.functional.conv_transpose3d(
            torch.tensor(x), torch.tensor(w), stride=2, padding=1,
            output_padding=1).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-4)

    def test_layer_shapes(self):
        y = nn.Conv1DTranspose(3, 5, 4, stride=2)(
            _t(np.zeros((2, 3, 8), np.float32)))
        assert y.shape == [2, 5, 18]
        y3 = nn.Conv3DTranspose(2, 4, 3)(
            _t(np.zeros((1, 2, 4, 4, 4), np.float32)))
        assert y3.shape == [1, 4, 6, 6, 6]


class TestFoldMaxout:
    def test_fold_inverts_unfold_ones(self):
        # fold(unfold(x)) multiplies each pixel by its window-coverage count;
        # verify against torch's fold on the same unfolded input
        x = np.random.RandomState(9).randn(1, 2, 6, 6).astype(np.float32)
        cols = F.unfold(_t(x), 3, strides=1, paddings=1)
        ours = _np(F.fold(cols, (6, 6), 3, strides=1, paddings=1))
        tcols = torch.nn.functional.unfold(torch.tensor(x), 3, padding=1)
        ref = torch.nn.functional.fold(tcols, (6, 6), 3, padding=1).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_maxout(self):
        x = np.random.RandomState(10).randn(2, 6, 4, 4).astype(np.float32)
        ours = _np(nn.Maxout(3)(_t(x)))
        ref = x.reshape(2, 2, 3, 4, 4).max(axis=2)
        np.testing.assert_allclose(ours, ref)

    def test_pads(self):
        x = np.zeros((1, 2, 4), np.float32)
        assert nn.Pad1D([1, 2])(_t(x)).shape == [1, 2, 7]
        x3 = np.zeros((1, 2, 3, 4, 5), np.float32)
        assert nn.Pad3D(1)(_t(x3)).shape == [1, 2, 5, 6, 7]
        x2 = np.ones((1, 1, 2, 2), np.float32)
        z = _np(nn.ZeroPad2D(1)(_t(x2)))
        assert z.shape == (1, 1, 4, 4) and z[0, 0, 0, 0] == 0

    def test_softmax2d(self):
        x = np.random.RandomState(11).randn(2, 3, 4, 4).astype(np.float32)
        out = _np(nn.Softmax2D()(_t(x)))
        np.testing.assert_allclose(out.sum(axis=1), np.ones((2, 4, 4)),
                                   atol=1e-5)


class TestLossZoo:
    def test_ctc_loss_vs_torch(self):
        rng = np.random.RandomState(12)
        T, B, C, L = 12, 3, 6, 4
        logits = rng.randn(T, B, C).astype(np.float32)
        labels = rng.randint(1, C, (B, L)).astype(np.int32)
        in_len = np.array([12, 10, 8], np.int64)
        lab_len = np.array([4, 3, 2], np.int64)
        ours = _np(F.ctc_loss(_t(logits), _t(labels), _t(in_len), _t(lab_len),
                              blank=0, reduction="none"))
        ref = torch.nn.functional.ctc_loss(
            torch.tensor(logits).log_softmax(-1), torch.tensor(labels.astype(np.int64)),
            torch.tensor(in_len), torch.tensor(lab_len), blank=0,
            reduction="none").numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)

    def test_ctc_loss_grad_flows(self):
        rng = np.random.RandomState(13)
        logits = _t(rng.randn(8, 2, 5).astype(np.float32))
        logits.stop_gradient = False
        loss = F.ctc_loss(logits, _t(rng.randint(1, 5, (2, 3)).astype(np.int32)),
                          _t(np.array([8, 8], np.int64)),
                          _t(np.array([3, 2], np.int64)))
        loss.backward()
        g = _np(logits.grad)
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_ctc_mean_divides_by_label_len(self):
        rng = np.random.RandomState(18)
        T, B, C = 10, 2, 5
        logits = rng.randn(T, B, C).astype(np.float32)
        labels = rng.randint(1, C, (B, 4)).astype(np.int32)
        il = np.array([10, 9], np.int64)
        ll = np.array([4, 2], np.int64)
        ours = float(_np(F.ctc_loss(_t(logits), _t(labels), _t(il), _t(ll),
                                    reduction="mean")))
        ref = torch.nn.functional.ctc_loss(
            torch.tensor(logits).log_softmax(-1),
            torch.tensor(labels.astype(np.int64)), torch.tensor(il),
            torch.tensor(ll), reduction="mean").item()
        np.testing.assert_allclose(ours, ref, rtol=1e-4)

    def test_soft_margin_stable_at_large_logits(self):
        out = _np(F.soft_margin_loss(_t(np.array([100.0], np.float32)),
                                     _t(np.array([-1.0], np.float32)),
                                     reduction="none"))
        np.testing.assert_allclose(out, [100.0], rtol=1e-5)

    def test_ctc_layer_reduction(self):
        rng = np.random.RandomState(14)
        logits = _t(rng.randn(8, 2, 5).astype(np.float32))
        crit = nn.CTCLoss(blank=0, reduction="mean")
        out = crit(logits, _t(rng.randint(1, 5, (2, 3)).astype(np.int32)),
                   _t(np.array([8, 8], np.int64)),
                   _t(np.array([3, 3], np.int64)))
        assert np.isfinite(float(out.value))

    def test_simple_losses_vs_torch(self):
        rng = np.random.RandomState(15)
        x = rng.randn(4, 5).astype(np.float32)
        y = rng.choice([-1.0, 1.0], (4, 5)).astype(np.float32)
        np.testing.assert_allclose(
            _np(F.soft_margin_loss(_t(x), _t(y))),
            torch.nn.functional.soft_margin_loss(
                torch.tensor(x), torch.tensor(y)).numpy(), rtol=1e-5)
        lab01 = (y > 0).astype(np.float32)
        np.testing.assert_allclose(
            _np(F.multi_label_soft_margin_loss(_t(x), _t(lab01))),
            torch.nn.functional.multilabel_soft_margin_loss(
                torch.tensor(x), torch.tensor(lab01)).numpy(), rtol=1e-5)
        tgt = rng.rand(4, 5).astype(np.float32) + 0.1
        np.testing.assert_allclose(
            _np(F.poisson_nll_loss(_t(x), _t(tgt))),
            torch.nn.functional.poisson_nll_loss(
                torch.tensor(x), torch.tensor(tgt)).numpy(), rtol=1e-5)
        var = rng.rand(4, 5).astype(np.float32) + 0.1
        np.testing.assert_allclose(
            _np(F.gaussian_nll_loss(_t(x), _t(tgt), _t(var))),
            torch.nn.functional.gaussian_nll_loss(
                torch.tensor(x), torch.tensor(tgt), torch.tensor(var)).numpy(),
            rtol=1e-4)

    def test_margin_family_vs_torch(self):
        rng = np.random.RandomState(16)
        a = rng.randn(4, 8).astype(np.float32)
        p = rng.randn(4, 8).astype(np.float32)
        n = rng.randn(4, 8).astype(np.float32)
        np.testing.assert_allclose(
            _np(F.triplet_margin_loss(_t(a), _t(p), _t(n))),
            torch.nn.functional.triplet_margin_loss(
                torch.tensor(a), torch.tensor(p), torch.tensor(n),
                eps=1e-6).numpy(), rtol=1e-4)
        lab = np.array([1.0, -1.0, 1.0, -1.0], np.float32)
        np.testing.assert_allclose(
            _np(F.cosine_embedding_loss(_t(a), _t(p), _t(lab), margin=0.2)),
            torch.nn.functional.cosine_embedding_loss(
                torch.tensor(a), torch.tensor(p), torch.tensor(lab),
                margin=0.2).numpy(), rtol=1e-5)
        cls = np.array([0, 2, 1, 3], np.int64)
        np.testing.assert_allclose(
            _np(F.multi_margin_loss(_t(a[:, :4]), _t(cls))),
            torch.nn.functional.multi_margin_loss(
                torch.tensor(a[:, :4]), torch.tensor(cls)).numpy(), rtol=1e-5)
        np.testing.assert_allclose(
            _np(F.pairwise_distance(_t(a), _t(p))),
            torch.nn.functional.pairwise_distance(
                torch.tensor(a), torch.tensor(p), eps=1e-6).numpy(), rtol=1e-5)

    def test_misc_losses(self):
        rng = np.random.RandomState(17)
        probs = rng.rand(4, 3).astype(np.float32) * 0.8 + 0.1
        lab = rng.rand(4, 3).astype(np.float32)
        np.testing.assert_allclose(
            _np(F.square_error_cost(_t(probs), _t(lab))), (probs - lab) ** 2,
            rtol=1e-6)
        ll = _np(F.log_loss(_t(probs[:, :1]), _t((lab[:, :1] > 0.5).astype(np.float32))))
        assert ll.shape == (4, 1) and (ll > 0).all()
        soft = np.exp(rng.randn(4, 6, 5).astype(np.float32))
        soft = (soft / soft.sum(-1, keepdims=True)).astype(np.float32)
        dl = float(_np(F.dice_loss(_t(soft), _t(rng.randint(0, 5, (4, 6, 1))))))
        assert 0.0 < dl < 1.0
        anchor = rng.randn(4, 8).astype(np.float32)
        pos = rng.randn(4, 8).astype(np.float32)
        lab = np.array([0, 1, 0, 1], np.int64)
        npl = float(_np(F.npair_loss(_t(anchor), _t(pos), _t(lab),
                                     l2_reg=0.002)))
        sim = anchor @ pos.T
        tgt = (lab[:, None] == lab[None, :]).astype(np.float32)
        tgt /= tgt.sum(1, keepdims=True)
        lse = np.log(np.exp(sim).sum(1, keepdims=True))
        ce = np.mean(np.sum(-tgt * (sim - lse), axis=1))
        reg = 0.25 * 0.002 * ((anchor ** 2).sum(1).mean()
                              + (pos ** 2).sum(1).mean())
        np.testing.assert_allclose(npl, ce + reg, rtol=1e-4)


class TestInitializerR5:
    """Bilinear init + set_global_initializer (reference
    nn/initializer surface †)."""

    def test_bilinear_upsamples(self):
        import paddle_tpu.nn.initializer as I
        w = I.Bilinear()((1, 1, 4, 4), np.float32)
        # stride-2 conv_transpose with this kernel bilinearly upsamples a
        # constant image to a constant image (interior)
        x = paddle.to_tensor(np.ones((1, 1, 3, 3), np.float32))
        out = paddle.nn.functional.conv2d_transpose(
            x, paddle.to_tensor(np.asarray(w)), stride=2, padding=1)
        np.testing.assert_allclose(out.numpy()[0, 0, 1:-1, 1:-1], 1.0,
                                   atol=1e-6)

    def test_set_global_initializer(self):
        import paddle_tpu.nn.initializer as I
        try:
            I.set_global_initializer(I.Constant(0.5), I.Constant(0.25))
            lin = paddle.nn.Linear(3, 2)
            np.testing.assert_allclose(lin.weight.numpy(), 0.5)
            np.testing.assert_allclose(lin.bias.numpy(), 0.25)
        finally:
            I.set_global_initializer(None, None)
        lin2 = paddle.nn.Linear(3, 2)
        assert not np.allclose(lin2.weight.numpy(), 0.5)

    def test_bilinear_filter_values(self):
        import paddle_tpu.nn.initializer as I
        w3 = np.asarray(I.Bilinear()((1, 1, 3, 3), np.float32))
        np.testing.assert_allclose(w3[0, 0, 0], [0.0625, 0.1875, 0.1875],
                                   atol=1e-6)  # 0.25*[0.25,0.75,0.75]
        w4 = np.asarray(I.Bilinear()((1, 1, 4, 4), np.float32))
        np.testing.assert_allclose(w4[0, 0, 1],
                                   0.75 * np.float32([0.25, 0.75, 0.75, 0.25]),
                                   atol=1e-6)
