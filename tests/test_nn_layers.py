"""Layer/functional tests vs oracles (reference pattern: api unit tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

rng = np.random.RandomState(7)


def t(x, sg=True):
    return paddle.to_tensor(x, stop_gradient=sg)


class TestLinearEmbedding:
    def test_linear(self):
        layer = nn.Linear(4, 3)
        x = rng.randn(2, 4).astype(np.float32)
        out = layer(t(x))
        ref = x @ layer.weight.numpy() + layer.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_linear_backward(self):
        layer = nn.Linear(4, 3)
        x = t(rng.randn(2, 4).astype(np.float32), sg=False)
        loss = layer(x).sum()
        loss.backward()
        assert layer.weight.grad is not None
        assert layer.bias.grad is not None
        np.testing.assert_allclose(layer.bias.grad.numpy(), [2, 2, 2])

    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        idx = np.array([[1, 2], [3, 4]])
        out = emb(t(idx))
        np.testing.assert_allclose(out.numpy(), emb.weight.numpy()[idx])

    def test_embedding_padding_idx(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        np.testing.assert_allclose(emb.weight.numpy()[0], np.zeros(4))


class TestConvPool:
    def test_conv2d_shape_oracle(self):
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = rng.randn(2, 3, 16, 16).astype(np.float32)
        out = conv(t(x))
        assert out.shape == [2, 8, 8, 8]
        # oracle vs scipy-style direct computation on one output pixel
        w = conv.weight.numpy()
        b = conv.bias.numpy()
        patch = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))[0, :, 0:3, 0:3]
        ref00 = (patch * w).sum(axis=(1, 2, 3)) + b
        np.testing.assert_allclose(out.numpy()[0, :, 0, 0], ref00, rtol=1e-4,
                                   atol=1e-4)

    def test_conv_groups(self):
        conv = nn.Conv2D(4, 8, 3, groups=2, padding=1)
        out = conv(t(rng.randn(1, 4, 8, 8).astype(np.float32)))
        assert out.shape == [1, 8, 8, 8]

    def test_maxpool_avgpool(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        mp = nn.MaxPool2D(2, 2)(t(x))
        np.testing.assert_allclose(mp.numpy()[0, 0], [[5, 7], [13, 15]])
        ap = nn.AvgPool2D(2, 2)(t(x))
        np.testing.assert_allclose(ap.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])

    def test_adaptive_pool(self):
        x = rng.randn(2, 3, 7, 9).astype(np.float32)
        out = nn.AdaptiveAvgPool2D((1, 1))(t(x))
        np.testing.assert_allclose(out.numpy()[:, :, 0, 0],
                                   x.mean(axis=(2, 3)), rtol=1e-5)
        out2 = nn.AdaptiveAvgPool2D((3, 3))(t(x))
        assert out2.shape == [2, 3, 3, 3]


class TestNorms:
    def test_batchnorm_train_eval(self):
        bn = nn.BatchNorm2D(3)
        x = rng.randn(4, 3, 5, 5).astype(np.float32) * 2 + 1
        bn.train()
        out = bn(t(x))
        mean = x.mean(axis=(0, 2, 3))
        var = x.var(axis=(0, 2, 3))
        ref = (x - mean[None, :, None, None]) / np.sqrt(
            var[None, :, None, None] + 1e-5)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)
        # running stats updated
        np.testing.assert_allclose(bn._mean.numpy(), 0.1 * mean, rtol=1e-4,
                                   atol=1e-4)
        bn.eval()
        out_e = bn(t(x))
        ref_e = ((x - bn._mean.numpy()[None, :, None, None]) /
                 np.sqrt(bn._variance.numpy()[None, :, None, None] + 1e-5))
        np.testing.assert_allclose(out_e.numpy(), ref_e, rtol=1e-4, atol=1e-4)

    def test_layernorm(self):
        ln = nn.LayerNorm(6)
        x = rng.randn(2, 3, 6).astype(np.float32)
        out = ln(t(x))
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        np.testing.assert_allclose(out.numpy(), (x - mean) / np.sqrt(var + 1e-5),
                                   rtol=1e-4, atol=1e-4)

    def test_rmsnorm(self):
        rn = nn.RMSNorm(6)
        x = rng.randn(2, 6).astype(np.float32)
        out = rn(t(x))
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        x = rng.randn(2, 4, 3, 3).astype(np.float32)
        out = gn(t(x))
        xg = x.reshape(2, 2, 2, 3, 3)
        ref = ((xg - xg.mean(axis=(2, 3, 4), keepdims=True)) /
               np.sqrt(xg.var(axis=(2, 3, 4), keepdims=True) + 1e-5)).reshape(x.shape)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


class TestActivationsLosses:
    def test_softmax_ce(self):
        logits = rng.randn(4, 5).astype(np.float32)
        labels = np.array([0, 2, 4, 1])
        loss = F.cross_entropy(t(logits), t(labels))
        # numpy oracle
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(float(loss.value), ref, rtol=1e-5)

    def test_ce_ignore_index(self):
        logits = rng.randn(4, 5).astype(np.float32)
        labels = np.array([0, -100, 4, -100])
        loss = F.cross_entropy(t(logits), t(labels), ignore_index=-100)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[[0, 2], [0, 4]]).mean()
        np.testing.assert_allclose(float(loss.value), ref, rtol=1e-5)

    def test_ce_soft_label(self):
        logits = rng.randn(3, 4).astype(np.float32)
        soft = np.abs(rng.rand(3, 4)).astype(np.float32)
        soft /= soft.sum(-1, keepdims=True)
        loss = F.cross_entropy(t(logits), t(soft), soft_label=True)
        logp = logits - logits.max(-1, keepdims=True)
        logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
        ref = -(soft * logp).sum(-1).mean()
        np.testing.assert_allclose(float(loss.value), ref, rtol=1e-5)

    def test_mse_bce(self):
        a = rng.rand(3, 4).astype(np.float32)
        b = rng.rand(3, 4).astype(np.float32)
        np.testing.assert_allclose(float(F.mse_loss(t(a), t(b)).value),
                                   ((a - b) ** 2).mean(), rtol=1e-5)
        lg = rng.randn(3, 4).astype(np.float32)
        lab = (rng.rand(3, 4) > 0.5).astype(np.float32)
        out = F.binary_cross_entropy_with_logits(t(lg), t(lab))
        ref = np.maximum(lg, 0) - lg * lab + np.log1p(np.exp(-np.abs(lg)))
        np.testing.assert_allclose(float(out.value), ref.mean(), rtol=1e-5)

    def test_activations(self):
        x = rng.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(F.relu(t(x)).numpy(), np.maximum(x, 0))
        np.testing.assert_allclose(F.sigmoid(t(x)).numpy(),
                                   1 / (1 + np.exp(-x)), rtol=1e-5)
        np.testing.assert_allclose(F.silu(t(x)).numpy(),
                                   x / (1 + np.exp(-x)), rtol=1e-5)
        sm = F.softmax(t(x), axis=-1).numpy()
        np.testing.assert_allclose(sm.sum(-1), np.ones(3), rtol=1e-5)


class TestDropoutRng:
    def test_dropout_train_eval(self):
        x = np.ones((100, 100), np.float32)
        d = nn.Dropout(0.5)
        d.train()
        out = d(t(x))
        frac = (out.numpy() == 0).mean()
        assert 0.4 < frac < 0.6
        # upscale keeps expectation
        assert abs(out.numpy().mean() - 1.0) < 0.1
        d.eval()
        np.testing.assert_allclose(d(t(x)).numpy(), x)

    def test_dropout_deterministic_per_seed(self):
        x = np.ones((10, 10), np.float32)
        paddle.seed(5)
        a = F.dropout(t(x), 0.5).numpy()
        paddle.seed(5)
        b = F.dropout(t(x), 0.5).numpy()
        np.testing.assert_allclose(a, b)


class TestAttention:
    def test_sdpa_vs_oracle(self):
        B, S, H, D = 2, 5, 2, 4
        q = rng.randn(B, S, H, D).astype(np.float32)
        k = rng.randn(B, S, H, D).astype(np.float32)
        v = rng.randn(B, S, H, D).astype(np.float32)
        out = F.scaled_dot_product_attention(t(q), t(k), t(v)).numpy()
        # oracle
        qt = q.transpose(0, 2, 1, 3)
        kt = k.transpose(0, 2, 1, 3)
        vt = v.transpose(0, 2, 1, 3)
        logits = qt @ kt.transpose(0, 1, 3, 2) / np.sqrt(D)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = (p @ vt).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_causal(self):
        B, S, H, D = 1, 4, 1, 2
        q = rng.randn(B, S, H, D).astype(np.float32)
        k = rng.randn(B, S, H, D).astype(np.float32)
        v = rng.randn(B, S, H, D).astype(np.float32)
        out = F.scaled_dot_product_attention(t(q), t(k), t(v), is_causal=True)
        # first position attends only to itself
        np.testing.assert_allclose(out.numpy()[0, 0], v[0, 0], rtol=1e-5)

    def test_flash_matches_sdpa(self):
        from paddle_tpu.incubate.nn import functional as IF
        B, S, H, D = 2, 8, 2, 4
        q = rng.randn(B, S, H, D).astype(np.float32)
        k = rng.randn(B, S, H, D).astype(np.float32)
        v = rng.randn(B, S, H, D).astype(np.float32)
        fa, _ = IF.flash_attention(t(q), t(k), t(v), causal=True)
        ref = F.scaled_dot_product_attention(t(q), t(k), t(v), is_causal=True)
        np.testing.assert_allclose(fa.numpy(), ref.numpy(), rtol=1e-4, atol=1e-5)

    def test_multihead_attention_layer(self):
        mha = nn.MultiHeadAttention(8, 2)
        x = rng.randn(2, 5, 8).astype(np.float32)
        out = mha(t(x))
        assert out.shape == [2, 5, 8]


class TestLayerSystem:
    def test_state_dict_roundtrip(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = m.state_dict()
        assert set(sd) == {"0.weight", "0.bias", "2.weight", "2.bias"}
        m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m2.set_state_dict(sd)
        np.testing.assert_allclose(m2[0].weight.numpy(), m[0].weight.numpy())

    def test_named_parameters_buffers(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(2, 2)
                self.bn = nn.BatchNorm1D(2)

        net = Net()
        names = dict(net.named_parameters())
        assert "fc.weight" in names and "bn.weight" in names
        bufs = dict(net.named_buffers())
        assert "bn._mean" in bufs

    def test_save_load(self, tmp_path):
        m = nn.Linear(3, 3)
        path = str(tmp_path / "model.pdparams")
        paddle.save(m.state_dict(), path)
        loaded = paddle.load(path)
        m2 = nn.Linear(3, 3)
        m2.set_state_dict(loaded)
        np.testing.assert_allclose(m2.weight.numpy(), m.weight.numpy())

    def test_train_eval_propagation(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_forward_hooks(self):
        m = nn.Linear(2, 2)
        calls = []
        h = m.register_forward_post_hook(
            lambda layer, inp, out: calls.append(1))
        m(t(np.ones((1, 2), np.float32)))
        assert calls == [1]
        h.remove()
        m(t(np.ones((1, 2), np.float32)))
        assert calls == [1]

    def test_parameters_to(self):
        import jax.numpy as jnp
        m = nn.Linear(2, 2)
        m.to(dtype="bfloat16")
        assert m.weight.dtype == jnp.bfloat16
