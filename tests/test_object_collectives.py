"""Object collectives (reference
``python/paddle/distributed/communication/`` all_gather_object /
broadcast_object_list / scatter_object_list †) + the gather/wait/
destroy_process_group namespace parity additions."""
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.parallel.launch.rendezvous import KVServer
from paddle_tpu.parallel.object_collectives import _dec, _enc, _exchange

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestObjectCollectivesSingleProcess:
    def test_all_gather_object_world1(self):
        out = []
        dist.all_gather_object(out, {"vocab": 123})
        assert out == [{"vocab": 123}]

    def test_broadcast_object_list_world1_noop(self):
        lst = ["a", 1]
        dist.broadcast_object_list(lst, src=0)
        assert lst == ["a", 1]

    def test_scatter_object_list_world1(self):
        out = []
        dist.scatter_object_list(out, [["mine"]], src=0)
        assert out == [["mine"]]


class TestExchangeOverStore:
    def test_exchange_rank_ordered(self):
        srv = KVServer(port=0)
        try:
            from paddle_tpu.parallel.launch.rendezvous import connect
            results = {}

            def rank(r):
                store = connect(srv.endpoint)
                results[r] = _exchange(store, r, 3, seq=1,
                                       payload=_enc(f"obj{r}"))

            ts = [threading.Thread(target=rank, args=(r,)) for r in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            for r in range(3):
                assert [_dec(p) for p in results[r]] == \
                    ["obj0", "obj1", "obj2"]
        finally:
            srv.stop()

    def test_exchange_timeout_when_rank_missing(self):
        srv = KVServer(port=0)
        try:
            from paddle_tpu.parallel.launch.rendezvous import connect
            store = connect(srv.endpoint)
            with pytest.raises(TimeoutError, match="1/2 ranks"):
                _exchange(store, 0, 2, seq=9, payload=_enc("x"),
                          timeout=0.5)
        finally:
            srv.stop()


class TestObjectCollectivesMultiProcess:
    def test_two_process_all_gather_and_scatter(self, tmp_path):
        """Two real processes exchange objects through the rendezvous
        store — the exact PADDLE_MASTER_KV transport trainers get from
        the launcher."""
        srv = KVServer(port=0)
        child = (
            "import os, json, sys\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import paddle_tpu.distributed as dist\n"
            "r = int(os.environ['PADDLE_TRAINER_ID'])\n"
            "out = []\n"
            "dist.all_gather_object(out, {'rank': r})\n"
            "assert out == [{'rank': 0}, {'rank': 1}], out\n"
            "mine = []\n"
            "dist.scatter_object_list(mine, ['for0', 'for1'] if r == 0 "
            "else None, src=0)\n"
            "assert mine == [f'for{r}'], mine\n"
            "lst = ['seed', r] if r == 0 else [None, None]\n"
            "dist.broadcast_object_list(lst, src=0)\n"
            "assert lst == ['seed', 0], lst\n"
            "print('RANK_OK', r)\n")
        try:
            procs = []
            for r in range(2):
                env = dict(os.environ)
                env["PYTHONPATH"] = REPO + os.pathsep + env.get(
                    "PYTHONPATH", "")
                env["JAX_PLATFORMS"] = "cpu"
                env["PALLAS_AXON_POOL_IPS"] = ""
                env["PADDLE_TRAINER_ID"] = str(r)
                env["PADDLE_TRAINERS_NUM"] = "2"
                env["PADDLE_MASTER_KV"] = srv.endpoint
                procs.append(subprocess.Popen(
                    [sys.executable, "-c", child], env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True))
            for r, p in enumerate(procs):
                out, _ = p.communicate(timeout=120)
                assert p.returncode == 0, out[-800:]
                assert f"RANK_OK {r}" in out
        finally:
            srv.stop()


class TestNamespaceParity:
    def test_gather_and_wait(self):
        t = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
        lst = []
        dist.gather(t, lst, dst=0)
        assert len(lst) >= 1
        np.testing.assert_allclose(lst[0].numpy(), [1.0, 2.0])
        dist.wait(t)  # fence: must not raise

    def test_destroy_process_group(self):
        from paddle_tpu.parallel import env as env_mod
        dist.init_parallel_env()
        assert env_mod.is_initialized()
        dist.destroy_process_group()
        assert not env_mod.is_initialized()
        dist.init_parallel_env()  # restore for other tests
