"""Tests for the extra tensor-op batch + paddle.fft (reference tail of
``python/paddle/tensor/*`` and ``python/paddle/fft.py``). Oracles: numpy."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _t(x):
    return paddle.to_tensor(np.asarray(x))


class TestExtraMath:
    def test_elementwise_pairs(self):
        x = np.array([-1.5, 2.0, 3.0], np.float32)
        y = np.array([2.0, -0.5, 4.0], np.float32)
        np.testing.assert_allclose(paddle.logaddexp(_t(x), _t(y)).numpy(),
                                   np.logaddexp(x, y), rtol=1e-6)
        np.testing.assert_allclose(paddle.copysign(_t(x), _t(y)).numpy(),
                                   np.copysign(x, y))
        np.testing.assert_allclose(paddle.hypot(_t(x), _t(y)).numpy(),
                                   np.hypot(x, y), rtol=1e-6)
        np.testing.assert_allclose(
            paddle.heaviside(_t(x), _t(y)).numpy(), np.heaviside(x, y))
        np.testing.assert_allclose(paddle.sinc(_t(x)).numpy(), np.sinc(x),
                                   rtol=1e-5, atol=1e-6)

    def test_deg_rad_gcd_lcm(self):
        np.testing.assert_allclose(paddle.deg2rad(_t([180.0])).numpy(),
                                   [np.pi], rtol=1e-6)
        np.testing.assert_allclose(paddle.rad2deg(_t([np.pi])).numpy(),
                                   [180.0], rtol=1e-6)
        np.testing.assert_array_equal(
            paddle.gcd(_t([12, 18]), _t([8, 24])).numpy(), [4, 6])
        np.testing.assert_array_equal(
            paddle.lcm(_t([4, 6]), _t([6, 8])).numpy(), [12, 24])

    def test_nan_reductions_and_quantile(self):
        x = np.array([[1.0, np.nan, 3.0], [4.0, 5.0, np.nan]], np.float32)
        np.testing.assert_allclose(paddle.nanmean(_t(x)).numpy(),
                                   np.nanmean(x), rtol=1e-6)
        np.testing.assert_allclose(paddle.nansum(_t(x), axis=1).numpy(),
                                   np.nansum(x, 1), rtol=1e-6)
        y = np.random.RandomState(0).randn(100).astype(np.float32)
        np.testing.assert_allclose(paddle.quantile(_t(y), 0.25).numpy(),
                                   np.quantile(y, 0.25), rtol=1e-5)
        yn = y.copy()
        yn[::7] = np.nan
        np.testing.assert_allclose(paddle.nanquantile(_t(yn), 0.5).numpy(),
                                   np.nanquantile(yn, 0.5), rtol=1e-5)

    def test_logcumsumexp_matches_naive(self):
        x = np.random.RandomState(1).randn(16).astype(np.float32)
        got = paddle.logcumsumexp(_t(x), axis=0).numpy()
        expect = np.log(np.cumsum(np.exp(x)))
        np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_renorm_clips_norms(self):
        x = np.random.RandomState(2).randn(4, 8).astype(np.float32) * 5
        out = paddle.renorm(_t(x), p=2.0, axis=0, max_norm=1.0).numpy()
        norms = np.linalg.norm(out, axis=1)
        assert (norms <= 1.0 + 1e-5).all()

    def test_misc_float_ops(self):
        x = np.array([1.5, -2.25], np.float32)
        m, e = paddle.frexp(_t(x))
        np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), x)
        np.testing.assert_allclose(
            paddle.ldexp(_t(x), _t([2, 1])).numpy(), [6.0, -4.5])
        assert paddle.signbit(_t(x)).numpy().tolist() == [False, True]
        assert paddle.count_nonzero(_t([[0, 1], [2, 0]])).numpy() == 2
        inf = np.array([np.inf, -np.inf, 1.0], np.float32)
        assert paddle.isposinf(_t(inf)).numpy().tolist() == [True, False, False]
        assert paddle.isneginf(_t(inf)).numpy().tolist() == [False, True, False]


class TestExtraLinalgSearch:
    def test_inv_and_cholesky_solve(self):
        rng = np.random.RandomState(3)
        a = rng.randn(4, 4).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        np.testing.assert_allclose(paddle.inv(_t(spd)).numpy(),
                                   np.linalg.inv(spd), rtol=1e-3, atol=1e-4)
        chol = np.linalg.cholesky(spd).astype(np.float32)
        b = rng.randn(4, 2).astype(np.float32)
        z = paddle.cholesky_solve(_t(b), _t(chol)).numpy()
        np.testing.assert_allclose(spd @ z, b, rtol=1e-3, atol=1e-3)

    def test_lu_and_eigvals(self):
        rng = np.random.RandomState(4)
        a = rng.randn(4, 4).astype(np.float32)
        lu_mat, piv = paddle.lu(_t(a))
        assert lu_mat.shape == [4, 4] and piv.shape == [4]
        # LAPACK getrf contract: pivots are 1-based
        assert piv.numpy().min() >= 1 and piv.numpy().max() <= 4
        # reconstruct A = P L U from 1-based pivots
        l = np.tril(lu_mat.numpy(), -1) + np.eye(4, dtype=np.float32)
        u = np.triu(lu_mat.numpy())
        rec = l @ u
        for i in reversed(range(4)):
            j = int(piv.numpy()[i]) - 1
            rec[[i, j]] = rec[[j, i]]
        np.testing.assert_allclose(rec, a, rtol=1e-3, atol=1e-4)
        ev = paddle.eigvals(_t(a)).numpy()
        np.testing.assert_allclose(np.sort(ev.real),
                                   np.sort(np.linalg.eigvals(a).real),
                                   rtol=1e-3, atol=1e-3)

    def test_multi_dot_and_vander(self):
        rng = np.random.RandomState(5)
        ms = [rng.randn(3, 4), rng.randn(4, 5), rng.randn(5, 2)]
        ms = [m.astype(np.float32) for m in ms]
        got = paddle.multi_dot([_t(m) for m in ms]).numpy()
        np.testing.assert_allclose(got, ms[0] @ ms[1] @ ms[2], rtol=1e-5)
        x = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(paddle.vander(_t(x), 3).numpy(),
                                   np.vander(x, 3))

    def test_cdist_pdist(self):
        rng = np.random.RandomState(6)
        x = rng.randn(5, 3).astype(np.float32)
        y = rng.randn(4, 3).astype(np.float32)
        got = paddle.cdist(_t(x), _t(y)).numpy()
        expect = np.sqrt(((x[:, None] - y[None]) ** 2).sum(-1))
        np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)
        pd = paddle.pdist(_t(x)).numpy()
        assert pd.shape == (10,)
        np.testing.assert_allclose(pd[0], np.linalg.norm(x[0] - x[1]),
                                   rtol=1e-5)

    def test_bucketize_mode_diagonal(self):
        edges = np.array([1.0, 3.0, 5.0], np.float32)
        x = np.array([0.5, 1.0, 4.0, 9.0], np.float32)
        np.testing.assert_array_equal(
            paddle.bucketize(_t(x), _t(edges)).numpy(),
            np.searchsorted(edges, x, side="left"))
        v, i = paddle.mode(_t(np.array([[1.0, 2.0, 2.0, 3.0]])))
        assert v.numpy().tolist() == [2.0]
        assert i.numpy().tolist() == [2]  # last occurrence
        a = np.arange(9, dtype=np.float32).reshape(3, 3)
        np.testing.assert_array_equal(paddle.diagonal(_t(a)).numpy(),
                                      np.diagonal(a))

    def test_diag_embed_and_trapezoid(self):
        x = np.array([1.0, 2.0, 3.0], np.float32)
        d = paddle.diag_embed(_t(x)).numpy()
        np.testing.assert_allclose(d, np.diag(x))
        y = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        np.testing.assert_allclose(paddle.trapezoid(_t(y)).numpy(),
                                   np.trapezoid(y) if hasattr(np, "trapezoid")
                                   else np.trapz(y), rtol=1e-6)

    def test_combinations(self):
        x = np.array([10.0, 20.0, 30.0], np.float32)
        c = paddle.combinations(_t(x), 2).numpy()
        np.testing.assert_allclose(c, [[10, 20], [10, 30], [20, 30]])


class TestFFT:
    def test_fft_roundtrip(self):
        x = np.random.RandomState(7).randn(16).astype(np.float32)
        back = paddle.fft.ifft(paddle.fft.fft(_t(x))).numpy()
        np.testing.assert_allclose(back.real, x, atol=1e-5)

    def test_rfft_matches_numpy(self):
        x = np.random.RandomState(8).randn(32).astype(np.float32)
        got = paddle.fft.rfft(_t(x)).numpy()
        np.testing.assert_allclose(got, np.fft.rfft(x).astype(np.complex64),
                                   rtol=1e-4, atol=1e-4)

    def test_fft2_and_shift(self):
        x = np.random.RandomState(9).randn(8, 8).astype(np.float32)
        got = paddle.fft.fft2(_t(x)).numpy()
        np.testing.assert_allclose(got, np.fft.fft2(x).astype(np.complex64),
                                   rtol=1e-3, atol=1e-3)
        sh = paddle.fft.fftshift(_t(x)).numpy()
        np.testing.assert_allclose(sh, np.fft.fftshift(x))

    def test_fftfreq(self):
        np.testing.assert_allclose(paddle.fft.fftfreq(8, d=0.5).numpy(),
                                   np.fft.fftfreq(8, d=0.5), rtol=1e-6)

    def test_fft_grad_flows(self):
        """Spectral loss is differentiable w.r.t. the real input."""
        import jax
        import jax.numpy as jnp

        def loss(v):
            return jnp.sum(jnp.abs(paddle.fft.rfft(
                paddle.to_tensor(v)).value) ** 2)

        x = np.random.RandomState(10).randn(16).astype(np.float32)
        g = jax.grad(loss)(x)
        # Parseval: d/dx sum|X|^2 ~ 2*N*x-ish; just require nonzero finite
        assert np.all(np.isfinite(np.asarray(g)))
        assert np.any(np.abs(np.asarray(g)) > 0)


class TestSignal:
    def test_frame_overlap_add_roundtrip_hop_eq_len(self):
        x = np.arange(16, dtype=np.float32)
        f = paddle.signal.frame(_t(x), frame_length=4, hop_length=4)
        assert f.shape == [4, 4]
        back = paddle.signal.overlap_add(f, hop_length=4)
        np.testing.assert_allclose(back.numpy(), x)

    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 256).astype(np.float32)
        win = np.hanning(64).astype(np.float32)
        spec = paddle.signal.stft(_t(x), n_fft=64, hop_length=16,
                                  window=_t(win))
        assert spec.shape == [2, 33, 256 // 16 + 1]
        back = paddle.signal.istft(spec, n_fft=64, hop_length=16,
                                   window=_t(win), length=256)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-4)

    def test_stft_matches_manual_dft(self):
        rng = np.random.RandomState(1)
        x = rng.randn(128).astype(np.float32)
        spec = paddle.signal.stft(_t(x), n_fft=32, hop_length=8,
                                  center=False).numpy()
        # frame 0 == rfft of the first 32 samples (rect window)
        np.testing.assert_allclose(spec[:, 0], np.fft.rfft(x[:32]),
                                   rtol=1e-4, atol=1e-4)

    def test_frame_overlap_add_axis0(self):
        x = np.arange(32, dtype=np.float32).reshape(16, 2)
        f = paddle.signal.frame(_t(x), frame_length=4, hop_length=4, axis=0)
        assert f.shape == [4, 4, 2]  # [num_frames, frame_length, ...]
        back = paddle.signal.overlap_add(f, hop_length=4, axis=0)
        np.testing.assert_allclose(back.numpy(), x)

    def test_istft_return_complex_contract(self):
        rng = np.random.RandomState(2)
        x = rng.randn(128).astype(np.float32)
        spec = paddle.signal.stft(_t(x), n_fft=32, hop_length=8,
                                  onesided=False)
        out = paddle.signal.istft(spec, n_fft=32, hop_length=8,
                                  onesided=False, return_complex=True,
                                  length=128)
        assert np.iscomplexobj(out.numpy())
        np.testing.assert_allclose(out.numpy().real, x, rtol=1e-3, atol=1e-4)
        with pytest.raises(ValueError, match="onesided"):
            paddle.signal.istft(spec, n_fft=32, onesided=True,
                                return_complex=True)


class TestR3LongTail:
    """The r3 long-tail batch (broadcast_shape..randint_like), numpy oracles."""

    def test_shapes_and_views(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
        assert paddle.unflatten(_t(x), 1, (2, 2)).shape == [3, 2, 2]
        assert paddle.view_as(_t(x), _t(np.zeros((4, 3)))).shape == [4, 3]
        assert int(paddle.rank(_t(x)).numpy()) == 2
        np.testing.assert_allclose(paddle.mv(_t(x), _t(np.ones(4, np.float32))).numpy(),
                                   x @ np.ones(4, np.float32))

    def test_predicates(self):
        x = _t(np.zeros((2, 2), np.float32))
        assert paddle.is_tensor(x) and not paddle.is_tensor(0)
        assert paddle.is_floating_point(x)
        assert paddle.is_integer(_t(np.array([1])))
        assert paddle.is_complex(_t(np.array([1 + 2j], np.complex64)))
        assert bool(paddle.is_empty(_t(np.zeros((0, 3)))).numpy())
        assert not bool(paddle.is_empty(x).numpy())

    def test_complex_and_sgn(self):
        re = np.array([1.0, 0.0, -3.0], np.float32)
        im = np.array([0.0, 2.0, 4.0], np.float32)
        c = paddle.complex(_t(re), _t(im))
        np.testing.assert_allclose(c.numpy(), re + 1j * im)
        s = paddle.sgn(c).numpy()
        z = re + 1j * im
        expect = np.where(np.abs(z) == 0, 0, z / np.where(np.abs(z) == 0, 1, np.abs(z)))
        np.testing.assert_allclose(s, expect, rtol=1e-6)
        np.testing.assert_allclose(
            paddle.sgn(_t(np.array([-2.0, 0.0, 5.0], np.float32))).numpy(),
            [-1.0, 0.0, 1.0])

    def test_bessel_polygamma(self):
        from scipy import special
        x = np.linspace(0.1, 3.0, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.i0(_t(x)).numpy(), special.i0(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.i0e(_t(x)).numpy(), special.i0e(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.i1(_t(x)).numpy(), special.i1(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.i1e(_t(x)).numpy(), special.i1e(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.polygamma(_t(x), 1).numpy(),
                                   special.polygamma(1, x), rtol=1e-4)

    def test_take_modes(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        idx = np.array([0, 13, -1])
        np.testing.assert_allclose(paddle.take(_t(x), _t(idx), mode="wrap").numpy(),
                                   np.take(x, idx, mode="wrap"))
        np.testing.assert_allclose(paddle.take(_t(x), _t(np.array([-3, 0, 11, 20])),
                                               mode="clip").numpy(),
                                   np.take(x, [-3, 0, 11, 20], mode="clip"))
        np.testing.assert_allclose(paddle.take(_t(x), _t(np.array([-1, 2]))).numpy(),
                                   x.ravel()[[-1, 2]])

    def test_index_ops(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        idx = np.array([[0, 1], [2, 3], [0, 0]])
        np.testing.assert_allclose(paddle.index_sample(_t(x), _t(idx)).numpy(),
                                   np.take_along_axis(x, idx, axis=1))
        out = paddle.index_fill(_t(x), _t(np.array([1])), 0, -1.0).numpy()
        assert (out[1] == -1.0).all() and (out[0] == x[0]).all()
        ss = paddle.select_scatter(_t(x), _t(np.zeros(3, np.float32)), 1, 2).numpy()
        assert (ss[:, 2] == 0).all() and (ss[:, 0] == x[:, 0]).all()

    def test_masked_scatter_and_multiplex(self):
        x = np.zeros((2, 3), np.float32)
        mask = np.array([[True, False, True], [False, True, False]])
        vals = np.arange(10, 16, dtype=np.float32)
        out = paddle.masked_scatter(_t(x), _t(mask), _t(vals)).numpy()
        expect = x.copy()
        expect[mask] = vals[: mask.sum()]
        np.testing.assert_allclose(out, expect)
        a = np.arange(6, dtype=np.float32).reshape(3, 2)
        b = a + 100
        sel = paddle.multiplex([_t(a), _t(b)], _t(np.array([[0], [1], [0]]))).numpy()
        np.testing.assert_allclose(sel, np.stack([a[0], b[1], a[2]]))

    def test_shard_index(self):
        out = paddle.shard_index(_t(np.array([0, 5, 9, 15])), 20, 2, 0).numpy()
        np.testing.assert_array_equal(out, [0, 5, 9, -1])
        out1 = paddle.shard_index(_t(np.array([0, 5, 9, 15])), 20, 2, 1).numpy()
        np.testing.assert_array_equal(out1, [-1, -1, -1, 5])

    def test_splits(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 6, 2)
        for ours, ref in [(paddle.tensor_split(_t(x), 4, axis=1),
                           np.array_split(x, 4, axis=1)),
                          (paddle.hsplit(_t(x), 2), np.array_split(x, 2, 1)),
                          (paddle.vsplit(_t(x), 2), np.array_split(x, 2, 0)),
                          (paddle.dsplit(_t(x), 2), np.array_split(x, 2, 2))]:
            assert len(ours) == len(ref)
            for o, r in zip(ours, ref):
                np.testing.assert_allclose(o.numpy(), r)
        parts = paddle.tensor_split(_t(x), [1, 4], axis=1)
        assert [p.shape[1] for p in parts] == [1, 3, 2]

    def test_strided_slice(self):
        x = np.arange(24, dtype=np.float32).reshape(4, 6)
        out = paddle.strided_slice(_t(x), [0, 1], [0, 1], [4, 6], [2, 2]).numpy()
        np.testing.assert_allclose(out, x[0:4:2, 1:6:2])

    def test_unique_consecutive(self):
        x = np.array([1, 1, 2, 2, 2, 3, 1])
        u, inv, cnt = paddle.unique_consecutive(_t(x), return_inverse=True,
                                                return_counts=True)
        np.testing.assert_array_equal(u.numpy(), [1, 2, 3, 1])
        np.testing.assert_array_equal(inv.numpy(), [0, 0, 1, 1, 1, 2, 3])
        np.testing.assert_array_equal(cnt.numpy(), [2, 3, 1, 1])

    def test_tri_indices_and_tolist(self):
        np.testing.assert_array_equal(paddle.tril_indices(3).numpy(),
                                      np.stack(np.tril_indices(3)))
        np.testing.assert_array_equal(paddle.triu_indices(3, offset=1).numpy(),
                                      np.stack(np.triu_indices(3, k=1)))
        x = np.arange(4, dtype=np.float32).reshape(2, 2)
        assert _t(x).tolist() == [[0.0, 1.0], [2.0, 3.0]]
        assert paddle.tolist(_t(x)) == [[0.0, 1.0], [2.0, 3.0]]

    def test_nanmedian(self):
        x = np.array([[1.0, np.nan, 3.0], [4.0, 5.0, np.nan]], np.float32)
        np.testing.assert_allclose(paddle.nanmedian(_t(x)).numpy(),
                                   np.nanmedian(x))
        np.testing.assert_allclose(paddle.nanmedian(_t(x), axis=1).numpy(),
                                   np.nanmedian(x, axis=1))

    def test_random_ops(self):
        paddle.seed(123)
        p = paddle.poisson(_t(np.full((2000,), 4.0, np.float32))).numpy()
        assert abs(p.mean() - 4.0) < 0.3  # Poisson(4): se(mean) ~ 0.045
        assert p.dtype == np.float32
        r = paddle.randint_like(_t(np.zeros((100,), np.float32)), 2, 7).numpy()
        assert r.min() >= 2 and r.max() < 7
        r2 = paddle.randint_like(_t(np.zeros((10,), np.float32)), 5).numpy()
        assert r2.min() >= 0 and r2.max() < 5


class TestLinalgGaps:
    def test_norms_and_cond(self):
        x = np.random.RandomState(0).randn(4, 4).astype(np.float32)
        np.testing.assert_allclose(paddle.linalg.vector_norm(_t(x.ravel()), p=3).numpy(),
                                   np.linalg.norm(x.ravel(), ord=3), rtol=1e-5)
        np.testing.assert_allclose(paddle.linalg.matrix_norm(_t(x)).numpy(),
                                   np.linalg.norm(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.linalg.cond(_t(x)).numpy(),
                                   np.linalg.cond(x), rtol=1e-4)

    def test_svd_lowrank_reconstructs(self):
        rng = np.random.RandomState(1)
        # exactly rank-2 matrix: rank-2 truncation must reconstruct it
        a = (rng.randn(6, 2) @ rng.randn(2, 5)).astype(np.float32)
        u, s, v = paddle.linalg.svd_lowrank(_t(a), q=2)
        rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
        np.testing.assert_allclose(rec, a, atol=1e-4)

    def test_linalg_namespace_complete(self):
        for n in ["cholesky_solve", "eigvals", "householder_product", "inv",
                  "lu", "lu_unpack", "multi_dot", "vector_norm",
                  "matrix_norm", "cond", "svd_lowrank"]:
            assert hasattr(paddle.linalg, n), n


class TestReviewRegressions:
    def test_vector_norm_flattens(self):
        x = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        np.testing.assert_allclose(paddle.linalg.vector_norm(_t(x)).numpy(),
                                   np.linalg.norm(x.ravel()), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.linalg.vector_norm(_t(x), p=3).numpy(),
            np.linalg.norm(x.ravel(), ord=3), rtol=1e-5)

    def test_masked_scatter_undersupply_raises(self):
        x = np.zeros(4, np.float32)
        mask = np.array([True, True, True, True])
        with pytest.raises(ValueError):
            paddle.masked_scatter(_t(x), _t(mask),
                                  _t(np.array([1.0, 2.0], np.float32)))
