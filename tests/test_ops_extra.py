"""Tests for the extra tensor-op batch + paddle.fft (reference tail of
``python/paddle/tensor/*`` and ``python/paddle/fft.py``). Oracles: numpy."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _t(x):
    return paddle.to_tensor(np.asarray(x))


class TestExtraMath:
    def test_elementwise_pairs(self):
        x = np.array([-1.5, 2.0, 3.0], np.float32)
        y = np.array([2.0, -0.5, 4.0], np.float32)
        np.testing.assert_allclose(paddle.logaddexp(_t(x), _t(y)).numpy(),
                                   np.logaddexp(x, y), rtol=1e-6)
        np.testing.assert_allclose(paddle.copysign(_t(x), _t(y)).numpy(),
                                   np.copysign(x, y))
        np.testing.assert_allclose(paddle.hypot(_t(x), _t(y)).numpy(),
                                   np.hypot(x, y), rtol=1e-6)
        np.testing.assert_allclose(
            paddle.heaviside(_t(x), _t(y)).numpy(), np.heaviside(x, y))
        np.testing.assert_allclose(paddle.sinc(_t(x)).numpy(), np.sinc(x),
                                   rtol=1e-5, atol=1e-6)

    def test_deg_rad_gcd_lcm(self):
        np.testing.assert_allclose(paddle.deg2rad(_t([180.0])).numpy(),
                                   [np.pi], rtol=1e-6)
        np.testing.assert_allclose(paddle.rad2deg(_t([np.pi])).numpy(),
                                   [180.0], rtol=1e-6)
        np.testing.assert_array_equal(
            paddle.gcd(_t([12, 18]), _t([8, 24])).numpy(), [4, 6])
        np.testing.assert_array_equal(
            paddle.lcm(_t([4, 6]), _t([6, 8])).numpy(), [12, 24])

    def test_nan_reductions_and_quantile(self):
        x = np.array([[1.0, np.nan, 3.0], [4.0, 5.0, np.nan]], np.float32)
        np.testing.assert_allclose(paddle.nanmean(_t(x)).numpy(),
                                   np.nanmean(x), rtol=1e-6)
        np.testing.assert_allclose(paddle.nansum(_t(x), axis=1).numpy(),
                                   np.nansum(x, 1), rtol=1e-6)
        y = np.random.RandomState(0).randn(100).astype(np.float32)
        np.testing.assert_allclose(paddle.quantile(_t(y), 0.25).numpy(),
                                   np.quantile(y, 0.25), rtol=1e-5)
        yn = y.copy()
        yn[::7] = np.nan
        np.testing.assert_allclose(paddle.nanquantile(_t(yn), 0.5).numpy(),
                                   np.nanquantile(yn, 0.5), rtol=1e-5)

    def test_logcumsumexp_matches_naive(self):
        x = np.random.RandomState(1).randn(16).astype(np.float32)
        got = paddle.logcumsumexp(_t(x), axis=0).numpy()
        expect = np.log(np.cumsum(np.exp(x)))
        np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_renorm_clips_norms(self):
        x = np.random.RandomState(2).randn(4, 8).astype(np.float32) * 5
        out = paddle.renorm(_t(x), p=2.0, axis=0, max_norm=1.0).numpy()
        norms = np.linalg.norm(out, axis=1)
        assert (norms <= 1.0 + 1e-5).all()

    def test_misc_float_ops(self):
        x = np.array([1.5, -2.25], np.float32)
        m, e = paddle.frexp(_t(x))
        np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), x)
        np.testing.assert_allclose(
            paddle.ldexp(_t(x), _t([2, 1])).numpy(), [6.0, -4.5])
        assert paddle.signbit(_t(x)).numpy().tolist() == [False, True]
        assert paddle.count_nonzero(_t([[0, 1], [2, 0]])).numpy() == 2
        inf = np.array([np.inf, -np.inf, 1.0], np.float32)
        assert paddle.isposinf(_t(inf)).numpy().tolist() == [True, False, False]
        assert paddle.isneginf(_t(inf)).numpy().tolist() == [False, True, False]


class TestExtraLinalgSearch:
    def test_inv_and_cholesky_solve(self):
        rng = np.random.RandomState(3)
        a = rng.randn(4, 4).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        np.testing.assert_allclose(paddle.inv(_t(spd)).numpy(),
                                   np.linalg.inv(spd), rtol=1e-3, atol=1e-4)
        chol = np.linalg.cholesky(spd).astype(np.float32)
        b = rng.randn(4, 2).astype(np.float32)
        z = paddle.cholesky_solve(_t(b), _t(chol)).numpy()
        np.testing.assert_allclose(spd @ z, b, rtol=1e-3, atol=1e-3)

    def test_lu_and_eigvals(self):
        rng = np.random.RandomState(4)
        a = rng.randn(4, 4).astype(np.float32)
        lu_mat, piv = paddle.lu(_t(a))
        assert lu_mat.shape == [4, 4] and piv.shape == [4]
        # LAPACK getrf contract: pivots are 1-based
        assert piv.numpy().min() >= 1 and piv.numpy().max() <= 4
        # reconstruct A = P L U from 1-based pivots
        l = np.tril(lu_mat.numpy(), -1) + np.eye(4, dtype=np.float32)
        u = np.triu(lu_mat.numpy())
        rec = l @ u
        for i in reversed(range(4)):
            j = int(piv.numpy()[i]) - 1
            rec[[i, j]] = rec[[j, i]]
        np.testing.assert_allclose(rec, a, rtol=1e-3, atol=1e-4)
        ev = paddle.eigvals(_t(a)).numpy()
        np.testing.assert_allclose(np.sort(ev.real),
                                   np.sort(np.linalg.eigvals(a).real),
                                   rtol=1e-3, atol=1e-3)

    def test_multi_dot_and_vander(self):
        rng = np.random.RandomState(5)
        ms = [rng.randn(3, 4), rng.randn(4, 5), rng.randn(5, 2)]
        ms = [m.astype(np.float32) for m in ms]
        got = paddle.multi_dot([_t(m) for m in ms]).numpy()
        np.testing.assert_allclose(got, ms[0] @ ms[1] @ ms[2], rtol=1e-5)
        x = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(paddle.vander(_t(x), 3).numpy(),
                                   np.vander(x, 3))

    def test_cdist_pdist(self):
        rng = np.random.RandomState(6)
        x = rng.randn(5, 3).astype(np.float32)
        y = rng.randn(4, 3).astype(np.float32)
        got = paddle.cdist(_t(x), _t(y)).numpy()
        expect = np.sqrt(((x[:, None] - y[None]) ** 2).sum(-1))
        np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)
        pd = paddle.pdist(_t(x)).numpy()
        assert pd.shape == (10,)
        np.testing.assert_allclose(pd[0], np.linalg.norm(x[0] - x[1]),
                                   rtol=1e-5)

    def test_bucketize_mode_diagonal(self):
        edges = np.array([1.0, 3.0, 5.0], np.float32)
        x = np.array([0.5, 1.0, 4.0, 9.0], np.float32)
        np.testing.assert_array_equal(
            paddle.bucketize(_t(x), _t(edges)).numpy(),
            np.searchsorted(edges, x, side="left"))
        v, i = paddle.mode(_t(np.array([[1.0, 2.0, 2.0, 3.0]])))
        assert v.numpy().tolist() == [2.0]
        assert i.numpy().tolist() == [2]  # last occurrence
        a = np.arange(9, dtype=np.float32).reshape(3, 3)
        np.testing.assert_array_equal(paddle.diagonal(_t(a)).numpy(),
                                      np.diagonal(a))

    def test_diag_embed_and_trapezoid(self):
        x = np.array([1.0, 2.0, 3.0], np.float32)
        d = paddle.diag_embed(_t(x)).numpy()
        np.testing.assert_allclose(d, np.diag(x))
        y = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        np.testing.assert_allclose(paddle.trapezoid(_t(y)).numpy(),
                                   np.trapezoid(y) if hasattr(np, "trapezoid")
                                   else np.trapz(y), rtol=1e-6)

    def test_combinations(self):
        x = np.array([10.0, 20.0, 30.0], np.float32)
        c = paddle.combinations(_t(x), 2).numpy()
        np.testing.assert_allclose(c, [[10, 20], [10, 30], [20, 30]])


class TestFFT:
    def test_fft_roundtrip(self):
        x = np.random.RandomState(7).randn(16).astype(np.float32)
        back = paddle.fft.ifft(paddle.fft.fft(_t(x))).numpy()
        np.testing.assert_allclose(back.real, x, atol=1e-5)

    def test_rfft_matches_numpy(self):
        x = np.random.RandomState(8).randn(32).astype(np.float32)
        got = paddle.fft.rfft(_t(x)).numpy()
        np.testing.assert_allclose(got, np.fft.rfft(x).astype(np.complex64),
                                   rtol=1e-4, atol=1e-4)

    def test_fft2_and_shift(self):
        x = np.random.RandomState(9).randn(8, 8).astype(np.float32)
        got = paddle.fft.fft2(_t(x)).numpy()
        np.testing.assert_allclose(got, np.fft.fft2(x).astype(np.complex64),
                                   rtol=1e-3, atol=1e-3)
        sh = paddle.fft.fftshift(_t(x)).numpy()
        np.testing.assert_allclose(sh, np.fft.fftshift(x))

    def test_fftfreq(self):
        np.testing.assert_allclose(paddle.fft.fftfreq(8, d=0.5).numpy(),
                                   np.fft.fftfreq(8, d=0.5), rtol=1e-6)

    def test_fft_grad_flows(self):
        """Spectral loss is differentiable w.r.t. the real input."""
        import jax
        import jax.numpy as jnp

        def loss(v):
            return jnp.sum(jnp.abs(paddle.fft.rfft(
                paddle.to_tensor(v)).value) ** 2)

        x = np.random.RandomState(10).randn(16).astype(np.float32)
        g = jax.grad(loss)(x)
        # Parseval: d/dx sum|X|^2 ~ 2*N*x-ish; just require nonzero finite
        assert np.all(np.isfinite(np.asarray(g)))
        assert np.any(np.abs(np.asarray(g)) > 0)


class TestSignal:
    def test_frame_overlap_add_roundtrip_hop_eq_len(self):
        x = np.arange(16, dtype=np.float32)
        f = paddle.signal.frame(_t(x), frame_length=4, hop_length=4)
        assert f.shape == [4, 4]
        back = paddle.signal.overlap_add(f, hop_length=4)
        np.testing.assert_allclose(back.numpy(), x)

    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 256).astype(np.float32)
        win = np.hanning(64).astype(np.float32)
        spec = paddle.signal.stft(_t(x), n_fft=64, hop_length=16,
                                  window=_t(win))
        assert spec.shape == [2, 33, 256 // 16 + 1]
        back = paddle.signal.istft(spec, n_fft=64, hop_length=16,
                                   window=_t(win), length=256)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-4)

    def test_stft_matches_manual_dft(self):
        rng = np.random.RandomState(1)
        x = rng.randn(128).astype(np.float32)
        spec = paddle.signal.stft(_t(x), n_fft=32, hop_length=8,
                                  center=False).numpy()
        # frame 0 == rfft of the first 32 samples (rect window)
        np.testing.assert_allclose(spec[:, 0], np.fft.rfft(x[:32]),
                                   rtol=1e-4, atol=1e-4)

    def test_frame_overlap_add_axis0(self):
        x = np.arange(32, dtype=np.float32).reshape(16, 2)
        f = paddle.signal.frame(_t(x), frame_length=4, hop_length=4, axis=0)
        assert f.shape == [4, 4, 2]  # [num_frames, frame_length, ...]
        back = paddle.signal.overlap_add(f, hop_length=4, axis=0)
        np.testing.assert_allclose(back.numpy(), x)

    def test_istft_return_complex_contract(self):
        rng = np.random.RandomState(2)
        x = rng.randn(128).astype(np.float32)
        spec = paddle.signal.stft(_t(x), n_fft=32, hop_length=8,
                                  onesided=False)
        out = paddle.signal.istft(spec, n_fft=32, hop_length=8,
                                  onesided=False, return_complex=True,
                                  length=128)
        assert np.iscomplexobj(out.numpy())
        np.testing.assert_allclose(out.numpy().real, x, rtol=1e-3, atol=1e-4)
        with pytest.raises(ValueError, match="onesided"):
            paddle.signal.istft(spec, n_fft=32, onesided=True,
                                return_complex=True)
