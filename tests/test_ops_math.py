"""Op-vs-NumPy oracle tests (reference pattern: test/legacy_test/test_*_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import OpTest

rng = np.random.RandomState(42)


class TestElementwise(OpTest):
    def test_add(self):
        a = rng.randn(4, 5).astype(np.float32)
        b = rng.randn(4, 5).astype(np.float32)
        self.check_output(paddle.add, np.add, [a, b])
        self.check_grad(paddle.add, [a, b])

    def test_broadcast_ops(self):
        a = rng.randn(4, 5).astype(np.float32)
        b = rng.randn(5).astype(np.float32)
        self.check_output(paddle.multiply, np.multiply, [a, b])
        self.check_output(paddle.subtract, np.subtract, [a, b])
        self.check_output(paddle.divide, np.divide, [a, b + 2.0])

    def test_unary(self):
        x = np.abs(rng.randn(3, 4)).astype(np.float32) + 0.5
        self.check_output(paddle.sqrt, np.sqrt, [x])
        self.check_output(paddle.exp, np.exp, [x])
        self.check_output(paddle.log, np.log, [x])
        self.check_output(paddle.tanh, np.tanh, [x])
        self.check_output(paddle.abs, np.abs, [x])
        self.check_grad(paddle.sqrt, [x])
        self.check_grad(paddle.tanh, [x])

    def test_pow_clip(self):
        x = rng.rand(3, 4).astype(np.float32) + 0.5
        self.check_output(lambda t: paddle.pow(t, 2.0),
                          lambda a: np.power(a, 2.0), [x])
        self.check_output(lambda t: paddle.clip(t, 0.6, 1.0),
                          lambda a: np.clip(a, 0.6, 1.0), [x])


class TestMatmul(OpTest):
    def test_matmul(self):
        a = rng.randn(4, 5).astype(np.float32)
        b = rng.randn(5, 3).astype(np.float32)
        self.check_output(paddle.matmul, np.matmul, [a, b])
        self.check_grad(paddle.matmul, [a, b])

    def test_matmul_transpose(self):
        a = rng.randn(5, 4).astype(np.float32)
        b = rng.randn(5, 3).astype(np.float32)
        self.check_output(
            lambda x, y: paddle.matmul(x, y, transpose_x=True),
            lambda x, y: np.matmul(x.T, y), [a, b])

    def test_batched(self):
        a = rng.randn(2, 4, 5).astype(np.float32)
        b = rng.randn(2, 5, 3).astype(np.float32)
        self.check_output(paddle.bmm, np.matmul, [a, b])

    def test_einsum(self):
        a = rng.randn(2, 3, 4).astype(np.float32)
        b = rng.randn(4, 5).astype(np.float32)
        self.check_output(
            lambda x, y: paddle.einsum("bij,jk->bik", x, y),
            lambda x, y: np.einsum("bij,jk->bik", x, y), [a, b])


class TestReduce(OpTest):
    def test_sum_mean(self):
        x = rng.randn(3, 4, 5).astype(np.float32)
        self.check_output(paddle.sum, np.sum, [x])
        self.check_output(lambda t: paddle.sum(t, axis=1),
                          lambda a: np.sum(a, axis=1), [x])
        self.check_output(lambda t: paddle.mean(t, axis=[0, 2], keepdim=True),
                          lambda a: np.mean(a, axis=(0, 2), keepdims=True), [x])
        self.check_grad(lambda t: paddle.mean(t), [x])

    def test_max_min_prod(self):
        x = rng.randn(3, 4).astype(np.float32)
        self.check_output(paddle.max, np.max, [x])
        self.check_output(lambda t: paddle.min(t, axis=0),
                          lambda a: np.min(a, axis=0), [x])
        self.check_output(lambda t: paddle.prod(t, axis=1),
                          lambda a: np.prod(a, axis=1), [x])

    def test_std_var_logsumexp(self):
        x = rng.randn(6, 4).astype(np.float32)
        self.check_output(paddle.var, lambda a: np.var(a, ddof=1), [x],
                          rtol=1e-4)
        from scipy.special import logsumexp as _lse
        self.check_output(paddle.logsumexp, lambda a: _lse(a), [x], rtol=1e-4)

    def test_cumsum(self):
        x = rng.randn(3, 4).astype(np.float32)
        self.check_output(lambda t: paddle.cumsum(t, axis=1),
                          lambda a: np.cumsum(a, axis=1), [x])


class TestSearchSort(OpTest):
    def test_argmax_sort(self):
        x = rng.randn(4, 6).astype(np.float32)
        self.check_output(lambda t: paddle.argmax(t, axis=1),
                          lambda a: np.argmax(a, axis=1), [x])
        self.check_output(lambda t: paddle.sort(t, axis=1),
                          lambda a: np.sort(a, axis=1), [x])
        self.check_output(lambda t: paddle.argsort(t, axis=1),
                          lambda a: np.argsort(a, axis=1, kind="stable"), [x])

    def test_topk(self):
        x = rng.randn(3, 8).astype(np.float32)
        v, i = paddle.topk(paddle.to_tensor(x), 3, axis=1)
        ref = np.sort(x, axis=1)[:, ::-1][:, :3]
        np.testing.assert_allclose(v.numpy(), ref, rtol=1e-6)

    def test_where_comparison(self):
        a = rng.randn(3, 4).astype(np.float32)
        b = rng.randn(3, 4).astype(np.float32)
        self.check_output(lambda x, y: paddle.where(x > y, x, y),
                          lambda x, y: np.where(x > y, x, y), [a, b])


class TestManipulation(OpTest):
    def test_reshape_transpose(self):
        x = rng.randn(2, 3, 4).astype(np.float32)
        self.check_output(lambda t: paddle.reshape(t, [4, 6]),
                          lambda a: a.reshape(4, 6), [x])
        self.check_output(lambda t: paddle.transpose(t, [2, 0, 1]),
                          lambda a: np.transpose(a, (2, 0, 1)), [x])
        self.check_grad(lambda t: paddle.reshape(t, [-1]), [x])

    def test_concat_split_stack(self):
        a = rng.randn(2, 3).astype(np.float32)
        b = rng.randn(2, 3).astype(np.float32)
        out = paddle.concat([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
        np.testing.assert_allclose(out.numpy(), np.concatenate([a, b], 0))
        parts = paddle.split(paddle.to_tensor(a), [1, 2], axis=1)
        assert [p.shape for p in parts] == [[2, 1], [2, 2]]
        st = paddle.stack([paddle.to_tensor(a), paddle.to_tensor(b)], axis=1)
        assert st.shape == [2, 2, 3]

    def test_squeeze_expand_tile(self):
        x = rng.randn(1, 3, 1).astype(np.float32)
        assert paddle.squeeze(paddle.to_tensor(x)).shape == [3]
        assert paddle.unsqueeze(paddle.to_tensor(x), 0).shape == [1, 1, 3, 1]
        e = paddle.expand(paddle.to_tensor(x), [4, 3, 5])
        assert e.shape == [4, 3, 5]
        t = paddle.tile(paddle.to_tensor(x), [2, 1, 2])
        assert t.shape == [2, 3, 2]

    def test_gather_scatter(self):
        x = rng.randn(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4])
        self.check_output(lambda t, i: paddle.gather(t, i, axis=0),
                          lambda a, i: a[i], [x, idx])
        g = paddle.gather_nd(paddle.to_tensor(x),
                             paddle.to_tensor(np.array([[0, 1], [2, 2]])))
        np.testing.assert_allclose(g.numpy(), x[[0, 2], [1, 2]])

    def test_pad(self):
        x = rng.randn(2, 3, 4, 5).astype(np.float32)
        out = paddle.nn.functional.pad(paddle.to_tensor(x), [1, 2, 3, 4])
        assert out.shape == [2, 3, 4 + 3 + 4, 5 + 1 + 2]

    def test_getitem_setitem(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        np.testing.assert_allclose(x[1].numpy(), np.arange(4, 8))
        np.testing.assert_allclose(x[:, 1:3].numpy(),
                                   np.arange(12).reshape(3, 4)[:, 1:3])
        x[0] = 0.0
        assert float(x[0].sum().value) == 0.0


class TestCreation(OpTest):
    def test_basic(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        assert paddle.ones([2], dtype="int32").dtype == np.int32
        np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.eye(3).numpy(), np.eye(3))
        f = paddle.full([2, 2], 7.0)
        assert float(f.numpy()[0, 0]) == 7.0
        np.testing.assert_allclose(
            paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5), rtol=1e-6)

    def test_random_deterministic(self):
        paddle.seed(123)
        a = paddle.randn([4, 4])
        paddle.seed(123)
        b = paddle.randn([4, 4])
        np.testing.assert_allclose(a.numpy(), b.numpy())

    def test_tril_triu(self):
        x = rng.randn(4, 4).astype(np.float32)
        self.check_output(paddle.tril, np.tril, [x])
        self.check_output(paddle.triu, np.triu, [x])


class TestAutogradEngine:
    def test_chain(self):
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                             stop_gradient=False)
        y = (x * x + 2 * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy() + 2)

    def test_shared_node(self):
        x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        y = x * x
        z = y + y
        z.backward()
        np.testing.assert_allclose(float(x.grad.value), 8.0)

    def test_stop_gradient(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        y = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=True)
        (x * y).sum().backward()
        assert x.grad is not None and y.grad is None

    def test_no_grad(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        with paddle.no_grad():
            y = (x * 2).sum()
        assert y._grad_node is None

    def test_grad_api(self):
        x = paddle.to_tensor(np.float32(3.0), stop_gradient=False)
        y = x * x
        (g,) = paddle.grad(y, x)
        np.testing.assert_allclose(float(g.value), 6.0)
        assert x.grad is None  # paddle.grad must not pollute .grad

    def test_grad_unused_input_raises(self):
        """ADVICE r1: silently substituting zeros for unreachable inputs
        masks disconnected-graph bugs — the reference raises."""
        import pytest
        x = paddle.to_tensor(np.float32(3.0), stop_gradient=False)
        unused = paddle.to_tensor(np.float32(1.0), stop_gradient=False)
        y = x * x
        with pytest.raises(ValueError, match="unreachable"):
            paddle.grad(y, [unused])
        g, = paddle.grad(y, [unused], allow_unused=True)
        assert g is None

    def test_detach(self):
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        d = (x * 2).detach()
        (d * 3).sum().backward()
        assert x.grad is None

    def test_tensor_hook(self):
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        x.register_hook(lambda g: g * 10)
        (x * 1.0).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [10.0, 10.0])

    def test_retain_graph(self):
        x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(float(x.grad.value), 8.0)


class TestCodeReviewRegressions:
    """Regression tests for review findings (grad-on-intermediate, masked_select
    under grad, softplus overflow grad)."""

    def test_grad_on_intermediate_tensor(self):
        x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        y = x * 2
        z = (y * y).sum()
        (gy,) = paddle.grad(z, [y])
        np.testing.assert_allclose(float(gy.value), 8.0)  # dz/dy = 2y = 8

    def test_masked_select_with_grad_input(self):
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                             stop_gradient=False)
        out = paddle.masked_select(x, paddle.to_tensor(np.array([True, False, True])))
        np.testing.assert_allclose(out.numpy(), [1.0, 3.0])

    def test_softplus_large_input_grad(self):
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(np.float32(100.0), stop_gradient=False)
        y = F.softplus(x)
        y.backward()
        assert np.isfinite(float(x.grad.value))
        np.testing.assert_allclose(float(x.grad.value), 1.0, rtol=1e-5)

    def test_maxpool_return_mask_and_ceil(self):
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        out, mask = F.max_pool2d(x, 2, 2, return_mask=True)
        np.testing.assert_allclose(out.numpy()[0, 0], [[5, 7], [13, 15]])
        np.testing.assert_array_equal(mask.numpy()[0, 0], [[5, 7], [13, 15]])
        x5 = paddle.to_tensor(np.arange(25, dtype=np.float32).reshape(1, 1, 5, 5))
        out_c = F.max_pool2d(x5, 2, 2, ceil_mode=True)
        assert out_c.shape == [1, 1, 3, 3]
        out_f = F.max_pool2d(x5, 2, 2, ceil_mode=False)
        assert out_f.shape == [1, 1, 2, 2]
