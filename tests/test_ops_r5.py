"""Round-5 surface batch: hermitian fft family, paddle.geometric, linalg
tail (ormqr/cholesky_inverse/pca_lowrank), baddbmm/reduce_as, the 2.6-era
inplace batch, random refills, fill_diagonal_tensor, sigmoid_focal_loss,
adaptive_log_softmax_with_loss, deform_conv2d/psroi_pool/matrix_nms —
every name checked against a torch/numpy oracle (reference:
``python/paddle/tensor/``, ``python/paddle/geometric/``,
``python/paddle/vision/ops.py`` †)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.vision import ops as vops

torch = pytest.importorskip("torch")


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestHermitianFFT:
    @pytest.mark.parametrize("norm", ["backward", "forward", "ortho"])
    def test_hfft_family_matches_torch(self, norm):
        rng = np.random.RandomState(0)
        x = (rng.randn(4, 5, 6) + 1j * rng.randn(4, 5, 6)).astype(np.complex64)
        xr = rng.randn(4, 5, 8).astype(np.float32)
        for ours, theirs, arg in [
                (paddle.fft.hfft2, torch.fft.hfft2, x),
                (paddle.fft.ihfft2, torch.fft.ihfft2, xr),
                (paddle.fft.hfftn, torch.fft.hfftn, x),
                (paddle.fft.ihfftn, torch.fft.ihfftn, xr)]:
            np.testing.assert_allclose(
                ours(_t(arg), norm=norm).numpy(),
                theirs(torch.tensor(arg), norm=norm).numpy(),
                rtol=2e-4, atol=1e-4)


class TestGeometric:
    def test_segment_reductions(self):
        data = _t(np.arange(12, dtype=np.float32).reshape(4, 3))
        ids = _t(np.asarray([0, 0, 1, 3], np.int32))
        G = paddle.geometric
        np.testing.assert_allclose(
            G.segment_sum(data, ids).numpy()[0], [3, 5, 7])
        np.testing.assert_allclose(
            G.segment_mean(data, ids).numpy()[0], [1.5, 2.5, 3.5])
        # empty segment 2 -> 0, not +/-inf
        np.testing.assert_allclose(G.segment_max(data, ids).numpy()[2],
                                   [0, 0, 0])
        np.testing.assert_allclose(G.segment_min(data, ids).numpy()[0],
                                   [0, 1, 2])

    def test_send_recv_and_grad(self):
        G = paddle.geometric
        x = _t(np.arange(6, dtype=np.float32).reshape(3, 2))
        src = _t(np.asarray([0, 1, 2, 0], np.int32))
        dst = _t(np.asarray([1, 2, 1, 0], np.int32))
        np.testing.assert_allclose(
            G.send_u_recv(x, src, dst).numpy(),
            [[0, 1], [4, 6], [2, 3]])
        e = _t(np.ones((4, 2), np.float32))
        np.testing.assert_allclose(
            G.send_ue_recv(x, e, src, dst, "add", "max").numpy(),
            [[1, 2], [5, 6], [3, 4]])
        np.testing.assert_allclose(
            G.send_uv(x, x, src, dst, "mul").numpy(),
            [[0, 3], [8, 15], [8, 15], [0, 1]])
        xx = _t(np.arange(6, dtype=np.float32).reshape(3, 2))
        xx.stop_gradient = False
        loss = paddle.sum(G.send_u_recv(xx, src, dst) ** 2)
        loss.backward()
        assert np.abs(xx.grad.numpy()).sum() > 0


class TestLinalgTail:
    def test_cholesky_inverse_matches_torch(self):
        rng = np.random.RandomState(0)
        a = rng.randn(5, 5)
        A = (a @ a.T + 5 * np.eye(5)).astype(np.float32)
        L = np.linalg.cholesky(A).astype(np.float32)
        np.testing.assert_allclose(
            paddle.linalg.cholesky_inverse(_t(L)).numpy(),
            torch.cholesky_inverse(torch.tensor(L)).numpy(),
            rtol=1e-3, atol=1e-4)
        U = np.ascontiguousarray(L.T)
        np.testing.assert_allclose(
            paddle.linalg.cholesky_inverse(_t(U), upper=True).numpy(),
            torch.cholesky_inverse(torch.tensor(U), upper=True).numpy(),
            rtol=1e-3, atol=1e-4)

    @pytest.mark.parametrize("left", [True, False])
    @pytest.mark.parametrize("transpose", [True, False])
    def test_ormqr_matches_torch(self, left, transpose):
        rng = np.random.RandomState(1)
        m, n, k = 6, 4, 5
        qr = torch.geqrf(torch.tensor(rng.randn(m, n).astype(np.float32)))
        xg, tau = qr.a.numpy(), qr.tau.numpy()
        y = rng.randn(*((m, k) if left else (k, m))).astype(np.float32)
        got = paddle.linalg.ormqr(_t(xg), _t(tau), _t(y), left=left,
                                  transpose=transpose).numpy()
        want = torch.ormqr(torch.tensor(xg), torch.tensor(tau),
                           torch.tensor(y), left=left,
                           transpose=transpose).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_pca_lowrank_reconstructs(self):
        rng = np.random.RandomState(2)
        X = rng.randn(20, 8).astype(np.float32)
        u, s, v = paddle.linalg.pca_lowrank(_t(X), q=8)
        Xc = X - X.mean(0, keepdims=True)
        np.testing.assert_allclose(
            (u.numpy() * s.numpy()) @ v.numpy().T, Xc, rtol=1e-3, atol=1e-4)


class TestMathTail:
    def test_baddbmm_matches_torch(self):
        rng = np.random.RandomState(3)
        inp = rng.randn(2, 3, 5).astype(np.float32)
        x = rng.randn(2, 3, 4).astype(np.float32)
        y = rng.randn(2, 4, 5).astype(np.float32)
        np.testing.assert_allclose(
            paddle.baddbmm(_t(inp), _t(x), _t(y), beta=0.5, alpha=2.0).numpy(),
            torch.baddbmm(torch.tensor(inp), torch.tensor(x),
                          torch.tensor(y), beta=0.5, alpha=2.0).numpy(),
            rtol=1e-4, atol=1e-5)

    def test_reduce_as_is_broadcast_adjoint(self):
        rng = np.random.RandomState(4)
        x = rng.randn(3, 4, 5).astype(np.float32)
        tgt = np.zeros((4, 1), np.float32)
        np.testing.assert_allclose(
            paddle.reduce_as(_t(x), _t(tgt)).numpy(),
            x.sum(axis=(0, 2), keepdims=False).reshape(4, 1), rtol=1e-5)


class TestInplaceBatch:
    def test_elementwise_inplace_rebinds_and_keeps_grad(self):
        x = _t(np.asarray([1.0, 2.0], np.float32))
        x.stop_gradient = False
        w = x * 2
        w.lgamma_()
        paddle.sum(w).backward()
        # d lgamma(2x)/dx = 2 digamma(2x)
        from scipy.special import digamma
        np.testing.assert_allclose(x.grad.numpy(),
                                   2 * digamma([2.0, 4.0]), rtol=1e-4)

    def test_trig_and_triangular_inplace(self):
        x = _t(np.asarray([0.5, 0.2], np.float32))
        x.sin_()
        np.testing.assert_allclose(x.numpy(), np.sin([0.5, 0.2]), rtol=1e-6)
        y = _t(np.eye(3, dtype=np.float32))
        y.tril_(-1)
        assert y.numpy().sum() == 0
        z = _t(np.ones((3, 3), np.float32))
        z.triu_()
        assert z.numpy().sum() == 6

    def test_where_inplace_mutates_x(self):
        c = _t(np.asarray([True, False, True]))
        a = _t(np.asarray([1.0, 2.0, 3.0], np.float32))
        b = _t(np.asarray([9.0, 9.0, 9.0], np.float32))
        out = a.where_(c, b)
        assert out is a
        np.testing.assert_allclose(a.numpy(), [1.0, 9.0, 3.0])

    def test_comparison_logical_bitwise_inplace(self):
        # the 2.6 inplace batch: receiver rebinds to the op result
        a = _t(np.asarray([1, 2, 3], np.int32))
        a.bitwise_and_(_t(np.asarray([3, 3, 3], np.int32)))
        np.testing.assert_array_equal(a.numpy(), [1, 2, 3])
        b = _t(np.asarray([1.0, 5.0], np.float32))
        b.greater_than_(_t(np.asarray([2.0, 2.0], np.float32)))
        np.testing.assert_array_equal(b.numpy(), [False, True])
        c = _t(np.asarray([True, False]))
        c.logical_not_()
        np.testing.assert_array_equal(c.numpy(), [False, True])
        d = _t(np.asarray([1.0, 2.0], np.float32))
        d.equal_(_t(np.asarray([1.0, 3.0], np.float32)))
        np.testing.assert_array_equal(d.numpy(), [True, False])

    def test_incubate_segment_alias(self):
        import paddle_tpu.incubate as inc
        out = inc.segment_sum(_t(np.ones((3, 2), np.float32)),
                              _t(np.asarray([0, 0, 1], np.int32)))
        np.testing.assert_allclose(out.numpy(), [[2, 2], [1, 1]])

    def test_fill_zero_refills(self):
        k = _t(np.ones(5, np.float32))
        k.zero_()
        assert k.numpy().sum() == 0
        k.fill_(7.0)
        assert (k.numpy() == 7).all()


class TestRandomTail:
    def test_refill_distributions(self):
        paddle.seed(7)
        f = _t(np.zeros(4000, np.float32))
        f.log_normal_(0.0, 0.25)
        assert f.numpy().min() > 0
        g = _t(np.zeros(4000, np.float32))
        g.geometric_(0.5)
        assert g.numpy().min() >= 1 and abs(g.numpy().mean() - 2.0) < 0.15
        b = _t(np.zeros(4000, np.float32))
        b.bernoulli_(0.3)
        assert abs(b.numpy().mean() - 0.3) < 0.05
        c = _t(np.zeros(4000, np.float32))
        c.cauchy_()
        assert abs(np.median(c.numpy())) < 0.2  # heavy tails, median ~ loc

    def test_sampling_functions(self):
        paddle.seed(8)
        s = paddle.standard_gamma(_t(np.full(4000, 3.0, np.float32)))
        assert abs(s.numpy().mean() - 3.0) < 0.25
        n = paddle.binomial(_t(np.full(4000, 10.0, np.float32)),
                            _t(np.full(4000, 0.4, np.float32)))
        assert abs(n.numpy().mean() - 4.0) < 0.25


class TestFillDiagonalTensor:
    def test_offset_and_inplace(self):
        x = np.zeros((4, 5), np.float32)
        y = np.arange(1, 5, dtype=np.float32)
        got = paddle.fill_diagonal_tensor(_t(x), _t(y), offset=1).numpy()
        want = np.zeros((4, 5), np.float32)
        for i in range(4):
            want[i, i + 1] = y[i]
        np.testing.assert_allclose(got, want)
        z = _t(np.zeros((3, 3), np.float32))
        z.fill_diagonal_tensor_(_t(np.ones(3, np.float32)))
        np.testing.assert_allclose(z.numpy(), np.eye(3))


class TestNewLosses:
    def test_sigmoid_focal_loss(self):
        rng = np.random.RandomState(0)
        logit = rng.randn(6, 4).astype(np.float32)
        label = (rng.rand(6, 4) > 0.7).astype(np.float32)
        p = 1 / (1 + np.exp(-logit))
        ce = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        pt = p * label + (1 - p) * (1 - label)
        at = 0.25 * label + 0.75 * (1 - label)
        want = (at * (1 - pt) ** 2.0 * ce).sum()
        np.testing.assert_allclose(
            float(F.sigmoid_focal_loss(_t(logit), _t(label))), want,
            rtol=1e-4)

    def test_adaptive_log_softmax_matches_torch(self):
        rng = np.random.RandomState(1)
        H, n_classes, cutoffs = 16, 30, [10, 20]
        m = torch.nn.AdaptiveLogSoftmaxWithLoss(H, n_classes, cutoffs,
                                                div_value=2.0)
        x = rng.randn(12, H).astype(np.float32)
        y = rng.randint(0, n_classes, 12).astype(np.int64)
        with torch.no_grad():
            tout = m(torch.tensor(x), torch.tensor(y))
        head_w = m.head.weight.detach().numpy().T
        tails = [[_t(p.weight.detach().numpy().T) for p in seq]
                 for seq in m.tail]
        out, loss = F.adaptive_log_softmax_with_loss(
            _t(x), _t(y.astype(np.int32)), _t(head_w), tails, cutoffs)
        np.testing.assert_allclose(out.numpy(), tout.output.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(loss), float(tout.loss), rtol=1e-4)


class TestDeformConv2d:
    def test_zero_offset_equals_conv(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 4, 8, 8).astype(np.float32)
        w = rng.randn(6, 4, 3, 3).astype(np.float32)
        b = rng.randn(6).astype(np.float32)
        off = np.zeros((2, 18, 6, 6), np.float32)
        got = vops.deform_conv2d(_t(x), _t(off), _t(w), _t(b)).numpy()
        want = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_fractional_offset_and_mask_brute_force(self):
        rng = np.random.RandomState(1)
        B, Cin, H, W, Cout, k = 1, 2, 6, 6, 3, 3
        Ho = Wo = H - k + 1
        x = rng.randn(B, Cin, H, W).astype(np.float32)
        w = rng.randn(Cout, Cin, k, k).astype(np.float32)
        off = ((rng.rand(B, 2 * k * k, Ho, Wo) - 0.5) * 2).astype(np.float32)
        msk = rng.rand(B, k * k, Ho, Wo).astype(np.float32)
        got = vops.deform_conv2d(_t(x), _t(off), _t(w), mask=_t(msk)).numpy()

        def bil(img, py, px):
            y0, x0 = int(np.floor(py)), int(np.floor(px))
            v = 0.0
            for yy, wy in ((y0, 1 - (py - y0)), (y0 + 1, py - y0)):
                for xx, wx in ((x0, 1 - (px - x0)), (x0 + 1, px - x0)):
                    if 0 <= yy < H and 0 <= xx < W:
                        v += img[yy, xx] * wy * wx
            return v

        want = np.zeros_like(got)
        for co in range(Cout):
            for ho in range(Ho):
                for wo in range(Wo):
                    acc = 0.0
                    for ci in range(Cin):
                        for i in range(k):
                            for j in range(k):
                                tap = i * k + j
                                py = ho + i + off[0, 2 * tap, ho, wo]
                                px = wo + j + off[0, 2 * tap + 1, ho, wo]
                                acc += (w[co, ci, i, j] * msk[0, tap, ho, wo]
                                        * bil(x[0, ci], py, px))
                    want[0, co, ho, wo] = acc
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


class TestPSRoIPoolAndMatrixNMS:
    def test_psroi_pool_brute_force(self):
        rng = np.random.RandomState(0)
        xp = rng.randn(1, 8, 8, 8).astype(np.float32)  # 2 out-ch x 2x2 bins
        boxes = np.asarray([[0, 0, 5, 5], [2, 2, 7, 7]], np.float32)
        got = vops.psroi_pool(_t(xp), _t(boxes),
                              _t(np.asarray([2], np.int32)), 2).numpy()
        for r, box in enumerate(boxes):
            x1, y1 = round(box[0]), round(box[1])
            x2, y2 = round(box[2] + 1), round(box[3] + 1)
            bh, bw = max(y2 - y1, 0.1) / 2, max(x2 - x1, 0.1) / 2
            for co in range(2):
                for i in range(2):
                    for j in range(2):
                        hs = int(np.clip(np.floor(y1 + i * bh), 0, 8))
                        he = int(np.clip(np.ceil(y1 + (i + 1) * bh), 0, 8))
                        ws = int(np.clip(np.floor(x1 + j * bw), 0, 8))
                        we = int(np.clip(np.ceil(x1 + (j + 1) * bw), 0, 8))
                        reg = xp[0, (co * 2 + i) * 2 + j, hs:he, ws:we]
                        np.testing.assert_allclose(
                            got[r, co, i, j],
                            reg.mean() if reg.size else 0.0,
                            rtol=1e-4, atol=1e-5)

    def test_matrix_nms_decay_formula(self):
        bx = np.asarray([[[0, 0, 10, 10], [0, 0, 10.5, 10],
                          [20, 20, 30, 30]]], np.float32)
        sc = np.asarray([[[0.9, 0.8, 0.7]]], np.float32)
        out, num = vops.matrix_nms(_t(bx), _t(sc), score_threshold=0.05,
                                   post_threshold=0.0, nms_top_k=3,
                                   keep_top_k=3, background_label=-1)
        out = out.numpy()[0]
        assert int(num.numpy()[0]) == 3
        # rows sorted by decayed score: 0.9 (lead), 0.7 (distinct box),
        # near-dup decayed by exactly (1 - iou)
        iou = vops.box_iou(_t(bx[0, :2]), _t(bx[0, :2])).numpy()[0, 1]
        np.testing.assert_allclose(out[:, 1],
                                   [0.9, 0.7, 0.8 * (1 - iou)], rtol=1e-5)
        # gaussian decay: exp(-sigma*iou^2)/exp(-sigma*comp^2), sigma
        # MULTIPLYING the exponent (SOLOv2 kernel)
        out2, idx, num2 = vops.matrix_nms(
            _t(bx), _t(sc), 0.05, 0.0, 3, 3, use_gaussian=True,
            gaussian_sigma=2.0, background_label=-1, return_index=True)
        assert int(num2.numpy()[0]) == 3
        assert (idx.numpy()[0] >= 0).all()
        np.testing.assert_allclose(
            sorted(out2.numpy()[0][:, 1])[0],
            0.8 * np.exp(-2.0 * iou ** 2), rtol=1e-5)
        # defaults must not fault on small inputs (keep_top_k=200 > C*k)
        # and keep_top_k=-1 means keep-everything; background class 0 is
        # skipped by default (reference background_label=0)
        sc2 = np.concatenate([np.full((1, 1, 3), 0.99, np.float32), sc],
                             axis=1)  # class 0 = background
        out3, num3 = vops.matrix_nms(_t(bx), _t(sc2), 0.05)
        assert not (out3.numpy()[0][:, 0] == 0).any()   # bg never emitted
        out4, num4 = vops.matrix_nms(_t(bx), _t(sc2), 0.05, keep_top_k=-1)
        assert int(num4.numpy()[0]) == int(num3.numpy()[0])
        # normalized=False uses +1 pixel spans in the IoU
        out5, _ = vops.matrix_nms(_t(bx), _t(sc), 0.05, nms_top_k=3,
                                  keep_top_k=3, background_label=-1,
                                  normalized=False)
        a0 = (10 + 1) * (10 + 1)
        a1 = (10.5 + 1) * (10 + 1)
        inter = (10 + 1) * (10 + 1)
        iou_px = inter / (a0 + a1 - inter)
        np.testing.assert_allclose(
            sorted(out5.numpy()[0][:, 1])[0], 0.8 * (1 - iou_px), rtol=1e-5)


class TestSparseAttention:
    def test_csr_band_matches_dense_oracle(self):
        """CSR-pattern attention == dense attention under the equivalent
        additive mask (band pattern, ragged per-row counts)."""
        rng = np.random.RandomState(0)
        B, H, S, D = 2, 2, 6, 4
        q = rng.randn(B, H, S, D).astype(np.float32)
        k = rng.randn(B, H, S, D).astype(np.float32)
        v = rng.randn(B, H, S, D).astype(np.float32)
        offs = np.zeros((B, H, S + 1), np.int32)
        cols_l = []
        for i in range(S):
            cols_l.extend(range(max(0, i - 2), i + 1))
            offs[:, :, i + 1] = len(cols_l)
        cols = np.tile(np.asarray(cols_l, np.int32), (B, H, 1))
        got = F.sparse_attention(_t(q), _t(k), _t(v), _t(offs),
                                 _t(cols)).numpy()
        for b in range(B):
            for h in range(H):
                m = np.full((S, S), -1e30)
                for i in range(S):
                    m[i, max(0, i - 2):i + 1] = 0.0
                lg = q[b, h] @ k[b, h].T / 2.0 + m
                p = np.exp(lg - lg.max(-1, keepdims=True))
                p /= p.sum(-1, keepdims=True)
                np.testing.assert_allclose(got[b, h], p @ v[b, h],
                                           rtol=1e-4, atol=1e-5)
        # padded nnz slots (beyond off[-1]) must not leak attention
        cols_pad = np.concatenate(
            [cols, np.zeros((B, H, 3), np.int32)], axis=-1)
        got_pad = F.sparse_attention(_t(q), _t(k), _t(v), _t(offs),
                                     _t(cols_pad)).numpy()
        np.testing.assert_allclose(got_pad, got, rtol=1e-6)
        # reference mask contract: 0 == masked (not an additive bias) —
        # padding out the last 2 keys must equal truncating the pattern
        kpm = np.ones((B, S), np.float32)
        kpm[:, S - 2:] = 0.0
        got_kpm = F.sparse_attention(_t(q), _t(k), _t(v), _t(offs),
                                     _t(cols),
                                     key_padding_mask=_t(kpm)).numpy()
        for b in range(B):
            for h in range(H):
                m = np.full((S, S), -1e30)
                for i in range(S):
                    m[i, max(0, i - 2):i + 1] = 0.0
                m[:, S - 2:] = -1e30
                lg = q[b, h] @ k[b, h].T / 2.0 + m
                p = np.exp(lg - lg.max(-1, keepdims=True))
                p /= np.maximum(p.sum(-1, keepdims=True), 1e-30)
                np.testing.assert_allclose(got_kpm[b, h], p @ v[b, h],
                                           rtol=1e-4, atol=1e-5)


class TestClassCenterSample:
    def test_contains_positives_and_remaps(self):
        paddle.seed(3)
        lab = _t(np.asarray([5, 2, 5, 9], np.int32))
        rl, sc = F.class_center_sample(lab, num_classes=20, num_samples=6)
        rl, sc = rl.numpy(), sc.numpy()
        assert len(sc) == 6 and set([2, 5, 9]) <= set(sc.tolist())
        assert (sc[rl] == [5, 2, 5, 9]).all()
        assert (np.diff(sc) > 0).all()  # reference order: sorted ascending
        with pytest.raises(ValueError):
            F.class_center_sample(lab, num_classes=20, num_samples=2)
        with pytest.raises(ValueError):  # oversampling num_classes
            F.class_center_sample(_t(np.asarray([0, 1], np.int32)),
                                  num_classes=4, num_samples=6)
        with pytest.raises(ValueError):  # out-of-range label
            F.class_center_sample(_t(np.asarray([-1, 2], np.int32)),
                                  num_classes=10, num_samples=4)
        with pytest.raises(NotImplementedError):
            F.class_center_sample(lab, 20, 6, group=object())

    def test_cum_inplace(self):
        x = _t(np.asarray([1.0, 2.0, 3.0], np.float32))
        x.cumsum_()
        np.testing.assert_allclose(x.numpy(), [1, 3, 6])
        y = _t(np.asarray([1.0, 2.0, 3.0], np.float32))
        y.cumprod_(dim=0)
        np.testing.assert_allclose(y.numpy(), [1, 2, 6])


class TestPoolingTail:
    def test_max_pool1d_mask_and_unpool_match_torch(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8).astype(np.float32)
        p, m = F.max_pool1d(_t(x), 2, stride=2, return_mask=True)
        tw = torch.nn.functional.max_pool1d(torch.tensor(x), 2, 2,
                                            return_indices=True)
        np.testing.assert_allclose(p.numpy(), tw[0].numpy())
        np.testing.assert_allclose(m.numpy(), tw[1].numpy())
        u = F.max_unpool1d(p, m, 2, stride=2)
        np.testing.assert_allclose(
            u.numpy(),
            torch.nn.functional.max_unpool1d(*tw, 2, 2).numpy())

    def test_max_pool3d_mask_and_unpool_match_torch(self):
        rng = np.random.RandomState(1)
        x = rng.randn(2, 2, 4, 4, 4).astype(np.float32)
        p, m = F.max_pool3d(_t(x), 2, stride=2, return_mask=True)
        tw = torch.nn.functional.max_pool3d(torch.tensor(x), 2, 2,
                                            return_indices=True)
        np.testing.assert_allclose(p.numpy(), tw[0].numpy())
        np.testing.assert_allclose(m.numpy(), tw[1].numpy())
        u = F.max_unpool3d(p, m, 2, stride=2)
        np.testing.assert_allclose(
            u.numpy(),
            torch.nn.functional.max_unpool3d(*tw, 2, 2).numpy())

    def test_fractional_max_pool_degenerate_and_mask(self):
        # integer alpha + u=0.5: regions collapse to kernel2/stride2
        rng = np.random.RandomState(2)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        got = F.fractional_max_pool2d(_t(x), 4, random_u=0.5).numpy()
        np.testing.assert_allclose(
            got, torch.nn.functional.max_pool2d(torch.tensor(x), 2, 2))
        out, mask = F.fractional_max_pool2d(_t(x), 4, random_u=0.5,
                                            return_mask=True)
        g = x.reshape(2, 3, -1)
        np.testing.assert_allclose(
            np.take_along_axis(g, mask.numpy().reshape(2, 3, -1),
                               -1).reshape(out.shape), out.numpy())
        x3 = rng.randn(2, 2, 4, 4, 4).astype(np.float32)
        np.testing.assert_allclose(
            F.fractional_max_pool3d(_t(x3), 2, random_u=0.5).numpy(),
            torch.nn.functional.max_pool3d(torch.tensor(x3), 2, 2))
        # ragged output size + grads
        xx = _t(x)
        xx.stop_gradient = False
        out = F.fractional_max_pool2d(xx, 3, random_u=0.3)
        assert out.shape == [2, 3, 3, 3]
        paddle.sum(out * out).backward()
        assert np.abs(xx.grad.numpy()).sum() > 0
        # the return_mask variant backprops through the VALUES too
        # (r5 review: differentiable=False silently severed training)
        xm = _t(x)
        xm.stop_gradient = False
        vals, _mask = F.fractional_max_pool2d(xm, 4, random_u=0.5,
                                              return_mask=True)
        paddle.sum(vals).backward()
        assert np.abs(xm.grad.numpy()).sum() > 0

    def test_layer_wrappers(self):
        from paddle_tpu import nn
        rng = np.random.RandomState(3)
        x = rng.randn(1, 2, 8, 8).astype(np.float32)
        out = nn.FractionalMaxPool2D(4, random_u=0.5)(_t(x))
        assert out.shape == [1, 2, 4, 4]
        x1 = rng.randn(1, 2, 8).astype(np.float32)
        p, m = F.max_pool1d(_t(x1), 2, stride=2, return_mask=True)
        assert nn.MaxUnPool1D(2, stride=2)(p, m).shape == [1, 2, 8]
        x3 = rng.randn(1, 2, 4, 4, 4).astype(np.float32)
        p3, m3 = F.max_pool3d(_t(x3), 2, stride=2, return_mask=True)
        assert nn.MaxUnPool3D(2, stride=2)(p3, m3).shape == [1, 2, 4, 4, 4]


class TestNNUtilsReparam:
    def test_weight_norm_parity_grads_and_removal(self):
        from paddle_tpu import nn
        from paddle_tpu.nn.utils import remove_weight_norm, weight_norm
        rng = np.random.RandomState(0)
        lin = nn.Linear(4, 3)
        W = rng.randn(4, 3).astype(np.float32)
        lin.weight.set_value(W)
        lin.bias.set_value(np.zeros(3, np.float32))
        weight_norm(lin, dim=1)  # per-output column (torch Linear dim=0)
        x = rng.randn(2, 4).astype(np.float32)
        got = lin(_t(x)).numpy()
        tl = torch.nn.Linear(4, 3, bias=False)
        with torch.no_grad():
            tl.weight.copy_(torch.tensor(W.T))
        tl = torch.nn.utils.weight_norm(tl, dim=0)
        np.testing.assert_allclose(got, tl(torch.tensor(x)).detach().numpy(),
                                   rtol=1e-5, atol=1e-6)
        assert lin.weight_g.shape == [3]  # reference 1-D g (state_dict)
        loss = paddle.sum(lin(_t(x)) ** 2)
        loss.backward()
        assert np.abs(lin.weight_g.grad.numpy()).sum() > 0
        assert lin.weight_v.grad is not None
        eff = np.asarray(lin.weight.value).copy()
        remove_weight_norm(lin)
        assert "weight" in lin._parameters
        assert "weight_g" not in lin._parameters
        np.testing.assert_allclose(np.asarray(lin.weight.value), eff,
                                   rtol=1e-6)
        np.testing.assert_allclose(lin(_t(x)).numpy(), got, rtol=1e-5,
                                   atol=1e-6)

    def test_weight_norm_negative_dim(self):
        """r5 review: dim=-1 must exclude the LAST axis from the norm,
        not silently compute a global norm."""
        from paddle_tpu import nn
        from paddle_tpu.nn.utils import weight_norm
        lin = nn.Linear(4, 3)
        W = np.random.RandomState(5).randn(4, 3).astype(np.float32)
        lin.weight.set_value(W)
        weight_norm(lin, dim=-1)
        assert lin.weight_g.shape == [3]
        np.testing.assert_allclose(lin.weight_g.numpy(),
                                   np.linalg.norm(W, axis=0), rtol=1e-5)

    def test_spectral_norm_unit_sigma(self):
        from paddle_tpu import nn
        from paddle_tpu.nn.utils import spectral_norm
        rng = np.random.RandomState(1)
        lin = nn.Linear(6, 5)
        lin.weight.set_value((rng.randn(6, 5) * 3).astype(np.float32))
        spectral_norm(lin, n_power_iterations=20)
        _ = lin(_t(rng.randn(2, 6).astype(np.float32)))
        s = np.linalg.svd(np.asarray(lin.weight.value), compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, rtol=1e-3)

    def test_spectral_norm_grad_matches_torch(self):
        """The d(sigma)/dW term must flow: grads of sum(W_sn @ x) match
        torch's spectral_norm (same u seed via enough power iterations
        to converge both to the dominant singular vectors)."""
        rng = np.random.RandomState(2)
        W = (rng.randn(4, 3) * 2).astype(np.float32)  # paddle [in, out]
        x = rng.randn(5, 4).astype(np.float32)
        from paddle_tpu import nn
        from paddle_tpu.nn.utils import spectral_norm
        lin = nn.Linear(4, 3)
        lin.weight.set_value(W)
        lin.bias.set_value(np.zeros(3, np.float32))
        spectral_norm(lin, n_power_iterations=50, dim=1)
        loss = paddle.sum(lin(_t(x)))
        loss.backward()
        got = lin.weight_orig.grad.numpy()

        tl = torch.nn.Linear(4, 3, bias=False)
        with torch.no_grad():
            tl.weight.copy_(torch.tensor(W.T))
        tl = torch.nn.utils.spectral_norm(tl, n_power_iterations=50)
        tloss = tl(torch.tensor(x)).sum()
        tloss.backward()
        want = tl.weight_orig.grad.numpy().T
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-5)

    def test_spectral_norm_works_under_trainstep(self):
        """r5 review: the power iteration must be trace-safe (numpy on a
        tracer would crash TrainStep)."""
        from paddle_tpu import nn
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.nn.utils import spectral_norm
        from paddle_tpu.optimizer import SGD
        rng = np.random.RandomState(3)
        lin = nn.Linear(4, 3)
        spectral_norm(lin)
        opt = SGD(learning_rate=0.1, parameters=list(lin.parameters()))
        step = TrainStep(lin, lambda out, _l: paddle.sum(out * out), opt)
        x = _t(rng.randn(2, 4).astype(np.float32))
        l0 = float(step.step((x,), (x,)).value)
        l1 = float(step.step((x,), (x,)).value)
        assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0

    def test_clip_grad_value(self):
        from paddle_tpu import nn
        from paddle_tpu.nn.utils import clip_grad_value_
        rng = np.random.RandomState(2)
        lin = nn.Linear(4, 3)
        loss = paddle.sum(lin(_t(rng.randn(8, 4).astype(np.float32) * 50)))
        loss.backward()
        clip_grad_value_(list(lin.parameters()), 0.05)
        for p in lin.parameters():
            assert np.abs(p.grad.numpy()).max() <= 0.05 + 1e-8


class TestRegistryHonesty:
    def test_invented_names_gone(self):
        for bad in ("sinc_pi", "cosine_similarity_flat", "moveaxis_single",
                    "rot90_k", "flip_lr", "flip_ud", "take_diag",
                    "trace_offset", "count_unique"):
            assert not hasattr(paddle, bad), bad

    def test_registry_crosses_500(self):
        from paddle_tpu.ops._op import OP_REGISTRY
        assert len(OP_REGISTRY) >= 500, len(OP_REGISTRY)


class TestNormNuclear:
    """p='nuc' (sum of singular values) — crashed pre-r5-session-3 (the
    numeric-p power path received the string)."""

    def test_nuc_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.RandomState(11).randn(4, 6).astype(np.float32)
        got = float(paddle.linalg.norm(paddle.to_tensor(x), "nuc").numpy())
        exp = float(torch.linalg.norm(torch.tensor(x), "nuc"))
        assert abs(got - exp) < 1e-3
        got2 = float(paddle.linalg.norm(paddle.to_tensor(x), "nuc",
                                        axis=[0, 1]).numpy())
        assert abs(got2 - exp) < 1e-3

    def test_nuc_rejects_vector_axis(self):
        x = np.zeros((3, 4), np.float32)
        with pytest.raises(ValueError, match="matrix norm"):
            paddle.linalg.norm(paddle.to_tensor(x), "nuc", axis=0)


class TestInplaceR5Session3:
    """gcd_/lcm_ (2.6 inplace batch) + F.relu_ with autograd through the
    rebind."""

    def test_gcd_lcm_inplace(self):
        t = paddle.to_tensor(np.int32([12, 18]))
        assert t.gcd_(paddle.to_tensor(np.int32([8, 27]))) is t
        np.testing.assert_array_equal(t.numpy(), [4, 9])
        t2 = paddle.to_tensor(np.int32([4, 6]))
        t2.lcm_(paddle.to_tensor(np.int32([6, 4])))
        np.testing.assert_array_equal(t2.numpy(), [12, 12])

    def test_relu_inplace_grad(self):
        p = paddle.to_tensor(np.float32([-1.0, 3.0]))
        p.stop_gradient = False
        y = p * 2.0
        out = F.relu_(y)
        assert out is y
        y.sum().backward()
        np.testing.assert_array_equal(p.grad.numpy(), [0.0, 2.0])
