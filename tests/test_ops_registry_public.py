"""Registry honesty: the public op surface is enumerable under the names
users call (reference: OpInfoMap enumerates public op names; python/paddle/
tensor/manipulation.py † exposes tile/chunk/unbind/... as the public API).

Round-5 follow-up to VERDICT r4 item 5: thin normalization wrappers over
privately-registered kernels (tile → _tile) and composites (chunk → split)
are registered under their public names, and the one remaining invented
placeholder (`as_strided_like_view`) is gone.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops._op import OP_REGISTRY


class TestPublicRegistry:
    def test_public_wrappers_registered(self):
        for name in ("reshape", "split", "chunk", "unstack", "unbind",
                     "tile", "broadcast_to", "expand", "expand_as",
                     "broadcast_tensors", "scatter_nd", "pad", "cast",
                     "astype", "numel", "shape", "floor_mod", "view",
                     "bucketize", "lu_unpack", "broadcast_shape",
                     "tensor_split", "hsplit", "vsplit", "dsplit",
                     "tolist", "rank", "is_tensor", "is_complex",
                     "is_floating_point", "is_integer", "is_empty",
                     "tril_indices", "triu_indices", "poisson",
                     "randint_like", "set_printoptions"):
            assert name in OP_REGISTRY, name

    def test_no_invented_placeholder(self):
        assert "as_strided_like_view" not in OP_REGISTRY

    def test_registry_size_floor(self):
        # 577 measured pre-registration-sweep; the sweep adds the public
        # wrapper names. Floor, not exact, so adding ops never breaks this.
        assert len(OP_REGISTRY) >= 613

    def test_registered_view_is_shape_or_dtype(self):
        # paddle.view reinterprets shape OR dtype — it must be the tail.py
        # op, not the plain reshape alias
        x = paddle.to_tensor(np.arange(6, dtype=np.float32))
        assert tuple(paddle.view(x, [2, 3]).shape) == (2, 3)
        assert paddle.view(x, "int32").dtype == paddle.int32


class TestSetPrintoptions:
    @pytest.fixture(autouse=True)
    def _restore(self):
        from paddle_tpu.core.tensor import _print_options
        saved = dict(_print_options)
        yield
        _print_options.update(saved)

    def test_precision(self):
        t = paddle.to_tensor([0.123456789])
        paddle.set_printoptions(precision=2)
        assert "0.12]" in repr(t)
        paddle.set_printoptions(precision=8)
        assert "0.12345679" in repr(t)

    def test_threshold_summarizes(self):
        t = paddle.to_tensor(np.arange(2000, dtype=np.float32))
        paddle.set_printoptions(threshold=10, edgeitems=2)
        assert "..." in repr(t)

    def test_sci_mode_forces_and_forbids(self):
        # True must FORCE scientific even for values numpy would auto-print
        # plain; False must forbid it even for tiny values
        t = paddle.to_tensor([1.5])
        paddle.set_printoptions(sci_mode=True, precision=4)
        assert "e+00" in repr(t), repr(t)
        tiny = paddle.to_tensor([1e-9])
        paddle.set_printoptions(sci_mode=False, precision=8)
        assert "e-" not in repr(tiny)

    def test_numpy_globals_untouched(self):
        # reference scopes printer options to tensors; user numpy printing
        # must be unaffected
        before = np.get_printoptions()
        paddle.set_printoptions(precision=1, threshold=5, edgeitems=1,
                                sci_mode=True, linewidth=40)
        assert np.get_printoptions() == before
        arr = np.array([0.123456789])
        assert "0.12345679" in repr(arr)


class TestMethodSpellings:
    """Registry ops bound as Tensor methods (reference tensor_method_func
    patch list †) — r5 session-3 batch."""

    def test_bound_and_working(self):
        t = paddle.to_tensor(np.float32([3.7, -1.2, 0.5]))
        np.testing.assert_allclose(t.frac().numpy(), [0.7, -0.2, 0.5],
                                   atol=1e-6)
        v, i = paddle.to_tensor(np.float32([[1, 5, 2]])).cummax(axis=1)
        np.testing.assert_array_equal(v.numpy(), [[1, 5, 5]])
        u = paddle.to_tensor(np.arange(10, dtype=np.float32)).unfold(0, 4, 2)
        assert tuple(u.shape) == (4, 4)
        q = paddle.to_tensor(np.float32([1, 2, 3, 4])).quantile(0.5)
        assert float(q.numpy()) == 2.5
        for name in ("bucketize", "renorm", "logcumsumexp", "cummin",
                     "copysign", "hypot", "ldexp", "frexp", "nextafter",
                     "heaviside", "nanmean", "nansum", "nanquantile",
                     "cross", "histogram", "bincount", "vander",
                     "corrcoef", "cov", "trapezoid"):
            assert callable(getattr(paddle.Tensor, name)), name
