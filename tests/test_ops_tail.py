"""Round-4 op batch: inplace (*_) variants + long-tail ops vs numpy/torch
oracles (reference surface: ``python/paddle/tensor/`` † inplace APIs and
the math/manipulation/stat long tail)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestInplaceVariants:
    def test_arithmetic_inplace_rebinds_and_returns_self(self):
        x = _t([1.0, 2.0, 3.0])
        r = x.add_(_t([1.0, 1.0, 1.0]))
        assert r is x
        np.testing.assert_allclose(x.numpy(), [2, 3, 4])
        x.subtract_(_t([1.0, 0.0, 0.0]))
        np.testing.assert_allclose(x.numpy(), [1, 3, 4])
        x.multiply_(_t([2.0, 2.0, 2.0]))
        np.testing.assert_allclose(x.numpy(), [2, 6, 8])
        x.divide_(_t([2.0, 2.0, 2.0]))
        np.testing.assert_allclose(x.numpy(), [1, 3, 4])
        x.scale_(10.0)
        np.testing.assert_allclose(x.numpy(), [10, 30, 40])
        x.clip_(min=15.0, max=35.0)
        np.testing.assert_allclose(x.numpy(), [15, 30, 35])

    def test_unary_inplace(self):
        x = _t([4.0, 9.0])
        x.sqrt_()
        np.testing.assert_allclose(x.numpy(), [2, 3])
        x.exp_()
        np.testing.assert_allclose(x.numpy(), np.exp([2.0, 3.0]), rtol=1e-6)
        y = _t([-1.7, 2.3])
        y.trunc_()
        np.testing.assert_allclose(y.numpy(), [-1.0, 2.0])
        z = _t([-1.5, 0.5])
        z.abs_()
        np.testing.assert_allclose(z.numpy(), [1.5, 0.5])

    def test_module_level_inplace_functions(self):
        x = _t([1.0, 2.0])
        r = paddle.add_(x, _t([5.0, 5.0]))
        assert r is x
        np.testing.assert_allclose(x.numpy(), [6, 7])
        with pytest.raises(TypeError, match="mutates a Tensor"):
            paddle.add_(np.ones(2), _t([1.0, 1.0]))

    def test_shape_inplace(self):
        x = _t(np.arange(6, dtype=np.float32))
        x.reshape_([2, 3])
        assert x.shape == [2, 3]
        x.transpose_([1, 0])
        assert x.shape == [3, 2]
        x.flatten_()
        assert x.shape == [6]
        x.unsqueeze_(0)
        assert x.shape == [1, 6]
        x.squeeze_(0)
        assert x.shape == [6]

    def test_indexed_write_inplace(self):
        x = _t(np.zeros((4, 2), np.float32))
        x.scatter_(_t(np.asarray([1, 3])),
                   _t(np.ones((2, 2), np.float32)))
        np.testing.assert_allclose(x.numpy()[[1, 3]], 1.0)
        np.testing.assert_allclose(x.numpy()[[0, 2]], 0.0)
        m = _t(np.asarray([[True, False], [False, True]]))
        y = _t(np.zeros((2, 2), np.float32))
        y.masked_fill_(m, 7.0)
        np.testing.assert_allclose(y.numpy(), [[7, 0], [0, 7]])

    def test_inplace_keeps_gradient_flow(self):
        x = _t(np.asarray([1.0, 2.0], np.float32))
        x.stop_gradient = False
        y = (x * 2)
        y.add_(_t([1.0, 1.0]))  # inplace on an autograd intermediate
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    def test_setitem_keeps_gradient_flow(self):
        """Same aliasing rule for __setitem__: writing a slice of an
        autograd intermediate must not sever the path to its producers."""
        x = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], np.float32),
                             stop_gradient=False)
        y = x * 2
        y[0] = 10.0
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [0.0, 2.0, 2.0])

    def test_random_refills(self):
        paddle.seed(7)
        x = _t(np.zeros((64,), np.float32))
        x.uniform_(min=2.0, max=3.0)
        a = x.numpy()
        assert (a >= 2.0).all() and (a <= 3.0).all() and a.std() > 0
        x.normal_(mean=5.0, std=0.1)
        assert abs(x.numpy().mean() - 5.0) < 0.2
        x.exponential_(lam=2.0)
        assert (x.numpy() > 0).all()

    def test_fill_diagonal(self):
        x = _t(np.zeros((3, 4), np.float32))
        x.fill_diagonal_(9.0)
        a = x.numpy()
        assert a[0, 0] == a[1, 1] == a[2, 2] == 9.0
        assert a.sum() == 27.0
        # offset + wrap + 3-D semantics
        y = _t(np.zeros((4, 2), np.float32))
        y.fill_diagonal_(1.0, wrap=True)
        np.testing.assert_allclose(y.numpy().sum(), 3.0)  # numpy wrap
        z = _t(np.zeros((2, 2, 2), np.float32))
        z.fill_diagonal_(1.0)
        assert z.numpy()[0, 0, 0] == 1 and z.numpy()[1, 1, 1] == 1
        assert z.numpy().sum() == 2.0

    def test_fill_diagonal_keeps_gradient_flow(self):
        """ADVICE-class regression: fill_diagonal_ must not sever autograd
        through the untouched entries (paddle has a grad kernel for it)."""
        x = paddle.to_tensor(np.ones((2, 2), np.float32),
                             stop_gradient=False)
        y = x * 3
        y.fill_diagonal_(0.0)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[0, 3], [3, 0]])


class TestTailOps:
    def test_stacking_family(self):
        a, b = np.ones((2, 2)), np.zeros((2, 2))
        np.testing.assert_allclose(
            paddle.hstack([_t(a), _t(b)]).numpy(), np.hstack([a, b]))
        np.testing.assert_allclose(
            paddle.vstack([_t(a), _t(b)]).numpy(), np.vstack([a, b]))
        np.testing.assert_allclose(
            paddle.dstack([_t(a), _t(b)]).numpy(), np.dstack([a, b]))
        np.testing.assert_allclose(
            paddle.column_stack([_t(np.ones(3)), _t(np.zeros(3))]).numpy(),
            np.column_stack([np.ones(3), np.zeros(3)]))

    def test_atleast_and_block_diag(self):
        assert paddle.atleast_2d(_t(np.float32(3.0))).shape == [1, 1]
        assert paddle.atleast_3d(_t(np.ones((2, 2), np.float32))).shape \
            == [2, 2, 1]
        import scipy.linalg as sl
        a, b = np.ones((2, 2)), 2 * np.ones((3, 3))
        np.testing.assert_allclose(
            paddle.block_diag([_t(a), _t(b)]).numpy(), sl.block_diag(a, b))

    def test_diagonal_scatter_and_diagflat(self):
        x = np.zeros((3, 4), np.float32)
        y = np.asarray([1.0, 2.0, 3.0], np.float32)
        got = paddle.diagonal_scatter(_t(x), _t(y)).numpy()
        want = x.copy()
        np.fill_diagonal(want, y)
        np.testing.assert_allclose(got, want)
        got_off = paddle.diagonal_scatter(
            _t(x), _t(y[:2] * 0 + 5), offset=2).numpy()
        assert got_off[0, 2] == 5 and got_off[1, 3] == 5
        np.testing.assert_allclose(
            paddle.diagflat(_t(y), offset=1).numpy(), np.diagflat(y, 1))

    def test_unfold_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.arange(10, dtype=np.float32)
        got = paddle.unfold(_t(x), 0, 4, 3).numpy()
        want = torch.tensor(x).unfold(0, 4, 3).numpy()
        np.testing.assert_allclose(got, want)
        x2 = np.arange(24, dtype=np.float32).reshape(4, 6)
        got2 = paddle.unfold(_t(x2), 1, 3, 2).numpy()
        want2 = torch.tensor(x2).unfold(1, 3, 2).numpy()
        np.testing.assert_allclose(got2, want2)

    def test_cummax_cummin_match_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.RandomState(0).randn(4, 7).astype(np.float32)
        gv, gi = paddle.cummax(_t(x), axis=1)
        wv, wi = torch.cummax(torch.tensor(x), dim=1)
        np.testing.assert_allclose(gv.numpy(), wv.numpy())
        np.testing.assert_allclose(gi.numpy(), wi.numpy())
        gv, gi = paddle.cummin(_t(x), axis=0)
        wv, wi = torch.cummin(torch.tensor(x), dim=0)
        np.testing.assert_allclose(gv.numpy(), wv.numpy())
        np.testing.assert_allclose(gi.numpy(), wi.numpy())

    def test_scalar_math_tail(self):
        import scipy.special as sp
        x = np.asarray([0.5, 1.5, 2.5], np.float32)
        np.testing.assert_allclose(paddle.gammaln(_t(x)).numpy(),
                                   sp.gammaln(x), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.gammainc(_t(x), _t(x + 1)).numpy(),
            sp.gammainc(x, x + 1), rtol=1e-5)
        np.testing.assert_allclose(paddle.erfc(_t(x)).numpy(),
                                   sp.erfc(x), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.xlogy(_t(x), _t(x)).numpy(), sp.xlogy(x, x), rtol=1e-6)
        np.testing.assert_allclose(
            paddle.logaddexp2(_t(x), _t(x)).numpy(),
            np.logaddexp2(x, x), rtol=1e-6)
        np.testing.assert_allclose(paddle.negative(_t(x)).numpy(), -x)
        np.testing.assert_allclose(paddle.positive(_t(x)).numpy(), x)

    def test_shifts_and_isreal_isin(self):
        a = np.asarray([1, 2, 4], np.int32)
        np.testing.assert_array_equal(
            paddle.bitwise_left_shift(_t(a), _t(np.int32(2))).numpy(),
            a << 2)
        np.testing.assert_array_equal(
            paddle.bitwise_right_shift(_t(a), _t(np.int32(1))).numpy(),
            a >> 1)
        assert paddle.isreal(_t(np.ones(3, np.float32))).numpy().all()
        # logical shift zero-fills for EVERY signed width (advisor r4: only
        # int32 was reinterpreted; int8/int16/int64 sign-extended)
        for dt in (np.int8, np.int16, np.int32):  # int64->int32 (no x64)
            neg = np.asarray([-8, -1, 5], dt)
            got = paddle.bitwise_right_shift(
                _t(neg), _t(dt(1)), is_arithmetic=False).numpy()
            bits = neg.dtype.itemsize * 8
            udt = np.dtype(f"uint{bits}")
            want = (neg.view(udt) >> udt.type(1)).view(neg.dtype)
            np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(
            paddle.isin(_t(a), _t(np.asarray([2, 4], np.int32))).numpy(),
            np.isin(a, [2, 4]))

    def test_cumulative_trapezoid_matches_scipy(self):
        from scipy.integrate import cumulative_trapezoid as ct
        y = np.random.RandomState(1).rand(5, 8).astype(np.float32)
        np.testing.assert_allclose(
            paddle.cumulative_trapezoid(_t(y), dx=0.5).numpy(),
            ct(y, dx=0.5, axis=-1), rtol=1e-5)

    def test_misc_base_ops(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        np.testing.assert_allclose(paddle.increment(_t(x)).numpy(), x + 1)
        big = np.asarray([3.0, 4.0], np.float32)
        clipped = paddle.clip_by_norm(_t(big), 1.0).numpy()
        np.testing.assert_allclose(np.linalg.norm(clipped), 1.0, rtol=1e-6)
        np.testing.assert_allclose(
            paddle.crop(_t(x), shape=[2, 2], offsets=[1, 1]).numpy(),
            x[1:3, 1:3])
        np.testing.assert_allclose(
            paddle.vecdot(_t(x), _t(x)).numpy(), (x * x).sum(-1), rtol=1e-6)
        import scipy.linalg as sl
        m = np.asarray([[0.0, 1.0], [-1.0, 0.0]], np.float32)
        np.testing.assert_allclose(paddle.matrix_exp(_t(m)).numpy(),
                                   sl.expm(m), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.floor_mod(_t(np.asarray([5.0, -5.0])),
                             _t(np.asarray([3.0, 3.0]))).numpy(),
            np.mod([5.0, -5.0], 3.0))

    def test_histogram_family(self):
        x = np.random.RandomState(2).rand(100, 2).astype(np.float32)
        h, ex, ey = paddle.histogramdd(_t(x), bins=4)
        wh, (wex, wey) = np.histogramdd(x, bins=4)
        np.testing.assert_allclose(h.numpy(), wh)
        np.testing.assert_allclose(ex.numpy(), wex, rtol=1e-5)
        edges = paddle.histogram_bin_edges(_t(x[:, 0]), bins=10).numpy()
        np.testing.assert_allclose(
            edges, np.histogram_bin_edges(x[:, 0], bins=10), rtol=1e-5)

    def test_base_leftovers(self):
        x = np.asarray([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(paddle.exp2(_t(x)).numpy(), 2.0 ** x,
                                   rtol=1e-6)
        cp = paddle.cartesian_prod(
            [_t(np.asarray([1, 2], np.int32)),
             _t(np.asarray([3, 4, 5], np.int32))]).numpy()
        assert cp.shape == (6, 2) and cp[0].tolist() == [1, 3]
        # reference: single input comes back 1-D
        assert paddle.cartesian_prod(
            [_t(np.asarray([7, 8], np.int32))]).shape == [2]
        withnan = np.asarray([1.0, np.nan, 3.0], np.float32)
        assert paddle.nanmin(_t(withnan)).numpy() == 1.0
        assert paddle.nanmax(_t(withnan)).numpy() == 3.0
        m = np.asarray([[2.0, 0.0], [0.0, 3.0]], np.float32)
        np.testing.assert_allclose(paddle.logdet(_t(m)).numpy(),
                                   np.log(6.0), rtol=1e-6)
        # singular -> -inf (torch oracle), negative det -> nan
        assert paddle.logdet(_t(np.zeros((2, 2), np.float32))).numpy() \
            == -np.inf
        neg = np.asarray([[0.0, 1.0], [1.0, 0.0]], np.float32)
        assert np.isnan(paddle.logdet(_t(neg)).numpy())
        np.testing.assert_allclose(
            paddle.vdot(_t(x), _t(x)).numpy(), np.vdot(x, x), rtol=1e-6)
        np.testing.assert_array_equal(
            paddle.bitwise_invert(_t(np.asarray([0, 1], np.int32))).numpy(),
            np.invert(np.asarray([0, 1], np.int32)))
        assert paddle.ravel(_t(np.ones((2, 3)))).shape == [6]
        oh = paddle.one_hot(_t(np.asarray([0, 2], np.int32)), 3).numpy()
        np.testing.assert_allclose(oh, [[1, 0, 0], [0, 0, 1]])
        ms = [np.random.RandomState(i).rand(3, 3).astype(np.float32)
              for i in range(3)]
        np.testing.assert_allclose(
            paddle.chain_matmul([_t(m_) for m_ in ms]).numpy(),
            ms[0] @ ms[1] @ ms[2], rtol=1e-5)
        vals, idx, counts = paddle.unique_with_counts(
            _t(np.asarray([3, 1, 3, 2], np.int32)))
        np.testing.assert_array_equal(vals.numpy(), [1, 2, 3])  # exact size
        np.testing.assert_array_equal(counts.numpy(), [1, 1, 2])
        np.testing.assert_array_equal(idx.numpy(), [2, 0, 2, 1])

    def test_type_info_and_tensor_surface(self):
        assert paddle.finfo("float32").max > 3e38
        assert float(paddle.finfo("bfloat16").max) > 3e38
        assert paddle.iinfo("int32").max == 2**31 - 1
        t = paddle.to_tensor(np.ones((2, 3), np.float32))
        assert t.element_size() == 4 and t.nbytes == 24
        assert t.cuda() is t  # placement parity no-op on TPU

    def test_registry_crosses_450(self):
        """VERDICT r3 item 8: registry >= 450 ops."""
        from paddle_tpu.ops._op import OP_REGISTRY
        assert len(OP_REGISTRY) >= 450, len(OP_REGISTRY)


class TestShiftOperators:
    """`<<`/`>>` operator overloads on Tensor (reference installs
    __lshift__/__rshift__ over the bitwise shift kernels)."""

    def test_shift_dunders(self):
        x = paddle.to_tensor(np.int32([1, 2, 3]))
        np.testing.assert_array_equal((x << 2).numpy(), [4, 8, 12])
        y = paddle.to_tensor(np.int32([8, 16, 32]))
        np.testing.assert_array_equal((y >> 2).numpy(), [2, 4, 8])

    def test_reflected_shift_dunders(self):
        t = paddle.to_tensor(np.int32([1, 2, 3]))
        np.testing.assert_array_equal((2 << t).numpy(), [4, 8, 16])
        np.testing.assert_array_equal((256 >> t).numpy(), [128, 64, 32])
