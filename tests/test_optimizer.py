"""Optimizer + LR scheduler tests vs numpy oracles."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.optimizer import SGD, Adam, AdamW, Momentum, lr as lr_mod


def make_param(val):
    from paddle_tpu.core.tensor import Parameter
    return Parameter(np.asarray(val, np.float32))


class TestSGDMomentum:
    def test_sgd_step(self):
        p = make_param([1.0, 2.0])
        opt = SGD(learning_rate=0.1, parameters=[p])
        p.grad = paddle.to_tensor(np.array([0.5, 1.0], np.float32))
        opt.step()
        np.testing.assert_allclose(p.numpy(), [0.95, 1.9], rtol=1e-6)

    def test_momentum(self):
        p = make_param([1.0])
        opt = Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
        g = np.array([1.0], np.float32)
        p.grad = paddle.to_tensor(g)
        opt.step()
        np.testing.assert_allclose(p.numpy(), [0.9], rtol=1e-6)
        p.grad = paddle.to_tensor(g)
        opt.step()
        # v = 0.9*1 + 1 = 1.9; p = 0.9 - 0.19
        np.testing.assert_allclose(p.numpy(), [0.71], rtol=1e-5)

    def test_weight_decay_l2(self):
        p = make_param([1.0])
        opt = SGD(learning_rate=0.1, parameters=[p], weight_decay=0.1)
        p.grad = paddle.to_tensor(np.array([0.0], np.float32))
        opt.step()
        np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 0.1], rtol=1e-6)


class TestAdam:
    def test_adam_first_step(self):
        p = make_param([1.0])
        opt = Adam(learning_rate=0.1, parameters=[p])
        p.grad = paddle.to_tensor(np.array([0.5], np.float32))
        opt.step()
        # bias-corrected first step: delta ~= lr * g/|g| = lr
        np.testing.assert_allclose(p.numpy(), [1.0 - 0.1], rtol=1e-4)

    def test_adamw_decoupled(self):
        p = make_param([1.0])
        opt = AdamW(learning_rate=0.1, parameters=[p], weight_decay=0.1)
        p.grad = paddle.to_tensor(np.array([0.0], np.float32))
        opt.step()
        # no grad: adam delta 0, only decay: p *= (1 - lr*coeff)
        np.testing.assert_allclose(p.numpy(), [0.99], rtol=1e-5)

    def test_grad_clip_global_norm(self):
        p1 = make_param([3.0])
        p2 = make_param([4.0])
        clip = nn.ClipGradByGlobalNorm(1.0)
        opt = SGD(learning_rate=1.0, parameters=[p1, p2], grad_clip=clip)
        p1.grad = paddle.to_tensor(np.array([3.0], np.float32))
        p2.grad = paddle.to_tensor(np.array([4.0], np.float32))
        opt.step()
        # norm 5 -> scale 0.2
        np.testing.assert_allclose(p1.numpy(), [3.0 - 0.6], rtol=1e-5)
        np.testing.assert_allclose(p2.numpy(), [4.0 - 0.8], rtol=1e-5)

    def test_state_dict_roundtrip(self):
        p = make_param([1.0])
        p.name = "w"
        opt = Adam(learning_rate=0.1, parameters=[p])
        p.grad = paddle.to_tensor(np.array([0.5], np.float32))
        opt.step()
        sd = opt.state_dict()
        p2 = make_param([1.0])
        p2.name = "w"
        opt2 = Adam(learning_rate=0.1, parameters=[p2])
        opt2.set_state_dict(sd)
        assert opt2._step_count == 1
        np.testing.assert_allclose(
            opt2._slots[id(p2)]["moment1"], opt._slots[id(p)]["moment1"])


class TestLRSchedulers:
    def test_step_decay(self):
        s = lr_mod.StepDecay(learning_rate=1.0, step_size=2, gamma=0.5)
        vals = []
        for _ in range(5):
            vals.append(s())
            s.step()
        np.testing.assert_allclose(vals, [1.0, 1.0, 0.5, 0.5, 0.25])

    def test_linear_warmup(self):
        s = lr_mod.LinearWarmup(learning_rate=1.0, warmup_steps=4,
                                start_lr=0.0, end_lr=1.0)
        vals = [s() for _ in range(1) ]
        seq = []
        for _ in range(6):
            seq.append(s())
            s.step()
        np.testing.assert_allclose(seq[:4], [0.0, 0.25, 0.5, 0.75])
        np.testing.assert_allclose(seq[4:], [1.0, 1.0])

    def test_cosine(self):
        s = lr_mod.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-6
        for _ in range(10):
            s.step()
        assert s() < 1e-6

    def test_noam(self):
        s = lr_mod.NoamDecay(d_model=512, warmup_steps=100, learning_rate=1.0)
        v0 = s()
        for _ in range(99):
            s.step()
        v_peak = s()
        for _ in range(400):
            s.step()
        assert v_peak > v0 and v_peak > s()

    def test_reduce_on_plateau(self):
        s = lr_mod.ReduceOnPlateau(learning_rate=1.0, patience=1, factor=0.5)
        s.step(1.0)
        s.step(1.0)
        s.step(1.0)  # 2 bad steps -> reduce
        assert s() == 0.5

    def test_optimizer_uses_scheduler(self):
        p = make_param([1.0])
        sched = lr_mod.StepDecay(learning_rate=1.0, step_size=1, gamma=0.1)
        opt = SGD(learning_rate=sched, parameters=[p])
        assert opt.get_lr() == 1.0
        sched.step()
        assert abs(opt.get_lr() - 0.1) < 1e-9


class TestLBFGS:
    """paddle.optimizer.LBFGS (reference python/paddle/optimizer/lbfgs.py †:
    closure-based quasi-Newton, strong-Wolfe line search)."""

    def _rosenbrock_setup(self):
        paddle.seed(0)
        x = paddle.to_tensor(np.asarray([-1.2, 1.0], np.float32),
                             stop_gradient=False)
        from paddle_tpu.core.tensor import Parameter
        p = Parameter(x.value)
        p.stop_gradient = False
        return p

    def test_rosenbrock_converges_to_minimum(self):
        """Strong-Wolfe L-BFGS must crack Rosenbrock from the classic
        (-1.2, 1) start — gradient descent cannot in this budget."""
        from paddle_tpu.optimizer import LBFGS
        p = self._rosenbrock_setup()
        opt = LBFGS(learning_rate=1.0, max_iter=40,
                    line_search_fn="strong_wolfe", parameters=[p])

        def closure():
            opt.clear_grad()
            a = p[0]
            b = p[1]
            loss = (1.0 - a) ** 2 + 100.0 * (b - a * a) ** 2
            loss.backward()
            return loss

        for _ in range(8):
            loss = opt.step(closure)
        assert float(loss) < 1e-6, float(loss)
        np.testing.assert_allclose(p.numpy(), [1.0, 1.0], atol=1e-3)

    def test_quadratic_without_line_search(self):
        from paddle_tpu.optimizer import LBFGS
        paddle.seed(1)
        from paddle_tpu.core.tensor import Parameter
        import jax.numpy as jnp
        p = Parameter(jnp.asarray(np.asarray([3.0, -2.0, 1.0], np.float32)))
        p.stop_gradient = False
        opt = LBFGS(learning_rate=0.5, max_iter=30, parameters=[p])

        def closure():
            opt.clear_grad()
            loss = ((p - paddle.to_tensor([1.0, 2.0, 3.0])) ** 2).sum()
            loss.backward()
            return loss

        loss = opt.step(closure)
        assert float(loss) < 1e-8
        np.testing.assert_allclose(p.numpy(), [1.0, 2.0, 3.0], atol=1e-4)

    def test_fits_tiny_network(self):
        from paddle_tpu.optimizer import LBFGS
        paddle.seed(2)
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                   paddle.nn.Tanh(),
                                   paddle.nn.Linear(8, 1))
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
        y = paddle.to_tensor(
            (rng.randn(16, 1) * 0.1 + 0.5).astype(np.float32))
        opt = LBFGS(learning_rate=1.0, max_iter=10,
                    line_search_fn="strong_wolfe",
                    parameters=net.parameters())

        def closure():
            opt.clear_grad()
            loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            return loss

        first = float(closure())
        for _ in range(5):
            last = opt.step(closure)
        assert float(last) < first * 0.05, (first, float(last))

    def test_step_requires_closure(self):
        import pytest

        import jax.numpy as jnp

        from paddle_tpu.core.tensor import Parameter
        from paddle_tpu.optimizer import LBFGS
        p = Parameter(jnp.zeros((2,)))
        p.stop_gradient = False
        opt = LBFGS(parameters=[p])
        with pytest.raises(ValueError, match="closure"):
            opt.step()
