"""Block-table paged attention (serving/kv_cache.PagedKVCache +
engine paged_attn=True): zero-copy prefix hits over a shared block pool.

The load-bearing properties:

- **Transparency**: token streams of the paged engine are byte-identical
  to the dense engine — greedy AND seeded sampled — across hits, misses,
  evictions, COW divergence, and fused decode chunks. Paged changes
  WHERE KV physically lives (pool blocks behind a table vs dense slot
  rows), never what gets sampled.
- **Zero copies**: ``prefill_copy_dispatches`` stays at 0 — hits install
  by referencing published block ids, retirement DONATES blocks instead
  of copying out.
- **Physical sharing**: concurrent holders of one prefix reference the
  SAME block ids (refcount >= 2, ``kv_blocks_shared`` gauge), the win
  the dense install-copy path cannot have.
- **Compile-once survives paging**: block tables are runtime arguments;
  ``decode_compilations() == 1`` under any traffic mix.
- **Ownership discipline**: a mid-decode cancel frees the private tail
  but never the shared prefix; unref-to-zero returns a block to the
  heap exactly once; ``num_free`` is restored after an
  eviction-pressure + cancel storm.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (BlockManager, ContinuousBatchingEngine,
                                GenerationRequest, PagedKVCache)

from test_metrics_prom import parse_prometheus

BS = 8  # block_size for every engine here (tiny model, short prompts)


@pytest.fixture(scope="module")
def model():
    paddle.seed(21)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


def _engine(model, paged=True, prefix_cache=True, **kw):
    kw.setdefault("jit_cache", model.__dict__.setdefault("_serving_jit", {}))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    return ContinuousBatchingEngine(model, prefix_cache=prefix_cache,
                                    paged_attn=paged, **kw)


_SYS = np.random.RandomState(7).randint(0, 256, (20,)).astype(np.int32)


def _req(tail_seed, n_tail=6, sys_prompt=_SYS, **kw):
    """Shared-system-prompt request: 20 shared tokens + a unique tail."""
    tail = np.random.RandomState(tail_seed).randint(
        0, 256, (n_tail,)).astype(np.int32)
    kw.setdefault("max_new_tokens", 6)
    return GenerationRequest(prompt=np.concatenate([sys_prompt, tail]), **kw)


def _clone(req):
    return GenerationRequest(
        prompt=req.prompt, max_new_tokens=req.max_new_tokens,
        temperature=req.temperature, top_k=req.top_k,
        eos_token_id=req.eos_token_id, seed=req.seed)


def _dense_run(model, reqs, **kw):
    eng = _engine(model, paged=False, prefix_cache=False, **kw)
    return [o.tolist() for o in eng.generate([_clone(r) for r in reqs])]


class TestTransparency:
    def test_streams_identical_greedy_and_sampled(self, model):
        """The acceptance pin: hit/miss mixes, greedy and seeded-sampled,
        stream the exact dense-engine tokens with ZERO copy dispatches
        and one decode compilation."""
        reqs = [_req(1), _req(2),
                _req(3, temperature=0.9, top_k=5, seed=123),
                _req(4, temperature=0.7, top_k=3, seed=9)]
        want = _dense_run(model, reqs)
        eng = _engine(model)
        got = [o.tolist() for o in eng.generate([_clone(r) for r in reqs])]
        assert got == want
        pc = eng.prefix_cache
        assert pc.stats["hits"] >= 2           # later admissions reused
        assert pc.stats["donated_blocks"] > 0  # publish = adoption
        assert eng.stats["prefill_copy_dispatches"] == 0
        assert eng.decode_compilations() == 1
        # hits really skipped device prefill work, same accounting as
        # the dense prefix cache
        assert eng.stats["prefill_tokens"] == \
            sum(len(r.prompt) for r in reqs) - pc.stats["hit_tokens"]

    @pytest.mark.slow  # 8 s chunk-boundary duplicate: test_streams_identical_
    # greedy_and_sampled above is the default paged rep (870s cap)
    def test_fused_chunks_cross_block_boundaries(self, model):
        """decode_chunk > block-crossing distance: fused ticks write
        across block boundaries through pre-grown tables; streams stay
        byte-identical and the step-size compile set stays the pow2
        ladder."""
        reqs = [_req(10, max_new_tokens=20), _req(11, max_new_tokens=20)]
        want = _dense_run(model, reqs, decode_chunk=8)
        eng = _engine(model, decode_chunk=8)
        got = [o.tolist() for o in eng.generate([_clone(r) for r in reqs])]
        assert got == want

    def test_paged_without_prefix_cache(self, model):
        """paged_attn stands alone: pool sized to the live grid, no
        trie, same streams."""
        reqs = [_req(20), _req(21, temperature=0.8, top_k=4, seed=5)]
        want = _dense_run(model, reqs)
        eng = _engine(model, prefix_cache=False)
        got = [o.tolist() for o in eng.generate([_clone(r) for r in reqs])]
        assert got == want
        assert eng.prefix_cache is None
        assert eng.cache.pool.num_blocks == 2 * (64 // BS)  # live grid
        assert eng.cache.pool.num_used == 0  # all returned at retirement

    @pytest.mark.slow  # eviction-pressure duplicate: the unified
    # engine's matrix pins evictions + byte-identical streams on the
    # default path (test_ragged_step) and the dense eviction-equality
    # rep stays default in test_prefix_cache
    def test_eviction_pressure_keeps_streams_exact(self, model):
        """A trie budget far smaller than the working set: evictions
        fire, live sequences always win the pool (evict-on-demand), and
        streams stay byte-identical."""
        reqs = [_req(30 + i, sys_prompt=np.random.RandomState(100 + i % 5)
                     .randint(0, 256, (16,)).astype(np.int32),
                     max_new_tokens=4) for i in range(10)]
        want = _dense_run(model, reqs)
        eng = _engine(model, prefix_blocks=3)
        pool = eng.prefix_cache.pool
        outs = []
        for r in reqs:  # serially, so pool pressure peaks per publish
            outs.append(eng.generate([_clone(r)])[0].tolist())
            assert pool.num_used <= pool.num_blocks
        assert outs == want
        assert eng.prefix_cache.stats["evictions"] > 0
        assert eng.stats["prefill_copy_dispatches"] == 0


class TestZeroCopySharing:
    def test_concurrent_hits_share_physical_blocks(self, model):
        """Two live sequences hitting the same chain REFERENCE the same
        physical blocks (dense would hold two private copies): their
        table prefixes are equal, the blocks carry refcount 2, and the
        kv_blocks_shared accounting sees them. Divergent tails still
        match the dense streams (writes land in private tail blocks)."""
        a = _req(31, max_new_tokens=8)
        b = _req(32, max_new_tokens=8, temperature=0.9, top_k=4, seed=3)
        want = _dense_run(model, [a, b])
        eng = _engine(model)
        eng.generate([_req(30, max_new_tokens=2)])  # publish the chain
        sa, sb = eng.submit(_clone(a)), eng.submit(_clone(b))
        step0 = eng.stats["steps"]
        seen_shared = False
        while eng.has_work():
            eng.step()
            if eng.stats["steps"] == step0 + 1:
                shared = set(n.block_id for n in sa.prefix_nodes) & \
                    set(n.block_id for n in sb.prefix_nodes)
                assert shared          # same physical blocks, no copies
                assert all(eng.prefix_cache.pool.refcount(bid) == 2
                           for bid in shared)
                assert eng.cache.pool.num_shared >= len(shared)
                # the tables literally point at the shared blocks
                ta = eng.cache.tables[sa.slot][:len(sa.prefix_nodes)]
                tb = eng.cache.tables[sb.slot][:len(sb.prefix_nodes)]
                assert set(ta) & set(tb) == shared
                seen_shared = True
        assert seen_shared
        assert [sa.tokens, sb.tokens] == want
        assert sa.prefix_hit_tokens == sb.prefix_hit_tokens == 2 * BS
        assert eng.stats["prefill_copy_dispatches"] == 0
        # pins drained at retirement; trie-resident blocks are zero-ref
        assert not eng.prefix_cache.pool._ref.any()

    def test_donated_blocks_are_adopted_not_copied(self, model):
        """Retirement hands the sequence's own prompt blocks to the
        trie: the next identical prompt's matched chain holds the SAME
        physical ids the first sequence's table held."""
        eng = _engine(model)
        s1 = eng.submit(_req(40, max_new_tokens=4))
        eng.step()
        assert s1.status == "running"
        # prompt = 26 tokens -> blocks 0..2 hold the 24 full-block rows
        first_blocks = [int(b) for b in eng.cache.tables[s1.slot][:3]]
        while eng.has_work():
            eng.step()
        matched = eng.prefix_cache.lookup(_req(40).prompt, record=False)
        assert [n.block_id for n in matched] == first_blocks
        assert eng.prefix_cache.stats["donated_blocks"] >= 3


class TestOwnershipDiscipline:
    def test_cancel_mid_decode_frees_tail_not_shared_prefix(self, model):
        """The COW-fork teardown: cancelling a hit mid-decode returns
        its PRIVATE tail blocks to the heap while the shared prefix
        (pinned by the trie + the surviving holder) stays resident, and
        the survivor's stream is untouched."""
        b = _req(51, max_new_tokens=10)
        want_b = _dense_run(model, [b])
        eng = _engine(model)
        eng.generate([_req(50, max_new_tokens=2)])  # publish the chain
        pool = eng.prefix_cache.pool
        used_baseline = pool.num_used
        sa = eng.submit(_req(52, max_new_tokens=30))
        sb = eng.submit(_clone(b))
        eng.step()
        eng.step()
        assert sa.status == "running"
        shared = [n.block_id for n in sa.prefix_nodes]
        assert shared and shared == [n.block_id for n in sb.prefix_nodes]
        tail = [blk for blk in eng.cache.slot_block_ids(sa.slot)
                if blk not in shared]
        assert tail                    # private suffix/decode blocks
        free_before = pool.num_free
        assert eng.cancel(sa)
        # the whole private tail went back to the heap... except blocks
        # the cancel's own publish donated (full prompt blocks beyond
        # the matched chain); either way every shared block survived
        for blk in shared:
            assert pool.refcount(blk) >= 1   # sb still pinning
            assert blk not in pool._free_set
        assert pool.num_free >= free_before
        while eng.has_work():
            eng.step()
        assert sb.tokens == want_b[0]  # bystander byte-identical
        assert not pool._ref.any()
        assert pool.num_used >= used_baseline  # trie chain still cached

    def test_eviction_and_cancel_storm_restores_num_free(self, model):
        """Mirrors the PR 2 slot-recovery tests at block granularity: a
        storm of admissions, cancels, and trie-eviction pressure ends
        with every live pin drained and the free count consistent (pool
        = free + trie-resident blocks)."""
        eng = _engine(model, prefix_blocks=2, num_slots=2)
        pool = eng.prefix_cache.pool
        rng = np.random.RandomState(3)
        live = []
        for i in range(12):
            sysp = np.random.RandomState(200 + i % 3).randint(
                0, 256, (16,)).astype(np.int32)
            tail = rng.randint(0, 256, (5,)).astype(np.int32)
            live.append(eng.submit(GenerationRequest(
                prompt=np.concatenate([sysp, tail]),
                max_new_tokens=int(rng.randint(2, 12)))))
            eng.step()
            if i % 3 == 2:            # cancel a random still-live seq
                cand = [s for s in live if not s.done]
                if cand:
                    eng.cancel(cand[int(rng.randint(len(cand)))])
        while eng.has_work():
            eng.step()
        assert not pool._ref.any()               # every pin drained
        assert eng.cache.num_free == eng.num_slots
        # allocated == trie-resident exactly; nothing leaked
        assert pool.num_used == eng.prefix_cache.num_cached_blocks
        assert pool.num_free == pool.num_blocks - pool.num_used
        assert eng.prefix_cache.stats["evictions"] > 0

    def test_live_growth_reclaims_trie_blocks_on_demand(self):
        """A dry pool with unpinned trie residents: ensure_capacity
        evicts them to feed live growth (live sequences always win the
        pool); pinned chains survive and a fully-pinned dry pool is a
        hard error, not a corruption."""
        from paddle_tpu.serving import PrefixCache
        pool = BlockManager(1, 4, 4, 1, 2)
        pc = PrefixCache(pool, max_blocks=2)
        cache = PagedKVCache(1, 1, 16, 1, 2, block_size=4, pool=pool,
                             prefix_cache=pc)
        b0, b1 = pool.alloc(), pool.alloc()
        donated = pc.publish_donate(np.arange(8), [b0, b1])
        assert donated == {b0, b1} and pc.num_cached_blocks == 2
        slot = cache.alloc()
        cache.ensure_capacity(slot, 16)     # needs all 4: 2 free + 2 evicted
        assert int(cache._n_blocks[slot]) == 4
        assert pc.num_cached_blocks == 0    # trie yielded on demand
        assert pool.num_free == 0
        cache.free(slot)
        assert pool.num_free == 4           # private tail fully returned
        # fully-pinned dry pool: allocation refuses loudly
        b2 = pool.alloc()
        pc.publish_donate(np.arange(100, 104), [b2])
        matched = pc.lookup(np.arange(100, 105))
        pc.acquire(matched)                 # live reader pins the chain
        for _ in range(3):
            pool.ref(pool.alloc())          # the rest is live-owned too
        slot = cache.alloc()
        with pytest.raises(RuntimeError, match="pool exhausted"):
            cache.ensure_capacity(slot, 4)

    def test_unref_to_zero_frees_exactly_once(self):
        """BlockManager.drop: the heap gets the block back exactly when
        the count hits zero — once. A second drop raises, a drop while
        other readers remain frees nothing."""
        pool = BlockManager(1, 2, 4, 1, 2)
        blk = pool.alloc()
        pool.ref(blk)
        pool.ref(blk)                  # two readers
        assert pool.drop(blk) is False  # one left; still allocated
        assert blk not in pool._free_set
        assert pool.drop(blk) is True   # zero: freed, exactly once
        assert blk in pool._free_set
        with pytest.raises(ValueError, match="below zero"):
            pool.drop(blk)
        assert pool.num_free == 2 - 1 + 1  # only one free event happened


class TestCompileDiscipline:
    @pytest.mark.slow  # compile-discipline duplicate: the unified
    # engine's hit/miss/eviction/cancel matrix (test_ragged_step),
    # chunked closed-compile-set (test_chunked_prefill) and the
    # engine-level request-mix closure (test_serving) stay the default
    # reps of the same decode_compilations()==1 chain
    def test_mixed_traffic_keeps_decode_at_one(self, model):
        """Waves of hits/misses/divergence leave decode_compilations()
        at 1 and the prefill/suffix compile set closed over the pow2
        grid — block tables are runtime data. A dense engine sharing the
        same jit_cache counts its own programs separately."""
        jit = {}
        eng = _engine(model, jit_cache=jit)

        def wave(e):
            outs = e.generate(
                [_req(60), _req(61),
                 _req(62, temperature=0.8, top_k=6, seed=2),
                 GenerationRequest(
                     prompt=np.random.RandomState(63).randint(
                         0, 256, (2 * BS,)).astype(np.int32),
                     max_new_tokens=3),
                 _req(64, n_tail=3)])
            return [o.tolist() for o in outs]

        first = wave(eng)
        second = wave(eng)
        assert second == first
        assert eng.decode_compilations() == 1
        prefill0 = eng.prefill_compilations()
        third = wave(eng)
        assert third == first
        assert eng.decode_compilations() == 1
        assert eng.prefill_compilations() == prefill0  # zero new traces
        assert eng.stats["prefill_copy_dispatches"] == 0
        # dense engine on the SAME jit dict: separate decode kind, its
        # own count also 1 — and the cold prefill program is shared
        dense = _engine(model, paged=False, prefix_cache=False,
                        jit_cache=jit)
        assert wave(dense) == first
        assert dense.decode_compilations() == 1
        assert eng.decode_compilations() == 1


class TestMetricsSurface:
    def test_paged_gauges_strict_parsed(self, model):
        """/metrics grows kv_blocks_shared + kv_block_table_fill and the
        serving_prefill_copy_dispatches_total counter (pinned at 0 on
        the paged path), all valid under the strict v0.0.4 parser."""
        from paddle_tpu.serving.server import ServingGateway
        eng = _engine(model, num_slots=2)
        gw = ServingGateway(eng, start=False)  # no driver thread needed
        eng.generate([_req(70, max_new_tokens=2)])   # publish the chain
        # two live holders of the shared chain at scrape time
        sa = eng.submit(_req(71, max_new_tokens=20))
        sb = eng.submit(_req(72, max_new_tokens=20))
        eng.step()
        fams = parse_prometheus(gw.registry.render())  # strict: raises

        def val(name):
            return fams[name]["samples"][(name, ())]

        assert fams["kv_blocks_shared"]["type"] == "gauge"
        assert val("kv_blocks_shared") == eng.cache.pool.num_shared >= 2
        assert fams["kv_block_table_fill"]["type"] == "gauge"
        assert 0.0 < val("kv_block_table_fill") <= 1.0
        assert val("kv_block_table_fill") == pytest.approx(
            eng.cache.table_fill())
        assert fams["serving_prefill_copy_dispatches_total"]["type"] == \
            "counter"
        assert val("serving_prefill_copy_dispatches_total") == 0
        assert val("serving_prefix_cache_hits_total") >= 2
        assert val("kv_prefix_blocks") == eng.cache.pool.num_used
        eng.cancel(sa)
        eng.cancel(sb)
        while eng.has_work():
            eng.step()
        fams2 = parse_prometheus(gw.registry.render())
        assert fams2["kv_blocks_shared"]["samples"][
            ("kv_blocks_shared", ())] == 0
        assert fams2["kv_block_table_fill"]["samples"][
            ("kv_block_table_fill", ())] == 0.0

    def test_dense_engine_counts_copy_dispatches(self, model):
        """The counter the paged path eliminates is real on the dense
        path: hits there dispatch one copy per installed block."""
        eng = _engine(model, paged=False)
        eng.generate([_req(75, max_new_tokens=2)])
        eng.generate([_req(76, max_new_tokens=2)])   # hit: 2-block chain
        assert eng.stats["prefill_copy_dispatches"] >= 2


class TestConstruction:
    def test_pool_too_small_for_live_grid_rejected(self):
        pool = BlockManager(1, 3, BS, 1, 2)
        with pytest.raises(ValueError, match="cannot back"):
            PagedKVCache(1, 2, 64, 1, 2, block_size=BS, pool=pool)

    def test_shared_prefix_cache_geometry_validated(self, model):
        """A shared PrefixCache whose pool can't also hold the live
        block grid (or mismatches block size) fails fast at __init__."""
        donor = _engine(model, paged=False)   # dense-sized pool: too small
        with pytest.raises(ValueError, match="cannot back|live blocks"):
            _engine(model, prefix_cache=donor.prefix_cache)
        paged_donor = _engine(model)
        ok = _engine(model, prefix_cache=paged_donor.prefix_cache)
        assert ok.prefix_cache is paged_donor.prefix_cache
        with pytest.raises(ValueError, match="geometry|does not match"):
            _engine(model, prefix_cache=paged_donor.prefix_cache,
                    prefix_block_size=BS * 2)

    def test_prefix_blocks_zero_rejected(self, model):
        with pytest.raises(ValueError, match="prefix_blocks"):
            _engine(model, prefix_blocks=0)

    def test_shared_dense_idiom_cache_gets_a_trie_budget(self, model):
        """Adopting a budget-less PrefixCache caps trie residency at the
        pool's headroom over the live grid — donations stay bounded."""
        from paddle_tpu.serving import PrefixCache
        live = 2 * (64 // BS)
        pc = PrefixCache(BlockManager(4, live + 3, BS, 2, 16))
        assert pc.max_blocks is None
        eng = _engine(model, prefix_cache=pc)
        assert eng.prefix_cache is pc and pc.max_blocks == 3
