"""Pallas decode-attention kernel parity (reference: the
masked-multihead-attention decode kernel in
``paddle/phi/kernels/fusion/gpu/fused_multi_transformer_op.cu`` † — here a
Pallas ragged single-query kernel, tests/test_pallas_decode.py is its
interpret-mode oracle suite)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.pallas_decode import (decode_attention_pallas,
                                              decode_attention_reference)


def _mk(B, H, Hkv, D, s_max, seed=0, dtype=jnp.float32, nan_tail=False):
    r = np.random.RandomState(seed)
    q = jnp.asarray(r.randn(B, H, D), dtype)
    k = r.randn(B, s_max, Hkv, D).astype(np.float32)
    v = r.randn(B, s_max, Hkv, D).astype(np.float32)
    lengths = np.asarray(r.randint(1, s_max + 1, B), np.int32)
    if nan_tail:  # uninitialized cache rows must never reach the output
        for b in range(B):
            k[b, lengths[b]:] = np.nan
            v[b, lengths[b]:] = np.nan
    return q, jnp.asarray(k, dtype), jnp.asarray(v, dtype), jnp.asarray(lengths)


class TestDecodeKernelParity:
    @pytest.mark.parametrize("B,H,Hkv,D,s_max", [
        (2, 4, 4, 64, 128),      # MHA
        (2, 8, 2, 64, 128),      # GQA group 4
        (1, 16, 16, 128, 160),   # ragged tail (s_max % block != 0)
        (3, 8, 1, 64, 96),       # MQA
    ])
    def test_matches_reference(self, B, H, Hkv, D, s_max):
        q, k, v, lens = _mk(B, H, Hkv, D, s_max, seed=B + H)
        got = decode_attention_pallas(q, k, v, lens, block_k=64)
        want = decode_attention_reference(q, k, v, lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_nan_tail_isolated(self):
        """Rows past lengths[b] are uninitialized in real decode caches;
        NaNs there must not leak through the softmax."""
        q, k, v, lens = _mk(2, 8, 4, 64, 128, seed=7, nan_tail=True)
        got = np.asarray(decode_attention_pallas(q, k, v, lens, block_k=64))
        assert np.isfinite(got).all()
        want = np.asarray(decode_attention_reference(q, k, v, lens))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_ragged_lengths_differ_per_row(self):
        """Two rows with different lengths must see different contexts:
        row 0 (len 1) equals attention over exactly its first entry."""
        B, H, Hkv, D, s_max = 2, 4, 4, 64, 256
        r = np.random.RandomState(3)
        q = jnp.asarray(r.randn(B, H, D), jnp.float32)
        k = jnp.asarray(r.randn(B, s_max, Hkv, D), jnp.float32)
        v = jnp.asarray(r.randn(B, s_max, Hkv, D), jnp.float32)
        lens = jnp.asarray([1, 200], jnp.int32)
        got = np.asarray(decode_attention_pallas(q, k, v, lens))
        # len=1: output is exactly v[0, 0] per head (softmax over 1 entry)
        np.testing.assert_allclose(got[0], np.asarray(v)[0, 0], rtol=1e-5,
                                   atol=1e-5)

    def test_bf16_io(self):
        q, k, v, lens = _mk(2, 8, 8, 128, 128, seed=11, dtype=jnp.bfloat16)
        got = decode_attention_pallas(q, k, v, lens, block_k=128)
        want = decode_attention_reference(q, k, v, lens)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=3e-2, atol=3e-2)

    def test_jit_and_scan_composable(self):
        """The kernel must trace under jit inside a lax.scan over layers —
        the exact shape of the generate decode loop."""
        B, H, Hkv, D, s_max, L = 2, 4, 2, 64, 128, 3
        r = np.random.RandomState(5)
        q = jnp.asarray(r.randn(L, B, H, D), jnp.float32)
        k = jnp.asarray(r.randn(L, B, s_max, Hkv, D), jnp.float32)
        v = jnp.asarray(r.randn(L, B, s_max, Hkv, D), jnp.float32)
        lens = jnp.asarray([64, 100], jnp.int32)

        @jax.jit
        def run(q, k, v):
            def body(carry, xs):
                ql, kl, vl = xs
                return carry + 1, decode_attention_pallas(ql, kl, vl, lens)
            _, outs = jax.lax.scan(body, 0, (q, k, v))
            return outs

        outs = np.asarray(run(q, k, v))
        for l in range(L):
            want = np.asarray(decode_attention_reference(q[l], k[l], v[l],
                                                         lens))
            np.testing.assert_allclose(outs[l], want, rtol=2e-5, atol=2e-5)
