"""Pallas flash-attention kernels exercised in CI via interpret=True.

VERDICT r1 weak item 4: the CPU test suite only ever ran the jnp reference
path, so a kernel regression was invisible until a TPU bench run. These
tests force interpret mode so the actual kernel bodies (online softmax,
causal pruning, tail-block masking, bwd dkv/dq) run on every CI pass.

Oracle: ``_ref_attention`` (jnp, full S×S materialization) and its
``jax.grad`` — the reference's OpTest check_output/check_grad pattern
(SURVEY.md §4, test/legacy_test/op_test.py †).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.kernels import pallas_flash
from paddle_tpu.kernels.flash_attention import _ref_attention


@pytest.fixture(autouse=True)
def _interpret():
    pallas_flash._FORCE_INTERPRET[0] = True
    yield
    pallas_flash._FORCE_INTERPRET[0] = False


def _mk(bh, s, d, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(bh, s, d).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(bh, s, d).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(bh, s, d).astype(np.float32)) * 0.3
    return q, k, v


def _ref_bhsd(q, k, v, causal):
    # [BH, S, D] -> [BH, S, 1, D] paddle layout for the oracle
    out = _ref_attention(q[:, :, None], k[:, :, None], v[:, :, None], causal)
    return out[:, :, 0]


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s,block", [(256, 128), (320, 128), (384, 256)])
def test_fwd_matches_reference(causal, s, block):
    """320/384 with block 128/256 exercise the padded tail block — the
    ADVICE r1 high-severity bug (unmasked padded cols in non-causal)."""
    q, k, v = _mk(2, s, 64)
    out = pallas_flash.flash_attention_bhsd(q, k, v, causal=causal,
                                            block_q=block, block_k=block)
    ref = _ref_bhsd(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [pytest.param(False, marks=pytest.mark.slow), True])
@pytest.mark.parametrize("s,block", [
    (256, 128),
    # padded-tail grads at 320 are slow-marked; the fwd test keeps the
    # tail-block coverage (320 AND 384) in the default run
    pytest.param(320, 128, marks=pytest.mark.slow),
])
def test_grads_match_reference(causal, s, block):
    q, k, v = _mk(2, s, 32, seed=1)

    def loss_flash(q, k, v):
        o = pallas_flash.flash_attention_bhsd(q, k, v, causal=causal,
                                              block_q=block, block_k=block)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = _ref_bhsd(q, k, v, causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")


def test_gqa_paddle_layout():
    """[B,S,H,D] entry with grouped-query kv heads (H=4, Hk=2)."""
    rng = np.random.RandomState(2)
    B, S, H, Hk, D = 2, 256, 4, 2, 32
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, S, Hk, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, S, Hk, D).astype(np.float32)) * 0.3
    out = pallas_flash.flash_attention_pallas(q, k, v, causal=True,
                                              block_q=128, block_k=128)
    ref = _ref_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_tail_block_nondivisible_long():
    """S=1500-style case from ADVICE r1 (scaled down): S % block != 0,
    non-causal — previously returned silently wrong output."""
    q, k, v = _mk(1, 200, 32, seed=3)
    out = pallas_flash.flash_attention_bhsd(q, k, v, causal=False,
                                            block_q=128, block_k=128)
    ref = _ref_bhsd(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def _rope_tables_np(s, d, theta=10000.0):
    inv = 1.0 / (theta ** (np.arange(0, d, 2, dtype=np.float32) / d))
    freqs = np.outer(np.arange(s, dtype=np.float32), inv)
    emb = np.concatenate([freqs, freqs], axis=-1)
    return jnp.asarray(np.sin(emb)), jnp.asarray(np.cos(emb))


def _rope_np(x, sin, cos):
    d = x.shape[-1]
    rot = jnp.concatenate([-x[..., d // 2:], x[..., :d // 2]], axis=-1)
    return x * cos[None] + rot * sin[None]


@pytest.mark.parametrize("s,block", [(256, 128), (320, 128)])
def test_fused_rope_fwd_matches_rope_then_flash(s, block):
    """rope=(sin,cos) inside the kernel == apply_rope outside + flash
    (the fused_rope_kernel.cu fusion, VERDICT r3 item 9)."""
    q, k, v = _mk(2, s, 64, seed=3)
    sin, cos = _rope_tables_np(s, 64)
    fused = pallas_flash.flash_attention_bhsd(
        q, k, v, causal=True, block_q=block, block_k=block, rope=(sin, cos))
    unfused = pallas_flash.flash_attention_bhsd(
        _rope_np(q, sin, cos), _rope_np(k, sin, cos), v, causal=True,
        block_q=block, block_k=block)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(unfused),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("s,block", [(256, 128)])
def test_fused_rope_grads_match_rope_then_flash(s, block):
    """dq/dk must come back w.r.t. the PRE-rope projections (the in-kernel
    adjoint), matching autodiff through rope-outside + flash."""
    q, k, v = _mk(2, s, 32, seed=4)
    sin, cos = _rope_tables_np(s, 32)

    def loss_fused(q, k, v):
        o = pallas_flash.flash_attention_bhsd(
            q, k, v, causal=True, block_q=block, block_k=block,
            rope=(sin, cos))
        return jnp.sum(o * jnp.cos(o))

    def loss_unfused(q, k, v):
        o = pallas_flash.flash_attention_bhsd(
            _rope_np(q, sin, cos), _rope_np(k, sin, cos), v, causal=True,
            block_q=block, block_k=block)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    gu = jax.grad(loss_unfused, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gu, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")
