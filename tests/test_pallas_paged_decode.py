"""Pallas ragged-paged decode kernel parity ("Ragged Paged Attention",
PAPERS.md): single-query attention walking a per-sequence block table
over a shared KV pool, interpret-mode oracle suite mirroring
test_pallas_decode.py. The extra paged properties pinned here: the
table indirection is exact (scrambled physical placement changes
nothing), sharing a physical block between rows is exact (the zero-copy
prefix-hit story), and sentinel/dead-slot tables stay finite."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.pallas_decode import decode_attention_reference
from paddle_tpu.kernels.pallas_paged_decode import (
    paged_decode_attention_pallas, paged_decode_attention_reference)


def _mk_paged(B, H, Hkv, D, mb, bs, seed=0, dtype=jnp.float32,
              nan_free_pool=True, share=None):
    """Build a pool + scrambled tables so logical row order != physical
    order. ``share``: list of (row_a, row_b, n_blocks) aliasing the
    leading n blocks of two rows onto the same physical blocks."""
    r = np.random.RandomState(seed)
    q = jnp.asarray(r.randn(B, H, D), dtype)
    num_blocks = B * mb + 2
    pool_k = r.randn(num_blocks, bs, Hkv, D).astype(np.float32)
    pool_v = r.randn(num_blocks, bs, Hkv, D).astype(np.float32)
    perm = r.permutation(B * mb)
    tables = np.asarray(perm.reshape(B, mb), np.int32)
    for a, b, n in (share or []):
        tables[b, :n] = tables[a, :n]
    lengths = np.asarray(r.randint(1, mb * bs + 1, B), np.int32)
    return (q, jnp.asarray(pool_k, dtype), jnp.asarray(pool_v, dtype),
            jnp.asarray(tables), jnp.asarray(lengths))


def _dense_view(pool_k, pool_v, tables):
    """Gathered dense [B, mb*bs, Hkv, D] caches — the oracle's oracle."""
    pk = np.asarray(pool_k)[np.asarray(tables)]
    pv = np.asarray(pool_v)[np.asarray(tables)]
    B, mb, bs, Hkv, D = pk.shape
    return (jnp.asarray(pk.reshape(B, mb * bs, Hkv, D)),
            jnp.asarray(pv.reshape(B, mb * bs, Hkv, D)))


class TestPagedDecodeKernelParity:
    @pytest.mark.parametrize("B,H,Hkv,D,mb,bs", [
        # plain MHA is -m slow: the ragged/sentinel/indirection tests
        # below already cover MHA shapes (suite-budget discipline)
        pytest.param(2, 4, 4, 64, 4, 32, marks=pytest.mark.slow),  # MHA
        (2, 8, 2, 64, 4, 32),     # GQA group 4
        (3, 8, 1, 64, 3, 16),     # MQA, small blocks
        (1, 16, 16, 128, 2, 8),   # minimal sublane block
    ])
    def test_matches_paged_reference(self, B, H, Hkv, D, mb, bs):
        q, pk, pv, tbl, lens = _mk_paged(B, H, Hkv, D, mb, bs, seed=B + H)
        got = paged_decode_attention_pallas(q, pk, pv, tbl, lens)
        want = paged_decode_attention_reference(q, pk, pv, tbl, lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_table_indirection_equals_dense_gather(self):
        """The load-bearing paged property: attention through a
        SCRAMBLED table equals dense ragged attention over the gathered
        view — physical placement is invisible."""
        q, pk, pv, tbl, lens = _mk_paged(3, 8, 4, 64, 4, 16, seed=5)
        dk, dv = _dense_view(pk, pv, tbl)
        want = decode_attention_reference(q, dk, dv, lens)
        got = paged_decode_attention_pallas(q, pk, pv, tbl, lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        got_ref = paged_decode_attention_reference(q, pk, pv, tbl, lens)
        np.testing.assert_allclose(np.asarray(got_ref), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    def test_shared_physical_blocks_exact(self):
        """Two rows whose tables alias the same leading physical blocks
        (a zero-copy prefix hit) each compute exactly what a private
        copy would — reads don't care about sharing."""
        q, pk, pv, tbl, lens = _mk_paged(
            2, 4, 4, 64, 4, 16, seed=9, share=[(0, 1, 2)])
        assert np.asarray(tbl)[0, 0] == np.asarray(tbl)[1, 0]
        dk, dv = _dense_view(pk, pv, tbl)
        want = decode_attention_reference(q, dk, dv, lens)
        got = paged_decode_attention_pallas(q, pk, pv, tbl, lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_sentinel_tail_and_dead_slot_stay_finite(self):
        """Unmapped table entries carry the sentinel (>= num_blocks) and
        dead slots are all-sentinel with length 0 — both must clamp to
        harmless reads, never index out of the pool or emit NaN."""
        q, pk, pv, tbl, lens = _mk_paged(3, 8, 4, 64, 4, 16, seed=13)
        tbl = np.asarray(tbl).copy()
        lens = np.asarray(lens).copy()
        num_blocks = pk.shape[0]
        lens[1] = 16                      # one block valid
        tbl[1, 1:] = num_blocks           # unmapped tail -> sentinel
        tbl[2, :] = num_blocks            # dead slot
        lens[2] = 0
        got = np.asarray(paged_decode_attention_pallas(
            q, pk, pv, jnp.asarray(tbl), jnp.asarray(lens)))
        assert np.isfinite(got).all()
        want = np.asarray(paged_decode_attention_reference(
            q, pk, pv, jnp.asarray(tbl), jnp.asarray(lens)))
        # live rows match the oracle exactly; the dead row's output is
        # garbage-by-contract (engine never reads it) but stays finite
        np.testing.assert_allclose(got[:2], want[:2], rtol=2e-5, atol=2e-5)

    def test_ragged_len_one_row(self):
        """A length-1 row attends over exactly its first pool row."""
        B, H, Hkv, D, mb, bs = 2, 4, 4, 64, 4, 16
        q, pk, pv, tbl, lens = _mk_paged(B, H, Hkv, D, mb, bs, seed=3)
        lens = np.asarray(lens).copy()
        lens[0] = 1
        got = np.asarray(paged_decode_attention_pallas(
            q, pk, pv, tbl, jnp.asarray(lens)))
        first_block = int(np.asarray(tbl)[0, 0])
        np.testing.assert_allclose(got[0], np.asarray(pv)[first_block, 0],
                                   rtol=1e-5, atol=1e-5)

    def test_bf16_io(self):
        q, pk, pv, tbl, lens = _mk_paged(2, 8, 8, 128, 2, 32, seed=11,
                                         dtype=jnp.bfloat16)
        got = paged_decode_attention_pallas(q, pk, pv, tbl, lens)
        want = paged_decode_attention_reference(q, pk, pv, tbl, lens)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=3e-2, atol=3e-2)

    def test_jit_and_scan_composable(self):
        """Must trace under jit inside a lax.scan over layers — the
        exact shape of the paged decode loop (per-layer pool slices,
        one shared table)."""
        B, H, Hkv, D, mb, bs, L = 2, 4, 2, 64, 4, 16, 3
        r = np.random.RandomState(5)
        q = jnp.asarray(r.randn(L, B, H, D), jnp.float32)
        num_blocks = B * mb
        pk = jnp.asarray(r.randn(L, num_blocks, bs, Hkv, D), jnp.float32)
        pv = jnp.asarray(r.randn(L, num_blocks, bs, Hkv, D), jnp.float32)
        tbl = jnp.asarray(
            r.permutation(num_blocks).reshape(B, mb), jnp.int32)
        lens = jnp.asarray([40, 64], jnp.int32)

        @jax.jit
        def run(q, pk, pv):
            def body(carry, xs):
                ql, kl, vl = xs
                return carry + 1, paged_decode_attention_pallas(
                    ql, kl, vl, tbl, lens)
            _, outs = jax.lax.scan(body, 0, (q, pk, pv))
            return outs

        outs = np.asarray(run(q, pk, pv))
        for l in range(L):
            want = np.asarray(paged_decode_attention_reference(
                q[l], pk[l], pv[l], tbl, lens))
            np.testing.assert_allclose(outs[l], want, rtol=2e-5, atol=2e-5)
