"""Unified ragged prefill+decode paged attention kernel parity
(kernels/pallas_ragged_attention.py, "Ragged Paged Attention",
PAPERS.md): a PACKED buffer of variable-length query spans — decode
rows (span 1) and prefill chunks (span n) — attends causally through
per-sequence block tables in ONE kernel invocation. Interpret-mode
oracle suite mirroring test_pallas_paged_decode.py, plus the properties
the unification itself must pin:

- the jnp oracle equals an independently-built dense causal reference
  over the gathered (scrambled-table) view, span by span — BITWISE,
  because the oracle deliberately replays the old suffix-prefill
  program's op sequence;
- a span-1 row is BITWISE the old single-query paged decode kernel
  (pallas vs pallas, reference vs reference) — the unified serving step
  cannot perturb decode numerics;
- sentinel tables / dead rows / packed padding stay finite and come
  back as exact zeros.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.pallas_paged_decode import (
    paged_decode_attention_pallas, paged_decode_attention_reference)
from paddle_tpu.kernels.pallas_ragged_attention import (
    ragged_attention_reference, ragged_paged_attention_pallas)

NEG_INF = -1e30


def _mk(R, spans, H, Hkv, D, mb, bs, seed=0, dtype=jnp.float32, T=None):
    """Pool + scrambled tables + packed spans. ``spans``: per-sequence
    (qlen, kvlen); qlen=0 rows are dead. Returns the kernel's full
    argument tuple; T pads the packed buffer past the spans (dead
    packed rows)."""
    r = np.random.RandomState(seed)
    num_blocks = R * mb + 2
    pool_k = jnp.asarray(r.randn(num_blocks, bs, Hkv, D), dtype)
    pool_v = jnp.asarray(r.randn(num_blocks, bs, Hkv, D), dtype)
    perm = r.permutation(R * mb)
    tables = np.asarray(perm.reshape(R, mb), np.int32)
    qstart = np.zeros(R, np.int32)
    qlen = np.zeros(R, np.int32)
    kvlen = np.zeros(R, np.int32)
    cur = 0
    for i, (ql, kl) in enumerate(spans):
        qstart[i], qlen[i], kvlen[i] = cur, ql, kl
        cur += ql
    T = T or cur
    q = jnp.asarray(r.randn(T, H, D), dtype)
    return (q, pool_k, pool_v, jnp.asarray(tables), jnp.asarray(qstart),
            jnp.asarray(qlen), jnp.asarray(kvlen))


def _dense_span_oracle(q, pool_k, pool_v, tables, qstart, qlen, kvlen):
    """Independent oracle: per sequence, gather its logical cache dense,
    then plain masked softmax attention for its span — the exact math
    the old suffix-prefill program ran in-program. Built with the same
    op sequence so the comparison against the ragged oracle is
    BITWISE."""
    T, H, D = q.shape
    nb, bs, Hkv, _ = np.asarray(pool_k).shape
    R, mb = np.asarray(tables).shape
    G = H // Hkv
    s_tot = mb * bs
    out = np.zeros((T, H, D), np.asarray(q).dtype)
    for rr in range(R):
        ql, kl, qs = int(qlen[rr]), int(kvlen[rr]), int(qstart[rr])
        if ql == 0:
            continue
        tbl = np.minimum(np.asarray(tables)[rr], nb - 1)
        k = jnp.asarray(np.asarray(pool_k)[tbl].reshape(s_tot, Hkv, D))
        v = jnp.asarray(np.asarray(pool_v)[tbl].reshape(s_tot, Hkv, D))
        kf = (jnp.repeat(k, G, axis=1) if G > 1 else k)[None]
        vf = (jnp.repeat(v, G, axis=1) if G > 1 else v)[None]
        qs_span = q[None, qs:qs + ql]                 # [1, ql, H, D]
        # the suffix-prefill program's exact op sequence, batch of one
        logits = jnp.einsum("bqhd,bkhd->bhqk", qs_span, kf,
                            preferred_element_type=jnp.float32)
        logits = logits * (1.0 / np.sqrt(D))
        pos = kl - ql + np.arange(ql)
        mask = jnp.asarray(np.arange(s_tot)[None, :] <= pos[:, None])
        logits = jnp.where(mask[None, None], logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(mask[None, None], probs, 0.0)
        rv = jnp.asarray(np.arange(s_tot) < kl)
        vf = jnp.where(rv[None, :, None, None], vf, 0.0)
        o = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), vf)
        out[qs:qs + ql] = np.asarray(o[0])
    return out


MIXED = [(1, 40), (5, 37), (1, 3), (16, 16), (0, 0), (9, 64)]


class TestRaggedKernelParity:
    @pytest.mark.parametrize("H,Hkv,D,mb,bs", [
        (8, 2, 64, 4, 32),        # GQA group 4
        (8, 1, 64, 3, 16),        # MQA, small blocks
        (4, 4, 64, 4, 16),        # MHA
    ])
    def test_matches_reference_mixed_spans(self, H, Hkv, D, mb, bs):
        """Decode rows, multi-token chunks (1..block and beyond), a
        dead row — one invocation, all spans match the oracle."""
        spans = [(1, mb * bs), (min(5, bs), 12), (0, 0), (bs, bs),
                 (3, 2 * bs + 3), (1, 1)]
        args = _mk(len(spans), spans, H, Hkv, D, mb, bs, seed=H + bs)
        got = ragged_paged_attention_pallas(*args)
        want = ragged_attention_reference(*args)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_reference_bitwise_vs_two_program_split(self):
        """The acceptance pin, per old-program responsibility: in the
        two-program engine, span-1 rows were the DECODE program's and
        span-n rows the suffix-prefill program's. The unified oracle
        reproduces each one's output BITWISE on the same inputs —
        multi-token spans against an independently-assembled replay of
        the suffix program's op sequence, span-1 rows against the
        paged-decode reference (scrambled physical placement
        included)."""
        args = _mk(len(MIXED), MIXED, 8, 4, 16, 4, 16, seed=7)
        q, pk, pv, tbl, qs, ql, kl = args
        got = np.asarray(ragged_attention_reference(*args))
        want = _dense_span_oracle(*args)
        multi = np.concatenate(
            [np.arange(int(s), int(s) + int(n))
             for s, n, in zip(np.asarray(qs), np.asarray(ql))
             if int(n) > 1])
        assert (got[multi] == want[multi]).all()
        ones = [i for i, n in enumerate(np.asarray(ql)) if int(n) == 1]
        dec = np.asarray(paged_decode_attention_reference(
            q[np.asarray(qs)[ones]], pk, pv,
            jnp.asarray(np.asarray(tbl)[ones]),
            jnp.asarray(np.asarray(kl)[ones])))
        assert (got[np.asarray(qs)[ones]] == dec).all()

    def test_span1_bitwise_vs_paged_decode_kernel(self):
        """A span-1 row IS the old single-query kernel's row: same
        block walk, same online-softmax accumulation — pallas vs pallas
        and reference vs reference are both bitwise."""
        spans = [(1, 40), (1, 7), (1, 64)]
        q, pk, pv, tbl, qs, ql, kl = _mk(3, spans, 8, 2, 64, 4, 16,
                                         seed=3)
        got_k = np.asarray(ragged_paged_attention_pallas(
            q, pk, pv, tbl, qs, ql, kl))
        got_r = np.asarray(ragged_attention_reference(
            q, pk, pv, tbl, qs, ql, kl))
        # the packed buffer in span order == one query per sequence
        old_k = np.asarray(paged_decode_attention_pallas(
            q, pk, pv, tbl, kl))
        old_r = np.asarray(paged_decode_attention_reference(
            q, pk, pv, tbl, kl))
        assert (got_k == old_k).all()
        assert (got_r == old_r).all()

    def test_sentinel_dead_rows_and_padding_zero_and_finite(self):
        """Sentinel table tails clamp harmlessly; a dead row (qlen 0)
        and packed rows past every span come back as EXACT zeros from
        kernel and oracle alike — the engine's padded token buffer
        must never leak NaN into the residual stream."""
        spans = [(1, 20), (4, 17), (0, 0)]
        q, pk, pv, tbl, qs, ql, kl = _mk(3, spans, 8, 4, 16, 4, 8,
                                         seed=11, T=12)
        tbl = np.asarray(tbl).copy()
        nb = pk.shape[0]
        tbl[1, 3:] = nb                   # unmapped tail -> sentinel
        tbl[2, :] = nb                    # dead row: all-sentinel
        tbl = jnp.asarray(tbl)
        got = np.asarray(ragged_paged_attention_pallas(
            q, pk, pv, tbl, qs, ql, kl))
        ref = np.asarray(ragged_attention_reference(
            q, pk, pv, tbl, qs, ql, kl))
        assert np.isfinite(got).all() and np.isfinite(ref).all()
        assert (got[5:] == 0).all()       # rows past the spans
        assert (ref[5:] == 0).all()
        np.testing.assert_allclose(got[:5], ref[:5], rtol=2e-5,
                                   atol=2e-5)

    def test_bf16_io(self):
        spans = [(1, 30), (6, 22), (2, 8)]
        args = _mk(3, spans, 8, 8, 128, 2, 16, seed=13,
                   dtype=jnp.bfloat16)
        got = ragged_paged_attention_pallas(*args)
        want = ragged_attention_reference(*args)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=3e-2, atol=3e-2)

    def test_jit_and_scan_composable(self):
        """Must trace under jit inside a lax.scan over layers — the
        exact shape of the unified serving step's layer loop (per-layer
        pool slices, one shared table + span metadata)."""
        R, H, Hkv, D, mb, bs, L = 2, 4, 2, 64, 4, 16, 3
        r = np.random.RandomState(5)
        T = 6
        q = jnp.asarray(r.randn(L, T, H, D), jnp.float32)
        num_blocks = R * mb
        pk = jnp.asarray(r.randn(L, num_blocks, bs, Hkv, D), jnp.float32)
        pv = jnp.asarray(r.randn(L, num_blocks, bs, Hkv, D), jnp.float32)
        tbl = jnp.asarray(
            r.permutation(num_blocks).reshape(R, mb), jnp.int32)
        qs = jnp.asarray([0, 1], jnp.int32)
        ql = jnp.asarray([1, 5], jnp.int32)
        kl = jnp.asarray([40, 37], jnp.int32)

        @jax.jit
        def run(q, pk, pv):
            def body(carry, xs):
                qq, kk, vv = xs
                return carry + 1, ragged_paged_attention_pallas(
                    qq, kk, vv, tbl, qs, ql, kl)
            _, outs = jax.lax.scan(body, 0, (q, pk, pv))
            return outs

        outs = np.asarray(run(q, pk, pv))
        for layer in range(L):
            want = np.asarray(ragged_attention_reference(
                q[layer], pk[layer], pv[layer], tbl, qs, ql, kl))
            np.testing.assert_allclose(outs[layer], want, rtol=2e-5,
                                       atol=2e-5)

    def test_query_block_tiling_invariant(self):
        """Packed buffers larger than one query block (the kernel's
        block_q grid dim) still match — spans crossing a query-block
        boundary are handled by the masked read-modify-write."""
        spans = [(1, 33), (40, 40), (1, 60), (25, 26)]
        args = _mk(4, spans, 8, 2, 64, 4, 16, seed=17)
        got = ragged_paged_attention_pallas(*args, block_q=64)
        want = ragged_attention_reference(*args)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
