"""Distributed tests on the 8-device virtual mesh.

Reference patterns (SURVEY.md §4):
- parallel-vs-serial loss parity (TestDistBase / hybrid_parallel_mp_model.py)
- collective API correctness (test_collective_api_base.py)
- compile-only assertions on program transforms (auto-parallel tests) —
  here: collectives present/absent in the lowered HLO.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import SGD, AdamW


def _reset_fleet(**degrees):
    from paddle_tpu.parallel import mesh as mesh_mod
    mesh_mod._STATE["mesh"] = None
    s = fleet.DistributedStrategy()
    s.hybrid_configs = degrees
    fleet.init(is_collective=True, strategy=s)
    return fleet.get_hybrid_communicate_group()


def _data(n=16, din=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, din).astype(np.float32), rng.randint(0, classes, n))


class TestMeshTopology:
    def test_hybrid_mesh_shape(self):
        hcg = _reset_fleet(dp_degree=2, mp_degree=2, pp_degree=2)
        assert dict(hcg.mesh.shape) == {"dp": 2, "pp": 2, "sharding": 1,
                                        "sep": 1, "ep": 1, "mp": 2}
        assert hcg.get_model_parallel_group().nranks == 2
        assert hcg.get_data_parallel_group().nranks == 2

    def test_topology_rank_math(self):
        from paddle_tpu.parallel.fleet.topology import CommunicateTopology
        topo = CommunicateTopology(["dp", "pp", "mp"], [2, 2, 2])
        assert topo.get_rank(dp=0, pp=0, mp=1) == 1
        assert topo.get_rank(dp=1, pp=0, mp=0) == 4
        coord = topo.get_coord(5)
        assert (coord.dp, coord.pp, coord.mp) == (1, 0, 1)
        comm = topo.get_comm_list("mp")
        assert [0, 1] in comm and [4, 5] in comm


class TestCollectiveAPI:
    """Pattern B: known inputs -> exact collective results."""

    def test_all_reduce_sharded(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.parallel import mesh as mesh_mod
        mesh_mod._STATE["mesh"] = None
        mesh = mesh_mod.ensure_mesh({"dp": 8})
        # per-rank contributions 0..7 in the leading dim
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        arr = jax.device_put(x, NamedSharding(mesh, P("dp")))
        t = paddle.Tensor(arr)
        paddle.distributed.all_reduce(t)
        np.testing.assert_allclose(np.asarray(t.value), [[28.0]])

    def test_all_gather(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.parallel import mesh as mesh_mod
        mesh_mod._STATE["mesh"] = None
        mesh = mesh_mod.ensure_mesh({"dp": 8})
        x = np.arange(16, dtype=np.float32).reshape(8, 2)
        t = paddle.Tensor(jax.device_put(x, NamedSharding(mesh, P("dp"))))
        parts = paddle.distributed.all_gather(None, t)
        assert len(parts) == 8
        np.testing.assert_allclose(parts[3].numpy(), x[3:4])

    def test_barrier(self):
        from paddle_tpu.parallel import mesh as mesh_mod
        mesh_mod._STATE["mesh"] = None
        mesh_mod.ensure_mesh({"dp": 8})
        paddle.distributed.barrier()


class TestDataParallelParity:
    """Pattern A: dp-parallel loss == serial loss, step by step."""

    def test_dp8_matches_serial(self):
        paddle.seed(100)
        hcg = _reset_fleet(dp_degree=8)
        x, y = _data(n=16)
        m1 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        m2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        m2.set_state_dict(m1.state_dict())
        serial = TrainStep(m1, lambda o, l: F.cross_entropy(o, l),
                           SGD(learning_rate=0.1, parameters=m1.parameters()))
        par = TrainStep(m2, lambda o, l: F.cross_entropy(o, l),
                        SGD(learning_rate=0.1, parameters=m2.parameters()),
                        mesh=hcg.mesh)
        ls, lp = [], []
        for i in range(4):
            ls.append(float(serial.step((paddle.to_tensor(x),),
                                        (paddle.to_tensor(y),)).value))
            lp.append(float(par.step((paddle.to_tensor(x),),
                                     (paddle.to_tensor(y),)).value))
        np.testing.assert_allclose(ls, lp, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(m1[0].weight.numpy(), m2[0].weight.numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestTensorParallelParity:
    def _models(self, hcg):
        paddle.seed(200)
        serial = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        tp = nn.Sequential(
            fleet.meta_parallel.ColumnParallelLinear(8, 16, gather_output=False),
            nn.ReLU(),
            fleet.meta_parallel.RowParallelLinear(16, 4, input_is_parallel=True),
        )
        tp.set_state_dict(serial.state_dict())
        return serial, tp

    def test_mp8_matches_serial(self):
        hcg = _reset_fleet(mp_degree=8)
        serial, tp = self._models(hcg)
        x, y = _data(n=8)
        s_step = TrainStep(serial, lambda o, l: F.cross_entropy(o, l),
                           SGD(learning_rate=0.1,
                               parameters=serial.parameters()))
        t_step = TrainStep(tp, lambda o, l: F.cross_entropy(o, l),
                           SGD(learning_rate=0.1, parameters=tp.parameters()),
                           mesh=hcg.mesh)
        for i in range(3):
            ls = float(s_step.step((paddle.to_tensor(x),),
                                   (paddle.to_tensor(y),)).value)
            lt = float(t_step.step((paddle.to_tensor(x),),
                                   (paddle.to_tensor(y),)).value)
            np.testing.assert_allclose(ls, lt, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(serial[0].weight.numpy(),
                                   tp[0].weight.numpy(), rtol=1e-4, atol=1e-5)

    def test_mp_weights_actually_sharded(self):
        hcg = _reset_fleet(mp_degree=8)
        _, tp = self._models(hcg)
        step = TrainStep(tp, lambda o, l: F.cross_entropy(o, l),
                         SGD(learning_rate=0.1, parameters=tp.parameters()),
                         mesh=hcg.mesh)
        w = step.params["0.weight"]
        # column-parallel weight [8,16] sharded over mp on dim 1 -> local 8x2
        assert w.addressable_shards[0].data.shape == (8, 2)

    def test_vocab_parallel_embedding_and_ce(self):
        hcg = _reset_fleet(mp_degree=8)
        paddle.seed(201)
        V, H = 32, 16
        emb = fleet.meta_parallel.VocabParallelEmbedding(V, H)
        ref = nn.Embedding(V, H)
        ref.set_state_dict(emb.state_dict())
        idx = np.array([[1, 5, 31], [0, 2, 7]])
        out = emb(paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), ref(paddle.to_tensor(idx)).numpy(),
                                   rtol=1e-5)
        # parallel CE == plain CE
        logits = np.random.RandomState(0).randn(4, V).astype(np.float32)
        labels = np.array([1, 2, 3, 4])
        pce = fleet.meta_parallel.ParallelCrossEntropy()
        a = pce(paddle.to_tensor(logits), paddle.to_tensor(labels))
        b = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels),
                            reduction="none")
        np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-4)


class TestShardingZeRO:
    # stage 3 (param+grad+opt sharding) is the slowest compile; stages
    # 1/2 stay as the default-run ZeRO parity representatives
    @pytest.mark.parametrize("stage", [
        1, 2, pytest.param(3, marks=pytest.mark.slow)])
    def test_zero_stage_matches_serial(self, stage):
        paddle.seed(300 + stage)
        hcg = _reset_fleet(sharding_degree=8)
        x, y = _data(n=16)
        m1 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        m2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        m2.set_state_dict(m1.state_dict())
        serial = TrainStep(m1, lambda o, l: F.cross_entropy(o, l),
                           AdamW(learning_rate=0.01,
                                 parameters=m1.parameters()))
        zero = TrainStep(m2, lambda o, l: F.cross_entropy(o, l),
                         AdamW(learning_rate=0.01, parameters=m2.parameters()),
                         mesh=hcg.mesh, sharding_stage=stage)
        for i in range(3):
            ls = float(serial.step((paddle.to_tensor(x),),
                                   (paddle.to_tensor(y),)).value)
            lz = float(zero.step((paddle.to_tensor(x),),
                                 (paddle.to_tensor(y),)).value)
            np.testing.assert_allclose(ls, lz, rtol=1e-4, atol=1e-5)

    def test_stage3_params_sharded(self):
        hcg = _reset_fleet(sharding_degree=8)
        m = nn.Linear(16, 16)
        step = TrainStep(m, lambda o, l: F.mse_loss(o, l),
                         SGD(learning_rate=0.1, parameters=m.parameters()),
                         mesh=hcg.mesh, sharding_stage=3)
        w = step.params["weight"]
        assert w.addressable_shards[0].data.shape == (2, 16)

    def test_stage1_opt_state_sharded_params_replicated(self):
        hcg = _reset_fleet(sharding_degree=8)
        m = nn.Linear(16, 16)
        step = TrainStep(m, lambda o, l: F.mse_loss(o, l),
                         AdamW(learning_rate=0.1, parameters=m.parameters()),
                         mesh=hcg.mesh, sharding_stage=1)
        assert step.params["weight"].addressable_shards[0].data.shape == (16, 16)
        m1 = step.opt_state["slots"]["weight"]["moment1"]
        assert m1.addressable_shards[0].data.shape == (2, 16)

    def test_group_sharded_parallel_api(self):
        hcg = _reset_fleet(sharding_degree=8)
        m = nn.Linear(8, 8)
        opt = AdamW(learning_rate=0.01, parameters=m.parameters())
        from paddle_tpu.distributed import group_sharded_parallel
        m2, opt2 = group_sharded_parallel(m, opt, "p_g_os")
        assert m2._group_sharded_stage == 3


class TestCompileOnlyHLO:
    """Pattern 3: assert collectives in the lowered program."""

    def test_tp_step_has_allreduce(self):
        hcg = _reset_fleet(mp_degree=8)
        tp = nn.Sequential(
            fleet.meta_parallel.ColumnParallelLinear(8, 16, gather_output=False),
            nn.ReLU(),
            fleet.meta_parallel.RowParallelLinear(16, 4, input_is_parallel=True),
        )
        step = TrainStep(tp, lambda o, l: F.cross_entropy(o, l),
                         SGD(learning_rate=0.1, parameters=tp.parameters()),
                         mesh=hcg.mesh)
        x, y = _data(n=8)
        hlo = step.lower_text((paddle.to_tensor(x),), (paddle.to_tensor(y),))
        assert "all-reduce" in hlo

    def test_dp_grad_sync_present(self):
        hcg = _reset_fleet(dp_degree=8)
        m = nn.Linear(8, 4)
        step = TrainStep(m, lambda o, l: F.cross_entropy(o, l),
                         SGD(learning_rate=0.1, parameters=m.parameters()),
                         mesh=hcg.mesh)
        x, y = _data(n=8)
        hlo = step.lower_text((paddle.to_tensor(x),), (paddle.to_tensor(y),))
        assert ("all-reduce" in hlo) or ("reduce-scatter" in hlo)

    def test_hybrid_dp_mp_no_batch_allgather(self):
        """ADVICE r1: all-None activation specs in MP layers un-sharded the
        dp batch dim, forcing a batch all-gather at every MP layer. With
        P.UNCONSTRAINED on non-mp dims the dp sharding must survive — this
        forward/backward contains all-reduces but NO all-gather."""
        hcg = _reset_fleet(dp_degree=2, mp_degree=4)
        tp = nn.Sequential(
            fleet.meta_parallel.ColumnParallelLinear(8, 16, gather_output=False),
            nn.ReLU(),
            fleet.meta_parallel.RowParallelLinear(16, 4, input_is_parallel=True),
        )
        step = TrainStep(tp, lambda o, l: F.cross_entropy(o, l),
                         SGD(learning_rate=0.1, parameters=tp.parameters()),
                         mesh=hcg.mesh)
        x, y = _data(n=8)
        hlo = step.lower_text((paddle.to_tensor(x),), (paddle.to_tensor(y),))
        assert "all-reduce" in hlo
        assert "all-gather" not in hlo

    def test_hybrid_dp_mp_parity(self):
        """dp2×mp4 hybrid step matches serial (previously only dp-only and
        mp-only meshes were exercised)."""
        paddle.seed(202)
        hcg = _reset_fleet(dp_degree=2, mp_degree=4)
        serial = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        tp = nn.Sequential(
            fleet.meta_parallel.ColumnParallelLinear(8, 16, gather_output=False),
            nn.ReLU(),
            fleet.meta_parallel.RowParallelLinear(16, 4, input_is_parallel=True),
        )
        tp.set_state_dict(serial.state_dict())
        x, y = _data(n=8)
        s_step = TrainStep(serial, lambda o, l: F.cross_entropy(o, l),
                           SGD(learning_rate=0.1,
                               parameters=serial.parameters()))
        t_step = TrainStep(tp, lambda o, l: F.cross_entropy(o, l),
                           SGD(learning_rate=0.1, parameters=tp.parameters()),
                           mesh=hcg.mesh)
        for _ in range(3):
            ls = float(s_step.step((paddle.to_tensor(x),),
                                   (paddle.to_tensor(y),)).value)
            lt = float(t_step.step((paddle.to_tensor(x),),
                                   (paddle.to_tensor(y),)).value)
            np.testing.assert_allclose(ls, lt, rtol=1e-4, atol=1e-5)

    def test_serial_step_has_no_collectives(self):
        m = nn.Linear(8, 4)
        step = TrainStep(m, lambda o, l: F.cross_entropy(o, l),
                         SGD(learning_rate=0.1, parameters=m.parameters()))
        x, y = _data(n=8)
        hlo = step.lower_text((paddle.to_tensor(x),), (paddle.to_tensor(y),))
        assert "all-reduce" not in hlo


class TestMoE:
    def test_moe_forward_and_train(self):
        paddle.seed(400)
        from paddle_tpu.parallel.moe import ExpertLayer, MoELayer
        d = 16
        moe = MoELayer(d, [ExpertLayer(d, 32) for _ in range(4)],
                       gate={"type": "gshard", "top_k": 2},
                       capacity_factor=2.0)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 8, d).astype(np.float32),
            stop_gradient=False)
        out = moe(x)
        assert out.shape == [2, 8, d]
        assert moe.aux_loss is not None
        loss = out.sum() + moe.aux_loss * 0.01
        loss.backward()
        gate_grad = moe.gate.gate.weight.grad
        assert gate_grad is not None

    def test_switch_gate(self):
        paddle.seed(401)
        from paddle_tpu.parallel.moe import ExpertLayer, MoELayer
        moe = MoELayer(8, [ExpertLayer(8, 16) for _ in range(2)],
                       gate={"type": "switch"}, capacity_factor=4.0)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(4, 4, 8).astype(np.float32))
        out = moe(x)
        assert out.shape == [4, 4, 8]

    def test_capacity_ops(self):
        from paddle_tpu.parallel.moe import number_count, limit_by_capacity
        nums = paddle.to_tensor(np.array([0, 1, 1, 2, 2, 2]))
        cnt = number_count(nums, 4)
        np.testing.assert_array_equal(cnt.numpy(), [1, 2, 3, 0])


class TestDistributedCheckpoint:
    def test_save_load_roundtrip(self, tmp_path):
        from paddle_tpu.distributed import save_state_dict, load_state_dict
        m = nn.Linear(8, 8)
        sd = m.state_dict()
        save_state_dict(sd, str(tmp_path / "ckpt"))
        m2 = nn.Linear(8, 8)
        sd2 = m2.state_dict()
        load_state_dict(sd2, str(tmp_path / "ckpt"))
        np.testing.assert_allclose(m2.weight.numpy(), m.weight.numpy())

    def test_reshard_on_load(self, tmp_path):
        """Save sharded over 8, load into a differently-sharded target."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.parallel import mesh as mesh_mod
        from paddle_tpu.distributed import save_state_dict, load_state_dict
        mesh_mod._STATE["mesh"] = None
        mesh = mesh_mod.ensure_mesh({"dp": 8})
        w = np.arange(64, dtype=np.float32).reshape(8, 8)
        sharded = paddle.Tensor(jax.device_put(
            w, NamedSharding(mesh, P("dp", None))))
        save_state_dict({"w": sharded}, str(tmp_path / "ck2"))
        target = paddle.Tensor(np.zeros((8, 8), np.float32))
        load_state_dict({"w": target}, str(tmp_path / "ck2"))
        np.testing.assert_allclose(target.numpy(), w)

    def test_scalar_entries_restored(self, tmp_path):
        """ADVICE r1: optimizer scalars like '@step' were skipped on load,
        silently resetting Adam bias correction / LR schedule on resume."""
        from paddle_tpu.distributed import save_state_dict, load_state_dict
        sd = {"w": paddle.Tensor(np.ones((4,), np.float32)), "@step": 17}
        save_state_dict(sd, str(tmp_path / "ck3"))
        sd2 = {"w": paddle.Tensor(np.zeros((4,), np.float32)), "@step": 0}
        load_state_dict(sd2, str(tmp_path / "ck3"))
        assert int(sd2["@step"]) == 17
        np.testing.assert_allclose(sd2["w"].numpy(), 1.0)


class TestRecompute:
    def test_recompute_matches_plain(self):
        paddle.seed(500)
        from paddle_tpu.distributed import recompute
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 8))
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32),
                             stop_gradient=False)
        plain = m(x).sum()
        plain.backward()
        g_plain = m[0].weight.grad.numpy().copy()
        for p in m.parameters():
            p.clear_grad()
        x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
        out = recompute(m, x2).sum()
        out.backward()
        np.testing.assert_allclose(m[0].weight.grad.numpy(), g_plain,
                                   rtol=1e-5)


class TestPipelineLayerStructure:
    def test_segmentation_uniform(self):
        from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer
        descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(8)]
        pl = PipelineLayer(descs, num_stages=4)
        assert pl.segment_parts == [0, 2, 4, 6, 8]
        assert len(pl.get_stage_layers(0)) == 2

    def test_pipeline_forward_matches_sequential(self):
        paddle.seed(600)
        from paddle_tpu.distributed.fleet import PipelineLayer
        layers = [nn.Linear(8, 8) for _ in range(4)]
        pl = PipelineLayer(list(layers), num_stages=2)
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8).astype(np.float32))
        out = pl(x)
        ref = x
        for l in layers:
            ref = l(ref)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)

    def test_shared_layer_desc_ties_weights(self):
        from paddle_tpu.distributed.fleet import (LayerDesc, PipelineLayer,
                                                  SharedLayerDesc)
        descs = [
            SharedLayerDesc("emb", nn.Linear, None, "weight", 8, 8),
            LayerDesc(nn.Linear, 8, 8),
            SharedLayerDesc("emb", nn.Linear, None, "weight", 8, 8),
        ]
        pl = PipelineLayer(descs, num_stages=1)
        l0 = pl.run_function[0].shared
        l2 = pl.run_function[2].shared
        assert l0 is l2


class TestPipelineTrainBatch:
    def test_train_batch_runs_and_converges(self):
        paddle.seed(700)
        from paddle_tpu.parallel import mesh as mesh_mod
        mesh_mod._STATE["mesh"] = None
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"pp_degree": 1, "dp_degree": 1,
                            "pp_configs": {"accumulate_steps": 4,
                                           "micro_batch_size": 4}}
        fleet.init(is_collective=True, strategy=s)
        from paddle_tpu.distributed.fleet import PipelineLayer

        losses = []
        pl = PipelineLayer([nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4)],
                           num_stages=1,
                           loss_fn=lambda o, l: F.cross_entropy(o, l))
        model = fleet.distributed_model(pl)
        opt = fleet.distributed_optimizer(
            SGD(learning_rate=0.1, parameters=pl.parameters()))
        x, y = _data(n=16)
        for i in range(5):
            loss = model.train_batch([paddle.to_tensor(x), paddle.to_tensor(y)],
                                     opt)
            losses.append(float(loss.value))
        assert losses[-1] < losses[0]


class TestNewGroupAxisBinding:
    """r2 weak 7: new_group must bind to the axis whose SLICES contain the
    rank set, not just any axis of matching size."""

    def test_same_size_axes_disambiguated(self):
        import jax
        from paddle_tpu.parallel import mesh as mesh_mod
        from paddle_tpu.parallel.mesh import new_group
        mesh_mod._STATE["mesh"] = None
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                            "sharding_degree": 2}
        fleet.init(is_collective=True, strategy=s)
        mesh = mesh_mod.get_mesh()
        rank_of = {d.id: i for i, d in enumerate(jax.devices())}
        rank_arr = np.vectorize(lambda d: rank_of[d.id])(mesh.devices)
        flat = rank_arr.squeeze()
        dp_slice = [int(v) for v in np.moveaxis(flat, 0, 0).reshape(2, -1)[:, 0]]
        mp_slice = [int(v) for v in np.moveaxis(flat, -1, 0).reshape(2, -1)[:, 0]]
        assert new_group(dp_slice).axis_names == ("dp",)
        assert new_group(mp_slice).axis_names == ("mp",)

    def test_non_aligned_set_rejected(self):
        from paddle_tpu.parallel import mesh as mesh_mod
        from paddle_tpu.parallel.mesh import new_group
        mesh_mod._STATE["mesh"] = None
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                            "sharding_degree": 2}
        fleet.init(is_collective=True, strategy=s)
        with pytest.raises(ValueError, match="axis-aligned"):
            new_group([0, 7])
