"""Real pipeline-parallel schedule tests (VERDICT r1 item 2).

The r1 pp tests passed with or without a pipeline because execution was a
sequential loop. These test the actual schedule in parallel.pp:
- parity vs serial on pp2/pp4 meshes (fwd + grads)
- compile-only: collective-permute present, stage weights pp-sharded
- LLaMA end-to-end with pipeline_microbatches routed through the schedule
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW, SGD
from paddle_tpu.parallel.pp import pipeline_1f1b, pipeline_spmd


def _reset_fleet(**degrees):
    from paddle_tpu.parallel import mesh as mesh_mod
    mesh_mod._STATE["mesh"] = None
    s = fleet.DistributedStrategy()
    s.hybrid_configs = degrees
    fleet.init(is_collective=True, strategy=s)
    return fleet.get_hybrid_communicate_group()


def _layer(h, w):
    return jnp.tanh(h @ w), None


def _serial(W, x):
    y, _ = jax.lax.scan(_layer, x, W)
    return y


def _mk(L=8, H=16, B=8, seed=0):
    rng = np.random.RandomState(seed)
    W = jnp.asarray(rng.randn(L, H, H).astype(np.float32)) * 0.1
    x = jnp.asarray(rng.randn(B, H).astype(np.float32))
    return W, x


def _stage_fn(local_W, h):
    h, _ = jax.lax.scan(_layer, h, local_W)
    return h


class TestPipelineSpmd:
    @pytest.mark.parametrize("pp,m", [(2, 4), (4, 4), (8, 2)])
    def test_forward_parity(self, pp, m):
        hcg = _reset_fleet(pp_degree=pp, dp_degree=8 // pp)
        W, x = _mk()
        y0 = _serial(W, x)
        y1 = jax.jit(lambda W, x: pipeline_spmd(
            _stage_fn, W, x, num_microbatches=m, mesh=hcg.mesh))(W, x)
        np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                                   rtol=1e-5, atol=1e-6)

    def test_grad_parity(self):
        hcg = _reset_fleet(pp_degree=4, dp_degree=2)
        W, x = _mk(seed=1)

        def loss_pipe(W, x):
            return jnp.sum(jnp.sin(pipeline_spmd(
                _stage_fn, W, x, num_microbatches=4, mesh=hcg.mesh)))

        def loss_serial(W, x):
            return jnp.sum(jnp.sin(_serial(W, x)))

        gw0, gx0 = jax.grad(loss_serial, argnums=(0, 1))(W, x)
        gw1, gx1 = jax.jit(jax.grad(loss_pipe, argnums=(0, 1)))(W, x)
        np.testing.assert_allclose(np.asarray(gw0), np.asarray(gw1),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gx0), np.asarray(gx1),
                                   rtol=1e-4, atol=1e-5)

    def test_collective_permute_in_hlo(self):
        hcg = _reset_fleet(pp_degree=4, dp_degree=2)
        W, x = _mk()
        hlo = jax.jit(lambda W, x: pipeline_spmd(
            _stage_fn, W, x, num_microbatches=4,
            mesh=hcg.mesh)).lower(W, x).compile().as_text()
        assert "collective-permute" in hlo

    def test_validation_errors(self):
        hcg = _reset_fleet(pp_degree=4, dp_degree=2)
        W, x = _mk()
        with pytest.raises(ValueError, match="not divisible by microbatches"):
            pipeline_spmd(_stage_fn, W, x, num_microbatches=3, mesh=hcg.mesh)
        W6, _ = _mk(L=6)
        with pytest.raises(ValueError, match="not divisible by pp degree"):
            pipeline_spmd(_stage_fn, W6, x, num_microbatches=4, mesh=hcg.mesh)

    def test_pp1_falls_back_to_serial(self):
        _reset_fleet(dp_degree=8)
        W, x = _mk()
        y = pipeline_spmd(_stage_fn, W, x, num_microbatches=4)
        np.testing.assert_allclose(np.asarray(y), np.asarray(_serial(W, x)),
                                   rtol=1e-6)


class TestPipeline1F1BHeterogeneous:
    def test_switch_stages_parity(self):
        hcg = _reset_fleet(pp_degree=2, dp_degree=4)
        rng = np.random.RandomState(2)
        H = 8
        w0 = jnp.asarray(rng.randn(H, H).astype(np.float32)) * 0.1
        w1 = jnp.asarray(rng.randn(H, H).astype(np.float32)) * 0.1
        x = jnp.asarray(rng.randn(8, H).astype(np.float32))
        fns = [lambda p, h: jnp.tanh(h @ p),      # stage 0: tanh linear
               lambda p, h: jax.nn.relu(h @ p)]   # stage 1: relu linear
        y0 = fns[1](w1, fns[0](w0, x))
        y1 = jax.jit(lambda p, x: pipeline_1f1b(
            fns, p, x, num_microbatches=4, mesh=hcg.mesh))((w0, w1), x)
        np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                                   rtol=1e-5, atol=1e-6)

    def test_same_structure_stages_get_resident_weights(self):
        """VERDICT r3 item 4: same-pytree-structure stages must ship their
        stacked per-stage leaves sharded P('pp') into the schedule (each
        device holds ONLY its stage), falling back to replicated params
        only for structurally heterogeneous stages."""
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.parallel import pp as pp_mod
        hcg = _reset_fleet(pp_degree=2, dp_degree=4)
        rng = np.random.RandomState(5)
        H = 8
        w0 = jnp.asarray(rng.randn(H, H).astype(np.float32)) * 0.1
        w1 = jnp.asarray(rng.randn(H, H).astype(np.float32)) * 0.1
        x = jnp.asarray(rng.randn(8, H).astype(np.float32))
        fns = [lambda p, h: jnp.tanh(h @ p),
               lambda p, h: jax.nn.relu(h @ p)]
        captured = {}
        orig = pp_mod._run_schedule

        def spy(apply_fn, params, params_in_specs, *a, **k):
            captured["specs"] = params_in_specs
            return orig(apply_fn, params, params_in_specs, *a, **k)

        pp_mod._run_schedule, _saved = spy, orig
        try:
            y = jax.jit(lambda p, x: pipeline_1f1b(
                fns, p, x, num_microbatches=4, mesh=hcg.mesh))((w0, w1), x)
            assert jax.tree.leaves(captured["specs"]) == [P("pp")]
            np.testing.assert_allclose(
                np.asarray(y), np.asarray(fns[1](w1, fns[0](w0, x))),
                rtol=1e-5, atol=1e-6)
            # heterogeneous STRUCTURE: packed per-dtype buffers, still
            # sharded P('pp') — no replication (VERDICT r4 item 7)
            captured.clear()
            fns2 = [lambda p, h: jnp.tanh(h @ p),
                    lambda p, h: jax.nn.relu(h @ p[0] @ p[1])]
            p2 = (w0, (w1, jnp.eye(H)))
            y2 = jax.jit(lambda p, x: pipeline_1f1b(
                fns2, p, x, num_microbatches=4, mesh=hcg.mesh))(p2, x)
            assert all(s == P("pp")
                       for s in jax.tree.leaves(captured["specs"]))
            np.testing.assert_allclose(
                np.asarray(y2),
                np.asarray(fns2[1]((w1, jnp.eye(H)), fns2[0](w0, x))),
                rtol=1e-5, atol=1e-6)
        finally:
            pp_mod._run_schedule = _saved

    def test_heterogeneous_three_stage_residency_and_grads(self):
        """VERDICT r4 item 7: an embed->block->head pipeline with three
        DIFFERENT per-stage pytree structures must give every device only
        its own stage's weights (packed [S, L] buffers sharded 'pp' — no
        replication), with parity + grads vs serial."""
        from paddle_tpu.parallel import pp as pp_mod
        hcg = _reset_fleet(pp_degree=4, dp_degree=2)
        rng = np.random.RandomState(11)
        V, H = 12, 8
        emb = jnp.asarray(rng.randn(H, H).astype(np.float32)) * 0.1
        mkblk = lambda: {
            "w": jnp.asarray(rng.randn(H, H).astype(np.float32)) * 0.1,
            "b": jnp.zeros((H,), jnp.float32)}
        head = (jnp.asarray(rng.randn(H, V).astype(np.float32)) * 0.1,)
        # handoff contract: all stages map [B, H] float activations, so
        # the embed gather happens outside the pipeline; stage 0 is a
        # plain projection with the embedding matrix as its (unique) param
        blk_fn = lambda p, h: jnp.tanh(h @ p["w"] + p["b"])
        fns = [lambda p, h: h @ p, blk_fn, blk_fn,
               lambda p, h: jnp.sin(h @ p[0] @ jnp.ones((V, H)) * 0.1)]
        params = (emb, mkblk(), mkblk(), head)
        x = jnp.asarray(rng.randn(6, H).astype(np.float32))

        def serial(ps, x):
            h = x
            for f, p in zip(fns, ps):
                h = f(p, h)
            return h

        captured = {}
        orig = pp_mod._run_schedule

        def spy(apply_fn, params, params_in_specs, *a, **k):
            captured["specs"] = params_in_specs
            return orig(apply_fn, params, params_in_specs, *a, **k)

        pp_mod._run_schedule = spy
        try:
            y = jax.jit(lambda p, x: pipeline_1f1b(
                fns, p, x, num_microbatches=6, mesh=hcg.mesh))(params, x)
        finally:
            pp_mod._run_schedule = orig
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(serial(params, x)),
                                   rtol=1e-5, atol=1e-6)
        # every packed buffer is [S, L] sharded P('pp')
        from jax.sharding import NamedSharding, PartitionSpec as P
        assert captured["specs"] and all(
            s == P("pp") for s in jax.tree.leaves(captured["specs"]))
        # residency on device arrays: place the packed buffers with the
        # schedule's sharding and check each device holds 1/S rows
        bufs, _metas = pp_mod._pack_stages(params)
        assert jax.tree.leaves(bufs)
        for buf in jax.tree.leaves(bufs):
            placed = jax.device_put(
                buf, NamedSharding(hcg.mesh, P("pp")))
            for sh in placed.addressable_shards:
                assert sh.data.shape[0] == buf.shape[0] // 4
        # grads flow through the pack/unpack to the ORIGINAL leaves
        g_pipe = jax.jit(jax.grad(lambda p, x: jnp.sum(pipeline_1f1b(
            fns, p, x, num_microbatches=6, mesh=hcg.mesh) ** 2)))(params, x)
        g_ser = jax.grad(lambda p, x: jnp.sum(serial(p, x) ** 2))(params, x)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ser)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_switch_stages_grads(self):
        hcg = _reset_fleet(pp_degree=2, dp_degree=4)
        rng = np.random.RandomState(3)
        H = 8
        w0 = jnp.asarray(rng.randn(H, H).astype(np.float32)) * 0.1
        w1 = jnp.asarray(rng.randn(H, H).astype(np.float32)) * 0.1
        x = jnp.asarray(rng.randn(8, H).astype(np.float32))
        fns = [lambda p, h: jnp.tanh(h @ p),
               lambda p, h: jax.nn.relu(h @ p)]

        def loss_pipe(ps, x):
            return jnp.sum(jnp.sin(pipeline_1f1b(
                fns, ps, x, num_microbatches=4, mesh=hcg.mesh)))

        def loss_serial(ps, x):
            return jnp.sum(jnp.sin(fns[1](ps[1], fns[0](ps[0], x))))

        g0 = jax.grad(loss_serial)((w0, w1), x)
        g1 = jax.jit(jax.grad(loss_pipe))((w0, w1), x)
        for a, b in zip(g0, g1):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestFleetTrainBatchPipelined:
    """fleet.distributed_model(PipelineLayer).train_batch routes through the
    SPMD schedule when pp>1 and stages are homogeneous."""

    def _run(self, pp_degree, steps=4):
        import paddle_tpu.nn as nn
        from paddle_tpu.parallel import mesh as mesh_mod
        mesh_mod._STATE["mesh"] = None
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"pp_degree": pp_degree, "dp_degree": 8 // pp_degree,
                            "pp_configs": {"accumulate_steps": 4,
                                           "micro_batch_size": 4}}
        fleet.init(is_collective=True, strategy=s)
        from paddle_tpu.distributed.fleet import PipelineLayer
        paddle.seed(800)
        pl = PipelineLayer(
            [nn.Linear(8, 8) for _ in range(4)], num_stages=pp_degree,
            loss_fn=lambda o, l: F.mse_loss(o, l))
        model = fleet.distributed_model(pl)
        opt = fleet.distributed_optimizer(
            SGD(learning_rate=0.05, parameters=pl.parameters()))
        rng = np.random.RandomState(4)
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randn(16, 8).astype(np.float32)
        losses = []
        for _ in range(steps):
            loss = model.train_batch(
                [paddle.to_tensor(x), paddle.to_tensor(y)], opt)
            losses.append(float(loss.value))
        return losses, model

    def test_pp2_train_batch_matches_pp1(self):
        serial, _ = self._run(pp_degree=1)
        piped, model = self._run(pp_degree=2)
        assert model._uses_spmd_pipe
        np.testing.assert_allclose(serial, piped, rtol=1e-4, atol=1e-5)

    def test_pp4_train_batch_matches_pp1(self):
        serial, _ = self._run(pp_degree=1)
        piped, model = self._run(pp_degree=4)
        assert model._uses_spmd_pipe
        np.testing.assert_allclose(serial, piped, rtol=1e-4, atol=1e-5)

    def test_remainder_batch_does_not_freeze_decision(self):
        """A non-divisible first batch must not permanently disable the
        SPMD pipeline for later divisible batches (review finding)."""
        import paddle_tpu.nn as nn
        from paddle_tpu.parallel import mesh as mesh_mod
        mesh_mod._STATE["mesh"] = None
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"pp_degree": 2, "dp_degree": 4,
                            "pp_configs": {"accumulate_steps": 4}}
        fleet.init(is_collective=True, strategy=s)
        from paddle_tpu.distributed.fleet import PipelineLayer
        paddle.seed(802)
        pl = PipelineLayer([nn.Linear(8, 8) for _ in range(4)], num_stages=2,
                           loss_fn=lambda o, l: F.mse_loss(o, l))
        model = fleet.distributed_model(pl)
        opt = fleet.distributed_optimizer(
            SGD(learning_rate=0.05, parameters=pl.parameters()))
        rng = np.random.RandomState(6)
        x15 = rng.randn(15, 8).astype(np.float32)
        model.train_batch([paddle.to_tensor(x15),
                           paddle.to_tensor(x15.copy())], opt)
        assert not model._uses_spmd_pipe  # 15 % 4 != 0 -> fallback
        x16 = rng.randn(16, 8).astype(np.float32)
        model.train_batch([paddle.to_tensor(x16),
                           paddle.to_tensor(x16.copy())], opt)
        assert model._uses_spmd_pipe  # divisible batch re-enables

    def test_heterogeneous_shapes_fall_back(self):
        """Stage output shapes differ -> sequential fallback, still correct."""
        import paddle_tpu.nn as nn
        from paddle_tpu.parallel import mesh as mesh_mod
        mesh_mod._STATE["mesh"] = None
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"pp_degree": 2, "dp_degree": 4,
                            "pp_configs": {"accumulate_steps": 4}}
        fleet.init(is_collective=True, strategy=s)
        from paddle_tpu.distributed.fleet import PipelineLayer
        paddle.seed(801)
        pl = PipelineLayer(
            [nn.Linear(8, 16), nn.Linear(16, 8)], num_stages=2,
            loss_fn=lambda o, l: F.mse_loss(o, l))
        model = fleet.distributed_model(pl)
        opt = fleet.distributed_optimizer(
            SGD(learning_rate=0.05, parameters=pl.parameters()))
        rng = np.random.RandomState(5)
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randn(16, 8).astype(np.float32)
        loss = model.train_batch([paddle.to_tensor(x), paddle.to_tensor(y)],
                                 opt)
        assert not model._uses_spmd_pipe
        assert np.isfinite(float(loss.value))


class TestLlamaPipeline:
    def _losses(self, pp, microbatches, steps=3):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        hcg = _reset_fleet(pp_degree=pp, dp_degree=8 // pp)
        paddle.seed(42)
        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                          num_hidden_layers=4, num_attention_heads=4,
                          num_key_value_heads=4, max_position_embeddings=32,
                          use_recompute=False,
                          pipeline_microbatches=microbatches)
        model = LlamaForCausalLM(cfg)
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = TrainStep(model, lambda loss, _l: loss, opt,
                         mesh=hcg.mesh if pp > 1 else None)
        ids = paddle.to_tensor(np.random.RandomState(7).randint(
            0, 64, (8, 16)).astype(np.int32))
        out = []
        for _ in range(steps):
            out.append(float(step.step((ids, ids), (ids,)).value))
        return out, step

    def test_llama_pp2_pipeline_matches_serial(self):
        serial, _ = self._losses(pp=1, microbatches=0)
        piped, _ = self._losses(pp=2, microbatches=4)
        np.testing.assert_allclose(serial, piped, rtol=2e-4, atol=2e-5)

    def test_llama_pp4_pipeline_matches_serial(self):
        serial, _ = self._losses(pp=1, microbatches=0)
        piped, _ = self._losses(pp=4, microbatches=2)
        np.testing.assert_allclose(serial, piped, rtol=2e-4, atol=2e-5)

    def test_llama_pipeline_hlo_and_stage_residency(self):
        _, step = self._losses(pp=2, microbatches=4, steps=1)
        from paddle_tpu.models.llama import LlamaConfig
        ids = paddle.to_tensor(np.random.RandomState(7).randint(
            0, 64, (8, 16)).astype(np.int32))
        hlo = step.lower_text((ids, ids), (ids,))
        assert "collective-permute" in hlo
        # stage residency: stacked layer weights sharded over pp on dim 0
        wq = step.params["wq"]
        spec = wq.sharding.spec
        assert spec[0] == "pp" or spec[0] == ("pp",)
        # each device holds L/S = 2 of the 4 layers
        assert wq.addressable_shards[0].data.shape[0] == 2


class TestInterleavedPipeline:
    """Interleaved (virtual-stage) schedule — VERDICT r2 item 8."""

    def test_interleaved_matches_serial_pp2_v2(self):
        from paddle_tpu.parallel.pp import pipeline_interleaved
        hcg = _reset_fleet(pp_degree=2, dp_degree=4)
        W, x = _mk(L=8, H=16, B=8)

        def stage(chunk_w, h):
            h, _ = jax.lax.scan(_layer, h, chunk_w)
            return h

        out = jax.jit(lambda W, x: pipeline_interleaved(
            stage, W, x, num_microbatches=2, num_virtual=2,
            mesh=hcg.mesh))(W, x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_serial(W, x)),
                                   rtol=2e-5, atol=2e-6)

    def test_interleaved_matches_serial_pp4_v2(self):
        from paddle_tpu.parallel.pp import pipeline_interleaved
        hcg = _reset_fleet(pp_degree=4, dp_degree=2)
        W, x = _mk(L=16, H=8, B=8)

        def stage(chunk_w, h):
            h, _ = jax.lax.scan(_layer, h, chunk_w)
            return h

        out = jax.jit(lambda W, x: pipeline_interleaved(
            stage, W, x, num_microbatches=4, num_virtual=2,
            mesh=hcg.mesh))(W, x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_serial(W, x)),
                                   rtol=2e-5, atol=2e-6)

    def test_interleaved_gradients_match_serial(self):
        from paddle_tpu.parallel.pp import pipeline_interleaved
        hcg = _reset_fleet(pp_degree=2, dp_degree=4)
        W, x = _mk(L=4, H=8, B=4)

        def stage(chunk_w, h):
            h, _ = jax.lax.scan(_layer, h, chunk_w)
            return h

        def loss_pp(W):
            return (pipeline_interleaved(
                stage, W, x, num_microbatches=2, num_virtual=2,
                mesh=hcg.mesh) ** 2).sum()

        def loss_serial(W):
            return (_serial(W, x) ** 2).sum()

        g_pp = jax.jit(jax.grad(loss_pp))(W)
        g_s = jax.grad(loss_serial)(W)
        np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_s),
                                   rtol=5e-5, atol=5e-6)

    def test_interleaved_ring_permute_in_hlo(self):
        from paddle_tpu.parallel.pp import pipeline_interleaved
        hcg = _reset_fleet(pp_degree=2, dp_degree=4)
        W, x = _mk(L=4, H=8, B=4)

        def stage(chunk_w, h):
            h, _ = jax.lax.scan(_layer, h, chunk_w)
            return h

        f = jax.jit(lambda W, x: pipeline_interleaved(
            stage, W, x, num_microbatches=2, num_virtual=2, mesh=hcg.mesh))
        hlo = f.lower(W, x).compile().as_text()
        assert "collective-permute" in hlo

    def test_non_multiple_microbatches_rejected(self):
        from paddle_tpu.parallel.pp import pipeline_interleaved
        hcg = _reset_fleet(pp_degree=4, dp_degree=2)
        W, x = _mk(L=8, H=8, B=6)
        with pytest.raises(ValueError, match="multiple"):
            pipeline_interleaved(lambda w, h: h, W, x, num_microbatches=6,
                                 num_virtual=2, mesh=hcg.mesh)

    @pytest.mark.parametrize("pp,m,v", [(2, 4, 2), (2, 8, 2), (4, 8, 2),
                                        (2, 4, 4)])
    def test_interleaved_m_multiple_of_s_matches_serial(self, pp, m, v):
        """VERDICT r3 item 4: M = k*S is the regime that actually shrinks
        the bubble at scale (the reference constrains M to multiples of S
        †); group g's final-pass wrap must land exactly on group g+1's
        injection ticks."""
        from paddle_tpu.parallel.pp import pipeline_interleaved
        hcg = _reset_fleet(pp_degree=pp, dp_degree=8 // pp)
        W, x = _mk(L=pp * v * 2, H=8, B=m * 2, seed=pp + m + v)

        def stage(chunk_w, h):
            h, _ = jax.lax.scan(_layer, h, chunk_w)
            return h

        out = jax.jit(lambda W, x: pipeline_interleaved(
            stage, W, x, num_microbatches=m, num_virtual=v,
            mesh=hcg.mesh))(W, x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_serial(W, x)),
                                   rtol=2e-5, atol=2e-6)

    def test_interleaved_m_multiple_grads_match_serial(self):
        from paddle_tpu.parallel.pp import pipeline_interleaved
        hcg = _reset_fleet(pp_degree=2, dp_degree=4)
        W, x = _mk(L=8, H=8, B=8, seed=9)

        def stage(chunk_w, h):
            h, _ = jax.lax.scan(_layer, h, chunk_w)
            return h

        def loss_pp(W):
            return (pipeline_interleaved(
                stage, W, x, num_microbatches=4, num_virtual=2,
                mesh=hcg.mesh) ** 2).sum()

        def loss_serial(W):
            return (_serial(W, x) ** 2).sum()

        g_pp = jax.jit(jax.grad(loss_pp))(W)
        g_s = jax.grad(loss_serial)(W)
        np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_s),
                                   rtol=5e-5, atol=5e-6)


class TestLlamaInterleaved:
    def test_llama_interleaved_pp2_matches_serial(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        def losses(pp, micro, virtual):
            hcg = _reset_fleet(pp_degree=pp, dp_degree=8 // pp)
            paddle.seed(43)
            cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                              intermediate_size=64, num_hidden_layers=4,
                              num_attention_heads=4, num_key_value_heads=4,
                              max_position_embeddings=32, use_recompute=False,
                              pipeline_microbatches=micro,
                              pipeline_virtual_stages=virtual)
            model = LlamaForCausalLM(cfg)
            opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
            step = TrainStep(model, lambda loss, _l: loss, opt,
                             mesh=hcg.mesh if pp > 1 else None)
            ids = paddle.to_tensor(np.random.RandomState(7).randint(
                0, 64, (8, 16)).astype(np.int32))
            return [float(step.step((ids, ids), (ids,)).value)
                    for _ in range(3)]

        serial = losses(1, 0, 1)
        inter = losses(2, 2, 2)
        np.testing.assert_allclose(serial, inter, rtol=2e-4, atol=2e-5)
