"""Multi-tenant SLO serving (serving/policy/, README "Multi-tenant SLO
serving"): priority classes, deadline-aware admission, SLO-driven
preemption, and the class-headroom fleet signals.

The acceptance matrix:

- the CLASS TABLE parses the CLI spec (ranks descend with position,
  ``*`` default marker, ``:k`` reserved headroom, aligned ms target
  lists), resolves unknown names to a ValueError (the HTTP 400, never
  a driver crash), and the default single-class table is INACTIVE —
  the engine keeps the plain FIFO scheduler and every banked baseline
  stays byte-identical;
- ADMISSION order under the PolicyScheduler is (effective class rank,
  TTFT deadline slack, FIFO tick), deterministic under a VirtualClock;
  within one class it collapses to exact FIFO; anti-starvation aging
  promotes a long-waiting batch request one rank per quantum;
- HEADROOM: reserved slots are held back from other classes, and the
  reserving class admits into its own reservation first;
- PREEMPTION: an SLO-urgent latency request displaces running
  best-effort work through the ordinary preemption-by-recompute path
  — victim streams BYTE-IDENTICAL after restore (greedy AND seeded),
  ``decode_compilations() == 1`` throughout, equals never displace
  equals, and a fixed virtual-time schedule replays identically;
- the /metrics surface gains ``class``-labeled latency series plus the
  ``serving_slo_misses_total`` / ``serving_policy_preemptions_total``
  counters ONLY when a table is active (policy-off scrapes keep their
  exact label shape);
- fleet: ``class_pressure`` ranks preemptible-load replicas first and
  the ``class-headroom`` router stays pure/deterministic.
"""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (ClassTable, ContinuousBatchingEngine,
                                FIFOScheduler, GenerationRequest,
                                PolicyScheduler, PriorityClass,
                                VirtualClock)
from paddle_tpu.serving.policy import select_victims, victim_key
from paddle_tpu.serving.server import serve

from test_metrics_prom import parse_prometheus

BS = 8       # KV block size
CHUNK = 16   # chunked-prefill budget
SLOTS = 2
S_MAX = 96

#: the canonical three-way split the README documents
SPEC = dict(classes="latency:1,standard,batch*",
            slo_ttft_ms="80,400,0", slo_tpot_ms="50,0,0")
#: same tiers, no reserved headroom — the engine preemption tests want
#: batch work to be ABLE to fill every slot first
SPEC_NO_RESERVE = dict(SPEC, classes="latency,standard,batch*")


@pytest.fixture(scope="module")
def model():
    paddle.seed(33)
    return LlamaForCausalLM(llama_tiny())  # GQA tiny, pallas decode


def _prompt(seed, n=12):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _req(ps, n=12, **kw):
    kw.setdefault("max_new_tokens", 8)
    return GenerationRequest(prompt=_prompt(ps, n), **kw)


def _clone(r, drop_class=False):
    return GenerationRequest(
        prompt=r.prompt, max_new_tokens=r.max_new_tokens,
        temperature=r.temperature, top_k=r.top_k,
        eos_token_id=r.eos_token_id, seed=r.seed,
        priority_class=None if drop_class else r.priority_class)


def _engine(model, **kw):
    kw.setdefault("jit_cache", model.__dict__.setdefault("_serving_jit", {}))
    kw.setdefault("num_slots", SLOTS)
    kw.setdefault("max_seq_len", S_MAX)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    return ContinuousBatchingEngine(model, **kw)


def _baseline(model, reqs, **kw):
    """Policy-off single-class oracle streams for the same requests."""
    eng = _engine(model, **kw)
    return [o.tolist() for o in
            eng.generate([_clone(r, drop_class=True) for r in reqs])]


def _drive(eng, clk, dt=0.001):
    while eng.has_work():
        eng.step()
        clk.advance(dt)


# ------------------------------------------------------- class table units
class TestClassTable:
    def test_parse_canonical_three_way_spec(self):
        t = ClassTable.parse(**SPEC)
        assert [c.name for c in t] == ["latency", "standard", "batch"]
        assert [c.rank for c in t] == [2, 1, 0]     # descend with position
        lat, std, bat = t.classes
        assert lat.reserved_slots == 1 and std.reserved_slots == 0
        assert lat.ttft_slo_s == pytest.approx(0.08)
        assert std.ttft_slo_s == pytest.approx(0.4)
        assert bat.ttft_slo_s is None               # 0 = no target
        assert lat.tpot_slo_s == pytest.approx(0.05)
        assert t.default == "batch"                 # the '*' marker
        assert t.active
        rows = t.doc()                              # the banner surface
        assert rows[0]["ttft_slo_ms"] == 80 and rows[0]["rank"] == 2
        assert [r["default"] for r in rows] == [False, False, True]

    def test_parse_default_falls_to_last_and_rejects_bad_specs(self):
        assert ClassTable.parse("gold,best-effort").default == "best-effort"
        with pytest.raises(ValueError, match="two defaults"):
            ClassTable.parse("a*,b*")
        with pytest.raises(ValueError, match="bad class name"):
            ClassTable.parse("a,!b")
        with pytest.raises(ValueError, match="duplicate"):
            ClassTable.parse("a,a")
        with pytest.raises(ValueError, match="targets"):
            ClassTable.parse("a,b", slo_ttft_ms="1,2,3")
        with pytest.raises(ValueError, match=">= 0"):
            ClassTable.parse("a,b", slo_ttft_ms="-5")

    def test_resolve_unknown_is_the_400_valueerror(self):
        t = ClassTable.parse(**SPEC)
        assert t.resolve(None).name == "batch"      # unlabeled -> default
        assert t.resolve("latency").rank == 2
        with pytest.raises(ValueError, match="unknown priority_class"):
            t.resolve("gold")
        with pytest.raises(ValueError, match="batch.*latency.*standard"):
            t.resolve("gold")                       # names the closed set

    def test_neutral_single_table_is_inactive(self):
        """The byte-identity gate: no knobs -> no policy scheduler."""
        assert not ClassTable.single().active
        assert not ClassTable.coerce(None).active
        assert not ClassTable.parse("standard").active
        # any target, reservation, or second class flips it on
        assert ClassTable.parse("standard", slo_ttft_ms="100").active
        assert ClassTable.parse("standard:1").active
        assert ClassTable.parse("a,b").active


# ----------------------------------------------------- victim choice units
class _Slot:
    """Victim-facing stand-in for a running sequence."""

    def __init__(self, rid, rank, t_admitted, ntok, done=False):
        self.request_id = rid
        self.pclass = PriorityClass(f"c{rank}", rank=rank)
        self.t_admitted = t_admitted
        self.tokens = [0] * ntok
        self.done = done


class TestVictimSelection:
    def test_lowest_class_then_most_recent_then_least_work(self):
        slots = [
            _Slot(1, rank=1, t_admitted=1.0, ntok=2),   # higher class
            _Slot(2, rank=0, t_admitted=5.0, ntok=9),   # recent, much work
            _Slot(3, rank=0, t_admitted=9.0, ntok=4),   # most recent
            _Slot(4, rank=0, t_admitted=9.0, ntok=2),   # tie: least lost
            None,
            _Slot(5, rank=0, t_admitted=99.0, ntok=0, done=True),
        ]
        got = select_victims(slots, 3, below_rank=2)
        assert [s.request_id for s in got] == [4, 3, 2]  # never 1 first
        # strictly-below filter: rank 1 work is untouchable at rank 1
        assert select_victims(slots, 1, below_rank=1)[0].request_id == 4
        assert select_victims(slots, 9, below_rank=0) == []

    def test_victim_key_total_order_is_deterministic(self):
        a = _Slot(7, rank=0, t_admitted=3.0, ntok=5)
        b = _Slot(8, rank=0, t_admitted=3.0, ntok=5)
        assert victim_key(a) != victim_key(b)   # request_id tiebreak
        assert sorted([b, a], key=victim_key)[0].request_id == 8


# ------------------------------------------------- policy scheduler units
class _Q:
    """Scheduler-facing stand-in for a queued sequence."""

    _next_id = 0

    def __init__(self, pclass, t_submit, work_len=12):
        _Q._next_id += 1
        self.request_id = _Q._next_id
        self.pclass = pclass
        self.t_submit = t_submit
        self.work_len = work_len
        self.prefix_hit_tokens = 0
        self.done = False


def _sched(table, clk, **kw):
    return PolicyScheduler(decode_chunk=1, table=table, clock=clk, **kw)


class TestPolicyScheduler:
    def test_admission_orders_by_class_then_slack_then_fifo(self):
        t = ClassTable.parse(**dict(SPEC, classes="latency,standard,batch*"))
        clk = VirtualClock()
        s = _sched(t, clk)
        lat, std, bat = t.classes
        old_std = _Q(std, t_submit=0.0)     # waited longest: least slack
        new_std = _Q(std, t_submit=0.2)
        b1, b2 = _Q(bat, t_submit=0.0), _Q(bat, t_submit=0.1)
        late_lat = _Q(lat, t_submit=0.3)    # newest, highest class
        for q in (b1, b2, old_std, new_std, late_lat):
            s.submit(q)
        clk.advance(0.35)
        got = s.admissions(5)
        # class rank first; slack orders within standard; batch (no
        # target, equal inf slack) keeps exact FIFO by queue_tick
        assert got == [late_lat, old_std, new_std, b1, b2]
        assert s.num_queued == 0

    def test_single_class_collapses_to_exact_fifo(self):
        """Neutral table + PolicyScheduler == FIFOScheduler order (the
        scheduler-level half of the byte-identity story)."""
        clk = VirtualClock()
        s = _sched(ClassTable.single(), clk)
        f = FIFOScheduler(decode_chunk=1)
        std = ClassTable.single().classes[0]
        qs = [_Q(std, t_submit=0.01 * i) for i in range(6)]
        for q in qs:
            s.submit(q)
            f.submit(q)
        clk.advance(1.0)
        assert s.admissions(4) == f.admissions(4)
        assert s.admissions(4) == f.admissions(4)

    def test_aging_promotes_starved_batch_one_rank_per_quantum(self):
        """A steady latency arrival stream never permanently starves
        batch: each full aging quantum waited raises the EFFECTIVE
        admission rank by one, and two quanta outrank a fresh latency
        request outright."""
        t = ClassTable.parse("latency,batch*", slo_ttft_ms="500,0",
                             aging_s=10.0)
        clk = VirtualClock()
        s = _sched(t, clk)
        lat, bat = t.classes
        starved = _Q(bat, t_submit=0.0)
        s.submit(starved)
        s.submit(_Q(lat, t_submit=0.0))
        clk.advance(5.0)        # < one quantum: class order holds
        assert s.effective_rank(starved, clk()) == 0
        assert [q.pclass.name for q in s.admissions(1)] == ["latency"]
        s.submit(_Q(lat, t_submit=clk()))
        clk.advance(7.0)        # starved waited 12s = one quantum
        assert s.effective_rank(starved, clk()) == 1
        # equal effective rank: slack decides — the fresh latency
        # request's 500ms target is blown (negative slack beats inf)
        assert [q.pclass.name for q in s.admissions(1)] == ["latency"]
        s.submit(_Q(lat, t_submit=clk()))
        clk.advance(9.0)        # starved at 21s = two quanta; the
        assert s.effective_rank(starved, clk()) == 2    # fresh one at 0
        assert s.admissions(1) == [starved]     # batch finally drains

    def test_reserved_headroom_holds_slots_for_the_reserving_class(self):
        t = ClassTable.parse("latency:1,batch*")
        clk = VirtualClock()
        running = {"latency": 0}
        s = _sched(t, clk, slot_usage=lambda: dict(running))
        lat, bat = t.classes
        flood = [_Q(bat, t_submit=0.0) for _ in range(3)]
        for q in flood:
            s.submit(q)
        # 2 free slots, latency owed 1: the batch flood gets exactly 1
        assert s.admissions(2) == flood[:1]
        assert s.num_queued == 2
        # the reserving class admits INTO its reservation
        hot = _Q(lat, t_submit=0.0)
        s.submit(hot)
        got = s.admissions(1)
        assert got == [hot]
        # reservation satisfied by running work: batch flows again
        running["latency"] = 1
        assert s.admissions(2) == flood[1:]

    def test_urgent_names_only_ttft_classes_past_the_fraction(self):
        t = ClassTable.parse(**SPEC)
        clk = VirtualClock()
        s = _sched(t, clk)      # urgency_frac 0.5 default
        lat, std, bat = t.classes
        hot = _Q(lat, t_submit=0.0)
        warm = _Q(lat, t_submit=0.05)
        never = _Q(bat, t_submit=0.0)   # no TTFT target: never urgent
        for q in (hot, warm, never):
            s.submit(q)
        clk.advance(0.041)      # hot waited 41ms >= 80*0.5; warm hasn't
        assert s.urgent() == [hot]
        clk.advance(0.05)
        assert s.urgent() == [hot, warm]
        with pytest.raises(ValueError, match="urgency_frac"):
            _sched(t, clk, urgency_frac=0.0)

    def test_queue_object_identity_survives_admission(self):
        """The gateway snapshots ``scheduler.queue`` — the policy
        scheduler must mutate it in place, never rebind it."""
        t = ClassTable.parse("a,b*")
        s = _sched(t, VirtualClock())
        q0 = s.queue
        for q in [_Q(t.classes[1], 0.0) for _ in range(3)]:
            s.submit(q)
        s.admissions(2)
        assert s.queue is q0 and len(s.queue) == 1


# -------------------------------------------------- engine-level behavior
class TestEnginePolicy:
    def test_default_engine_keeps_fifo_and_streams_byte_identical(self, model):
        """No policy knobs (or an inactive single-class spec) -> the
        plain FIFOScheduler, no policy counters moving, and tokens
        byte-identical to the baseline."""
        reqs = [_req(1), _req(2, temperature=0.9, top_k=5, seed=123)]
        want = _baseline(model, reqs)
        eng = _engine(model, priority_classes="standard")
        assert type(eng.scheduler) is FIFOScheduler
        assert not eng.classes.active
        got = [o.tolist() for o in eng.generate([_clone(r) for r in reqs])]
        assert got == want
        assert eng.stats["policy_preemptions"] == 0

    def test_labeled_requests_resolve_and_unknown_is_valueerror(self, model):
        eng = _engine(model, priority_classes=ClassTable.parse(**SPEC))
        assert isinstance(eng.scheduler, PolicyScheduler)
        seq = eng.submit(_req(3, priority_class="latency"))
        assert seq.pclass.name == "latency" and seq.pclass.rank == 2
        unlabeled = eng.submit(_req(4))
        assert unlabeled.pclass.name == "batch"     # the '*' default
        with pytest.raises(ValueError, match="unknown priority_class"):
            eng.submit(_req(5, priority_class="gold"))
        _drive(eng, VirtualClock())

    def test_slo_urgent_latency_preempts_batch_byte_identically(self, model):
        """THE tentpole pin: a latency request that burns past half its
        TTFT budget displaces running batch work by recompute; all
        three streams — greedy batch, SEEDED batch, latency — finish
        byte-identical to their policy-off baselines, and the whole
        episode adds zero decode traces."""
        clk = VirtualClock()
        reqs = [_req(6, max_new_tokens=16, priority_class="batch"),
                _req(7, max_new_tokens=16, temperature=0.9, top_k=5,
                     seed=123, priority_class="batch"),
                _req(8, n=8, max_new_tokens=4, priority_class="latency")]
        want = [_baseline(model, [r])[0] for r in reqs]
        eng = _engine(model, step_clock=clk, jit_cache={},
                      priority_classes=ClassTable.parse(**SPEC_NO_RESERVE))
        b1, b2 = eng.submit(_clone(reqs[0])), eng.submit(_clone(reqs[1]))
        for _ in range(3):          # both batch rows running mid-decode
            eng.step()
            clk.advance(0.001)
        assert b1.status == "running" and b2.status == "running"
        lat = eng.submit(_clone(reqs[2]))
        assert eng.stats["policy_preemptions"] == 0
        clk.advance(0.05)           # 50ms >= 80ms * 0.5: urgent now
        eng.step()
        assert eng.stats["policy_preemptions"] == 1
        assert lat.slot is not None     # admitted into the freed slot
        victims = [s for s in (b1, b2) if s.status == "queued"]
        assert len(victims) == 1        # exactly one displaced
        _drive(eng, clk)
        got = [s.tokens for s in (b1, b2, lat)]
        assert got == want              # byte-identical incl. the victim
        assert eng.stats["restores"] >= 1
        assert eng.decode_compilations() == 1
        assert eng.cache.num_free == eng.num_slots

    def test_equals_never_displace_equals(self, model):
        """Urgent latency work never preempts running latency work —
        it waits for a natural slot."""
        clk = VirtualClock()
        eng = _engine(model, step_clock=clk,
                      priority_classes=ClassTable.parse(**SPEC))
        hogs = [eng.submit(_req(10 + i, max_new_tokens=10,
                                priority_class="latency"))
                for i in range(SLOTS)]
        eng.step()
        clk.advance(0.001)
        waiter = eng.submit(_req(15, priority_class="latency"))
        clk.advance(1.0)            # far past the whole TTFT budget
        eng.step()
        assert eng.stats["policy_preemptions"] == 0
        assert all(h.status == "running" for h in hogs)
        _drive(eng, clk)
        assert waiter.finish_reason == "length"

    def test_mixed_class_chaos_matrix_replays_deterministically(self, model):
        """A fixed virtual-time schedule of mixed-class traffic (bursts,
        preemptions, aging in play) loses ZERO requests and produces
        IDENTICAL streams, admission orders, and preemption counts on
        every replay."""
        def run():
            clk = VirtualClock()
            eng = _engine(model, step_clock=clk,
                          priority_classes=ClassTable.parse(
                              **SPEC_NO_RESERVE))
            seqs = [eng.submit(_req(20 + i, max_new_tokens=12,
                                    priority_class="batch"))
                    for i in range(3)]
            for _ in range(2):
                eng.step()
                clk.advance(0.002)
            seqs.append(eng.submit(_req(30, max_new_tokens=6,
                                        temperature=0.8, top_k=7, seed=11,
                                        priority_class="standard")))
            seqs.append(eng.submit(_req(31, n=8, max_new_tokens=4,
                                        priority_class="latency")))
            clk.advance(0.06)       # latency urgent, standard not yet
            for _ in range(4):
                eng.step()
                clk.advance(0.02)
            seqs.append(eng.submit(_req(32, n=8, max_new_tokens=4,
                                        priority_class="latency")))
            _drive(eng, clk, dt=0.02)
            return ([s.tokens for s in seqs],
                    [s.finish_reason for s in seqs],
                    eng.stats["policy_preemptions"], eng.stats["restores"])

        first, second = run(), run()
        assert first == second              # the replay pin
        toks, reasons, preempts, restores = first
        assert all(r in ("length", "stop") for r in reasons)  # 0 lost
        assert preempts >= 1 and restores >= preempts


# ------------------------------------------------------ HTTP + metrics
def _post(server, payload, headers=(), timeout=120):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        server.url + "/v1/completions", data=body,
        headers={"Content-Type": "application/json", **dict(headers)})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def _get(server, path, timeout=10):
    with urllib.request.urlopen(server.url + path, timeout=timeout) as r:
        return r.read().decode()


@pytest.fixture(scope="module")
def policy_server(model):
    srv = serve(model, port=0, num_slots=SLOTS, max_seq_len=S_MAX,
                max_queue=8, model_name="slo-test", **SPEC)
    yield srv
    srv.shutdown(drain=False, timeout=30)


class TestPolicyHTTP:
    def test_unknown_priority_class_is_a_400_not_a_crash(self, policy_server):
        status, doc = _post(policy_server, {
            "prompt": _prompt(40).tolist(), "max_tokens": 2,
            "priority_class": "gold"})
        assert status == 400
        assert doc["error"]["type"] == "invalid_request"
        assert "unknown priority_class" in doc["error"]["message"]
        # the engine is alive and still serving after the rejection
        status, doc = _post(policy_server, {
            "prompt": _prompt(40).tolist(), "max_tokens": 2})
        assert status == 200

    def test_body_field_and_header_both_select_the_class(self, policy_server):
        for extra in ({"priority_class": "latency"}, {}):
            headers = () if extra else \
                (("X-Priority-Class", "latency"),)
            status, doc = _post(policy_server, {
                "prompt": _prompt(41).tolist(), "max_tokens": 3, **extra},
                headers=headers)
            assert status == 200
            assert len(doc["choices"][0]["token_ids"]) == 3

    def test_metrics_scrape_carries_class_labels_and_policy_series(
            self, policy_server):
        _post(policy_server, {"prompt": _prompt(42).tolist(),
                              "max_tokens": 3, "priority_class": "latency"})
        fams = parse_prometheus(_get(policy_server, "/metrics"))
        # the new counters strict-parse, zero-seeded per class so the
        # series exist (and stay monotonic) before any miss/preemption
        miss = fams["serving_slo_misses_total"]
        assert miss["type"] == "counter"
        labels = {lab for (_, lab) in miss["samples"]}
        for cls in ("latency", "standard", "batch"):
            for slo in ("ttft", "tpot"):
                assert (("class", cls), ("slo", slo)) in labels
        pre = fams["serving_policy_preemptions_total"]["samples"]
        assert (("serving_policy_preemptions_total",
                 (("victim_class", "batch"),)) in pre)
        # the latency histograms carry the class label when policy is on
        ttft = fams["serving_ttft_seconds"]["samples"]
        assert any(name == "serving_ttft_seconds_count"
                   and ("class", "latency") in lab
                   for (name, lab) in ttft)

    def test_policy_off_scrape_keeps_the_unlabeled_shape(self, model):
        """The metrics back-compat gate: without a class table the
        histograms keep their EMPTY label tuples and the policy
        families are absent entirely."""
        srv = serve(model, port=0, num_slots=SLOTS, max_seq_len=S_MAX,
                    max_queue=8, model_name="plain")
        try:
            _post(srv, {"prompt": _prompt(43).tolist(), "max_tokens": 2})
            fams = parse_prometheus(_get(srv, "/metrics"))
            assert "serving_slo_misses_total" not in fams
            assert "serving_policy_preemptions_total" not in fams
            ttft = fams["serving_ttft_seconds"]["samples"]
            assert ttft[("serving_ttft_seconds_count", ())] > 0
        finally:
            srv.shutdown(drain=False, timeout=30)

    def test_debug_requests_gains_class_and_slack_columns(
            self, policy_server):
        gw = policy_server.gateway
        hogs = [gw.submit(_req(50 + i, max_new_tokens=40,
                               priority_class="batch"))
                for i in range(SLOTS)]
        waiter = gw.submit(_req(55, max_new_tokens=2,
                                priority_class="latency"))
        deadline = time.monotonic() + 10
        rows = []
        while time.monotonic() < deadline:
            rows = json.loads(_get(policy_server,
                                   "/debug/requests"))["requests"]
            if len(rows) >= 2:
                break
            time.sleep(0.01)
        by_class = {}
        for row in rows:
            assert "class" in row and "slo_slack_s" in row
            by_class.setdefault(row["class"], []).append(row)
        assert "batch" in by_class
        for row in by_class["batch"]:
            assert row["slo_slack_s"] is None       # no TTFT target
        for s in hogs + [waiter]:
            s.result()


# ------------------------------------------------------------ fleet units
class _StubReplica:
    """Router-facing stand-in with fixed load + class pressure."""

    def __init__(self, index, load, pressure):
        self.index = index
        self._load = load
        self._pressure = pressure
        self.routable = True
        self.alive = True

    def load(self):
        return self._load

    def class_pressure(self, request):
        return self._pressure


class TestClassHeadroomRouter:
    def test_ranks_by_pressure_then_load_then_index(self):
        from paddle_tpu.serving.fleet import (ClassHeadroomRouter,
                                              make_router)
        r = make_router("class-headroom")
        assert isinstance(r, ClassHeadroomRouter)
        # a busy-but-preemptible replica beats an idle-looking one
        # saturated with same-class work; ties fall to load, then index
        reps = [_StubReplica(0, load=9, pressure=4),
                _StubReplica(1, load=2, pressure=4),
                _StubReplica(2, load=50, pressure=0),
                _StubReplica(3, load=2, pressure=4)]
        order = r.rank(_req(60), reps)
        assert [x.index for x in order] == [2, 1, 3, 0]

    def test_fleet_replica_pressure_and_debug_row(self, model):
        """End-to-end replica signals: a replica whose slots hold batch
        work shows ZERO pressure to a latency request (all displaceable)
        and full pressure to a batch one; /debug/fleet rows gain the
        per-class occupancy + preemption columns only when policy is
        on."""
        from paddle_tpu.serving.fleet import EngineFleet
        fleet = EngineFleet(
            model, replicas=2, router="class-headroom", num_slots=SLOTS,
            max_seq_len=S_MAX, prefix_block_size=BS, prefill_chunk=CHUNK,
            max_queue=8, start=False, priority_classes=ClassTable.parse(
                **SPEC))
        try:
            assert fleet.classes.active
            rep = fleet.replicas[0]
            eng = rep.gateway.engine
            assert isinstance(eng.scheduler, PolicyScheduler)
            # table is shared fleet-wide, not re-parsed per replica
            assert all(r.gateway.engine.classes is fleet.classes
                       for r in fleet.replicas)
            b = eng.submit(_req(61, max_new_tokens=6,
                                priority_class="batch"))
            eng.step()
            assert rep.class_counts() == {"batch": 1}
            assert rep.class_pressure(_req(62, priority_class="latency")) == 0
            assert rep.class_pressure(_req(63, priority_class="batch")) == 1
            row = rep.row()
            assert row["classes"] == {"batch": 1}
            assert row["policy_preemptions"] == 0
            while eng.has_work():
                eng.step()
            assert b.finish_reason == "length"
        finally:
            fleet.shutdown(drain=False, timeout=30)
