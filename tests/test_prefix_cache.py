"""Automatic prefix caching (serving/prefix_cache.py + block_manager.py):
block-granular KV reuse across requests sharing prompt prefixes.

The load-bearing properties:

- **Transparency**: token streams with the cache on are byte-identical
  to the cache-disabled engine — greedy AND seeded sampled — across
  hits, misses, evictions, and COW divergence. The cache changes WHERE
  prefix KV comes from (pool copy + suffix prefill vs full prefill),
  never what gets sampled.
- **Compile-once survives caching**: mixed traffic keeps
  ``decode_compilations() == 1``; the prefill (cold + suffix) and
  block-copy compile sets are bounded by geometry, not traffic.
- **Ref-count lifecycle**: matched chains are pinned for the sequence
  lifetime, pins drain to zero at retirement, pinned blocks never
  evict, and pool occupancy never exceeds the block budget.
- **LRU eviction** under pool pressure degrades hit-rate, never
  correctness; exhausted-pool publishes skip instead of failing.
"""
import collections
import zlib

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (BlockManager, ContinuousBatchingEngine,
                                GenerationRequest, PrefixCache)
from paddle_tpu.serving.kv_cache import copy_compilations

from test_metrics_prom import parse_prometheus

BS = 8  # block_size for every engine here (tiny model, short prompts)


@pytest.fixture(scope="module")
def model():
    paddle.seed(21)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


def _engine(model, prefix_cache=True, **kw):
    kw.setdefault("jit_cache", model.__dict__.setdefault("_serving_jit", {}))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("decode_chunk", 1)
    # this module pins the DENSE prefix-cache semantics (install-copy,
    # publish-under-pressure skip, pool-as-budget) — the paged default
    # has its own matrix in test_paged_attention/test_chunked_prefill
    kw.setdefault("paged_attn", False)
    if prefix_cache:
        kw.setdefault("prefix_block_size", BS)
    return ContinuousBatchingEngine(model, prefix_cache=prefix_cache, **kw)


_SYS = np.random.RandomState(7).randint(0, 256, (20,)).astype(np.int32)


def _req(tail_seed, n_tail=6, sys_prompt=_SYS, **kw):
    """Shared-system-prompt request: 20 shared tokens + a unique tail."""
    tail = np.random.RandomState(tail_seed).randint(
        0, 256, (n_tail,)).astype(np.int32)
    kw.setdefault("max_new_tokens", 6)
    return GenerationRequest(prompt=np.concatenate([sys_prompt, tail]), **kw)


def _clone(req):
    return GenerationRequest(
        prompt=req.prompt, max_new_tokens=req.max_new_tokens,
        temperature=req.temperature, top_k=req.top_k,
        eos_token_id=req.eos_token_id, seed=req.seed)


def _cold_run(model, reqs, **kw):
    eng = _engine(model, prefix_cache=False, **kw)
    return [o.tolist() for o in eng.generate([_clone(r) for r in reqs])]


class TestTransparency:
    def test_hit_stream_identical_greedy_and_sampled(self, model):
        """Requests sharing the system prompt: the later ones hit the
        published chain yet stream the exact cold-engine tokens —
        greedy and seeded-sampled both (same PRNG split walk)."""
        reqs = [_req(1), _req(2),
                _req(3, temperature=0.9, top_k=5, seed=123),
                _req(4, temperature=0.7, top_k=3, seed=9)]
        want = _cold_run(model, reqs)
        eng = _engine(model)
        got = [o.tolist() for o in eng.generate([_clone(r) for r in reqs])]
        assert got == want
        pc = eng.prefix_cache
        assert pc.stats["hits"] >= 2          # later admissions reused
        assert pc.stats["hit_tokens"] >= 2 * BS
        assert eng.stats["prefill_tokens_saved"] == pc.stats["hit_tokens"]
        # hits really skipped device prefill work
        assert eng.stats["prefill_tokens"] == \
            sum(len(r.prompt) for r in reqs) - pc.stats["hit_tokens"]

    def test_full_block_prompt_leaves_final_token_uncovered(self, model):
        """A prompt that is an exact block multiple of a cached chain
        still prefills >= 1 token (the engine samples token 0 from the
        suffix logits): lookup never covers the final prompt token."""
        prompt = np.random.RandomState(40).randint(
            0, 256, (2 * BS,)).astype(np.int32)  # exactly 2 blocks
        reqs = [GenerationRequest(prompt=prompt, max_new_tokens=5),
                GenerationRequest(prompt=prompt.copy(), max_new_tokens=5)]
        want = _cold_run(model, reqs)
        eng = _engine(model)
        a = eng.generate([_clone(reqs[0])])[0]
        b = eng.generate([_clone(reqs[1])])[0]
        assert [a.tolist(), b.tolist()] == want
        # second run matched only 1 block: final block holds the last
        # prompt token, which must go through the suffix prefill
        assert eng.stats["prefill_tokens_saved"] == BS
        assert eng.prefix_cache.stats["hit_blocks"] == 1

    def test_cow_divergence_never_aliases(self, model):
        """Two concurrent sequences hitting the SAME cached chain then
        diverging (different tails, one sampled) match their solo runs:
        install-copy means pool blocks are read-only and appends land in
        private slots."""
        a = _req(31, max_new_tokens=8)
        b = _req(32, max_new_tokens=8, temperature=0.9, top_k=4, seed=3)
        want = _cold_run(model, [a, b])
        eng = _engine(model)
        eng.generate([_req(30, max_new_tokens=2)])  # publish the chain
        sa, sb = eng.submit(_clone(a)), eng.submit(_clone(b))
        step0 = eng.stats["steps"]
        while eng.has_work():
            eng.step()
            if eng.stats["steps"] == step0 + 1:
                # both admitted in one step, pinning the same blocks
                shared = set(n.block_id for n in sa.prefix_nodes) & \
                    set(n.block_id for n in sb.prefix_nodes)
                assert shared  # genuinely the same physical blocks
                assert all(eng.prefix_cache.pool.refcount(bid) == 2
                           for bid in shared)
        assert [sa.tokens, sb.tokens] == want
        assert sa.prefix_hit_tokens == sb.prefix_hit_tokens == 2 * BS
        # pins drained at retirement
        assert not eng.prefix_cache.pool._ref.any()


class TestEvictionAndBudget:
    def test_eviction_under_pressure_keeps_streams_exact(self, model):
        """A pool far smaller than the working set: evictions fire, the
        budget is never exceeded, streams stay byte-identical."""
        reqs = [_req(i, sys_prompt=np.random.RandomState(100 + i % 5)
                     .randint(0, 256, (16,)).astype(np.int32),
                     max_new_tokens=4) for i in range(10)]
        want = _cold_run(model, reqs)
        eng = _engine(model, prefix_blocks=3)
        pool = eng.prefix_cache.pool
        outs = []
        for r in reqs:  # serially, so pool pressure peaks per publish
            outs.append(eng.generate([_clone(r)])[0].tolist())
            assert pool.num_used <= pool.num_blocks
        assert outs == want
        assert eng.prefix_cache.stats["evictions"] > 0

    def test_pinned_blocks_never_evict_and_publish_degrades(self, model):
        """Every pool block pinned by a live sequence: a retirement's
        publish finds nothing evictable and SKIPS (degrade, not fail);
        the pinned chain survives untouched."""
        eng = _engine(model, prefix_blocks=2, num_slots=2)
        pc = eng.prefix_cache
        eng.generate([_req(50, max_new_tokens=2)])   # fills both blocks
        assert pc.pool.num_free == 0
        holder = eng.submit(_req(51, max_new_tokens=30))  # pins the chain
        eng.step()
        assert len(holder.prefix_nodes) == 2
        # a different prompt retires while everything is pinned
        other = GenerationRequest(prompt=np.random.RandomState(52).randint(
            0, 256, (2 * BS,)).astype(np.int32), max_new_tokens=2)
        want = _cold_run(model, [other])[0]
        got = eng.generate([_clone(other)])[0].tolist()
        assert got == want
        assert pc.stats["skipped_publishes"] >= 1
        assert pc.stats["evictions"] == 0           # pins held
        eng.cancel(holder)
        assert not pc.pool._ref.any()

    def test_same_step_cold_retirement_cannot_evict_pending_hit(self, model):
        """Regression: a cold sequence retiring INSIDE the admission
        group (max_new_tokens=1 publishes under pool pressure) must not
        evict the chain a same-step hit matched but hasn't installed
        yet — matched chains are pinned at lookup, before any cold
        admission runs."""
        sys16 = np.random.RandomState(55).randint(
            0, 256, (16,)).astype(np.int32)
        hit_req = GenerationRequest(
            prompt=np.concatenate([sys16, [5, 6, 7]]), max_new_tokens=6)
        cold_req = GenerationRequest(
            prompt=np.random.RandomState(56).randint(
                0, 256, (16,)).astype(np.int32), max_new_tokens=1)
        want_hit = _cold_run(model, [hit_req])[0]
        eng = _engine(model, prefix_blocks=2, num_slots=2)
        eng.generate([GenerationRequest(prompt=sys16, max_new_tokens=1)])
        assert eng.prefix_cache.pool.num_free == 0  # chain fills the pool
        cold_seq = eng.submit(_clone(cold_req))  # cold path admits first
        hit_seq = eng.submit(_clone(hit_req))
        while eng.has_work():
            eng.step()
        assert cold_seq.finish_reason == "length"
        assert hit_seq.tokens == want_hit        # chain survived intact
        assert hit_seq.prefix_hit_tokens == 2 * BS  # whole chain matched
        assert eng.prefix_cache.stats["evictions"] == 0  # pin held
        assert eng.prefix_cache.stats["skipped_publishes"] >= 1

    def test_lru_order_evicts_coldest_chain_first(self):
        """Unit-level: trie eviction picks the least-recently-touched
        zero-ref LEAF, keeping interior nodes reachable."""
        pool = BlockManager(1, 3, 4, 1, 2)
        pc = PrefixCache(pool)

        class _FakeKV:  # host-only: no device copies needed
            def copy_block_out(self, slot, row0, pool_, block):
                pass

        kv = _FakeKV()
        pc.publish(np.arange(8), 0, kv)       # chain A: 2 blocks
        pc.publish(np.arange(100, 104), 0, kv)  # chain B: 1 block
        assert pool.num_used == 3
        m = pc.lookup(np.arange(9))           # touch chain A (fresh tick)
        assert len(m) == 2
        pc.publish(np.arange(200, 204), 0, kv)  # needs an eviction
        assert pc.stats["evictions"] == 1
        # B (coldest) died; A's chain still matches end to end
        assert len(pc.lookup(np.arange(9))) == 2
        assert pc.lookup(np.asarray([100, 101, 102, 103, 1])) == []


class TestCompileDiscipline:
    @pytest.mark.slow  # DENSE-shim compile discipline: the paged
    # default's twins (test_paged_attention mixed-traffic +
    # test_chunked_prefill's hit/miss/cancel/divergence matrix) stay
    # the default reps — no new features land on the dense path
    def test_mixed_traffic_keeps_decode_at_one_and_prefill_bounded(
            self, model):
        """The acceptance pin: hits, misses, evictions, and a COW
        divergence leave ``decode_compilations() == 1``; once the
        bucket/group grid is warm a repeat wave adds ZERO prefill /
        suffix / copy traces (the compile sets are closed over
        geometry, not traffic history)."""
        jit = {}
        eng = _engine(model, jit_cache=jit)  # ample pool: steady state

        def wave(e):
            outs = e.generate(
                [_req(60), _req(61),                       # hit pair
                 _req(62, temperature=0.8, top_k=6, seed=2),
                 GenerationRequest(                        # distinct miss
                     prompt=np.random.RandomState(63).randint(
                         0, 256, (2 * BS,)).astype(np.int32),
                     max_new_tokens=3),
                 _req(64, n_tail=3)])                      # divergence
            return [o.tolist() for o in outs]

        first = wave(eng)
        second = wave(eng)       # all-hit steady state; grid fully warm
        assert second == first   # caching is deterministic too
        assert eng.decode_compilations() == 1
        prefill0, copy0 = eng.prefill_compilations(), copy_compilations()
        third = wave(eng)
        assert third == first
        assert eng.decode_compilations() == 1
        assert eng.prefill_compilations() == prefill0   # zero new traces
        assert copy_compilations() == copy0
        # eviction churn (pool of 4): hit patterns shift wave to wave as
        # blocks die, so new (group, bucket) combos may legitimately
        # appear — but only within the static pow2 grid. For this
        # traffic: cold prompts bucket to {16, 32}, suffixes to {8, 16},
        # groups to {1, 2} -> at most 4 cold + 4 suffix shapes total, vs
        # ~15 per wave if shapes leaked per-request. Copy programs are
        # geometry-keyed: the smaller pool adds its pair once, then the
        # count is closed no matter how much churn runs.
        eng2 = _engine(model, jit_cache=jit, prefix_blocks=4)
        assert wave(eng2) == first
        copy1 = copy_compilations()
        assert wave(eng2) == first
        assert wave(eng2) == first
        assert eng2.prefix_cache.stats["evictions"] > 0
        assert eng2.decode_compilations() == 1
        assert copy_compilations() == copy1
        assert eng2.prefill_compilations() <= 8


class TestMetricsSurface:
    def test_gateway_exposes_prefix_series_strict_parsed(self, model):
        """The gateway's /metrics body (registry.render IS the scrape
        body) carries hit/miss/eviction counters and the live
        kv_prefix_blocks gauge, valid under the strict v0.0.4 parser."""
        from paddle_tpu.serving.server import ServingGateway
        eng = _engine(model, prefix_blocks=3)
        gw = ServingGateway(eng, start=False)  # no driver thread needed
        for r in [_req(70), _req(71), _req(72)]:
            eng.generate([r])
        for i in range(4):  # distinct prompts: force evictions
            eng.generate([GenerationRequest(
                prompt=np.random.RandomState(80 + i).randint(
                    0, 256, (2 * BS,)).astype(np.int32),
                max_new_tokens=2)])
        fams = parse_prometheus(gw.registry.render())  # strict: raises

        def val(name):
            return fams[name]["samples"][(name, ())]

        assert fams["serving_prefix_cache_hits_total"]["type"] == "counter"
        assert val("serving_prefix_cache_hits_total") == \
            eng.prefix_cache.stats["hits"] >= 2
        assert val("serving_prefix_cache_misses_total") == \
            eng.prefix_cache.stats["misses"] >= 1
        assert val("serving_prefix_cache_evictions_total") == \
            eng.prefix_cache.stats["evictions"] >= 1
        assert val("serving_prefill_tokens_saved_total") == \
            eng.stats["prefill_tokens_saved"] > 0
        assert fams["kv_prefix_blocks"]["type"] == "gauge"
        assert val("kv_prefix_blocks") == eng.prefix_cache.pool.num_used
        assert val("kv_prefix_blocks_capacity") == 3
        # live gauge: occupancy changes move the next scrape
        before = val("kv_prefix_blocks")
        while eng.prefix_cache._evict_one():
            pass
        fams2 = parse_prometheus(gw.registry.render())
        assert fams2["kv_prefix_blocks"]["samples"][
            ("kv_prefix_blocks", ())] < before


class TestConstruction:
    def test_shared_cache_geometry_validated(self, model):
        """Passing another engine's PrefixCache with mismatched pool
        geometry fails fast at __init__, not mid-serving in XLA."""
        donor = _engine(model)
        ok = ContinuousBatchingEngine(  # matching geometry: accepted
            model, num_slots=2, max_seq_len=64, paged_attn=False,
            prefix_cache=donor.prefix_cache,
            jit_cache=model.__dict__["_serving_jit"])
        assert ok.prefix_cache is donor.prefix_cache
        paddle.seed(5)
        other = LlamaForCausalLM(llama_tiny(hidden_size=32))  # head_dim 8
        with pytest.raises(ValueError, match="geometry"):
            ContinuousBatchingEngine(other, num_slots=2, max_seq_len=64,
                                     paged_attn=False,
                                     prefix_cache=donor.prefix_cache)

    def test_prefix_blocks_zero_rejected_not_defaulted(self, model):
        with pytest.raises(ValueError, match="num_blocks"):
            _engine(model, prefix_blocks=0)


class TestTrieInvariantsRandomized:
    """ISSUE 16 satellite: randomized interleavings of publish /
    acquire / release / evict — with the host tier spilling and
    readmitting underneath — uphold the trie's structural invariants
    at every step:

    - no orphaned interior node (every resident node is reachable from
      the root with consistent parent/child links, and node count ==
      pool occupancy — nothing leaks, nothing aliases);
    - a pinned chain is never evicted (its nodes stay reachable while
      held);
    - refcounts equal the live pins exactly, and drain to zero;
    - the tier never exceeds its byte budget;
    - spill/readmit preserves block CONTENT: each published block
      carries a value derived from its full token path, and whatever
      is resident after any amount of churn still holds its path's
      exact bytes.
    """

    NB, BSU = 6, 4          # 6-block pool, 4-token blocks
    SHAPE = (1, 1, BSU, 1, 2)   # one block: [L, 1, bs, Hkv, D]

    def _expected(self, path):
        v = float(zlib.crc32(repr(path).encode()) % 65536)
        return {"k": np.full(self.SHAPE, v, np.float32),
                "v": np.full(self.SHAPE, v + 0.5, np.float32)}

    class _ContentKV:
        """publish()-facing stand-in whose copy_block_out writes the
        path-derived content through the pool's own h2d program."""

        def __init__(self, test, pc):
            self.test, self.pc, self.tokens = test, pc, None

        def copy_block_out(self, slot, row0, pool, block):
            i = row0 // pool.block_size
            path = tuple(self.pc._blocks_of(self.tokens,
                                            len(self.tokens))[:i + 1])
            pool.write_block(block, self.test._expected(path))

    def _check(self, pc, pool, held, content=False):
        nodes, stack = [], [(None, pc._root)]
        while stack:
            parent, children = stack.pop()
            for key, node in children.items():
                assert node.tokens == key          # key/identity agree
                assert node.parent is parent       # no orphaned interior
                nodes.append(node)
                stack.append((node, node.children))
        assert len(nodes) == pc._nodes == pool.num_used
        ids = [n.block_id for n in nodes]
        assert len(set(ids)) == len(ids)           # no block aliased
        want = collections.Counter()
        for chain in held:
            for n in chain:
                want[n.block_id] += 1
        for b in range(pool.num_blocks):
            assert pool.refcount(b) == want.get(b, 0)
        reachable = {id(n) for n in nodes}
        for chain in held:                         # pinned never evicted
            for n in chain:
                assert id(n) in reachable
        assert pc.tier.bytes_used <= pc.tier.capacity_bytes
        if content:
            for n in nodes:
                path = pc._path_of(n)
                got = pool.read_block(n.block_id)
                exp = self._expected(path)
                np.testing.assert_array_equal(got["k"], exp["k"])
                np.testing.assert_array_equal(got["v"], exp["v"])

    def test_random_interleavings_uphold_invariants(self):
        rng = np.random.RandomState(17)
        pool = BlockManager(1, self.NB, self.BSU, 1, 2)
        # tier budget of 4 blocks (64 B each): tier-side LRU trims and
        # descendant cascades fire too, not just spill/readmit
        pc = PrefixCache(pool, host_tier_bytes=4 * 64)
        kv = self._ContentKV(self, pc)
        # small alphabet + short lengths: prompts share prefixes often
        prompts = [rng.randint(0, 3, (int(n),)).astype(np.int32)
                   for n in rng.randint(4, 18, size=12)]
        held = []
        for step in range(150):
            op = rng.rand()
            prompt = prompts[rng.randint(len(prompts))]
            if op < 0.35:
                kv.tokens = prompt
                pc.publish(prompt, 0, kv)
            elif op < 0.65:
                m = pc.lookup(prompt)       # may readmit from the tier
                if m:
                    pc.acquire(m)
                    held.append(m)
            elif op < 0.9 and held:
                pc.release(held.pop(rng.randint(len(held))))
            else:
                pc._evict_one()
            self._check(pc, pool, held, content=(step % 10 == 9))
        # churn actually exercised every path
        assert pc.stats["evictions"] > 0
        assert pc.stats["spilled_blocks"] > 0
        assert pc.stats["readmitted_blocks"] > 0
        assert pc.stats["tier_evictions"] > 0      # tier LRU trimmed too
        # drain: release every pin, evict everything — refs to zero,
        # trie and pool empty, no stranded bookkeeping
        for chain in held:
            pc.release(chain)
        self._check(pc, pool, [], content=True)
        while pc._evict_one():
            pass
        assert pc._nodes == 0 and pool.num_used == 0
        assert not pool._ref.any()


class TestBlockManagerUnit:
    def test_alloc_free_ref_lifecycle(self):
        pool = BlockManager(1, 2, 4, 1, 2)
        a, b = pool.alloc(), pool.alloc()
        assert (a, b) == (0, 1) and pool.alloc() is None
        pool.ref(a)
        with pytest.raises(ValueError, match="refcount"):
            pool.free(a)                 # pinned blocks can't be freed
        assert pool.unref(a) == 0
        pool.free(a)
        with pytest.raises(ValueError, match="double-freed"):
            pool.free(a)
        with pytest.raises(ValueError, match="below zero"):
            pool.unref(b)
        assert pool.num_used == 1 and pool.num_free == 1
