"""XPlane per-op statistics (VERDICT r3 weak 10: summary-level only, no
per-op aggregation from real traces — reference
``python/paddle/profiler/profiler_statistic.py`` † op tables).

The wire-format reader is validated against an ACTUAL jax.profiler trace,
so an xplane.proto schema drift fails here rather than in a bench run."""
import tempfile

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.profiler.xplane import (_trace_files, op_statistics,
                                        parse_xplane, summarize)


def _capture_trace():
    d = tempfile.mkdtemp(prefix="xplane_test_")
    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: jnp.tanh(a @ a).sum())
    f(x).block_until_ready()  # compile outside the trace
    jax.profiler.start_trace(d)
    for _ in range(4):
        f(x).block_until_ready()
    jax.profiler.stop_trace()
    return d


class TestXPlaneStatistics:
    @pytest.mark.slow  # live jax.profiler trace (~17s); the synthetic
    # device-plane tests stay as the default-run wire-format reps
    def test_parses_real_trace_and_finds_the_dot(self):
        d = _capture_trace()
        files = _trace_files(d)
        assert files, "jax.profiler wrote no .xplane.pb"
        planes = parse_xplane(files[0])
        assert planes and all("name" in p and "events" in p for p in planes)
        rows = op_statistics(d, device_only=False)
        assert rows, "no events aggregated"
        names = " ".join(r["name"] for r in rows)
        # the traced computation must surface as an XLA dot op
        assert "dot" in names, names[:400]
        dot = next(r for r in rows if "dot" in r["name"])
        assert dot["count"] >= 4 and dot["total_ms"] > 0
        assert dot["avg_us"] > 0

    @pytest.mark.slow  # second live-trace capture (~10s);
    # test_summarize_renders_table keeps a live-trace default rep
    def test_rows_sorted_by_total_and_top_limits(self):
        d = _capture_trace()
        rows = op_statistics(d, device_only=False)
        totals = [r["total_ms"] for r in rows]
        assert totals == sorted(totals, reverse=True)
        assert len(op_statistics(d, device_only=False, top=3)) <= 3

    @pytest.mark.slow  # 20 s render duplicate: test_parses_real_trace_and_finds
    # _the_dot above keeps the default xplane rep (870s cap)
    def test_summarize_renders_table(self):
        d = _capture_trace()
        s = summarize.__wrapped__(d) if hasattr(summarize, "__wrapped__") \
            else summarize(d, top=5)
        # CPU backend has no device plane: fall back for the assertion
        if s == "no device events parsed":
            from paddle_tpu.profiler.xplane import op_statistics as stats
            assert stats(d, device_only=False)
        else:
            assert "total_ms" in s
