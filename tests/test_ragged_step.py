"""The unified ragged serving step (engine ``ragged_step=True``, README
"Unified ragged attention"): decode rows and prefill chunks ride ONE
device program per step, with the chunk grant adapted from measured
headroom EWMAs. The load-bearing properties:

- **Transparency**: unified token streams are byte-identical to the
  two-program (PR-5) engine — greedy AND seeded-sampled, across a
  hit/miss/eviction/cancel/chunked mix — and ``decode_compilations()``
  stays at 1.
- **One launch**: a step carrying both a prefill chunk and live decode
  rows dispatches exactly ONE program where the baseline pair
  dispatched two — and no discarded decode row runs for a mid-prefill
  slot.
- **Headroom-adaptive budgeting**: the grant follows the measured
  tokens-per-second EWMA (deterministically, via an injected step
  clock), is capped at ``prefill_chunk``, and a throttled sub-block
  grant CARRIES to the next plan instead of starving the pipeline
  (the ``prefill_plan`` carry fix + its 1-token-over regression).
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (ContinuousBatchingEngine, FIFOScheduler,
                                GenerationRequest)

BS = 8      # block size
CHUNK = 16  # 2 blocks per chunk


@pytest.fixture(scope="module")
def model():
    paddle.seed(33)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


def _engine(model, **kw):
    kw.setdefault("jit_cache", model.__dict__.setdefault("_serving_jit", {}))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    return ContinuousBatchingEngine(model, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _req(ps, n=40, **kw):
    kw.setdefault("max_new_tokens", 6)
    return GenerationRequest(prompt=_prompt(ps, n), **kw)


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             eos_token_id=r.eos_token_id, seed=r.seed)


class TestTransparency:
    @pytest.mark.slow  # 10 s transparency matrix duplicate: the one-launch and
    # dense-engine reps below run by default (870s cap)
    def test_unified_equals_two_program_mixed_matrix(self, model):
        """The acceptance pin: a hit/miss/eviction/cancel/chunked
        traffic matrix — varied prompt lengths, shared system prompt,
        greedy and seeded-sampled rows, a mid-prefill cancellation, a
        trie small enough to evict under pressure — streams byte-
        identical between ``ragged_step=True`` and the PR-5 two-program
        engine, with one unified decode program."""
        sysp = _prompt(90, 32)

        def drive(ragged):
            eng = _engine(model, ragged_step=ragged, prefix_cache=True,
                          prefix_blocks=32)   # tight trie: evictions
            outs = []
            for wave in range(2):
                reqs = [_req(1, n=40), _req(2, n=61),
                        GenerationRequest(
                            prompt=np.concatenate([sysp, _prompt(3, 24)]),
                            max_new_tokens=5),
                        GenerationRequest(
                            prompt=np.concatenate([sysp, _prompt(4, 31)]),
                            max_new_tokens=5, temperature=0.8, top_k=4,
                            seed=7),
                        _req(5, n=53, temperature=0.9, top_k=5, seed=123),
                        _req(6, n=12)]
                seqs = [eng.submit(_clone(r)) for r in reqs]
                victim = eng.submit(_req(7, n=70))
                steps = 0
                while eng.has_work():
                    eng.step()
                    steps += 1
                    if steps == 4 and victim.status == "prefilling":
                        eng.cancel(victim)   # mid-chunk cancellation
                outs.append([s.tokens for s in seqs])
            return outs, eng

        want, base = drive(False)
        got, eng = drive(True)
        assert got == want
        assert eng.decode_compilations() == 1
        assert eng.stats["prefill_chunks"] >= 6
        assert eng.prefix_cache.stats["evictions"] >= 1
        assert eng.prefix_cache.stats["hits"] >= 1
        # the unified engine really ran unified steps (not the pair)
        assert eng.stats["unified_steps"] > 0
        assert base.stats["unified_steps"] == 0

    def test_dense_engine_ignores_ragged_step(self, model):
        reqs = [_req(10, n=24), _req(11, n=12)]
        a = _engine(model, paged_attn=False, ragged_step=True)
        b = _engine(model, paged_attn=False, ragged_step=False)
        assert a.ragged_step is False and b.ragged_step is False
        oa = [o.tolist() for o in a.generate([_clone(r) for r in reqs])]
        ob = [o.tolist() for o in b.generate([_clone(r) for r in reqs])]
        assert oa == ob
        assert a.stats["unified_steps"] == 0


class TestOneLaunch:
    def test_mixed_step_single_program_no_dead_decode_row(self, model):
        """While a long prompt chunks, a step that ALSO decodes a live
        slot dispatches exactly one program — the two-program engine's
        chunk-call + decode-call pair collapses — and the mid-prefill
        slot contributes its chunk span instead of a discarded
        full-length decode row."""
        calls = {"ragged": 0, "suffix": 0, "decode": 0}
        eng = _engine(model, headroom_mult=None)
        for name, orig in (("ragged", eng._ragged_fn),
                           ("decode", eng._decode_fn)):
            def wrap(n, _name=name, _orig=orig):
                calls[_name] += 1
                return _orig(n)
            setattr(eng, "_" + name + "_fn", wrap)
        orig_sfx = eng._suffix_fn
        eng._suffix_fn = lambda: (calls.__setitem__(
            "suffix", calls["suffix"] + 1) or orig_sfx())
        short = eng.submit(_req(20, n=8, max_new_tokens=40))
        eng.step()                      # admit + first token
        longy = eng.submit(_req(21, n=80, max_new_tokens=4))
        while longy.status != "running":
            before = dict(calls)
            toks0 = len(short.tokens)
            eng.step()
            # one ragged launch; NO separate chunk or decode program
            assert calls["ragged"] == before["ragged"] + 1
            assert calls["decode"] == before["decode"]
            assert calls["suffix"] == before["suffix"]
            assert len(short.tokens) == toks0 + 1   # decode kept going
        assert eng.stats["prefill_chunks"] == 5     # ceil(80/16)

    def test_two_program_baseline_pays_the_pair(self, model):
        """The baseline the bench compares against: the same traffic on
        ``ragged_step=False`` really does launch chunk + decode
        programs in one step."""
        eng = _engine(model, ragged_step=False)
        calls = {"suffix": 0, "decode": 0}
        orig_sfx, orig_dec = eng._suffix_fn, eng._decode_fn
        eng._suffix_fn = lambda: (calls.__setitem__(
            "suffix", calls["suffix"] + 1) or orig_sfx())
        eng._decode_fn = lambda n: (calls.__setitem__(
            "decode", calls["decode"] + 1) or orig_dec(n))
        short = eng.submit(_req(22, n=8, max_new_tokens=40))
        eng.step()
        longy = eng.submit(_req(23, n=80, max_new_tokens=4))
        before = dict(calls)
        eng.step()                      # chunk + decode: two programs
        assert longy.status == "prefilling"
        assert calls["suffix"] == before["suffix"] + 1
        assert calls["decode"] == before["decode"] + 1


class TestHeadroomBudget:
    def test_budget_defaults_to_cap_until_measured(self, model):
        eng = _engine(model)
        assert eng._prefill_budget() == CHUNK
        assert eng.stats["headroom"] == CHUNK

    def test_budget_tracks_measured_headroom_and_clamps(self, model):
        """The grant is tps_ewma x mult x decode-step-time minus the
        decode rows sharing the step, clamped to [1, cap]: fast packed
        steps pin it at the cap, slow ones throttle it toward 1."""
        eng = _engine(model, headroom_mult=2.0)
        eng._dt_decode_ewma = 0.010
        eng._tps_ewma = 2000.0          # 2k tok/s -> 40 affordable
        assert eng._prefill_budget() == CHUNK          # cap clamps
        eng._tps_ewma = 300.0           # 6 affordable
        assert eng._prefill_budget() == 6
        eng._tps_ewma = 10.0            # under a token -> floor at 1
        assert eng._prefill_budget() == 1
        assert eng.stats["headroom"] == 1
        with pytest.raises(ValueError, match="headroom_mult"):
            _engine(model, headroom_mult=0.0)

    def test_injected_clock_feeds_ewmas_deterministically(self, model):
        """``step_clock`` is the EWMAs' timebase: a virtual clock
        advancing 10 ms per reading yields exactly reproducible
        headroom stats — the hook the deterministic benches use."""
        ticks = itertools.count()
        eng = _engine(model, step_clock=lambda: next(ticks) * 0.010)
        eng.generate([_req(30, n=50, max_new_tokens=3)])
        assert eng.stats["last_step_duration_s"] == pytest.approx(0.010)
        assert eng.stats["headroom_tps"] > 0      # chunk steps measured
        assert eng._dt_decode_ewma == pytest.approx(0.010)

    def test_throttled_grant_still_completes_one_token_over(self, model):
        """The regression the plan-carry fix exists for: a prompt ONE
        token over the chunk cap, with the adaptive grant throttled to
        a single token per step, must still complete — sub-block
        grants accumulate at the plan head instead of serializing the
        queue behind the misaligned prompt."""
        eng = _engine(model)
        # pin the EWMAs so every grant is 1 token (floor)
        eng._tps_ewma = 1.0
        eng._dt_decode_ewma = 0.010
        bystander = eng.submit(_req(31, n=8, max_new_tokens=30))
        seq = eng.submit(_req(32, n=CHUNK + 1, max_new_tokens=3))
        steps = 0
        while not seq.done:
            eng.step()
            steps += 1
            assert steps < 300, "1-token-over prompt starved"
        assert seq.finish_reason == "length"
        want, _ = (lambda e: ([o.tolist() for o in e.generate(
            [_req(32, n=CHUNK + 1, max_new_tokens=3)])], e))(
            _engine(model, prefill_chunk=None))
        assert seq.tokens == want[0]
        while not bystander.done:
            eng.step()
        assert len(bystander.tokens) == 30


class TestSchedulerCarry:
    def test_sub_block_budgets_accumulate_at_plan_head(self):
        class S:
            def __init__(self, plen, done):
                self.work_len, self.prefilled = plen, done
        sched = FIFOScheduler()
        a = S(100, 0)
        sched.enter_prefill(a)
        # three sub-block grants accumulate, the fourth releases a block
        assert sched.prefill_plan(3, align=8) == []
        assert sched.prefill_plan(3, align=8) == []
        assert sched.prefill_plan(1, align=8) == []
        assert sched.prefill_plan(3, align=8) == [(a, 8)]
        # a granted plan consumes the carry — no double counting
        a.prefilled = 8
        assert sched.prefill_plan(16, align=8) == [(a, 16)]
        assert sched.prefill_plan(4, align=8) == []   # fresh carry: 4
        assert sched.prefill_plan(4, align=8) == [(a, 8)]

    def test_banked_carry_never_pushes_a_full_cap_grant_past_cap(self):
        """The overflow path the ``cap`` argument exists for: a
        throttled sub-block grant banks a carry, then the adaptive
        budget swings back to the full cap — the next plan must stay
        within ``cap`` tokens (the packed token buffer and the chunk
        compile bucket are sized for exactly that), not ``cap+carry``.
        A final chunk is the dangerous case: it skips block alignment,
        so an uncapped budget would hand out ``cap + carry`` tokens."""
        class S:
            def __init__(self, plen, done):
                self.work_len, self.prefilled = plen, done
        sched = FIFOScheduler()
        a = S(24 + 7, 0)                   # remaining > cap, final-chunk
        sched.enter_prefill(a)
        assert sched.prefill_plan(7, align=8, cap=24) == []
        assert sched._plan_carry == 7
        plan = sched.prefill_plan(24, align=8, cap=24)
        assert plan == [(a, 24)]           # clamped: NOT 24 + 7
        a.prefilled = 24
        # the tail completes on the next grant (carry was not needed)
        assert sched.prefill_plan(24, align=8, cap=24) == [(a, 7)]

    def test_carry_caps_at_one_block_and_clears_when_idle(self):
        class S:
            def __init__(self, plen, done):
                self.work_len, self.prefilled = plen, done
        sched = FIFOScheduler()
        a = S(40, 0)
        sched.enter_prefill(a)
        assert sched.prefill_plan(7, align=8) == []
        assert sched._plan_carry == 7
        sched.leave_prefill(a)
        # emptying the pipeline clears the carry EAGERLY — the engine
        # stops planning while idle, so a banked grant must not leak
        # into a later unrelated prompt's first plan
        assert sched._plan_carry == 0
        assert sched.prefill_plan(100, align=8) == []
        assert sched._plan_carry == 0


class TestMetricsSurface:
    def test_step_metrics_strict_parsed(self, model):
        """serving_step_duration_seconds (STEP_BUCKETS ladder),
        serving_step_tokens and serving_prefill_headroom_tokens land on
        /metrics, valid under the strict v0.0.4 parser, reading the
        same stats the adaptive budget does."""
        from test_metrics_prom import parse_prometheus

        from paddle_tpu.profiler.metrics import STEP_BUCKETS
        from paddle_tpu.serving.server import ServingGateway
        eng = _engine(model)
        gw = ServingGateway(eng, start=False)   # no driver thread needed
        eng.generate([_req(40, n=50, max_new_tokens=2)])
        # engine-direct runs bypass the driver's observe; one explicit
        # observation materializes the histogram series
        gw._m_step_dur.observe(eng.stats["last_step_duration_s"])
        fams = parse_prometheus(gw.registry.render())
        name = "serving_step_duration_seconds"
        assert fams[name]["type"] == "histogram"
        le = [k for k in fams[name]["samples"] if k[0] == name + "_bucket"]
        bounds = {lbl[1] for _, lbls in le for lbl in lbls
                  if lbl[0] == "le"}
        assert len(bounds) == len(STEP_BUCKETS) + 1  # ladder + +Inf
        assert fams[name]["samples"][(name + "_count", ())] == 1
        assert fams["serving_step_tokens"]["type"] == "gauge"
        assert fams["serving_step_tokens"]["samples"][
            ("serving_step_tokens", ())] == eng.stats["last_step_tokens"]
        assert fams["serving_prefill_headroom_tokens"]["samples"][
            ("serving_prefill_headroom_tokens", ())] == \
            eng.stats["headroom"]
