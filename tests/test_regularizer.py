"""paddle.regularizer L1Decay/L2Decay applied through weight_decay=
(reference: python/paddle/regularizer.py †, optimizer folds the penalty
into the gradient; AdamW's decoupled decay is unaffected)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.optimizer import SGD


def _one_step(weight_decay, w0=0.5, g=0.1, lr=0.1):
    p = paddle.to_tensor(np.full((3,), w0, np.float32))
    p.stop_gradient = False
    opt = SGD(learning_rate=lr, parameters=[p], weight_decay=weight_decay)
    p.grad = paddle.to_tensor(np.full((3,), g, np.float32))
    opt.step()
    return p.numpy()


class TestRegularizer:
    def test_l2_matches_bare_float(self):
        np.testing.assert_allclose(
            _one_step(paddle.regularizer.L2Decay(0.01)), _one_step(0.01),
            rtol=1e-6)

    def test_l2_value(self):
        # p - lr*(g + c*p) = 0.5 - 0.1*(0.1 + 0.01*0.5)
        np.testing.assert_allclose(
            _one_step(paddle.regularizer.L2Decay(0.01)),
            np.full((3,), 0.5 - 0.1 * (0.1 + 0.005)), rtol=1e-6)

    def test_l1_sign_penalty(self):
        # p - lr*(g + c*sign(p)) with p>0 -> 0.5 - 0.1*(0.1 + 0.01)
        np.testing.assert_allclose(
            _one_step(paddle.regularizer.L1Decay(0.01)),
            np.full((3,), 0.5 - 0.1 * 0.11), rtol=1e-6)
        # negative weights decay UP (sign = -1)
        out = _one_step(paddle.regularizer.L1Decay(0.01), w0=-0.5)
        np.testing.assert_allclose(
            out, np.full((3,), -0.5 - 0.1 * (0.1 - 0.01)), rtol=1e-6)

    def test_jit_apply_gradients_path(self):
        import jax.numpy as jnp
        p = paddle.to_tensor(np.full((2,), 0.5, np.float32))
        p.stop_gradient = False
        opt = SGD(learning_rate=0.1, parameters=[p],
                  weight_decay=paddle.regularizer.L1Decay(0.01))
        state = opt.init_state({"w": p.value})
        new_p, _ = opt.apply_gradients(
            {"w": p.value}, {"w": jnp.full((2,), 0.1, jnp.float32)}, state)
        np.testing.assert_allclose(np.asarray(new_p["w"]),
                                   np.full((2,), 0.5 - 0.1 * 0.11), rtol=1e-6)

    def test_repr_and_coeff(self):
        r = paddle.regularizer.L1Decay(0.25)
        assert r.coeff == 0.25 and "L1Decay" in repr(r)

    def test_adamw_rejects_l1(self):
        import pytest
        p = paddle.to_tensor(np.ones((2,), np.float32))
        p.stop_gradient = False
        with pytest.raises(TypeError, match="L2Decay"):
            paddle.optimizer.AdamW(parameters=[p],
                                   weight_decay=paddle.regularizer.L1Decay(0.01))
        # L2Decay object maps onto the decoupled coeff; None means no decay
        opt = paddle.optimizer.AdamW(
            parameters=[p], weight_decay=paddle.regularizer.L2Decay(0.02))
        assert opt._coeff == 0.02
        assert paddle.optimizer.AdamW(parameters=[p],
                                      weight_decay=None)._coeff == 0.0

    def test_pure_path_warns_on_param_regularizer(self):
        import jax.numpy as jnp
        import pytest
        from paddle_tpu.framework import ParamAttr
        lin = paddle.nn.Linear(
            2, 1,
            weight_attr=ParamAttr(regularizer=paddle.regularizer.L1Decay(0.5)),
            bias_attr=False)
        opt = SGD(learning_rate=0.1, parameters=lin.parameters())
        state = opt.init_state({"w": lin.weight.value})
        with pytest.warns(UserWarning, match="eager"):
            opt.apply_gradients({"w": lin.weight.value},
                                {"w": jnp.zeros((2, 1))}, state)

    def test_param_attr_regularizer_overrides(self):
        # per-param ParamAttr(regularizer=...) wins over the optimizer-level
        # weight_decay (reference append_regularization_ops precedence)
        from paddle_tpu.framework import ParamAttr
        lin = paddle.nn.Linear(
            2, 1,
            weight_attr=ParamAttr(regularizer=paddle.regularizer.L1Decay(0.5)),
            bias_attr=False)
        w0 = lin.weight.numpy().copy()
        opt = SGD(learning_rate=0.1, parameters=lin.parameters(),
                  weight_decay=paddle.regularizer.L2Decay(0.9))
        lin.weight.grad = paddle.to_tensor(np.zeros_like(w0))
        opt.step()
        # zero grad -> update comes from the penalty alone: L1 (0.5*sign),
        # NOT L2 (0.9*w)
        np.testing.assert_allclose(
            lin.weight.numpy(), w0 - 0.1 * 0.5 * np.sign(w0), rtol=1e-6)
