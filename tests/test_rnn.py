"""Recurrent layer tests (reference: ``test/legacy_test/test_rnn_*.py`` —
cell/stack correctness vs an independent oracle). Oracle: torch.nn (cpu),
whose LSTM/GRU gate conventions match paddle's (i,f,g,o / r,u,c)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn

torch = pytest.importorskip("torch")


def _np(t):
    return np.asarray(t.value if hasattr(t, "value") else t)


def _copy_cell_from_torch(cell, t_mod, layer=0, direction=0, torch_is_cell=False):
    sfx = "" if torch_is_cell else f"_l{layer}{'_reverse' if direction else ''}"
    cell.weight_ih.set_value(getattr(t_mod, f"weight_ih{sfx}").detach().numpy())
    cell.weight_hh.set_value(getattr(t_mod, f"weight_hh{sfx}").detach().numpy())
    cell.bias_ih.set_value(getattr(t_mod, f"bias_ih{sfx}").detach().numpy())
    cell.bias_hh.set_value(getattr(t_mod, f"bias_hh{sfx}").detach().numpy())


B, T, I, H = 2, 6, 3, 5


def _x(seed=0):
    return np.random.RandomState(seed).randn(B, T, I).astype(np.float32)


class TestCellsVsTorch:
    def test_lstm_cell(self):
        tc = torch.nn.LSTMCell(I, H)
        c = nn.LSTMCell(I, H)
        _copy_cell_from_torch(c, tc, torch_is_cell=True)
        x = _x()[:, 0]
        th, tcc = tc(torch.tensor(x))
        h, (h2, cc) = c(paddle.to_tensor(x))
        np.testing.assert_allclose(_np(h), th.detach().numpy(), atol=1e-5)
        np.testing.assert_allclose(_np(cc), tcc.detach().numpy(), atol=1e-5)

    def test_gru_cell(self):
        tc = torch.nn.GRUCell(I, H)
        c = nn.GRUCell(I, H)
        _copy_cell_from_torch(c, tc, torch_is_cell=True)
        x = _x()[:, 0]
        th = tc(torch.tensor(x))
        h, _ = c(paddle.to_tensor(x))
        np.testing.assert_allclose(_np(h), th.detach().numpy(), atol=1e-5)

    def test_simple_cell(self):
        tc = torch.nn.RNNCell(I, H)
        c = nn.SimpleRNNCell(I, H)
        _copy_cell_from_torch(c, tc, torch_is_cell=True)
        x = _x()[:, 0]
        np.testing.assert_allclose(_np(c(paddle.to_tensor(x))[0]),
                                   tc(torch.tensor(x)).detach().numpy(),
                                   atol=1e-5)


class TestStacksVsTorch:
    @pytest.mark.parametrize("mode,ours,theirs", [
        ("lstm", nn.LSTM, torch.nn.LSTM),
        ("gru", nn.GRU, torch.nn.GRU),
        ("simple", nn.SimpleRNN, torch.nn.RNN),
    ])
    def test_single_layer(self, mode, ours, theirs):
        tm = theirs(I, H, num_layers=1, batch_first=True)
        m = ours(I, H, num_layers=1)
        _copy_cell_from_torch(m.cells[0], tm)
        x = _x(1)
        ty = tm(torch.tensor(x))[0].detach().numpy()
        y, _ = m(paddle.to_tensor(x))
        np.testing.assert_allclose(_np(y), ty, atol=1e-5)

    def test_bidirectional_two_layer_lstm(self):
        tm = torch.nn.LSTM(I, H, num_layers=2, batch_first=True,
                           bidirectional=True)
        m = nn.LSTM(I, H, num_layers=2, direction="bidirect")
        for li in range(2):
            for di in range(2):
                _copy_cell_from_torch(m.cells[li * 2 + di], tm, li, di)
        x = _x(2)
        ty, (thn, tcn) = tm(torch.tensor(x))
        y, (hn, cn) = m(paddle.to_tensor(x))
        np.testing.assert_allclose(_np(y), ty.detach().numpy(), atol=1e-5)
        np.testing.assert_allclose(_np(hn), thn.detach().numpy(), atol=1e-5)
        np.testing.assert_allclose(_np(cn), tcn.detach().numpy(), atol=1e-5)

    def test_initial_states_roundtrip(self):
        tm = torch.nn.GRU(I, H, num_layers=1, batch_first=True)
        m = nn.GRU(I, H)
        _copy_cell_from_torch(m.cells[0], tm)
        h0 = np.random.RandomState(3).randn(1, B, H).astype(np.float32)
        x = _x(3)
        ty, thn = tm(torch.tensor(x), torch.tensor(h0))
        y, hn = m(paddle.to_tensor(x), paddle.to_tensor(h0))
        np.testing.assert_allclose(_np(y), ty.detach().numpy(), atol=1e-5)
        np.testing.assert_allclose(_np(hn), thn.detach().numpy(), atol=1e-5)


class TestRNNWrapperAndTraining:
    def test_rnn_matches_manual_cell_loop(self):
        paddle.seed(7)
        cell = nn.SimpleRNNCell(I, H)
        y, hT = nn.RNN(cell)(paddle.to_tensor(_x(4)))
        st, outs = None, []
        for t in range(T):
            o, st = cell(paddle.to_tensor(_x(4)[:, t]), st)
            outs.append(_np(o))
        np.testing.assert_allclose(_np(y), np.stack(outs, 1), rtol=1e-5)
        np.testing.assert_allclose(_np(hT), outs[-1], rtol=1e-5)

    def test_reverse_direction(self):
        paddle.seed(8)
        cell = nn.GRUCell(I, H)
        y_fwd, _ = nn.RNN(cell)(paddle.to_tensor(_x(5)[:, ::-1].copy()))
        y_rev, _ = nn.RNN(cell, is_reverse=True)(paddle.to_tensor(_x(5)))
        np.testing.assert_allclose(_np(y_rev), _np(y_fwd)[:, ::-1], rtol=1e-5)

    def test_time_major(self):
        paddle.seed(9)
        cell = nn.LSTMCell(I, H)
        x = _x(6)
        y_bm, _ = nn.RNN(cell)(paddle.to_tensor(x))
        y_tm, _ = nn.RNN(cell, time_major=True)(
            paddle.to_tensor(x.transpose(1, 0, 2).copy()))
        np.testing.assert_allclose(_np(y_tm), _np(y_bm).transpose(1, 0, 2),
                                   rtol=1e-5)

    @pytest.mark.slow  # convergence run; fused-scan torch-parity tests
    # stay as the default-run LSTM correctness reps
    def test_lstm_trains(self):
        paddle.seed(10)
        m = nn.LSTM(I, H)
        head = nn.Linear(H, 1)
        opt = paddle.optimizer.Adam(
            learning_rate=1e-2,
            parameters=list(m.parameters()) + list(head.parameters()))
        x = paddle.to_tensor(_x(7))
        tgt = paddle.to_tensor(np.ones((B, 1), np.float32))
        losses = []
        for _ in range(8):
            y, (hn, cn) = m(x)
            pred = head(hn[-1])
            loss = paddle.mean((pred - tgt) * (pred - tgt))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.value))
        assert losses[-1] < losses[0]

    def test_jit_train_step(self):
        paddle.seed(11)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.rnn = nn.GRU(I, H)
                self.fc = nn.Linear(H, 2)

            def forward(self, x):
                y, hn = self.rnn(x)
                return self.fc(hn[-1])

        from paddle_tpu.jit import TrainStep
        net = Net()
        step = TrainStep(net, nn.CrossEntropyLoss(),
                         paddle.optimizer.Adam(learning_rate=1e-2,
                                               parameters=net.parameters()))
        x = paddle.to_tensor(_x(8))
        lab = paddle.to_tensor(np.array([0, 1], np.int64))
        losses = [float(step.step((x,), (lab,)).value) for _ in range(8)]
        assert losses[-1] < losses[0]


class TestCustomCell:
    def test_rnn_accepts_user_cell(self):
        paddle.seed(12)

        class MyCell(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(I, H)
                self.hh = nn.Linear(H, H, bias_attr=False)

            def forward(self, x, states=None):
                import paddle_tpu as p
                pre = self.fc(x) if states is None else \
                    self.fc(x) + self.hh(states)
                h = p.tanh(pre)
                return h, h

        x = paddle.to_tensor(_x(13))
        y, hT = nn.RNN(MyCell())(x)
        assert y.shape == [B, T, H]
        np.testing.assert_allclose(_np(hT), _np(y)[:, -1], rtol=1e-6)
