"""Megatron-SP tests (VERDICT r2 item 5 — fleet/sp.py had zero tests).

Covers: scatter→gather round-trip value preservation, Column/Row
SequenceParallelLinear parity vs plain linears on an mp2 mesh,
reduce-scatter presence in the lowered HLO, and the eager
all_reduce-on-replicated semantics pin (reference:
``python/paddle/distributed/fleet/utils/sequence_parallel_utils.py`` †).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import SGD
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.fleet.sp import (ColumnSequenceParallelLinear,
                                          GatherOp,
                                          RowSequenceParallelLinear,
                                          ScatterOp,
                                          mark_as_sequence_parallel_parameter)


def _reset_fleet(**degrees):
    mesh_mod._STATE["mesh"] = None
    s = fleet.DistributedStrategy()
    s.hybrid_configs = degrees
    fleet.init(is_collective=True, strategy=s)
    return fleet.get_hybrid_communicate_group()


class _SPBlock(nn.Layer):
    """LN -> ColumnSP -> gelu -> RowSP, the Megatron-SP FFN shape."""

    def __init__(self, d, dh):
        super().__init__()
        self.ln = nn.LayerNorm(d)
        mark_as_sequence_parallel_parameter(self.ln.weight)
        mark_as_sequence_parallel_parameter(self.ln.bias)
        self.up = ColumnSequenceParallelLinear(d, dh, gather_output=False)
        self.down = RowSequenceParallelLinear(dh, d, input_is_parallel=True)

    def forward(self, x):
        h = ScatterOp(x)            # [B, S/mp, d] region
        h = self.ln(h)
        h = self.down(nn.functional.gelu(self.up(h)))
        return GatherOp(h)          # back to replicated seq


class TestSequenceParallel:
    def test_scatter_gather_roundtrip(self):
        _reset_fleet(mp_degree=2, dp_degree=4)
        x_np = np.random.RandomState(0).randn(2, 8, 16).astype(np.float32)
        x = paddle.to_tensor(x_np)
        y = GatherOp(ScatterOp(x))
        np.testing.assert_allclose(y.numpy(), x_np)
        # scatter really shards the seq dim on the mesh
        sharded = ScatterOp(x)
        spec = sharded.value.sharding.spec
        assert spec[1] in ("mp", ("mp",)), spec

    def test_sp_linear_parity_vs_plain(self):
        """The SP block must compute the same function as plain linears."""
        _reset_fleet(mp_degree=2, dp_degree=4)
        paddle.seed(123)
        d, dh = 16, 32
        blk = _SPBlock(d, dh)
        x_np = np.random.RandomState(1).randn(4, 8, d).astype(np.float32)
        out = blk(paddle.to_tensor(x_np)).numpy()
        # plain oracle with the same weights
        ln_w, ln_b = blk.ln.weight.numpy(), blk.ln.bias.numpy()
        w1, b1 = blk.up.weight.numpy(), blk.up.bias.numpy()
        w2 = blk.down.weight.numpy()
        b2 = blk.down.bias.numpy()
        mu = x_np.mean(-1, keepdims=True)
        var = x_np.var(-1, keepdims=True)
        h = (x_np - mu) / np.sqrt(var + 1e-5) * ln_w + ln_b
        h = nn.functional.gelu(paddle.to_tensor(h @ w1 + b1)).numpy()
        oracle = h @ w2 + b2
        np.testing.assert_allclose(out, oracle, rtol=2e-4, atol=2e-5)

    def test_sp_train_step_matches_serial(self):
        """Train an SP block on mp2 vs meshless; losses must match."""
        d, dh = 8, 16
        x_np = np.random.RandomState(2).randn(4, 4, d).astype(np.float32)

        def run(on_mesh):
            if on_mesh:
                hcg = _reset_fleet(mp_degree=2, dp_degree=4)
                mesh = hcg.mesh
            else:
                mesh_mod._STATE["mesh"] = None
                mesh = None
            paddle.seed(7)
            blk = _SPBlock(d, dh)
            step = TrainStep(blk, lambda out, _l: (out * out).mean(),
                             SGD(learning_rate=0.05,
                                 parameters=blk.parameters()),
                             mesh=mesh)
            x = paddle.to_tensor(x_np)
            return [float(step.step((x,), (x,)).value) for _ in range(3)]

        np.testing.assert_allclose(run(False), run(True), rtol=1e-4,
                                   atol=1e-6)

    def test_reduce_scatter_in_hlo(self):
        """The Row linear's output reshard must lower to a reduce-scatter
        (not allreduce+slice) on the mp axis — the optimization Megatron-SP
        hand-writes and GSPMD derives."""
        hcg = _reset_fleet(mp_degree=2, dp_degree=4)
        paddle.seed(9)
        blk = _SPBlock(16, 32)
        step = TrainStep(blk, lambda out, _l: (out * out).mean(),
                         SGD(learning_rate=0.05,
                             parameters=blk.parameters()),
                         mesh=hcg.mesh)
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(4, 8, 16).astype(np.float32))
        hlo = step.lower_text((x,), (x,))
        assert "reduce-scatter" in hlo or "all-reduce-scatter" in hlo, \
            "expected a reduce-scatter in the SP train step HLO"

    def test_column_weight_sharded_on_mp(self):
        _reset_fleet(mp_degree=2, dp_degree=4)
        lin = ColumnSequenceParallelLinear(8, 16)
        assert tuple(lin.weight.dist_spec) == (None, "mp")
        row = RowSequenceParallelLinear(16, 8)
        assert tuple(row.weight.dist_spec) == ("mp", None)


class TestEagerCollectiveSemantics:
    """Pin the documented all_reduce semantics (VERDICT r2 weak 6)."""

    def test_allreduce_sharded_sums_shards(self):
        from paddle_tpu.distributed import all_reduce
        mesh_mod._STATE["mesh"] = None
        n = len(jax.devices())
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = mesh_mod.ensure_mesh()
        axes = tuple(mesh.axis_names)
        v = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
        x = paddle.to_tensor(
            jax.device_put(jnp.asarray(v), NamedSharding(mesh, P(axes))))
        all_reduce(x)
        # sharded input = per-rank contributions; result is the reduced
        # (replicated) value with the rank dim collapsed
        np.testing.assert_allclose(x.numpy(), v.sum(0, keepdims=True))

    def test_allreduce_replicated_multiplies_by_nranks(self):
        """Replicated input = N identical per-rank copies; allreduce(sum) of
        N copies is v*N. Pinned as documented behavior."""
        from paddle_tpu.distributed import all_reduce
        mesh_mod._STATE["mesh"] = None
        n = len(jax.devices())
        x = paddle.to_tensor(np.ones((4,), np.float32))
        all_reduce(x)
        np.testing.assert_allclose(x.numpy(), np.full((4,), float(n)))


class TestAllToAllSingle:
    """paddle.distributed.alltoall_single (reference: communication/
    all_to_all.py †): leading dim split into nranks chunks, chunk j to
    rank j, concatenated by source."""

    def test_transposes_chunk_matrix(self):
        from paddle_tpu.distributed import alltoall_single
        mesh_mod._STATE["mesh"] = None
        n = len(jax.devices())
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = mesh_mod.ensure_mesh()
        axes = tuple(mesh.axis_names)
        # global [n*n] layout: rank r holds rows r*n..r*n+n, row r*n+j is
        # the chunk r sends to j; after a2a rank r holds column r
        v = np.arange(n * n, dtype=np.float32).reshape(n * n, 1)
        x = paddle.to_tensor(
            jax.device_put(jnp.asarray(v), NamedSharding(mesh, P(axes))))
        out = paddle.to_tensor(np.zeros_like(v))
        alltoall_single(x, out)
        expect = v.reshape(n, n, 1).transpose(1, 0, 2).reshape(n * n, 1)
        np.testing.assert_allclose(out.numpy(), expect)

    def test_ragged_split_sizes_rejected(self):
        import pytest
        from paddle_tpu.distributed import alltoall_single
        x = paddle.to_tensor(np.ones((8, 2), np.float32))
        with pytest.raises(NotImplementedError, match="split_sizes"):
            alltoall_single(x, in_split_sizes=[3, 5])

    def test_single_rank_group_writes_out_tensor(self):
        # nranks==1: out == in, and the out-tensor contract still holds
        # (the early-return path must rebind, not skip)
        from paddle_tpu.parallel.communication import alltoall_single
        from paddle_tpu.parallel import mesh as _m
        import jax as _jax
        saved = _m._STATE["mesh"]
        try:
            _m._STATE["mesh"] = None
            _m.set_mesh(_m.build_mesh({"dp": 1},
                                      devices=_jax.devices()[:1]))
            x = paddle.to_tensor(np.arange(4, dtype=np.float32))
            out = paddle.to_tensor(np.zeros(4, np.float32))
            alltoall_single(x, out)
            np.testing.assert_array_equal(out.numpy(), [0, 1, 2, 3])
        finally:
            _m._STATE["mesh"] = saved
