"""Continuous-batching serving engine (serving/engine.py, SURVEY §3.5 /
PAPERS.md): slot KV cache, mid-flight admission, EOS early-exit, per-slot
sampling params, and the compile-once contract of the decode step
function. The load-bearing property throughout: a request's token stream
depends only on its own prompt/key — never on batch composition or
admission timing."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (ContinuousBatchingEngine, GenerationRequest,
                                FIFOScheduler, SlotKVCache)


@pytest.fixture(scope="module")
def model():
    paddle.seed(21)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


def _engine(model, **kw):
    # share jitted programs across engines like model.generate does, so
    # the module's tests compile each decode program once
    kw.setdefault("jit_cache", model.__dict__.setdefault("_serving_jit", {}))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 48)
    return ContinuousBatchingEngine(model, **kw)


def _prompt(seed, n=8):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _solo(model, req, **ekw):
    out = _engine(model, **ekw).generate([req])[0]
    return out.tolist()


class TestEngineBasics:
    @pytest.mark.slow  # 9 s generate-parity duplicate: test_mid_flight_admission_
    # matches_solo and the pallas/jnp identity test keep the default reps (870s cap)
    def test_greedy_matches_model_generate(self, model):
        ids = np.stack([_prompt(0), _prompt(1)])
        want = model.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
        outs = _engine(model).generate(
            [GenerationRequest(prompt=ids[i], max_new_tokens=6)
             for i in range(2)])
        np.testing.assert_array_equal(np.stack(outs), want)

    @pytest.mark.slow  # slot-recycling duplicate (bigger traffic of
    # the same property): test_slot_reuse_after_finish and the
    # scheduler unit tests stay the default reps
    def test_queue_longer_than_slots(self, model):
        """5 requests through 2 slots: all finish, all correct."""
        reqs = [GenerationRequest(prompt=_prompt(i), max_new_tokens=4)
                for i in range(5)]
        eng = _engine(model)
        outs = eng.generate(reqs)
        assert len(outs) == 5 and all(len(o) == 4 for o in outs)
        solo = [_solo(model, r) for r in reqs]
        for o, s in zip(outs, solo):
            assert o.tolist() == s
        assert eng.cache.num_free == eng.num_slots  # all slots returned

    def test_submit_validation(self, model):
        eng = _engine(model)
        with pytest.raises(ValueError, match="KV cache"):
            eng.submit(GenerationRequest(prompt=_prompt(0, 40),
                                         max_new_tokens=9))
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(GenerationRequest(prompt=_prompt(0), max_new_tokens=0))


class TestDecodePathEquivalence:
    @pytest.mark.slow  # engine-level pallas≡jnp (two engine builds);
    # the kernel-vs-oracle suites (test_pallas_decode,
    # test_pallas_paged_decode) and test_decode's program-level parity
    # stay the default reps of the same chain
    def test_pallas_and_jnp_tokens_identical(self):
        """The ragged Pallas decode kernel and the jnp oracle produce the
        same greedy continuation AND the same sampled continuation under
        one seed (token-exact, GQA included)."""
        outs = {}
        for attn in ("pallas", "jnp"):
            paddle.seed(33)
            m = LlamaForCausalLM(llama_tiny(decode_attention=attn))
            eng = ContinuousBatchingEngine(m, num_slots=2, max_seq_len=48)
            outs[attn] = eng.generate([
                GenerationRequest(prompt=_prompt(3), max_new_tokens=8),
                GenerationRequest(prompt=_prompt(4), max_new_tokens=8,
                                  temperature=0.8, top_k=7, seed=11)])
        np.testing.assert_array_equal(outs["pallas"][0], outs["jnp"][0])
        np.testing.assert_array_equal(outs["pallas"][1], outs["jnp"][1])


class TestEOS:
    @pytest.mark.slow  # 6 s EOS duplicate: test_generate_eos_pads_output below
    # is the default EOS rep (870s cap)
    def test_eos_early_exit_frees_slot(self, model):
        req = GenerationRequest(prompt=_prompt(5), max_new_tokens=12)
        free_run = _solo(model, req)
        eos = free_run[2]
        stop_at = free_run.index(eos)  # first occurrence wins
        eng = _engine(model)
        seq = eng.submit(GenerationRequest(
            prompt=_prompt(5), max_new_tokens=12, eos_token_id=eos))
        while eng.has_work():
            eng.step()
        assert seq.finish_reason == "stop"  # OpenAI-style reason for EOS
        assert seq.tokens == free_run[:stop_at + 1]  # EOS included
        assert eng.cache.num_free == eng.num_slots
        assert eng.cache.lengths[seq.slot] == 0  # slot really reset

    def test_generate_eos_pads_output(self, model):
        req = GenerationRequest(prompt=_prompt(5), max_new_tokens=12)
        eos = _solo(model, req)[2]
        out = model.generate(paddle.to_tensor(_prompt(5)[None]),
                             max_new_tokens=12, eos_token_id=eos).numpy()
        assert out.shape == (1, 12)
        first = out[0].tolist().index(eos)
        assert all(t == eos for t in out[0][first:])


class TestContinuousBatching:
    def test_mid_flight_admission_matches_solo(self, model):
        """A request admitted into a slot freed mid-flight produces the
        exact tokens of its solo run — greedy and sampled both."""
        late_g = GenerationRequest(prompt=_prompt(6), max_new_tokens=6)
        late_s = GenerationRequest(prompt=_prompt(7), max_new_tokens=6,
                                   temperature=0.9, top_k=5, seed=123)
        solo_g = _solo(model, late_g)
        solo_s = _solo(model, late_s)

        eng = _engine(model, decode_chunk=1)
        long_seq = eng.submit(GenerationRequest(prompt=_prompt(8),
                                                max_new_tokens=20))
        short = eng.submit(GenerationRequest(prompt=_prompt(9),
                                             max_new_tokens=3))
        for _ in range(5):  # short finishes, long still mid-flight
            eng.step()
        assert short.done and not long_seq.done
        lg = eng.submit(late_g)  # admitted into short's freed slot
        for _ in range(3):
            eng.step()
        ls = eng.submit(late_s)  # second reuse, while decode continues
        while eng.has_work():
            eng.step()
        assert lg.tokens == solo_g and ls.tokens == solo_s
        assert long_seq.done and len(long_seq.tokens) == 20

    def test_slot_reuse_after_finish(self, model):
        eng = _engine(model, num_slots=1)
        a = eng.submit(GenerationRequest(prompt=_prompt(10), max_new_tokens=3))
        b = eng.submit(GenerationRequest(prompt=_prompt(11), max_new_tokens=3))
        while eng.has_work():
            eng.step()
        assert a.slot == b.slot == 0  # same physical slot, serially reused
        assert b.tokens == _solo(model, b.request)
        assert eng.stats["prefills"] == 2

    def test_fused_chunks_match_single_steps(self, model):
        """decode_chunk>1 (multi-step fused scan) changes dispatch count,
        never tokens."""
        reqs = [GenerationRequest(prompt=_prompt(12), max_new_tokens=17),
                GenerationRequest(prompt=_prompt(13), max_new_tokens=17,
                                  temperature=0.7, top_k=9, seed=3)]
        eng1 = _engine(model, decode_chunk=1)
        outs1 = eng1.generate([GenerationRequest(**{
            k: getattr(r, k) for k in ("prompt", "max_new_tokens",
                                       "temperature", "top_k", "seed")})
            for r in reqs])
        eng8 = _engine(model, decode_chunk=8)
        outs8 = eng8.generate(reqs)
        for a, b in zip(outs1, outs8):
            np.testing.assert_array_equal(a, b)
        assert eng8.stats["decode_calls"] < eng1.stats["decode_calls"]


class TestCompileOnce:
    def test_decode_compiles_once_across_request_mixes(self, model):
        """One decode trace serves every (max_new, temperature, top_k)
        mix — the knob arrays are runtime values, not trace constants."""
        # fresh jit cache: count only this (num_slots, max_seq_len)'s traces
        eng = _engine(model, decode_chunk=1, jit_cache={})
        eng.generate([GenerationRequest(prompt=_prompt(14), max_new_tokens=4)])
        assert eng.decode_compilations() == 1
        eng.generate([
            GenerationRequest(prompt=_prompt(15), max_new_tokens=7,
                              temperature=1.3, top_k=11, seed=8),
            GenerationRequest(prompt=_prompt(16, n=5), max_new_tokens=2,
                              temperature=0.4, top_k=0, seed=9)])
        assert eng.decode_compilations() == 1

    @pytest.mark.slow  # model.generate compile-reuse duplicate:
    # test_generate's jit-cache-reused + engine≡model.generate
    # (test_greedy_matches_model_generate) and the engine-level
    # request-mix closure stay the default reps
    def test_model_generate_shares_decode_program(self, model):
        """model.generate() rides the same compile-once contract when the
        cache length is pinned: sampling-knob changes add no traces.
        (model.generate inherits the paged engine default, so the
        programs counted are the unified "ragged" kind.)"""
        t = paddle.to_tensor(np.stack([_prompt(17)]))
        m = model

        def decode_traces():
            return sum(fn._cache_size()
                       for key, fn in m._serving_jit.items()
                       if key[0] == "ragged")

        before = decode_traces()  # other tests share this model's cache
        m.generate(t, max_new_tokens=6, max_cache_len=32)
        n0 = decode_traces()
        # sampling-knob changes: zero new decode traces
        m.generate(t, max_new_tokens=6, temperature=0.7, top_k=3,
                   seed=1, max_cache_len=32)
        m.generate(t, max_new_tokens=6, temperature=1.1, top_k=0,
                   seed=2, max_cache_len=32)
        assert decode_traces() == n0
        # a different token budget may add pow2 step sizes but stays
        # within the bounded level set {1, 2, 4, ..., decode_chunk}
        m.generate(t, max_new_tokens=4, max_cache_len=32)
        import math
        chunk = 16  # model.generate's engine decode_chunk
        assert decode_traces() - before <= int(math.log2(chunk)) + 1


class TestFinishReasons:
    """Engine-level finish_reason surface (no gateway involved): the
    closed vocabulary stop|length|cancelled|timeout, surfaced both on
    the Sequence handle and on generate()'s GenerationResult."""

    def test_generate_results_carry_finish_reason(self, model):
        from paddle_tpu.serving import GenerationResult
        eng = _engine(model)
        probe = eng.generate([GenerationRequest(prompt=_prompt(30),
                                                max_new_tokens=8)])[0]
        eos = probe[2]
        outs = eng.generate([
            GenerationRequest(prompt=_prompt(30), max_new_tokens=8,
                              eos_token_id=int(eos)),
            GenerationRequest(prompt=_prompt(31), max_new_tokens=4)])
        assert all(isinstance(o, GenerationResult) for o in outs)
        assert outs[0].finish_reason == "stop"
        assert outs[1].finish_reason == "length"
        # array-likeness: the old ndarray call sites keep working
        assert len(outs[1]) == 4
        np.testing.assert_array_equal(np.stack([outs[1], outs[1]])[0],
                                      outs[1].ids)

    def test_cancel_running_frees_slot_mid_decode(self, model):
        eng = _engine(model, decode_chunk=1)
        victim = eng.submit(GenerationRequest(prompt=_prompt(32),
                                              max_new_tokens=30))
        bystander = eng.submit(GenerationRequest(prompt=_prompt(33),
                                                 max_new_tokens=10))
        solo = _solo(model, bystander.request)
        for _ in range(4):
            eng.step()
        assert victim.status == "running"
        free_before = eng.cache.num_free
        assert eng.cancel(victim) is True
        assert victim.finish_reason == "cancelled"
        assert eng.cache.num_free == free_before + 1  # slot back NOW
        assert eng.cache.lengths[victim.slot] == 0
        assert eng.cancel(victim) is False  # idempotent on finished
        while eng.has_work():
            eng.step()
        assert bystander.tokens == solo  # cancel never perturbs others
        assert eng.stats["cancelled"] == 1

    def test_cancel_queued_never_prefills(self, model):
        eng = _engine(model, num_slots=1)
        hog = eng.submit(GenerationRequest(prompt=_prompt(34),
                                           max_new_tokens=6))
        queued = eng.submit(GenerationRequest(prompt=_prompt(35),
                                              max_new_tokens=6))
        eng.step()  # hog takes the only slot
        assert queued.status == "queued"
        assert eng.cancel(queued) is True
        while eng.has_work():
            eng.step()
        assert queued.finish_reason == "cancelled"
        assert eng.stats["prefills"] == 1  # only the hog ever prefilled
        assert hog.finish_reason == "length"

    def test_timeout_running_and_queued(self, model):
        import time as _time
        eng = _engine(model, num_slots=1, max_seq_len=64, decode_chunk=1)
        # warm the programs so the deadline measures steps, not compiles
        eng.generate([GenerationRequest(prompt=_prompt(36),
                                        max_new_tokens=2)])
        runner = eng.submit(GenerationRequest(
            prompt=_prompt(36), max_new_tokens=50, timeout_s=0.03))
        starved = eng.submit(GenerationRequest(
            prompt=_prompt(37), max_new_tokens=4, timeout_s=0.01))
        prefills0 = eng.stats["prefills"]
        while eng.has_work():
            eng.step()
            _time.sleep(0.002)  # keep wall moving on fast boxes
        assert runner.finish_reason == "timeout"
        assert 0 < len(runner.tokens) < 50  # partial output preserved
        # the starved request expired in the queue: no slot, no prefill
        # (the +1 is the runner's own admission)
        assert starved.finish_reason == "timeout"
        assert starved.tokens == []
        assert eng.stats["prefills"] == prefills0 + 1
        assert eng.stats["timeouts"] == 2
        assert eng.cache.num_free == eng.num_slots

    def test_timeout_validation(self, model):
        eng = _engine(model)
        with pytest.raises(ValueError, match="timeout_s"):
            eng.submit(GenerationRequest(prompt=_prompt(38),
                                         max_new_tokens=2, timeout_s=0))

    def test_on_token_callback_streams_every_token(self, model):
        """on_token fires once per generated token in order, including
        the prefill-sampled first token — the gateway's wire."""
        eng = _engine(model, decode_chunk=1)
        seen = []
        eng.on_token = lambda seq, tok: seen.append((seq.request_id, tok))
        done = []
        eng.on_finish = lambda seq: done.append(seq.request_id)
        seq = eng.submit(GenerationRequest(prompt=_prompt(39),
                                           max_new_tokens=5))
        while eng.has_work():
            eng.step()
        assert [t for _, t in seen] == seq.tokens
        assert done == [seq.request_id]


class TestKVCacheManager:
    def test_alloc_free_cycle(self):
        c = SlotKVCache(2, 3, 16, 2, 8)
        slots = [c.alloc() for _ in range(3)]
        assert slots == [0, 1, 2] and c.alloc() is None
        c.free(1)
        assert c.num_free == 1 and c.alloc() == 1
        with pytest.raises(ValueError, match="double-freed"):
            c.free(1) or c.free(1)

    def test_lengths_reset_on_free(self):
        c = SlotKVCache(2, 2, 16, 2, 8)
        s = c.alloc()
        c.lengths[s] = 9
        c.free(s)
        assert c.lengths[s] == 0

    def test_heap_allocator_deterministic_and_double_free_guarded(self):
        """The heap+set allocator (replacing the O(n) list scan /
        sort-on-alloc): lowest-free-index order survives interleaved
        frees, and the double-free guard stays O(1) AND correct across
        alloc/free cycles — the regression the membership set pins."""
        c = SlotKVCache(2, 4, 16, 2, 8)
        assert [c.alloc() for _ in range(4)] == [0, 1, 2, 3]
        c.free(2)
        c.free(0)
        c.free(3)
        assert c.alloc() == 0          # lowest index first, always
        assert c.alloc() == 2
        c.free(2)                      # re-free after re-alloc is legal
        with pytest.raises(ValueError, match="double-freed"):
            c.free(2)                  # immediate double-free caught
        assert c.alloc() == 2          # guard never corrupted the pool
        assert c.alloc() == 3 and c.alloc() is None
        assert c.num_free == 0


class TestScheduler:
    def test_fifo_admission_order(self):
        sched = FIFOScheduler()
        sched.submit("a"); sched.submit("b"); sched.submit("c")
        assert sched.admissions(2) == ["a", "b"]
        assert sched.admissions(2) == ["c"]

    def test_remove_while_queued_vs_after_admission_pop(self):
        """remove() edge cases: a queued sequence is droppable exactly
        once; a sequence already popped by admissions() (mid-admission
        group, no longer the scheduler's to drop) returns False — the
        engine relies on that to distinguish 'never claims a slot' from
        'already being prefilled' in cancel/deadline paths."""
        sched = FIFOScheduler()
        sched.submit("a"); sched.submit("b"); sched.submit("c")
        assert sched.remove("b") is True       # queued: dropped
        assert sched.remove("b") is False      # idempotent
        popped = sched.admissions(2)
        assert popped == ["a", "c"]
        assert sched.remove("a") is False      # mid-admission: not ours
        assert sched.num_queued == 0
        sched.submit("d")
        assert sched.remove("d") is True and sched.num_queued == 0

    def test_hit_aware_admission_orders_by_suffix_keeps_fifo_set(self):
        """With a hit_len_fn the admitted SET is still the FIFO head
        (fairness), ordered by ascending uncovered suffix so same-bucket
        prefills group; ties keep FIFO order (stable sort)."""
        class S:
            def __init__(self, name, plen):
                # work_len is what admission orders by (== prompt_len
                # unless restored for recovery-by-recompute)
                self.name, self.work_len = name, plen
                self.prefix_hit_tokens = 0
        a, b, c, d = S("a", 40), S("b", 48), S("c", 40), S("d", 8)
        sched = FIFOScheduler()
        for s in (a, b, c, d):
            sched.submit(s)
        hits = {"a": 0, "b": 32, "c": 0}
        out = sched.admissions(3, hit_len_fn=lambda s: hits[s.name])
        # d never jumps the line despite its tiny prompt
        assert [s.name for s in out] == ["b", "a", "c"]  # suffixes 16,40,40
        assert out[0].prefix_hit_tokens == 32
        assert [s.name for s in sched.admissions(2)] == ["d"]

    def test_chunk_fusion_policy(self):
        class S:  # stub sequence
            def __init__(self, remaining):
                self.remaining = remaining

        sched = FIFOScheduler(decode_chunk=8)
        assert sched.choose_num_steps([S(20), S(9)]) == 8
        # near-finisher: largest pow2 within its remaining budget
        assert sched.choose_num_steps([S(20), S(7)]) == 4
        assert sched.choose_num_steps([S(20), S(1)]) == 1
        sched.submit("queued")
        assert sched.choose_num_steps([S(20), S(20)]) == 1  # admission due
