"""Async serving gateway (serving/server/): localhost integration tests.

The properties under test, per the serving contract:

- HTTP output is the ENGINE's output: blocking and SSE completions
  reproduce ``engine.generate()`` token-for-token for the same seeded
  request (the gateway adds no device work and no nondeterminism);
- cancellation (client disconnect or handle.cancel()) frees the KV slot
  mid-decode (``num_free`` recovers) and never perturbs other streams;
- deadlines expire queued AND running requests with
  ``finish_reason="timeout"``;
- admission control sheds load at the waiting-room bound (429);
- ``GET /metrics`` renders valid Prometheus text (validated by the
  strict parser from test_metrics_prom) with the serving series;
- graceful drain finishes in-flight work and 503s new work;
- the compile-once contract survives mixed HTTP traffic: varied
  sampling knobs, prompt lengths, a cancellation and a timeout leave
  ``decode_compilations() == 1``.
"""
import http.client
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import ContinuousBatchingEngine, GenerationRequest
from paddle_tpu.serving.server import (QueueFullError, ServingGateway,
                                       ServingHTTPServer, serve)

from test_metrics_prom import parse_prometheus

NUM_SLOTS, S_MAX, MAX_QUEUE = 2, 128, 4


@pytest.fixture(scope="module")
def model():
    paddle.seed(21)
    return LlamaForCausalLM(llama_tiny())  # GQA tiny, pallas decode path


@pytest.fixture(scope="module")
def server(model):
    srv = serve(model, port=0, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
                max_queue=MAX_QUEUE, model_name="llama-tiny-test")
    # warm every program shape the tests hit (decode, prefill groups of
    # 1 and 2) so latency-sensitive cases measure steps, not compiles
    a = srv.gateway.submit(GenerationRequest(prompt=_prompt(0),
                                             max_new_tokens=2))
    b = srv.gateway.submit(GenerationRequest(prompt=_prompt(1),
                                             max_new_tokens=2))
    a.result(), b.result()
    yield srv
    srv.shutdown(drain=False, timeout=30)


def _prompt(seed, n=8):
    return np.random.RandomState(seed).randint(0, 256, (n,)).tolist()


def _direct(model, req):
    """The oracle: the same request straight through the engine."""
    eng = ContinuousBatchingEngine(
        model, num_slots=NUM_SLOTS, max_seq_len=S_MAX, decode_chunk=1,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}))
    out = eng.generate([req])[0]
    return out.tolist(), out.finish_reason


def _post(server, payload, timeout=120):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        server.url + "/v1/completions", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e), dict(e.headers)


def _sse(server, payload, timeout=120):
    """POST with stream=true; return (tokens, finish_reason, usage)."""
    body = json.dumps(dict(payload, stream=True)).encode()
    req = urllib.request.Request(server.url + "/v1/completions", data=body)
    toks, reason, usage = [], None, None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        for line in r:
            line = line.decode().strip()
            if not line.startswith("data: "):
                continue
            data = line[len("data: "):]
            if data == "[DONE]":
                break
            ev = json.loads(data)
            ch = ev["choices"][0]
            if ch["finish_reason"] is not None:
                reason, usage = ch["finish_reason"], ev.get("usage")
            elif ch["token_id"] is not None:
                toks.append(ch["token_id"])
    return toks, reason, usage


class TestCompletions:
    def test_blocking_matches_direct_engine(self, model, server):
        req = GenerationRequest(prompt=_prompt(2), max_new_tokens=6)
        want, want_reason = _direct(model, req)
        status, doc, _ = _post(server, {"prompt": _prompt(2),
                                        "max_tokens": 6})
        assert status == 200 and doc["object"] == "text_completion"
        choice = doc["choices"][0]
        assert choice["token_ids"] == want
        assert choice["finish_reason"] == want_reason == "length"
        assert doc["usage"] == {"prompt_tokens": 8, "completion_tokens": 6,
                                "total_tokens": 14}

    def test_sse_stream_matches_direct_engine_sampled(self, model, server):
        """Seeded sampled request: the SSE token-by-token stream equals
        the offline engine run exactly — per-request key chains make
        tokens independent of serving-side batching."""
        knobs = dict(max_new_tokens=7, temperature=0.9, top_k=5, seed=123)
        want, _ = _direct(model, GenerationRequest(prompt=_prompt(3),
                                                   **knobs))
        toks, reason, usage = _sse(server, {
            "prompt": _prompt(3), "max_tokens": 7, "temperature": 0.9,
            "top_k": 5, "seed": 123})
        assert toks == want
        assert reason == "length"
        assert usage["completion_tokens"] == 7

    def test_eos_maps_to_stop(self, model, server):
        free = _direct(model, GenerationRequest(prompt=_prompt(4),
                                                max_new_tokens=12))[0]
        eos = free[2]
        status, doc, _ = _post(server, {
            "prompt": _prompt(4), "max_tokens": 12, "eos_token_id": eos})
        assert status == 200
        choice = doc["choices"][0]
        assert choice["finish_reason"] == "stop"
        assert choice["token_ids"] == free[:free.index(eos) + 1]

    def test_validation_400(self, server):
        for bad in ({"max_tokens": 4},                       # no prompt
                    {"prompt": "text"},                      # not ids
                    {"prompt": [1, 2], "max_tokens": 0},
                    {"prompt": [1] * 200, "max_tokens": 8}):  # > cache
            status, doc, _ = _post(server, bad)
            assert status == 400, bad
            assert doc["error"]["type"] == "invalid_request"

    def test_unknown_routes_404(self, server):
        status, doc, _ = _post(server, {})
        assert status in (400, 404)
        try:
            urllib.request.urlopen(server.url + "/nope", timeout=10)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404

    def test_healthz(self, server):
        with urllib.request.urlopen(server.url + "/healthz",
                                    timeout=10) as r:
            doc = json.load(r)
        assert doc["status"] == "ok"
        assert doc["num_slots"] == NUM_SLOTS


class TestCancellation:
    def test_cancel_mid_stream_frees_slot(self, model, server):
        """Iterate a few tokens, cancel, and the slot returns to the
        free list while a concurrent stream finishes byte-identical to
        its solo run."""
        gw = server.gateway
        eng = gw.engine
        free0 = eng.cache.num_free
        bystander_req = GenerationRequest(prompt=_prompt(5),
                                          max_new_tokens=40)
        want, _ = _direct(model, bystander_req)
        bystander = gw.submit(GenerationRequest(prompt=_prompt(5),
                                                max_new_tokens=40))
        victim = gw.submit(GenerationRequest(prompt=_prompt(6),
                                             max_new_tokens=100))
        it = iter(victim)
        got = [next(it) for _ in range(3)]
        victim.cancel()
        # cancellation lands at the next step boundary: tokens already
        # decoded before it applies still stream out, then it stops
        tail = list(it)
        assert victim.finish_reason == "cancelled"
        assert len(got) == 3 and len(got) + len(tail) < 100
        ids, reason = bystander.result()
        assert ids.tolist() == want and reason == "length"
        deadline = time.monotonic() + 5
        while eng.cache.num_free != free0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.cache.num_free == free0  # both slots back

    def test_http_client_disconnect_cancels(self, server):
        """Dropping the SSE connection mid-stream cancels the request:
        the engine's cancelled counter ticks and the slot frees."""
        gw = server.gateway
        eng = gw.engine
        free0 = eng.cache.num_free
        cancelled0 = eng.stats["cancelled"]
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=30)
        conn.request("POST", "/v1/completions", json.dumps(
            {"prompt": _prompt(7), "max_tokens": 110, "stream": True}))
        resp = conn.getresponse()
        assert resp.status == 200
        # read a couple of SSE events, then vanish (closing with unread
        # data in the recv buffer RSTs the server's next write)
        resp.fp.readline(), resp.fp.readline()
        resp.close()
        conn.close()
        deadline = time.monotonic() + 10
        while (eng.stats["cancelled"] == cancelled0
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert eng.stats["cancelled"] == cancelled0 + 1
        deadline = time.monotonic() + 5
        while eng.cache.num_free != free0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.cache.num_free == free0


class TestDeadlines:
    def test_running_timeout_over_http(self, server):
        eng = server.gateway.engine
        free0 = eng.cache.num_free
        status, doc, _ = _post(server, {
            "prompt": _prompt(8), "max_tokens": 119, "timeout_s": 0.05})
        assert status == 200
        choice = doc["choices"][0]
        assert choice["finish_reason"] == "timeout"
        assert 0 < len(choice["token_ids"]) < 119  # partial output kept
        deadline = time.monotonic() + 5
        while eng.cache.num_free != free0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.cache.num_free == free0

    def test_queued_timeout_never_claims_slot(self, server):
        """A request whose deadline expires while still queued times out
        without a prefill (the slot goes to live work instead)."""
        gw = server.gateway
        eng = gw.engine
        hogs = [gw.submit(GenerationRequest(prompt=_prompt(9 + i),
                                            max_new_tokens=60))
                for i in range(NUM_SLOTS)]
        while gw.queue_depth:          # hogs admitted to slots
            time.sleep(0.005)
        prefills0 = eng.stats["prefills"]
        doomed = gw.submit(GenerationRequest(
            prompt=_prompt(11), max_new_tokens=50, timeout_s=0.01))
        ids, reason = doomed.result()
        assert reason == "timeout" and len(ids) == 0
        for h in hogs:
            assert h.result()[1] == "length"  # bystanders unaffected
        assert eng.stats["prefills"] == prefills0 + 0  # doomed never prefilled


class TestAdmissionControl:
    def test_429_when_waiting_room_full(self, server):
        gw = server.gateway
        hogs = [gw.submit(GenerationRequest(prompt=_prompt(20 + i),
                                            max_new_tokens=100))
                for i in range(NUM_SLOTS)]
        while gw.queue_depth:
            time.sleep(0.005)
        queued = [gw.submit(GenerationRequest(prompt=_prompt(30 + i),
                                              max_new_tokens=4))
                  for i in range(MAX_QUEUE)]
        with pytest.raises(QueueFullError):
            gw.submit(GenerationRequest(prompt=_prompt(40),
                                        max_new_tokens=4))
        status, doc, headers = _post(server, {"prompt": _prompt(41),
                                              "max_tokens": 4})
        assert status == 429
        assert doc["error"]["type"] == "rate_limit"
        assert headers.get("Retry-After") == "1"
        for s in hogs + queued:        # drain so later tests start clean
            s.result()


class TestMetricsEndpoint:
    def test_scrape_parses_with_required_series(self, server):
        _post(server, {"prompt": _prompt(50), "max_tokens": 3})
        with urllib.request.urlopen(server.url + "/metrics",
                                    timeout=10) as r:
            assert r.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            text = r.read().decode()
        fams = parse_prometheus(text)  # strict: raises on format errors
        assert fams["serving_queue_depth"]["type"] == "gauge"
        assert fams["serving_active_slots"]["type"] == "gauge"
        assert fams["serving_num_slots"]["samples"][
            ("serving_num_slots", ())] == NUM_SLOTS
        assert fams["serving_generated_tokens_total"]["type"] == "counter"
        assert fams["serving_generated_tokens_total"]["samples"][
            ("serving_generated_tokens_total", ())] > 0
        lat = fams["serving_request_latency_seconds"]
        assert lat["type"] == "histogram"
        assert lat["samples"][
            ("serving_request_latency_seconds_count", ())] > 0
        ttft = fams["serving_ttft_seconds"]["samples"]
        assert ttft[("serving_ttft_seconds_count", ())] > 0
        # finish reasons accumulated under labels
        fin = fams["serving_finished_total"]["samples"]
        assert any(lab == (("reason", "length"),) for (_, lab) in fin)


class TestGracefulDrain:
    def test_drain_finishes_inflight_then_503(self, model):
        """Own server: shutdown(drain=True) lets queued + running work
        finish (finish_reason intact, tokens consumable afterwards),
        then the front door 503s."""
        srv = serve(model, port=0, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
                    max_queue=8, model_name="drain-test")
        gw = srv.gateway
        streams = [gw.submit(GenerationRequest(prompt=_prompt(60 + i),
                                               max_new_tokens=10 + i))
                   for i in range(4)]
        url = srv.url
        srv.shutdown(drain=True, timeout=60)
        assert [s.finish_reason for s in streams] == ["length"] * 4
        ids, _ = streams[2].result()   # events survive the drain
        assert len(ids) == 12
        with pytest.raises(Exception):
            gw.submit(GenerationRequest(prompt=_prompt(70),
                                        max_new_tokens=2))

    def test_shutdown_without_drain_cancels(self, model):
        srv = serve(model, port=0, num_slots=1, max_seq_len=S_MAX,
                    max_queue=8, model_name="cancel-test")
        gw = srv.gateway
        streams = [gw.submit(GenerationRequest(prompt=_prompt(80 + i),
                                               max_new_tokens=110))
                   for i in range(3)]
        srv.shutdown(drain=False, timeout=30)
        # everything not already finished was cancelled; nothing hangs
        assert all(s.finish_reason in ("cancelled", "length")
                   for s in streams)
        assert any(s.finish_reason == "cancelled" for s in streams)


class TestCompileOnce:
    def test_mixed_http_traffic_keeps_one_decode_trace(self, model):
        """The acceptance pin: varied sampling knobs, varied prompt
        lengths, a cancellation, and a timeout over HTTP leave
        ``decode_compilations() == 1`` — serving adds zero retraces."""
        from paddle_tpu.serving.server.gateway import ServingGateway
        eng = ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX, decode_chunk=1,
            jit_cache={})  # fresh cache: count only this engine's traces
        gw = ServingGateway(eng, max_queue=8)
        srv = ServingHTTPServer(gw, port=0).start()
        try:
            _post(srv, {"prompt": _prompt(90), "max_tokens": 5})
            assert eng.decode_compilations() == 1
            _post(srv, {"prompt": _prompt(91), "max_tokens": 9,
                        "temperature": 1.1, "top_k": 7, "seed": 4})
            _post(srv, {"prompt": _prompt(92, n=13), "max_tokens": 3,
                        "temperature": 0.4, "seed": 9})
            toks, reason, _ = _sse(srv, {"prompt": _prompt(93, n=5),
                                         "max_tokens": 6, "seed": 1,
                                         "temperature": 0.7, "top_k": 3})
            assert len(toks) == 6 and reason == "length"
            # cancellation leg
            victim = gw.submit(GenerationRequest(prompt=_prompt(94),
                                                 max_new_tokens=100))
            next(iter(victim))
            victim.cancel()
            # timeout leg
            _, t_reason = gw.submit(GenerationRequest(
                prompt=_prompt(95), max_new_tokens=119,
                timeout_s=0.05)).result()
            assert t_reason == "timeout"
            assert eng.decode_compilations() == 1  # the whole point
        finally:
            srv.shutdown(drain=False, timeout=30)
