"""paddle.sparse + paddle.quantization tests (SURVEY §2.2 row 26 — both
packages were absent). Reference surfaces: ``python/paddle/sparse/`` †,
``python/paddle/quantization/`` †.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, sparse
from paddle_tpu.quantization import PTQ, QAT, QuantConfig, fake_quant


class TestSparseCoo:
    def _coo(self):
        indices = [[0, 1, 2], [1, 2, 0]]
        values = [1.0, 2.0, 3.0]
        return sparse.sparse_coo_tensor(indices, values, shape=[3, 3])

    def test_create_and_dense_roundtrip(self):
        s = self._coo()
        d = s.to_dense().numpy()
        expect = np.zeros((3, 3), np.float32)
        expect[0, 1], expect[1, 2], expect[2, 0] = 1, 2, 3
        np.testing.assert_allclose(d, expect)
        assert s.nnz == 3
        np.testing.assert_array_equal(s.indices().numpy(),
                                      [[0, 1, 2], [1, 2, 0]])

    def test_csr_views(self):
        crows = [0, 2, 3, 5]
        cols = [1, 3, 2, 0, 1]
        vals = [1.0, 2.0, 3.0, 4.0, 5.0]
        s = sparse.sparse_csr_tensor(crows, cols, vals, [3, 4])
        d = s.to_dense().numpy()
        expect = np.zeros((3, 4), np.float32)
        expect[0, 1], expect[0, 3], expect[1, 2] = 1, 2, 3
        expect[2, 0], expect[2, 1] = 4, 5
        np.testing.assert_allclose(d, expect)
        np.testing.assert_array_equal(s.crows().numpy(), crows)
        np.testing.assert_array_equal(s.cols().numpy(), cols)

    def test_unary_preserves_pattern(self):
        s = self._coo()
        r = sparse.relu(sparse.neg(s))
        assert r.nnz == 3
        np.testing.assert_allclose(r.to_dense().numpy(), 0.0)

    @pytest.mark.slow  # 8 s spmm duplicate: test_masked_matmul_sddmm below
    # keeps the default sparse-matmul rep (870s cap)
    def test_spmm_matches_dense(self):
        rng = np.random.RandomState(0)
        dense = rng.randn(4, 5).astype(np.float32)
        dense[dense < 0.3] = 0.0
        s = sparse.to_sparse_coo(paddle.to_tensor(dense))
        y = rng.randn(5, 6).astype(np.float32)
        out = sparse.matmul(s, paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(out, dense @ y, rtol=1e-5, atol=1e-5)

    def test_masked_matmul_sddmm(self):
        rng = np.random.RandomState(1)
        x = rng.randn(3, 4).astype(np.float32)
        y = rng.randn(4, 3).astype(np.float32)
        mask = sparse.sparse_coo_tensor([[0, 1, 2], [1, 2, 0]],
                                        [1.0, 1.0, 1.0], shape=[3, 3])
        out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                                   mask)
        full = x @ y
        d = out.to_dense().numpy()
        np.testing.assert_allclose(d[0, 1], full[0, 1], rtol=1e-5)
        np.testing.assert_allclose(d[1, 2], full[1, 2], rtol=1e-5)
        assert d[0, 0] == 0.0

    def test_add_and_transpose(self):
        s = self._coo()
        two = sparse.add(s, s)
        np.testing.assert_allclose(two.to_dense().numpy(),
                                   2 * s.to_dense().numpy())
        t = sparse.transpose(s, [1, 0])
        np.testing.assert_allclose(t.to_dense().numpy(),
                                   s.to_dense().numpy().T)


class TestQuantization:
    def test_fake_quant_ste_grad(self):
        """STE: gradient passes through inside the clip range, zero outside."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.quantization import _fake_quant_ste

        def f(x):
            return jnp.sum(_fake_quant_ste(x, jnp.float32(1.0), 8))

        x = jnp.asarray([0.5, -0.3, 2.0, -1.5])
        g = jax.grad(f)(x)
        np.testing.assert_allclose(np.asarray(g), [1.0, 1.0, 0.0, 0.0])

    def test_fake_quant_error_bounded(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(64).astype(np.float32))
        y = fake_quant(x, float(np.abs(x.numpy()).max()), 8)
        step = np.abs(x.numpy()).max() / 127
        assert np.max(np.abs(y.numpy() - x.numpy())) <= step * 0.5 + 1e-6

    def _model(self):
        paddle.seed(99)

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 16)
                self.fc2 = nn.Linear(16, 4)

            def forward(self, x):
                return self.fc2(nn.functional.relu(self.fc1(x)))

        return M()

    @pytest.mark.slow  # 6 s QAT train duplicate: test_qat_gradients_flow below
    # keeps the default QAT rep (870s cap)
    def test_qat_quantize_swaps_and_stays_close(self):
        from paddle_tpu.quantization import QuantedLinear
        m = self._model()
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(4, 8).astype(np.float32))
        ref = m(x).numpy()
        qat = QAT(QuantConfig(weight_bits=8, activation_bits=8))
        qm = qat.quantize(m)
        assert isinstance(qm.fc1, QuantedLinear)
        out = qm(x).numpy()
        # 8-bit fake quant stays close to the float forward
        assert np.max(np.abs(out - ref)) < 0.15, np.max(np.abs(out - ref))

    def test_qat_gradients_flow(self):
        m = self._model()
        qm = QAT(QuantConfig()).quantize(m)
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(4, 8).astype(np.float32))
        loss = (qm(x) ** 2).mean()
        loss.backward()
        assert qm.fc1.weight.grad is not None
        assert np.any(np.abs(qm.fc1.weight.grad.numpy()) > 0)

    @pytest.mark.slow  # 6 s convert duplicate: test_converted_linear_dequant_
    # follows_input_dtype below is the default PTQ rep (870s cap)
    def test_ptq_observe_convert_int8(self):
        from paddle_tpu.quantization import ConvertedLinear, ObservedLinear
        m = self._model()
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(16, 8).astype(np.float32))
        ref = m(x).numpy()
        ptq = PTQ(QuantConfig(weight_bits=8, activation_bits=8))
        om = ptq.quantize(m)
        assert isinstance(om.fc1, ObservedLinear)
        om(x)  # calibration pass populates observers
        assert float(om.fc1.observer.scale.numpy()) > 0
        cm = ptq.convert(om)
        assert isinstance(cm.fc1, ConvertedLinear)
        assert cm.fc1.qweight.numpy().dtype == np.int8
        out = cm(x).numpy()
        assert np.max(np.abs(out - ref)) < 0.15

    def test_converted_linear_dequant_follows_input_dtype(self):
        """The int8 inference path composes with bf16 autocast: the
        dequantized weight follows the INPUT dtype instead of forcing
        fp32 (which silently promoted the whole matmul back)."""
        import jax.numpy as jnp
        m = self._model()
        ptq = PTQ(QuantConfig(weight_bits=8, activation_bits=8))
        om = ptq.quantize(m)
        x32 = paddle.to_tensor(
            np.random.RandomState(4).randn(4, 8).astype(np.float32))
        om(x32)
        cm = ptq.convert(om)
        ref = cm(x32).numpy()

        # direct bf16 input (no autocast): output stays bf16
        x16 = paddle.to_tensor(x32.value.astype(jnp.bfloat16))
        out16 = cm(x16)
        assert out16.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            out16.value.astype(jnp.float32), ref, atol=0.1)

        # under autocast O1 the quantized forward runs end-to-end bf16
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            out_ac = cm(x32)
        assert out_ac.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            out_ac.value.astype(jnp.float32), ref, atol=0.1)


class TestWeightOnlyInt8:
    """ISSUE 14 satellite: ConvertedLinear's scales are PER-CHANNEL and
    hoisted to convert time, and the weight-only conversion surface is
    idempotent."""

    def _model(self):
        import paddle_tpu.nn as nn

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                paddle.seed(7)
                self.fc1 = nn.Linear(8, 16)
                self.fc2 = nn.Linear(16, 4)

            def forward(self, x):
                return self.fc2(self.fc1(x))

        return M()

    def test_per_channel_scales_hoisted_to_convert_time(self):
        from paddle_tpu.quantization import (ConvertedLinear,
                                             convert_weights_int8,
                                             quantize_weight_int8)
        m = convert_weights_int8(self._model())
        assert isinstance(m.fc1, ConvertedLinear)
        # one scale PER OUTPUT CHANNEL ([1, out] for the [in, out]
        # layout), computed once at convert time — not per call
        assert m.fc1.w_scale.shape == [1, 16]
        assert m.fc1.qweight.numpy().dtype == np.int8
        # the shared helper is the single quantization rule
        w = paddle.to_tensor(
            np.random.RandomState(9).randn(8, 16).astype(np.float32))
        q, s = quantize_weight_int8(w.value, reduce_axis=0)
        deq = np.asarray(q, np.float32) * np.asarray(s)
        assert np.all(np.abs(deq - w.numpy()) <= np.asarray(s) / 2 + 1e-7)

    def test_per_channel_beats_per_tensor_on_outlier_channel(self):
        from paddle_tpu.quantization import quantize_weight_int8
        rng = np.random.RandomState(11)
        w = rng.randn(8, 16).astype(np.float32)
        w[:, 3] *= 100.0                     # one outlier channel
        q, s = quantize_weight_int8(w, reduce_axis=0)
        deq = np.asarray(q, np.float32) * np.asarray(s)
        # per-tensor absmax would flatten every other channel's
        # resolution to ~absmax/127 ≈ 2.4; per-channel keeps them sharp
        err = np.abs(deq - w)[:, [c for c in range(16) if c != 3]]
        assert err.max() < 0.05

    def test_convert_weights_int8_idempotent(self):
        from paddle_tpu.quantization import (ConvertedLinear,
                                             convert_weights_int8)
        m = convert_weights_int8(self._model())
        fc1, q1 = m.fc1, m.fc1.qweight
        m2 = convert_weights_int8(m)        # quantize(quantize(m))
        # a no-op: same layer objects, same int8 arrays — the second
        # pass must never re-quantize an int8 weight (which would
        # double the quantization error)
        assert m2.fc1 is fc1 and m2.fc1.qweight is q1
        x = paddle.to_tensor(
            np.random.RandomState(12).randn(4, 8).astype(np.float32))
        np.testing.assert_array_equal(m(x).numpy(), m2(x).numpy())
        assert isinstance(m2.fc2, ConvertedLinear)

    def test_ptq_convert_idempotent_and_bias_dtype_under_autocast(self):
        import jax.numpy as jnp
        from paddle_tpu.quantization import ConvertedLinear
        m = self._model()
        ref = None
        x = paddle.to_tensor(
            np.random.RandomState(13).randn(4, 8).astype(np.float32))
        ptq = PTQ(QuantConfig(weight_bits=8, activation_bits=8))
        om = ptq.quantize(m)
        om(x)
        cm = ptq.convert(om)
        ref = cm(x).numpy()
        cm2 = ptq.convert(cm)               # convert(convert(m)): no-op
        assert cm2.fc1 is cm.fc1
        np.testing.assert_array_equal(cm2(x).numpy(), ref)
        # bias dtype follows the activation dtype under bf16 autocast
        # (an fp32 bias would silently re-promote the whole matmul)
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            out = cm(x)
        assert out.dtype == jnp.bfloat16
        assert isinstance(cm.fc1, ConvertedLinear)
        np.testing.assert_allclose(out.value.astype(jnp.float32), ref,
                                   atol=0.15)


class TestTensorToSparseR5:
    """Tensor.to_sparse_coo / to_sparse_csr method spellings vs scipy."""

    def test_roundtrip_and_csr_layout(self):
        import scipy.sparse as sp
        rng = np.random.RandomState(47)
        d = rng.rand(5, 6).astype(np.float32); d[d < 0.6] = 0
        t = paddle.to_tensor(d)
        sc = t.to_sparse_coo(2)
        np.testing.assert_allclose(sc.to_dense().numpy(), d)
        csr = t.to_sparse_csr()
        ref = sp.csr_matrix(d)
        np.testing.assert_array_equal(np.asarray(csr.crows().numpy()),
                                      ref.indptr)
        np.testing.assert_array_equal(np.asarray(csr.cols().numpy()),
                                      ref.indices)
        np.testing.assert_allclose(np.asarray(csr.values().numpy()),
                                   ref.data)

    def test_validation(self):
        import pytest
        t = paddle.to_tensor(np.zeros((2, 3), np.float32))
        with pytest.raises(ValueError, match="sparse_dim"):
            t.to_sparse_coo(5)
        with pytest.raises(NotImplementedError, match="hybrid"):
            t.to_sparse_coo(1)  # hybrid layouts refused, not mis-handled
        with pytest.raises(ValueError, match="2-D"):
            paddle.to_tensor(np.zeros((2, 3, 4), np.float32)).to_sparse_csr()
