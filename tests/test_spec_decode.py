"""Speculative multi-token decode on the paged path (ISSUE 9): draft →
ragged-span verify → block-tail rollback (README "Speculative
decoding"). The load-bearing properties:

- **Transparency**: token streams with speculation ON are
  byte-identical to speculation OFF — greedy AND seeded-sampled,
  across a hit/miss/chunked/cancel matrix — acceptance only reorders
  work; ``decode_compilations() == 1`` including the verify geometry.
- **Rollback accounting**: rejected draft K/V hands its blocks back
  exactly (``PagedKVCache.truncate``): num_free restored after full
  rejection, shared/donated prefix blocks never truncated, refcounts
  untouched, cancel-mid-verify restores the pool.
- **The speed structure**: with an accepting drafter a launch advances
  a slot by more than one token (fewer launches than tokens).
- **Drafters**: prompt-lookup n-gram proposals (model-free default)
  and the tiny-draft-model path behind one interface.
- **Fault interplay**: a fatal fault mid-speculation recovers
  byte-identically — ``restore()`` recomputes from ACCEPTED tokens
  only; unverified draft K/V never survives a rebuild.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (BlockManager, ContinuousBatchingEngine,
                                Drafter, FaultPlan, GenerationRequest,
                                ModelDrafter, NgramDrafter, PagedKVCache,
                                FIFOScheduler)

BS = 8       # KV block size
CHUNK = 16   # chunked-prefill budget (2 blocks)
SPEC_K = 3


@pytest.fixture(scope="module")
def model():
    paddle.seed(33)
    return LlamaForCausalLM(llama_tiny())  # GQA tiny, pallas decode


def _engine(model, **kw):
    kw.setdefault("jit_cache", {})  # isolated: decode_compilations()==1
    # pins need identical pool geometry per cache (see PR-7 notes)
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    return ContinuousBatchingEngine(model, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _req(ps, n=20, **kw):
    kw.setdefault("max_new_tokens", 8)
    return GenerationRequest(prompt=_prompt(ps, n), **kw)


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             eos_token_id=r.eos_token_id, seed=r.seed)


class _Seq:
    """Host-only stand-in for drafter unit tests."""

    def __init__(self, prompt, tokens=()):
        self.prompt = np.asarray(prompt, np.int32)
        self.tokens = list(tokens)


class _JunkDrafter(Drafter):
    """Always proposes the same (almost surely wrong) tokens — the
    full-rejection instrument."""

    def propose(self, seq, k):
        return np.full(int(k), 7, np.int32)


class TestNgramDrafter:
    def test_matches_most_recent_ngram_continuation(self):
        d = NgramDrafter(max_ngram=3, min_ngram=1)
        #           0  1  2  3  4  5  6  7   tail [2,3] matches @2..3
        s = _Seq([9, 8, 2, 3, 5, 6, 2, 3])
        assert d.propose(s, 2).tolist() == [5, 6]
        # continuation capped at k
        assert d.propose(s, 1).tolist() == [5]

    def test_generated_tokens_extend_the_history(self):
        d = NgramDrafter()
        s = _Seq([1, 2, 3, 4], tokens=[1, 2])   # history ...3,4,1,2
        assert d.propose(s, 4).tolist() == [3, 4, 1, 2]

    def test_most_recent_occurrence_wins(self):
        d = NgramDrafter(max_ngram=1)
        s = _Seq([5, 1, 5, 2, 5])     # unigram 5: latest earlier @2
        assert d.propose(s, 1).tolist() == [2]

    def test_no_match_and_short_history_edges(self):
        d = NgramDrafter()
        assert d.propose(_Seq([1, 2, 3, 4]), 4).size == 0   # no repeat
        assert d.propose(_Seq([1]), 4).size == 0            # too short
        assert d.propose(_Seq([1, 1]), 0).size == 0         # k == 0
        # [1, 1]: unigram tail matches position 0, continuation = [1]
        assert d.propose(_Seq([1, 1]), 4).tolist() == [1]

    def test_validation(self):
        with pytest.raises(ValueError, match="max_ngram"):
            NgramDrafter(max_ngram=0)
        with pytest.raises(ValueError, match="max_ngram"):
            NgramDrafter(max_ngram=1, min_ngram=2)


class TestSpecGrants:
    def test_greedy_in_order_with_budget(self):
        s = FIFOScheduler()
        assert s.spec_grants([3, 3, 3], 5) == [3, 2, 0]
        assert s.spec_grants([2, 2], 10) == [2, 2]
        assert s.spec_grants([4], 0) == [0]
        assert s.spec_grants([4], -3) == [0]   # over-spent plan clamps
        assert s.spec_grants([], 7) == []


class TestTruncate:
    def _cache(self, blocks=12):
        pool = BlockManager(1, blocks, BS, 1, 4)
        return PagedKVCache(1, 2, 6 * BS, 1, 4, block_size=BS,
                            pool=pool), pool

    def test_frees_exactly_the_private_tail(self):
        cache, pool = self._cache()
        slot = cache.alloc()
        cache.ensure_capacity(slot, 4 * BS)       # 4 private blocks
        assert pool.num_free == 12 - 4
        cache.lengths[slot] = 2 * BS + 3
        cache.truncate(slot, BS + 2)              # keep ceil(10/8) = 2
        assert pool.num_free == 12 - 2
        assert int(cache._n_blocks[slot]) == 2
        assert int(cache.lengths[slot]) == BS + 2     # clamped down
        assert all(int(b) == cache.sentinel
                   for b in cache.tables[slot, 2:])
        # covering rows: no-op
        cache.truncate(slot, BS + 2)
        assert pool.num_free == 12 - 2
        # regrowth reuses the heap
        cache.ensure_capacity(slot, 4 * BS)
        assert pool.num_free == 12 - 4

    def test_never_touches_shared_prefix_blocks(self):
        cache, pool = self._cache()
        shared = [pool.alloc(), pool.alloc()]
        for b in shared:
            pool.ref(b)                  # the trie's pins (readers')
        slot = cache.alloc()
        cache.install_prefix(slot, shared)
        cache.ensure_capacity(slot, 3 * BS)   # + 1 private block
        refs_before = [pool.refcount(b) for b in shared]
        free_before = pool.num_free
        # rows=0 would reach into the shared prefix: clamped, only the
        # private tail drops
        cache.truncate(slot, 0)
        assert [pool.refcount(b) for b in shared] == refs_before
        assert int(cache._n_blocks[slot]) == 2
        assert pool.num_free == free_before + 1
        for j, b in enumerate(shared):
            assert int(cache.tables[slot, j]) == b   # still installed


class TestValidation:
    def test_spec_requires_paged(self, model):
        with pytest.raises(ValueError, match="paged"):
            _engine(model, paged_attn=False, spec_decode=True)

    def test_spec_k_validated(self, model):
        with pytest.raises(ValueError, match="spec_k"):
            _engine(model, spec_decode=True, spec_k=0)


class TestTransparency:
    @pytest.mark.slow  # 17 s transparency matrix duplicate: the drafter/eos/
    # compile-isolation reps below run by default (870s cap)
    def test_spec_equals_baseline_mixed_matrix(self, model):
        """The acceptance pin: a hit/miss/chunked/cancel traffic matrix
        — shared system prompt, greedy and seeded-sampled rows, a long
        prompt that chunks, a mid-prefill cancellation — streams
        byte-identical between ``spec_decode=True`` (prompt-lookup
        drafts) and speculation off, with ONE verify-program trace."""
        sysp = _prompt(90, 32)

        def drive(spec):
            eng = _engine(model, spec_decode=spec, spec_k=SPEC_K,
                          prefix_cache=True, prefix_blocks=32)
            outs = []
            for wave in range(2):
                reqs = [_req(1, n=40), _req(2, n=21),
                        GenerationRequest(
                            prompt=np.concatenate([sysp, _prompt(3, 9)]),
                            max_new_tokens=6),
                        GenerationRequest(
                            prompt=np.concatenate([sysp, _prompt(4, 15)]),
                            max_new_tokens=5, temperature=0.8, top_k=4,
                            seed=7),
                        _req(5, n=33, temperature=0.9, top_k=5, seed=123)]
                seqs = [eng.submit(_clone(r)) for r in reqs]
                victim = eng.submit(_req(7, n=70))
                steps = 0
                while eng.has_work():
                    eng.step()
                    steps += 1
                    if steps == 4 and victim.status == "prefilling":
                        eng.cancel(victim)   # mid-chunk cancellation
                outs.append([s.tokens for s in seqs])
            return outs, eng

        want, base = drive(False)
        got, eng = drive(True)
        assert got == want
        assert eng.decode_compilations() == 1
        assert eng.stats["spec_steps"] > 0
        assert eng.stats["spec_proposed"] > 0
        assert base.stats["spec_steps"] == 0
        assert eng.prefix_cache.stats["hits"] >= 1
        assert eng.stats["prefill_chunks"] >= 1   # chunks rode the
        # same one-launch-per-step verify program

    def test_decode_compilations_isolates_spec_k_variants(self, model):
        """Two spec engines sharing one jit cache and a packed budget
        (the chunk term of the max dominates both) but differing in
        spec_k trace two DIFFERENT verify programs — each engine must
        count exactly its own (the spec_len key-filter regression)."""
        cache = {}
        a = _engine(model, spec_decode=True, spec_k=2, jit_cache=cache)
        b = _engine(model, spec_decode=True, spec_k=3, jit_cache=cache)
        assert a._spec_budget == b._spec_budget   # the hazard is real
        a.generate([_req(91, max_new_tokens=3)])
        b.generate([_req(92, max_new_tokens=3)])
        assert a.decode_compilations() == 1
        assert b.decode_compilations() == 1

    @pytest.mark.slow  # 6 s launch-count duplicate: the eos and compile-
    # isolation reps in this class run by default (870s cap)
    def test_accepting_drafter_fewer_launches_than_tokens(self, model):
        """With the always-accept oracle (the target model drafting for
        itself) a launch advances a slot by up to spec_k + 1 tokens:
        fewer verify launches than generated tokens, streams still
        byte-identical — the speed structure the bench banks."""
        want = [o.tolist() for o in _engine(model).generate(
            [_req(11, max_new_tokens=12), _req(12, max_new_tokens=12)])]
        eng = _engine(model, spec_decode=True, spec_k=SPEC_K,
                      drafter=ModelDrafter(model))
        launches = {"n": 0}
        orig = eng._spec_fn
        eng._spec_fn = lambda: (launches.__setitem__(
            "n", launches["n"] + 1) or orig())
        outs = eng.generate(
            [_req(11, max_new_tokens=12), _req(12, max_new_tokens=12)])
        assert [o.tolist() for o in outs] == want
        assert eng.stats["spec_accepted"] > 0
        assert launches["n"] < eng.stats["spec_tokens"]
        # greedy self-drafting accepts fully: mean emitted per span > 2
        assert eng.stats["spec_tokens"] > 2 * launches["n"]

    def test_eos_mid_acceptance_stops_the_stream(self, model):
        """An accepted draft token equal to EOS must finish the
        sequence exactly where sequential decode would — tokens past it
        are never emitted even when the verify accepted further."""
        base = _engine(model).generate(
            [_req(21, max_new_tokens=24, eos_token_id=3)])
        eng = _engine(model, spec_decode=True, spec_k=SPEC_K,
                      drafter=ModelDrafter(model))
        outs = eng.generate([_req(21, max_new_tokens=24, eos_token_id=3)])
        assert [o.tolist() for o in outs] == [b.tolist() for b in base]
        assert outs[0].finish_reason == base[0].finish_reason


class TestRollbackAccounting:
    def test_full_rejection_restores_pool_exactly(self, model):
        """A drafter that is always wrong: every verify writes k draft
        rows and truncates them all back. Streams stay byte-identical
        (the correction token is the model's own) and after retirement
        the pool is exactly restored — no leaked, no double-freed
        blocks."""
        want = [o.tolist() for o in _engine(model).generate(
            [_req(31), _req(32, n=33)])]
        eng = _engine(model, spec_decode=True, spec_k=SPEC_K,
                      drafter=_JunkDrafter())
        pool = eng.cache.pool
        nfree0 = pool.num_free
        outs = eng.generate([_req(31), _req(32, n=33)])
        assert [o.tolist() for o in outs] == want
        assert eng.stats["spec_proposed"] > 0
        # junk drafts verified and rolled back; occasional flukes aside
        # the acceptance stays near zero
        assert eng.stats["spec_accepted"] <= eng.stats["spec_proposed"] / 2
        assert pool.num_free == nfree0
        assert int((pool._ref > 0).sum()) == 0

    def test_cancel_mid_verify_restores_pool(self, model):
        eng = _engine(model, spec_decode=True, spec_k=SPEC_K,
                      drafter=ModelDrafter(model))
        pool = eng.cache.pool
        nfree0 = pool.num_free
        seq = eng.submit(_req(41, max_new_tokens=40))
        other = eng.submit(_req(42, max_new_tokens=6))
        for _ in range(3):
            eng.step()
        assert seq.status == "running"
        eng.cancel(seq)                  # mid-speculation teardown
        while eng.has_work():
            eng.step()
        assert other.done and seq.finish_reason == "cancelled"
        assert pool.num_free == nfree0
        assert int((pool._ref > 0).sum()) == 0

    def test_donated_blocks_survive_rollback_traffic(self, model):
        """With the prefix trie on, retirement donates written chains;
        later speculative traffic truncates only private tails — every
        pool block ends up free or trie-owned, refcounts exact."""
        eng = _engine(model, spec_decode=True, spec_k=SPEC_K,
                      prefix_cache=True, prefix_blocks=16,
                      drafter=_JunkDrafter())
        reqs = [_req(51, n=24, max_new_tokens=10),
                _req(51, n=24, max_new_tokens=10),   # hits the donation
                _req(52, n=17, max_new_tokens=10)]
        for r in reqs:
            eng.generate([r])
        pool = eng.cache.pool
        trie_blocks = eng.prefix_cache.num_cached_blocks
        assert pool.num_used == trie_blocks      # free or trie-owned
        assert int((pool._ref > 0).sum()) == 0   # trie holds no pins
        assert eng.prefix_cache.stats["hits"] >= 1


class TestFaultInterplay:
    def test_fatal_mid_speculation_recovers_byte_identical(self, model):
        """The chaos satellite: a NaN-corrupting fatal fault lands
        while drafts are in flight; the supervisor rebuilds and
        ``restore()`` recomputes from ACCEPTED tokens only, so every
        stream continues byte-identically — unverified draft K/V (and
        the corrupted pool) never survive the rebuild."""
        from paddle_tpu.serving.server import ServingGateway
        reqs = [_req(61, max_new_tokens=10), _req(62, n=26,
                                                  max_new_tokens=10),
                _req(63, temperature=0.9, top_k=5, seed=9,
                     max_new_tokens=8)]
        want = [o.tolist() for o in _engine(model).generate(
            [_clone(r) for r in reqs])]
        cache = {}
        drafter = ModelDrafter(model)

        def factory():
            return _engine(model, spec_decode=True, spec_k=SPEC_K,
                           drafter=drafter, jit_cache=cache)

        plan = FaultPlan().at_step(4, "nan")
        gw = ServingGateway(factory(), engine_factory=factory,
                            fault_hook=plan, max_restarts=4,
                            retry_backoff_s=0.0, start=False)
        streams = [gw.submit(_clone(r)) for r in reqs]
        gw.start()
        outs = [st.result() for st in streams]
        gw.shutdown(drain=True, timeout=60)
        assert [list(ids) for ids, _ in outs] == want
        assert gw.restarts == 1
        assert plan.log == [(4, "nan")]
        assert gw.engine.decode_compilations() == 1   # shared factory
        # cache: the rebuild re-traced nothing

    @pytest.mark.slow  # 6 s fault duplicate: test_fatal_mid_speculation_
    # recovers_byte_identical above is the default fault rep (870s cap)
    def test_restore_recomputes_from_accepted_tokens_only(self, model):
        """Engine-level restore pin: displace a speculating sequence
        mid-flight; its recompute work is prompt + ACCEPTED tokens
        (drafts never entered ``seq.tokens``) and the continuation is
        byte-identical."""
        want = _engine(model).generate(
            [_req(71, max_new_tokens=14)])[0].tolist()
        eng = _engine(model, spec_decode=True, spec_k=SPEC_K,
                      drafter=ModelDrafter(model))
        seq = eng.submit(_req(71, max_new_tokens=14))
        for _ in range(3):
            eng.step()
        assert 0 < len(seq.tokens) < 14
        eng._preempt(seq)                 # donate + requeue (recompute)
        assert seq.status == "queued"
        assert len(seq.work) == seq.prompt_len + len(seq.tokens) - 1
        while eng.has_work():
            eng.step()
        assert seq.tokens == want


class TestMetricsSurface:
    def test_spec_metrics_strict_parsed(self, model):
        """serving_spec_proposed_total / serving_spec_accepted_total,
        the serving_spec_accept_length histogram (SPEC_ACCEPT_BUCKETS
        ladder) and the launches-per-accepted-token gauge land on
        /metrics, valid under the strict v0.0.4 parser, reading the
        engine's own stats."""
        from test_metrics_prom import parse_prometheus

        from paddle_tpu.profiler.metrics import SPEC_ACCEPT_BUCKETS
        from paddle_tpu.serving.server import ServingGateway
        cache = {}
        drafter = ModelDrafter(model)

        def factory():
            return _engine(model, spec_decode=True, spec_k=SPEC_K,
                           drafter=drafter, jit_cache=cache)

        gw = ServingGateway(factory(), engine_factory=factory,
                            start=False)
        streams = [gw.submit(_req(81, max_new_tokens=10)),
                   gw.submit(_req(82, max_new_tokens=8))]
        gw.start()
        for st in streams:
            st.result()
        eng = gw.engine
        # scrape after the driver exits: the acceptance-length drain
        # runs post-step on the driver thread
        gw.shutdown(drain=True, timeout=60)
        fams = parse_prometheus(gw.registry.render())
        assert fams["serving_spec_proposed_total"]["samples"][
            ("serving_spec_proposed_total", ())] == \
            eng.stats["spec_proposed"]
        assert fams["serving_spec_accepted_total"]["samples"][
            ("serving_spec_accepted_total", ())] == \
            eng.stats["spec_accepted"]
        name = "serving_spec_accept_length"
        assert fams[name]["type"] == "histogram"
        le = [k for k in fams[name]["samples"] if k[0] == name + "_bucket"]
        bounds = {lbl[1] for _, lbls in le for lbl in lbls
                  if lbl[0] == "le"}
        assert len(bounds) == len(SPEC_ACCEPT_BUCKETS) + 1   # + +Inf
        # the driver drained every verify span into the histogram: the
        # observation total is the emitted-token total, one acceptance
        # length per span
        assert fams[name]["samples"][(name + "_sum", ())] == \
            eng.stats["spec_tokens"]
        assert fams[name]["samples"][(name + "_count", ())] > 0
        assert eng.stats["spec_last_accept"] == []   # fully drained
        # decode_calls, not spec_steps: chunk-only launches carry no
        # verify rows and must not inflate the launches-per-token ratio
        g = "serving_spec_launches_per_accepted_token"
        assert fams[g]["samples"][(g, ())] == pytest.approx(
            eng.stats["decode_calls"] / max(eng.stats["spec_tokens"], 1))
