"""Define-and-run static engine tests (reference: ``paddle.static``
Program/Executor semantics — ``test/legacy_test/test_executor_*`` †
pattern: build under program_guard, run with feeds, compare against the
dygraph oracle)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


def _build_mlp(prog):
    paddle.seed(7)
    with static.program_guard(prog):
        x = static.data("x", shape=[-1, 4])
        fc1 = paddle.nn.Linear(4, 8)
        fc2 = paddle.nn.Linear(8, 3)
        h = paddle.nn.functional.relu(fc1(x))
        out = fc2(h)
    return (fc1, fc2), x, out


class TestStaticProgram:
    def test_capture_and_replay_matches_eager(self):
        prog = static.StaticProgram()
        (fc1, fc2), x, out = _build_mlp(prog)
        exe = static.Executor()
        xs = np.random.RandomState(0).rand(5, 4).astype(np.float32)
        res, = exe.run(prog, feed={"x": xs}, fetch_list=[out])
        ref = fc2(paddle.nn.functional.relu(fc1(paddle.to_tensor(xs))))
        np.testing.assert_allclose(res, ref.numpy(), rtol=1e-5)

    def test_new_feed_new_result(self):
        prog = static.StaticProgram()
        _, x, out = _build_mlp(prog)
        exe = static.Executor()
        a = np.ones((2, 4), np.float32)
        r1, = exe.run(prog, feed={"x": a}, fetch_list=[out])
        r2, = exe.run(prog, feed={"x": 2 * a}, fetch_list=[out])
        assert not np.allclose(r1, r2)

    def test_missing_feed_raises(self):
        prog = static.StaticProgram()
        _, x, out = _build_mlp(prog)
        with pytest.raises(ValueError, match="missing feeds"):
            static.Executor().run(prog, feed={}, fetch_list=[out])

    def test_op_names_recorded(self):
        prog = static.StaticProgram()
        _build_mlp(prog)
        names = prog.op_names()
        assert names.count("linear") == 2 and "relu" in names

    def test_weights_snapshot_at_build(self):
        # persistable vars are captured by value at record time (define-
        # time snapshot, like a serialized ProgramDesc)
        prog = static.StaticProgram()
        (fc1, fc2), x, out = _build_mlp(prog)
        exe = static.Executor()
        a = np.ones((2, 4), np.float32)
        r1, = exe.run(prog, feed={"x": a}, fetch_list=[out])
        fc1.weight.set_value(np.zeros_like(fc1.weight.numpy()))
        r2, = exe.run(prog, feed={"x": a}, fetch_list=[out])
        np.testing.assert_allclose(r1, r2)

    def test_multiple_fetches_and_intermediate(self):
        prog = static.StaticProgram()
        with static.program_guard(prog):
            x = static.data("x", shape=[-1, 3])
            h = paddle.nn.functional.relu(x)
            s = paddle.sum(h)
        xs = np.array([[-1.0, 0.5, 2.0]], np.float32)
        h_v, s_v = static.Executor().run(prog, feed={"x": xs},
                                         fetch_list=[h, s])
        np.testing.assert_allclose(h_v, np.maximum(xs, 0))
        np.testing.assert_allclose(s_v, 2.5)

    def test_nested_guard_restores_outer(self):
        p1, p2 = static.StaticProgram(), static.StaticProgram()
        with static.program_guard(p1):
            x1 = static.data("a", shape=[2])
            with static.program_guard(p2):
                x2 = static.data("b", shape=[2])
                paddle.exp(x2)
            paddle.tanh(x1)
        assert p1.op_names() == ["tanh"] and p2.op_names() == ["exp"]

    def test_default_main_program_exists(self):
        assert isinstance(static.default_main_program(),
                          static.StaticProgram)
        assert isinstance(static.default_startup_program(),
                          static.StaticProgram)

    def test_unjitted_run_matches_jitted(self):
        prog = static.StaticProgram()
        _, x, out = _build_mlp(prog)
        exe = static.Executor()
        a = np.random.RandomState(1).rand(3, 4).astype(np.float32)
        rj, = exe.run(prog, feed={"x": a}, fetch_list=[out], jit=True)
        re_, = exe.run(prog, feed={"x": a}, fetch_list=[out], jit=False)
        np.testing.assert_allclose(rj, re_, rtol=1e-6)

    def test_feed_shape_validation(self):
        prog = static.StaticProgram()
        _, x, out = _build_mlp(prog)
        with pytest.raises(ValueError, match="expected"):
            static.Executor().run(prog, feed={"x": np.ones((2, 5), np.float32)},
                                  fetch_list=[out])
        # batch dim is -1: any batch size accepted
        r, = static.Executor().run(
            prog, feed={"x": np.ones((7, 4), np.float32)}, fetch_list=[out])
        assert r.shape == (7, 3)

    def test_symbolic_dim_reads_as_minus_one(self):
        """ADVICE r3: data() with a -1 dim must not let build-time shape
        reads bake batch=1. The placeholder's .shape returns the declared
        spec (-1 stays -1, reference static-mode contract), so
        reshape(x.shape[0], ...) records -1 and infers per-feed."""
        prog = static.StaticProgram()
        with static.program_guard(prog):
            x = static.data("x", shape=[-1, 4])
            assert x.shape == [-1, 4]  # not [1, 4]
            y = paddle.reshape(x, [x.shape[0], 2, 2])
            out = paddle.sum(y, axis=[1, 2])
        for batch in (3, 5):
            a = np.ones((batch, 4), np.float32)
            r, = static.Executor().run(prog, feed={"x": a},
                                       fetch_list=[out])
            assert r.shape == (batch,)
            np.testing.assert_allclose(r, np.full(batch, 4.0))

    def test_bypass_dispatch_warns(self):
        import warnings
        from paddle_tpu.core.tensor import Tensor as RawTensor
        prog = static.StaticProgram()
        with static.program_guard(prog):
            x = static.data("x", shape=[2])
            # raw construction bypassing dispatch: frozen as a constant
            frozen = RawTensor(np.ones(2, np.float32))
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                paddle.add(frozen, x)
            assert any("BUILD-TIME CONSTANT" in str(wi.message) for wi in w)

    def test_static_strict_promotes_hazard_to_error(self):
        """FLAGS_static_strict: a data()-derived tensor flowing around
        the dispatch (here: rebuilt from the placeholder's host value —
        the classic silent-freeze bug) is CAUGHT as an error instead of
        a warning; the same capture keeps working with the flag off."""
        from paddle_tpu.core.tensor import Tensor as RawTensor
        paddle.set_flags({"FLAGS_static_strict": True})
        try:
            prog = static.StaticProgram()
            with static.program_guard(prog):
                x = static.data("x", shape=[2])
                # derives from the placeholder but bypasses dispatch:
                # the feed would be silently ignored at replay
                leaked = RawTensor(np.asarray(x.numpy() + 1.0))
                with pytest.raises(RuntimeError,
                                   match="BUILD-TIME CONSTANT"):
                    paddle.add(leaked, x)
        finally:
            paddle.set_flags({"FLAGS_static_strict": False})
        # flag off: same construction degrades to the warning, and the
        # frozen value really is a build-time constant at replay
        import warnings
        prog = static.StaticProgram()
        with static.program_guard(prog):
            x = static.data("x", shape=[2])
            leaked = RawTensor(np.asarray(x.numpy() + 1.0))
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                out = paddle.add(leaked, x)
            assert any("BUILD-TIME CONSTANT" in str(wi.message)
                       for wi in w)
        r, = static.Executor().run(
            prog, feed={"x": np.asarray([5.0, 5.0], np.float32)},
            fetch_list=[out])
        # leaked froze at build-time values (zeros + 1), ignoring the feed
        np.testing.assert_allclose(r, [6.0, 6.0])


class TestInferenceModelSaveLoad:
    """static.save_inference_model / load_inference_model (reference
    deployment pair †): the captured program's pure replay exported as
    StableHLO with feeds as (symbolic-batch) arguments, reloadable and
    runnable through the same Executor.run contract."""

    def _build(self):
        paddle.seed(0)
        main = static.StaticProgram()
        with static.program_guard(main):
            x = static.data("x", [-1, 4], "float32")
            lin = paddle.nn.Linear(4, 3)
            y = paddle.nn.functional.relu(lin(x))
        return main, x, y

    def test_roundtrip_dynamic_batch(self, tmp_path):
        main, x, y = self._build()
        exe = static.Executor()
        prefix = str(tmp_path / "infer")
        static.save_inference_model(prefix, [x], [y], exe, program=main)
        # feed/fetch metadata rides in .pdmeta — NOT .pdiparams, whose
        # real-paddle format is serialized parameters (weights are baked
        # into the StableHLO .pdmodel here)
        assert sorted(p.name for p in tmp_path.iterdir()) == [
            "infer.pdmeta", "infer.pdmodel"]
        prog, feeds, fetches = static.load_inference_model(prefix, exe)
        assert feeds == ["x"]
        for b in (5, 9):
            xs = np.random.RandomState(b).randn(b, 4).astype(np.float32)
            ref = exe.run(main, feed={"x": xs}, fetch_list=[y])[0]
            out = exe.run(prog, feed={"x": xs}, fetch_list=fetches)[0]
            np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_prunes_training_only_feeds(self, tmp_path):
        # the canonical train-then-deploy flow: label feeds the loss only
        # and must drop out of the exported inference graph
        paddle.seed(0)
        main = static.StaticProgram()
        with static.program_guard(main):
            x = static.data("x", [-1, 4], "float32")
            label = static.data("label", [-1, 1], "float32")
            lin = paddle.nn.Linear(4, 1)
            y = lin(x)
            loss = ((y - label) ** 2).mean()  # noqa: F841 (training half)
        exe = static.Executor()
        prefix = str(tmp_path / "pruned")
        static.save_inference_model(prefix, [x], [y], exe, program=main)
        prog, feeds, fetches = static.load_inference_model(prefix, exe)
        assert feeds == ["x"]
        xs = np.ones((3, 4), np.float32)
        ref = exe.run(main, feed={"x": xs, "label": np.zeros((3, 1),
                                                            np.float32)},
                      fetch_list=[y])[0]
        out = exe.run(prog, feed={"x": xs}, fetch_list=fetches)[0]
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_legacy_pdiparams_sidecar_still_loads(self, tmp_path):
        """Back-compat: artifacts from before the .pdmeta rename kept
        their metadata in a .pdiparams-named sidecar; load falls back
        to it when no .pdmeta exists."""
        import os
        main, x, y = self._build()
        exe = static.Executor()
        prefix = str(tmp_path / "legacy")
        static.save_inference_model(prefix, [x], [y], exe, program=main)
        os.rename(prefix + ".pdmeta", prefix + ".pdiparams")
        prog, feeds, fetches = static.load_inference_model(prefix, exe)
        assert feeds == ["x"]
        xs = np.ones((2, 4), np.float32)
        ref = exe.run(main, feed={"x": xs}, fetch_list=[y])[0]
        out = exe.run(prog, feed={"x": xs}, fetch_list=fetches)[0]
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_missing_required_feed_raises(self, tmp_path):
        main = static.StaticProgram()
        with static.program_guard(main):
            x = static.data("x", [-1, 4], "float32")
            y = paddle.nn.functional.relu(x)
        with pytest.raises(ValueError, match="depend on feeds"):
            static.save_inference_model(str(tmp_path / "m"), [], [y],
                                        program=main)

    def test_two_dynamic_inputs_share_batch(self, tmp_path):
        # both feeds share the batch axis: one shared symbol must let
        # add(a, b) export (independent symbols fail shape checks)
        main = static.StaticProgram()
        with static.program_guard(main):
            a = static.data("a", [-1, 4], "float32")
            b = static.data("b", [-1, 4], "float32")
            c = a + b
        exe = static.Executor()
        prefix = str(tmp_path / "two")
        static.save_inference_model(prefix, [a, b], [c], exe, program=main)
        prog, feeds, fetches = static.load_inference_model(prefix, exe)
        for n in (2, 6):
            av = np.full((n, 4), 2.0, np.float32)
            bv = np.full((n, 4), 3.0, np.float32)
            out = exe.run(prog, feed={"a": av, "b": bv},
                          fetch_list=fetches)[0]
            np.testing.assert_allclose(out, 5.0)
