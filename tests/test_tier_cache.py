"""Tiered KV prefix cache (ISSUE 16): host-RAM spill tier behind
PrefixCache + the fleet cache plane (README "Tiered KV prefix cache").

The acceptance matrix:

- **Transparency**: token streams with the tier on are byte-identical
  to the tier-off engine AND to the cache-disabled engine — greedy and
  seeded-sampled — under eviction thrash that spills and readmits
  whole chains (the tier changes WHERE a hit's KV comes from, never
  what gets sampled). The int8-KV pool rides the same pin with its
  scale planes spilled and readmitted alongside.
- **Default-off**: ``host_tier_bytes=0`` constructs no tier, moves no
  bytes, and leaves every tier stat at zero — banked baselines cannot
  shift.
- **Compile-once**: the fetch/inject transfer pair is lru-cached per
  pool geometry (``kv_cache.tier_compilations``), readmission adds no
  jit keys, and ``decode_compilations() == 1`` holds through spill/
  readmit churn.
- **HostTier unit**: content-chained digests, LRU trim under the byte
  budget with descendant cascade (no unreachable orphans), oversize
  entries degrade to empty-never-over-budget.
- **Fleet cache plane**: a routed request about to miss on its replica
  pulls the spilled chain host-to-host from the sibling that evicted
  it (digest-addressed, by reference), the readmission is a local tier
  hit, the stream stays byte-identical, and the transfer shows up on
  ``/fleet/cacheplane``, ``/debug/fleet`` and the fleet metrics.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (BlockManager, ContinuousBatchingEngine,
                                GenerationRequest, HostTier, PrefixCache)
from paddle_tpu.serving.fleet import EngineFleet
from paddle_tpu.serving.kv_cache import tier_compilations

from test_metrics_prom import parse_prometheus

BS = 8       # KV block size
CHUNK = 16   # chunked-prefill budget (2 blocks)
TIER = 1 << 24   # a generous host budget: LRU never trims in the legs


@pytest.fixture(scope="module")
def model():
    paddle.seed(29)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


def _engine(model, **kw):
    kw.setdefault("jit_cache", model.__dict__.setdefault("_serving_jit", {}))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    return ContinuousBatchingEngine(model, **kw)


#: two 2-block system-prompt families; under a 2-block trie budget only
#: one family is resident at a time, so alternating them thrashes:
#: every family switch evicts (= spills) the other family's chain and
#: every return readmits it
_FAMS = [np.random.RandomState(200 + f).randint(
    0, 256, (2 * BS,)).astype(np.int32) for f in range(2)]


def _req(fam, tail_seed, **kw):
    tail = np.random.RandomState(tail_seed).randint(
        0, 256, (6,)).astype(np.int32)
    kw.setdefault("max_new_tokens", 6)
    return GenerationRequest(
        prompt=np.concatenate([_FAMS[fam], tail]), **kw)


def _thrash(rounds=3):
    """A/B/A/B...: one request per family per round, round 2 sampled."""
    reqs = []
    for i in range(rounds):
        for fam in (0, 1):
            kw = {}
            if i == 1:
                kw = dict(temperature=0.8, top_k=5,
                          seed=700 + 10 * fam + i)
            reqs.append(_req(fam, 10 * fam + i, **kw))
    return reqs


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             seed=r.seed, eos_token_id=r.eos_token_id)


def _serial(eng, reqs):
    """One request at a time, so trie pressure peaks per publish and
    the spill/readmit order is deterministic."""
    return [eng.generate([_clone(r)])[0].tolist() for r in reqs]


# --------------------------------------------------------- transparency
class TestTierTransparency:
    def test_dense_thrash_streams_identical_and_hits_recovered(
            self, model):
        """The headline pin, dense engine: a 2-block pool thrashed by
        two alternating families. HBM-only forgets each evicted family
        (zero hits); the tier readmits them (hits recovered) — and both
        stream the exact cache-disabled tokens, greedy and sampled."""
        reqs = _thrash()
        cold = _engine(model, prefix_cache=False, paged_attn=False)
        want = _serial(cold, reqs)

        hbm = _engine(model, paged_attn=False, prefix_blocks=2)
        got_hbm = _serial(hbm, reqs)
        assert got_hbm == want
        assert hbm.prefix_cache.stats["tier_hits"] == 0

        eng = _engine(model, paged_attn=False, prefix_blocks=2,
                      host_tier_bytes=TIER)
        pc = eng.prefix_cache
        got = _serial(eng, reqs)
        assert got == want                     # transparency
        # the tier actually worked: spills fired, readmissions hit
        assert pc.stats["spilled_blocks"] > 0
        assert pc.stats["tier_hits"] > 0
        assert pc.stats["readmitted_blocks"] >= 2 * pc.stats["tier_hits"]
        # ... and recovered hits the HBM-only trie lost to eviction
        assert pc.stats["hits"] > hbm.prefix_cache.stats["hits"]
        assert pc.tier.bytes_used > 0
        # readmission re-allocates through the pool: budget never busts
        assert pc.pool.num_used <= pc.pool.num_blocks
        assert not pc.pool._ref.any()          # transient pins drained
        # compile-once survives spill/readmit churn
        assert eng.decode_compilations() == 1

    def test_paged_thrash_streams_identical(self, model):
        """Same pin on the paged default: donation-trim evictions spill,
        lookups readmit into the block-table install path."""
        reqs = _thrash()
        off = _engine(model, prefix_blocks=2)
        want = _serial(off, reqs)
        eng = _engine(model, prefix_blocks=2, host_tier_bytes=TIER)
        pc = eng.prefix_cache
        assert _serial(eng, reqs) == want
        assert pc.stats["spilled_blocks"] > 0
        assert pc.stats["readmitted_blocks"] > 0
        assert pc.stats["hits"] > off.prefix_cache.stats["hits"]
        assert eng.decode_compilations() == 1

    def test_int8_kv_tier_roundtrips_scale_planes(self, model):
        """The int8 pool's scale planes spill and readmit alongside the
        quantized KV (the PR-13 block-id-keyed layout, one tier entry),
        with streams byte-identical to the tier-off quantized engine."""
        reqs = _thrash()
        off = _engine(model, kv_dtype="int8", prefix_blocks=2)
        want = _serial(off, reqs)
        eng = _engine(model, kv_dtype="int8", prefix_blocks=2,
                      host_tier_bytes=TIER)
        pc = eng.prefix_cache
        assert _serial(eng, reqs) == want
        assert pc.stats["spilled_blocks"] > 0
        assert pc.stats["readmitted_blocks"] > 0
        # a resident tier entry carries all four planes
        with pc.tier._lock:
            bufs = next(iter(pc.tier._entries.values()))[0]
        assert set(bufs) == {"k", "v", "k_scale", "v_scale"}
        assert bufs["k"].dtype == np.int8
        assert bufs["k_scale"].dtype == np.float32
        assert eng.decode_compilations() == 1


# ----------------------------------------------------------- default off
class TestTierDefaultOff:
    def test_zero_budget_constructs_no_tier_and_moves_no_bytes(
            self, model):
        eng = _engine(model, paged_attn=False, prefix_blocks=2)
        pc = eng.prefix_cache
        assert pc.tier is None and pc.host_tier_bytes == 0
        _serial(eng, _thrash(rounds=2))
        assert pc.stats["evictions"] > 0       # thrash really evicted
        for key in ("spilled_blocks", "tier_hits", "readmitted_blocks",
                    "tier_evictions", "tier_transfers"):
            assert pc.stats[key] == 0, key

    def test_negative_budget_rejected(self, model):
        with pytest.raises(ValueError, match="host_tier_bytes"):
            PrefixCache(BlockManager(1, 2, 4, 1, 2), host_tier_bytes=-1)
        with pytest.raises(ValueError, match="host_tier_bytes"):
            _engine(model, host_tier_bytes=-5)


# -------------------------------------------------------- compile budget
class TestTierCompileDiscipline:
    def test_transfer_programs_bounded_by_geometry_not_traffic(
            self, model):
        """The fetch/inject pair is compile-once per (quantized, tp)
        pool geometry: a repeat thrash wave moves more blocks but adds
        ZERO tier traces (runtime-scalar block ids — python-int
        indexing would trace per block)."""
        eng = _engine(model, paged_attn=False, prefix_blocks=2,
                      host_tier_bytes=TIER)
        reqs = _thrash(rounds=2)
        _serial(eng, reqs)
        n0 = tier_compilations()
        assert n0 >= 2          # >= one fetch + one inject trace
        spilled0 = eng.prefix_cache.stats["spilled_blocks"]
        _serial(eng, reqs)
        assert eng.prefix_cache.stats["spilled_blocks"] > spilled0
        assert tier_compilations() == n0       # zero new traces
        assert eng.decode_compilations() == 1


# ------------------------------------------------------- staging reuse
class TestStagingReuse:
    """ISSUE 20 satellite: spills used to land in freshly-allocated
    pageable numpy per block; they now land in the pool's per-shape
    staging buffers, recycled when a tier entry dies (trim / replace /
    readmission-inject). The pin is the allocation COUNT: one real
    ``np.empty`` per (shape, dtype), not one per spill."""

    def test_unit_one_allocation_per_shape_across_spill_cycles(self):
        pool = BlockManager(2, 4, 4, 1, 2)
        for cycle in range(5):
            for b in range(pool.num_blocks):
                bufs = pool.read_block(b)
                assert set(bufs) == {"k", "v"}
                pool.recycle_staging(bufs)      # entry died
        alloc = pool.staging.allocations
        assert alloc and all(n == 1 for n in alloc.values()), alloc

    def test_engine_thrash_allocates_once_per_shape(self, model):
        """A one-block tier budget under the thrash workload: every
        spill replaces (= recycles) the previous entry and every
        readmission injects-then-recycles, so dozens of spills draw on
        the per-shape steady state. The insert-then-trim window keeps
        at most TWO entries alive per plane (the incoming spill stages
        before the LRU victim recycles), so the pin is <= 2 buffers
        per plane ever allocated — and a repeat wave, spilling just as
        much again, allocates ZERO more (per shape, not per spill)."""
        probe = _engine(model, prefix_blocks=2)
        per_block = (probe.cache.pool.block_nbytes
                     + probe.cache.pool.scale_block_nbytes)
        eng = _engine(model, prefix_blocks=2,
                      host_tier_bytes=per_block)
        pc = eng.prefix_cache
        reqs = _thrash(rounds=3)
        _serial(eng, reqs)
        warm = dict(pc.pool.staging.allocations)
        spilled = pc.stats["spilled_blocks"]
        assert warm and all(n <= 2 for n in warm.values()), warm
        _serial(eng, reqs)
        assert pc.stats["spilled_blocks"] > spilled     # kept spilling
        assert pc.pool.staging.allocations == warm      # zero new

    def test_shared_entries_are_never_recycled(self):
        """The fleet cache plane holds exported buffers by reference:
        a shared entry's death must NOT hand its buffers to the
        recycler (the sibling tier would read the next spill's
        bytes)."""
        t = HostTier(capacity_bytes=64)
        recycled = []
        t.on_recycle = recycled.append
        own = {"k": np.full((64,), 1, np.uint8)}
        t.put(((1,),), own)
        # export marks shared; the replacement drop must skip recycle
        assert t.export_digest(HostTier.chain_digests(((1,),))[-1])
        t.put(((1,),), {"k": np.full((64,), 2, np.uint8)})
        assert recycled == []
        # the unshared replacement recycles normally when dropped
        t.put(((1,),), {"k": np.full((64,), 3, np.uint8)})
        assert len(recycled) == 1 and recycled[0]["k"][0] == 2


# ---------------------------------------------------------- HostTier unit
class TestHostTierUnit:
    def _bufs(self, fill, nbytes=64):
        return {"k": np.full((nbytes // 2,), fill, np.uint8),
                "v": np.full((nbytes // 2,), fill, np.uint8)}

    def test_chain_digests_content_only_and_incremental(self):
        a = [(1, 2, 3), (4, 5, 6), (7, 8, 9)]
        d = HostTier.chain_digests(a)
        assert len(d) == 3 and len(set(d)) == 3
        # two replicas that never exchanged state agree per depth
        assert HostTier.chain_digests(list(a)) == d
        # digest i depends on keys[:i+1] only (the prefix property)
        assert HostTier.chain_digests(a[:2]) == d[:2]
        assert HostTier.chain_digests([(9, 9, 9)] + a[1:])[0] != d[0]

    def test_put_pop_lru_and_descendant_cascade(self):
        t = HostTier(capacity_bytes=192)     # three 64-byte entries
        pa = ((1,),)
        pb = ((1,), (2,))                    # child of pa
        pc_ = ((3,),)                        # unrelated chain
        assert t.put(pa, self._bufs(1)) == 0
        assert t.put(pb, self._bufs(2)) == 0
        assert t.put(pc_, self._bufs(3)) == 0
        assert t.num_blocks == 3 and t.bytes_used == 192
        t.export_digest(HostTier.chain_digests(pc_)[-1])  # touch pc_
        # over budget: the LRU victim is pa — and evicting pa cascades
        # to pb (a spilled block with no resident/tier parent is
        # unreachable; keeping it would lie to the byte gauge)
        dropped = t.put(((4,),), self._bufs(4))
        assert dropped == 2
        assert not t.has(pa) and not t.has(pb)
        assert t.has(pc_) and t.has(((4,),))
        assert t.bytes_used == 128
        # pop removes (returning the shared flag alongside the
        # buffers — True here: export_digest handed out pc_'s
        # buffers by reference above); a second pop misses
        bufs, shared = t.pop(pc_)
        assert bufs["k"][0] == 3 and shared is True
        assert t.pop(pc_) is None
        assert t.export_digest("no-such-digest") is None

    def test_oversize_entry_degrades_to_empty_never_over_budget(self):
        t = HostTier(capacity_bytes=32)
        t.put(((1,),), self._bufs(1, nbytes=64))
        assert t.num_blocks == 0 and t.bytes_used == 0

    def test_replace_refreshes_bytes_not_duplicates(self):
        t = HostTier(capacity_bytes=1024)
        p = ((1,), (2,))
        t.put(p, self._bufs(1, nbytes=64))
        t.put(p, self._bufs(2, nbytes=128))
        assert t.num_blocks == 1 and t.bytes_used == 128
        assert t.pop(p)[0]["k"][0] == 2


# ------------------------------------------------------ fleet cache plane
class TestFleetCachePlane:
    def test_miss_on_a_hits_siblings_tier_byte_identical(self, model):
        """The distributed-prefix-cache pin: round-robin sends family A
        back to replica 1 AFTER replica 0 spilled A's chain — the fleet
        plane moves the chain host-to-host at submit, replica 1's
        admission readmits it as a local tier hit, and the stream is
        byte-identical to a cold single-engine run."""
        reqs = [_req(0, 50), _req(1, 60), _req(1, 61), _req(0, 51)]
        oracle = _engine(model, prefix_blocks=2)
        want = _serial(oracle, reqs)

        fl = EngineFleet(model, replicas=2, router="round-robin",
                         num_slots=2, max_seq_len=96,
                         prefix_block_size=BS, prefix_blocks=2,
                         prefill_chunk=CHUNK, max_queue=8,
                         host_tier_bytes=TIER, retry_backoff_s=0.0)
        try:
            got = []
            for r in reqs:     # serial: publishes land before the next
                st = fl.submit(_clone(r))  # route order: r0 r1 r0 r1
                got.append(st.result()[0].tolist())
            assert got == want
            doc = fl.cache_plane_doc()
            # family A's 2-block system chain moved r0 -> r1
            assert doc["transfers_total"] >= 2
            assert doc["transfer_bytes_total"] > 0
            rows = {r["replica"]: r for r in doc["replicas"]}
            assert rows[0]["enabled"] and rows[1]["enabled"]
            assert rows[0]["spilled_blocks"] >= 2      # the donor spilled
            assert rows[1]["tier_transfers_in"] >= 2   # the target pulled
            assert rows[1]["tier_hits"] >= 1           # ...and hit locally
            assert rows[1]["readmitted_blocks"] >= 2
            # /debug/fleet carries the cache-plane columns
            frow = [r for r in fl.fleet_table() if r["replica"] == 1][0]
            assert frow["tier_transfers_in"] >= 2
            # fleet metrics: one scrape covers the plane
            fams = parse_prometheus(fl.registry.render())
            s = fams["serving_fleet_tier_transfers_total"]["samples"]
            assert s[("serving_fleet_tier_transfers_total", ())] \
                == doc["transfers_total"]
            s = fams["serving_fleet_tier_transfer_bytes_total"]["samples"]
            assert s[("serving_fleet_tier_transfer_bytes_total", ())] \
                == doc["transfer_bytes_total"]
            # the peer direction landed on the target's tier ledger,
            # matching the fleet's byte total (r1 was the only puller)
            co = fl.replicas[1].gateway.cost
            assert co.tier_bytes("peer") == doc["transfer_bytes_total"]
        finally:
            fl.shutdown(drain=True, timeout=60)

    def test_plane_disabled_rows_when_tier_off(self, model):
        fl = EngineFleet(model, replicas=2, router="round-robin",
                         num_slots=2, max_seq_len=96,
                         prefix_block_size=BS, prefill_chunk=CHUNK,
                         max_queue=8, start=False)
        try:
            doc = fl.cache_plane_doc()
            assert doc["transfers_total"] == 0
            assert all(not r["enabled"] for r in doc["replicas"])
            # tier-off submits never touch the plane
            fl.start()
            st = fl.submit(_req(0, 70))
            st.result()
            assert fl.cache_plane_doc()["transfers_total"] == 0
        finally:
            fl.shutdown(drain=True, timeout=60)


# ------------------------------------------------------------- tier bench
@pytest.mark.slow   # ISSUE 16 satellite: the tier bench is nightly-class
def test_bench_tier_accepts():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    from bench_tier import measure_tier
    res = measure_tier(quick=True)
    assert res["accepted"], res
