"""Multi-chip tensor-parallel serving (engine ``tp=N``, README
"Tensor-parallel serving"): every serving program shard_map'd over a
heads-sharded CPU mesh (conftest forces 8 virtual devices) with the
paged KV pool partitioned per shard. The load-bearing properties:

- **Transparency**: TP=2 (and TP=4) token streams are BYTE-IDENTICAL
  to the single-chip baseline — greedy AND seeded-sampled, across the
  hit/miss/chunked matrix and the spec / multi-tick / int8-KV engine
  variants — and ``decode_compilations() == 1`` holds INCLUSIVE of the
  sharded geometry (the tp tag keys the shard_map trace apart in a
  shared jit cache).
- **Exact collective accounting**: the per-layer all-reduce pair is
  the only cross-chip traffic; its wire bytes are counted shape-exactly
  (``serving_collective_bytes_total{dtype}``) and host-boundary h2d/d2h
  bytes are LOGICAL — never double-counted across mesh shards (the
  cost-observatory satellite).
- **EQuARX int8 collectives**: ``collective_dtype="int8"`` cuts wire
  bytes >= 3x with MEASURED (not assumed) divergence, deterministic
  under replay.
- **Lifecycle**: displacement/restore and crash recovery carry the
  per-shard pools correctly — recompute is byte-identical on a sharded
  engine, chaos matrix loses nothing.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.profiler.cost import CostObservatory
from paddle_tpu.quantization import (collective_wire_bytes,
                                     quantized_psum_int8)
from paddle_tpu.serving import ContinuousBatchingEngine, GenerationRequest
from paddle_tpu.serving.faults import FaultPlan
from paddle_tpu.serving.server.gateway import ServingGateway

from test_metrics_prom import parse_prometheus

BS = 8      # block size
CHUNK = 16  # 2 blocks per chunk
SLOTS = 2
S_MAX = 96


@pytest.fixture(scope="module")
def model():
    paddle.seed(33)
    return LlamaForCausalLM(llama_tiny())  # GQA: nkv=2 < nh=4


@pytest.fixture(scope="module")
def mha_model():
    paddle.seed(34)
    return LlamaForCausalLM(llama_tiny(num_key_value_heads=4))  # tp=4-able


def _engine(model, **kw):
    kw.setdefault("jit_cache", model.__dict__.setdefault("_serving_jit", {}))
    kw.setdefault("num_slots", SLOTS)
    kw.setdefault("max_seq_len", S_MAX)
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefill_chunk", CHUNK)
    return ContinuousBatchingEngine(model, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 256, (n,)).astype(np.int32)


def _req(ps, n=12, **kw):
    kw.setdefault("max_new_tokens", 5)
    return GenerationRequest(prompt=_prompt(ps, n), **kw)


def _clone(r):
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             eos_token_id=r.eos_token_id, seed=r.seed)


#: the hit/miss/chunked matrix: greedy shorts, a seeded-sampled row,
#: and a long prompt that chunks (40 > CHUNK)
def _traffic():
    return [_req(1), _req(2, n=10),
            _req(3, temperature=0.9, top_k=5, seed=123),
            _req(4, n=40, max_new_tokens=4)]


def _run_matrix(model, **kw):
    """Two passes of the traffic (pass 2 = trie hits on pass 1's
    donated chains) through one engine; returns (streams, engine)."""
    eng = _engine(model, prefix_cache=True, **kw)
    outs = [o.tolist() for o in eng.generate(_traffic())]
    outs += [o.tolist() for o in
             eng.generate([_clone(r) for r in _traffic()])]
    return outs, eng


# ----------------------------------------------------------- transparency
class TestTPByteIdentity:
    @pytest.mark.slow  # 14 s matrix duplicate: tp4/spec/multitick/int8 byte-
    # identity reps below run by default (870s cap)
    def test_tp2_matrix_byte_identical_and_compile_once(self, model):
        """THE acceptance pin: TP=2 streams equal the single-chip
        baseline byte-for-byte — greedy AND seeded-sampled, cold/hit/
        chunked — with ``decode_compilations() == 1`` on BOTH engines
        (they share one jit cache; the tp tag keys the sharded traces
        apart, so neither engine's pin sees the other's programs)."""
        base, e1 = _run_matrix(model, tp=1)
        tp2, e2 = _run_matrix(model, tp=2)
        assert tp2 == base
        assert e1.decode_compilations() == 1
        assert e2.decode_compilations() == 1
        # prefill side stays bounded and tag-isolated the same way
        assert e2.prefill_compilations() >= 1
        assert e2.tp == 2 and e1.tp == 1
        assert e1.collective_dtype == "fp"

    @pytest.mark.slow
    def test_tp4_byte_identical(self, mha_model):
        """TP=1 ≡ TP=4 on the MHA tiny model (nkv=4 divides 4)."""
        base, _ = _run_matrix(mha_model, tp=1)
        tp4, e4 = _run_matrix(mha_model, tp=4)
        assert tp4 == base
        assert e4.decode_compilations() == 1

    @pytest.mark.slow
    def test_tp_spec_decode_byte_identical(self, model):
        """The spec-verify program rides ``_packed_span_forward`` too:
        a sharded speculative engine streams byte-identically to the
        single-chip speculative engine (which is itself pinned equal to
        non-spec), compile-once inclusive of the spec+tp geometry."""
        base, _ = _run_matrix(model, tp=1, spec_decode=True, spec_k=3)
        tp2, e2 = _run_matrix(model, tp=2, spec_decode=True, spec_k=3)
        assert tp2 == base
        assert e2.decode_compilations() == 1

    @pytest.mark.slow
    def test_tp_multitick_byte_identical(self, model):
        """The multi-tick while_loop tail shards like the scan tail:
        decode_ticks=4 on TP=2 equals decode_ticks=4 on one chip."""
        base, _ = _run_matrix(model, tp=1, decode_ticks=4)
        tp2, e2 = _run_matrix(model, tp=2, decode_ticks=4)
        assert tp2 == base
        assert e2.decode_compilations() == 1

    @pytest.mark.slow
    def test_tp_int8_kv_byte_identical(self, model):
        """int8 KV pools shard on the same head axis (scale planes
        ride along): TP=2 int8-KV streams equal single-chip int8-KV."""
        base, _ = _run_matrix(model, tp=1, kv_dtype="int8")
        tp2, e2 = _run_matrix(model, tp=2, kv_dtype="int8")
        assert tp2 == base
        assert e2.decode_compilations() == 1
        # the pool really is partitioned: data AND scale planes carry
        # the head-sharded NamedSharding
        spec = e2.cache.pool.k.sharding.spec
        assert "tp" in tuple(spec)
        assert "tp" in tuple(e2.cache.pool.k_scale.sharding.spec)


# ---------------------------------------------------- collective accounting
class TestCollectiveAccounting:
    def _one_req_run(self, model, tp, collective_dtype="fp"):
        co = CostObservatory()
        eng = _engine(model, tp=tp, collective_dtype=collective_dtype)
        eng.cost = co
        # 14 tokens <= prefill_chunk: ONE-SHOT cold prefill, bucket 16
        eng.generate([GenerationRequest(
            prompt=(np.arange(14, dtype=np.int32) % 100),
            max_new_tokens=5)])
        return co, eng

    def test_ledger_exact_and_h2d_parity(self, model):
        """Closed-form collective-byte pin + the cost-observatory
        satellite: one 14-token prompt, 5 greedy tokens, no chunking =
        one cold prefill launch (bucket 16) + four single-tick unified
        steps (the padded packed buffer) — 2L all-reduces each, bytes
        equal to the shared wire model TO THE BYTE. And the h2d/d2h
        boundary ledger of the tp=2 run equals the tp=1 run's exactly:
        per-shard arg/result leaves count LOGICAL bytes once, never
        once per mesh device."""
        c = model.config
        L, hm = c.num_hidden_layers, c.hidden_size
        co1, _ = self._one_req_run(model, 1)
        co2, e2 = self._one_req_run(model, 2)
        # tp=1: no mesh, no wire — explicit zero, empty ledger
        assert co1.collectives == {}
        assert co1.collective_bytes("fp") == 0
        want = 2 * L * collective_wire_bytes(16, hm, 2, "fp")
        want += 4 * 2 * L * collective_wire_bytes(
            e2._token_budget, hm, 2, "fp")
        assert co2.collective_bytes("fp") == want
        assert co2.collectives["fp"]["ops"] == 2 * L * 5
        # the satellite pin: logical-once boundary accounting — the
        # sharded engine's h2d/d2h totals match the single-chip run
        assert co2.totals["h2d_bytes"] == co1.totals["h2d_bytes"]
        assert co2.totals["d2h_bytes"] == co1.totals["d2h_bytes"]

    def test_int8_collective_cuts_wire_bytes_3x(self, model):
        """Same workload, wire dtype swapped: op counts match and the
        byte ratio shows the EQuARX cut (>= 3x; scale overhead is
        4·tp/hidden). Streams replay deterministically."""
        co_fp, _ = self._one_req_run(model, 2, "fp")
        co_q, _ = self._one_req_run(model, 2, "int8")
        assert co_q.collectives["int8"]["ops"] == \
            co_fp.collectives["fp"]["ops"]
        ratio = co_fp.collective_bytes("fp") \
            / co_q.collective_bytes("int8")
        assert ratio >= 3.0

    def test_wire_model_units(self):
        """The shared wire model: tp<=1 is free; fp prices the ring
        reduce-scatter+all-gather on the fp payload; int8 prices the
        int8 payload plus one fp32 scale per (row, chunk) per phase."""
        assert collective_wire_bytes(10, 64, 1, "fp") == 0
        rows, hm, tp = 6, 64, 2
        assert collective_wire_bytes(rows, hm, tp, "fp") == \
            2 * rows * hm * 4 * (tp - 1) // tp
        assert collective_wire_bytes(rows, hm, tp, "int8") == \
            2 * (rows * hm + rows * tp * 4) * (tp - 1) // tp
        # >= 3x whenever hidden dominates the scale overhead
        assert (collective_wire_bytes(8, 64, 2, "fp")
                / collective_wire_bytes(8, 64, 2, "int8")) > 3.0

    def test_metrics_and_profile_surface(self, model):
        """``serving_collective_bytes_total{dtype}`` scrapes from a
        sharded gateway (fp > 0, int8 an explicit 0 — both series
        exist), and ``/debug/profile`` carries the per-layer
        collective-bytes column."""
        jit = model.__dict__.setdefault("_serving_jit", {})

        def factory():
            return _engine(model, tp=2, jit_cache=jit)

        gw = ServingGateway(factory(), engine_factory=factory,
                            max_queue=8, start=False)
        st = gw.submit(_req(7))
        gw.start()
        st.result()
        fams = parse_prometheus(gw.registry.render())
        s = fams["serving_collective_bytes_total"]["samples"]
        assert s[("serving_collective_bytes_total",
                  (("dtype", "fp"),))] > 0
        assert s[("serving_collective_bytes_total",
                  (("dtype", "int8"),))] == 0
        doc = gw.profile_doc()
        assert doc["collectives"]["tp"] == 2
        fp = doc["collectives"]["per_dtype"]["fp"]
        assert fp["bytes"] > 0 and fp["bytes_per_layer"] > 0
        assert fp["bytes"] == pytest.approx(
            fp["bytes_per_layer"] * model.config.num_hidden_layers)
        gw.shutdown(drain=True, timeout=30)


# ----------------------------------------------------- quantized all-reduce
class TestQuantizedPsum:
    def test_roundtrip_vs_fp_psum(self):
        """Under shard_map on a 2-device mesh the quantized all-reduce
        approximates psum within the double-quantization error bound,
        is exact on exactly-representable payloads, and preserves
        zeros exactly."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.serving.decode import _tp_mesh

        mesh = _tp_mesh(2)
        x = np.random.RandomState(0).randn(2, 6, 64).astype(np.float32)

        def body(v):
            loc = v[jax.lax.axis_index("tp")]
            return (quantized_psum_int8(loc, "tp", 2),
                    jax.lax.psum(loc, "tp"))

        q, exact = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False))(x)
        err = np.max(np.abs(np.asarray(q) - np.asarray(exact)))
        # two absmax/127 roundings: bound ~2 * amax/127 per element sum
        bound = 2.5 * float(np.max(np.abs(x))) * 2 / 127.0
        assert err <= bound
        # all-zero payloads stay exactly zero (scale-0 rule)
        z, _ = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False))(np.zeros_like(x))
        assert np.all(np.asarray(z) == 0.0)

    def test_exact_on_representable_payload(self):
        """A payload whose every quantization step is lossless —
        integer values with amax exactly 127 in every (row, chunk) on
        one shard, zeros on the other (the scale-0 rule) — survives
        BOTH wire phases bit-exactly: pins the dequant math itself,
        not just an error bound."""
        import jax
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.serving.decode import _tp_mesh

        mesh = _tp_mesh(2)
        rng = np.random.RandomState(1)
        x = np.zeros((2, 4, 64), np.float32)
        x[0] = rng.randint(-127, 128, (4, 64)).astype(np.float32)
        x[0, :, 0] = 127.0      # amax 127 in chunk 0 of every row
        x[0, :, 32] = 127.0     # ...and in chunk 1 (H/tp = 32)

        def body(v):
            loc = v[jax.lax.axis_index("tp")]
            return (quantized_psum_int8(loc, "tp", 2),
                    jax.lax.psum(loc, "tp"))

        q, exact = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False))(x)
        assert np.array_equal(np.asarray(q), np.asarray(exact))


# -------------------------------------------------------------- validation
class TestTPValidation:
    def test_rejects_bad_configs(self, model):
        with pytest.raises(ValueError, match="tp must be >= 1"):
            _engine(model, tp=0)
        with pytest.raises(ValueError, match="collective_dtype"):
            _engine(model, tp=2, collective_dtype="fp8")
        with pytest.raises(ValueError, match="unified ragged paged"):
            _engine(model, tp=2, paged_attn=False)
        with pytest.raises(ValueError, match="unified ragged paged"):
            _engine(model, tp=2, ragged_step=False)
        with pytest.raises(ValueError, match="must divide"):
            _engine(model, tp=3)       # nh=4, nkv=2: 3 divides neither
        from paddle_tpu.serving.decode import _tp_mesh
        with pytest.raises(ValueError, match="XLA_FLAGS"):
            _tp_mesh(64)               # conftest forces 8 devices

    def test_tp1_int8_collectives_are_inert(self, model):
        """tp=1 has no mesh and no wire: the effective collective
        dtype normalizes to fp (banners/geometry report what runs)."""
        eng = _engine(model, tp=1, collective_dtype="int8")
        assert eng.collective_dtype == "fp"

    def test_jit_keys_carry_the_tp_tag(self, model):
        """The TP degree joins the jit key: after a sharded run every
        program key of the tp=2 engine carries the ("tp2", dtype) tail
        while tp=1 keys stay byte-identical to the pre-TP spelling (no
        tag — banked baselines can't have drifted)."""
        jit = {}
        e1 = _engine(model, tp=1, jit_cache=jit)
        e1.generate([_req(11, max_new_tokens=2)])
        keys1 = set(jit)
        assert all("tp2" not in k for k in keys1)
        e2 = _engine(model, tp=2, jit_cache=jit)
        e2.generate([_req(11, max_new_tokens=2)])
        keys2 = set(jit) - keys1
        assert keys2 and all(k[-2:] == ("tp2", "fp") for k in keys2)
        assert e1.decode_compilations() == 1
        assert e2.decode_compilations() == 1

    def test_fleet_geometry_grows_tp(self, model):
        """Replicas with different TP degrees get isolated jit-cache
        dicts: (tp, collective_dtype) joins the fleet geometry tuple —
        same memory-note discipline as the kv8/w8 tags."""
        from paddle_tpu.serving.fleet import EngineFleet
        model.__dict__.pop("_serving_jit_fleet", None)
        fleet = EngineFleet(model, replicas=1, num_slots=SLOTS,
                            max_seq_len=S_MAX, prefill_chunk=CHUNK,
                            prefix_block_size=BS, tp=2,
                            collective_dtype="int8", start=False)
        jits = model.__dict__["_serving_jit_fleet"]
        (geom,) = jits.keys()
        # tail of the geometry tuple: (tp, collective_dtype,
        # fused_tick, collective_overlap)
        assert geom[-4:] == (2, "int8", False, False)
        assert fleet.replicas[0].gateway.engine.tp == 2
        fleet.shutdown(drain=False, timeout=5)


# ------------------------------------------------------------- lifecycle
@pytest.mark.slow
class TestTPLifecycle:
    def test_displace_restore_carries_sharded_pool(self, model):
        """Mid-decode evict + restore on a sharded engine: the chain
        donates to the trie (per-shard blocks and all), recompute
        readmits as a trie hit, and the continuation is byte-identical
        to the uninterrupted single-chip baseline."""
        reqs = _traffic()
        base = [o.tolist() for o in
                _engine(model, tp=1, prefix_cache=True).generate(
                    [_clone(r) for r in reqs])]
        eng = _engine(model, tp=2, prefix_cache=True)
        seqs = [eng.submit(_clone(r)) for r in reqs]
        for _ in range(3):
            eng.step()
        victim = next(s for s in seqs if s.status == "running")
        assert eng.evict(victim)
        eng.restore(victim)
        while eng.has_work():
            eng.step()
        assert [list(s.output_ids()) for s in seqs] == base
        assert eng.decode_compilations() == 1

    def test_chaos_matrix_zero_lost_on_sharded_engine(self, model):
        """transient -> fatal -> nan against a tp=2 supervised gateway:
        the nan fault REALLY poisons the SHARDED pool before crashing,
        so byte-identical streams prove recovery rebuilt the mesh
        engine and recomputed per-shard KV from host token state.
        0 requests lost."""
        reqs = _traffic()
        jit = model.__dict__.setdefault("_serving_jit", {})
        base = [o.tolist() for o in
                _engine(model, tp=2, prefix_cache=True,
                        jit_cache=jit).generate(
                    [_clone(r) for r in reqs])]

        def factory():
            return _engine(model, tp=2, prefix_cache=True,
                           jit_cache=jit)

        plan = FaultPlan().at_step(1, "transient") \
                          .at_step(3, "fatal").at_step(6, "nan")
        gw = ServingGateway(factory(), engine_factory=factory,
                            fault_hook=plan, max_queue=16, start=False)
        streams = [gw.submit(_clone(r)) for r in reqs]
        gw.start()
        outs = [st.result() for st in streams]
        assert [ids.tolist() for ids, _ in outs] == base
        assert gw.restarts == 2
        assert gw.engine.decode_compilations() == 1
        gw.shutdown(drain=True, timeout=30)
