"""Request-lifecycle tracing, step timeline, and SLO instrumentation
(profiler/tracing.py + its threading through the serving stack; README
"Tracing & debugging").

The properties under test, per the observability contract:

- the tracer itself: bounded ring, injectable clock, off-by-default
  no-op path, dense request-lane normalization;
- every emitted event is valid Chrome trace JSON (``ph/ts/pid/tid/
  name``) and same-lane spans nest properly;
- the engine emits the full request lifecycle (``queued → prefill /
  prefill_chunk[i] → decode → finished``) and step phases (``plan /
  launch / host-accept / donate``), with tracing NEVER changing a
  token;
- the SLO substrate: ``Sequence`` carries engine-clock TTFT/TPOT/
  queue-wait stamps, and ``serving_tpot_seconds`` /
  ``serving_queue_wait_seconds`` strict-parse on ``/metrics`` and keep
  accumulating across an engine rebuild;
- a mixed chaos+spec trace under ``VirtualClock`` is byte-stable
  across replays and contains the fault/rebuild/recovery/preemption/
  spec-acceptance events, with streams byte-identical to the
  fault-free baseline and ``decode_compilations() == 1``;
- the ``/debug/trace`` and ``/debug/requests`` endpoints work over
  live HTTP, and ``/healthz`` reports the saturation fields;
- the ``python -m paddle_tpu.profiler`` CLI summarizes a real trace
  directory.
"""
import contextlib
import io
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.profiler.tracing import (NULL_SPAN, TID_ENGINE,
                                         TID_GATEWAY, TID_REQ0, SpanTracer)
from paddle_tpu.serving import (ContinuousBatchingEngine, FaultPlan,
                                GenerationRequest, VirtualClock)
from paddle_tpu.serving.server import (ServingGateway, TraceBusyError,
                                       serve)

from test_metrics_prom import parse_prometheus

NUM_SLOTS, S_MAX = 2, 256


@pytest.fixture(scope="module")
def model():
    paddle.seed(31)
    return LlamaForCausalLM(llama_tiny())


def _reqs(n=3, max_new=5, plen=8, seed0=100):
    rng = np.random.RandomState(7)
    out = []
    for i in range(n):
        kw = {}
        if i % 3 == 2:     # every third request seeded-sampled
            kw = dict(temperature=0.8, top_k=5, seed=seed0 + i)
        out.append(GenerationRequest(
            prompt=rng.randint(0, 256, (plen,)).astype(np.int32),
            max_new_tokens=max_new, **kw))
    return out


def _engine(model, tracer=None, jit_cache=None, **kw):
    kw.setdefault("num_slots", NUM_SLOTS)
    kw.setdefault("max_seq_len", S_MAX)
    kw.setdefault("decode_chunk", 1)
    eng = ContinuousBatchingEngine(
        model, jit_cache=jit_cache if jit_cache is not None else {}, **kw)
    eng.tracer = tracer
    return eng


def validate_chrome_trace(doc, require_events=True):
    """The schema pin: every event carries ph/ts/pid/tid/name, spans
    are X events with non-negative durations, and same-lane spans nest
    (no partial overlap)."""
    assert "traceEvents" in doc
    evs = doc["traceEvents"]
    if require_events:
        assert evs, "empty trace"
    for e in evs:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(e), e
        assert e["ph"] in ("X", "i", "C"), e
        if e["ph"] == "C":      # counter samples carry numeric series
            assert e["args"] and all(
                isinstance(v, (int, float)) for v in e["args"].values())
        assert e["ts"] >= 0
        assert isinstance(e["tid"], int) and isinstance(e["pid"], int)
        if e["ph"] == "X":
            assert e["dur"] >= 0
    for tid in {e["tid"] for e in evs}:
        spans = sorted((e for e in evs
                        if e["tid"] == tid and e["ph"] == "X"),
                       key=lambda e: (e["ts"], -e["dur"]))
        stack = []          # open spans' end timestamps
        for e in spans:
            while stack and e["ts"] >= stack[-1] - 1e-9:
                stack.pop()
            if stack:       # strictly inside the enclosing span
                assert e["ts"] + e["dur"] <= stack[-1] + 1e-6, \
                    f"span {e} overlaps its enclosing span on tid {tid}"
            stack.append(e["ts"] + e["dur"])
    return evs


# ---------------------------------------------------------------- unit
class TestSpanTracerUnit:
    def test_disabled_is_noop(self):
        clk = VirtualClock(5.0)
        tr = SpanTracer(capacity=16, clock=clk)
        assert not tr.enabled
        tr.instant("x")
        tr.complete("y", 5.0)
        assert tr.span("z") is NULL_SPAN
        with tr.span("z"):
            pass
        assert tr.events() == []

    def test_ring_buffer_bounds_and_drop_count(self):
        tr = SpanTracer(capacity=4, clock=VirtualClock()).enable()
        for i in range(10):
            tr.instant(f"e{i}")
        evs = tr.events()
        assert len(evs) == 4 and tr.dropped == 6
        assert [e["name"] for e in evs] == ["e6", "e7", "e8", "e9"]

    def test_injectable_clock_and_epoch_relative_ts(self):
        clk = VirtualClock(100.0)
        tr = SpanTracer(clock=clk).enable()      # epoch = 100.0
        clk.advance(0.5)
        tr.instant("a")
        t0 = tr.now()
        clk.advance(0.25)
        tr.complete("b", t0)
        a, b = tr.events()
        assert a["ts"] == pytest.approx(500000.0)
        assert b["ts"] == pytest.approx(500000.0)
        assert b["dur"] == pytest.approx(250000.0)

    def test_req_tid_dense_first_seen(self):
        tr = SpanTracer(clock=VirtualClock()).enable()
        assert tr.req_tid(42) == TID_REQ0
        assert tr.req_tid(7) == TID_REQ0 + 1
        assert tr.req_tid(42) == TID_REQ0
        tr.clear()
        assert tr.req_tid(7) == TID_REQ0      # re-normalized

    def test_req_tid_map_bounded_by_capacity(self):
        # persistent tracing must not grow host memory with total
        # requests served: the id->tid map prunes to the ring capacity
        # (tids stay dense and are never reused)
        tr = SpanTracer(capacity=4, clock=VirtualClock()).enable()
        tids = [tr.req_tid(i) for i in range(10)]
        assert tids == list(range(TID_REQ0, TID_REQ0 + 10))
        assert len(tr._req_tids) <= 4
        assert tr.req_tid(9) == TID_REQ0 + 9    # recent ids stable

    def test_clear_resets_epoch_and_pre_window_marks_clamp(self):
        clk = VirtualClock()
        tr = SpanTracer(clock=clk).enable()
        stale = tr.now()                      # mark before the window
        clk.advance(2.0)
        tr.clear()                            # epoch = 2.0
        tr.complete("x", stale)               # t0 predates the epoch
        tr.complete("y", None)                # None = since epoch
        x, y = tr.events()
        assert x["ts"] == 0.0                 # clamped, not negative
        assert y["ts"] == 0.0
        with pytest.raises(ValueError):
            SpanTracer(capacity=0)

    def test_export_is_json_and_span_cm(self):
        tr = SpanTracer(clock=VirtualClock()).enable()
        with tr.span("outer", args={"k": 1}):
            tr.instant("inner", args={"j": 2})
        doc = json.loads(json.dumps(tr.export()))
        evs = validate_chrome_trace(doc)
        assert [e["name"] for e in evs] == ["inner", "outer"]
        assert evs[1]["args"] == {"k": 1}


# -------------------------------------------------------- engine spans
class TestEngineTracing:
    @pytest.mark.slow  # 6 s schema duplicate: the chunk-span and midflight-capture
    # reps below run by default (870s cap)
    def test_lifecycle_and_step_phases_schema(self, model):
        tracer = SpanTracer().enable()
        eng = _engine(model, tracer=tracer, prefix_cache=True,
                      prefix_block_size=8)
        outs = eng.generate(_reqs(3, max_new=5))
        assert all(o.finish_reason == "length" for o in outs)
        doc = tracer.export()
        evs = validate_chrome_trace(doc)
        names = {e["name"] for e in evs}
        assert {"queued", "prefill", "decode", "finished", "step",
                "plan", "launch", "host-accept", "admit",
                "prefill_launch", "donate"} <= names
        # one lifecycle lane per request, each with exactly one
        # queued span, one decode span and one finished instant
        lanes = {e["tid"] for e in evs if e["tid"] >= TID_REQ0}
        assert len(lanes) == 3
        for lane in lanes:
            mine = [e for e in evs if e["tid"] == lane]
            assert [e["name"] for e in mine if e["name"] == "queued"] \
                == ["queued"]
            dec = [e for e in mine if e["name"] == "decode"]
            assert len(dec) == 1
            assert dec[0]["args"]["finish_reason"] == "length"
            assert dec[0]["args"]["tokens"] == 5
            fin = [e for e in mine if e["name"] == "finished"]
            assert len(fin) == 1 and fin[0]["ph"] == "i"
        # engine-lane step spans: one per engine step
        steps = [e for e in evs
                 if e["name"] == "step" and e["tid"] == TID_ENGINE]
        assert len(steps) == eng.stats["steps"]

    def test_chunked_prefill_chunk_spans(self, model):
        tracer = SpanTracer().enable()
        eng = _engine(model, tracer=tracer, prefill_chunk=32,
                      prefix_block_size=8)
        long_req = GenerationRequest(
            prompt=np.arange(1, 81, dtype=np.int32), max_new_tokens=3)
        out = eng.generate([long_req])[0]
        assert out.finish_reason == "length"
        evs = validate_chrome_trace(tracer.export())
        chunks = sorted((e for e in evs
                         if e["name"].startswith("prefill_chunk[")),
                        key=lambda e: e["args"]["offset"])
        # 80 tokens through a 32-token chunk: 32 + 32 + 16
        assert [e["name"] for e in chunks] == [
            "prefill_chunk[0]", "prefill_chunk[1]", "prefill_chunk[2]"]
        assert [e["args"]["tokens"] for e in chunks] == [32, 32, 16]
        assert [e["args"]["offset"] for e in chunks] == [0, 32, 64]
        assert all(e["args"]["offset"] % 8 == 0 for e in chunks)

    def test_midflight_capture_names_phases_correctly(self, model):
        # a capture window opened AFTER a request was admitted must
        # close its spans under the right phase name: the phase tracks
        # state even while tracing is off
        tr = SpanTracer()
        eng = _engine(model, tracer=tr)
        seq = eng.submit(GenerationRequest(prompt=[1, 2, 3, 4],
                                           max_new_tokens=6))
        eng.step()                      # admitted + decoding, tracer off
        assert seq.status == "running"
        tr.enable()                     # mid-flight capture
        while eng.has_work():
            eng.step()
        lane = [e for e in tr.events() if e["tid"] >= TID_REQ0]
        names = [e["name"] for e in lane]
        assert "decode" in names
        assert "queued" not in names    # it was NOT queued this window
        dec = next(e for e in lane if e["name"] == "decode")
        assert dec["ts"] == 0.0         # since capture epoch

    def test_tracing_never_changes_tokens_and_off_is_silent(self, model):
        jit = {}
        reqs = _reqs(3, max_new=6)
        base = [o.tolist() for o in
                _engine(model, jit_cache=jit).generate(reqs)]
        # attached-but-disabled: no events, identical streams
        tr_off = SpanTracer()
        eng_off = _engine(model, tracer=tr_off, jit_cache=jit)
        assert [o.tolist() for o in eng_off.generate(reqs)] == base
        assert tr_off.events() == []
        # recording: identical streams, compile-once intact
        tr_on = SpanTracer().enable()
        eng_on = _engine(model, tracer=tr_on, jit_cache=jit)
        assert [o.tolist() for o in eng_on.generate(reqs)] == base
        assert tr_on.events()
        assert eng_on.decode_compilations() == 1


# ------------------------------------------------------- SLO substrate
class TestSLOSubstrate:
    def test_sequence_latency_stamps(self, model):
        eng = _engine(model)
        seqs = [eng.submit(r) for r in _reqs(2, max_new=4)]
        while eng.has_work():
            eng.step()
        for seq in seqs:
            assert seq.t_submit is not None
            assert seq.t_admitted >= seq.t_submit
            assert seq.t_first_token >= seq.t_admitted
            assert seq.t_finish >= seq.t_first_token
            assert seq.queue_wait_s >= 0
            assert seq.ttft_s > 0
            assert seq.tpot_s > 0       # 4 tokens -> 3 gaps
        # a one-token request has no inter-token gap
        one = eng.submit(GenerationRequest(prompt=[1, 2, 3],
                                           max_new_tokens=1))
        while eng.has_work():
            eng.step()
        assert one.tpot_s is None and one.ttft_s is not None

    def test_slo_histograms_strict_parse(self, model):
        gw = ServingGateway(_engine(model), start=False)
        streams = [gw.submit(r) for r in _reqs(4, max_new=4)]
        gw.start()
        for s in streams:
            s.result()
        text = gw.registry.render()
        gw.shutdown(drain=True, timeout=30)
        fams = parse_prometheus(text)   # strict: raises on format errors
        for name in ("serving_tpot_seconds", "serving_queue_wait_seconds"):
            assert fams[name]["type"] == "histogram"
            assert fams[name]["samples"][(f"{name}_count", ())] == 4.0
            assert fams[name]["samples"][(f"{name}_sum", ())] >= 0.0
        # TPOT is a per-token cadence: sum/count must sit well under
        # the whole-request latency average
        lat = fams["serving_request_latency_seconds"]["samples"]
        tp = fams["serving_tpot_seconds"]["samples"]
        assert (tp[("serving_tpot_seconds_sum", ())]
                <= lat[("serving_request_latency_seconds_sum", ())])

    @pytest.mark.slow  # 5 s rebuild duplicate: test_slo_histograms_strict_parse
    # above is the default SLO-histogram rep (870s cap)
    def test_slo_histograms_accumulate_across_rebuild(self, model):
        jit = {}

        def factory():
            return _engine(model, jit_cache=jit)

        plan = FaultPlan().at_step(2, "fatal")
        gw = ServingGateway(factory(), engine_factory=factory,
                            fault_hook=plan, retry_backoff_s=0.0,
                            start=False)
        streams = [gw.submit(r) for r in _reqs(3, max_new=5)]
        gw.start()
        for s in streams:
            ids, reason = s.result()
            assert reason == "length"
        assert gw.restarts >= 1
        fams = parse_prometheus(gw.registry.render())
        gw.shutdown(drain=True, timeout=30)
        # gateway-owned, Sequence-stamp-backed: every request lands in
        # the histograms exactly once even though the engine (and its
        # stats) was rebuilt mid-flight
        assert fams["serving_tpot_seconds"]["samples"][
            ("serving_tpot_seconds_count", ())] == 3.0
        assert fams["serving_queue_wait_seconds"]["samples"][
            ("serving_queue_wait_seconds_count", ())] == 3.0


# ------------------------------------- deterministic chaos+spec trace
def _chaos_workload():
    rng = np.random.RandomState(17)
    reqs = []
    for i in range(5):
        kw = {}
        if i % 3 == 2:
            kw = dict(temperature=0.8, top_k=5, seed=300 + i)
        reqs.append(GenerationRequest(
            prompt=rng.randint(0, 256, (10,)).astype(np.int32),
            max_new_tokens=8, **kw))
    reqs.append(GenerationRequest(
        prompt=rng.randint(0, 256, (72,)).astype(np.int32),
        max_new_tokens=4))
    return reqs


def _chaos_run(model, jit, reqs, with_plan, trace):
    """One full supervised serving pass under a VirtualClock; the fault
    plan (when on) exercises transient retry, pool preemption, fatal
    rebuild, NaN recompute and a hung-step watchdog rebuild."""
    clk = VirtualClock()

    def factory():
        return ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
            decode_chunk=1, prefix_cache=True, prefix_block_size=8,
            prefill_chunk=32, spec_decode=True, spec_k=3,
            step_clock=clk, jit_cache=jit)

    plan = None
    if with_plan:
        plan = (FaultPlan(clock=clk)
                .at_step(3, "transient")
                .at_step(6, "pool")
                .at_step(9, "fatal")
                .at_step(13, "hung", stall_s=60.0)
                .at_step(17, "nan"))
    tracer = SpanTracer(clock=clk)
    gw = ServingGateway(factory(), engine_factory=factory, max_queue=32,
                        fault_hook=plan, clock=clk,
                        watchdog_deadline_s=5.0, retry_backoff_s=0.0,
                        max_restarts=16, start=False, tracer=tracer,
                        trace=trace)
    streams = [gw.submit(r) for r in reqs]
    gw.start()
    outs = [s.result() for s in streams]
    engine = gw.engine
    gw.shutdown(drain=True, timeout=60)
    return ([(list(ids), reason) for ids, reason in outs], tracer,
            gw, engine, plan)


class TestDeterministicChaosTrace:
    @pytest.mark.slow  # 6 s chaos-trace duplicate: tracing-off token identity and
    # the chaos byte-identity pins elsewhere run by default (870s cap)
    def test_chaos_spec_trace_byte_stable_and_complete(self, model):
        jit = {}            # one jit cache: identical config all runs
        reqs = _chaos_workload()
        # fault-free baseline, tracing OFF (also warms every program)
        base, _, _, base_eng, _ = _chaos_run(model, jit, reqs,
                                             with_plan=False, trace=False)
        assert all(r in ("stop", "length") for _, r in base)
        # warm pass WITH the plan (recovery-path prefill buckets may
        # compile here; the compared replays below must both run warm,
        # or the watchdog's compile exemption could classify the hung
        # step differently between them)
        _chaos_run(model, jit, reqs, with_plan=True, trace=True)
        outs1, tr1, gw1, eng1, plan1 = _chaos_run(
            model, jit, reqs, with_plan=True, trace=True)
        outs2, tr2, gw2, eng2, plan2 = _chaos_run(
            model, jit, reqs, with_plan=True, trace=True)
        # token streams: byte-identical to the fault-free baseline —
        # tracing observes, recovery recomputes, neither changes a token
        assert outs1 == base and outs2 == base
        # the trace replays BYTE-STABLE: same events, same ts, same
        # normalized request lanes
        doc1 = json.dumps(tr1.export(), sort_keys=True)
        doc2 = json.dumps(tr2.export(), sort_keys=True)
        assert doc1 == doc2
        assert plan1.log == plan2.log and gw1.restarts == gw2.restarts
        # valid chrome trace, and the chaos story is all there
        evs = validate_chrome_trace(json.loads(doc1))
        names = {e["name"] for e in evs}
        assert {"step", "plan", "launch", "host-accept", "queued",
                "decode", "finished", "spec_accept", "fault",
                "rebuild", "recovery", "preempted"} <= names
        kinds = {e["args"]["kind"] for e in evs if e["name"] == "fault"}
        assert kinds == {"transient", "fatal", "hung"}
        assert gw1.restarts >= 3      # fatal + hung + nan
        rebuilds = [e for e in evs if e["name"] == "rebuild"]
        assert len(rebuilds) == gw1.restarts
        assert all(e["tid"] == TID_GATEWAY for e in rebuilds)
        recoveries = [e for e in evs if e["name"] == "recovery"]
        assert len(recoveries) == gw1.restarts
        # spec acceptance is visible per launch AND per request
        acc = [e for e in evs if e["name"] == "spec_accept"]
        assert acc and all(e["args"]["accept_lens"] for e in acc)
        dec_args = [e["args"] for e in evs if e["name"] == "decode"]
        assert any("accept_lens" in a for a in dec_args)
        # the hung fault's virtual stall is on the timeline: events
        # after it sit >= 60s past the epoch
        assert max(e["ts"] for e in evs) >= 60e6
        # compile-once discipline includes the traced replay
        assert eng2.decode_compilations() == 1
        assert base_eng.decode_compilations() == 1


# ----------------------------------------------------------- live HTTP
@pytest.fixture(scope="class")
def server(model):
    srv = serve(model, port=0, num_slots=NUM_SLOTS, max_seq_len=S_MAX,
                max_queue=8, model_name="trace-test")
    # warm the decode/prefill programs so capture windows see steps
    s = srv.gateway.submit(GenerationRequest(prompt=[1, 2, 3, 4],
                                             max_new_tokens=2))
    s.result()
    yield srv
    srv.shutdown(drain=False, timeout=30)


def _get(server, path, timeout=60):
    try:
        with urllib.request.urlopen(server.url + path,
                                    timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


class TestDebugEndpointsHTTP:
    def test_healthz_saturation_fields(self, server):
        status, doc = _get(server, "/healthz")
        assert status == 200
        assert doc["running_slots"] == 0
        assert doc["prefilling_slots"] == 0
        assert doc["waiting_room_occupancy"] == 0
        assert doc["waiting_room_capacity"] == 8
        assert doc["num_slots"] == NUM_SLOTS

    def test_debug_requests_live_table(self, server):
        stream = server.gateway.submit(GenerationRequest(
            prompt=[5, 6, 7, 8], max_new_tokens=64))
        row = None
        for _ in range(200):
            status, doc = _get(server, "/debug/requests")
            assert status == 200
            rows = [r for r in doc["requests"] if r["id"] == stream.id]
            if rows and rows[0]["state"] == "running" \
                    and rows[0]["generated_tokens"] > 1:
                row = rows[0]
                break
            time.sleep(0.02)
        assert row is not None, "request never showed as running"
        assert row["slot"] is not None
        assert row["prompt_tokens"] == 4
        assert row["max_new_tokens"] == 64
        assert row["queue_wait_s"] is not None
        assert row["ttft_s"] is not None and row["ttft_s"] >= 0
        assert row["kv_tokens"] > 0
        assert row["kv_blocks"] >= 1      # paged default
        ids, reason = stream.result()
        assert reason == "length"
        # drained: the table empties
        _, doc = _get(server, "/debug/requests")
        assert all(r["id"] != stream.id for r in doc["requests"])

    def test_debug_trace_capture_over_http(self, server):
        stream = server.gateway.submit(GenerationRequest(
            prompt=[9, 10, 11, 12], max_new_tokens=96))
        status, doc = _get(server, "/debug/trace?steps=4&timeout_s=30")
        stream.result()
        assert status == 200
        evs = validate_chrome_trace(doc)
        steps = [e for e in evs if e["name"] == "step"]
        assert len(steps) == 4
        assert {"plan", "launch", "host-accept"} <= \
            {e["name"] for e in evs}
        # the capture window closed: tracer is disabled again (this
        # server was not started with --trace)
        assert server.gateway.tracer.enabled is False
        # steps=0 on a non-persistent server: immediate snapshot of
        # whatever the last window captured
        status, doc0 = _get(server, "/debug/trace?steps=0")
        assert status == 200 and doc0["traceEvents"]
        status, _ = _get(server, "/debug/trace?steps=bogus")
        assert status == 400

    def test_capture_serializes(self, model):
        gw = ServingGateway(_engine(model), start=False)
        done = threading.Event()
        results = {}

        def first():
            # idle engine: no steps complete, the window times out and
            # returns whatever was captured (here: nothing)
            results["first"] = gw.capture_trace(steps=4, timeout_s=1.5)
            done.set()

        t = threading.Thread(target=first)
        t.start()
        for _ in range(200):
            if gw._capture is not None:
                break
            time.sleep(0.005)
        assert gw._capture is not None
        with pytest.raises(TraceBusyError):
            gw.capture_trace(steps=1, timeout_s=0.1)
        done.wait(10)
        t.join(10)
        assert "traceEvents" in results["first"]
        assert gw.tracer.enabled is False
        gw.shutdown(drain=False, timeout=10)

    def test_capture_timeout_clamps_and_cleans_up(self, model):
        gw = ServingGateway(_engine(model), start=False)
        # negative timeout clamps to 0: immediate empty-window return,
        # with the capture slot released and the tracer disabled (a
        # failed capture must never 409 every later one)
        doc = gw.capture_trace(steps=2, timeout_s=-5)
        assert "traceEvents" in doc
        assert gw._capture is None
        assert gw.tracer.enabled is False
        doc = gw.capture_trace(steps=2, timeout_s=0)    # reusable
        assert "traceEvents" in doc and gw._capture is None
        gw.shutdown(drain=False, timeout=10)

    def test_persistent_trace_flag_reports_effective(self, model):
        srv = serve(model, port=0, num_slots=NUM_SLOTS,
                    max_seq_len=S_MAX, start=False, trace=True,
                    trace_buffer=2048)
        try:
            # the banner reads exactly these (effective-value idiom)
            assert srv.gateway.tracer.enabled is True
            assert srv.gateway.tracer.capacity == 2048
        finally:
            srv.gateway.shutdown(drain=False, timeout=10)


# -------------------------------------------------------- profiler CLI
class TestProfilerCLI:
    @pytest.fixture(scope="class")
    def trace_dir(self):
        import tempfile

        import jax
        import jax.numpy as jnp
        d = tempfile.mkdtemp(prefix="profcli_test_")
        x = jnp.ones((64, 64))
        f = jax.jit(lambda a: jnp.tanh(a @ a).sum())
        f(x).block_until_ready()
        jax.profiler.start_trace(d)
        for _ in range(3):
            f(x).block_until_ready()
        jax.profiler.stop_trace()
        return d

    def _run(self, argv):
        from paddle_tpu.profiler.__main__ import main
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(argv)
        return rc, buf.getvalue()

    def test_text_table(self, trace_dir):
        rc, out = self._run([trace_dir, "--top", "5"])
        assert rc == 0
        assert "total_ms" in out and "avg_us" in out
        # CPU traces carry ops on host planes: the fallback announces
        # itself rather than silently printing nothing
        assert "no device planes" in out

    def test_json_output_and_top(self, trace_dir):
        rc, out = self._run([trace_dir, "--json", "--top", "3"])
        assert rc == 0
        doc = json.loads(out)
        assert 0 < len(doc["rows"]) <= 3
        assert all({"name", "total_ms", "count", "avg_us"} <= set(r)
                   for r in doc["rows"])

    def test_empty_dir_exits_nonzero(self, tmp_path):
        rc, out = self._run([str(tmp_path)])
        assert rc == 1
        assert "no events parsed" in out
