"""End-to-end training tests — the M0 milestone slice (SURVEY.md §7):
eager loop, jitted TrainStep, AMP, hapi Model.fit, ResNet fwd/bwd
(reference pattern: model-level smoke tests + convergence-direction checks).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import SGD, Adam


def _toy_data(n=64, din=8, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(din, 1).astype(np.float32)
    x = rng.randn(n, din).astype(np.float32)
    y = x @ w + 0.01 * rng.randn(n, 1).astype(np.float32)
    return x, y


class TestEagerTraining:
    def test_regression_converges(self):
        paddle.seed(0)
        x, y = _toy_data()
        model = nn.Linear(8, 1)
        opt = SGD(learning_rate=0.05, parameters=model.parameters())
        first = None
        for i in range(50):
            pred = model(paddle.to_tensor(x))
            loss = F.mse_loss(pred, paddle.to_tensor(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss.value)
        assert float(loss.value) < first * 0.1

    @pytest.mark.slow  # 12 s convergence duplicate (870s cap):
    # test_regression_converges is the default eager-convergence rep
    # and test_jit_matches_eager keeps the classification head covered
    def test_classification_eager(self):
        paddle.seed(1)
        rng = np.random.RandomState(1)
        x = rng.randn(128, 4).astype(np.float32)
        y = (x.sum(-1) > 0).astype(np.int32)
        model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
        opt = Adam(learning_rate=0.01, parameters=model.parameters())
        for _ in range(30):
            loss = F.cross_entropy(model(paddle.to_tensor(x)),
                                   paddle.to_tensor(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
        acc = (np.argmax(model(paddle.to_tensor(x)).numpy(), -1) == y).mean()
        assert acc > 0.9


class TestJitTrainStep:
    def test_jit_matches_eager(self):
        paddle.seed(3)
        x, y = _toy_data()
        m1 = nn.Linear(8, 1)
        m2 = nn.Linear(8, 1)
        m2.set_state_dict(m1.state_dict())
        opt1 = SGD(learning_rate=0.1, parameters=m1.parameters())
        opt2 = SGD(learning_rate=0.1, parameters=m2.parameters())
        # eager steps
        eager_losses = []
        for i in range(5):
            loss = F.mse_loss(m1(paddle.to_tensor(x)), paddle.to_tensor(y))
            loss.backward()
            opt1.step()
            opt1.clear_grad()
            eager_losses.append(float(loss.value))
        # jitted steps
        step = TrainStep(m2, lambda out, lab: F.mse_loss(out, lab), opt2)
        jit_losses = [float(step.step((paddle.to_tensor(x),),
                                      (paddle.to_tensor(y),)).value)
                      for _ in range(5)]
        np.testing.assert_allclose(eager_losses, jit_losses, rtol=1e-4)
        step.sync_to_model()
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                                   rtol=1e-4)

    def test_batchnorm_buffers_update_in_jit(self):
        paddle.seed(4)
        model = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8),
                              nn.Linear(8, 1))
        opt = SGD(learning_rate=0.01, parameters=model.parameters())
        step = TrainStep(model, lambda o, l: F.mse_loss(o, l), opt)
        x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
        y = np.zeros((16, 1), np.float32)
        before = model[1]._mean.numpy().copy()
        step.step((paddle.to_tensor(x),), (paddle.to_tensor(y),))
        step.sync_to_model()
        after = model[1]._mean.numpy()
        assert not np.allclose(before, after)

    def test_accum_step_equivalence(self):
        paddle.seed(5)
        x, y = _toy_data(n=32)
        m1 = nn.Linear(8, 1)
        m2 = nn.Linear(8, 1)
        m2.set_state_dict(m1.state_dict())
        o1 = SGD(learning_rate=0.1, parameters=m1.parameters())
        o2 = SGD(learning_rate=0.1, parameters=m2.parameters())
        s1 = TrainStep(m1, lambda o, l: F.mse_loss(o, l), o1)
        s2 = TrainStep(m2, lambda o, l: F.mse_loss(o, l), o2)
        l1 = s1.step((paddle.to_tensor(x),), (paddle.to_tensor(y),))
        l2 = s2.accum_step((paddle.to_tensor(x),), (paddle.to_tensor(y),), 4)
        s1.sync_to_model()
        s2.sync_to_model()
        # microbatched grads averaged == full-batch grads (linear + mse mean)
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_dropout_varies_across_steps_in_jit(self):
        paddle.seed(6)
        model = nn.Sequential(nn.Linear(8, 32), nn.Dropout(0.5),
                              nn.Linear(32, 1))
        opt = SGD(learning_rate=0.0, parameters=model.parameters())
        step = TrainStep(model, lambda o, l: F.mse_loss(o, l), opt)
        x = np.ones((4, 8), np.float32)
        y = np.zeros((4, 1), np.float32)
        l1 = float(step.step((paddle.to_tensor(x),), (paddle.to_tensor(y),)).value)
        l2 = float(step.step((paddle.to_tensor(x),), (paddle.to_tensor(y),)).value)
        assert l1 != l2  # different dropout masks per step under jit


class TestAMP:
    def test_autocast_bf16_matmul(self):
        import jax.numpy as jnp
        a = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        b = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        with paddle.amp.auto_cast(level="O1"):
            out = paddle.matmul(a, b)
        assert out.dtype == jnp.bfloat16
        # blacklisted op stays fp32
        with paddle.amp.auto_cast(level="O1"):
            s = F.softmax(a)
        assert s.dtype == jnp.float32

    def test_amp_training_converges(self):
        paddle.seed(7)
        x, y = _toy_data()
        model = nn.Linear(8, 1)
        opt = SGD(learning_rate=0.05, parameters=model.parameters())
        first = None
        for _ in range(30):
            with paddle.amp.auto_cast(level="O1"):
                loss = F.mse_loss(model(paddle.to_tensor(x)),
                                  paddle.to_tensor(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first or float(loss.value)
        assert float(loss.value) < first * 0.3

    def test_grad_scaler_fp16_flow(self):
        paddle.seed(8)
        model = nn.Linear(4, 1)
        opt = SGD(learning_rate=0.01, parameters=model.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        y = paddle.to_tensor(np.zeros((2, 1), np.float32))
        loss = F.mse_loss(model(x), y)
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        opt.clear_grad()
        assert scaler.state_dict()["scale"] == 128.0

    def test_grad_scaler_state_roundtrip_keeps_schedule(self):
        """state_dict must carry the WHOLE loss-scale schedule (enable
        flag + incr/decr cadence + step counters), so a resumed fp16
        run continues the schedule instead of restarting it."""
        src = paddle.amp.GradScaler(
            init_loss_scaling=4096.0, incr_ratio=3.0, decr_ratio=0.25,
            incr_every_n_steps=5, decr_every_n_nan_or_inf=3)
        # advance mid-window: 4 good steps (one short of an increase)
        for _ in range(4):
            src._found_inf = False
            src._unscaled = True
            src.update()
        state = src.state_dict()
        assert state["enable"] is True
        assert state["incr_every_n_steps"] == 5
        assert state["decr_every_n_nan_or_inf"] == 3
        assert state["use_dynamic_loss_scaling"] is True
        assert state["good_steps"] == 4

        # resume into a default-constructed scaler: one more good step
        # must trigger the increase at the LOADED cadence and ratio
        dst = paddle.amp.GradScaler()
        dst.load_state_dict(state)
        dst._found_inf = False
        dst._unscaled = True
        dst.update()
        assert dst.get_init_loss_scaling() == 4096.0 * 3.0
        # and the loaded decr window drives the backoff cadence too
        for _ in range(3):
            dst._found_inf = True
            dst._unscaled = True
            dst.update()
        assert dst.get_init_loss_scaling() == 4096.0 * 3.0 * 0.25

    def test_grad_scaler_disabled_roundtrip(self):
        src = paddle.amp.GradScaler(enable=False)
        dst = paddle.amp.GradScaler(enable=True)
        dst.load_state_dict(src.state_dict())
        assert dst.is_enable() is False  # passthrough survives resume


class TestHapiModel:
    def test_fit_evaluate(self):
        paddle.seed(9)
        from paddle_tpu.io import TensorDataset
        x, y = _toy_data(n=32)
        ds = TensorDataset([x, y])
        model = paddle.Model(nn.Linear(8, 1))
        model.prepare(SGD(learning_rate=0.05,
                          parameters=model.parameters()),
                      nn.MSELoss())
        model.fit(ds, batch_size=8, epochs=15, verbose=0)
        logs = model.evaluate(ds, batch_size=8, verbose=0)
        assert logs["loss"] < 1.0

    def test_save_load(self, tmp_path):
        model = paddle.Model(nn.Linear(4, 2))
        model.prepare(SGD(learning_rate=0.1, parameters=model.parameters()),
                      nn.MSELoss())
        p = str(tmp_path / "ckpt")
        model.save(p)
        m2 = paddle.Model(nn.Linear(4, 2))
        m2.prepare(SGD(learning_rate=0.1, parameters=m2.parameters()),
                   nn.MSELoss())
        m2.load(p)
        np.testing.assert_allclose(m2.network.weight.numpy(),
                                   model.network.weight.numpy())


class TestResNet:
    @pytest.mark.slow  # ~47 s eager conv net; the jitted train smoke
    # below keeps resnet18 fwd+bwd+opt covered in the default run
    def test_resnet18_fwd_bwd(self):
        paddle.seed(10)
        from paddle_tpu.vision.models import resnet18
        model = resnet18(num_classes=10)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3, 32, 32).astype(np.float32))
        y = paddle.to_tensor(np.array([1, 3]))
        out = model(x)
        assert out.shape == [2, 10]
        loss = F.cross_entropy(out, y)
        loss.backward()
        grads = [p.grad for p in model.parameters() if not p.stop_gradient]
        assert all(g is not None for g in grads)

    @pytest.mark.slow  # 18 s jit conv train duplicate: conv-train stays covered
    # by TestEagerTraining.test_classification_eager (870s cap)
    def test_resnet18_jit_train_smoke(self):
        paddle.seed(11)
        from paddle_tpu.vision.models import resnet18
        model = resnet18(num_classes=4)
        opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                        parameters=model.parameters())
        step = TrainStep(model, lambda o, l: F.cross_entropy(o, l), opt)
        rng = np.random.RandomState(1)
        x = rng.randn(4, 3, 32, 32).astype(np.float32)
        y = rng.randint(0, 4, 4)
        l1 = float(step.step((paddle.to_tensor(x),),
                             (paddle.to_tensor(y),)).value)
        for _ in range(5):
            l2 = float(step.step((paddle.to_tensor(x),),
                                 (paddle.to_tensor(y),)).value)
        assert l2 < l1  # memorizes the fixed batch


class TestDataLoader:
    def test_dataloader_batching(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        x = np.arange(20, dtype=np.float32).reshape(10, 2)
        y = np.arange(10, dtype=np.int32)
        dl = DataLoader(TensorDataset([x, y]), batch_size=4, drop_last=False)
        batches = list(dl)
        assert len(batches) == 3
        assert batches[0][0].shape == [4, 2]
        assert batches[2][0].shape == [2, 2]

    def test_dataloader_workers_order(self):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataset import Dataset

        class Sq(Dataset):
            def __len__(self):
                return 17

            def __getitem__(self, i):
                return np.asarray([i], np.int32)

        dl = DataLoader(Sq(), batch_size=4, num_workers=2)
        got = np.concatenate([b.numpy().ravel() for b in dl])
        np.testing.assert_array_equal(got, np.arange(17))

    def test_distributed_batch_sampler(self):
        from paddle_tpu.io import DistributedBatchSampler
        from paddle_tpu.io.dataset import TensorDataset
        ds = TensorDataset([np.arange(10, dtype=np.float32)])
        s0 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0)
        s1 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=1)
        i0 = [i for b in s0 for i in b]
        i1 = [i for b in s1 for i in b]
        assert len(set(i0) & set(i1)) == 0
        assert len(i0) == len(i1) == 5
