"""utils.dlpack / utils.unique_name / callbacks.ReduceLROnPlateau
(reference: python/paddle/utils/dlpack.py †, utils/unique_name.py †,
hapi/callbacks.py † ReduceLROnPlateau)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestDlpack:
    def test_torch_roundtrip(self):
        torch = pytest.importorskip("torch")
        src = torch.arange(6, dtype=torch.float32).reshape(2, 3)
        t = paddle.utils.dlpack.from_dlpack(src)  # torch -> paddle
        assert isinstance(t, paddle.Tensor)
        np.testing.assert_array_equal(t.numpy(), src.numpy())
        back = torch.utils.dlpack.from_dlpack(   # paddle -> torch
            paddle.utils.dlpack.to_dlpack(t * 2))
        np.testing.assert_array_equal(back.numpy(), src.numpy() * 2)

    def test_numpy_from_dlpack(self):
        t = paddle.to_tensor(np.float32([1.0, 2.0]))
        out = np.from_dlpack(t.value)
        np.testing.assert_array_equal(out, [1.0, 2.0])

    def test_tensor_is_dlpack_object(self):
        # the Tensor itself speaks the protocol: consumers need no unwrap
        t = paddle.to_tensor(np.float32([3.0, 4.0]))
        np.testing.assert_array_equal(np.from_dlpack(t), [3.0, 4.0])
        torch = pytest.importorskip("torch")
        np.testing.assert_array_equal(
            torch.utils.dlpack.from_dlpack(t).numpy(), [3.0, 4.0])

    def test_capsule_self_roundtrip(self):
        # the canonical reference usage: to_dlpack hands out a bare capsule
        # and from_dlpack consumes it (modern jax needs the shim for this)
        t = paddle.to_tensor(np.float32([[1, 2], [3, 4]]))
        out = paddle.utils.dlpack.from_dlpack(paddle.utils.dlpack.to_dlpack(t))
        np.testing.assert_array_equal(out.numpy(), [[1, 2], [3, 4]])

    def test_torch_capsule_to_paddle(self):
        torch = pytest.importorskip("torch")
        cap = torch.utils.dlpack.to_dlpack(torch.arange(4, dtype=torch.int32))
        out = paddle.utils.dlpack.from_dlpack(cap)
        np.testing.assert_array_equal(out.numpy(), [0, 1, 2, 3])


class TestUniqueName:
    def test_generate_increments(self):
        from paddle_tpu.utils import unique_name
        with unique_name.guard():
            assert unique_name.generate("fc") == "fc_0"
            assert unique_name.generate("fc") == "fc_1"
            assert unique_name.generate("conv") == "conv_0"

    def test_guard_scopes_and_prefix(self):
        from paddle_tpu.utils import unique_name
        with unique_name.guard():
            unique_name.generate("fc")
            with unique_name.guard("block_"):
                assert unique_name.generate("fc") == "block_fc_0"
            # outer generator resumes where it left off
            assert unique_name.generate("fc") == "fc_1"


class TestReduceLROnPlateau:
    def _model_with_opt(self, lr=0.1):
        class M:  # minimal hapi-model stand-in: callback reads ._optimizer
            pass
        m = M()
        p = paddle.to_tensor(np.ones((2,), np.float32))
        p.stop_gradient = False
        m._optimizer = paddle.optimizer.SGD(learning_rate=lr, parameters=[p])
        return m

    def test_reduces_after_patience(self):
        cb = paddle.callbacks.ReduceLROnPlateau(
            monitor="loss", factor=0.5, patience=2, verbose=0)
        cb.model = self._model_with_opt(0.1)
        cb.on_eval_end({"loss": 1.0})        # best
        for _ in range(2):                   # two bad evals = patience
            cb.on_eval_end({"loss": 1.0})
        assert cb.model._optimizer.get_lr() == pytest.approx(0.05)

    def test_improvement_resets_wait(self):
        cb = paddle.callbacks.ReduceLROnPlateau(
            monitor="loss", factor=0.5, patience=2, verbose=0)
        cb.model = self._model_with_opt(0.1)
        cb.on_eval_end({"loss": 1.0})
        cb.on_eval_end({"loss": 1.0})        # bad (wait=1)
        cb.on_eval_end({"loss": 0.5})        # improvement resets
        cb.on_eval_end({"loss": 0.5})        # bad (wait=1)
        assert cb.model._optimizer.get_lr() == pytest.approx(0.1)

    def test_min_lr_floor(self):
        cb = paddle.callbacks.ReduceLROnPlateau(
            monitor="loss", factor=0.1, patience=1, min_lr=0.05, verbose=0)
        cb.model = self._model_with_opt(0.1)
        cb.on_eval_end({"loss": 1.0})
        cb.on_eval_end({"loss": 1.0})
        assert cb.model._optimizer.get_lr() == pytest.approx(0.05)

    def test_auto_mode_is_min_for_error_monitors(self):
        # 'val_error' must resolve to min-mode: a plateauing error reduces
        # the LR (max-mode would treat every eval as an improvement)
        cb = paddle.callbacks.ReduceLROnPlateau(
            monitor="val_error", factor=0.5, patience=1, verbose=0)
        assert cb.mode == "min"
        cb.model = self._model_with_opt(0.1)
        cb.on_eval_end({"val_error": 1.0})
        cb.on_eval_end({"val_error": 1.0})
        assert cb.model._optimizer.get_lr() == pytest.approx(0.05)
        # accuracy-like monitors resolve to max
        assert paddle.callbacks.ReduceLROnPlateau(monitor="acc").mode == "max"
        assert paddle.callbacks.EarlyStopping(monitor="val_acc").mode == "max"
        assert paddle.callbacks.EarlyStopping(monitor="val_error").mode == "min"

    def test_cooldown_resets_patience_counting(self):
        # keras-exact: the cooldown branch zeroes wait, decrements, and a
        # bad eval counts once the counter has reached zero — so with
        # cooldown=2, the first post-reduction bad eval is swallowed
        # (counter 2->1) and the second starts patience counting fresh
        cb = paddle.callbacks.ReduceLROnPlateau(
            monitor="loss", factor=0.5, patience=2, cooldown=2, verbose=0)
        cb.model = self._model_with_opt(0.1)
        cb.on_eval_end({"loss": 1.0})            # best
        cb.on_eval_end({"loss": 1.0})            # bad 1
        cb.on_eval_end({"loss": 1.0})            # bad 2 -> reduce, cooldown=2
        assert cb.model._optimizer.get_lr() == pytest.approx(0.05)
        cb.on_eval_end({"loss": 1.0})            # cooldown 2->1: swallowed
        cb.on_eval_end({"loss": 1.0})            # cooldown 1->0: wait=1
        assert cb.model._optimizer.get_lr() == pytest.approx(0.05)
        cb.on_eval_end({"loss": 1.0})            # wait=2 -> second reduction
        assert cb.model._optimizer.get_lr() == pytest.approx(0.025)

    def test_cooldown_elapses_during_improvement(self):
        # cooldown burns down on improving evals too (keras semantics): a
        # plateau that starts after the cooldown window has passed needs
        # only `patience` bad evals, not cooldown+patience
        cb = paddle.callbacks.ReduceLROnPlateau(
            monitor="loss", factor=0.5, patience=2, cooldown=3, verbose=0)
        cb.model = self._model_with_opt(0.1)
        cb.on_eval_end({"loss": 1.0})
        cb.on_eval_end({"loss": 1.0})
        cb.on_eval_end({"loss": 1.0})        # reduce #1, cooldown=3
        assert cb.model._optimizer.get_lr() == pytest.approx(0.05)
        for v in (0.9, 0.8, 0.7, 0.6):       # improving: cooldown expires
            cb.on_eval_end({"loss": v})
        cb.on_eval_end({"loss": 0.6})        # bad 1
        cb.on_eval_end({"loss": 0.6})        # bad 2 -> reduce #2
        assert cb.model._optimizer.get_lr() == pytest.approx(0.025)

    def test_scheduler_driven_optimizer_skipped(self):
        from paddle_tpu.optimizer.lr import StepDecay
        cb = paddle.callbacks.ReduceLROnPlateau(monitor="loss", patience=0,
                                                verbose=0)
        cb.model = self._model_with_opt()
        p = paddle.to_tensor(np.ones((2,), np.float32))
        p.stop_gradient = False
        cb.model._optimizer = paddle.optimizer.SGD(
            learning_rate=StepDecay(0.1, step_size=5), parameters=[p])
        cb.on_eval_end({"loss": 1.0})
        with pytest.warns(UserWarning, match="LRScheduler"):
            cb.on_eval_end({"loss": 1.0})


class TestFlashAttentionCanonicalPath:
    """F.flash_attention re-exported under nn.functional (reference path:
    python/paddle/nn/functional/flash_attention.py †) matches the incubate
    implementation exactly."""

    def test_alias_matches_incubate(self):
        import paddle_tpu.incubate.nn.functional as iF
        F = paddle.nn.functional
        rng = np.random.RandomState(0)
        q = paddle.to_tensor(rng.randn(2, 8, 4, 16).astype(np.float32))
        a, _ = F.flash_attention(q, q, q, causal=True)
        b, _ = iF.flash_attention(q, q, q, causal=True)
        np.testing.assert_array_equal(a.numpy(), b.numpy())
        for n in ("flash_attn_unpadded", "flash_attn_qkvpacked"):
            assert callable(getattr(F, n))
