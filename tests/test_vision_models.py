"""Vision model zoo shape/train tests (reference pattern:
``test/legacy_test/test_vision_models.py`` — forward-shape smoke over the
model zoo, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M


def _img(n=1, c=3, hw=64):
    rng = np.random.RandomState(0)
    return paddle.to_tensor(rng.rand(n, c, hw, hw).astype("float32"))


class TestNewZooForwardShapes:
    # the conv-heaviest ctors are slow-marked (VERDICT r5 weak 3: suite
    # wall time; widened again to fit the 870s tier-1 cap): every
    # parametrized arch runs under `-m slow` — the googlenet/inception/
    # densenet-width tests below keep the default zoo forward coverage
    @pytest.mark.parametrize("ctor", [
        pytest.param(M.densenet121, marks=pytest.mark.slow),
        pytest.param(M.squeezenet1_0, marks=pytest.mark.slow),
        pytest.param(M.squeezenet1_1, marks=pytest.mark.slow),
        pytest.param(M.mobilenet_v1, marks=pytest.mark.slow),
        pytest.param(M.mobilenet_v3_small, marks=pytest.mark.slow),
        pytest.param(M.mobilenet_v3_large, marks=pytest.mark.slow),
        pytest.param(M.shufflenet_v2_x0_25, marks=pytest.mark.slow),
        pytest.param(M.shufflenet_v2_x0_5, marks=pytest.mark.slow),
        pytest.param(M.shufflenet_v2_swish, marks=pytest.mark.slow),
    ], ids=lambda f: f.__name__)
    def test_forward_shape(self, ctor):
        m = ctor(num_classes=7)
        m.eval()
        out = m(_img())
        assert out.shape == [1, 7]

    @pytest.mark.slow
    def test_googlenet_aux_heads(self):
        m = M.googlenet(num_classes=5)
        m.eval()
        out, aux1, aux2 = m(_img(hw=96))
        assert out.shape == [1, 5]
        assert aux1.shape == [1, 5]
        assert aux2.shape == [1, 5]

    @pytest.mark.slow
    def test_inception_v3_shape(self):
        # 160 px (not the canonical 299): the adaptive pool makes the head
        # size-agnostic and every mixed grid stays >= the 5x5 aux pool, so
        # shape-flow coverage is identical at ~40% of the conv cost
        m = M.inception_v3(num_classes=4)
        m.eval()
        assert m(_img(hw=160)).shape == [1, 4]

    @pytest.mark.slow
    def test_densenet_variant_widths(self):
        # densenet161 uses growth 48 / init 96 — distinct trunk widths.
        # slow-marked (VERDICT r4 weak 8): densenet121 in the default run
        # already compiles the same block/transition plumbing; this only
        # re-checks the width variant at ~90s of XLA-CPU conv compiles
        m = M.densenet161(num_classes=3, with_pool=True)
        m.eval()
        assert m(_img()).shape == [1, 3]

    @pytest.mark.slow  # mobilenet_v3 trunk = ~25s of conv compiles
    def test_feature_mode_no_head(self):
        m = M.mobilenet_v3_small(num_classes=0, with_pool=False)
        m.eval()
        out = m(_img())
        assert len(out.shape) == 4 and out.shape[1] == 576


class TestChannelShuffle:
    def test_matches_manual(self):
        from paddle_tpu.nn import functional as F
        x = np.arange(1 * 6 * 2 * 2, dtype=np.float32).reshape(1, 6, 2, 2)
        out = np.asarray(F.channel_shuffle(paddle.to_tensor(x), 3).value)
        ref = x.reshape(1, 3, 2, 2, 2).transpose(0, 2, 1, 3, 4).reshape(1, 6, 2, 2)
        np.testing.assert_array_equal(out, ref)

    def test_layer_and_roundtrip(self):
        import paddle_tpu.nn as nn
        x = paddle.to_tensor(np.random.rand(2, 8, 4, 4).astype("float32"))
        y = nn.ChannelShuffle(2)(x)
        # shuffle with groups g then with C//g is the identity permutation
        z = nn.ChannelShuffle(4)(y)
        np.testing.assert_allclose(np.asarray(z.value), np.asarray(x.value))


class TestNewZooTrains:
    @pytest.mark.slow  # 21 s conv train-step duplicate: conv-train stays
    # covered by TestEagerTraining.test_classification_eager (870s cap)
    def test_squeezenet_train_step(self):
        paddle.seed(0)
        m = M.squeezenet1_1(num_classes=4)
        m.train()
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.optimizer import SGD
        step = TrainStep(m, paddle.nn.CrossEntropyLoss(),
                         SGD(learning_rate=0.05, parameters=m.parameters()))
        rng = np.random.RandomState(1)
        imgs = paddle.to_tensor(rng.rand(4, 3, 64, 64).astype("float32"))
        labels = paddle.to_tensor(rng.randint(0, 4, (4,)).astype("int64"))
        losses = [float(step.step((imgs,), (labels,)).value) for _ in range(5)]
        assert np.isfinite(losses).all()
        # dropout resamples every step, so the tail loss can bounce above
        # the start on some jax key streams; "the optimizer moves the loss
        # down" is what this pins — best-seen loss, not last-step loss
        assert min(losses) < losses[0] - 0.05
