"""paddle.vision.ops detection primitives + lu_unpack (reference:
``python/paddle/vision/ops.py`` CUDA nms/roi_align kernels,
``paddle.linalg.lu_unpack``). Oracles: brute-force numpy."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops


def _t(x):
    return paddle.to_tensor(np.asarray(x))


def _nms_oracle(boxes, scores, thr):
    order = np.argsort(-scores)
    keep = []
    while len(order):
        i = order[0]
        keep.append(i)
        rest = order[1:]
        xx1 = np.maximum(boxes[i, 0], boxes[rest, 0])
        yy1 = np.maximum(boxes[i, 1], boxes[rest, 1])
        xx2 = np.minimum(boxes[i, 2], boxes[rest, 2])
        yy2 = np.minimum(boxes[i, 3], boxes[rest, 3])
        w = np.maximum(0, xx2 - xx1)
        h = np.maximum(0, yy2 - yy1)
        inter = w * h
        a_i = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        a_r = ((boxes[rest, 2] - boxes[rest, 0]) *
               (boxes[rest, 3] - boxes[rest, 1]))
        iou = inter / (a_i + a_r - inter)
        order = rest[iou <= thr]
    return keep


class TestNMS:
    def test_matches_bruteforce(self):
        rng = np.random.RandomState(0)
        xy = rng.rand(30, 2) * 60
        wh = rng.rand(30, 2) * 30 + 2
        boxes = np.concatenate([xy, xy + wh], -1).astype(np.float32)
        scores = rng.rand(30).astype(np.float32)
        got = vops.nms(_t(boxes), 0.4, _t(scores)).numpy()
        expect = _nms_oracle(boxes, scores, 0.4)
        np.testing.assert_array_equal(got, expect)

    def test_top_k_padding(self):
        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10], [50, 50, 60, 60]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        got = vops.nms(_t(boxes), 0.5, _t(scores), top_k=3).numpy()
        np.testing.assert_array_equal(got, [0, 2, -1])  # 1 suppressed by 0

    def test_multiclass_suppresses_per_category(self):
        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1])
        got = vops.nms(_t(boxes), 0.5, _t(scores), category_idxs=_t(cats),
                       top_k=2).numpy()
        np.testing.assert_array_equal(got, [0, 1])  # different class: kept

    def test_box_iou_and_area(self):
        a = np.array([[0, 0, 10, 10]], np.float32)
        b = np.array([[5, 5, 15, 15], [20, 20, 30, 30]], np.float32)
        iou = vops.box_iou(_t(a), _t(b)).numpy()
        np.testing.assert_allclose(iou, [[25.0 / 175.0, 0.0]], rtol=1e-5)
        np.testing.assert_allclose(vops.box_area(_t(b)).numpy(), [100, 100])


class TestRoiAlign:
    def test_constant_map_returns_constant(self):
        x = np.full((1, 3, 16, 16), 7.0, np.float32)
        rois = np.array([[2, 2, 10, 10]], np.float32)
        out = vops.roi_align(_t(x), _t(rois), output_size=4).numpy()
        assert out.shape == (1, 3, 4, 4)
        np.testing.assert_allclose(out, 7.0, rtol=1e-5)

    def test_gradient_ramp(self):
        # linear ramp in x: averaged samples reproduce the ramp center
        H = W = 16
        ramp = np.tile(np.arange(W, dtype=np.float32), (H, 1))
        x = ramp[None, None]
        rois = np.array([[4.0, 4.0, 12.0, 12.0]], np.float32)
        out = vops.roi_align(_t(x), _t(rois), output_size=2,
                             aligned=False).numpy()[0, 0]
        # columns centered at x = 4 + {1, 3}/4 * 8 -> 6, 10
        np.testing.assert_allclose(out[:, 0], 6.0, atol=0.3)
        np.testing.assert_allclose(out[:, 1], 10.0, atol=0.3)

    def test_multi_image_batch(self):
        x = np.stack([np.full((1, 8, 8), 1.0), np.full((1, 8, 8), 2.0)]) \
            .astype(np.float32)
        rois = np.array([[0, 0, 4, 4], [0, 0, 4, 4]], np.float32)
        out = vops.roi_align(_t(x), _t(rois), boxes_num=_t(np.array([1, 1])),
                             output_size=2).numpy()
        np.testing.assert_allclose(out[0], 1.0, rtol=1e-5)
        np.testing.assert_allclose(out[1], 2.0, rtol=1e-5)


class TestBoxCoderFpn:
    def test_encode_decode_roundtrip(self):
        priors = np.array([[0, 0, 10, 10], [5, 5, 20, 25]], np.float32)
        targets = np.array([[1, 1, 9, 11], [6, 4, 18, 22]], np.float32)
        var = np.ones((4,), np.float32)
        enc = vops.box_coder(_t(priors), _t(var), _t(targets),
                             code_type="encode_center_size")
        dec = vops.box_coder(_t(priors), _t(var), enc,
                             code_type="decode_center_size").numpy()
        np.testing.assert_allclose(dec, targets, rtol=1e-4, atol=1e-4)

    def test_fpn_levels(self):
        rois = np.array([[0, 0, 56, 56], [0, 0, 224, 224], [0, 0, 448, 448]],
                        np.float32)
        lvl = vops.distribute_fpn_proposals(_t(rois), 2, 5, 4, 224).numpy()
        np.testing.assert_array_equal(lvl, [2, 4, 5])


class TestLuUnpack:
    def test_reconstructs_input(self):
        rng = np.random.RandomState(1)
        a = rng.randn(5, 5).astype(np.float32)
        lu_mat, piv = paddle.lu(_t(a))
        P, L, U = paddle.lu_unpack(lu_mat, piv)
        rec = P.numpy() @ L.numpy() @ U.numpy()
        np.testing.assert_allclose(rec, a, rtol=1e-3, atol=1e-4)

    def test_batched(self):
        rng = np.random.RandomState(2)
        a = rng.randn(3, 4, 4).astype(np.float32)
        lu_mat, piv = paddle.lu(_t(a))
        P, L, U = paddle.lu_unpack(lu_mat, piv)
        rec = np.einsum("bij,bjk,bkl->bil", P.numpy(), L.numpy(), U.numpy())
        np.testing.assert_allclose(rec, a, rtol=1e-3, atol=1e-4)

    def test_flags_return_none(self):
        rng = np.random.RandomState(3)
        a = rng.randn(4, 4).astype(np.float32)
        lu_mat, piv = paddle.lu(_t(a))
        P, L, U = paddle.lu_unpack(lu_mat, piv, unpack_ludata=False)
        assert L is None and U is None and P is not None
        P2, L2, U2 = paddle.lu_unpack(lu_mat, piv, unpack_pivots=False)
        assert P2 is None and L2 is not None
